"""Connection management over the stream fabric — the TcpListener/
TcpStream surface (sim/net/tcp/{listener,stream}.rs).

net/stream.py supplies the byte-pipe semantics (ordered, reliable,
windowed); this layer adds the connection lifecycle the reference models:
handshake before data flows (stream.rs:93 sleeps 3x latency for the
handshake), connection state per peer, refusal when nobody listens, and
reset on peer death (stream.rs:162-209: reads EOF / writes fail once the
peer socket is gone).

State machine per (node, peer): CLOSED -> SYN_SENT -> ESTABLISHED on the
initiator; CLOSED -> ESTABLISHED on the listener when a SYN arrives while
listening. A SYN to a non-listening node draws RST. Death detection is the
application's concern (as in the reference, where only a *reset* — not a
kill alone — tears streams down).

All helpers are masked/traceable; see tests/test_conn.py for the idiom.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.api import Ctx

TAG_SYN = (1 << 21)
TAG_SYN_ACK = (1 << 21) + 1
TAG_RST = (1 << 21) + 2

CLOSED, SYN_SENT, ESTABLISHED = 0, 1, 2


def conn_state(n_nodes: int):
    return dict(
        cn_state=jnp.zeros((n_nodes,), jnp.int32),   # per-peer conn state
        cn_listen=jnp.asarray(0, jnp.int32),         # listening flag
    )


def listen(ctx: Ctx, st, *, when=True):
    """Start accepting connections (TcpListener::bind analog)."""
    st["cn_listen"] = jnp.where(when, 1, st["cn_listen"])


def connect(ctx: Ctx, st, dst, *, when=True):
    """Initiate a handshake (TcpStream::connect). Completion is observed
    via is_established once the SYN-ACK returns; pair with a retry timer
    for lossy networks."""
    from ..utils.maskutil import statically_false
    if statically_false(when):
        return jnp.asarray(False)
    dst = jnp.asarray(dst, jnp.int32)
    # dialing is idempotent from SYN_SENT so a retry timer can re-send a
    # lost SYN (the reference's connect retries inside try_send)
    ok = jnp.asarray(when) & ((st["cn_state"][dst] == CLOSED)
                              | (st["cn_state"][dst] == SYN_SENT))
    st["cn_state"] = st["cn_state"].at[dst].set(
        jnp.where(ok, SYN_SENT, st["cn_state"][dst]))
    ctx.send(dst, TAG_SYN, [0], when=ok)
    return ok


def is_established(st, peer):
    return st["cn_state"][jnp.asarray(peer, jnp.int32)] == ESTABLISHED


def on_message(ctx: Ctx, st, src, tag):
    """Feed connection-control messages through the state machine. Returns
    (accepted, established, reset) masks for this event. Call before
    stream.on_message; data for CLOSED peers should be ignored by the app.
    """
    from ..utils.maskutil import statically_false
    if statically_false((tag == TAG_SYN) | (tag == TAG_SYN_ACK)
                        | (tag == TAG_RST)):
        f = jnp.asarray(False)
        return f, f, f
    src = jnp.asarray(src, jnp.int32)

    # listener side: SYN while listening -> ESTABLISHED + SYN-ACK;
    # SYN while not listening -> RST (connection refused)
    is_syn = tag == TAG_SYN
    accept = is_syn & (st["cn_listen"] == 1)
    refuse = is_syn & (st["cn_listen"] != 1)
    st["cn_state"] = st["cn_state"].at[src].set(
        jnp.where(accept, ESTABLISHED, st["cn_state"][src]))
    ctx.send(src, TAG_SYN_ACK, [0], when=accept)
    ctx.send(src, TAG_RST, [0], when=refuse)

    # initiator side: SYN-ACK completes the handshake
    is_sa = (tag == TAG_SYN_ACK) & (st["cn_state"][src] == SYN_SENT)
    st["cn_state"] = st["cn_state"].at[src].set(
        jnp.where(is_sa, ESTABLISHED, st["cn_state"][src]))

    # RST tears the connection down (ConnectionReset)
    is_rst = tag == TAG_RST
    st["cn_state"] = st["cn_state"].at[src].set(
        jnp.where(is_rst, CLOSED, st["cn_state"][src]))

    return accept, is_sa, is_rst


def reset(ctx: Ctx, st, peer, *, when=True):
    """Abort a connection and notify the peer (the reset-on-close path)."""
    from ..utils.maskutil import statically_false
    if statically_false(when):
        return
    peer = jnp.asarray(peer, jnp.int32)
    w = jnp.asarray(when) & (st["cn_state"][peer] != CLOSED)
    st["cn_state"] = st["cn_state"].at[peer].set(
        jnp.where(w, CLOSED, st["cn_state"][peer]))
    ctx.send(peer, TAG_RST, [0], when=w)
