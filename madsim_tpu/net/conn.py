"""Connection management over the stream fabric — the TcpListener/
TcpStream surface (sim/net/tcp/{listener,stream}.rs).

net/stream.py supplies the byte-pipe semantics (ordered, reliable,
windowed); this layer adds the connection lifecycle the reference models:
handshake before data flows (stream.rs:93 sleeps 3x latency for the
handshake), connection state per peer, refusal when nobody listens, and
reset on peer death (stream.rs:162-209: reads EOF / writes fail once the
peer socket is gone).

State machine per (node, peer): CLOSED -> SYN_SENT -> ESTABLISHED on the
initiator; CLOSED -> ESTABLISHED on the listener when a SYN arrives while
listening. A SYN to a non-listening node draws RST. Death detection is the
application's concern (as in the reference, where only a *reset* — not a
kill alone — tears streams down).

PEER INCARNATIONS (r19, DESIGN §20): every peering carries an epoch
counter (`cn_epoch[peer]`) that strictly increases across connection
generations. The handshake NEGOTIATES the generation: a SYN proposes the
initiator's epoch, the listener accepts at max(proposal, own) and echoes
it in the SYN-ACK, so both endpoints land on the same value — and any
stream fabric present in the same state dict is re-based onto it
(stream.reset_peer(epoch=)). Every RST names the generation it tears
(its payload word), so a DELAYED RST from a pre-reset incarnation is
rejected instead of closing the successor connection; every local or
remote teardown bumps the counter, so the next negotiated generation
strictly exceeds every segment still in flight from the torn one.
`OP_RESET_PEER` (core/step.py) applies the same teardown+bump to both
endpoints atomically — the reset_node parity.

All helpers are masked/traceable; see tests/test_conn.py for the idiom.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.api import Ctx

TAG_SYN = (1 << 21)
TAG_SYN_ACK = (1 << 21) + 1
TAG_RST = (1 << 21) + 2

CLOSED, SYN_SENT, ESTABLISHED = 0, 1, 2

# stream-fabric leaves conn re-bases on handshake/teardown when the model
# composes both layers in one state dict (the minipg/stream_echo idiom)
_STREAM_KEYS = frozenset(
    ("sx_seq", "sx_base", "sx_val", "sr_next", "sr_val", "sr_have",
     "st_epoch"))


def conn_state(n_nodes: int):
    return dict(
        cn_state=jnp.zeros((n_nodes,), jnp.int32),   # per-peer conn state
        cn_listen=jnp.asarray(0, jnp.int32),         # listening flag
        # per-peer incarnation counter: the connection GENERATION this
        # node will propose/accept next; strictly increases across
        # resets, negotiated to a common value at each handshake
        cn_epoch=jnp.zeros((n_nodes,), jnp.int32),
    )


def _has_stream(st) -> bool:
    return _STREAM_KEYS <= set(st.keys())


def listen(ctx: Ctx, st, *, when=True):
    """Start accepting connections (TcpListener::bind analog)."""
    st["cn_listen"] = jnp.where(when, 1, st["cn_listen"])


def connect(ctx: Ctx, st, dst, *, when=True):
    """Initiate a handshake (TcpStream::connect). Completion is observed
    via is_established once the SYN-ACK returns; pair with a retry timer
    for lossy networks. The SYN proposes this node's epoch for the new
    connection generation (r19)."""
    from ..utils.maskutil import statically_false
    if statically_false(when):
        return jnp.asarray(False)
    dst = jnp.asarray(dst, jnp.int32)
    # dialing is idempotent from SYN_SENT so a retry timer can re-send a
    # lost SYN (the reference's connect retries inside try_send)
    ok = jnp.asarray(when) & ((st["cn_state"][dst] == CLOSED)
                              | (st["cn_state"][dst] == SYN_SENT))
    st["cn_state"] = st["cn_state"].at[dst].set(
        jnp.where(ok, SYN_SENT, st["cn_state"][dst]))
    ctx.send(dst, TAG_SYN, [st["cn_epoch"][dst]], when=ok)
    return ok


def is_established(st, peer):
    return st["cn_state"][jnp.asarray(peer, jnp.int32)] == ESTABLISHED


def on_message(ctx: Ctx, st, src, tag, payload=None, *, epoch_guard=True):
    """Feed connection-control messages through the state machine. Returns
    (accepted, established, reset) masks for this event. Call before
    stream.on_message; data for CLOSED peers should be ignored by the app.

    `payload` carries the epoch word of the r19 handshake frames; passing
    None degrades to epoch 0 everywhere (legacy call sites — the guard
    then never rejects, which is also what `epoch_guard=False` selects:
    the pre-r19 behavior where ANY RST closes an ESTABLISHED connection
    regardless of incarnation; kept compilable as the honest red control
    for the exactly-once flagship).
    """
    from ..utils.maskutil import needed, statically_false
    if statically_false((tag == TAG_SYN) | (tag == TAG_SYN_ACK)
                        | (tag == TAG_RST)):
        f = jnp.asarray(False)
        return f, f, f
    src = jnp.asarray(src, jnp.int32)
    carried = (jnp.asarray(payload[0], jnp.int32) if payload is not None
               else jnp.asarray(0, jnp.int32))

    # listener side: SYN while listening -> ESTABLISHED + SYN-ACK;
    # SYN while not listening -> RST (connection refused). The accepted
    # generation is max(proposal, own counter) — monotone across resets
    # on EITHER side, idempotent for duplicate SYNs of the same dial.
    is_syn = tag == TAG_SYN
    accept = is_syn & (st["cn_listen"] == 1)
    refuse = is_syn & (st["cn_listen"] != 1)
    e_acc = jnp.maximum(carried, st["cn_epoch"][src])
    st["cn_epoch"] = st["cn_epoch"].at[src].set(
        jnp.where(accept, e_acc, st["cn_epoch"][src]))
    st["cn_state"] = st["cn_state"].at[src].set(
        jnp.where(accept, ESTABLISHED, st["cn_state"][src]))
    ctx.send(src, TAG_SYN_ACK, [e_acc], when=accept)
    # a refusal RST names the generation the SYN proposed, so the
    # initiator recognizes it as aimed at ITS current dial
    ctx.send(src, TAG_RST, [carried], when=refuse)
    if needed(accept) and _has_stream(st):
        # fresh connection, fresh stream fabric, re-based on the
        # negotiated generation (both endpoints land on the same value).
        # ONLY when the generation actually advances: a network-
        # DUPLICATED SYN of the current generation (the r19 dup-storm
        # fault) re-accepts with the same epoch, and re-wiping then
        # would reopen the receive window — already-delivered same-
        # epoch segments would deliver again, breaking exactly-once
        from . import stream
        stream.reset_peer(st, src,
                          when=accept & (e_acc > st["st_epoch"][src]),
                          epoch=e_acc)

    # initiator side: SYN-ACK completes the handshake and installs the
    # negotiated generation (>= the proposal by construction)
    is_sa = (tag == TAG_SYN_ACK) & (st["cn_state"][src] == SYN_SENT)
    st["cn_epoch"] = st["cn_epoch"].at[src].set(
        jnp.where(is_sa, jnp.maximum(carried, st["cn_epoch"][src]),
                  st["cn_epoch"][src]))
    st["cn_state"] = st["cn_state"].at[src].set(
        jnp.where(is_sa, ESTABLISHED, st["cn_state"][src]))
    if needed(is_sa) and _has_stream(st):
        # same advance-only gate as the accept side: a dup-storm copy
        # of the SYN-ACK must not re-wipe the initiator's fabric
        from . import stream
        e_sa = jnp.maximum(carried, st["cn_epoch"][src])
        stream.reset_peer(st, src,
                          when=is_sa & (e_sa > st["st_epoch"][src]),
                          epoch=e_sa)

    # RST tears the connection down (ConnectionReset) — but only an RST
    # aimed at THIS incarnation (its payload word == our counter): a
    # delayed RST from a torn generation is noise, not a teardown
    # (satellite fix r19; epoch_guard=False restores the pre-r19 close-
    # on-any-RST behavior). A valid RST bumps the counter so the next
    # negotiated generation strictly exceeds the torn one.
    is_rst = tag == TAG_RST
    if epoch_guard:
        is_rst = is_rst & (carried == st["cn_epoch"][src])
    st["cn_state"] = st["cn_state"].at[src].set(
        jnp.where(is_rst, CLOSED, st["cn_state"][src]))
    st["cn_epoch"] = st["cn_epoch"].at[src].set(
        st["cn_epoch"][src] + is_rst)
    if needed(is_rst) and _has_stream(st):
        # the torn generation's in-flight segments must be stale to
        # whatever connection comes next
        from . import stream
        stream.reset_peer(st, src, when=is_rst)

    return accept, is_sa, is_rst


def reset(ctx: Ctx, st, peer, *, when=True):
    """Abort a connection and notify the peer (the reset-on-close path).
    The RST names the torn generation; the local counter bumps past it."""
    from ..utils.maskutil import statically_false
    if statically_false(when):
        return
    peer = jnp.asarray(peer, jnp.int32)
    w = jnp.asarray(when) & (st["cn_state"][peer] != CLOSED)
    ctx.send(peer, TAG_RST, [st["cn_epoch"][peer]], when=w)
    st["cn_state"] = st["cn_state"].at[peer].set(
        jnp.where(w, CLOSED, st["cn_state"][peer]))
    st["cn_epoch"] = st["cn_epoch"].at[peer].set(st["cn_epoch"][peer] + w)
