"""Network layers over the engine's message fabric:

  rpc      — typed request/response with call-id matching and retries
  service  — @rpc method dispatch with stable hashed tags
  stream   — ordered reliable delivery (sliding window, retransmission)
  conn     — connection lifecycle (listen/connect/accept/reset)
"""
