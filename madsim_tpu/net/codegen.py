"""Schema -> service codegen: the madsim-tonic-build analog.

The reference generates its client/server API from .proto files at build
time (madsim-tonic-build/src/server.rs:104-128 emits the server trait +
dispatch; client.rs the typed stubs). The state-machine analog consumes the
same proto3 *shape* — `message` word layouts and `service { rpc ... }`
blocks — and emits a Python module:

  - one `Layout` + pack/unpack converters per message (float fields ride
    int32 words by bitcast, utils/structs.py),
  - one `<Service>Base(Service)` class whose generated `@rpc` methods
    unpack the request, delegate to an abstract `handle_<method>`, and
    pack the reply (`@rpc_stream` stubs for streaming rpcs),
  - one typed client helper per method wrapping `net.rpc.call`.

Supported field scalars: one int32 word each — int32, uint32, sint32,
bool, float (bitcast). `repeated`/nested messages are rejected: payloads
are fixed-width word vectors (DESIGN §5 "bulk data" explains the stance);
ship fixed-size bursts as explicit fields or use the streaming fabric.

Usage:
    python -m madsim_tpu.net.codegen schema.proto -o schema_pb.py
or  source = generate(open("schema.proto").read())
"""

from __future__ import annotations

import re

_WORD_TYPES = ("int32", "uint32", "sint32", "bool", "float")


def _strip_comments(text: str) -> str:
    text = re.sub(r"//[^\n]*", "", text)
    return re.sub(r"/\*.*?\*/", "", text, flags=re.S)


def _snake(name: str) -> str:
    s = re.sub(r"(?<=[a-z0-9])(?=[A-Z])", "_", name)
    return s.lower()


def _blocks(text: str, kw: str):
    """Brace-balanced `kw Name { body }` blocks. Balanced extraction (not
    a [^{}]* regex) so a nested brace is SEEN and rejected by the caller
    instead of silently un-matching the whole block."""
    for m in re.finditer(rf"\b{kw}\s+(\w+)\s*\{{", text):
        depth, i = 1, m.end()
        while depth:
            assert i < len(text), f"unbalanced braces in {kw} {m.group(1)}"
            depth += (text[i] == "{") - (text[i] == "}")
            i += 1
        yield m.group(1), text[m.end():i - 1]


def parse(text: str):
    """-> (messages, services); messages: {name: [(type, field)...]},
    services: {name: [(method, req, req_stream, rsp, rsp_stream)...]}."""
    text = _strip_comments(text)
    messages, services = {}, {}
    for name, body in _blocks(text, "message"):
        assert "{" not in body, (
            f"message {name}: nested messages are unsupported — payloads "
            "are flat fixed-width word vectors")
        fields = []
        assert name not in messages, f"duplicate message {name}"
        seen_nums = set()
        for line in filter(None, (s.strip() for s in body.split(";"))):
            fm = re.match(r"(repeated\s+)?(\w+)\s+(\w+)\s*=\s*(\d+)$", line)
            assert fm, f"unparseable field in message {name}: {line!r}"
            fnum = int(fm.group(4))
            assert fnum not in seen_nums, (
                f"message {name}: duplicate field number {fnum}")
            seen_nums.add(fnum)
            assert not fm.group(1), (
                f"{name}.{fm.group(3)}: repeated fields are unsupported — "
                "payloads are fixed-width word vectors; use explicit "
                "fields or the streaming fabric")
            ftype = fm.group(2)
            assert ftype in _WORD_TYPES, (
                f"{name}.{fm.group(3)}: type {ftype!r} unsupported "
                f"(one-word scalars only: {_WORD_TYPES})")
            fields.append((ftype, fm.group(3)))
        messages[name] = fields
    for name, body in _blocks(text, "service"):
        assert name not in services, f"duplicate service {name}"
        assert "{" not in body, (
            f"service {name}: rpc options blocks ('rpc ... {{}}') are "
            "unsupported — end each rpc with ';'")
        rpcs = []
        for rm in re.finditer(
                r"rpc\s+(\w+)\s*\(\s*(stream\s+)?(\w+)\s*\)\s*"
                r"returns\s*\(\s*(stream\s+)?(\w+)\s*\)", body):
            meth, req_s, req, rsp_s, rsp = rm.groups()
            assert req in messages, f"{name}.{meth}: unknown message {req}"
            assert rsp in messages, f"{name}.{meth}: unknown message {rsp}"
            assert meth not in (r[0] for r in rpcs), (
                f"service {name}: duplicate rpc {meth} — the generated "
                "class would silently shadow the first definition")
            rpcs.append((meth, req, bool(req_s), rsp, bool(rsp_s)))
        services[name] = rpcs
    return messages, services


def _const(name: str) -> str:
    return _snake(name).upper()


def _emit_message(name, fields, out):
    names = ", ".join(repr(f) for _, f in fields)
    floats = [f for t, f in fields if t == "float"]
    out.append(f"{_const(name)} = Layout({names})")
    out.append(f"def pack_{_snake(name)}(**fields):")
    for f in floats:
        out.append(f"    if {f!r} in fields:"
                   f" fields[{f!r}] = f32_to_word(fields[{f!r}])")
    out.append(f"    return {_const(name)}.pack(**fields)")
    out.append(f"def unpack_{_snake(name)}(words):")
    out.append(f"    d = {_const(name)}.unpack(words)")
    for f in floats:
        out.append(f"    d[{f!r}] = word_to_f32(d[{f!r}])")
    out.append("    return d")
    out.append("")


def _emit_service(name, rpcs, out):
    base = f"{name}Base"
    out.append(f"class {base}(Service):")
    out.append(f'    """Override each handle_* (server half); the @rpc')
    out.append("    wrappers do the unpack/dispatch/pack plumbing.\"\"\"")
    for meth, req, req_s, rsp, rsp_s in rpcs:
        h = f"handle_{_snake(meth)}"
        if req_s or rsp_s:
            out.append("    @rpc_stream")
            out.append(f"    def {meth}(self, ctx, st, src, kind, call_id,"
                       " body, when):")
            out.append(f"        self.{h}(ctx, st, src, kind, call_id,"
                       " body, when)")
            out.append(f"    def {h}(self, ctx, st, src, kind, call_id,"
                       " body, when):")
            out.append(f"        raise NotImplementedError({h!r})")
        else:
            out.append("    @rpc")
            out.append(f"    def {meth}(self, ctx, st, payload, when):")
            out.append(f"        req = unpack_{_snake(req)}(payload[1:])")
            out.append(f"        rsp = self.{h}(ctx, st, req, when)")
            out.append(f"        return pack_{_snake(rsp)}(**rsp)")
            out.append(f"    def {h}(self, ctx, st, req, when):")
            out.append(f"        raise NotImplementedError({h!r})")
    out.append("")
    for meth, req, req_s, rsp, rsp_s in rpcs:
        if req_s or rsp_s:
            continue  # stream calls go through net.streaming directly
        out.append(f"def {_snake(name)}_{_snake(meth)}(ctx, dst, call_id,"
                   " *, retry_timer_tag, timeout, when=True, **fields):")
        out.append(f'    """Typed client stub: {name}.{meth}({req}) ->'
                   f" {rsp}. Reply arrives tagged"
                   f" reply_tag({base}.{meth}.tag) with payload[0] ="
                   ' call_id, body unpacked by'
                   f' unpack_{_snake(rsp)}(payload[1:])."""')
        out.append(f"    _rpc.call(ctx, dst, {base}.{meth}.tag,"
                   f" pack_{_snake(req)}(**fields), call_id,")
        out.append("              retry_timer_tag=retry_timer_tag,"
                   " timeout=timeout, when=when)")
    out.append("")


def generate(text: str) -> str:
    """Proto3-subset schema text -> Python module source."""
    messages, services = parse(text)
    out = [
        '"""Generated by madsim_tpu.net.codegen — DO NOT EDIT."""',
        "from madsim_tpu.net import rpc as _rpc",
        "from madsim_tpu.net.service import Service, rpc, rpc_stream",
        "from madsim_tpu.utils.structs import (Layout, f32_to_word,",
        "                                      word_to_f32)",
        "",
    ]
    for name, fields in messages.items():
        _emit_message(name, fields, out)
    for name, rpcs in services.items():
        _emit_service(name, rpcs, out)
    return "\n".join(out) + "\n"


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description="Generate a madsim_tpu service module from a "
                    "proto3-subset schema (the tonic-build analog).")
    ap.add_argument("schema")
    ap.add_argument("-o", "--out", required=True)
    args = ap.parse_args(argv)
    with open(args.schema) as f:
        src = generate(f.read())
    with open(args.out, "w") as f:
        f.write(src)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
