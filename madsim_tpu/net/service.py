"""Service sugar: the `#[madsim::service]` / tonic-server analog.

The reference macro scans an impl block for `#[rpc]` methods and generates a
`serve()` that registers each as a tag handler, deriving stable request IDs
by const-hashing the type path (madsim-macros/src/service.rs:61-111,
net/rpc.rs:81-91 `hash_str`). The state-machine analog: subclass `Service`,
decorate methods with `@rpc`, and the base class's `on_message` dispatches
by a stable per-method tag (same hash idea) and sends the reply — every
method body runs each event (SIMD), gated by its `when` mask.

    class Counter(Service):
        @rpc
        def add(self, ctx, st, payload, when):
            st["total"] = st["total"] + jnp.where(when, payload[1], 0)
            return [st["total"]]          # reply body

    client side: net.rpc.call(ctx, server, Counter.add.tag, [5], call_id,
                              retry_timer_tag=..., timeout=...)
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.api import Ctx, Program
from . import rpc as _rpc


def _hash33(s: str) -> int:
    """Stable 31-bit string hash (the hash_str const-fn shape,
    rpc.rs:81-91) for deriving method tags from qualified names."""
    h = 5381
    for c in s.encode():
        h = (h * 33 + c) & 0x7FFFFFFF
    return h | 1  # never 0, keep positive, below the REPLY_BIT


def rpc(fn):
    """Mark a Service method as an RPC handler. The method receives
    (ctx, st, payload, when) and returns the reply body (list of int32
    words); its tag is `Method.tag`."""
    fn._rpc_tag = _hash33(fn.__qualname__) % (1 << 29)
    fn.tag = fn._rpc_tag
    return fn


def rpc_stream(fn):
    """Mark a Service method as a STREAMING handler (the tonic
    client/server/bidi-streaming shapes, madsim-tonic client.rs:52-124).

    Called once per frame delivered by the reliable stream fabric, with
    (ctx, st, src, kind, call_id, body, when); kind is streaming.K_CALL /
    K_ITEM / K_END. The method consumes the request stream frame-by-frame
    and produces its response (stream) with streaming.push/finish/reply.
    Senders must pass `method=Method.tag` on every frame so dispatch works
    on items, not just the opening call.
    """
    fn._rpc_stream_tag = _hash33(fn.__qualname__) % (1 << 29)
    fn.tag = fn._rpc_stream_tag
    return fn


class Service(Program):
    """Base class dispatching tagged requests to @rpc methods and sending
    replies with the net.rpc call-id convention."""

    def _handlers(self):
        hs = []
        for name in dir(type(self)):
            m = getattr(type(self), name)
            if callable(m) and hasattr(m, "_rpc_tag"):
                hs.append(m)
        hs.sort(key=lambda m: m._rpc_tag)
        tags = [m._rpc_tag for m in hs]
        assert len(set(tags)) == len(tags), (
            f"@rpc tag hash collision in {type(self).__name__}: "
            f"{[m.__qualname__ for m in hs]} — rename a method")
        return hs

    def _stream_handlers(self):
        hs = []
        for name in dir(type(self)):
            m = getattr(type(self), name)
            if callable(m) and hasattr(m, "_rpc_stream_tag"):
                hs.append(m)
        hs.sort(key=lambda m: m._rpc_stream_tag)
        tags = [m._rpc_stream_tag for m in hs]
        assert len(set(tags)) == len(tags), (
            f"@rpc_stream tag hash collision in {type(self).__name__}: "
            f"{[m.__qualname__ for m in hs]} — rename a method")
        return hs

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        # handler tags are mutually exclusive, so all replies SHARE one send
        # slot (the emission-count discipline of raft's merged broadcasts)
        hs = self._handlers()
        width = 0
        merged_tag = jnp.asarray(0, jnp.int32)
        merged_when = jnp.asarray(False)
        bodies = []
        for m in hs:
            when = tag == m._rpc_tag
            body = [jnp.asarray(wd, jnp.int32) for wd in
                    m(self, ctx, st, payload, when)]
            bodies.append((when, body))
            width = max(width, len(body))
            merged_tag = jnp.where(when, m._rpc_tag, merged_tag)
            merged_when = merged_when | when
        zero = jnp.asarray(0, jnp.int32)
        merged_body = [zero] * width
        for when, body in bodies:
            for i, wd in enumerate(body):
                merged_body[i] = jnp.where(when, wd, merged_body[i])
        ctx.send(src, _rpc.reply_tag(merged_tag),
                 [payload[0]] + merged_body, when=merged_when)

        # ---- streaming methods: dispatch each frame the reliable stream
        # fabric delivers this event (requires streaming_state fields in
        # the service's state spec)
        shs = self._stream_handlers()
        if shs:
            assert "sx_val" in st, (
                f"{type(self).__name__} has @rpc_stream methods but its "
                "state spec lacks streaming_state(...) fields — frames "
                "would be silently ignored")
            from . import stream as _stream
            from . import streaming
            kinds, methods, cids, bodies_f, mask = streaming.on_stream(
                ctx, st, src, tag, payload)
            for i in _stream.delivered_slots(mask):
                for m in shs:
                    m(self, ctx, st, src, kinds[i], cids[i], bodies_f[i],
                      mask[i] & (methods[i] == m._rpc_stream_tag))
        ctx.state = st
