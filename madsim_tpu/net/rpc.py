"""Typed request/response helpers — the sim RPC layer.

madsim's RPC (net/rpc.rs:93-165) works by drawing a random response tag,
sending `(rsp_tag, request)` on the request type's tag, and awaiting the
response tag. The state-machine analog: the caller draws a random call id,
stashes it in its protocol state, sends it in the payload, and matches it on
the reply; a retry timer re-sends until the matching reply lands (timeouts
are first-class here rather than bolted on via `call_timeout`).

Conventions used by these helpers:
  payload[0] = call id (random per attempt chain, constant across retries)
  payload[1:] = request/response body
Reply tags are `reply_tag(req_tag)` = req_tag | REPLY_BIT.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.api import Ctx

REPLY_BIT = 1 << 30


def reply_tag(req_tag):
    return req_tag | REPLY_BIT


def is_reply(tag):
    return (tag & REPLY_BIT) != 0


def new_call_id(ctx: Ctx):
    """Random positive int32 call id (rpc.rs:120 draws a random rsp tag)."""
    return ctx.randint(1, 2**30 - 1)


def call(ctx: Ctx, dst, req_tag, body, call_id, *, retry_timer_tag,
         timeout, when=True):
    """Send a request and arm its retry/timeout timer.

    body: list of int32 words (payload[1:]). On timeout the caller's
    on_timer fires with `retry_timer_tag`; re-issue with the SAME call_id to
    retry, or a fresh id to abandon.
    """
    ctx.send(dst, req_tag, [call_id] + list(body), when=when)
    ctx.set_timer(timeout, retry_timer_tag, [call_id], when=when)


def reply(ctx: Ctx, src, req_tag, payload, body, *, when=True):
    """Answer a request: echoes payload[0] (the call id) back with the body
    (the server half of add_rpc_handler, rpc.rs:142-165)."""
    ctx.send(src, reply_tag(req_tag), [payload[0]] + list(body), when=when)


def matches(payload, call_id):
    """Does this reply answer the outstanding call? (stale/duplicate replies
    — e.g. from a retry race — must be ignored by the caller)."""
    return payload[0] == call_id
