"""Reliable ordered streams over the unordered, lossy message layer — the
simulated-TCP analog.

The reference gives applications `TcpStream` objects backed by an in-memory
duplex ring buffer with loss-free FIFO delivery (sim/net/tcp/stream.rs:
96-126), while its datagram Endpoint may drop and reorder. Here the same
split exists: the engine's messages are UDP-like (latency jitter reorders,
loss drops, clogs block — and under the r19 dup-storm knob, DUPLICATES),
and this module layers TCP semantics on top as a state-machine library:
sliding-window transmission, cumulative acks, timer-driven retransmission,
exactly-once in-order delivery. Window slots are a fixed ring (seq %
window), so everything is static-shape and vectorizes across the seed
batch.

PEER INCARNATIONS (r19, DESIGN §20): every DATA and ACK frame is stamped
with the sender's per-peer stream epoch (`st_epoch[peer]` — the
connection GENERATION, negotiated by net/conn.py's handshake or bumped
locally by `reset_peer`). The receiver drops frames from an OLDER
generation (a killed-and-restarted peer's stale retransmits can no
longer be accepted into the fresh sequence space — the corruption this
plane exists to prevent) and ADOPTS a newer one (the reset it missed:
wipe both directions, jump the epoch, process the frame). A stale ACK is
equally rejected — it must not slide the successor window. Pass
`epoch_guard=False` to `on_message` to compile the pre-r19 accept-
everything behavior (the flagship's honest red control).

Usage inside a Program (see tests/test_stream.py):
    spec = {**my_spec, **stream.stream_state(n_nodes, window=4)}
    # sender:  stream.send(ctx, st, dst, value, when=...)
    #          stream.retransmit(ctx, st, dst, when=timer_fired)
    # receiver (in on_message):
    #          vals, mask = stream.on_message(ctx, st, src, tag, payload)
    #          -> up to `window` values delivered IN ORDER this event
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.api import Ctx

TAG_DATA = 1 << 20
TAG_ACK = (1 << 20) + 1


def stream_state(n_nodes: int, window: int = 4, item_words: int = 1):
    """Per-node stream state: one bidirectional stream per peer.

    item_words > 1 makes each stream element a fixed int32 vector instead of
    a scalar (the framed-message case: streaming RPC items, file chunks) —
    rings gain a trailing [item_words] axis and send/on_message move whole
    vectors. Requires payload_words >= 2 + item_words (seq + epoch +
    item — the r19 incarnation stamp widened every frame by one word).
    """
    N, W, V = n_nodes, window, item_words
    z = jnp.zeros((N,), jnp.int32)
    shape = (N, W) if V == 1 else (N, W, V)
    return dict(
        sx_seq=z,                                  # next seq to assign (tx)
        sx_base=z,                                 # lowest unacked seq
        sx_val=jnp.zeros(shape, jnp.int32),        # unacked ring
        sr_next=z,                                 # next expected seq (rx)
        sr_val=jnp.zeros(shape, jnp.int32),        # out-of-order ring
        sr_have=jnp.zeros((N, W), bool),
        st_epoch=z,                                # peering incarnation
    )


def _window(st):
    return st["sr_have"].shape[1]


def _item_words(st):
    v = st["sx_val"]
    return 1 if v.ndim == 2 else v.shape[2]


def _as_item(val, V):
    """Coerce a scalar / list / vector into the stream's item shape."""
    if V == 1:
        return jnp.asarray(val, jnp.int32)
    if isinstance(val, (list, tuple)):
        items = [jnp.asarray(x, jnp.int32) for x in val]
        items += [jnp.zeros((), jnp.int32)] * (V - len(items))
        return jnp.stack(items)
    val = jnp.asarray(val, jnp.int32)
    assert val.shape == (V,), f"stream item must be ({V},), got {val.shape}"
    return val


def _data_payload(seq, epoch, item, V):
    if V == 1:
        return [seq, epoch, item]
    return jnp.concatenate([jnp.stack([seq, epoch]), item])


def send(ctx: Ctx, st, dst, val, *, when=True):
    """Enqueue one value on the stream to `dst` and transmit it. Refused
    (returns False mask) when the send window is full — like a TCP write
    blocking on a full buffer (stream.rs:185-209)."""
    from ..utils.maskutil import statically_false
    if statically_false(when):
        return jnp.asarray(False)
    W, V = _window(st), _item_words(st)
    dst = jnp.asarray(dst, jnp.int32)
    val = _as_item(val, V)
    seq = st["sx_seq"][dst]
    room = (seq - st["sx_base"][dst]) < W
    ok = jnp.asarray(when) & room
    slot = seq % W
    st["sx_val"] = st["sx_val"].at[dst, slot].set(
        jnp.where(ok, val, st["sx_val"][dst, slot]))
    st["sx_seq"] = st["sx_seq"].at[dst].set(seq + ok)
    ctx.send(dst, TAG_DATA, _data_payload(seq, st["st_epoch"][dst], val, V),
             when=ok)
    return ok


def retransmit(ctx: Ctx, st, dst, *, when=True):
    """Resend every unacked value to `dst` (cumulative-ack Go-Back-N).
    Arm a periodic timer and call this on fire.

    Incarnation contract (r19 satellite): a timer that fires AFTER
    `reset_peer` tore this peer's fabric is a structural no-op — the
    reset zeroed sx_base == sx_seq, so no slot is live — and anything it
    WOULD send stamps the CURRENT epoch, so a stale timer can never
    inject old-incarnation segments into the successor connection
    (tests/test_connfault.py holds reset-between-send-and-fire)."""
    from ..utils.maskutil import statically_false
    if statically_false(when):
        return
    W, V = _window(st), _item_words(st)
    dst = jnp.asarray(dst, jnp.int32)
    base, nxt = st["sx_base"][dst], st["sx_seq"][dst]
    for i in range(W):
        seq = base + i
        live = jnp.asarray(when) & (seq < nxt)
        if statically_false(live):
            continue
        ctx.send(dst, TAG_DATA,
                 _data_payload(seq, st["st_epoch"][dst],
                               st["sx_val"][dst, seq % W], V),
                 when=live)


def delivered_slots(mask):
    """Iteration helper for the per-event delivery loop.

    Under jit/vmap (the simulator) `mask` is a tracer, so every slot must
    be visited with masked ops — that's the fixed-shape discipline. In the
    real-world runtime (real/runtime.py) values are concrete and almost
    every slot is empty; visiting only the delivered ones keeps eager
    dispatch cost proportional to actual traffic. Call sites are identical
    in both worlds.
    """
    import jax

    if isinstance(mask, jax.core.Tracer):
        return range(mask.shape[0])
    import numpy as np

    return np.nonzero(np.asarray(mask))[0].tolist()


def _wipe_peer(st, peer, w):
    """Zero both directions of the ring/counter fabric to `peer` under
    mask `w` — shared by reset_peer and the on_message adoption path."""
    z = jnp.zeros((), jnp.int32)
    for k in ("sx_seq", "sx_base", "sr_next"):
        st[k] = st[k].at[peer].set(jnp.where(w, z, st[k][peer]))
    st["sx_val"] = st["sx_val"].at[peer].set(
        jnp.where(w, 0, st["sx_val"][peer]))
    st["sr_val"] = st["sr_val"].at[peer].set(
        jnp.where(w, 0, st["sr_val"][peer]))
    st["sr_have"] = st["sr_have"].at[peer].set(
        jnp.where(w, False, st["sr_have"][peer]))


def reset_peer(st, peer, *, when=True, epoch=None):
    """Wipe both directions of the stream to `peer` (fresh sequence space)
    and advance the peering's incarnation. Pair with conn-layer
    reset/reconnect: a restarted peer lost its stream state, so the
    survivor must restart the sequence space too — exactly a new TCP
    connection after the old one died (stream.rs:162-209).

    `epoch=None` (standalone use) bumps the incarnation by one — the old
    generation's in-flight segments and acks become STALE to this
    endpoint. The conn layer instead passes the handshake-NEGOTIATED
    generation so both endpoints land on the same value (conn.py r19)."""
    from ..utils.maskutil import statically_false
    if statically_false(when):
        return
    peer = jnp.asarray(peer, jnp.int32)
    w = jnp.asarray(when)
    _wipe_peer(st, peer, w)
    new_ep = (st["st_epoch"][peer] + 1 if epoch is None
              else jnp.asarray(epoch, jnp.int32))
    st["st_epoch"] = st["st_epoch"].at[peer].set(
        jnp.where(w, new_ep, st["st_epoch"][peer]))


def on_message(ctx: Ctx, st, src, tag, payload, *, epoch_guard=True):
    """Feed a received message through the stream layer.

    Returns (vals, mask): up to `window` values newly deliverable IN ORDER
    (mask[i] marks validity; process them with masked ops). vals has shape
    [window] for scalar streams, [window, item_words] for vector streams.
    Non-stream tags return an all-False mask — safe to call unconditionally.

    Incarnation guard (r19): payload[1] carries the sender's stream epoch
    on every DATA and ACK frame. Frames from an OLDER generation than
    `st_epoch[src]` are dropped (no delivery, no re-ack, no window
    slide); a NEWER generation is ADOPTED — both directions wiped, epoch
    jumped — before the frame is processed, covering the endpoint that
    missed a reset. `epoch_guard=False` compiles the pre-r19 behavior
    (every frame accepted regardless of incarnation) — the red control
    that lets tests PROVE the guard is what makes restart-under-churn
    sound.
    """
    from ..utils.maskutil import statically_false
    W, V = _window(st), _item_words(st)
    if statically_false((tag == TAG_DATA) | (tag == TAG_ACK)):
        shape = (W,) if V == 1 else (W, V)
        return jnp.zeros(shape, jnp.int32), jnp.zeros((W,), bool)
    from ..utils.maskutil import needed
    src = jnp.asarray(src, jnp.int32)

    is_data = tag == TAG_DATA
    is_ack = tag == TAG_ACK
    if epoch_guard:
        ep = jnp.asarray(payload[1], jnp.int32)
        cur = st["st_epoch"][src]
        relevant = is_data | is_ack
        fresh = relevant & (ep > cur)
        stale = relevant & (ep < cur)
        if needed(fresh):
            # the peer moved to a newer incarnation (a reset this side
            # missed): wipe both directions onto it, then let the frame
            # land in the fresh window
            _wipe_peer(st, src, fresh)
            st["st_epoch"] = st["st_epoch"].at[src].set(
                jnp.where(fresh, ep, cur))
        is_data = is_data & ~stale
        is_ack = is_ack & ~stale

    # ---- DATA: buffer in-window segments, deliver the contiguous run ----
    if needed(is_data):
        seq = payload[0]
        val = payload[2] if V == 1 else payload[2:2 + V]
        nxt = st["sr_next"][src]
        in_win = is_data & (seq >= nxt) & (seq < nxt + W)
        slot = seq % W
        st["sr_val"] = st["sr_val"].at[src, slot].set(
            jnp.where(in_win, val, st["sr_val"][src, slot]))
        st["sr_have"] = st["sr_have"].at[src, slot].set(
            st["sr_have"][src, slot] | in_win)

        # longest contiguous run starting at sr_next (exactly-once, in-order)
        offs = jnp.arange(W, dtype=jnp.int32)
        have_seq = st["sr_have"][src, (nxt + offs) % W]
        run = jnp.cumprod(have_seq.astype(jnp.int32))      # 1,1,..,0,..
        count = run.sum()
        deliver = is_data & (run == 1)
        vals = st["sr_val"][src, (nxt + offs) % W]
        # clear delivered slots, advance the window
        st["sr_have"] = st["sr_have"].at[src, (nxt + offs) % W].set(
            jnp.where(deliver, False, st["sr_have"][src, (nxt + offs) % W]))
        st["sr_next"] = st["sr_next"].at[src].set(
            nxt + jnp.where(is_data, count, 0))
        # cumulative ack (also for duplicates below the window — re-ack),
        # stamped with the generation it acknowledges
        ctx.send(src, TAG_ACK, [st["sr_next"][src], st["st_epoch"][src]],
                 when=is_data)
    else:
        shape = (W,) if V == 1 else (W, V)
        vals = jnp.zeros(shape, jnp.int32)
        deliver = jnp.zeros((W,), bool)

    # ---- ACK: slide the send window ------------------------------------
    if needed(is_ack):
        cum = payload[0]
        st["sx_base"] = st["sx_base"].at[src].set(
            jnp.where(is_ack,
                      jnp.clip(cum, st["sx_base"][src], st["sx_seq"][src]),
                      st["sx_base"][src]))

    return vals, deliver
