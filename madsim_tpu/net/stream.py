"""Reliable ordered streams over the unordered, lossy message layer — the
simulated-TCP analog.

The reference gives applications `TcpStream` objects backed by an in-memory
duplex ring buffer with loss-free FIFO delivery (sim/net/tcp/stream.rs:
96-126), while its datagram Endpoint may drop and reorder. Here the same
split exists: the engine's messages are UDP-like (latency jitter reorders,
loss drops, clogs block), and this module layers TCP semantics on top as a
state-machine library: sliding-window transmission, cumulative acks,
timer-driven retransmission, exactly-once in-order delivery. Window slots
are a fixed ring (seq % window), so everything is static-shape and
vectorizes across the seed batch.

Usage inside a Program (see tests/test_stream.py):
    spec = {**my_spec, **stream.stream_state(n_nodes, window=4)}
    # sender:  stream.send(ctx, st, dst, value, when=...)
    #          stream.retransmit(ctx, st, dst, when=timer_fired)
    # receiver (in on_message):
    #          vals, mask = stream.on_message(ctx, st, src, tag, payload)
    #          -> up to `window` values delivered IN ORDER this event
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.api import Ctx

TAG_DATA = 1 << 20
TAG_ACK = (1 << 20) + 1


def stream_state(n_nodes: int, window: int = 4):
    """Per-node stream state: one bidirectional stream per peer."""
    N, W = n_nodes, window
    z = jnp.zeros((N,), jnp.int32)
    return dict(
        sx_seq=z,                                  # next seq to assign (tx)
        sx_base=z,                                 # lowest unacked seq
        sx_val=jnp.zeros((N, W), jnp.int32),       # unacked ring
        sr_next=z,                                 # next expected seq (rx)
        sr_val=jnp.zeros((N, W), jnp.int32),       # out-of-order ring
        sr_have=jnp.zeros((N, W), bool),
    )


def _window(st):
    return st["sr_have"].shape[1]


def send(ctx: Ctx, st, dst, val, *, when=True):
    """Enqueue one value on the stream to `dst` and transmit it. Refused
    (returns False mask) when the send window is full — like a TCP write
    blocking on a full buffer (stream.rs:185-209)."""
    W = _window(st)
    dst = jnp.asarray(dst, jnp.int32)
    seq = st["sx_seq"][dst]
    room = (seq - st["sx_base"][dst]) < W
    ok = jnp.asarray(when) & room
    slot = seq % W
    st["sx_val"] = st["sx_val"].at[dst, slot].set(
        jnp.where(ok, val, st["sx_val"][dst, slot]))
    st["sx_seq"] = st["sx_seq"].at[dst].set(seq + ok)
    ctx.send(dst, TAG_DATA, [seq, val], when=ok)
    return ok


def retransmit(ctx: Ctx, st, dst, *, when=True):
    """Resend every unacked value to `dst` (cumulative-ack Go-Back-N).
    Arm a periodic timer and call this on fire."""
    W = _window(st)
    dst = jnp.asarray(dst, jnp.int32)
    base, nxt = st["sx_base"][dst], st["sx_seq"][dst]
    for i in range(W):
        seq = base + i
        live = jnp.asarray(when) & (seq < nxt)
        ctx.send(dst, TAG_DATA, [seq, st["sx_val"][dst, seq % W]], when=live)


def on_message(ctx: Ctx, st, src, tag, payload):
    """Feed a received message through the stream layer.

    Returns (vals, mask): up to `window` values newly deliverable IN ORDER
    (mask[i] marks validity; process them with masked ops). Non-stream tags
    return an all-False mask — safe to call unconditionally.
    """
    W = _window(st)
    src = jnp.asarray(src, jnp.int32)

    # ---- DATA: buffer in-window segments, deliver the contiguous run ----
    is_data = tag == TAG_DATA
    seq, val = payload[0], payload[1]
    nxt = st["sr_next"][src]
    in_win = is_data & (seq >= nxt) & (seq < nxt + W)
    slot = seq % W
    st["sr_val"] = st["sr_val"].at[src, slot].set(
        jnp.where(in_win, val, st["sr_val"][src, slot]))
    st["sr_have"] = st["sr_have"].at[src, slot].set(
        st["sr_have"][src, slot] | in_win)

    # longest contiguous run starting at sr_next (exactly-once, in-order)
    offs = jnp.arange(W, dtype=jnp.int32)
    have_seq = st["sr_have"][src, (nxt + offs) % W]
    run = jnp.cumprod(have_seq.astype(jnp.int32))      # 1,1,..,0,..
    count = run.sum()
    deliver = is_data & (run == 1)
    vals = st["sr_val"][src, (nxt + offs) % W]
    # clear delivered slots, advance the window
    st["sr_have"] = st["sr_have"].at[src, (nxt + offs) % W].set(
        jnp.where(deliver, False, st["sr_have"][src, (nxt + offs) % W]))
    st["sr_next"] = st["sr_next"].at[src].set(
        nxt + jnp.where(is_data, count, 0))
    # cumulative ack (also for duplicates below the window — re-ack)
    ctx.send(src, TAG_ACK, [st["sr_next"][src]], when=is_data)

    # ---- ACK: slide the send window ------------------------------------
    is_ack = tag == TAG_ACK
    cum = payload[0]
    st["sx_base"] = st["sx_base"].at[src].set(
        jnp.where(is_ack,
                  jnp.clip(cum, st["sx_base"][src], st["sx_seq"][src]),
                  st["sx_base"][src]))

    return vals, deliver
