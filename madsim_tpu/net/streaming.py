"""Streaming RPC — the madsim-tonic analog (all four gRPC method shapes).

The reference simulates tonic by sending each stream item as its own tagged
message and marking termination with a `StreamEnd` sentinel
(madsim-tonic/src/client.rs:52-124 drives unary / client-streaming /
server-streaming / bidi through one code path; codec.rs:30-45 encodes the
end marker). Here the same framing rides the RELIABLE ordered stream layer
(net/stream.py with vector items), so streaming calls survive the lossy
reordering datagram fabric the way tonic calls survive TCP:

  frame = [kind, method_tag, call_id, *body]
    kind: K_CALL (open, carries the request or stream header)
          K_ITEM (one stream element, either direction)
          K_END  (StreamEnd marker)
          K_REPLY (unary/final response)

Call ids are random per call (net/rpc.py convention); items of concurrent
calls interleave on one peer-stream and demux by call_id. Delivery is
exactly-once in-order per peer, so seq numbers and dedup come for free from
the transport — what the reference gets from tonic-over-sim-TCP.

Shapes (client.rs:52-124 parity):
  unary            open(K_CALL+body) ......... reply(K_REPLY+body)
  client-streaming open, push*, finish ....... reply(K_REPLY aggregate)
  server-streaming open(request) ............. push*, finish
  bidi             open, push*, finish ....... push* (echo pacing), finish
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.api import Ctx
from . import stream

K_CALL, K_ITEM, K_END, K_REPLY = 1, 2, 3, 4

HEADER_WORDS = 3  # kind, method_tag, call_id


def streaming_state(n_nodes: int, window: int = 4, body_words: int = 2):
    """Stream-fabric state sized for framed RPC items. Requires
    cfg.payload_words >= 2 + HEADER_WORDS + body_words (seq + epoch +
    frame — the r19 incarnation stamp widened the transport by a word)."""
    return stream.stream_state(n_nodes, window,
                               item_words=HEADER_WORDS + body_words)


def body_width(st) -> int:
    return st["sx_val"].shape[2] - HEADER_WORDS


def _frame(kind, method, call_id, body, V):
    kind = jnp.asarray(kind, jnp.int32)
    words = [kind, jnp.asarray(method, jnp.int32),
             jnp.asarray(call_id, jnp.int32)]
    words += [jnp.asarray(b, jnp.int32) for b in body]
    assert len(words) <= V, f"frame ({len(words)} words) exceeds item ({V})"
    return words


def open_call(ctx: Ctx, st, dst, method, call_id, body=(), *, when=True):
    """Start a call (any shape): K_CALL carries the unary request or the
    stream header. Returns ok mask (False = send window full, try again)."""
    V = st["sx_val"].shape[2]
    return stream.send(ctx, st, dst,
                       _frame(K_CALL, method, call_id, body, V), when=when)


def push(ctx: Ctx, st, dst, call_id, body=(), *, method=0, when=True):
    """Send one stream item on an open call (either direction)."""
    V = st["sx_val"].shape[2]
    return stream.send(ctx, st, dst,
                       _frame(K_ITEM, method, call_id, body, V), when=when)


def finish(ctx: Ctx, st, dst, call_id, *, method=0, when=True):
    """Send the StreamEnd marker (codec.rs:30-45)."""
    V = st["sx_val"].shape[2]
    return stream.send(ctx, st, dst,
                       _frame(K_END, method, call_id, (), V), when=when)


def reply(ctx: Ctx, st, dst, call_id, body=(), *, method=0, when=True):
    """Send the unary / aggregate response for a call."""
    V = st["sx_val"].shape[2]
    return stream.send(ctx, st, dst,
                       _frame(K_REPLY, method, call_id, body, V), when=when)


def on_stream(ctx: Ctx, st, src, tag, payload, *, epoch_guard=True):
    """Feed a received message through transport + framing.

    Returns (kinds[W], methods[W], call_ids[W], bodies[W, B], mask[W]):
    the frames newly deliverable IN ORDER this event. Safe to call
    unconditionally; non-stream tags yield an all-False mask.
    `epoch_guard` passes through to the transport's incarnation check
    (net/stream.py r19)."""
    vals, mask = stream.on_message(ctx, st, src, tag, payload,
                                   epoch_guard=epoch_guard)
    return (vals[:, 0], vals[:, 1], vals[:, 2],
            vals[:, HEADER_WORDS:], mask)


def tick(ctx: Ctx, st, peers, *, when=True):
    """Retransmit unacked frames to each peer (arm a periodic timer and
    call this on fire — the transport's Go-Back-N driver)."""
    for p in peers:
        stream.retransmit(ctx, st, p, when=when)


def reset_peer(st, peer, *, when=True, epoch=None):
    """Tear down the stream fabric to a (restarted) peer — outstanding
    calls die with the connection, as when a tonic channel breaks.
    `epoch` passes through to the transport's incarnation counter."""
    stream.reset_peer(st, peer, when=when, epoch=epoch)
