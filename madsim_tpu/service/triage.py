"""The campaign triage plane: a long campaign as a DIFFABLE product.

`campaign_report` answers "what is in this store right now"; after an
overnight multi-worker run the operator's real questions are *what
changed since yesterday*, *which fault recipe earned which bucket*, and
*do our old repros still reproduce*. This module makes those questions
cheap by snapshotting the store into a standing, versioned history the
rest of the plane (diff, attribution, audit, dashboard) reads:

  triage/NNNN.json   one SNAPSHOT: corpus/coverage/bucket/worker truth
                     folded into a byte-stable document (sorted keys,
                     atomic write-then-rename per §13, and NO field
                     sampled from the wall clock at snapshot time — the
                     identity contract: the same store always produces
                     byte-identical snapshot bodies, so history never
                     lies about what changed)
  triage/ROWS.json   the scenario row table (store.write_triage_rows,
                     appended by the first worker) the recipe
                     classifier reads — attribution without a Runtime
  triage/AUDIT.json  the repro-health ledger `audit_buckets` rotates
                     through (pass/fail/flaky per bucket; snapshots
                     fold it in)

Lifecycle (triage_diff): every causal-fingerprint bucket classifies as
  new        in cur only — a bug the window between snapshots found
  grew       in both, observed again, and it was ACTIVE at prev — the
             still-reproducing known bug (summary only, it is expected)
  regressed  in both, observed again AFTER a quiet period (no
             observation within `quiet_rounds` of prev's newest round)
             — a bug that had gone silent and came back
  stale      vanished from the store, or newly quiet — no observation
             in the recent rounds anymore (candidate for the
             repro-health audit: silent because fixed, or because the
             fuzzer stopped reaching it?)
Diff of a snapshot against itself is provably empty: every diff field
is a prev-vs-cur difference, so equal inputs produce no entries.

Attribution accounting contract: per-recipe attribution assigns every
DISTINCT coverage key (and every merged bucket) exactly one
`runtime.scenario.RECIPE_FAMILIES` family via the persisted row table +
the entry's own knob vector (row toggles, torn/direction flags, and dup
clones all respected — a mutant that dropped its torn row classifies by
what actually ran); per-operator attribution folds the r15 `op_yield`
vectors (coverage) and the bucket records' havoc-operator provenance
(buckets). Both sum EXACTLY to their totals: anything unattributable
(no row table, pre-r18 bucket, worker state without yield vectors)
lands in an explicit `base` class — never a silent "other".

Cost: O(new files) per snapshot off a long-lived store handle — entry
files are immutable, so their (hash, family) classification caches
forever (`CorpusStore._triage_cache`), exactly like the campaign poll
loop's coverage-key cache.
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..runtime.scenario import (RECIPE_FAMILIES, classify_recipe,
                                row_recipe_class)
from ..search.corpus import YIELD_NAMES, split_entry_id
from ..search.mutate import N_MUT_OPS
from .buckets import merged_buckets
from .campaign import campaign_timeline
from .store import CorpusStore, _atomic_bytes

TRIAGE_FORMAT = "madsim-triage"
# v2 (r20): bucket rows carry chain_complete + window_trace, audit
# rows carry chain_complete — additive; v1 snapshots still diff cleanly
# v3 (r22): attribution gains the origin axis (origin_coverage /
# origin_buckets: lineage-targeted vs havoc, search/ldfi.py) and bucket
# rows carry `origin` — additive; v2 snapshots still diff cleanly
TRIAGE_VERSION = 3

# the explicit unattributable class (accounting contract above)
BASE_CLASS = "base"
ATTR_FAMILIES = RECIPE_FAMILIES + (BASE_CLASS,)

# the r22 origin axis: which search arm produced an admission/bucket.
# Anything without a recorded origin (pre-r22 stores, ldfi-less
# campaigns) is "havoc" — factually honest, nothing before r22 aimed.
ORIGIN_CLASSES = ("targeted", "havoc")


# ---------------------------------------------------------------------------
# snapshot naming / loading
# ---------------------------------------------------------------------------

def _as_store(store_or_dir) -> CorpusStore:
    if isinstance(store_or_dir, CorpusStore):
        return store_or_dir
    return CorpusStore(store_or_dir, create=False)


def snapshot_path(store: CorpusStore, n: int) -> str:
    return os.path.join(store.triage_dir(), f"{n:04d}.json")


def list_snapshots(store: CorpusStore) -> list[int]:
    """Snapshot numbers present, ascending (the standing history)."""
    try:
        names = os.listdir(store.triage_dir())
    except FileNotFoundError:
        return []
    out = []
    for n in names:
        stem, ext = os.path.splitext(n)
        if ext == ".json" and stem.isdigit():
            out.append(int(stem))
    return sorted(out)


def load_snapshot(store_or_dir, which="last") -> dict:
    """Load one snapshot: an int NNNN, "last", or "prev" (the one
    before last). Raises FileNotFoundError when the history is too
    short — a campaign that never snapshotted has nothing to diff."""
    store = _as_store(store_or_dir)
    have = list_snapshots(store)
    if isinstance(which, str) and which.isdigit():
        which = int(which)
    if which == "last":
        if not have:
            raise FileNotFoundError(
                f"no triage snapshots under {store.triage_dir()} — "
                "run triage_snapshot() (or service.report --snapshot)")
        which = have[-1]
    elif which == "prev":
        if len(have) < 2:
            raise FileNotFoundError(
                f"need two snapshots to diff against 'prev'; "
                f"{store.triage_dir()} has {len(have)}")
        which = have[-2]
    with open(snapshot_path(store, int(which))) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# the recipe classifier (entry/bucket knob vector -> one family)
# ---------------------------------------------------------------------------

def _row_torn(rows: dict, r: int, knobs: dict) -> bool:
    """The effective torn flag of scenario row r under this knob vector
    (the fuzzer's fault_perturb toggles it; non-torn-capable rows keep
    their base encoding)."""
    if rows["torn_ok"][r]:
        flag = np.asarray(knobs.get("row_flag", ()))
        if flag.size > r:
            return bool(int(flag[r]) & 1)
    return bool(rows["base_torn"][r])


def classify_knobs(rows: dict | None, knobs: dict) -> str:
    """One recipe family for one knob vector, against the persisted row
    table: the classes of every row that would actually RUN under it —
    enabled scenario rows (pinned rows always run) plus enabled dup
    clones of droppable rows (`KnobPlan.to_scenario` semantics) —
    folded by `classify_recipe` precedence. No row table -> the
    explicit BASE_CLASS (pre-r18 store; zero silent leakage)."""
    if rows is None:
        return BASE_CLASS
    ops = rows["op"]
    R = len(ops)
    row_on = np.asarray(knobs.get("row_on", np.ones(R, bool)))
    classes = []
    for r in range(R):
        if not (bool(row_on[r]) or not rows["drop_ok"][r]):
            continue
        classes.append(row_recipe_class(int(ops[r]),
                                        _row_torn(rows, r, knobs)))
    dup_on = np.atleast_1d(np.asarray(knobs.get("dup_on", ())))
    dup_src = np.atleast_1d(np.asarray(knobs.get("dup_src", ())))
    for d in range(dup_on.size):
        if not bool(dup_on[d]):
            continue
        srow = int(np.clip(dup_src[d], 0, R - 1))
        if not rows["drop_ok"][srow]:
            continue
        classes.append(row_recipe_class(int(ops[srow]),
                                        _row_torn(rows, srow, knobs)))
    return classify_recipe(classes)


def _op_name(op) -> str:
    """Havoc-operator index -> YIELD_NAMES label; -1/unknown/missing ->
    the explicit base class (bootstrap lanes, pre-r18 records)."""
    if op is None:
        return BASE_CLASS
    op = int(op)
    return YIELD_NAMES[op] if 0 <= op < N_MUT_OPS else BASE_CLASS


# ---------------------------------------------------------------------------
# the snapshot
# ---------------------------------------------------------------------------

def _entry_files_by_ns(store: CorpusStore) -> dict[int, list[str]]:
    out: dict[int, list[str]] = {}
    for name in store.entry_names():
        w = split_entry_id(store._parse_entry_name(name))[0]
        out.setdefault(w, []).append(name)
    return out


def _committed_entries(store: CorpusStore, states: dict) -> list[str]:
    """The entry files attribution walks: per namespace, only counters
    BELOW the owner's persisted next_counter (half-synced leftovers of
    an interrupted round are quarantined exactly like load_corpus — the
    re-run rewrites them, and counting them now would let a snapshot
    taken mid-kill disagree with one taken after the resume). Files of
    namespaces with no scheduler state at all are kept: a foreign
    merge-only dir is still coverage."""
    next_counter = {w: int(s.get("next_counter", 0))
                    for w, s in states.items()}
    out = []
    for w, names in _entry_files_by_ns(store).items():
        nc = next_counter.get(w)
        for name in sorted(names):
            c = split_entry_id(store._parse_entry_name(name))[1]
            if nc is None or c < nc:
                out.append(name)
    return sorted(out)


# snapshots embed at most this many points per timeline curve (the
# sparkline resolution ceiling; endpoints always kept)
_CURVE_CAP = 512


def _downsample(curve: list, cap: int = _CURVE_CAP) -> list:
    """Deterministic stride-downsample of a [[t, v], ...] series to at
    most `cap` points, first and last always kept — the snapshot's
    curves must not grow the triage history quadratically with a long
    campaign's sync count."""
    n = len(curve)
    if n <= cap:
        return curve
    idx = sorted({round(i * (n - 1) / (cap - 1)) for i in range(cap)})
    return [curve[i] for i in idx]


def _scheduler_states(store: CorpusStore) -> tuple[dict, dict]:
    """({namespace: scheduler state}, {top-level label: state}) over
    plain workers and sharded groups (a group contributes one top-level
    row but one scheduler state per shard namespace)."""
    by_ns: dict[int, dict] = {}
    top: dict[str, dict] = {}
    for w in store.worker_ids():
        ws = store.load_worker_state(w)
        by_ns[w] = ws
        top[f"w{w:04d}"] = ws
    for g in store.shard_group_ids():
        gs = store.load_shard_group_state(g)
        top[f"g{g:04d}"] = gs
        for sh in gs.get("shard_states", []):
            by_ns[int(sh["worker_id"])] = sh
    return by_ns, top


def triage_snapshot(store_or_dir, quiet_rounds: int = 2) -> tuple[int, dict]:
    """Fold the store into one snapshot and append it to the triage/
    history. Returns (snapshot number, body). Byte-stable: the body is
    a pure function of the store's durable contents (sorted keys, no
    wall-clock sampling — `created_at`-style fields are deliberately
    absent), so snapshotting an unchanged store twice writes two files
    with identical bytes and `triage_diff` of the pair is empty."""
    store = _as_store(store_or_dir)
    rows = store.load_triage_rows()
    by_ns, top_states = _scheduler_states(store)
    entry_files = _committed_entries(store, by_ns)

    # -- coverage + per-recipe / per-operator / per-origin attribution --
    recipe_cov = {f: 0 for f in ATTR_FAMILIES}
    origin_cov = {o: 0 for o in ORIGIN_CLASSES}
    claimed: set[int] = set()
    for name in entry_files:
        got = store._triage_cache.get(name)
        # a classification cached while ROWS.json was still absent is
        # provisional (fam None): reclassify once the table appears —
        # entry files are immutable, so everything else caches forever.
        # Pre-r22 cache tuples (len 2, no origin slot) reload once.
        if got is None or (got[1] is None and rows is not None) \
                or len(got) < 3:
            e = store.load_entry(name)
            got = (int(e["hash"]),
                   None if rows is None
                   else classify_knobs(rows, e["knobs"]),
                   e.get("origin") or "havoc")
            store._triage_cache[name] = got
        h, fam = got[0], (BASE_CLASS if got[1] is None else got[1])
        if h in claimed:
            continue                    # first claim wins (sorted walk)
        claimed.add(h)
        recipe_cov[fam] += 1
        origin_cov[got[2] if got[2] in ORIGIN_CLASSES else "havoc"] += 1

    op_cov = {n: 0 for n in YIELD_NAMES}
    attributed_ns: set[int] = set()
    for label, st in sorted(top_states.items()):
        oy = st.get("op_yield")
        if not oy:
            continue
        for i, n in enumerate(oy[:len(YIELD_NAMES)]):
            op_cov[YIELD_NAMES[i]] += int(n)
        if label.startswith("g"):
            attributed_ns |= {int(sh["worker_id"])
                              for sh in st.get("shard_states", [])}
        else:
            attributed_ns.add(int(st.get("worker_id", int(label[1:]))))
    # admissions of workers that never persisted a yield vector land in
    # the explicit base class, so the operator side still sums to the
    # committed-admission total
    for name in entry_files:
        w = split_entry_id(store._parse_entry_name(name))[0]
        if w not in attributed_ns:
            op_cov[BASE_CLASS] += 1

    # -- buckets: merged truth + lifecycle-bearing fields ---------------
    # parse the observation log ONCE and share it with merged_buckets
    # (on a long campaign the log is the store's biggest file)
    obs_log = store.bucket_log_deduped()
    merged = merged_buckets(store, log=obs_log)
    obs_rounds: dict[str, list[int]] = {}
    obs_workers: dict[str, set[int]] = {}
    by_member = {k: m["key"] for m in merged for k in m["members"]}
    for line in obs_log:
        home = by_member.get(line.get("bucket"))
        if home is None:
            continue
        obs_rounds.setdefault(home, []).append(int(line.get("round", 0)))
        obs_workers.setdefault(home, set()).add(
            int(line.get("worker_id", 0)))
    recipe_bk = {f: 0 for f in ATTR_FAMILIES}
    op_bk = {n: 0 for n in YIELD_NAMES}
    origin_bk = {o: 0 for o in ORIGIN_CLASSES}
    buckets = {}
    for m in merged:
        fam = BASE_CLASS
        if rows is not None:
            try:
                _seed, knobs = store.load_bucket_repro(m["key"])
                fam = classify_knobs(rows, knobs)
            except (FileNotFoundError, KeyError):
                fam = BASE_CLASS        # race-only / repro-less bucket
        opn = _op_name(m.get("op"))
        ogn = m.get("origin") if m.get("origin") in ORIGIN_CLASSES \
            else "havoc"
        recipe_bk[fam] += 1
        op_bk[opn] += 1
        origin_bk[ogn] += 1
        rounds = obs_rounds.get(m["key"], [m["repro"].get("round", 0)])
        # r20: chain completeness + the replayed-window trace link.
        # chain_truncated is the recorded truth when present (r20+
        # observations and time-travel upgrades); older records fall
        # back to the fingerprint's depth-capped completeness bit.
        ct = m.get("chain_truncated")
        buckets[m["key"]] = dict(
            crash_code=int(m["crash_code"]),
            crash_node=int(m.get("crash_node", -1)),
            members=sorted(m["members"]),
            observations=int(m["observations"]),
            first_round=int(min(rounds)),
            last_round=int(max(rounds)),
            workers=sorted(obs_workers.get(
                m["key"], {m["repro"].get("worker_id", 0)})),
            recipe=fam,
            op=opn,
            origin=ogn,
            repro={k: int(v) for k, v in m["repro"].items()},
            minimized=bool("minimized" in m),
            chain_complete=((not ct) if ct is not None
                            else bool(m["fingerprint"].get("complete",
                                                           False))),
            # the traced MEMBER key (or None): replay_bucket/audit write
            # the trace under whichever member they replayed, which is
            # not always the merged bucket's canonical key — report/
            # dashboard link the file that actually exists
            window_trace=next(
                (k2 for k2 in sorted(m["members"])
                 if os.path.exists(
                     store.bucket_path(k2, ".window.trace.json"))),
                None))

    # -- durable timeline curves + worker health ------------------------
    # curves embed DOWNSAMPLED (≤ _CURVE_CAP points, endpoints kept,
    # deterministic stride): a long campaign's timeline grows per sync,
    # and the snapshot history must not grow quadratically with it. The
    # coverage KEY LIST stays complete on purpose — exact added/removed
    # diffing is the plane's contract, and keys are the one set a diff
    # cannot reconstruct from counts (17 bytes/key; a 100k-key campaign
    # pays ~1.7MB per snapshot, the documented price of exactness —
    # DESIGN §19).
    tl = campaign_timeline(store)
    from ..obs.profiler import curve_brief
    health = {
        label: dict(rounds_done=h["rounds_done"],
                    last_seen=round(float(h["last_seen"]), 3),
                    sync_gap_s=h["sync_gap_s"],
                    # age vs the campaign's newest activity — NOT vs the
                    # wall clock at snapshot time (identity contract)
                    age_s=h["age_s"],
                    stale=bool(h["stale"]))
        for label, h in sorted(tl["workers_health"].items())}

    max_round = max([s.get("rounds_done", 0) for s in top_states.values()],
                    default=0)
    # AUDIT ledger (audit_buckets) folds in when present
    audit = load_audit(store).get("buckets", {})
    body = dict(
        format=TRIAGE_FORMAT,
        version=TRIAGE_VERSION,
        quiet_rounds=int(quiet_rounds),
        store=dict(
            entries=len(entry_files),
            coverage_total=len(claimed),
            buckets_total=len(merged),
            crash_observations=sum(
                b["observations"] for b in buckets.values()),
            max_round=int(max_round),
            workers={label: dict(
                rounds_done=int(s.get("rounds_done", 0)),
                wall_s=round(float(s.get("wall_s", 0.0)), 3),
                dry=int(s.get("dry", 0)),
                shards=int(s["shards"])) if "shards" in s else dict(
                rounds_done=int(s.get("rounds_done", 0)),
                wall_s=round(float(s.get("wall_s", 0.0)), 3),
                dry=int(s.get("dry", 0)))
                for label, s in sorted(top_states.items())}),
        coverage=dict(keys=sorted(f"{h:016x}" for h in claimed)),
        buckets=buckets,
        attribution=dict(
            recipe_coverage=recipe_cov,
            recipe_buckets=recipe_bk,
            operator_coverage=op_cov,
            operator_buckets=op_bk,
            origin_coverage=origin_cov,
            origin_buckets=origin_bk,
            rows_known=rows is not None),
        curves=dict(coverage=_downsample(tl["coverage_curve"]),
                    rate=_downsample(tl["rate_curve"]),
                    p99=_downsample(tl["p99_curve"])),
        p99=curve_brief(tl["p99_curve"]),
        # the SLO context for the p99 tile (r23): target + total misses
        # over the deduped timeline; None when no worker ran the
        # latency plane — the tile then shows the curve alone
        slo=tl.get("slo"),
        rate=curve_brief(tl["rate_curve"]),
        workers_health=health,
        audit={k: dict(v) for k, v in sorted(audit.items())
               if k in by_member or k in buckets},
    )
    have = list_snapshots(store)
    n = (have[-1] + 1) if have else 1
    os.makedirs(store.triage_dir(), exist_ok=True)
    _atomic_bytes(snapshot_path(store, n),
                  (json.dumps(body, sort_keys=True, indent=1)
                   + "\n").encode())
    return n, body


# ---------------------------------------------------------------------------
# the diff
# ---------------------------------------------------------------------------

def _delta_map(a: dict, b: dict) -> dict:
    """{key: [prev, cur]} for keys whose values differ (either side's
    missing key reads as absent-marker None) — the empty-on-equal
    building block."""
    out = {}
    for k in sorted(set(a) | set(b)):
        va, vb = a.get(k), b.get(k)
        if va != vb:
            out[k] = [va, vb]
    return out


def _quiet(b: dict, snap: dict, quiet_rounds: int) -> bool:
    return (int(snap["store"]["max_round"]) - int(b["last_round"])
            >= quiet_rounds)


def triage_diff(prev: dict, cur: dict,
                quiet_rounds: int | None = None) -> dict:
    """Classify everything that changed between two snapshots. Buckets
    are matched by canonical key OR member overlap (a deeper chain
    arriving between snapshots can re-elect a merged bucket's canonical
    key; member overlap keeps that one bug from reading as new+stale).
    `quiet_rounds` defaults to the snapshots' own setting. Equal
    snapshots produce {'empty': True, ...all fields empty...} — every
    field below is a prev-vs-cur difference by construction."""
    if quiet_rounds is None:
        quiet_rounds = int(cur.get("quiet_rounds", 2))
    pb, cb = prev.get("buckets", {}), cur.get("buckets", {})
    # member -> canonical maps for cross-snapshot identity
    p_by_member = {m: k for k, b in pb.items() for m in b["members"]}
    pairs: dict[str, str | None] = {}       # cur key -> prev key
    matched_prev: set[str] = set()
    for k, b in cb.items():
        hit = None
        if k in pb:
            hit = k
        else:
            for m in b["members"]:
                if m in p_by_member:
                    hit = p_by_member[m]
                    break
        pairs[k] = hit
        if hit is not None:
            matched_prev.add(hit)
    new, regressed, grew, stale = [], [], [], []
    for k in sorted(cb):
        pk = pairs[k]
        b = cb[k]
        if pk is None:
            new.append(k)
            continue
        p = pb[pk]
        seen_again = (b["observations"] > p["observations"]
                      or b["last_round"] > p["last_round"])
        if seen_again:
            (regressed if _quiet(p, prev, quiet_rounds)
             else grew).append(k)
        elif _quiet(b, cur, quiet_rounds) \
                and not _quiet(p, prev, quiet_rounds):
            stale.append(k)             # newly quiet
    stale += sorted(k for k in pb if k not in matched_prev)  # removed
    p_keys = set(prev.get("coverage", {}).get("keys", []))
    c_keys = set(cur.get("coverage", {}).get("keys", []))
    pa = prev.get("attribution", {})
    ca = cur.get("attribution", {})
    out = dict(
        buckets=dict(new=new, regressed=regressed, grew=grew,
                     stale=sorted(stale)),
        coverage=dict(
            added=len(c_keys - p_keys), removed=len(p_keys - c_keys)),
        attribution={dim: _delta_map(pa.get(dim, {}), ca.get(dim, {}))
                     for dim in ("recipe_coverage", "recipe_buckets",
                                 "operator_coverage", "operator_buckets",
                                 "origin_coverage", "origin_buckets")},
        p99=_delta_map(dict(brief=prev.get("p99")),
                       dict(brief=cur.get("p99"))),
        workers=_delta_map(prev.get("workers_health", {}),
                           cur.get("workers_health", {})),
        audit=_delta_map(prev.get("audit", {}), cur.get("audit", {})),
        rounds=_delta_map(dict(max_round=prev["store"]["max_round"]),
                          dict(max_round=cur["store"]["max_round"])),
    )
    out["empty"] = not (
        any(out["buckets"].values())
        or out["coverage"]["added"] or out["coverage"]["removed"]
        or any(out["attribution"].values())
        or out["p99"] or out["workers"] or out["audit"] or out["rounds"])
    return out


def bucket_lifecycle(key: str, diff: dict | None) -> str:
    """One bucket's lifecycle class per `diff` (the renderers' shared
    lookup — "known" when no diff names it)."""
    if diff:
        for cls in ("new", "regressed", "grew", "stale"):
            if key in diff.get("buckets", {}).get(cls, ()):
                return cls
    return "known"


def bucket_audit(snapshot: dict, key: str,
                 members=()) -> dict | None:
    """The audit-ledger verdict for a bucket, falling back through its
    merged members (the ledger keys RAW bucket files; a merged bucket's
    canonical may differ from the member that was audited)."""
    audit = snapshot.get("audit", {})
    hit = audit.get(key)
    if hit is not None:
        return hit
    return next((audit[m] for m in members if m in audit), None)


# ---------------------------------------------------------------------------
# repro-health audit
# ---------------------------------------------------------------------------

def audit_path(store: CorpusStore) -> str:
    return os.path.join(store.triage_dir(), "AUDIT.json")


def load_audit(store_or_dir) -> dict:
    store = _as_store(store_or_dir)
    try:
        with open(audit_path(store)) as f:
            return json.load(f)
    except (FileNotFoundError, ValueError):
        return dict(cursor_key="", buckets={})


def audit_buckets(rt, store_or_dir, max_steps: int, budget: int = 4,
                  chunk: int = 512, dup_slots: int = 2,
                  full_chain: bool = False) -> dict:
    """Re-verify a deterministic rotation of bucket repro handles — the
    standing answer to "do our repros still reproduce on this
    toolchain" (and a continuous canary for the known jaxlib
    persistent-cache first-invocation corruption, which is exactly why
    every replay goes through `replay_bucket(verify=True)`).

    Per audited bucket: `pass` (the handle still crashes — any code;
    the fingerprint, not the code, is the bucket's identity), `fail`
    (replayed clean — the bug no longer reproduces here), `flaky`
    (replay itself misbehaved: three-way disagreement under the verify
    guard, or the handle's artifacts are broken). A failing or flaky
    handle NEVER aborts the sweep — it is the finding. A structurally
    mismatched runtime still raises StoreMismatch out: that is operator
    error, not bucket health.

    The rotation cursor and per-bucket tallies live in triage/AUDIT.json
    (atomic rewrite); snapshots fold the ledger in, so the dashboard
    always shows the latest verdict per bucket. `budget` bounds replays
    per call — a nightly `budget=4` sweeps a 40-bucket corpus every ten
    nights, for free.

    The ledger also records each audited bucket's CHAIN COMPLETENESS
    (r20): whether its recorded causal chain is complete or still
    truncated-at-wrap (`chain_complete`). With `full_chain=True` each
    audited replay additionally runs the time-travel hook
    (`replay_bucket(full_chain=True, window_trace=True)`) — truncated
    buckets are upgraded to their complete chain and gain a focused
    window trace as they rotate through the audit."""
    from ..service.store import StoreMismatch
    from .campaign import replay_bucket
    store = _as_store(store_or_dir)
    ledger = load_audit(store)
    keys = store.bucket_keys()
    audited = []
    if keys:
        # rotation resumes AFTER the last audited KEY, not at a numeric
        # index: buckets opened between calls shift every index in the
        # sorted list, and an index cursor would re-audit some buckets
        # while starving the ones that were next in line
        import bisect
        cursor_key = ledger.get("cursor_key", "")
        start = bisect.bisect_right(keys, cursor_key) % len(keys)
        todo = [keys[(start + i) % len(keys)]
                for i in range(min(int(budget), len(keys)))]
        for key in todo:
            rec = store.load_bucket(key)
            try:
                crashed, code, _ = replay_bucket(
                    rt, store.dir, key, max_steps, chunk=chunk,
                    dup_slots=dup_slots, verify=True,
                    full_chain=full_chain, window_trace=full_chain)
                status = "pass" if crashed else "fail"
                note = None
            except StoreMismatch:
                raise
            except Exception as e:  # noqa: BLE001 - per-bucket verdict
                status, code = "flaky", None
                note = f"{type(e).__name__}: {e}"
            if full_chain:
                rec = store.load_bucket(key)   # may have been upgraded
            b = ledger["buckets"].setdefault(
                key, {"audits": 0, "pass": 0, "fail": 0, "flaky": 0})
            b["audits"] += 1
            b[status] += 1
            b["status"] = status
            b["expected_code"] = int(rec["crash_code"])
            b["last_code"] = None if code is None else int(code)
            # is the bucket's recorded chain the WHOLE story, or still
            # cut at ring wrap? (pre-r20 records without the flag fall
            # back to the fingerprint's depth-capped completeness bit)
            ct = rec.get("chain_truncated")
            b["chain_complete"] = (
                (not ct) if ct is not None
                else bool(rec["fingerprint"].get("complete", False)))
            if note is not None:
                b["note"] = note
            elif "note" in b:
                del b["note"]
            audited.append(dict(bucket=key, status=status, code=code,
                                chain_complete=b["chain_complete"]))
        ledger["cursor_key"] = todo[-1]
        ledger.pop("cursor", None)
    os.makedirs(store.triage_dir(), exist_ok=True)
    _atomic_bytes(audit_path(store),
                  (json.dumps(ledger, sort_keys=True, indent=1)
                   + "\n").encode())
    return dict(audited=audited,
                counts={s: sum(1 for a in audited if a["status"] == s)
                        for s in ("pass", "fail", "flaky")},
                cursor_key=ledger.get("cursor_key", ""), ledger=ledger)
