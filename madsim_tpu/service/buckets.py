"""Crash dedup by causal fingerprint: one bug = one bucket.

The raw crash code is too coarse a dedup key (every invariant trip in a
model shares one code) and the raw (seed, lane) too fine (millions of
lanes hit the same bug). The r10 lineage layer gives the right key: the
`explain_crash` parent chain — WHAT sequence of events caused the crash,
independent of which lane/seed/process observed it. `obs/causal.py
causal_fingerprint` hashes that chain wrap-stably (deepest-common-suffix
matching, so ring truncation at different points can't split a bug);
this module keeps the durable bucket files in a `CorpusStore`:

  buckets/<key>.json        the fingerprint record + chain summary + the
                            kept repro handle (seed, round, worker)
  buckets/<key>.npz         the repro's full knob vector — with the seed,
                            the complete replay handle (a mutated lane is
                            NOT reproducible from its seed alone)
  buckets/<key>.trace.json  Perfetto export of the crash lane's ring
                            (flow arrows = the causal chain, r10)
  buckets.jsonl             one line per bucketed observation (telemetry)

Buckets are not only crashes: confirmed SCHEDULE RACES (analyze/races.py)
land here too, under `obs.causal.race_fingerprint` — same files, same
dedup machinery, with the repro handle extended to (seed, knobs, nudge)
since a race only manifests under its PCT tie-break policy.

Cross-process dedup is mostly by construction: two workers that compute
the same fingerprint race to `os.replace` the same file name — last
writer wins with equivalent content. The residual race (two workers
opening buckets for one bug truncated at DIFFERENT wrap depths in the
same instant) is repaired at read time: `merged_buckets` folds
suffix-matching buckets together, so campaign reports count bugs, not
write races.
"""

from __future__ import annotations

import time

import numpy as np

from ..obs.causal import (causal_fingerprint, code_fingerprint,
                          explain_crash, fingerprints_match)
from .store import CorpusStore


class CrashBuckets:
    """The write-side bucket index one worker holds over a store."""

    def __init__(self, store: CorpusStore):
        self.store = store
        self._index: dict[str, dict] = {}
        self.new_keys: list[str] = []   # buckets THIS worker opened

    def refresh(self) -> None:
        for key in self.store.bucket_keys():
            if key not in self._index:
                self._index[key] = self.store.load_bucket(key)

    def _match(self, fp: dict) -> str | None:
        if fp["key"] in self._index:
            return fp["key"]
        best = None
        best_depth = -1
        for key, rec in self._index.items():
            if fingerprints_match(fp, rec["fingerprint"]) \
                    and rec["fingerprint"]["depth"] > best_depth:
                best, best_depth = key, rec["fingerprint"]["depth"]
        return best

    def observe(self, fp: dict, *, seed: int, knobs: dict | None,
                round_no: int, worker_id: int, chain: list | None = None,
                state=None, lane: int | None = None,
                nudge: int | None = None,
                last_op: int | None = None,
                chain_truncated: bool | None = None,
                origin: str | None = None) -> tuple[str, bool]:
        """Fold one crash observation in. Returns (bucket key, opened):
        `opened` is True when this observation created a new bucket (and
        wrote its repro + trace artifacts); an observation matching an
        existing bucket only appends a telemetry line — the first repro
        stays the bucket's canonical handle.

        `nudge` extends the repro handle for CONFIRMED SCHEDULE RACES
        (analyze/races.py, fp kind="race"): the race only manifests
        under that PCT tie-break policy, so the full replay handle is
        (seed, knobs, nudge) — `search.pct.with_prio_nudge` applies the
        third leg at replay.

        `last_op` (r18) records the havoc operator that produced the
        crashing lane's knob vector (KnobPlan.mutate's per-lane
        attribution; -1 = untouched/bootstrap) into the bucket record —
        the triage plane's per-operator bucket attribution; buckets
        without it (pre-r18, or races) attribute to the explicit
        `base` class.

        `origin` (r22) records which SEARCH ARM produced the crashing
        lane's knob vector — "targeted" (a lineage-synthesized vector,
        search/ldfi.py) or "havoc" — into the bucket record and every
        telemetry line, the triage plane's targeted-vs-havoc bucket
        attribution. Additive: buckets observed without it (pre-r22, or
        ldfi-less campaigns) carry no origin field and triage
        attributes them to "havoc" (factually honest — nothing before
        r22 ever aimed).

        `chain_truncated` (r20) records whether this observation's
        chain was cut at ring wrap. Completeness UPGRADE rule: an
        observation matching an existing bucket with a DEEPER (or
        newly complete) chain — e.g. a time-travel replay
        (`explain_crash(replay=True)`) recovering the full chain its
        truncated sibling opened the bucket with — rewrites the
        bucket's fingerprint/chain in place (deepest-common-suffix
        already proved them the same bug; the repro handle and key
        stay canonical). The bucket record therefore converges to the
        most complete chain any worker ever observed."""
        self.refresh()
        key = self._match(fp)
        opened = key is None
        if opened:
            key = fp["key"]
            repro = dict(seed=int(seed), round=int(round_no),
                         worker_id=int(worker_id))
            if nudge is not None:
                repro["nudge"] = int(nudge)
            rec = dict(
                key=key, fingerprint=fp,
                crash_code=fp["crash_code"], crash_node=fp["crash_node"],
                chain=[{k: int(c[k]) for k in c} for c in (chain or [])],
                repro=repro,
                created_at=time.time())
            if last_op is not None:
                rec["op"] = int(last_op)
            if origin is not None:
                rec["origin"] = str(origin)
            if chain_truncated is not None:
                rec["chain_truncated"] = bool(chain_truncated)
            self.store.write_bucket(key, rec, knobs=knobs)
            if state is not None and lane is not None:
                from ..obs.trace import export_chrome_trace
                export_chrome_trace(self.store.bucket_path(
                    key, ".trace.json"), state=state, lane=int(lane))
            self._index[key] = rec
            self.new_keys.append(key)
        else:
            old = self._index[key]["fingerprint"]
            deeper = (fp["depth"] > old["depth"]
                      or (fp.get("complete") and not old.get("complete")))
            if deeper and chain:
                rec = dict(self._index[key], fingerprint=fp,
                           chain=[{k: int(c[k]) for k in c}
                                  for c in chain],
                           upgraded_at=time.time())
                if chain_truncated is not None:
                    rec["chain_truncated"] = bool(chain_truncated)
                self.store.write_bucket(key, rec)   # no knobs: the
                self._index[key] = rec              # canonical repro stays
        line = dict(
            kind="crash", bucket=key, fp_key=fp["key"],
            crash_code=fp["crash_code"], seed=int(seed),
            round=int(round_no), worker_id=int(worker_id),
            opened=bool(opened))
        if origin is not None:
            line["origin"] = str(origin)
        self.store.append_bucket_log(line)
        return key, opened

    def observe_lane(self, state, lane: int, *, seed: int,
                     knobs: dict | None, round_no: int,
                     worker_id: int,
                     last_op: int | None = None,
                     origin: str | None = None) -> tuple[str, bool]:
        """Fingerprint one crashed lane straight off its ring. Falls back
        to the code fingerprint when the build compiled lineage out
        (cfg.trace_cap == 0) — coarser buckets, still deduped."""
        try:
            exp = explain_crash(state, lane)
            fp = causal_fingerprint(exp)
            chain = exp["chain"]
            truncated = bool(exp["truncated"])
        except ValueError:
            code = int(np.asarray(state.crash_code).reshape(-1)[lane])
            node = int(np.asarray(state.crash_node).reshape(-1)[lane])
            fp, chain, state, lane = code_fingerprint(code, node), None, \
                None, None
            truncated = None
        return self.observe(fp, seed=seed, knobs=knobs, round_no=round_no,
                            worker_id=worker_id, chain=chain, state=state,
                            lane=lane, last_op=last_op,
                            chain_truncated=truncated, origin=origin)


def merged_buckets(store: CorpusStore, log: list | None = None) -> list[dict]:
    """The read-side truth: all buckets, with suffix-matching ones folded
    together (repairing the concurrent-open race and cross-ring-depth
    splits). Deepest chain wins as canonical; observation counts come
    from the telemetry log DEDUPED by (fingerprint, worker, round) —
    a killed worker's interrupted round re-appends its observation line
    on resume, and counting the replay twice inflated every bug-rate
    curve downstream (campaign_report). Deterministic: candidates are
    processed in (depth desc, key) order. `log` short-circuits the
    observation-log read with an already-deduped row list — a caller
    that needs the rows itself (triage_snapshot) parses the file once
    and shares."""
    recs = [store.load_bucket(k) for k in store.bucket_keys()]
    recs.sort(key=lambda r: (-r["fingerprint"]["depth"], r["key"]))
    merged: list[dict] = []
    for rec in recs:
        home = None
        for m in merged:
            if fingerprints_match(rec["fingerprint"], m["fingerprint"]):
                home = m
                break
        if home is None:
            merged.append(dict(rec, members=[rec["key"]], observations=0))
        else:
            home["members"].append(rec["key"])
    by_member = {k: m for m in merged for k in m["members"]}
    for line in (store.bucket_log_deduped() if log is None else log):
        m = by_member.get(line.get("bucket"))
        if m is not None:
            m["observations"] += 1
    return merged
