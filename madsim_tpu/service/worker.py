"""One campaign worker process: `python -m madsim_tpu.service.worker`.

The smallest unit of a persistent fuzzing service — builds its runtime
from a "module:function" factory spec, joins the shared corpus dir under
its worker id, runs its share of rounds through `fuzz(corpus_dir=...)`,
and exits with a one-line JSON result on stdout. SIGKILL-safe at any
instant (the store's write-then-rename contract); relaunching with the
same arguments resumes where it died.

Factory specs resolve against sys.path plus the current working
directory, so `--factory bench:_make_crashrich_runtime` works from a
repo checkout and `--factory mypkg.workloads:make_rt` from an install.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys


def _force_cpu_mesh(n: int, argv=None) -> None:
    """Grow the host platform to >= n virtual devices for a mesh-sharded
    worker (--shards). A spawner that already put
    xla_force_host_platform_device_count in XLA_FLAGS
    (service/campaign.py does) wins outright — newer jaxlibs REJECT
    having both that flag and jax_num_cpu_devices set, so the config
    option is only tried when the flag is absent. On jaxlibs without
    jax_num_cpu_devices the flag is the only mechanism, and XLA parses
    it at library load — long past by the time `-m` has imported the
    package — so the fallback RE-EXECS this worker once with the flag
    in its env (idempotent: the re-exec'd process sees the flag and
    returns here immediately). Harmless on accelerator hosts either
    way: both knobs only size the HOST (cpu) backend."""
    if "xla_force_host_platform_device_count" in os.environ.get(
            "XLA_FLAGS", ""):
        return
    import jax
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}").strip()
        os.execv(sys.executable,
                 [sys.executable, "-m", "madsim_tpu.service.worker"]
                 + list(argv if argv is not None else sys.argv[1:]))


def resolve_factory(spec: str):
    mod, _, fn = spec.partition(":")
    if not fn:
        raise SystemExit(f"--factory must be 'module:function', got {spec!r}")
    if os.getcwd() not in sys.path:
        sys.path.insert(0, os.getcwd())
    return getattr(importlib.import_module(mod), fn)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--corpus-dir", required=True)
    ap.add_argument("--worker-id", type=int, default=0)
    ap.add_argument("--factory", required=True,
                    help="module:function returning a Runtime")
    ap.add_argument("--factory-kwargs", default=None,
                    help="JSON kwargs for the factory")
    ap.add_argument("--max-steps", type=int, required=True)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--max-rounds", type=int, default=4,
                    help="campaign-total rounds for this worker "
                         "(a resume runs only the remainder)")
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--dry-rounds", type=int, default=None)
    ap.add_argument("--base-seed", type=int, default=0)
    ap.add_argument("--rng-seed", type=int, default=None,
                    help="corpus/mutation randomness (default: worker id)")
    ap.add_argument("--sync-every", type=int, default=1)
    ap.add_argument("--minimize", action="store_true")
    ap.add_argument("--shards", type=int, default=1,
                    help="mesh-shard this worker's campaign across N "
                         "devices (search/shard.py); shard namespaces "
                         "are worker_id*shards+s")
    ap.add_argument("--verify-resume", action="store_true",
                    help="run-twice guard on the first post-resume "
                         "round (the persistent-cache first-invocation "
                         "transient, ROADMAP r12)")
    ap.add_argument("--progress", action="store_true",
                    help="render live rounds on stderr too")
    args = ap.parse_args(argv)

    if args.shards > 1:
        # unconditional on platform: this only sizes the HOST (cpu)
        # backend's virtual device count — inert when an accelerator is
        # the default platform, required when the worker lands on CPU
        _force_cpu_mesh(args.shards, argv)

    # all workers of a campaign share one persistent compile cache (r8):
    # honor an inherited JAX_COMPILATION_CACHE_DIR, else keep it inside
    # the corpus dir so the campaign is self-contained
    from ..compile.persistent import enable_persistent_cache
    enable_persistent_cache(
        os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or os.path.join(os.path.abspath(args.corpus_dir), ".jax_cache"))

    factory = resolve_factory(args.factory)
    rt = factory(**json.loads(args.factory_kwargs or "{}"))

    from ..obs import JsonlObserver, ProgressObserver, TeeObserver
    from ..search.fuzz import fuzz
    from .store import CorpusStore, store_signature
    from ..search.mutate import KnobPlan
    # fail fast (and loudly, before compiling anything) on a dir written
    # by a structurally different runtime
    store = CorpusStore(args.corpus_dir, signature=store_signature(
        rt, KnobPlan.from_runtime(rt)))
    # fsync per record: under supervise_campaign respawns the observer
    # log must be complete up to the last sync even across power loss —
    # the r15 campaign timeline's trust anchor
    obs = JsonlObserver(store.worker_log_path(args.worker_id), fsync=True)
    if args.progress:
        obs = TeeObserver(obs, ProgressObserver())
    dry = (args.dry_rounds if args.dry_rounds is not None
           else args.max_rounds + 1)
    kw = dict(max_steps=args.max_steps, batch=args.batch,
              max_rounds=args.max_rounds, dry_rounds=dry,
              base_seed=args.base_seed, chunk=args.chunk,
              observer=obs, minimize=args.minimize,
              corpus_dir=args.corpus_dir, worker_id=args.worker_id,
              sync_every=args.sync_every,
              verify_resume=args.verify_resume or None)
    if args.shards > 1:
        from ..search.shard import fuzz_sharded
        # default rng spacing worker_id*shards: shard s of worker w
        # draws with rng_seed w*shards+s — groups stay disjoint exactly
        # like their namespaces
        res = fuzz_sharded(rt, shards=args.shards,
                           rng_seed=(args.rng_seed
                                     if args.rng_seed is not None
                                     else args.worker_id * args.shards),
                           **kw)
    else:
        res = fuzz(rt,
                   rng_seed=(args.rng_seed if args.rng_seed is not None
                             else args.worker_id), **kw)
    print(json.dumps({
        k: res[k] for k in
        ("seeds_run", "rounds", "rounds_done_total", "distinct_schedules",
         "saturated", "crashes", "corpus_size", "buckets_total",
         "buckets_opened", "shards") if k in res}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
