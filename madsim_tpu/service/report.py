"""`python -m madsim_tpu.service.report <corpus_dir>` — the triage CLI.

The operator's standing view of a durable campaign: renders the latest
triage snapshot (service/triage.py) as a terminal report, diffs it
against the previous one (`--against prev`, the default when history
exists) or any numbered snapshot (`--against 0003`), and optionally
writes the self-contained HTML dashboard (`--html out.html`,
obs/dashboard.py). Pure read side: it never runs the engine — taking a
fresh snapshot first is `--snapshot` (cheap, store-only), and the
repro-health audit stays in `triage.audit_buckets` because it needs a
Runtime. Works on any store a worker ever synced, live or long dead.
"""

from __future__ import annotations

import argparse
import sys

from .store import CorpusStore
from .triage import (bucket_audit, bucket_lifecycle, list_snapshots,
                     load_snapshot, triage_diff, triage_snapshot)


def _fmt_counts(counts: dict) -> str:
    return "  ".join(f"{k}:{v}" for k, v in counts.items() if v) or "-"


def render_text(cur: dict, diff: dict | None = None) -> str:
    """The terminal report (the HTML dashboard's plain twin)."""
    st = cur["store"]
    lines = [
        f"corpus: {st['entries']} entries  "
        f"coverage: {st['coverage_total']}  "
        f"buckets: {st['buckets_total']} "
        f"({st['crash_observations']} observations)  "
        f"rounds: {st['max_round']}",
    ]
    if cur.get("rate"):
        lines[-1] += f"  sched/s: {cur['rate']['last']}"
    if cur.get("p99"):
        lines[-1] += f"  p99: {cur['p99']['last']}us"
    attr = cur["attribution"]
    lines.append("recipe coverage:   "
                 + _fmt_counts(attr["recipe_coverage"]))
    lines.append("recipe buckets:    "
                 + _fmt_counts(attr["recipe_buckets"]))
    lines.append("operator coverage: "
                 + _fmt_counts(attr["operator_coverage"]))
    lines.append("operator buckets:  "
                 + _fmt_counts(attr["operator_buckets"]))
    if not attr.get("rows_known"):
        lines.append("  (no triage/ROWS.json — recipe attribution is "
                     "all `base`; run one r18+ worker to write it)")
    if diff is not None:
        if diff["empty"]:
            lines.append("diff: EMPTY — nothing changed")
        else:
            b = diff["buckets"]
            lines.append(
                f"diff: +{diff['coverage']['added']} coverage keys "
                f"(-{diff['coverage']['removed']})  buckets: "
                f"{len(b['new'])} new, {len(b['regressed'])} regressed, "
                f"{len(b['grew'])} grew, {len(b['stale'])} stale")
            for cls in ("new", "regressed", "stale"):
                for k in b[cls]:
                    bk = cur.get("buckets", {}).get(k) or {}
                    lines.append(f"  [{cls}] {k[:16]} "
                                 f"code={bk.get('crash_code', '?')} "
                                 f"recipe={bk.get('recipe', '?')}")
    lines.append(f"{'bucket':<18}{'life':<11}{'code':>5} "
                 f"{'recipe':<15}{'operator':<17}{'obs':>4} "
                 f"{'rounds':<9}{'audit':<7}{'chain':<9} repro")

    for k, bk in sorted(cur.get("buckets", {}).items()):
        a = bucket_audit(cur, k, bk.get("members", ()))
        r = bk["repro"]
        repro = (f"seed={r.get('seed')} round={r.get('round')} "
                 f"worker={r.get('worker_id')}")
        if bk.get("minimized"):
            repro += " minimized"
        # r20: is the recorded causal chain the whole story or still
        # truncated-at-wrap, and does a replayed window trace exist?
        # (pre-r20 snapshots lack both fields — rendered as "-")
        if "chain_complete" not in bk:
            chain = "-"
        else:
            chain = "full" if bk["chain_complete"] else "cut"
            if bk.get("window_trace"):
                chain += "+tr"
        if bk.get("window_trace"):
            # the full member key: this line is the copy-pasteable
            # repro surface, so the path must be the real filename
            repro += (f" trace=buckets/{bk['window_trace']}"
                      ".window.trace.json")
        lines.append(
            f"{k[:16]:<18}{bucket_lifecycle(k, diff):<11}"
            f"{bk['crash_code']:>5} "
            f"{bk['recipe']:<15}{bk['op']:<17}"
            f"{bk['observations']:>4} "
            f"{bk['first_round']}-{bk['last_round']:<7}"
            f"{(a or {}).get('status', '-'):<7}"
            f"{chain:<9} {repro}")
    stale_w = [w for w, h in cur.get("workers_health", {}).items()
               if h.get("stale")]
    if stale_w:
        lines.append(f"STALE workers: {', '.join(stale_w)}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m madsim_tpu.service.report", description=__doc__)
    ap.add_argument("corpus_dir")
    ap.add_argument("--snapshot", action="store_true",
                    help="fold the store into a fresh triage snapshot "
                         "first (store-only, no engine)")
    ap.add_argument("--against", default=None, metavar="prev|NNNN",
                    help="diff the latest snapshot against this one "
                         "(default: prev when history allows)")
    ap.add_argument("--html", default=None, metavar="OUT",
                    help="also write the self-contained HTML dashboard")
    ap.add_argument("--quiet-rounds", type=int, default=2,
                    help="rounds without observation before a bucket "
                         "counts as quiet (lifecycle thresholds)")
    ap.add_argument("--json", action="store_true",
                    help="emit {snapshot, diff} as one JSON document "
                         "instead of the text report")
    args = ap.parse_args(argv)

    store = CorpusStore(args.corpus_dir, create=False)
    if args.snapshot:
        n, cur = triage_snapshot(store, quiet_rounds=args.quiet_rounds)
        print(f"snapshot {n:04d} written", file=sys.stderr)
    else:
        cur = load_snapshot(store, "last")
    have = list_snapshots(store)
    against = args.against
    if against is None and len(have) >= 2:
        against = "prev"
    diff = None
    if against is not None:
        prev = load_snapshot(store, against)
        diff = triage_diff(prev, cur, quiet_rounds=args.quiet_rounds)
    if args.html:
        from ..obs.dashboard import render_html
        with open(args.html, "w") as f:
            f.write(render_html(cur, diff))
        print(f"dashboard: {args.html}", file=sys.stderr)
    if args.json:
        import json
        print(json.dumps(dict(snapshot=cur, diff=diff)))
    else:
        print(render_text(cur, diff))
    return 0


if __name__ == "__main__":
    sys.exit(main())
