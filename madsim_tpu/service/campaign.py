"""The multi-process campaign driver: many cheap workers, one durable store.

The CI-farm shape madsim users actually run (ROADMAP "production
traffic"): N worker processes fuzz the same runtime into one shared
corpus directory. Each worker owns its id's namespace (entry ids, seed
space, scheduler state), merges the others' coverage at its round syncs,
and dedups crashes into the shared causal-fingerprint buckets — the
Podracer split (PAPERS.md) of many actors over one store, where the
determinism core makes every merge safe by construction.

Workers are real OS processes (`python -m madsim_tpu.service.worker`),
not threads: each gets its own jax runtime, and all of them share the
r8 persistent compile cache, so only the first cold worker pays the
trace+compile wall. The driver here spawns them, polls the corpus dir
for campaign-level stats (kind="campaign" SweepObserver records:
uptime, schedules/s, buckets), and renders the merged report. Killing
a worker — SIGKILL included — loses at most its work since its last
round sync; relaunching the same worker id resumes it exactly
(search/fuzz.py durability contract).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

from .buckets import merged_buckets
from .store import CorpusStore


def worker_cmd(corpus_dir: str, worker_id: int, factory: str, *,
               factory_kwargs: dict | None = None, max_steps: int,
               batch: int = 64, max_rounds: int = 4, chunk: int = 256,
               dry_rounds: int | None = None, base_seed: int = 0,
               sync_every: int = 1, minimize: bool = False,
               shards: int = 1, verify_resume: bool = False,
               python: str = sys.executable) -> list[str]:
    """The argv for one campaign worker process. `factory` is a
    "module:function" spec resolved in the worker (the runtime itself
    is not picklable across processes — a factory is the contract).
    `shards` > 1 makes the worker drive a mesh-sharded campaign
    (search/shard.py — the worker forces a wide-enough CPU mesh when
    the platform is cpu); `verify_resume` arms the run-twice guard on
    its first post-resume round."""
    cmd = [python, "-m", "madsim_tpu.service.worker",
           "--corpus-dir", corpus_dir,
           "--worker-id", str(worker_id),
           "--factory", factory,
           "--max-steps", str(max_steps),
           "--batch", str(batch),
           "--max-rounds", str(max_rounds),
           "--chunk", str(chunk),
           "--base-seed", str(base_seed),
           "--sync-every", str(sync_every)]
    if factory_kwargs:
        cmd += ["--factory-kwargs", json.dumps(factory_kwargs)]
    if dry_rounds is not None:
        cmd += ["--dry-rounds", str(dry_rounds)]
    if minimize:
        cmd += ["--minimize"]
    if shards != 1:
        cmd += ["--shards", str(shards)]
    if verify_resume:
        cmd += ["--verify-resume"]
    return cmd


def spawn_worker(corpus_dir: str, worker_id: int, factory: str,
                 env: dict | None = None, **kw) -> subprocess.Popen:
    """Launch one worker detached from this process's jax runtime. `env`
    REPLACES the child environment when given (callers that must unpin a
    TPU platform need removals, not just overrides); default inherits.
    All workers share the persistent compile cache via
    JAX_COMPILATION_CACHE_DIR; stdout carries the worker's final result
    as one JSON line."""
    e = dict(env) if env is not None else dict(os.environ)
    # workers share the campaign's compile cache by default (r8): the
    # first cold worker compiles, the rest replay the executable
    e.setdefault("JAX_COMPILATION_CACHE_DIR",
                 os.path.join(os.path.abspath(corpus_dir), ".jax_cache"))
    # a mesh-sharded worker needs its virtual CPU devices before jax
    # initializes — the flag in the child env is the robust path (the
    # worker's in-process fallback only fires when it is absent).
    # Unconditional on platform: the flag only sizes the HOST (cpu)
    # backend, so on an accelerator host it is inert and the mesh spans
    # the real devices
    if kw.get("shards", 1) > 1 \
            and "xla_force_host_platform_device_count" \
            not in e.get("XLA_FLAGS", ""):
        e["XLA_FLAGS"] = (e.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count="
                          + str(kw["shards"])).strip()
    return subprocess.Popen(
        worker_cmd(corpus_dir, worker_id, factory, **kw), env=e,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)


def campaign_stats(corpus_dir: str, *, uptime_s: float = 0.0,
                   workers: int = 0, workers_alive: int = 0,
                   round_no: int = 0, store: CorpusStore | None = None
                   ) -> dict:
    """One campaign-level rollup record off the shared dir (cheap scan;
    the poll loop's SweepObserver.on_round payload and the basis of the
    final report). Pass a long-lived `store` when polling — its
    immutable-entry hash cache keeps each poll O(new files). Wall time
    is the max over workers' own accounts — workers run concurrently,
    their walls overlap."""
    if store is None:
        store = CorpusStore(corpus_dir, create=False)
    coverage = store.coverage_keys()
    states = [store.load_worker_state(w) for w in store.worker_ids()]
    # mesh-sharded groups (r13) roll up next to plain workers: their
    # group json carries the same top-level rounds_done/wall_s
    states += [store.load_shard_group_state(g)
               for g in store.shard_group_ids()]
    wall = max([s.get("wall_s", 0.0) for s in states], default=0.0)
    rounds_done = sum(s.get("rounds_done", 0) for s in states)
    buckets = store.bucket_keys()
    # deduped by (fingerprint, worker, round): a resumed worker's
    # replayed round re-appends identical observation lines, which
    # inflated the rate curves (store.bucket_log_deduped)
    crash_obs = len(store.bucket_log_deduped())
    return dict(
        kind="campaign", round=round_no, uptime_s=round(uptime_s, 2),
        workers=workers, workers_alive=workers_alive,
        corpus_entries=len(store.entry_names()),
        coverage_keys=len(coverage),
        rounds_done=rounds_done,
        buckets=len(buckets),
        crash_observations=crash_obs,
        schedules_per_sec=round(len(coverage) / wall, 2) if wall else 0.0,
        buckets_per_min=round(60.0 * len(buckets) / wall, 3) if wall
        else 0.0,
        worker_wall_s=round(wall, 2))


def run_campaign(factory: str, corpus_dir: str, *, workers: int = 2,
                 max_steps: int, batch: int = 64, max_rounds: int = 4,
                 chunk: int = 256, factory_kwargs: dict | None = None,
                 base_seed: int = 0, sync_every: int = 1,
                 minimize: bool = False, shards: int = 1,
                 verify_resume: bool = False, observer=None,
                 env: dict | None = None, poll_s: float = 2.0,
                 python: str = sys.executable) -> dict:
    """Run one campaign segment: spawn `workers` processes on one corpus
    dir, poll campaign stats while they run, and return the merged
    report. Re-running with the same arguments RESUMES the campaign
    (each worker picks up at its rounds_done) — an always-on service is
    `supervise_campaign` (this call in a loop with a growing
    `max_rounds`, dead-worker restarts, and cold-entry pruning).
    `shards` > 1 makes every worker a mesh-sharded campaign of that
    width (search/shard.py): the two scale axes compose — processes
    multiply meshes, and all namespaces stay disjoint by the
    worker_id*shards+s mapping."""
    t0 = time.monotonic()
    procs = {
        w: spawn_worker(corpus_dir, w, factory,
                        factory_kwargs=factory_kwargs, max_steps=max_steps,
                        batch=batch, max_rounds=max_rounds, chunk=chunk,
                        base_seed=base_seed, sync_every=sync_every,
                        minimize=minimize, shards=shards,
                        verify_resume=verify_resume, env=env, python=python)
        for w in range(workers)}
    results = {}
    poll = 0
    poll_store = None
    try:
        while any(p.poll() is None for p in procs.values()):
            time.sleep(poll_s)
            poll += 1
            if observer is not None and os.path.exists(
                    os.path.join(corpus_dir, "MANIFEST.json")):
                if poll_store is None:
                    poll_store = CorpusStore(corpus_dir, create=False)
                alive = sum(p.poll() is None for p in procs.values())
                observer.on_round(campaign_stats(
                    corpus_dir, uptime_s=time.monotonic() - t0,
                    workers=workers, workers_alive=alive, round_no=poll,
                    store=poll_store))
    except KeyboardInterrupt:
        # graceful stop: SIGTERM the workers, let their round finish is
        # not guaranteed — but the store contract means nothing past the
        # last sync is lost, and the next run_campaign resumes
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        raise
    for w, p in procs.items():
        out, _ = p.communicate()
        line = (out or "").strip().splitlines()
        results[w] = dict(returncode=p.returncode,
                          result=json.loads(line[-1]) if line else None)
    return campaign_report(corpus_dir, uptime_s=time.monotonic() - t0,
                           workers=workers, worker_results=results)


def campaign_timeline(store: CorpusStore, stale_after: float = 3.0,
                      now: float | None = None) -> dict:
    """Fold the workers' durable `metrics/` rows (appended at every
    durability sync, fsync'd — search/fuzz.py) into the campaign's
    after-the-fact telemetry. No live poller required: a finished or
    killed campaign is inspectable from the dir alone.

    Rows are deduped by (worker, rounds_done) keeping the LAST
    occurrence — a killed-and-resumed worker re-appends its interrupted
    sync's row with identical content (the append-before-commit
    ordering), so the folded timeline has no double-counted rounds and
    no gaps. Returns:

      timeline         all rows, deduped, time-ordered
      coverage_curve   [[t_rel_s, coverage]] — campaign-global coverage
                       over wall time (running max over workers' views)
      rate_curve       [[t_rel_s, schedules_per_sec]] — coverage/wall
                       at each sync
      p99_curve        [[t_rel_s, lat_p99_us]] — the campaign's
                       end-to-end p99 over wall time, from rows whose
                       worker ran with the SLO latency plane compiled
                       in (cfg.latency_hist > 0, r16); empty otherwise
      slo              {target, miss} — the advertised SLO target (µs)
                       and total misses over every deduped round row
                       (r23); None when no row carried latency fields
      workers_health   {label: {last_seen, age_s, rounds_done, sync_gap_s,
                       stale}} — `stale` means the CAMPAIGN has newer
                       activity than the worker: no row of this worker
                       within `stale_after` × its own observed sync
                       cadence of the newest row ANY worker appended.
                       Staleness is always measured against that newest
                       row, never against `now` — a worker whose last
                       row IS the campaign's latest activity can't be
                       stale, so a finished campaign (one worker
                       included) reads healthy no matter how long ago
                       it finished, while a worker that died unresumed
                       beside still-running peers reads stale — its
                       last counters are history, not current state.
                       `now` (default: the newest row's timestamp) only
                       scales the reported age_s.
    """
    by_worker = store.read_metrics()
    rows = []
    health = {}
    for label, raw in by_worker.items():
        dedup: dict[int, dict] = {}
        for rec in raw:
            dedup[int(rec.get("rounds_done", 0))] = rec
        wrows = sorted(dedup.values(),
                       key=lambda r: (r.get("t", 0.0),
                                      r.get("rounds_done", 0)))
        rows += [dict(r, worker=label) for r in wrows]
        if wrows:
            ts = [r.get("t", 0.0) for r in wrows]
            gaps = [b - a for a, b in zip(ts, ts[1:]) if b > a]
            health[label] = dict(
                last_seen=ts[-1],
                rounds_done=int(wrows[-1].get("rounds_done", 0)),
                sync_gap_s=round(float(np.median(gaps)) if gaps else 0.0,
                                 3))
    rows.sort(key=lambda r: (r.get("t", 0.0), r.get("rounds_done", 0)))
    t_latest = max((r.get("t", 0.0) for r in rows), default=0.0)
    t_ref = now if now is not None else t_latest
    for label, h in health.items():
        h["age_s"] = round(max(t_ref - h["last_seen"], 0.0), 3)
        # a worker with one row has no observed cadence — only flag it
        # against cadences its peers establish
        gap = h["sync_gap_s"] or max(
            (g["sync_gap_s"] for g in health.values() if g["sync_gap_s"]),
            default=0.0)
        # stale only when the CAMPAIGN has newer activity than the
        # worker: the lag is vs the newest row any worker appended, not
        # vs `now` — a finished campaign's last-syncing worker (its own
        # worker, in the 1-worker case) would otherwise read stale the
        # moment a late report passed a wall-clock `now`
        lag = max(t_latest - h["last_seen"], 0.0)
        h["stale"] = bool(gap and lag > stale_after * gap)
    t0 = rows[0].get("t", 0.0) if rows else 0.0
    coverage_curve = []
    rate_curve = []
    p99_curve = []
    cov = 0
    # schedules/s uses campaign_stats' denominator rule at each point in
    # time: campaign coverage over the MAX of the workers' own wall
    # accounts so far (workers run concurrently, their walls overlap) —
    # dividing by the current ROW's wall would spike whenever a young
    # worker's small wall met the campaign-global coverage
    wall_by_worker: dict[str, float] = {}
    for r in rows:
        cov = max(cov, int(r.get("coverage", 0)))
        t_rel = round(r.get("t", 0.0) - t0, 3)
        coverage_curve.append([t_rel, cov])
        if r.get("wall_s"):
            wall_by_worker[r["worker"]] = max(
                wall_by_worker.get(r["worker"], 0.0), float(r["wall_s"]))
        wall = max(wall_by_worker.values(), default=0.0)
        if wall:
            rate_curve.append([t_rel, round(cov / wall, 2)])
        if r.get("lat_p99") is not None:
            p99_curve.append([t_rel, int(r["lat_p99"])])
    # SLO rollup (r23): total misses over every deduped round row plus
    # the last advertised target — the tile next to the p99 curve. None
    # when no worker ran the latency plane (section doesn't render).
    slo_rows = [r for r in rows if r.get("slo_miss") is not None]
    slo = (dict(target=max((int(r.get("slo_target", 0))
                            for r in slo_rows), default=0),
                miss=sum(int(r["slo_miss"]) for r in slo_rows))
           if slo_rows else None)
    return dict(timeline=rows, coverage_curve=coverage_curve,
                rate_curve=rate_curve, p99_curve=p99_curve,
                slo=slo, workers_health=health)


def campaign_report(corpus_dir: str, uptime_s: float = 0.0,
                    workers: int = 0, worker_results: dict | None = None,
                    stale_after: float = 3.0) -> dict:
    """The merged truth of a campaign dir: coverage, per-worker rounds,
    crash buckets AFTER the read-side suffix merge (so the count is
    bugs, not bucket-open races), and the durable timeline
    (`campaign_timeline` — coverage/schedules-per-sec/p99 curves +
    per-worker last-seen health, with stale workers FLAGGED rather than
    their last counters silently reported as current)."""
    store = CorpusStore(corpus_dir, create=False)
    stats = campaign_stats(corpus_dir, uptime_s=uptime_s, workers=workers,
                           store=store)
    merged = merged_buckets(store)
    per_worker = {
        w: store.load_worker_state(w) for w in store.worker_ids()}
    tl = campaign_timeline(store, stale_after=stale_after)
    return dict(
        stats,
        timeline=tl["timeline"],
        coverage_curve=tl["coverage_curve"],
        rate_curve=tl["rate_curve"],
        p99_curve=tl["p99_curve"],
        slo=tl["slo"],
        workers_health=tl["workers_health"],
        stale_workers=sorted(w for w, h in tl["workers_health"].items()
                             if h["stale"]),
        buckets_merged=len(merged),
        bucket_detail=[
            dict(key=m["key"], crash_code=m["crash_code"],
                 members=m["members"], observations=m["observations"],
                 repro=m["repro"],
                 minimized="minimized" in m)
            for m in merged],
        workers_detail={
            **{w: dict(rounds_done=s.get("rounds_done", 0),
                       corpus_entries=len(s.get("order", [])),
                       wall_s=round(s.get("wall_s", 0.0), 2),
                       dry=s.get("dry", 0))
               for w, s in per_worker.items()},
            # mesh-sharded groups: one row per group, shard widths and
            # the per-shard live-entry split visible
            **{f"g{g}": dict(
                rounds_done=s.get("rounds_done", 0),
                shards=s.get("shards", 0),
                corpus_entries=sum(len(sh.get("order", []))
                                   for sh in s.get("shard_states", [])),
                per_shard_entries=[len(sh.get("order", []))
                                   for sh in s.get("shard_states", [])],
                wall_s=round(s.get("wall_s", 0.0), 2),
                dry=s.get("dry", 0))
               for g, s in ((g, store.load_shard_group_state(g))
                            for g in store.shard_group_ids())}},
        worker_results=worker_results)


def prune_cold_entries(corpus_dir: str, below: float = 0.1,
                       keep_min: int = 4) -> dict:
    """Supervisor policy op: drop cold entries (current energy < `below`)
    from every worker's and shard's LIVE corpus, keeping at least the
    `keep_min` hottest per corpus. Rewrites only the scheduler `order`
    lists (one atomic replace per state file); entry FILES are immutable
    admission records and stay — the campaign's coverage frontier
    (`_seen`, dedup, dry detection) is untouched, exactly like an
    eviction. Run it only between segments (no live workers): a pruned
    order changes the resumed run's parent draws BY DESIGN — this is a
    supervisor intervention, not a resume, so the split==continuous
    equality contract deliberately does not span it.

    Returns {pruned, kept, workers} counts."""
    from .store import _atomic_json
    store = CorpusStore(corpus_dir, create=False)
    pruned = kept = touched = 0

    def prune_order(order):
        nonlocal pruned, kept
        if len(order) <= keep_min:
            kept += len(order)
            return order, False
        hot = sorted(range(len(order)), key=lambda i: -order[i][1])
        protect = set(hot[:keep_min])
        new = [row for i, row in enumerate(order)
               if row[1] >= below or i in protect]
        pruned += len(order) - len(new)
        kept += len(new)
        return new, len(new) != len(order)

    for w in store.worker_ids():
        ws = store.load_worker_state(w)
        if not ws:
            continue
        ws["order"], changed = prune_order(ws.get("order", []))
        if changed:
            _atomic_json(store.worker_state_path(w), ws)
            touched += 1
    for g in store.shard_group_ids():
        gs = store.load_shard_group_state(g)
        changed_any = False
        for sh in gs.get("shard_states", []):
            sh["order"], changed = prune_order(sh.get("order", []))
            changed_any |= changed
        if changed_any:
            _atomic_json(store.shard_group_path(g), gs)
            touched += 1
    return dict(pruned=pruned, kept=kept, workers=touched)


def supervise_campaign(factory: str, corpus_dir: str, *, workers: int = 2,
                       segments: int = 3, rounds_per_segment: int = 4,
                       max_steps: int, batch: int = 64, chunk: int = 256,
                       factory_kwargs: dict | None = None,
                       base_seed: int = 0, sync_every: int = 1,
                       minimize: bool = False, shards: int = 1,
                       verify_resume: bool = False,
                       prune_below: float = 0.1, prune_keep_min: int = 4,
                       observer=None, env: dict | None = None,
                       poll_s: float = 2.0,
                       python: str = sys.executable,
                       run_segment=None, triage: bool = True) -> dict:
    """The always-on supervisor loop (the r11 follow-on): run campaign
    SEGMENTS back to back, each rotating the per-worker `max_rounds`
    target up by `rounds_per_segment` — so `run_campaign`'s
    one-segment-per-call contract becomes a service. Between segments
    the supervisor:

      - RESTARTS dead workers: a worker that exited nonzero (crash,
        OOM, SIGKILL) left its store consistent at its last sync; the
        next segment respawns every worker id, and the dead one resumes
        from where it actually synced (the durability contract) — the
        restart count is reported per segment;
      - PRUNES cold corpus entries (`prune_cold_entries`): energies
        decay every round, so multi-segment campaigns accumulate dead
        weight in the scheduler orders; pruning keeps parent sampling
        sharp without ever forgetting coverage.

      - SNAPSHOTS the triage plane (`triage=True`, the default): one
        `service.triage.triage_snapshot` per segment, so a long
        campaign accretes a diffable `triage/` history for free —
        `python -m madsim_tpu.service.report <dir> --against prev`
        answers "what did the last segment buy" without re-reading raw
        entry files (the snapshot walk is O(new files) on the
        supervisor's long-lived store handle, like the poll loop).

    `run_segment` injects the segment runner (tests stub it); default
    is `run_campaign`. Returns {segments: [per-segment report summary
    incl. its snapshot number], restarts, pruned, report: final merged
    campaign_report}."""
    runner = run_campaign if run_segment is None else run_segment
    seg_rows = []
    restarts = 0
    pruned_total = 0
    triage_store = None
    prev_snap = None
    for seg in range(segments):
        target = (seg + 1) * rounds_per_segment
        rep = runner(factory, corpus_dir, workers=workers,
                     max_steps=max_steps, batch=batch, max_rounds=target,
                     chunk=chunk, factory_kwargs=factory_kwargs,
                     base_seed=base_seed, sync_every=sync_every,
                     minimize=minimize, shards=shards,
                     verify_resume=verify_resume, observer=observer,
                     env=env, poll_s=poll_s, python=python)
        dead = sorted(
            int(w) for w, r in (rep.get("worker_results") or {}).items()
            if r.get("returncode") not in (0, None))
        if seg + 1 < segments:
            restarts += len(dead)
            pr = prune_cold_entries(corpus_dir, below=prune_below,
                                    keep_min=prune_keep_min)
            pruned_total += pr["pruned"]
        snap_no = None
        if triage and os.path.exists(
                os.path.join(corpus_dir, "MANIFEST.json")):
            from .triage import triage_diff, triage_snapshot
            if triage_store is None:
                triage_store = CorpusStore(corpus_dir, create=False)
            snap_no, snap = triage_snapshot(triage_store)
            if observer is not None:
                rec = dict(kind="triage", segment=seg, snapshot=snap_no)
                if prev_snap is not None:
                    d = triage_diff(prev_snap, snap)
                    rec.update(
                        empty=d["empty"],
                        coverage_added=d["coverage"]["added"],
                        **{f"buckets_{k}": len(v)
                           for k, v in d["buckets"].items()})
                observer.on_round(rec)
            prev_snap = snap
        seg_rows.append(dict(
            segment=seg, max_rounds=target,
            rounds_done=rep.get("rounds_done", 0),
            coverage_keys=rep.get("coverage_keys", 0),
            buckets=rep.get("buckets", 0),
            dead_workers=dead,
            snapshot=snap_no))
        if observer is not None:
            observer.on_round(dict(kind="supervisor", segment=seg,
                                   max_rounds=target,
                                   dead_workers=dead,
                                   restarts=restarts,
                                   pruned=pruned_total))
    return dict(segments=seg_rows, restarts=restarts,
                pruned=pruned_total,
                report=campaign_report(corpus_dir, workers=workers))


def replay_bucket(rt, corpus_dir: str, key: str, max_steps: int,
                  chunk: int = 256, dup_slots: int = 2,
                  verify: bool | None = None,
                  full_chain: bool = False, window_trace: bool = False):
    """Re-run a bucket's kept repro — the durable analog of pasting a
    madsim seed into a failing test. Returns (crashed, crash_code,
    explain dict or None): the (seed, knobs) handle replays the exact
    trajectory on any host with a structurally equal runtime — the
    manifest signature guards that (a mismatched `rt`, or a different
    `dup_slots` than the campaign fuzzed with, raises StoreMismatch
    here instead of replaying knobs onto the wrong rows).

    verify (r13, knob-gated; None reads MADSIM_FUZZ_VERIFY_RESUME,
    default off): run-twice guard mirroring `analyze.replay_race` — a
    bucket replay is replay-AUTHORITATIVE ("does this bug still
    exist?"), and this jaxlib's first invocation of a fused executable
    deserialized from the shared persistent compile cache can return a
    deterministic-but-wrong result under load (ROADMAP r12 note;
    campaign workers share one cache dir by design). With verify on,
    the lane re-runs until two consecutive invocations agree on
    (crashed, code, fingerprint); three distinct results raise — real
    nondeterminism, not the known transient.

    full_chain (r20, DESIGN §21): when the replayed crash's chain is
    truncated at ring wrap — or the runtime compiled the ring out —
    re-run the handle through `obs.timetravel.full_chain_replay` (the
    t=0 checkpoint, ring upgraded to hold the whole trajectory) and
    return the COMPLETE chain instead; the bucket record is upgraded
    in place when the complete chain matches the bucket
    (deepest-common-suffix), so triage converges to full chains.
    window_trace additionally writes the replayed window's focused
    Perfetto export next to the bucket artifacts
    (`buckets/<key>.window.trace.json` — service.report links it)."""
    import numpy as np

    from ..obs.causal import explain_crash
    from ..search.fuzz import _env_verify_resume
    from ..search.mutate import KnobPlan
    from .store import store_signature
    if verify is None:
        verify = _env_verify_resume()
    plan = KnobPlan.from_runtime(rt, dup_slots=dup_slots)
    store = CorpusStore(corpus_dir, signature=store_signature(rt, plan),
                        create=False)
    seed, knobs = store.load_bucket_repro(key)

    def once():
        state = plan.apply(rt.init_batch(np.asarray([seed], np.uint32)),
                           KnobPlan.stack([knobs]))
        state = rt.run_fused(state, max_steps, chunk)
        return state, (bool(np.asarray(state.crashed)[0]),
                       int(np.asarray(state.crash_code)[0]),
                       int(rt.fingerprints(state)[0]))

    state, out = once()
    if verify:
        from ..utils.verify import agree_twice
        state, out = agree_twice(
            (state, out), lambda _: once(), key_of=lambda t: t[1],
            what=f"bucket {key}",
            detail=lambda a, b, c: (f"fingerprints {a[1][2]}, {b[1][2]}, "
                                    f"{c[1][2]}"))
    crashed, code, fp_seen = out
    exp = None
    if crashed and rt.cfg.trace_cap > 0:
        exp = explain_crash(state, 0)
    if full_chain and crashed and (exp is None or exp.get("truncated")):
        from ..obs.causal import causal_fingerprint, fingerprints_match
        from ..obs.timetravel import full_chain_replay
        trace_path = (store.bucket_path(key, ".window.trace.json")
                      if window_trace else None)
        rep = full_chain_replay(
            rt, seed=seed, knobs=knobs,
            expect=dict(crashed=crashed, crash_code=code,
                        fingerprint=fp_seen),
            max_steps=max_steps, chunk=chunk,
            trace_cap=int(np.asarray(state.steps)[0]) + 1,
            export_trace=trace_path)
        exp = rep["explain"]
        # converge the durable record to the complete chain — only when
        # deepest-common-suffix proves it the same bug as the bucket
        rec = store.load_bucket(key)
        new_fp = causal_fingerprint(exp)
        old_fp = rec["fingerprint"]
        deeper = (new_fp["depth"] > old_fp["depth"]
                  or (new_fp.get("complete") and not old_fp.get("complete"))
                  or rec.get("chain_truncated") is not False)
        if deeper and fingerprints_match(new_fp, old_fp):
            rec.update(fingerprint=new_fp,
                       chain=[{k: int(c[k]) for k in c}
                              for c in exp["chain"]],
                       chain_truncated=bool(exp["truncated"]))
            store.write_bucket(key, rec)
    elif full_chain and crashed and window_trace:
        # chain already complete in the live ring: still attach the
        # focused trace so the bucket row links it
        from ..obs.trace import export_chrome_trace
        export_chrome_trace(store.bucket_path(key, ".window.trace.json"),
                            state=state, lane=0)
    return crashed, code, exp
