"""The durable corpus store: a fuzzing campaign that survives its process.

madsim's determinism core makes distributed crash harvests mergeable BY
CONSTRUCTION — a `(seed, knobs)` pair reproduces an entire execution, so
two workers' corpora are just two sets of replayable handles keyed by
coverage (PAPER.md). This module turns the r9 in-memory `search.Corpus`
into that durable, mergeable artifact: a directory any number of worker
processes share, written with the same versioned reject-on-mismatch
contract as `runtime/checkpoint.py` and read back into a campaign that
resumes exactly where it left off.

Layout (one campaign = one directory):

  MANIFEST.json           format + version + structural signature —
                          validated on open, REJECTED on mismatch (the
                          checkpoint contract: silently merging corpora
                          from different structures would poison both)
  entries/w<w>-<c>.npz    one admitted corpus entry per file, IMMUTABLE
                          once renamed into place: knob arrays + coverage
                          key (sched_hash) + admission metadata. The file
                          name IS the namespaced entry id (worker w,
                          counter c), so cross-process merge is lock-free
                          set union — no two workers can mint the same
                          name, and a scan is a dedup-by-construction
                          merge (search/corpus.py `admit_foreign`)
  state/w<w>.json         one worker's scheduler state: rounds done, rng
                          state, live-entry order + CURRENT energies,
                          cross-round consensus sketch counters — energy
                          and rng are per-worker POLICY state; coverage
                          (the entry files) is the shared campaign truth
  state/g<w>.json         a MESH-SHARDED worker's group state (r13):
                          every shard's scheduler state plus the
                          cross-shard consensus tally, committed by ONE
                          rename per sync so a kill can never tear the
                          shards of one worker apart (shard s mints
                          entries in namespace w*shards+s — just more
                          worker ids to everyone else; because that
                          mapping numerically overlaps plain worker
                          ids, fuzz/fuzz_sharded refuse at open any
                          namespace another owner's state claims —
                          `claimed_namespaces`)
  buckets/<key>.json|.npz|.trace.json
                          crash buckets (service/buckets.py): fingerprint
                          record, minimal (seed, knobs) repro, Perfetto
                          trace of the crash lane
  buckets.jsonl           append-only observation log (one line per
                          bucketed crash observation; the bucket DIR is
                          the deduped truth, this is the rate telemetry)
  logs/w<w>.jsonl         per-worker SweepObserver records (fuzz rounds)
  metrics/w<w>.jsonl      per-worker campaign-timeline rows (r15): one
  metrics/g<w>.jsonl      append per durability sync (sharded groups use
                          the g-prefix), fsync'd, carrying
                          (t, rounds_done, coverage, seeds_run, crashes,
                          corpus_size, wall_s, op_yield). Appended BEFORE
                          the state sync, so a kill between the two
                          re-appends an identical row on resume —
                          `campaign_timeline` dedups by rounds_done, so
                          the durable timeline has no gaps and no double
                          counts, and a campaign is inspectable after
                          the fact without a live poller
                          (service/campaign.py `campaign_report`)

Atomicity: every file is written to a `.tmp-<pid>` sibling and
`os.replace`d into place, so a SIGKILL at any instant leaves either the
old file or the new one, never a torn read — loaders additionally skip
tmp names outright. A kill mid-SYNC (some entry files renamed, the state
json not yet) is repaired on resume: own-namespace entry files whose
counter is at or past the state's `next_counter` are ignored (the
interrupted round re-runs deterministically and rewrites them with
identical bytes), so resume converges to exactly the uninterrupted run.
"""

from __future__ import annotations

import json
import os
import tempfile

import numpy as np

from ..search.corpus import _ID_SHIFT, Corpus, split_entry_id

CORPUS_FORMAT = "madsim-corpus"
CORPUS_VERSION = 1

_TMP_MARK = ".tmp-"


class StoreMismatch(ValueError):
    """Corpus dir was written by a different format version or a
    structurally different runtime — resuming would corrupt both."""


# ---------------------------------------------------------------------------
# atomic write primitives (write-then-rename; the whole durability story)
# ---------------------------------------------------------------------------

def _atomic_bytes(path: str, data: bytes) -> None:
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path)
                               + _TMP_MARK)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            # fsync data before the rename and the directory after it:
            # SIGKILL-safety needs only the rename, but the durability
            # claim covers power loss, where an unsynced rename can
            # reach disk before the data blocks (zero-length "new" file)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        dfd = os.open(d or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _atomic_json(path: str, obj) -> None:
    _atomic_bytes(path, (json.dumps(obj, indent=1) + "\n").encode())


def _atomic_npz(path: str, arrays: dict) -> None:
    import io
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    _atomic_bytes(path, buf.getvalue())


def _is_tmp(name: str) -> bool:
    return _TMP_MARK in name


# ---------------------------------------------------------------------------
# signature
# ---------------------------------------------------------------------------

def store_signature(rt, plan) -> list:
    """The structural identity a corpus dir is bound to: the step
    program's structural signature (compile domain, DESIGN §10) plus the
    knob-vector schema (shapes/dtypes of everything an entry stores).
    Dynamic knobs (time_limit, exact latencies, ...) deliberately do NOT
    key the store — they ride inside entries, the same split that lets
    one executable serve many configs."""
    knobs = plan.base_knobs()
    return [
        "corpus-sig-v1",
        list(rt.cfg.structural_signature()),
        [int(plan.n_init), int(plan.R), int(plan.D), int(plan.N),
         int(plan.payload_words), bool(plan.jitter_gate)],
        [[k, list(np.asarray(v).shape), str(np.asarray(v).dtype)]
         for k, v in sorted(knobs.items())],
    ]


def _norm(sig) -> str:
    return json.dumps(sig, sort_keys=True)


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------

class CorpusStore:
    def __init__(self, corpus_dir: str, signature=None, create: bool = True):
        self.dir = os.path.abspath(corpus_dir)
        self.entries_dir = os.path.join(self.dir, "entries")
        self.state_dir = os.path.join(self.dir, "state")
        self.buckets_dir = os.path.join(self.dir, "buckets")
        self.logs_dir = os.path.join(self.dir, "logs")
        self.metrics_dir = os.path.join(self.dir, "metrics")
        manifest_path = os.path.join(self.dir, "MANIFEST.json")
        if create:
            for d in (self.entries_dir, self.state_dir, self.buckets_dir,
                      self.logs_dir, self.metrics_dir):
                os.makedirs(d, exist_ok=True)
        if os.path.exists(manifest_path):
            with open(manifest_path) as f:
                man = json.load(f)
            if man.get("format") != CORPUS_FORMAT:
                raise StoreMismatch(
                    f"{self.dir} is not a corpus dir "
                    f"(format={man.get('format')!r})")
            if man.get("version") != CORPUS_VERSION:
                raise StoreMismatch(
                    f"corpus format version {man.get('version')} != "
                    f"supported {CORPUS_VERSION} — refusing to merge "
                    "across formats; start a fresh dir (or migrate)")
            if signature is not None and _norm(man.get("signature")) \
                    != _norm(json.loads(json.dumps(signature))):
                raise StoreMismatch(
                    "corpus dir was written by a structurally different "
                    "runtime/knob-plan — entries would not be replayable "
                    "here. Expected signature:\n  "
                    f"{_norm(signature)}\nfound:\n  "
                    f"{_norm(man.get('signature'))}")
            self.signature = man.get("signature")
        else:
            if not create:
                raise FileNotFoundError(f"no corpus at {self.dir}")
            if signature is None:
                raise ValueError("creating a corpus dir needs a signature "
                                 "(store_signature(rt, plan))")
            self.signature = json.loads(json.dumps(signature))
            _atomic_json(manifest_path, dict(
                format=CORPUS_FORMAT, version=CORPUS_VERSION,
                signature=self.signature))
        # filenames already folded into the live corpus (merge cursor)
        self._scanned: set[str] = set()
        # entry files are IMMUTABLE once renamed into place, so their
        # coverage keys cache forever on a store handle — keeps the
        # campaign driver's poll loop O(new entries), not O(corpus)
        self._hash_cache: dict[str, int] = {}
        # triage-plane sibling cache (r18, service/triage.py): per entry
        # file, (coverage hash, recipe family) — same immutability
        # argument, so repeated snapshots off one handle re-read each
        # raw entry file at most once (O(new files), like the poll loop)
        self._triage_cache: dict[str, tuple] = {}

    # -- naming --------------------------------------------------------
    @staticmethod
    def _entry_name(eid: int) -> str:
        w, c = split_entry_id(eid)
        return f"w{w:04d}-{c:012d}.npz"

    @staticmethod
    def _parse_entry_name(name: str) -> int | None:
        if not (name.startswith("w") and name.endswith(".npz")) \
                or _is_tmp(name):
            return None
        try:
            w, c = name[1:-4].split("-")
            return (int(w) << _ID_SHIFT) | int(c)
        except ValueError:
            return None

    def worker_state_path(self, worker_id: int) -> str:
        return os.path.join(self.state_dir, f"w{worker_id:04d}.json")

    def shard_group_path(self, worker_id: int) -> str:
        """A mesh-sharded worker's GROUP state (r13, search/shard.py):
        all of its shards' scheduler states in one file, written by one
        atomic rename — a SIGKILL can never tear the shards of one
        worker apart. Named g<id>, not w<id>, so a plain fuzz() worker
        scanning the dir never mistakes a group file for its own state
        (the shard namespaces are disjoint from base worker ids by the
        worker_id*shards+s mapping, so entries can't collide either)."""
        return os.path.join(self.state_dir, f"g{worker_id:04d}.json")

    def worker_log_path(self, worker_id: int) -> str:
        return os.path.join(self.logs_dir, f"w{worker_id:04d}.jsonl")

    def metrics_path(self, worker_id: int, group: bool = False) -> str:
        return os.path.join(self.metrics_dir,
                            f"{'g' if group else 'w'}{worker_id:04d}.jsonl")

    # -- campaign timeline (r15) ---------------------------------------
    def append_metrics(self, worker_id: int, rec: dict,
                       group: bool = False) -> None:
        """Append one campaign-timeline row for this worker (fsync'd:
        the timeline must be trustworthy under SIGKILL respawns —
        single-line O_APPEND writes are atomic on POSIX at this size).
        Called right BEFORE the state sync it describes; see the layout
        docstring for the dedup contract that ordering buys."""
        os.makedirs(self.metrics_dir, exist_ok=True)
        with open(self.metrics_path(worker_id, group), "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def read_metrics(self) -> dict:
        """{worker label: [timeline rows, file order]} for every worker
        (and g<id> sharded group) that ever appended. Unparseable tail
        lines (a torn write under power loss — O_APPEND makes this
        unlikely, fsync ordering makes it harmless) are skipped."""
        out: dict[str, list] = {}
        try:
            names = sorted(os.listdir(self.metrics_dir))
        except FileNotFoundError:
            return out
        for n in names:
            if not n.endswith(".jsonl") or _is_tmp(n):
                continue
            rows = []
            with open(os.path.join(self.metrics_dir, n)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rows.append(json.loads(line))
                    except ValueError:
                        continue
            out[n[:-6]] = rows
        return out

    # -- entries -------------------------------------------------------
    def write_entry(self, entry: dict) -> None:
        """Persist one corpus entry (immutable admission record). Safe to
        re-run: a deterministic re-execution of an interrupted round
        rewrites the same name with identical content."""
        arrays = {f"knob_{k}": np.asarray(v)
                  for k, v in entry["knobs"].items()}
        arrays.update(
            id=np.int64(entry["id"]),
            hash=np.uint64(entry["hash"]),
            seed=np.int64(entry["seed"]),
            energy0=np.float64(entry["energy"]),
            round=np.int64(entry["round"]),
            div_slot=np.int64(-1 if entry.get("div_slot") is None
                              else entry["div_slot"]),
            crash_code=np.int64(entry.get("crash_code", 0)))
        # ADDITIVE r22 field: only lineage-targeted admissions carry an
        # origin member at all — a campaign without the LDFI arm writes
        # byte-identical files to a pre-r22 store, and pre-r22 readers
        # ignore unknown members by construction (np.load key access)
        if entry.get("origin"):
            arrays["origin"] = np.str_(entry["origin"])
        _atomic_npz(os.path.join(self.entries_dir,
                                 self._entry_name(entry["id"])), arrays)

    def load_entry(self, name: str) -> dict:
        with np.load(os.path.join(self.entries_dir, name)) as z:
            knobs = {k[5:]: np.array(z[k]) for k in z.files
                     if k.startswith("knob_")}
            div = int(z["div_slot"])
            out = dict(id=int(z["id"]), hash=int(z["hash"]),
                       seed=int(z["seed"]), energy=float(z["energy0"]),
                       round=int(z["round"]),
                       div_slot=None if div < 0 else div,
                       crash_code=int(z["crash_code"]), knobs=knobs)
            if "origin" in z.files:
                out["origin"] = str(z["origin"])
            return out

    def entry_names(self) -> list[str]:
        try:
            names = os.listdir(self.entries_dir)
        except FileNotFoundError:
            return []
        return sorted(n for n in names
                      if self._parse_entry_name(n) is not None)

    # -- worker state --------------------------------------------------
    def load_worker_state(self, worker_id: int) -> dict:
        p = self.worker_state_path(worker_id)
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    @staticmethod
    def _scheduler_state(corpus: Corpus) -> dict:
        """One corpus's serialized scheduler state — the per-worker (or
        per-shard) half of a state file: live-entry order + CURRENT
        energies, namespace counter, rng, consensus counters."""
        return dict(
            next_counter=split_entry_id(corpus._next_id)[1],
            order=[[int(e["id"]), float(e["energy"])]
                   for e in corpus.entries],
            crash_codes=sorted(int(c) for c in corpus.crash_codes),
            sketch_counts=(None if corpus._slot_counts is None else
                           [sorted((int(v), int(c)) for v, c in s.items())
                            for s in corpus._slot_counts]),
            rng_state=corpus.rng.bit_generator.state)

    def write_worker_state(self, corpus: Corpus, worker_id: int,
                           rounds_done: int, dry: int, op_hist,
                           wall_s: float, op_yield=None,
                           targeted_yield=None) -> None:
        self._write_own_entries(corpus, worker_id)
        st = dict(
            worker_id=int(worker_id),
            rounds_done=int(rounds_done),
            dry=int(dry),
            wall_s=float(wall_s),
            op_hist=[int(x) for x in np.asarray(op_hist)],
            op_yield=(None if op_yield is None
                      else [int(x) for x in np.asarray(op_yield)]),
            **self._scheduler_state(corpus))
        if targeted_yield is not None:
            # additive r22 counter (LDFI campaigns only): cumulative
            # targeted admissions — absent ⇒ byte-identical pre-r22 json
            st["targeted_yield"] = int(targeted_yield)
        _atomic_json(self.worker_state_path(worker_id), st)

    def write_shard_group_state(self, corpora, worker_id: int, shards: int,
                                rounds_done: int, dry: int, op_hist,
                                wall_s: float, tally=None,
                                op_yield=None,
                                targeted_yield=None) -> None:
        """Persist a sharded worker's WHOLE group as one atomic write:
        per-shard scheduler states (namespaced worker_id*shards+s), the
        shared round/dry/wall counters, and the cross-shard consensus
        tally. Top-level rounds_done/wall_s keep campaign_stats readers
        working unchanged. Entry files must already be on disk
        (`persist_entries` per shard) — the group json is the commit
        point, exactly like a worker state. `targeted_yield` (r22) is
        the group's cumulative targeted-arm admission count — written
        only when the campaign aimed (additive; ldfi-less group jsons
        stay byte-identical)."""
        st = dict(
            worker_id=int(worker_id),
            shards=int(shards),
            rounds_done=int(rounds_done),
            dry=int(dry),
            wall_s=float(wall_s),
            op_hist=[int(x) for x in np.asarray(op_hist)],
            op_yield=(None if op_yield is None
                      else [int(x) for x in np.asarray(op_yield)]),
            tally=(None if tally is None else
                   [sorted((int(v), int(c)) for v, c in s.items())
                    for s in tally]),
            shard_states=[
                dict(worker_id=int(c.worker_id),
                     **self._scheduler_state(c))
                for c in corpora])
        if targeted_yield is not None:
            st["targeted_yield"] = int(targeted_yield)
        _atomic_json(self.shard_group_path(worker_id), st)

    def load_shard_group_state(self, worker_id: int) -> dict:
        p = self.shard_group_path(worker_id)
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def persist_entries(self, corpus: Corpus, worker_id: int) -> None:
        """Write this corpus's not-yet-persisted own-namespace
        admissions (the public entry-file half of a sync; the sharded
        driver commits the group state separately, in one write)."""
        self._write_own_entries(corpus, worker_id)

    def _write_own_entries(self, corpus: Corpus, worker_id: int) -> None:
        """Write any of this worker's admissions not yet on disk (ids in
        the worker's namespace whose file is new to this store handle) —
        including entries admitted AND evicted since the last sync, whose
        coverage keys must survive a resume."""
        for e in list(corpus.entries) + corpus.evicted_unsynced:
            if split_entry_id(e["id"])[0] != worker_id:
                continue
            name = self._entry_name(e["id"])
            if name in self._scanned:
                continue
            self.write_entry(e)
            self._scanned.add(name)
        corpus.evicted_unsynced.clear()

    # -- corpus load / merge -------------------------------------------
    def load_corpus(self, plan, worker_id: int = 0, rng_seed: int = 0,
                    state: dict | None = None, **corpus_kwargs) -> Corpus:
        """Rebuild this worker's corpus: its own scheduler state (entry
        order, current energies, rng, consensus counters) from the state
        json, its own coverage history from its entry files, and every
        OTHER worker's entries merged in (`admit_foreign`). A fresh dir
        returns a fresh corpus seeded with `rng_seed`. `state` overrides
        the on-disk worker json — the sharded driver passes one shard's
        slice of a group state (the shards share a file, not a schema)."""
        corpus = Corpus(plan, rng=np.random.default_rng(rng_seed),
                        worker_id=worker_id, **corpus_kwargs)
        corpus.track_evictions = True
        ws = self.load_worker_state(worker_id) if state is None else state
        order = ws.get("order", [])
        if ws:
            corpus.rng.bit_generator.state = ws["rng_state"]
            corpus._next_id = ((worker_id << _ID_SHIFT)
                               | int(ws["next_counter"]))
            corpus.crash_codes = set(ws.get("crash_codes", []))
            sk = ws.get("sketch_counts")
            if sk is not None:
                corpus._slot_counts = [
                    {int(v): int(c) for v, c in slot} for slot in sk]
            for eid, energy in order:
                e = self.load_entry(self._entry_name(int(eid)))
                e["energy"] = float(energy)
                corpus._seen.add(e["hash"])
                corpus._insert(e)
        next_counter = int(ws.get("next_counter", 0))
        in_order = {int(eid) for eid, _ in order}
        for name in self.entry_names():
            eid = self._parse_entry_name(name)
            w, c = split_entry_id(eid)
            if w == worker_id:
                self._scanned.add(name)
                if eid in in_order:
                    continue        # already placed, in slot order
                if c >= next_counter:
                    # half-synced leftover of an interrupted round: the
                    # re-run regenerates it bit-identically — loading it
                    # now would fork the resumed corpus from the
                    # uninterrupted one
                    continue
                # admitted before the sync point but evicted since: its
                # coverage key must stay seen (eviction never forgets)
                corpus._seen.add(self.load_entry(name)["hash"])
            else:
                self._scanned.add(name)
                corpus.admit_foreign(self.load_entry(name))
        return corpus

    def merge_foreign(self, corpus: Corpus) -> int:
        """Fold entries other workers persisted since the last scan into
        the live corpus. Lock-free: entry files are immutable and
        namespaced, dedup is by coverage key."""
        admitted = 0
        for name in self.entry_names():
            if name in self._scanned:
                continue
            eid = self._parse_entry_name(name)
            if split_entry_id(eid)[0] == corpus.worker_id:
                continue            # own files are written, never merged
            self._scanned.add(name)
            if corpus.admit_foreign(self.load_entry(name)):
                admitted += 1
        return admitted

    def sync(self, corpus: Corpus, worker_id: int, rounds_done: int,
             dry: int, op_hist, wall_s: float, op_yield=None,
             targeted_yield=None) -> dict:
        """One durability point: merge other workers' new entries, then
        persist this worker's admissions and scheduler state. Called at
        round boundaries (fuzz(..., sync_every=)); everything between two
        syncs is re-derived deterministically on resume."""
        merged = self.merge_foreign(corpus)
        self.write_worker_state(corpus, worker_id, rounds_done, dry,
                                op_hist, wall_s, op_yield=op_yield,
                                targeted_yield=targeted_yield)
        return dict(merged_foreign=merged)

    # -- read-only reporting -------------------------------------------
    def worker_ids(self) -> list[int]:
        out = []
        for n in sorted(os.listdir(self.state_dir)):
            if n.startswith("w") and n.endswith(".json") \
                    and not _is_tmp(n):
                out.append(int(n[1:-5]))
        return out

    def claimed_namespaces(self) -> dict:
        """{worker-id namespace: owner label} for every namespace with
        scheduler state in this dir: a plain worker owns its own id, a
        shard group owns worker_id*shards+s for each of its shards.
        The shard↔worker mapping means a group's namespaces NUMERICALLY
        overlap plain worker ids (group 0 at 2 shards owns 0 and 1), so
        mixing plain and sharded workers carelessly on one dir would
        mint colliding entry files; fuzz()/fuzz_sharded() consult this
        map at open and refuse a namespace another owner already
        claimed. Best-effort (a check, not a lock): two workers racing
        their FIRST sync can still pass — the guard is for the
        misconfiguration, which persists, not the race window."""
        out = {}
        for w in self.worker_ids():
            out[w] = f"worker w{w}"
        for g in self.shard_group_ids():
            gs = self.load_shard_group_state(g)
            for sh in gs.get("shard_states", []):
                out[int(sh["worker_id"])] = f"shard group g{g}"
        return out

    def shard_group_ids(self) -> list[int]:
        """Base worker ids of mesh-sharded groups syncing into this dir
        (their g<id>.json files; campaign_stats folds these into the
        rollup next to plain worker states)."""
        out = []
        for n in sorted(os.listdir(self.state_dir)):
            if n.startswith("g") and n.endswith(".json") \
                    and not _is_tmp(n):
                out.append(int(n[1:-5]))
        return out

    def coverage_keys(self) -> set[int]:
        """The campaign's coverage frontier: every sched_hash any worker
        ever admitted (entry files are immutable admission records, so
        this is exact even across evictions; cached per file on this
        handle for the same reason)."""
        for n in self.entry_names():
            if n not in self._hash_cache:
                self._hash_cache[n] = self.load_entry(n)["hash"]
        return set(self._hash_cache.values())

    # -- triage plane (r18, service/triage.py) -------------------------
    def triage_dir(self) -> str:
        """The standing triage history subdir (ADDITIVE: no store schema
        bump — pre-r18 stores open cleanly and simply have no triage/
        yet). Holds numbered snapshots (NNNN.json), the scenario row
        table the recipe classifier needs (ROWS.json), and the
        repro-health audit ledger (AUDIT.json)."""
        return os.path.join(self.dir, "triage")

    def triage_rows_path(self) -> str:
        return os.path.join(self.triage_dir(), "ROWS.json")

    def write_triage_rows(self, plan) -> None:
        """Persist the base scenario ROW TABLE the recipe classifier
        reads (op codes + the classifier-relevant guards/flags) — the
        read side of attribution must not need the Runtime. Derived
        deterministically from the KnobPlan, so every worker writes
        identical bytes; skipped once present (write-once)."""
        p = self.triage_rows_path()
        if os.path.exists(p):
            return
        os.makedirs(self.triage_dir(), exist_ok=True)
        P = int(plan.payload_words)
        pay = np.asarray(plan.base["payload"])
        base_torn = (pay[:, P - 2] & 1 if P >= 2
                     else np.zeros(plan.R, np.int32))
        _atomic_bytes(p, (json.dumps(dict(
            op=[int(x) for x in np.asarray(plan.base["op"])],
            drop_ok=[bool(x) for x in np.asarray(plan.drop_ok)],
            torn_ok=[bool(x) for x in np.asarray(plan.torn_ok)],
            base_torn=[int(x) for x in base_torn]),
            sort_keys=True, indent=1) + "\n").encode())

    def load_triage_rows(self) -> dict | None:
        """The persisted row table, or None (pre-r18 store / no worker
        wrote it yet) — attribution then reports everything under the
        explicit `base` class instead of guessing."""
        try:
            with open(self.triage_rows_path()) as f:
                return json.load(f)
        except (FileNotFoundError, ValueError):
            return None

    # -- crash buckets (plumbing for service/buckets.py) ---------------
    def bucket_path(self, key: str, suffix: str = ".json") -> str:
        return os.path.join(self.buckets_dir, key + suffix)

    def bucket_keys(self) -> list[str]:
        try:
            names = os.listdir(self.buckets_dir)
        except FileNotFoundError:
            return []
        return sorted(n[:-5] for n in names
                      if n.endswith(".json")
                      and not n.endswith(".trace.json")
                      and not _is_tmp(n))

    def write_bucket(self, key: str, record: dict,
                     knobs: dict | None = None) -> None:
        if knobs is not None:
            _atomic_npz(self.bucket_path(key, ".npz"),
                        {f"knob_{k}": np.asarray(v)
                         for k, v in knobs.items()})
        _atomic_json(self.bucket_path(key), record)

    def load_bucket(self, key: str) -> dict:
        with open(self.bucket_path(key)) as f:
            return json.load(f)

    def load_bucket_repro(self, key: str) -> tuple[int, dict]:
        """(seed, knobs) — the full replay handle of a bucket's kept
        repro (a mutated lane's behavior needs both)."""
        rec = self.load_bucket(key)
        p = self.bucket_path(key, ".npz")
        with np.load(p) as z:
            knobs = {k[5:]: np.array(z[k]) for k in z.files
                     if k.startswith("knob_")}
        return int(rec["repro"]["seed"]), knobs

    def append_bucket_log(self, rec: dict) -> None:
        # single-line O_APPEND writes are atomic on POSIX at this size;
        # this is telemetry (rates), the bucket dir is the deduped truth
        with open(os.path.join(self.dir, "buckets.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")

    def bucket_log(self) -> list[dict]:
        p = os.path.join(self.dir, "buckets.jsonl")
        if not os.path.exists(p):
            return []
        out = []
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out

    def bucket_log_deduped(self) -> list[dict]:
        """The observation log with replayed duplicates collapsed:
        rows dedup by (fingerprint, worker, round), first kept. The
        append-only log gains an IDENTICAL line whenever a killed
        worker's interrupted round re-runs on resume (the append-
        before-sync ordering re-observes the same representative lane),
        and fuzz logs one representative per distinct code per round —
        so within one (fp, worker, round) a second line is always a
        replay artifact, never a new observation. Rate/observation
        consumers (campaign_stats, merged_buckets) fold THIS view; the
        raw log stays the forensic record."""
        seen: set[tuple] = set()
        out = []
        for line in self.bucket_log():
            k = (line.get("fp_key", line.get("bucket")),
                 line.get("worker_id"), line.get("round"))
            if k in seen:
                continue
            seen.add(k)
            out.append(line)
        return out
