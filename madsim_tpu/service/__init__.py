"""Persistent fuzzing campaigns (r11): the service layer over search/.

A campaign today survives its process: the corpus, the cross-round
consensus sketch, and every crash repro serialize into a versioned
corpus directory (`store.py`, the checkpoint contract of MIGRATION.md:
schema version + structural signature, reject-on-mismatch); crashes
dedup into causal-fingerprint buckets (`buckets.py`, one bug = one
bucket across lanes, seeds, processes, and ring-wrap depths); and N
worker processes share one dir lock-free (`campaign.py`/`worker.py`,
merge-by-construction: namespaced immutable entries + atomic renames).

See DESIGN.md §13 "Persistence discipline".
"""

from .buckets import CrashBuckets, merged_buckets
from .campaign import (campaign_report, campaign_stats, campaign_timeline,
                       prune_cold_entries, replay_bucket, run_campaign,
                       spawn_worker, supervise_campaign, worker_cmd)
from .store import CorpusStore, StoreMismatch, store_signature

__all__ = [
    "CorpusStore", "StoreMismatch", "store_signature",
    "CrashBuckets", "merged_buckets",
    "run_campaign", "supervise_campaign", "prune_cold_entries",
    "campaign_report", "campaign_stats", "campaign_timeline",
    "spawn_worker", "worker_cmd", "replay_bucket",
]
