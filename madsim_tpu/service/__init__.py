"""Persistent fuzzing campaigns (r11): the service layer over search/.

A campaign today survives its process: the corpus, the cross-round
consensus sketch, and every crash repro serialize into a versioned
corpus directory (`store.py`, the checkpoint contract of MIGRATION.md:
schema version + structural signature, reject-on-mismatch); crashes
dedup into causal-fingerprint buckets (`buckets.py`, one bug = one
bucket across lanes, seeds, processes, and ring-wrap depths); and N
worker processes share one dir lock-free (`campaign.py`/`worker.py`,
merge-by-construction: namespaced immutable entries + atomic renames).

The triage plane (r18, `triage.py`) sits on top as the read side's
product surface: byte-stable `triage/NNNN.json` snapshots, run-over-run
diffs with a bucket lifecycle (new/regressed/grew/stale), per-recipe
and per-operator attribution with exact sum-to-total accounting, the
repro-health audit ledger, and the `python -m madsim_tpu.service.report`
terminal/HTML dashboard (obs/dashboard.py). See DESIGN.md §13
"Persistence discipline" and §19 "Triage discipline".
"""

from .buckets import CrashBuckets, merged_buckets
from .campaign import (campaign_report, campaign_stats, campaign_timeline,
                       prune_cold_entries, replay_bucket, run_campaign,
                       spawn_worker, supervise_campaign, worker_cmd)
from .store import CorpusStore, StoreMismatch, store_signature
from .triage import (audit_buckets, list_snapshots, load_snapshot,
                     triage_diff, triage_snapshot)

__all__ = [
    "CorpusStore", "StoreMismatch", "store_signature",
    "CrashBuckets", "merged_buckets",
    "run_campaign", "supervise_campaign", "prune_cold_entries",
    "campaign_report", "campaign_stats", "campaign_timeline",
    "spawn_worker", "worker_cmd", "replay_bucket",
    "triage_snapshot", "triage_diff", "audit_buckets",
    "list_snapshots", "load_snapshot",
]
