"""Batch analytics: what the supervisor reads back from a seed sweep.

The reference exposes per-run Stat{msg_count} (network.rs:82-85) and prints
a repro line on failure. A batched runtime wants fleet-level reductions
(SURVEY §7 L6: first-crash seed, coverage stats): crash histograms by code,
schedule-space coverage (distinct terminal fingerprints), throughput
figures. Two tiers: cheap host-side numpy over transferred final state
(crash histograms, representatives), and — for the coverage question the
pipelined explore() asks every round — an ON-DEVICE distinct-schedule
reduction (`coverage_digest`) that ships only the O(distinct) summary
across the host boundary, never the full [B] hash array.
"""

from __future__ import annotations

from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _coverage_digest(sched_hash):
    """Device-side distinct-schedule reduction over the two uint32
    sched_hash lanes: lexicographic sort (two stable argsorts — uint64 is
    unavailable without x64), adjacent-compare for first occurrences, and
    a stable compaction of the distinct pairs to the front.

    Returns (pairs [B, 2] uint32 with the `n` distinct rows packed first
    in sorted order, n int32). Everything stays on-device; the caller
    transfers only the packed prefix — O(distinct) uint64s across the
    host boundary per round instead of the full [B] hash array (the
    TPU-Ising "ship summaries, not samples" discipline, PAPERS.md)."""
    h0, h1 = sched_hash[:, 0], sched_hash[:, 1]
    order = jnp.argsort(h1, stable=True)          # minor key first,
    order = order[jnp.argsort(h0[order], stable=True)]   # then major
    h0s, h1s = h0[order], h1[order]
    first = jnp.concatenate([
        jnp.ones((1,), bool),
        (h0s[1:] != h0s[:-1]) | (h1s[1:] != h1s[:-1])])
    pack = jnp.argsort(~first, stable=True)       # distinct rows first
    return jnp.stack([h0s[pack], h1s[pack]], axis=1), first.sum(
        dtype=jnp.int32)


def coverage_digest(state):
    """Launch the device-side coverage reduction; returns DEVICE arrays
    (pairs, n) without blocking — JAX async dispatch means the caller can
    queue more work (the pipelined explore()) before forcing either."""
    return _coverage_digest(state.sched_hash)


def digest_hashes(pairs, n) -> np.ndarray:
    """Materialize a coverage digest host-side: transfers only the `n`
    distinct rows (a device slice, not the full [B] array) and combines
    the lanes into uint64 — same value domain as `sched_hash_u64`, but
    already deduplicated and sorted."""
    top = np.asarray(pairs[:int(n)]).astype(np.uint64)
    return (top[:, 0] << np.uint64(32)) | top[:, 1]


def distinct_schedules(state) -> int:
    """Distinct dispatch-order count via the on-device reduction; only
    one int32 crosses the host boundary."""
    _, n = coverage_digest(state)
    return int(n)


def sched_hash_u64(state) -> np.ndarray:
    """Combine the two uint32 sched_hash lanes into one uint64 per
    trajectory (see core/state.py — two lanes keep birthday collisions
    negligible at 100k-seed fuzz scale)."""
    h = np.asarray(state.sched_hash).astype(np.uint64)
    return (h[..., 0] << np.uint64(32)) | h[..., 1]


@jax.jit
def _consensus_modal(sketches):
    """Per-slot modal sketch value over the whole (possibly sharded)
    batch, ties to the SMALLEST value — the `first_divergence_slots`
    consensus rule, computed on device in O(B log B) per slot (sort +
    rank-difference run lengths; no [B, B] compare, so the working set
    stays [B, S] however wide the mesh grows). Under a mesh the
    per-slot sort is a batch-global op — one gather across shards."""
    def one(col):
        v = jnp.sort(col)
        counts = (jnp.searchsorted(v, v, side="right")
                  - jnp.searchsorted(v, v, side="left"))
        # argmax takes the FIRST maximal count; v ascends, and every
        # occurrence of a value shares its count, so the first max IS
        # the smallest modal value — the ties-to-smallest rule
        return v[jnp.argmax(counts)]

    return jax.vmap(one, in_axes=1)(sketches)


def consensus_allreduce(sketches) -> np.ndarray:
    """The cross-shard consensus fold (r13): one device reduction over a
    mesh-sharded [B, S] prefix-sketch batch yielding the batch-global
    per-slot modal value (ties to smallest — bit-compatible with the
    host rule in `first_divergence_slots(consensus=None)`, which the
    tests assert). The sharded fuzz driver uses it for round-level
    divergence telemetry: the modal is computed where the sketch lanes
    live instead of re-deriving it in host numpy. (The per-lane sketch
    batch itself still reaches the host — each shard's corpus needs
    per-lane attribution, the same bill fuzz() pays — so this saves the
    host-side mode pass, not the transfer.) The corpus's CROSS-ROUND
    consensus counters remain host state (search/corpus.py) and merge
    across shards at sync points."""
    return np.asarray(_consensus_modal(jnp.asarray(sketches)))


def first_divergence_slots(sketches, consensus=None) -> np.ndarray:
    """Per-lane first-divergence slot from a [B, S] prefix-sketch array
    (SimState.cov_sketch): the first slot where a lane's sketch differs
    from the consensus prefix — by default the BATCH's per-slot modal
    value (ties to the smallest value, np.unique order); pass
    `consensus` (uint32[S]) to measure against another reference, e.g.
    the corpus's cross-round campaign consensus (search/corpus.py).
    Returns int64[B] in [0, S]; S means the lane never left the
    consensus within the recorded window (identical schedule, or
    divergence past slot S). Host-side numpy over a [B, S] transfer —
    kilobytes, after the sweep; the recording itself never left the
    device mid-run."""
    sk = np.asarray(sketches)
    B, S = sk.shape
    if S == 0:
        return np.zeros(B, np.int64)
    if consensus is None:
        consensus = np.zeros(S, sk.dtype)
        for j in range(S):
            vals, counts = np.unique(sk[:, j], return_counts=True)
            consensus[j] = vals[np.argmax(counts)]
    differs = sk != np.asarray(consensus)[None, :]
    return np.where(differs.any(1), differs.argmax(1), S).astype(np.int64)


def divergence_profile(state) -> dict | None:
    """First-divergence-step percentiles across a sweep, from the
    on-device prefix-coverage sketches (cfg.sketch_slots > 0): WHEN the
    batch's schedules split, not just HOW MANY terminal classes they
    reached (`distinct_schedules`). None when the sketch is compiled out
    or the state is unbatched. Steps are upper bounds: a lane whose
    first divergent slot is j matched the consensus prefix through slot
    j-1's checkpoint, i.e. through (j)*sketch_every dispatches."""
    sk = getattr(state, "cov_sketch", None)
    if sk is None:
        return None
    sk = np.asarray(sk)
    if sk.ndim != 2 or sk.shape[1] == 0:
        return None
    every = int(np.atleast_1d(np.asarray(state.sketch_every)).reshape(-1)[0])
    first = first_divergence_slots(sk)
    S = sk.shape[1]
    div = first < S
    out = dict(slots=S, every=every, batch=int(len(first)),
               diverged=int(div.sum()))
    if div.any():
        steps = (first[div] + 1) * every
        out.update(
            p10=int(np.percentile(steps, 10)),
            p50=int(np.percentile(steps, 50)),
            p90=int(np.percentile(steps, 90)),
            mean=round(float(steps.mean()), 1))
    return out


def _masked_half_sums(x, wm):
    """Masked batch sum of an int32 counter array, WIDE: int64 is
    unavailable without x64, and a plain int32 sum of per-lane counters
    wraps at realistic scale (512 lanes × ~1e7 busy ticks > 2^31) —
    exactly the wrapped-negative reading the saturating per-lane
    counters exist to prevent. Each counter is split into 16-bit halves
    and the halves summed separately; the host recombines hi·2^16 + lo
    into exact Python ints. Half-sums stay in-range for B ≤ 32767
    lanes — far above any single-device batch. `wm` is the 0/1 lane
    mask broadcast to x's shape. The ONE masked-reduction helper shared
    by the profiler and latency digests (traced inside both jits)."""
    xm = x * wm
    return jnp.stack([(xm >> 16).sum(0), (xm & 0xFFFF).sum(0)])


def _masked_lane_pcts(x, on, n, qs=(50, 90, 100)):
    """Per-lane percentiles of an int32[B] metric over the masked-ON
    lanes: sort with masked lanes pushed to +inf and index at the
    on-lane count, so a partially-masked batch never dilutes its own
    statistics; an all-masked batch reads 0, not the sort sentinel.
    Shared by `_profile_digest` and `_latency_digest` (q=100 = max)."""
    v = jnp.sort(jnp.where(on, x, jnp.int32(2**31 - 1)))

    def at(q):
        i = jnp.clip((jnp.maximum(n, 1) - 1) * q // 100,
                     0, x.shape[0] - 1)
        return v[i]

    return jnp.where(n > 0, jnp.stack([at(q) for q in qs]), 0)


@jax.jit
def _profile_digest(pf_dispatch, pf_busy, pf_kill, pf_restart, pf_qmax,
                    pf_drop, pf_delay, pf_on, steps, now):
    """Device-side reduction of the sim-profiler counter plane
    (cfg.profile, DESIGN §16): batch sums over the PROFILED lanes plus
    per-lane percentiles, so only the O(counters) summary crosses the
    host boundary — the same ship-summaries discipline as
    `coverage_digest`. Counter-plane half of the digest family; the
    latency histograms (cfg.latency_hist, r16) reduce through the
    sibling `_latency_digest` — both ride the shared
    `_masked_half_sums` / `_masked_lane_pcts` lane-mask plumbing."""
    onf = pf_on
    w = onf.astype(jnp.int32)
    n = w.sum()
    s64 = _masked_half_sums

    def pcts(x):
        return _masked_lane_pcts(x, onf, n)

    return dict(
        lanes=n,
        dispatch=s64(pf_dispatch, w[:, None, None]),   # [2, N, K]
        busy=s64(pf_busy, w[:, None]),                 # [2, N]
        kill=s64(pf_kill, w[:, None]),                 # [2, N]
        restart=s64(pf_restart, w[:, None]),           # [2, N]
        drop=s64(pf_drop, w),
        delay=s64(pf_delay, w),
        now_sum=s64(now, w),
        steps_sum=s64(steps, w),
        # per-lane [p50, p90, max] over profiled lanes
        qmax_pct=pcts(pf_qmax),
        steps_pct=pcts(steps),
        now_pct=pcts(now),
        # per-lane busy total for the percentile only: float32 sum
        # clipped below int32 max — N saturated per-node counters would
        # wrap an int32 per-lane sum (the percentile is a distribution
        # readout, exactness lives in the `busy` sums above)
        busy_total_pct=pcts(jnp.clip(
            pf_busy.astype(jnp.float32).sum(-1), 0,
            float(2**31 - 256)).astype(jnp.int32)),
    )


def profile_digest(state):
    """Launch the device-side profiler reduction over a batched state;
    returns DEVICE arrays (a dict — JAX async dispatch, force lazily)
    or None when the counter plane is compiled out (cfg.profile=False)
    or the state is unbatched. O(counters) crosses the host boundary
    when the caller materializes it, never the [B] lanes."""
    pf = getattr(state, "pf_busy", None)
    if pf is None or pf.ndim != 2 or pf.shape[1] == 0:
        return None
    return _profile_digest(state.pf_dispatch, state.pf_busy, state.pf_kill,
                           state.pf_restart, state.pf_qmax, state.pf_drop,
                           state.pf_delay, state.pf_on, state.steps,
                           state.now)


# confirmed-digest memo: (digest name, input-leaf ids) -> (leaf refs,
# result). The held leaf references keep the ids from being reused, so
# an id-tuple hit is a true identity hit; bounded LRU, entries are
# O(counters) plus the input device arrays they pin.
_DIGEST_MEMO: OrderedDict = OrderedDict()
_DIGEST_MEMO_CAP = 8


def _confirmed_digest(digest, state, leaves) -> dict | None:
    """Host-materialize a masked digest CONFIRMED by two agreeing
    invocations (`utils/verify.agree_twice`, the r12/r13 playbook
    applied to the report boundary), MEMOIZED on the identity of its
    input leaves: the known jaxlib compile-cache transient (ROADMAP
    r12 item, sharpened r16/r20) can corrupt digest invocations in a
    long-lived process — observed both as a one-off (next invocation
    correct) and STICKY (an early invocation correct, later ones
    folding the masked gate to all-zero). agree-twice absorbs the
    one-off; the memo absorbs the sticky shape (the digest is a pure
    function of immutable arrays, so the first confirmed result for a
    given state is THE result — re-deriving it can only re-roll the
    transient). Also saves a launch on the common
    counters-then-summary call pattern."""
    from ..utils.verify import agree_twice
    key = (getattr(digest, "__name__", str(digest)),
           tuple(map(id, leaves)))
    hit = _DIGEST_MEMO.get(key)
    if hit is not None:
        _DIGEST_MEMO.move_to_end(key)
        return hit[1]
    d = digest(state)
    if d is None:
        return None

    def host(dd):
        return {k: np.asarray(v) for k, v in dd.items()}

    out = agree_twice(
        host(d), lambda _: host(digest(state)),
        key_of=lambda r: tuple((k, r[k].tobytes()) for k in sorted(r)),
        what="masked-digest reduction")
    _DIGEST_MEMO[key] = (tuple(leaves), out)
    while len(_DIGEST_MEMO) > _DIGEST_MEMO_CAP:
        _DIGEST_MEMO.popitem(last=False)
    return out


def profile_counters(state) -> dict | None:
    """Materialize `profile_digest` host-side: plain numpy/int values
    (the split 16-bit half-sums recombined into exact int64s), None
    when the plane is compiled out. The raw-counter half of the
    profiler report — `obs.profiler.profile_summary` derives the
    human-facing rates (busy%, drop rate, mean delay) from it.
    Run-twice confirmed + memoized (`_confirmed_digest`)."""
    pf = getattr(state, "pf_busy", None)
    if pf is None or pf.ndim != 2 or pf.shape[1] == 0:
        return None
    d = _confirmed_digest(
        profile_digest, state,
        (state.pf_dispatch, state.pf_busy, state.pf_kill,
         state.pf_restart, state.pf_qmax, state.pf_drop,
         state.pf_delay, state.pf_on, state.steps, state.now))
    if d is None:
        return None

    def wide(a):        # hi·2^16 + lo — exact, however big the batch sum
        a = a.astype(np.int64)
        return a[0] * 65536 + a[1]

    return dict(
        lanes=int(d["lanes"]),
        dispatch=wide(d["dispatch"]),
        busy=wide(d["busy"]), kill=wide(d["kill"]),
        restart=wide(d["restart"]),
        drop=int(wide(d["drop"])), delay=int(wide(d["delay"])),
        now_sum=int(wide(d["now_sum"])),
        steps_sum=int(wide(d["steps_sum"])),
        qmax_p50=int(d["qmax_pct"][0]), qmax_p90=int(d["qmax_pct"][1]),
        qmax_max=int(d["qmax_pct"][2]),
        steps_p50=int(d["steps_pct"][0]), steps_p90=int(d["steps_pct"][1]),
        steps_max=int(d["steps_pct"][2]),
        now_p50=int(d["now_pct"][0]), now_p90=int(d["now_pct"][1]),
        now_max=int(d["now_pct"][2]),
        busy_total_p50=int(d["busy_total_pct"][0]),
        busy_total_p90=int(d["busy_total_pct"][1]),
        busy_total_max=int(d["busy_total_pct"][2]),
    )


# latency-plane bucket edges: bucket j of a cfg.latency_hist histogram
# holds latencies in [edge(j), edge(j+1)) ticks with edge(0) = 0,
# edge(j) = 2^(j-1) (core/step.py's exact integer bucketing rule)
def latency_bucket_edges(buckets: int) -> np.ndarray:
    """Lower edge of each log2 latency bucket, in ticks (int64[B]) —
    the host-side table of `bucket_lower_edge`."""
    return np.asarray([0] + [1 << j for j in range(buckets - 1)], np.int64)


def bucket_lower_edge(b):
    """Traced lower edge (ticks) of log2 bucket index `b` (int32): 0
    for bucket 0, 2^(b-1) otherwise. The ONE encoding of the
    bucket→edge rule — `_hist_quantiles` and `harness.slo.
    _hist_quantile_edge` both use it, so the invariant can never fire
    against a different edge than the one the reports print."""
    return jnp.where(b == 0, 0,
                     jnp.left_shift(jnp.int32(1), jnp.maximum(b - 1, 0)))


def _hist_quantiles(hist_f, qs):
    """Bucket-CDF quantile estimates for a [..., B] float32 histogram:
    for each q, the LOWER EDGE of the bucket containing the ceil(q·total)-th
    sample — a deterministic lower bound on the true quantile (so an
    SLO comparison `estimate > target` can never fire on a value the
    true quantile doesn't exceed). Counts are float32: totals can pass
    2^31 (saturated int32 per-lane counts × lanes) and the comparison
    against a float threshold is deterministic. Returns int32[..., Q];
    an empty histogram reads 0."""
    cdf = jnp.cumsum(hist_f, axis=-1)                     # [..., B]
    total = cdf[..., -1:]                                 # [..., 1]
    out = []
    for q in qs:
        need = jnp.ceil(total * q)
        # first bucket whose cdf reaches the q-th sample
        b = jnp.argmax(cdf >= jnp.maximum(need, 1.0), axis=-1).astype(
            jnp.int32)
        out.append(jnp.where(total[..., 0] > 0, bucket_lower_edge(b), 0))
    return jnp.stack(out, axis=-1)


_LAT_QS = (0.50, 0.90, 0.99, 0.999)
_LAT_QNAMES = ("p50", "p90", "p99", "p999")


@jax.jit
def _latency_digest(lh_sojourn, lh_e2e, lh_slo_miss, lh_on):
    """Device-side reduction of the SLO latency plane (cfg.latency_hist,
    DESIGN §17): histogram MERGE over the recorded lanes (wide masked
    sums — the shared `_masked_half_sums` plumbing) plus on-device
    quantile estimation from the merged bucket CDFs. O(buckets)
    crosses the host boundary, never the [B, N, buckets] lanes —
    p50/p90/p99/p999 at sweep scale for the cost of one small
    transfer at syncs the runners already pay."""
    onf = lh_on
    w = onf.astype(jnp.int32)
    n = w.sum()
    s64 = _masked_half_sums
    # merged histograms as floats for the quantile CDFs (exactness for
    # the counts themselves lives in the half-sums)
    wf = onf.astype(jnp.float32)
    soj_f = (lh_sojourn.astype(jnp.float32)
             * wf[:, None, None]).sum(0)                  # [N, B]
    e2e_f = (lh_e2e.astype(jnp.float32) * wf[:, None, None]).sum(0)
    return dict(
        lanes=n,
        sojourn=s64(lh_sojourn, w[:, None, None]),        # [2, N, B]
        e2e=s64(lh_e2e, w[:, None, None]),                # [2, N, B]
        slo_miss=s64(lh_slo_miss, w[:, None]),            # [2, N]
        # cluster-wide quantiles (all nodes folded) + per-node p99
        sojourn_q=_hist_quantiles(soj_f.sum(0), _LAT_QS),  # [4]
        e2e_q=_hist_quantiles(e2e_f.sum(0), _LAT_QS),      # [4]
        e2e_p99_by_node=_hist_quantiles(e2e_f, (0.99,))[..., 0],  # [N]
    )


def latency_digest(state):
    """Launch the device-side latency reduction over a batched state;
    returns DEVICE arrays (force lazily) or None when the plane is
    compiled out (cfg.latency_hist == 0) or the state is unbatched."""
    lh = getattr(state, "lh_e2e", None)
    if lh is None or lh.ndim != 3 or lh.shape[1] == 0 or lh.shape[2] == 0:
        return None
    return _latency_digest(state.lh_sojourn, state.lh_e2e,
                           state.lh_slo_miss, state.lh_on)


def latency_counters(state) -> dict | None:
    """Materialize `latency_digest` host-side: exact merged histograms
    (int64[N, B]), total SLO misses, and the quantile estimates in
    ticks (µs). None when the plane is compiled out. Run-twice
    confirmed + memoized (`_confirmed_digest`)."""
    lh = getattr(state, "lh_e2e", None)
    if lh is None or lh.ndim != 3 or lh.shape[1] == 0 or lh.shape[2] == 0:
        return None
    d = _confirmed_digest(
        latency_digest, state,
        (state.lh_sojourn, state.lh_e2e, state.lh_slo_miss, state.lh_on))
    if d is None:
        return None

    def wide(a):
        a = a.astype(np.int64)
        return a[0] * 65536 + a[1]

    out = dict(
        lanes=int(d["lanes"]),
        sojourn_hist=wide(d["sojourn"]),
        e2e_hist=wide(d["e2e"]),
        slo_miss_by_node=wide(d["slo_miss"]).tolist(),
        slo_miss=int(wide(d["slo_miss"]).sum()),
        e2e_p99_by_node=d["e2e_p99_by_node"].tolist(),
    )
    for i, nm in enumerate(_LAT_QNAMES):
        out[f"sojourn_{nm}"] = int(d["sojourn_q"][i])
        out[f"e2e_{nm}"] = int(d["e2e_q"][i])
    return out


@jax.jit
def _lane_e2e_p99(lh_e2e):
    """Per-LANE p99 estimate from each lane's own e2e histogram (nodes
    folded): int32[B] bucket lower edges — the tail-latency signal the
    fuzzer's corpus energy consumes (search/corpus.py lat_bonus).
    Lanes with no completions read 0."""
    hist = lh_e2e.astype(jnp.float32).sum(1)              # [B, BK]
    return _hist_quantiles(hist, (0.99,))[..., 0]


def lane_e2e_p99(state) -> np.ndarray | None:
    """Host-side per-lane p99 (ticks) off the latency plane; None when
    compiled out. One [B] int32 transfer — the per-lane attribution the
    corpus needs, the same bill the sketch batch pays."""
    lh = getattr(state, "lh_e2e", None)
    if lh is None or lh.ndim != 3 or lh.shape[1] == 0 or lh.shape[2] == 0:
        return None
    return np.asarray(_lane_e2e_p99(state.lh_e2e))


def latency_brief(state) -> dict | None:
    """The small JSON-able latency rollup observer records and
    `summarize()` carry: cluster p50/p99/p999, sojourn p99, SLO misses.
    None when the plane is compiled out."""
    c = latency_counters(state)
    if c is None:
        return None
    return dict(lanes=c["lanes"],
                e2e_p50=c["e2e_p50"], e2e_p99=c["e2e_p99"],
                e2e_p999=c["e2e_p999"], sojourn_p99=c["sojourn_p99"],
                slo_miss=c["slo_miss"],
                # the dynamic per-lane target, folded like the
                # attribution digest folds it (max = the report knob) —
                # so dashboards can show WHAT the misses missed (r23)
                slo_target=int(np.asarray(
                    getattr(state, "slo_target", 0)).max()),
                completions=int(c["e2e_hist"].sum()))


# series-plane fault-marker bits small enough that an 8-lane bit
# decomposition covers them (core/types.py SRF_*: 7 bits today)
_SRF_BITS = 8


@jax.jit
def _series_digest(sr_dispatch, sr_busy, sr_qhw, sr_drop, sr_dup,
                   sr_complete, sr_slo_miss, sr_lat, sr_fault, sr_on,
                   window_len):
    """Device-side reduction of the windowed telemetry plane
    (cfg.series_windows, DESIGN §22): per-WINDOW masked batch sums over
    the recording lanes — the sim-time shape the counter tracks and
    sparklines render — plus per-window p99 estimates off the merged
    window latency histograms and an OR-fold of the fault-marker words.
    O(W·K) crosses the host boundary, never the [B, W, ...] lanes; the
    same ship-summaries discipline as `_profile_digest` /
    `_latency_digest`, riding the shared `_masked_half_sums` plumbing."""
    onf = sr_on
    w = onf.astype(jnp.int32)
    n = w.sum()
    s64 = _masked_half_sums
    out = dict(
        lanes=n,
        # dominant dynamic knob across the recording lanes (all lanes
        # normally share it; `set_window_len` writes the full batch)
        window_len=jnp.where(onf, window_len, 0).max(),
        dispatch=s64(sr_dispatch, w[:, None, None]),      # [2, W, N]
        busy=s64(sr_busy, w[:, None, None]),              # [2, W, N]
        drop=s64(sr_drop, w[:, None]),                    # [2, W]
        dup=s64(sr_dup, w[:, None]),                      # [2, W]
        complete=s64(sr_complete, w[:, None]),            # [2, W]
        slo_miss=s64(sr_slo_miss, w[:, None]),            # [2, W]
        # high-water is a MAX fold, not a sum: deepest queue any
        # recording lane saw inside each window
        qhw=jnp.where(onf[:, None], sr_qhw, 0).max(0),    # [W]
    )
    # fault markers are bitmasks — OR over lanes via bit decomposition
    # (no integer or-reduce needed; SRF_* fits in _SRF_BITS lanes)
    bits = jnp.arange(_SRF_BITS)
    present = (((sr_fault[:, :, None] >> bits) & 1) > 0) & onf[:, None, None]
    out["fault"] = (present.any(0).astype(jnp.int32) << bits).sum(-1)
    if sr_lat.shape[1] > 0 and sr_lat.shape[2] > 0:
        wf = onf.astype(jnp.float32)
        lat_f = (sr_lat.astype(jnp.float32)
                 * wf[:, None, None]).sum(0)              # [W, LB]
        out["lat"] = s64(sr_lat, w[:, None, None])        # [2, W, LB]
        out["e2e_p99_by_window"] = _hist_quantiles(
            lat_f, (0.99,))[..., 0]                       # [W]
    return out


def series_digest(state):
    """Launch the device-side series reduction over a batched state;
    returns DEVICE arrays (force lazily) or None when the plane is
    compiled out (cfg.series_windows == 0) or the state is unbatched."""
    sq = getattr(state, "sr_qhw", None)
    if sq is None or sq.ndim != 2 or sq.shape[1] == 0:
        return None
    return _series_digest(state.sr_dispatch, state.sr_busy, state.sr_qhw,
                          state.sr_drop, state.sr_dup, state.sr_complete,
                          state.sr_slo_miss, state.sr_lat, state.sr_fault,
                          state.sr_on, state.window_len)


def series_counters(state) -> dict | None:
    """Materialize `series_digest` host-side: exact per-window int64
    series (the split 16-bit half-sums recombined), the batch-OR fault
    words, and per-window p99 estimates in ticks. None when the plane
    is compiled out. Run-twice confirmed + memoized
    (`_confirmed_digest` — the same persistent-cache containment the
    profiler and latency digests ride, r20)."""
    sq = getattr(state, "sr_qhw", None)
    if sq is None or sq.ndim != 2 or sq.shape[1] == 0:
        return None
    d = _confirmed_digest(
        series_digest, state,
        (state.sr_dispatch, state.sr_busy, state.sr_qhw, state.sr_drop,
         state.sr_dup, state.sr_complete, state.sr_slo_miss, state.sr_lat,
         state.sr_fault, state.sr_on, state.window_len))
    if d is None:
        return None

    def wide(a):
        a = a.astype(np.int64)
        return a[0] * 65536 + a[1]

    out = dict(
        lanes=int(d["lanes"]),
        windows=int(sq.shape[1]),
        window_len=int(d["window_len"]),
        dispatch=wide(d["dispatch"]),                     # int64 [W, N]
        busy=wide(d["busy"]),                             # int64 [W, N]
        drop=wide(d["drop"]).tolist(),
        dup=wide(d["dup"]).tolist(),
        complete=wide(d["complete"]).tolist(),
        slo_miss=wide(d["slo_miss"]).tolist(),
        qhw=d["qhw"].tolist(),
        fault=d["fault"].tolist(),
    )
    if "lat" in d:
        out["lat"] = wide(d["lat"])                       # int64 [W, LB]
        out["e2e_p99_by_window"] = d["e2e_p99_by_window"].tolist()
    return out


@jax.jit
def _lane_burst_lat(sr_lat):
    """Per-lane deepest TRANSIENT p99: each lane's per-window e2e p99
    estimate (windows kept separate — the whole point), max over
    windows. int32[B] bucket lower edges."""
    hist = sr_lat.astype(jnp.float32)                     # [B, W, LB]
    return _hist_quantiles(hist, (0.99,))[..., 0].max(-1)


@jax.jit
def _lane_burst_qhw(sr_qhw):
    return sr_qhw.max(-1)


def lane_burst(state) -> np.ndarray | None:
    """Host-side per-lane burst metric off the series plane: the
    deepest per-WINDOW p99 spike a lane hit (falling back to the
    per-window queue high-water when the latency plane is compiled
    out). This is the transient signal `lane_e2e_p99` cannot see — an
    aggregate p99 over the whole run dilutes a one-window spike that a
    heal then papers over, which is exactly the trajectory shape the
    recovery oracle and the fuzzer's burst_bonus hunt. None when the
    series plane is compiled out. One [B] int32 transfer."""
    sq = getattr(state, "sr_qhw", None)
    if sq is None or sq.ndim != 2 or sq.shape[1] == 0:
        return None
    sl = state.sr_lat
    if sl.ndim == 3 and sl.shape[1] > 0 and sl.shape[2] > 0:
        return np.asarray(_lane_burst_lat(sl))
    return np.asarray(_lane_burst_qhw(sq))


def series_brief(state) -> dict | None:
    """The small JSON-able series rollup `summarize()` carries: window
    geometry, the peak window's dispatch volume and queue high-water,
    the worst per-window p99, and which windows saw disruptive faults.
    None when the plane is compiled out."""
    c = series_counters(state)
    if c is None:
        return None
    disp_w = c["dispatch"].sum(-1)                        # [W] totals
    out = dict(lanes=c["lanes"], windows=c["windows"],
               window_len=c["window_len"],
               dispatch_peak=int(disp_w.max(initial=0)),
               dispatch_peak_window=int(disp_w.argmax()) if len(disp_w)
               else 0,
               qhw_peak=int(max(c["qhw"], default=0)),
               drops=int(sum(c["drop"])), dups=int(sum(c["dup"])),
               fault_windows=[i for i, f in enumerate(c["fault"]) if f])
    if "e2e_p99_by_window" in c:
        p99w = c["e2e_p99_by_window"]
        out["e2e_p99_peak"] = int(max(p99w, default=0))
        out["slo_miss"] = int(sum(c["slo_miss"]))
    return out


@jax.jit
def _attribution_digest(sa_tail, sa_bottleneck, sp_on, slo_target):
    """Device-side reduction of the critical-path attribution plane
    (cfg.span_attr, DESIGN §24): wide masked batch sums of the
    per-completion-node [N, SA_COMPONENTS] tail counters and of the
    dominant-hop bottleneck histogram — the shared `_masked_half_sums`
    plumbing, same ship-summaries discipline as the profiler/latency/
    series digests. O(N) crosses the host boundary, never the lanes."""
    onf = sp_on
    w = onf.astype(jnp.int32)
    s64 = _masked_half_sums
    return dict(
        lanes=w.sum(),
        # dominant dynamic SLO across the recording lanes (normally
        # shared; retune can split the batch — max is the report knob)
        slo_target=jnp.where(onf, slo_target, 0).max(),
        tail=s64(sa_tail, w[:, None, None]),              # [2, N, SA]
        bottleneck=s64(sa_bottleneck, w[:, None]),        # [2, N]
    )


def attribution_digest(state):
    """Launch the device-side attribution reduction over a batched
    state; returns DEVICE arrays (force lazily) or None when the plane
    is compiled out (cfg.span_attr False) or the state is unbatched."""
    sa = getattr(state, "sa_tail", None)
    if sa is None or sa.ndim != 3 or sa.shape[1] == 0:
        return None
    return _attribution_digest(state.sa_tail, state.sa_bottleneck,
                               state.sp_on, state.slo_target)


def attribution_counters(state) -> dict | None:
    """Materialize `attribution_digest` host-side: exact int64 tail
    component sums per completion node ([N, SA_COMPONENTS]:
    count/qwait/net/hops — core/state.py SA_*) and the bottleneck-node
    histogram ([N]: dominant-segment owner of each tail request). None
    when the plane is compiled out. Run-twice confirmed + memoized
    (`_confirmed_digest` — the r20 persistent-cache containment)."""
    sa = getattr(state, "sa_tail", None)
    if sa is None or sa.ndim != 3 or sa.shape[1] == 0:
        return None
    d = _confirmed_digest(
        attribution_digest, state,
        (state.sa_tail, state.sa_bottleneck, state.sp_on,
         state.slo_target))
    if d is None:
        return None

    def wide(a):
        a = a.astype(np.int64)
        return a[0] * 65536 + a[1]

    return dict(
        lanes=int(d["lanes"]),
        slo_target=int(d["slo_target"]),
        tail=wide(d["tail"]),                             # int64 [N, SA]
        bottleneck=wide(d["bottleneck"]).tolist(),
    )


def attribution_brief(state) -> dict | None:
    """The small JSON-able attribution rollup `summarize()` carries:
    how many requests blew the SLO, where their time went (queue-wait
    vs network/disk transit, cluster-total µs and the wait share),
    their mean hop depth, and which node owned the dominant segment
    most often. None when the plane is compiled out."""
    from ..core.state import SA_COUNT, SA_HOPS, SA_NET, SA_QWAIT
    c = attribution_counters(state)
    if c is None:
        return None
    t = c["tail"]
    tails = int(t[:, SA_COUNT].sum())
    qwait = int(t[:, SA_QWAIT].sum())
    net = int(t[:, SA_NET].sum())
    hops = int(t[:, SA_HOPS].sum())
    bn = c["bottleneck"]
    out = dict(lanes=c["lanes"], slo_target=c["slo_target"],
               tails=tails, qwait_us=qwait, net_us=net,
               wait_share=(round(qwait / (qwait + net), 4)
                           if qwait + net else None),
               hops_mean=round(hops / tails, 2) if tails else None,
               tails_by_node=t[:, SA_COUNT].tolist(),
               bottleneck_by_node=bn)
    if tails:
        out["bottleneck_node"] = int(np.argmax(bn))
        out["bottleneck_share"] = round(max(bn) / sum(bn), 4) if sum(bn) \
            else None
    return out


def schedule_representatives(state, seeds) -> dict:
    """{sched_hash: first seed that produced it} — one replayable
    representative per distinct interleaving class. After a sweep, replay
    just these with `Runtime.run_single` to see every distinct behavior
    the batch explored instead of eyeballing thousands of near-duplicate
    trajectories.

    `seeds` is required: it must be the exact seed array the batch was
    initialized with. Defaulting to arange(batch) would silently label
    lane indices as seeds after a sweep over non-contiguous seeds —
    non-replayable handles."""
    hashes = sched_hash_u64(state)
    seeds = np.asarray(seeds)
    # return_index gives first-occurrence indices: first seed wins
    uniq, idx = np.unique(hashes, return_index=True)
    return dict(zip(uniq.tolist(), seeds[idx].tolist()))


def summarize(rt, state, seeds=None) -> dict:
    """One-call fleet report for a (finished or running) batched state.

    `seeds` should be the exact seed array the batch was initialized
    with; the `first_seed_by_code`/`first_crash_seed` fields are then
    replayable handles. Without it the report falls back to LANE INDICES
    — the exact trap `schedule_representatives` documents and refuses —
    so the report says so explicitly: `seed_labels` is "seed" when real
    seeds were given and "lane_index" otherwise (a lane index only
    replays when the batch happened to be arange(B))."""
    halted = np.asarray(state.halted)
    crashed = np.asarray(state.crashed)
    codes = np.asarray(state.crash_code)
    now = np.asarray(state.now)
    B = halted.shape[0]
    seed_labels = "seed" if seeds is not None else "lane_index"
    seeds = (np.asarray(seeds) if seeds is not None
             else np.arange(B))

    crash_hist: dict[int, int] = {}
    first_seed_by_code: dict[int, int] = {}
    for i in np.nonzero(crashed)[0]:
        c = int(codes[i])
        crash_hist[c] = crash_hist.get(c, 0) + 1
        first_seed_by_code.setdefault(c, int(seeds[i]))

    fps = rt.fingerprints(state)
    return dict(
        batch=B,
        # what the *_seed fields actually label (see docstring): "seed"
        # when the caller passed the batch's seed array, "lane_index"
        # when it didn't — ambiguity is the footgun, so the report
        # carries the distinction instead of implying seeds
        seed_labels=seed_labels,
        halted=int(halted.sum()),
        crashed=int(crashed.sum()),
        crash_histogram=crash_hist,
        first_seed_by_code=first_seed_by_code,
        first_crash_seed=(int(seeds[np.argmax(crashed)])
                          if crashed.any() else None),
        virtual_time_mean_us=float(now.mean()),
        virtual_time_max_us=int(now.max()),
        events_total=int(np.asarray(state.steps).sum()),
        # None (not 0) when the run disabled stat collection — a literal 0
        # would read as "no traffic" in dashboards
        msgs_sent=(int(np.asarray(state.msg_sent).sum())
                   if rt.cfg.collect_stats else None),
        msgs_dropped=(int(np.asarray(state.msg_dropped).sum())
                      if rt.cfg.collect_stats else None),
        ev_peak_max=(int(np.asarray(state.ev_peak).max())
                     if rt.cfg.collect_stats else None),
        # schedule-space coverage proxy: distinct terminal states
        distinct_outcomes=int(len(np.unique(fps))),
        # schedule-space coverage proper: distinct dispatch ORDERS — the
        # batched form of task.rs:572-596's "N seeds -> N schedules".
        # Coarser than distinct_outcomes (fingerprints cover sched_hash
        # plus all payload/state differences) but it answers the coverage
        # question directly: how many INTERLEAVINGS did the batch explore,
        # independent of what values flowed through them. Counted by the
        # on-device reduction: one int32 crosses the host boundary, not
        # the [B] hash array.
        distinct_schedules=distinct_schedules(state),
        # schedule-space coverage DEPTH (r10): when the batch's schedules
        # first split, from the on-device prefix sketches — None when
        # cfg.sketch_slots == 0. distinct_schedules says how many
        # interleaving classes; first_divergence says how early the
        # batch bought them.
        first_divergence=divergence_profile(state),
        # where the cluster spent its effort (r15): the profiler digest
        # rollup — counters AND, since r16, the latency-histogram
        # quantiles ride the digest family; None when cfg.profile is
        # off. Arrays summarized to lists so the report stays JSON-able
        # like everything else.
        profile=_profile_brief(state),
        # how long requests took (r16): cluster p50/p99/p999 + SLO
        # misses off the latency plane — None when cfg.latency_hist
        # is 0.
        latency=latency_brief(state),
        # WHEN inside the run it happened (r21): the windowed series
        # rollup — peak window, transient p99 spike, fault windows.
        # None when cfg.series_windows is 0.
        series=series_brief(state),
        # WHY the tail was slow (r23): queue-wait vs transit split and
        # the bottleneck-node histogram over SLO-missing requests, off
        # the critical-path attribution plane — None when
        # cfg.span_attr is off.
        attribution=attribution_brief(state),
        oops=int((np.asarray(state.oops) != 0).sum()),
    )


def _profile_brief(state) -> dict | None:
    c = profile_counters(state)
    if c is None:
        return None
    return dict(
        lanes=c["lanes"],
        dispatch_by_node=c["dispatch"].sum(-1).tolist(),
        busy_by_node=c["busy"].tolist(),
        kills=int(c["kill"].sum()), restarts=int(c["restart"].sum()),
        drops=c["drop"], delay_ticks=c["delay"],
        qmax_p50=c["qmax_p50"], qmax_max=c["qmax_max"])
