"""Coverage-driven schedule exploration — loop-until-dry seed sweeps.

The reference's only exploration lever is "run more seeds": a FIXED
iteration count via `MADSIM_TEST_NUM` (madsim-macros/src/lib.rs:152-167),
with no way to know whether the extra seeds bought new schedules. With
the per-trajectory dispatch-order hash (`SimState.sched_hash`) the
batched engine can measure that directly: sweep successive seed batches
and stop when consecutive rounds stop producing schedules never seen
before — spend device time where coverage still grows, stop when the
schedule space (as the hash observes it) is saturated.

Crashes don't abort the sweep: every distinct crash code is collected
with its first seed (the repro handle), because a fuzzing run wants the
full harvest, not the first kill.

Pipelining (the Podracer discipline, PAPERS.md): each round is one fused
`run_fused` dispatch plus an on-device coverage reduction, both queued
asynchronously — so round r+1's init+run is DISPATCHED before the host
blocks on round r's digest, and the host-side dedup/crash-harvest of
round r overlaps round r+1's device compute. The device only idles when
the sweep is genuinely done. `pipeline=False` restores the serial
round-by-round order for debugging (identical results — pipelining only
reorders host work, never device math).
"""

from __future__ import annotations

import time

import numpy as np

from . import stats


def explore(rt, max_steps: int, batch: int = 512, max_rounds: int = 16,
            dry_rounds: int = 2, base_seed: int = 0, chunk: int = 512,
            pipeline: bool = True, fused: bool = True, observer=None):
    """Sweep seed batches until `dry_rounds` consecutive rounds add no
    new distinct schedule (or `max_rounds` is hit).

    Args beyond the sweep shape:
      pipeline: dispatch round r+1 before blocking on round r's results
        (double-buffered; JAX async dispatch overlaps host dedup with
        device compute). When the dry-stop fires, the one speculatively
        dispatched round is discarded — its device work is wasted, the
        price of never idling the device on the common (non-dry) path.
        Effective only with fused=True (the chunked runner blocks per
        chunk, so speculation there would be pure waste; it is gated
        off automatically).
      fused: drive each round with `Runtime.run_fused` (one XLA dispatch
        per round, on-device halt test) instead of the chunked `run()`.
        The chunked runner syncs to the host every `chunk` steps, which
        serializes rounds regardless of `pipeline`; fused is what makes
        the pipeline actually overlap.
      observer: optional obs.metrics.SweepObserver — an `on_round`
        record per harvested round (coverage growth off the digest the
        round already transfers: new_schedules, distinct_total, crashes)
        and `on_done` with the final result. Hooks fire at the harvest
        the loop already blocks on — no new host syncs, and observer
        wall-time sits exactly where host dedup already overlaps device
        compute in the pipelined path.

    Returns a dict:
      seeds_run            total seeds executed (harvested rounds only —
                           a discarded speculative round is not counted)
      rounds               rounds executed
      distinct_schedules   cumulative distinct sched_hash values
      new_per_round        schedules first seen in each round (the
                           saturation curve — diagnostic for how much a
                           bigger sweep could still buy)
      saturated            True if the dry-round stop fired
      crash_first_seed_by_code   {crash_code: first seed} repro handles
      crashes              total crashed trajectories
    """
    def launch(r):
        """Dispatch one round's full device program without blocking:
        init + run + coverage reduction are all queued async."""
        seeds = np.arange(base_seed + r * batch,
                          base_seed + (r + 1) * batch, dtype=np.uint32)
        if fused:
            state = rt.run_fused(rt.init_batch(seeds), max_steps, chunk)
        else:
            state, _ = rt.run(rt.init_batch(seeds), max_steps, chunk)
        pairs, n = stats.coverage_digest(state)
        return seeds, state, pairs, n

    def harvest(launched):
        """Block on one round's results. Transfers the O(distinct) hash
        digest plus the [B] crash lanes — never the full [B] hash array."""
        seeds, state, pairs, n = launched
        hashes = stats.digest_hashes(pairs, n)
        return (seeds, hashes, np.asarray(state.crashed),
                np.asarray(state.crash_code))

    seen: set[int] = set()
    crashes: dict[int, int] = {}
    n_crashed = 0
    new_per_round: list[int] = []
    dry = 0
    rounds = 0
    # speculation requires the fused runner: the chunked run() blocks on
    # every chunk's host sync, so a "speculative" chunked round would run
    # to completion inline — all waste, no overlap
    speculate = pipeline and fused
    t0 = time.perf_counter()
    pending = launch(0) if max_rounds > 0 else None
    for r in range(max_rounds):
        nxt = (launch(r + 1) if speculate and r + 1 < max_rounds else None)
        seeds, hashes, crashed, codes = harvest(pending)
        for i in np.nonzero(crashed)[0]:
            crashes.setdefault(int(codes[i]), int(seeds[i]))
        n_crashed += int(crashed.sum())
        fresh = set(hashes.tolist()) - seen
        new = len(fresh)
        seen |= fresh
        new_per_round.append(new)
        rounds += 1
        dry = dry + 1 if new == 0 else 0
        if observer is not None:
            observer.on_round(dict(
                kind="round", round=rounds, batch=batch,
                seeds_run=rounds * batch, new_schedules=new,
                distinct_total=len(seen), crashes=n_crashed,
                dry_rounds=dry, wall_s=time.perf_counter() - t0))
        if dry >= dry_rounds:
            break
        pending = nxt if nxt is not None else (
            launch(r + 1) if r + 1 < max_rounds else None)
    result = dict(
        seeds_run=rounds * batch,
        rounds=rounds,
        distinct_schedules=len(seen),
        new_per_round=new_per_round,
        saturated=dry >= dry_rounds,
        crash_first_seed_by_code=crashes,
        crashes=n_crashed,
    )
    if observer is not None:
        observer.on_done(dict(
            kind="done", distinct_total=len(seen),
            wall_s=time.perf_counter() - t0, **result))
    return result
