"""Coverage-driven schedule exploration — loop-until-dry seed sweeps.

The reference's only exploration lever is "run more seeds": a FIXED
iteration count via `MADSIM_TEST_NUM` (madsim-macros/src/lib.rs:152-167),
with no way to know whether the extra seeds bought new schedules. With
the per-trajectory dispatch-order hash (`SimState.sched_hash`) the
batched engine can measure that directly: sweep successive seed batches
and stop when consecutive rounds stop producing schedules never seen
before — spend device time where coverage still grows, stop when the
schedule space (as the hash observes it) is saturated.

Crashes don't abort the sweep: every distinct crash code is collected
with its first seed (the repro handle), because a fuzzing run wants the
full harvest, not the first kill.
"""

from __future__ import annotations

import numpy as np

from . import stats


def explore(rt, max_steps: int, batch: int = 512, max_rounds: int = 16,
            dry_rounds: int = 2, base_seed: int = 0, chunk: int = 512):
    """Sweep seed batches until `dry_rounds` consecutive rounds add no
    new distinct schedule (or `max_rounds` is hit).

    Returns a dict:
      seeds_run            total seeds executed
      rounds               rounds executed
      distinct_schedules   cumulative distinct sched_hash values
      new_per_round        schedules first seen in each round (the
                           saturation curve — diagnostic for how much a
                           bigger sweep could still buy)
      saturated            True if the dry-round stop fired
      crash_first_seed_by_code   {crash_code: first seed} repro handles
      crashes              total crashed trajectories
    """
    seen: set[int] = set()
    crashes: dict[int, int] = {}
    n_crashed = 0
    new_per_round: list[int] = []
    dry = 0
    rounds = 0
    for r in range(max_rounds):
        seeds = np.arange(base_seed + r * batch, base_seed + (r + 1) * batch,
                          dtype=np.uint32)
        state, _ = rt.run(rt.init_batch(seeds), max_steps, chunk)
        hashes = stats.sched_hash_u64(state).tolist()
        crashed = np.asarray(state.crashed)
        codes = np.asarray(state.crash_code)
        for i in np.nonzero(crashed)[0]:
            crashes.setdefault(int(codes[i]), int(seeds[i]))
        n_crashed += int(crashed.sum())
        new = len(set(hashes) - seen)
        seen.update(hashes)
        new_per_round.append(new)
        rounds += 1
        dry = dry + 1 if new == 0 else 0
        if dry >= dry_rounds:
            break
    return dict(
        seeds_run=rounds * batch,
        rounds=rounds,
        distinct_schedules=len(seen),
        new_per_round=new_per_round,
        saturated=dry >= dry_rounds,
        crash_first_seed_by_code=crashes,
        crashes=n_crashed,
    )
