"""Scale-out: shard the seed batch over a device mesh.

The reference scales schedule exploration by running more `cargo test`
processes (SURVEY.md §5 "long-context"); its real-mode comm backends are
TCP/UCX/eRPC (std/net/). The TPU-native equivalent (SURVEY.md §2.9):
trajectories are independent, so the seed batch is pure data parallelism —
shard it over ICI with `jax.sharding`, and the only cross-chip traffic is
reductions (all-halted tests, first-crash argmin, stat sums), which XLA
lowers to psum/all-reduce over the mesh. Multi-host scale-out uses the same
spec over a DCN-spanning mesh via `jax.distributed.initialize()`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SEED_AXIS = "seeds"


def seed_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices, named 'seeds'."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (SEED_AXIS,))


def shard_batch(state, mesh: Mesh):
    """Place a batched SimState so the leading [seed_batch] axis is sharded
    across the mesh; all other dims replicated. jit calls then run SPMD with
    no per-step communication (trajectories never talk to each other)."""
    sharding = NamedSharding(mesh, P(SEED_AXIS))
    return jax.tree.map(lambda a: jax.device_put(a, sharding), state)


def first_crash_seed(state, seeds) -> jax.Array:
    """Index of the lowest-index crashed trajectory, or -1. Under a sharded
    batch this is a cross-chip min-reduction riding ICI."""
    seeds = jnp.asarray(seeds)
    big = jnp.iinfo(jnp.int32).max
    lowest = jnp.min(jnp.where(state.crashed, jnp.arange(seeds.shape[0]),
                               big))
    return jnp.where(lowest == big, -1, lowest)


def compact(state, seeds):
    """Drop halted trajectories (host-side gather): returns (live_state,
    live_seeds). The early-exit compaction of BASELINE.md config 4 — after
    most seeds finish, re-pack the survivors into a dense smaller batch so
    lockstep stepping stops wasting lanes on frozen trajectories."""
    live = ~np.asarray(state.halted)
    idx = np.nonzero(live)[0]
    live_state = jax.tree.map(lambda a: jnp.asarray(np.asarray(a)[idx]),
                              state)
    return live_state, np.asarray(seeds)[idx]
