"""Multi-host scale-out over DCN — the jax.distributed wiring.

The reference's real-mode comm backends (TCP/UCX/eRPC) exist to span hosts;
its simulation scale-out lever is "run more processes" (SURVEY §5). Here
multi-host works the same way single-host multi-chip does: initialize the
jax.distributed runtime, build one global mesh over every chip of every
host, shard the seed batch over it, and let XLA route the only cross-chip
traffic (reductions) over ICI within a host and DCN between hosts.

Single-controller-per-host SPMD: every host runs the same program on its
own slice of the seed batch; `host_seed_slice` carves the global seed range
so lanes land on their local chips.

Validated two ways: the sharded compile path via dryrun_multichip's virtual
mesh, and a real two-process run over a loopback coordinator
(tests/test_distributed.py). Real multi-HOST hardware has not been
available; the recipe is the standard jax.distributed one.
"""

from __future__ import annotations

import jax
import numpy as np

from .mesh import seed_mesh, shard_batch


_initialized = False


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Initialize the multi-host runtime (idempotent within a process;
    no-op when single-process and no coordinator is configured). Must run
    before anything initializes the XLA backend — including importing
    libraries that touch jax.devices() (flax does)."""
    global _initialized
    if coordinator_address is None and num_processes is None:
        return  # single-process: nothing to do
    if _initialized:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True


def global_seed_mesh():
    """1-D 'seeds' mesh over EVERY device of every process."""
    return seed_mesh(jax.devices())


def host_seed_slice(total_seeds: int, base_seed: int = 0) -> np.ndarray:
    """This process's contiguous slice of the global seed range. The global
    batch must divide evenly across processes (global-shard assembly
    requires equal local shards); round the sweep size up rather than
    passing a ragged total."""
    n_proc = jax.process_count()
    pid = jax.process_index()
    assert total_seeds % n_proc == 0, (
        f"total_seeds {total_seeds} must divide evenly across {n_proc} "
        f"processes — pad the sweep to a multiple")
    per = total_seeds // n_proc
    return np.arange(base_seed + pid * per, base_seed + (pid + 1) * per,
                     dtype=np.uint32)


def run_fused_sharded(rt, seeds: np.ndarray, max_steps: int,
                      chunk: int = 512):
    """Whole-sweep-on-device at multi-process scale: assemble the global
    sharded batch (this process contributes its `host_seed_slice`) and
    drive it with the fused while_loop runner. The loop predicate's
    `halted.all()` lowers to a cross-chip all-reduce (ICI within a host,
    DCN between hosts) each chunk — no host touches the sweep until the
    caller reads results.

    This is the sharded complement to `run_compacting_sharded`: the
    compacting path re-packs lanes through host numpy and is therefore
    per-host by construction (Runtime.run_compacting refuses
    non-addressable batches); the fused path is pure SPMD, so the
    non-addressable global state goes straight through `run_fused` —
    which, unlike the chunked `run()`, never calls `bool(halted.all())`
    on the host and so never forces a cross-process sync point in
    Python. Choose fused when lanes halt together (no compaction win),
    compacting when the halt distribution is long-tailed.

    `seeds` is this process's LOCAL slice (from `host_seed_slice`).
    Returns the global sharded final state.
    """
    return rt.run_fused(shard_global(rt, seeds), max_steps, chunk)


def run_compacting_sharded(rt, seeds: np.ndarray, max_steps: int,
                           chunk: int = 512, compact_when: float = 0.5,
                           min_batch: int = 256):
    """Divergent-trajectory compaction at multi-process scale (BASELINE
    config 4): each process runs `Runtime.run_compacting` on ITS
    host-addressable slice of the sweep — early-halting lanes are stashed
    and survivors re-packed entirely within the host, so no cross-host
    traffic happens during the run — then the per-host full-slice final
    states are assembled into one global sharded array for cross-process
    reductions (first-crash argmin, stats), the only collective step.

    `seeds` is this process's LOCAL slice (from `host_seed_slice`).
    Returns the global sharded state in global lane order.

    This is the documented per-host-compaction path of
    `Runtime.run_compacting` (runtime/runtime.py), which itself refuses
    non-addressable batches: compaction re-packs lanes through host numpy
    and is inherently a local operation. Reference analog: each `cargo
    test` process finishes its own seeds at its own pace; only results
    are aggregated (SURVEY.md §5 scale-out lever).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    local = rt.init_batch(seeds)
    final = rt.run_compacting(local, max_steps, chunk=chunk,
                              compact_when=compact_when,
                              min_batch=min_batch)
    mesh = global_seed_mesh()
    if jax.process_count() == 1:
        return shard_batch(final, mesh)
    sharding = NamedSharding(mesh, P("seeds"))
    return jax.tree.map(
        lambda a: jax.make_array_from_process_local_data(
            sharding, np.asarray(a)), final)


def shard_global(rt, seeds: np.ndarray):
    """Build this host's LOCAL batch (its host_seed_slice) and assemble the
    global sharded state. Multi-process JAX requires assembling global
    arrays from per-process local shards (device_put with a global sharding
    wants the full value everywhere), hence make_array_from_process_local_
    data on the multi-host path."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    state = rt.init_batch(seeds)
    mesh = global_seed_mesh()
    if jax.process_count() == 1:
        return shard_batch(state, mesh)
    sharding = NamedSharding(mesh, P("seeds"))
    return jax.tree.map(
        lambda a: jax.make_array_from_process_local_data(
            sharding, np.asarray(a)), state)
