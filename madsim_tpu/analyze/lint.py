"""DetSan lint: static determinism hazards in traced callables.

The determinism contract (DESIGN §4) is a *discipline*, not a property
the engine can enforce at runtime: a Program handler that calls
`time.time()` or `np.random.rand()` executes that call ONCE, at trace
time, and bakes the value into the compiled program — the run still
replays bit-identically, but rebuilding the Runtime (or losing the
compile-cache entry) silently changes behavior, and the printed
`MADSIM_TEST_SEED=` repro line stops reproducing. This linter finds
those hazards where they are cheapest to find: in the source, before
anything runs.

What counts as a TRACED SCOPE (the only place the rules apply — host
driver code may use clocks and RNG freely):
  - methods of classes deriving from `Program` or `Extension` (by base
    name), including functions nested in them;
  - callables passed as `invariant=` / `halt_when=` (lambdas, named
    module functions, and the closures returned by factories called in
    those positions — `invariant=raft_invariant(5, 32)` marks
    `raft_invariant`'s inner def);
  - nested defs of any module function whose name contains
    "invariant" (the factory idiom every flagship model uses, reachable
    even when the construction site lives in another file).

The rule table (each finding carries its rule id):

  host-time        wall-clock reads (`time.time`, `datetime.now`, ...)
  host-random      host RNG (`random.*`, `np.random.*`, `os.urandom`,
                   `uuid.uuid1/4`, `secrets.*`) — draw from `ctx.rand*`
                   / the engine key stream instead
  unordered-iter   iterating a set/frozenset/`vars()`/`__dict__` —
                   Python sets iterate in hash order, which PYTHONHASHSEED
                   re-randomizes per interpreter; trace once and the
                   baked emission ORDER differs between processes
  host-callback    `jax.pure_callback` / `io_callback` / `debug.callback`
                   inside a traced body — host code running mid-step is
                   outside the replay domain entirely
  mutable-capture  a closure cell / default / Program attribute holding
                   a list/dict/set/bytearray: the signature freezes its
                   VALUE at construction, so mutating it later changes
                   the traced program invisibly (DESIGN §10 freezes
                   semantics at construction; this flags the footgun)
  sig-degrade      a capture `compile/signature.py` can only freeze to a
                   per-object identity token — the step-program cache
                   silently falls back to per-instance entries (no
                   cross-Runtime sharing) and warm-cache repros stop
                   matching; the finding names the offending cell

Suppression: append `# detsan: ok(<rule>)` (or `ok(*)`) to the flagged
line, or put it alone on the line directly above. Suppressed findings
stay in the report (marked) but do not fail the gate.

Entry points: `lint_source` (one blob), `lint_paths` (the repo gate —
`python -m madsim_tpu.analyze`), `lint_callable` / `lint_program` /
`lint_runtime` (live objects: AST of their source PLUS the closure
inspection only runtime has — `Runtime(..., lint=True)` runs the last
one at construction).
"""

from __future__ import annotations

import ast
import dataclasses
import functools
import inspect
import os
import re
import textwrap
from typing import Any, Callable, Iterable

# ---------------------------------------------------------------------------
# rule table
# ---------------------------------------------------------------------------

RULES = {
    "host-time": "wall-clock read in a traced body (baked in at trace time)",
    "host-random": "host RNG in a traced body (use ctx.rand*/engine keys)",
    "unordered-iter": "iteration over a set/vars()/__dict__ (hash order "
                      "varies per interpreter)",
    "host-callback": "host callback compiled into a traced body",
    "mutable-capture": "mutable container captured by a traced callable "
                       "(frozen by value at construction; later mutation "
                       "is invisible)",
    "sig-degrade": "capture freezes to an identity token — compile cache "
                   "degrades to per-instance (no cross-Runtime sharing)",
    "parse-error": "file could not be parsed",
}

_TIME_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "time.monotonic_ns", "time.perf_counter_ns", "time.process_time",
    "time.sleep", "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}
_RANDOM_CALLS = {"os.urandom", "os.getrandom", "uuid.uuid1", "uuid.uuid4"}
_RANDOM_PREFIXES = ("random.", "numpy.random.", "secrets.")
_CALLBACK_CALLS = {
    "jax.pure_callback", "jax.experimental.io_callback",
    "jax.debug.callback", "jax.experimental.host_callback.call",
}
_UNORDERED_BUILTINS = {"set", "frozenset", "vars"}
_MUTABLE_TYPES = (list, dict, set, bytearray)

_SUPPRESS_RE = re.compile(r"#\s*detsan:\s*ok\(\s*([a-z*\-]+)\s*\)")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    where: str          # qualname-ish label of the traced scope
    message: str
    suppressed: bool = False

    def format(self) -> str:
        mark = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}: [{self.rule}] {self.where}: "
                f"{self.message}{mark}")


class DeterminismLintError(AssertionError):
    """Raised by `Runtime(..., lint=True)` on active (unsuppressed)
    findings."""

    def __init__(self, findings: list[Finding]):
        self.findings = findings
        lines = "\n  ".join(f.format() for f in findings)
        super().__init__(
            f"determinism lint: {len(findings)} active finding(s)\n  "
            f"{lines}\n(suppress intentional ones with "
            f"`# detsan: ok(<rule>)` on the flagged line)")


def active(findings: Iterable[Finding]) -> list[Finding]:
    """The findings that fail the gate (suppressions filtered out)."""
    return [f for f in findings if not f.suppressed and f.rule in RULES]


# ---------------------------------------------------------------------------
# dotted-name resolution through the module's import aliases
# ---------------------------------------------------------------------------


def _import_aliases(tree: ast.Module) -> dict[str, str]:
    """alias -> dotted path, from the module's import statements (walked
    everywhere: function-local imports are common in this repo)."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            # relative imports keep their dots ("from . import raft as R"
            # -> ".raft"): the traced-scope heuristics use the prefix to
            # recognize in-package model imports
            prefix = "." * node.level + (node.module or "")
            for a in node.names:
                dotted = f"{prefix}.{a.name}" if prefix else a.name
                aliases[a.asname or a.name] = dotted
    return aliases


def _dotted(expr: ast.AST, aliases: dict[str, str]) -> str | None:
    """`np.random.default_rng` -> "numpy.random.default_rng" (root name
    rewritten through the alias table); None when the chain does not
    bottom out in a plain name."""
    parts: list[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(aliases.get(node.id, node.id))
    return ".".join(reversed(parts))


# ---------------------------------------------------------------------------
# traced-scope discovery
# ---------------------------------------------------------------------------

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _base_label(b: ast.AST) -> str:
    if isinstance(b, ast.Name):
        return b.id
    if isinstance(b, ast.Attribute):
        return b.attr
    return ""


def _nested_funcs(fn: ast.AST):
    for n in ast.walk(fn):
        if isinstance(n, _FUNC_NODES + (ast.Lambda,)) and n is not fn:
            yield n


def _traced_roots(tree: ast.Module,
                  path: str = "<string>") -> list[tuple[ast.AST, str]]:
    """(node, label) pairs for every scope the rules apply to."""
    roots: list[tuple[ast.AST, str]] = []
    seen: set[int] = set()
    aliases = _import_aliases(tree)

    def add(node, label):
        if id(node) not in seen:
            seen.add(id(node))
            roots.append((node, label))

    # program-ish classes: direct Program/Extension bases, transitive
    # in-module subclasses, and cross-module model inheritance
    # (`class CfgRaft(R.Raft)` — the base resolves into a models module,
    # or into a relative sibling of a file that itself lives in models/;
    # `Runtime(..., lint=True)` resolves the real MRO, this is the best
    # a single-file static pass can do)
    in_models = f"{os.sep}models{os.sep}" in path

    def programish(b: ast.AST, prog_classes: set[str]) -> bool:
        lbl = _base_label(b)
        if not lbl:
            return False
        if lbl in prog_classes or lbl.endswith(("Program", "Extension")):
            return True
        if isinstance(b, ast.Attribute):
            root = _dotted(b.value, aliases) or ""
        else:
            root = aliases.get(lbl, "")
        return ".models." in root or "models." in root \
            or (in_models and root.startswith("."))

    classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
    prog_classes: set[str] = set()
    changed = True
    while changed:
        changed = False
        for c in classes:
            if c.name not in prog_classes and \
                    any(programish(b, prog_classes) for b in c.bases):
                prog_classes.add(c.name)
                changed = True
    for node in classes:
        if node.name in prog_classes:
            for n in node.body:
                if isinstance(n, _FUNC_NODES):
                    add(n, f"{node.name}.{n.name}")

    mod_defs = {n.name: n for n in tree.body if isinstance(n, _FUNC_NODES)}

    def mark_value(v: ast.AST, slot: str):
        if isinstance(v, ast.Lambda):
            add(v, f"<lambda {slot}>")
        elif isinstance(v, ast.Name) and v.id in mod_defs:
            add(mod_defs[v.id], v.id)
        elif isinstance(v, ast.Call):
            f = v.func
            if isinstance(f, ast.Name) and f.id in mod_defs:
                for n in _nested_funcs(mod_defs[f.id]):
                    add(n, f"{f.id}.{getattr(n, 'name', '<lambda>')}")
        elif isinstance(v, ast.BoolOp):
            for x in v.values:
                mark_value(x, slot)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg in ("invariant", "halt_when"):
                    mark_value(kw.value, kw.arg)

    # the factory idiom, reachable from other files: raft_kv constructs
    # with `R.raft_invariant(...)` — raft.py itself must still lint the
    # closure, so any module function named like an invariant factory
    # has its nested defs treated as traced
    for name, fn in mod_defs.items():
        if "invariant" in name:
            for n in _nested_funcs(fn):
                add(n, f"{name}.{getattr(n, 'name', '<lambda>')}")
    return roots


# ---------------------------------------------------------------------------
# the AST rules
# ---------------------------------------------------------------------------


def _is_unordered_iterable(expr: ast.AST, aliases: dict[str, str]) -> bool:
    if isinstance(expr, ast.Set):
        return True
    if isinstance(expr, ast.Attribute) and expr.attr == "__dict__":
        return True
    if isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Name) and f.id in _UNORDERED_BUILTINS:
            return True
        # .keys()/.values()/.items() over one of the above
        if isinstance(f, ast.Attribute) and f.attr in ("keys", "values",
                                                       "items"):
            return _is_unordered_iterable(f.value, aliases)
    return False


def _check_call(dotted: str | None) -> tuple[str, str] | None:
    if dotted is None:
        return None
    if dotted in _TIME_CALLS:
        return "host-time", f"`{dotted}()` reads the host clock"
    if dotted in _RANDOM_CALLS or dotted.startswith(_RANDOM_PREFIXES):
        return "host-random", f"`{dotted}()` draws host randomness"
    if dotted in _CALLBACK_CALLS:
        return "host-callback", f"`{dotted}` runs host code mid-step"
    return None


def _scan_scope(root: ast.AST, label: str, aliases: dict[str, str],
                path: str, out: list[Finding], line_off: int = 0) -> None:
    for node in ast.walk(root):
        if isinstance(node, ast.Call):
            hit = _check_call(_dotted(node.func, aliases))
            if hit:
                out.append(Finding(hit[0], path, node.lineno + line_off,
                                   label, hit[1]))
        iters: list[ast.AST] = []
        if isinstance(node, (ast.For, ast.AsyncFor)):
            iters.append(node.iter)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            iters.extend(g.iter for g in node.generators)
        for it in iters:
            if _is_unordered_iterable(it, aliases):
                out.append(Finding(
                    "unordered-iter", path, it.lineno + line_off, label,
                    "iteration order is hash order — sort it (or iterate "
                    "a tuple/dict, which keep insertion order)"))


def _apply_suppressions(findings: list[Finding],
                        src_lines: list[str], line_off: int = 0) -> None:
    """Mark findings covered by a `# detsan: ok(rule)` on the flagged
    line or alone on the line above (lines are 1-based file lines;
    `line_off` maps them back into `src_lines`)."""

    def rules_at(i: int) -> set[str]:
        if 0 <= i < len(src_lines):
            return set(_SUPPRESS_RE.findall(src_lines[i]))
        return set()

    for f in findings:
        i = f.line - line_off - 1
        ok = rules_at(i) | rules_at(i - 1)
        if f.rule in ok or "*" in ok:
            f.suppressed = True


# ---------------------------------------------------------------------------
# entry points — source side
# ---------------------------------------------------------------------------


def lint_source(src: str, path: str = "<string>") -> list[Finding]:
    """Lint one source blob: find its traced scopes, apply the AST rules,
    honor suppressions. Returns ALL findings (suppressed ones marked);
    `active()` filters to the gate-failing subset."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("parse-error", path, e.lineno or 0, "<module>",
                        str(e.msg))]
    aliases = _import_aliases(tree)
    findings: list[Finding] = []
    for root, label in _traced_roots(tree, path):
        _scan_scope(root, label, aliases, path, findings)
    # one scope can be reached twice (class rule + kwarg rule): dedupe
    uniq: dict[tuple, Finding] = {}
    for f in findings:
        uniq.setdefault((f.rule, f.path, f.line, f.message), f)
    findings = sorted(uniq.values(), key=lambda f: (f.path, f.line, f.rule))
    _apply_suppressions(findings, src.splitlines())
    return findings


def lint_paths(paths: Iterable[str]) -> list[Finding]:
    """The repo gate: lint every .py under `paths` (files or dirs)."""
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _, names in os.walk(p):
                files.extend(os.path.join(dirpath, n)
                             for n in sorted(names) if n.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    findings: list[Finding] = []
    for f in sorted(set(files)):
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
        except OSError as e:
            findings.append(Finding("parse-error", f, 0, "<file>", str(e)))
            continue
        findings.extend(lint_source(src, f))
    return findings


# ---------------------------------------------------------------------------
# entry points — live-object side (closure inspection needs runtime)
# ---------------------------------------------------------------------------


def _contains_unique(frozen: Any) -> bool:
    from ..compile.signature import contains_identity_token
    return contains_identity_token(frozen)


@functools.lru_cache(maxsize=256)
def _module_aliases(mod_file: str | None) -> dict[str, str]:
    """The import-alias table of a module FILE, cached: lint_runtime
    lints every handler of every program, most defined in one module —
    re-parsing it per callable would be pure repeated work."""
    if not mod_file:
        return {}
    try:
        with open(mod_file, encoding="utf-8") as f:
            return _import_aliases(ast.parse(f.read()))
    except (OSError, SyntaxError, ValueError):
        return {}


def _callable_src(fn) -> tuple[str | None, str, int]:
    """(dedented source, file path, first line - 1) — best effort; live
    callables without retrievable source (REPL lambdas) skip the AST
    half and keep the closure checks."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        path = inspect.getsourcefile(fn) or "<live>"
        code = getattr(fn, "__code__", None)
        line0 = (code.co_firstlineno if code is not None
                 else inspect.getsourcelines(fn)[1]) - 1
        ast.parse(src)              # a lambda's clipped line may not parse
        return src, path, line0
    except (OSError, TypeError, SyntaxError):
        return None, "<live>", 0


def lint_callable(fn: Callable, name: str | None = None) -> list[Finding]:
    """Lint one live traced callable: the AST rules over its source (the
    WHOLE body is a traced scope here — the caller vouched that `fn` is
    traced) plus the closure checks source alone cannot do."""
    if isinstance(fn, property):
        return []
    raw = fn.__func__ if inspect.ismethod(fn) else fn
    label = name or getattr(raw, "__qualname__", repr(fn))
    findings: list[Finding] = []
    src, path, line0 = _callable_src(raw)
    src_lines: list[str] = []
    def_line = getattr(getattr(raw, "__code__", None), "co_firstlineno", 0)
    if src is not None:
        tree = ast.parse(src)
        aliases = _import_aliases(tree)
        # module-level imports are invisible from the clipped source;
        # resolve the function's own global names through its module
        mod = inspect.getmodule(raw)
        if mod is not None:
            aliases = {**_module_aliases(getattr(mod, "__file__", None)),
                       **aliases}
        for node in tree.body:
            _scan_scope(node, label, aliases, path, findings,
                        line_off=line0)
        src_lines = src.splitlines()
    code = getattr(raw, "__code__", None)
    closure = getattr(raw, "__closure__", None) or ()
    names = code.co_freevars if code is not None else ()
    from ..compile.signature import freeze
    for cname, cell in zip(names, closure):
        try:
            val = cell.cell_contents
        except ValueError:          # empty cell
            continue
        if isinstance(val, _MUTABLE_TYPES):
            findings.append(Finding(
                "mutable-capture", path, def_line, label,
                f"closure cell `{cname}` holds a "
                f"{type(val).__name__} — its value is frozen into the "
                f"compile signature at construction; mutate it and the "
                f"traced program silently diverges"))
        if _contains_unique(freeze(val)):
            findings.append(Finding(
                "sig-degrade", path, def_line, label,
                f"closure cell `{cname}` "
                f"({type(val).__name__}) freezes to an identity token — "
                f"this callable opts its Runtime out of cross-instance "
                f"program sharing (compile/signature.py)"))
    for dflt in (getattr(raw, "__defaults__", None) or ()):
        if isinstance(dflt, _MUTABLE_TYPES):
            findings.append(Finding(
                "mutable-capture", path, def_line, label,
                f"mutable default ({type(dflt).__name__}) on a traced "
                f"callable — frozen by value at construction"))
    _apply_suppressions(findings, src_lines, line_off=line0)
    return findings


def lint_program(prog, name: str | None = None) -> list[Finding]:
    """Lint one Program (or Extension) instance: its handler methods via
    `lint_callable`, plus its instance attributes (they are captured
    parameters — the signature freezes them by value)."""
    label = name or type(prog).__name__
    findings: list[Finding] = []
    for m in ("init", "on_message", "on_timer", "on_op", "on_event",
              "reset_node"):
        fn = getattr(prog, m, None)
        base = getattr(type(prog).__mro__[-2], m, None)  # Program/Extension
        if fn is None or getattr(fn, "__func__", fn) is base:
            continue                # inherited no-op: nothing to lint
        findings.extend(lint_callable(fn, name=f"{label}.{m}"))
    from ..compile.signature import freeze
    src, path, line0 = _callable_src(type(prog))
    def_line = line0 + 1 if src else 0
    attr_findings: list[Finding] = []
    for aname, val in sorted(vars(prog).items()):
        if aname.startswith("_madsim"):
            continue
        if isinstance(val, _MUTABLE_TYPES):
            attr_findings.append(Finding(
                "mutable-capture", path, def_line, label,
                f"attribute `{aname}` holds a {type(val).__name__} — "
                f"frozen by value into the compile signature at "
                f"construction"))
        if _contains_unique(freeze(val)):
            attr_findings.append(Finding(
                "sig-degrade", path, def_line, label,
                f"attribute `{aname}` ({type(val).__name__}) freezes to "
                f"an identity token — no cross-Runtime program sharing"))
    if src is not None:
        # suppressions against THIS class's source apply only to the
        # attribute findings minted above — handler findings already
        # carry their own source's suppressions (lint_callable), and a
        # handler inherited from another FILE would misindex here
        _apply_suppressions(attr_findings, src.splitlines(),
                            line_off=line0)
    return findings + attr_findings


def lint_runtime(rt) -> list[Finding]:
    """Everything a Runtime construction bakes into its trace: programs,
    invariant, halt_when, extensions. `Runtime(..., lint=True)` raises
    `DeterminismLintError` when `active()` of this is non-empty."""
    findings: list[Finding] = []
    for i, prog in enumerate(rt.programs):
        findings.extend(lint_program(
            prog, name=f"programs[{i}]:{type(prog).__name__}"))
    if rt.invariant is not None:
        findings.extend(lint_callable(rt.invariant, name="invariant"))
    halt = getattr(rt, "_halt_when", None)
    if halt is not None:
        findings.extend(lint_callable(halt, name="halt_when"))
    for e in rt.extensions:
        findings.extend(lint_program(e, name=f"extension:{e.name}"))
    return findings
