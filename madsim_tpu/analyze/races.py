"""Schedule-race detection over the happens-before rings, with
forced-commute confirmation.

The r10 lineage layer records, for every dispatched event, the dispatch
that ENQUEUED it (`parent`) — the happens-before edges of the
trajectory. The scheduler's only free decision (core/step.py) is the
tie-break among earliest-deadline events, so a *schedule race* has a
precise shape here: two dispatches at the SAME node, at the SAME
virtual instant, neither an HB-ancestor of the other — their order was
tie-break luck, and both mutate the node's state. That is the batched
analog of a FastTrack vector-clock race: same location, conflicting
accesses, unordered by happens-before.

Classic race detectors stop at "unordered + conflicting" and drown in
benign reports. Here suspicion is CONFIRMED by construction: the r9
PCT priority nudge (`SimState.prio_nudge`) replaces the uniform
tie-break with a deterministic policy without recompiling, so the
commuted order is just another lane — `confirm_race` replays the
(seed, knobs) handle under a batch of nudge policies, finds lanes
where the pair actually dispatched in the flipped order, and diffs
final-state fingerprints and crash verdicts against the observed
order. Only a pair whose commutation CHANGES the outcome is reported;
a pair that commutes to a bit-identical state is recorded as benign.
A confirmed race carries a complete, deterministic repro — the
(seed, knobs, nudge) triple — and buckets like a crash
(service/buckets.py, `obs.causal.race_fingerprint`).

Everything here is host-side numpy over ring reads plus ordinary
batched replays; nothing touches the step program.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np

from ..obs.causal import race_fingerprint
from ..obs.rings import ring_records, sampled_lanes

_TOKEN_KEYS = ("kind", "node", "src", "tag")


def _tokens(recs: dict) -> list[tuple[int, ...]]:
    cols = [np.asarray(recs[k]) for k in _TOKEN_KEYS]
    return [tuple(int(c[i]) for c in cols)
            for i in range(len(cols[0]))]


def find_races(state, lane: int = 0, max_pairs: int = 16,
               window: int = 4) -> list[dict]:
    """Candidate race pairs from one lane's ring: dispatches (a, b) at
    the same node and the same virtual `now`, with a NOT an HB-ancestor
    of b (walking `parent` edges), within `window` dispatches of each
    other at that node. Pairs whose two events carry identical
    (kind, node, src, tag) tokens are skipped — commuting them is
    unobservable, so they can never confirm.

    Returns candidate dicts {lane, node, now, a, b} with `a`/`b` the
    full ring records (a dispatched first). Raises (via ring_records)
    when the ring is compiled out or the lane unsampled; pre-r10 states
    without lineage columns raise ValueError.
    """
    recs = ring_records(state, lane)
    if "parent" not in recs:
        raise ValueError("no lineage columns: state predates r10 or was "
                         "built without cfg.trace_cap > 0")
    steps = np.asarray(recs["step"])
    nows = np.asarray(recs["now"])
    parents = np.asarray(recs["parent"])
    n = len(steps)
    by_step = {int(s): i for i, s in enumerate(steps)}
    toks = _tokens(recs)

    def ancestors(i: int) -> set[int]:
        out: set[int] = set()
        j = i
        while True:
            p = int(parents[j])
            if p < 0 or p not in by_step:
                return out          # external root or wrap-truncated
            j = by_step[p]
            out.add(j)

    by_node: dict[int, list[int]] = defaultdict(list)
    for i in range(n):
        by_node[int(recs["node"][i])].append(i)

    out: list[dict] = []
    for node, idxs in sorted(by_node.items()):
        for k in range(len(idxs) - 1):
            a = idxs[k]
            anc_cache: dict[int, set[int]] = {}
            for m in range(k + 1, min(k + 1 + window, len(idxs))):
                b = idxs[m]
                if int(nows[a]) != int(nows[b]):
                    break           # nows are monotonic: no later tie
                if toks[a] == toks[b]:
                    continue        # commuting identical tokens is moot
                anc = anc_cache.get(b)
                if anc is None:
                    anc = anc_cache[b] = ancestors(b)
                if a in anc:
                    continue        # causally ordered: not a race
                rec = {key: {f: int(recs[f][i]) for f in
                             ("step", "now", "kind", "node", "src", "tag",
                              "parent", "lamport")}
                       for key, i in (("a", a), ("b", b))}
                out.append(dict(lane=int(lane), node=int(node),
                                now=int(nows[a]), **rec))
                if len(out) >= max_pairs:
                    return out
    return out


def _pair_order(recs: dict, cand: dict) -> bool | None:
    """Did this lane dispatch the candidate's `a` token before its `b`
    token at a shared instant? True = observed order, False = commuted,
    None = the pair never co-occurred at one instant in this lane's
    surviving window. Uses the FIRST co-occurrence: nows are monotonic,
    so records sharing an instant are contiguous in ring order."""
    toks = _tokens(recs)
    nows = np.asarray(recs["now"])
    ta = tuple(cand["a"][k] for k in _TOKEN_KEYS)
    tb = tuple(cand["b"][k] for k in _TOKEN_KEYS)
    i = 0
    n = len(toks)
    while i < n:
        j = i
        while j < n and nows[j] == nows[i]:
            j += 1
        grp = toks[i:j]
        if ta in grp and tb in grp:
            return grp.index(ta) < grp.index(tb)
        i = j
    return None


def confirm_race(rt, seed: int, cand: dict, *, knobs: dict | None = None,
                 plan=None, nudges=None, max_steps: int = 20_000,
                 chunk: int = 512, base_nudge: int | None = None,
                 full_chain: bool = False) -> dict:
    """Force the commuted order of one candidate pair and diff outcomes.

    Replays `seed` (with `knobs` applied when the candidate came from a
    fuzz mutant — a mutated lane is not reproducible from its seed
    alone) as one batch: lane 0 under `base_nudge` (the observed
    tie-break policy) and one lane per candidate nudge policy. For each
    nudged lane whose ring shows the pair in the FLIPPED order, the
    final-state fingerprint and crash verdict are diffed against lane
    0. The verdicts:

      confirmed   some commuting lane's outcome differs AND the
                  divergence survives replay verification — both the
                  baseline and the commuted handle replay as single
                  lanes until self-consistent (`replay_race`), so
                  `diff` carries the VERIFIED values the
                  (seed, knobs, nudge) handle actually reproduces
      benign      at least one lane commuted the pair and EVERY one of
                  them finished bit-identical to the baseline — the
                  operations commute
      inconclusive  no nudge in the sweep flipped the pair (or the pair
                  left the ring window); widen `nudges`

    full_chain (r20): when the CONFIRMED commuted outcome is a crash,
    re-run the (seed, knobs, nudge) handle through
    `obs.timetravel.full_chain_replay` (ring upgraded to hold the
    whole trajectory) and attach `chain`/`chain_complete` to the
    result — the same hook `replay_bucket` grew, so a race bucket can
    carry the complete causal chain of the outcome the race flips the
    run into (`scan_races` threads it into the bucket record).

    Returns {status, nudge, repro, baseline, diff, commuted, swept
    [, chain, chain_complete]}.
    """
    if base_nudge is None:
        # the baseline must replay the OBSERVED schedule: a fuzz mutant
        # may carry its own tie-break policy in the knob vector, and
        # diffing commuted lanes against any other policy would compare
        # against a run the candidate never came from
        base_nudge = (int(np.asarray(knobs["prio_nudge"]))
                      if knobs is not None else 0)
    if nudges is None:
        nudges = np.arange(1, 25, dtype=np.int32)
    nudges = np.asarray(nudges, np.int32).reshape(-1)
    nudges = nudges[nudges != base_nudge]   # a baseline clone confirms nothing
    all_n = np.concatenate([np.asarray([base_nudge], np.int32), nudges])
    B = all_n.shape[0]
    state = rt.init_batch(np.full(B, seed, np.uint32))
    if knobs is not None:
        from ..search.mutate import apply_repro_knobs
        state, plan = apply_repro_knobs(rt, state, knobs, plan)
    from ..search.pct import with_prio_nudge
    state = with_prio_nudge(state, all_n)
    state = rt.run_fused(state, max_steps, chunk)
    fps = rt.fingerprints(state)
    crashed = np.asarray(state.crashed)
    codes = np.asarray(state.crash_code)
    cnodes = np.asarray(state.crash_node)

    def verdict(i):
        return dict(crashed=bool(crashed[i]), crash_code=int(codes[i]),
                    crash_node=int(cnodes[i]))

    base = dict(order_observed=_pair_order(ring_records(state, 0), cand),
                fingerprint=int(fps[0]), **verdict(0))
    commuted: list[int] = []
    hits: list[int] = []
    for i in range(1, B):
        if _pair_order(ring_records(state, i), cand) is False:
            commuted.append(int(all_n[i]))
            if int(fps[i]) != int(fps[0]):
                hits.append(i)
    out = dict(baseline=base, commuted=commuted,
               swept=[int(x) for x in all_n[1:]],
               candidate=cand)
    # "confirmed" is a REPLAY claim, so verify it by replaying: the
    # sweep batch is a screen, and a screen lane can be wrong — this
    # jaxlib's first invocation of a fused executable deserialized from
    # the persistent compile cache can return a corrupted result under
    # concurrent load (reproduced with stock runners, never surviving a
    # second invocation; ROADMAP r12). replay_race runs each handle
    # until two consecutive invocations agree, so the diff reported
    # here is the one the repro handle actually reproduces.
    base_rep = None
    for i in hits:
        nudge = int(all_n[i])
        if base_rep is None:
            base_rep = replay_race(
                rt, dict(seed=int(seed), knobs=knobs, nudge=base_nudge),
                plan=plan, max_steps=max_steps, chunk=chunk)
        repro = dict(seed=int(seed), knobs=knobs, nudge=nudge)
        hit_rep = replay_race(rt, repro, plan=plan, max_steps=max_steps,
                              chunk=chunk)
        if hit_rep["fingerprint"] == base_rep["fingerprint"]:
            continue            # the screen lane was the corrupted one
        out.update(
            status="confirmed", nudge=nudge, repro=repro,
            diff=dict(fingerprint=(base_rep["fingerprint"],
                                   hit_rep["fingerprint"]),
                      baseline={k: base_rep[k] for k in
                                ("crashed", "crash_code", "crash_node")},
                      commuted={k: hit_rep[k] for k in
                                ("crashed", "crash_code", "crash_node")}))
        if full_chain and hit_rep["crashed"]:
            from ..obs.timetravel import full_chain_replay
            rep = full_chain_replay(
                rt, seed=int(seed), knobs=knobs, nudge=nudge,
                expect={k: hit_rep[k] for k in
                        ("crashed", "crash_code", "crash_node",
                         "fingerprint")},
                max_steps=max_steps, chunk=chunk)
            out["chain"] = rep["explain"]["chain"]
            out["chain_complete"] = not rep["explain"]["truncated"]
        return out
    if commuted:
        out.update(status="benign", nudge=None, repro=None, diff=None)
    else:
        out.update(status="inconclusive", nudge=None, repro=None, diff=None)
    return out


def replay_race(rt, repro: dict, *, plan=None, max_steps: int = 20_000,
                chunk: int = 512, verify: bool = True) -> dict:
    """Replay a confirmed race's (seed, knobs, nudge) handle alone —
    one lane — and return {fingerprint, crashed, crash_code,
    crash_node}. Determinism (seed i in any batch == seed i alone,
    DESIGN §4) makes this match the confirming lane bit-for-bit; the
    tests hold that.

    verify=True (default) runs the lane until two CONSECUTIVE
    invocations agree and returns that fixed point. The engine is
    deterministic, but this jaxlib's first invocation of a fused
    executable deserialized from the persistent compile cache can
    return a corrupted result under concurrent machine load (found by
    this very check during r12 — the corruption is transient and never
    survives a re-invocation; minimal repro in the ROADMAP r12 note).
    An authoritative repro value must not depend on that coin flip.
    Raises RuntimeError if three invocations yield three values — that
    is real nondeterminism, not the known transient."""
    from ..search.pct import with_prio_nudge

    def once():
        nonlocal plan
        state = rt.init_batch(np.asarray([repro["seed"]], np.uint32))
        knobs = repro.get("knobs")
        if knobs is not None:
            from ..search.mutate import apply_repro_knobs
            state, plan = apply_repro_knobs(rt, state, knobs, plan)
        state = with_prio_nudge(state,
                                np.asarray([repro["nudge"]], np.int32))
        state = rt.run_fused(state, max_steps, chunk)
        return dict(fingerprint=int(rt.fingerprints(state)[0]),
                    crashed=bool(np.asarray(state.crashed)[0]),
                    crash_code=int(np.asarray(state.crash_code)[0]),
                    crash_node=int(np.asarray(state.crash_node)[0]))

    out = once()
    if not verify:
        return out
    from ..utils.verify import agree_twice
    return agree_twice(
        out, lambda _: once(), what="race repro",
        detail=lambda a, b, c: (f"fingerprints {a['fingerprint']}, "
                                f"{b['fingerprint']}, {c['fingerprint']}"))


def _dedupe_key(cand: dict) -> tuple:
    ta = tuple(cand["a"][k] for k in _TOKEN_KEYS)
    tb = tuple(cand["b"][k] for k in _TOKEN_KEYS)
    return (cand["node"],) + tuple(sorted((ta, tb)))


def scan_races(rt, seeds, max_steps: int = 20_000, chunk: int = 512,
               *, knobs: dict | None = None, plan=None, lanes=None,
               max_lanes: int = 4, max_confirm: int = 8, nudges=None,
               buckets=None, worker_id: int = 0,
               full_chain: bool = False) -> dict:
    """The batteries-included pass: run a seed batch with the ring on,
    harvest candidate pairs from (by default) the crashed lanes — a
    crash is where an order bug is worth the confirm budget — dedupe
    them by token pair, and `confirm_race` each against its own lane's
    (seed, knobs) handle.

    `buckets` (a service.buckets.CrashBuckets) turns confirmed races
    into first-class findings: each is observed under its
    `race_fingerprint`, so two lanes/workers hitting one pair share one
    bucket, with the (seed, knobs, nudge) repro as the bucket's handle.

    Returns {candidates, confirmed, benign, inconclusive, lanes,
    bucket_keys}. Requires cfg.trace_cap > 0.
    """
    if rt.cfg.trace_cap == 0:
        raise ValueError("scan_races needs the flight-recorder ring: "
                         "build the runtime with SimConfig(trace_cap > 0)")
    seeds = np.asarray(seeds, np.uint32).reshape(-1)
    state = rt.init_batch(seeds)
    if knobs is not None:
        from ..search.mutate import apply_repro_knobs
        state, plan = apply_repro_knobs(rt, state, knobs, plan)
    state = rt.run_fused(state, max_steps, chunk)
    if lanes is None:
        crashed = np.asarray(state.crashed)
        lanes = np.nonzero(crashed)[0][:max_lanes]
        if len(lanes) == 0:
            lanes = sampled_lanes(state)[:max_lanes]
    by_key: dict[tuple, dict] = {}
    lane_list = [int(x) for x in lanes]
    for lane in lane_list:
        for cand in find_races(state, lane):
            by_key.setdefault(_dedupe_key(cand), cand)
    results = dict(candidates=len(by_key), confirmed=[], benign=0,
                   inconclusive=0, lanes=lane_list, bucket_keys=[])
    for cand in list(by_key.values())[:max_confirm]:
        seed = int(seeds[cand["lane"]])
        conf = confirm_race(rt, seed, cand, knobs=knobs, plan=plan,
                            nudges=nudges, max_steps=max_steps, chunk=chunk,
                            full_chain=full_chain)
        if conf["status"] == "confirmed":
            results["confirmed"].append(conf)
            if buckets is not None:
                # with full_chain the bucket carries the complete chain
                # of the commuted OUTCOME (what the race flips the run
                # into), not just the racing pair
                key, _ = buckets.observe(
                    race_fingerprint(cand, conf["diff"]),
                    seed=seed, knobs=knobs, round_no=0,
                    worker_id=worker_id, nudge=conf["nudge"],
                    chain=conf.get("chain") or [cand["a"], cand["b"]],
                    chain_truncated=(None if "chain_complete" not in conf
                                     else not conf["chain_complete"]))
                results["bucket_keys"].append(key)
        elif conf["status"] == "benign":
            results["benign"] += 1
        else:
            results["inconclusive"] += 1
    return results
