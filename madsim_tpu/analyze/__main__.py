"""The repo determinism-lint gate: `python -m madsim_tpu.analyze [paths]`.

With no arguments, lints the installed `madsim_tpu` package tree and an
`examples/` directory next to it (i.e. the repo layout) — the whole
surface where traced callables live. Exit status 0 = clean (suppressed
findings are reported but do not fail); 1 = active findings; 2 = usage.

  python -m madsim_tpu.analyze               # repo gate
  python -m madsim_tpu.analyze models/x.py   # one file
  python -m madsim_tpu.analyze -q dir/       # counts only
"""

from __future__ import annotations

import os
import sys

from .lint import RULES, active, lint_paths


def _default_paths() -> list[str]:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo = os.path.dirname(pkg)
    paths = [pkg]
    examples = os.path.join(repo, "examples")
    if os.path.isdir(examples):
        paths.append(examples)
    return paths


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    quiet = "-q" in argv
    argv = [a for a in argv if a != "-q"]
    if any(a.startswith("-") for a in argv):
        print(__doc__, file=sys.stderr)
        return 2
    paths = argv or _default_paths()
    findings = lint_paths(paths)
    bad = active(findings)
    if not quiet:
        for f in findings:
            print(f.format())
    n_sup = sum(1 for f in findings if f.suppressed)
    print(f"detsan lint: {len(bad)} active finding(s), {n_sup} suppressed "
          f"({len(RULES) - 1} rules over {', '.join(paths)})")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
