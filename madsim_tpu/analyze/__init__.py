"""Static + dynamic analysis over the determinism discipline (DESIGN §14).

madsim's one guarantee — one seed reproduces one execution — holds only
while user code stays inside the discipline DESIGN §4 spells out: traced
bodies draw randomness from the engine's key stream, capture only values
the signature can freeze, and never reach for host state. Nothing
enforced that until r12; this package does, at three depths:

  lint.py    STATIC: AST + closure inspection over the traced callables
             (Program handlers, invariant/halt_when closures, Extension
             hooks) — host clocks, host RNG, unordered-set iteration,
             host callbacks, mutable captures, and captures the compile
             signature can only freeze to identity tokens. Run it as
             `python -m madsim_tpu.analyze [paths...]` (the repo gate)
             or at construction with `Runtime(..., lint=True)`.
  races.py   DYNAMIC, POST-HOC: walk the r10 happens-before rings for
             unordered same-instant dispatch pairs at one node, then
             CONFIRM each candidate by forcing the commuted tie-break
             order with the r9 PCT nudge in fresh lanes and diffing
             fingerprints — confirmed races carry a (seed, knobs, nudge)
             repro and bucket like crashes (service/buckets.py).
  harness/simtest.py detsan=True   DYNAMIC, ONLINE: every seed batch
             runs twice under permuted lane placement and is diffed
             leaf-for-leaf — the net for whatever the static pass
             cannot see.
"""

from .lint import (DeterminismLintError, Finding, lint_callable,
                   lint_paths, lint_runtime, lint_source)
from .races import confirm_race, find_races, scan_races

__all__ = [
    "Finding", "DeterminismLintError", "lint_source", "lint_callable",
    "lint_runtime", "lint_paths",
    "find_races", "confirm_race", "scan_races",
]
