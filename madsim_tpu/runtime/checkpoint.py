"""Checkpoint / resume: snapshot whole seed batches.

The reference has NO checkpointing — reproducibility is replay-by-seed only
(SURVEY.md §5). Here the entire cluster state of every trajectory is one
pytree of device arrays, so a checkpoint is a device-to-host copy: save a
100k-seed fuzz mid-flight, resume it later (or elsewhere), or stash the
exact pre-crash batch for postmortem. This is strictly beyond reference
parity, enabled by the state-as-tensor design.

Two checkpoint shapes exist (MIGRATION r20):

  * this module's BATCH snapshot — the whole [B]-lane pytree, headerless
    npz, loaded back against a `like` state from the SAME runtime;
  * the LANE checkpoint (core/state.checkpoint_lane / LaneCheckpoint,
    re-exported here) — one lane's state with a VERSIONED header
    (format marker + structural signature), the unit time-travel
    replay and prefix-fork build on: `seed_batch_from` re-seeds it
    into a fresh batch, including one with MORE observability compiled
    in (DESIGN §21). `LaneCheckpoint.load` rejects this module's
    headerless batch files cleanly — the formats never alias.
"""

from __future__ import annotations

import jax
import numpy as np

from ..core.state import (CheckpointMismatch, LaneCheckpoint,  # noqa: F401
                          SimState, checkpoint_lane, seed_batch_from)


def save(path: str, state: SimState) -> None:
    """Write a (batched or single) SimState to an .npz archive."""
    leaves, treedef = jax.tree.flatten(state)
    np.savez_compressed(
        path, __treedef__=np.frombuffer(
            repr(treedef).encode(), dtype=np.uint8),
        **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)})


def load(path: str, like: SimState) -> SimState:
    """Read a SimState saved by `save`. `like` supplies the pytree structure
    (build it from the same Runtime, e.g. rt.init_batch(...)); shapes and
    dtypes are validated leaf-by-leaf."""
    with np.load(path) as z:
        leaves_like, treedef = jax.tree.flatten(like)
        n = len([k for k in z.files if k.startswith("leaf_")])
        if n != len(leaves_like):
            raise ValueError(
                f"checkpoint has {n} leaves, runtime expects "
                f"{len(leaves_like)} — different config/programs?")
        leaves = []
        for i, ref in enumerate(leaves_like):
            arr = z[f"leaf_{i}"]
            if arr.shape != ref.shape or arr.dtype != np.asarray(ref).dtype:
                raise ValueError(
                    f"checkpoint leaf {i}: {arr.shape}/{arr.dtype} != "
                    f"expected {ref.shape}/{np.asarray(ref).dtype}")
            leaves.append(arr)
        return jax.tree.unflatten(treedef, leaves)
