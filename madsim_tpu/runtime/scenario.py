"""Scenario scripts: the supervisor future, compiled to data.

In madsim the supervisor is an async future on node 0 that sleeps to
checkpoints and calls `Handle::{kill, restart, pause, resume}` /
`NetSim::{clog_node, clog_link, ...}` (runtime/mod.rs:200-256,
net/mod.rs:98-157). Keeping that imperative loop on the host would force a
device sync per fault. Instead a Scenario is a static table of scheduled
supervisor ops baked into the initial event table, so fault injection happens
*inside* the jitted trace at full speed — and ops may take NODE_RANDOM
targets, resolved per-trajectory from the seed's PRNG, which is how one
scenario fuzzes thousands of distinct fault schedules at once.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..core import types as T


@dataclasses.dataclass
class _Row:
    time: int
    op: int
    node: int = 0
    src: int = 0
    payload: tuple = ()


class Scenario:
    """Builder for scheduled supervisor ops.

    Example (a MadRaft-style chaos schedule)::

        sc = Scenario()
        sc.at(T.sec(1)).partition([0, 1])        # cut {0,1} from the rest
        sc.at(T.sec(2)).heal()
        for t in range(5):
            sc.at(T.sec(3 + t)).kill_random()    # per-seed random victim
            sc.at(T.sec(3 + t) + T.ms(500)).restart_random()
        sc.at(T.sec(10)).halt()
    """

    def __init__(self):
        self.rows: list[_Row] = []

    # -- time cursor -------------------------------------------------------
    def at(self, time: int) -> "_At":
        return _At(self, int(time))

    def has_halt(self) -> bool:
        return any(r.op == T.OP_HALT for r in self.rows)

    _OP_NAMES = {
        T.OP_INIT: "boot", T.OP_KILL: "kill", T.OP_RESTART: "restart",
        T.OP_PAUSE: "pause", T.OP_RESUME: "resume",
        T.OP_CLOG_NODE: "clog", T.OP_UNCLOG_NODE: "unclog",
        T.OP_CLOG_LINK: "clog_link", T.OP_UNCLOG_LINK: "unclog_link",
        T.OP_SET_LOSS: "set_loss", T.OP_SET_LATENCY: "set_latency",
        T.OP_HEAL: "heal", T.OP_PARTITION: "partition", T.OP_HALT: "halt",
    }

    @staticmethod
    def _unpack_members(words):
        """Inverse of the 31-nodes/word packing (pools, partitions)."""
        return [w * 31 + b for w, word in enumerate(words)
                for b in range(31) if (int(word) >> b) & 1]

    def describe(self) -> str:
        """Faithful one-line-per-row rendering (repro reports): exact
        tick times, decoded pools/partitions/rates — a script re-entered
        from this text reproduces the original fault model."""
        out = []
        for r in self.rows:
            name = self._OP_NAMES.get(r.op, f"op{r.op}")
            if r.node == T.NODE_RANDOM:
                pool = self._unpack_members(r.payload)
                tgt = (f"random among {pool}" if pool else "random")
            else:
                tgt = f"node {r.node}"
            extra = ""
            if r.op in (T.OP_CLOG_LINK, T.OP_UNCLOG_LINK):
                extra = f" {r.src}->{r.node}"
                tgt = ""
            elif r.op == T.OP_PARTITION:
                tgt = ""
                extra = f" group_a={self._unpack_members(r.payload)}"
            elif r.op == T.OP_SET_LOSS:
                tgt = ""
                extra = f" rate={r.payload[0] / 1e6:g}"
            elif r.op == T.OP_SET_LATENCY:
                tgt = ""
                extra = (f" latency={r.payload[0]}us"
                         f"..{r.payload[1]}us")
            elif r.op == T.OP_HALT:
                tgt = ""
            out.append(f"  t={r.time}us {name}"
                       f"{' ' + tgt if tgt else ''}{extra}")
        return "\n".join(out)

    def build(self, cfg: T.SimConfig):
        """-> dict of numpy arrays (time, op, node, src, payload[R, P])."""
        R = len(self.rows)
        P = cfg.payload_words
        out = dict(
            time=np.zeros(R, np.int32), op=np.zeros(R, np.int32),
            node=np.zeros(R, np.int32), src=np.zeros(R, np.int32),
            payload=np.zeros((R, P), np.int32),
        )
        for i, r in enumerate(self.rows):
            if len(r.payload) > P:
                raise ValueError(
                    f"scenario op {r.op} at t={r.time} needs "
                    f"{len(r.payload)} payload words but cfg.payload_words="
                    f"{P} (partition masks pack 31 nodes per word)")
            out["time"][i] = r.time
            out["op"][i] = r.op
            out["node"][i] = r.node
            out["src"][i] = r.src
            for j, w in enumerate(r.payload):
                out["payload"][i, j] = w
        return out


class _At:
    def __init__(self, sc: Scenario, time: int):
        self._sc, self._t = sc, time

    def _add(self, op, node=0, src=0, payload=()):
        self._sc.rows.append(_Row(self._t, op, int(node), int(src),
                                  tuple(payload)))
        return self

    # -- node lifecycle (Handle::kill/restart/pause/resume) ----------------
    def boot(self, node):
        """Bring `node` up at this time instead of t=0 — the
        Handle::create_node analog (runtime/mod.rs:66-76): scheduling a
        boot makes the Runtime skip that node's automatic t=0 init, so the
        node simply does not exist (messages to it vanish) until now."""
        return self._add(T.OP_INIT, node)

    def kill(self, node):
        return self._add(T.OP_KILL, node)

    def restart(self, node):
        return self._add(T.OP_RESTART, node)

    def pause(self, node):
        return self._add(T.OP_PAUSE, node)

    def resume(self, node):
        return self._add(T.OP_RESUME, node)

    @staticmethod
    def _pool(among):
        """Candidate bitmask for random targets (None = everyone).
        Packed 31 nodes/word across payload words (the OP_PARTITION
        packing), so pools cover any N <= 31 * payload_words."""
        if among is None:
            return ()
        among = list(among)
        assert among, "among=[] would mean 'no restriction'; pass None for that"
        words = [0] * (1 + max(int(n) for n in among) // 31)
        for n in among:
            assert int(n) >= 0, "node ids are non-negative"
            words[int(n) // 31] |= 1 << (int(n) % 31)
        return tuple(words)

    def kill_random(self, among=None):
        """Kill a random alive node — target drawn per-seed at fire time.
        `among` restricts candidates (e.g. servers only, not clients)."""
        return self._add(T.OP_KILL, T.NODE_RANDOM, payload=self._pool(among))

    def restart_random(self, among=None):
        """Restart a random dead node."""
        return self._add(T.OP_RESTART, T.NODE_RANDOM,
                         payload=self._pool(among))

    def pause_random(self, among=None):
        return self._add(T.OP_PAUSE, T.NODE_RANDOM, payload=self._pool(among))

    def resume_random(self, among=None):
        return self._add(T.OP_RESUME, T.NODE_RANDOM,
                         payload=self._pool(among))

    # -- network faults (NetSim) ------------------------------------------
    def clog_node(self, node):
        return self._add(T.OP_CLOG_NODE, node)

    def unclog_node(self, node):
        return self._add(T.OP_UNCLOG_NODE, node)

    def clog_node_random(self):
        return self._add(T.OP_CLOG_NODE, T.NODE_RANDOM)

    def clog_link(self, src, dst):
        return self._add(T.OP_CLOG_LINK, dst, src)

    def unclog_link(self, src, dst):
        return self._add(T.OP_UNCLOG_LINK, dst, src)

    def partition(self, group_a):
        """Cut group_a <-> everyone else, both directions (disconnect2 x N^2
        collapsed into one op). Membership is packed 31 nodes per payload
        word (sign bit unused), so up to 31 * payload_words nodes."""
        words = [0] * (1 + max((int(n) for n in group_a), default=0) // 31)
        for n in group_a:
            n = int(n)
            words[n // 31] |= 1 << (n % 31)
        return self._add(T.OP_PARTITION, payload=tuple(words))

    def heal(self):
        """Clear all clogs/partitions."""
        return self._add(T.OP_HEAL)

    def set_loss(self, rate: float):
        return self._add(T.OP_SET_LOSS, payload=(int(rate * 1e6),))

    def set_latency(self, lo: int, hi: int):
        return self._add(T.OP_SET_LATENCY, payload=(int(lo), int(hi)))

    # -- extension custom ops (plugin framework analog) --------------------
    def custom(self, op: int, node=0, src=0, payload=()):
        """Schedule an extension's supervisor op (op >= extension.OP_USER);
        dispatched to every registered Extension.on_op at fire time."""
        from ..core.extension import OP_USER
        assert op >= OP_USER, f"custom ops must be >= {OP_USER}"
        return self._add(op, node, src, payload)

    # -- end of simulation -------------------------------------------------
    def halt(self):
        return self._add(T.OP_HALT)
