"""Scenario scripts: the supervisor future, compiled to data.

In madsim the supervisor is an async future on node 0 that sleeps to
checkpoints and calls `Handle::{kill, restart, pause, resume}` /
`NetSim::{clog_node, clog_link, ...}` (runtime/mod.rs:200-256,
net/mod.rs:98-157). Keeping that imperative loop on the host would force a
device sync per fault. Instead a Scenario is a static table of scheduled
supervisor ops baked into the initial event table, so fault injection happens
*inside* the jitted trace at full speed — and ops may take NODE_RANDOM
targets, resolved per-trajectory from the seed's PRNG, which is how one
scenario fuzzes thousands of distinct fault schedules at once.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..core import types as T


# -- recipe-family row classes (r18; service/triage.py attribution) --------
# Every supervisor op belongs to one chaos-recipe FAMILY — the row-class
# tags the campaign triage plane uses to attribute coverage keys and
# crash buckets to the fault shape that earned them (runtime/chaos.py
# recipes compose ops from exactly these families). Order IS precedence:
# a scenario mixing families classifies as the first present — most
# gray/specific first, so a gray_failure mix whose mutant kept its torn
# kill reads "torn_write" even while its latency rows stay on. "none"
# covers the classic lifecycle/partition/clog chaos (and a faultless
# script); the triage accounting contract adds an explicit "base" class
# for rows it cannot see at all — never a silent "other".
RECIPE_FAMILIES = ("conn_fault", "torn_write", "slow_disk", "clock_skew",
                   "asym_partition", "loss_latency", "none")


def row_recipe_class(op: int, torn: bool = False) -> str:
    """The recipe family one scenario row encodes. OP_SET_DISK splits on
    its torn flag (a torn-armed disk row is the torn_write_kill recipe's
    signature; a plain latency stall is slow_disk). The r19 connection-
    fault ops (reset-peer teardown, duplicate-delivery storm) class as
    conn_fault — first in precedence, so a mutant that kept its
    connection fault reads as the conn recipe even while gray rows
    stay on."""
    from ..core import types as _T
    if op in (_T.OP_RESET_PEER, _T.OP_SET_DUP):
        return "conn_fault"
    if op == _T.OP_SET_DISK:
        return "torn_write" if torn else "slow_disk"
    if op == _T.OP_SET_SKEW:
        return "clock_skew"
    if op == _T.OP_PARTITION_ONEWAY:
        return "asym_partition"
    if op in (_T.OP_SET_LOSS, _T.OP_SET_LATENCY):
        return "loss_latency"
    return "none"


def classify_recipe(row_classes) -> str:
    """Fold per-row classes into ONE family by RECIPE_FAMILIES
    precedence — the entry/bucket-level classifier (each coverage key
    gets exactly one family, so attribution sums to the total)."""
    present = set(row_classes)
    for fam in RECIPE_FAMILIES:
        if fam in present:
            return fam
    return "none"


@dataclasses.dataclass
class _Row:
    time: int
    op: int
    node: int = 0
    src: int = 0
    payload: tuple = ()
    # value words written RIGHT-ALIGNED into the payload (tail[-1] lands
    # at payload_words-1): the r17 value-carrying ops (OP_SET_SKEW /
    # OP_SET_DISK) keep their values past the pool segment so a
    # NODE_RANDOM pool and a value coexist in one row (step.py
    # _apply_super reads values from the tail, pools from the head)
    payload_tail: tuple = ()


class Scenario:
    """Builder for scheduled supervisor ops.

    Example (a MadRaft-style chaos schedule)::

        sc = Scenario()
        sc.at(T.sec(1)).partition([0, 1])        # cut {0,1} from the rest
        sc.at(T.sec(2)).heal()
        for t in range(5):
            sc.at(T.sec(3 + t)).kill_random()    # per-seed random victim
            sc.at(T.sec(3 + t) + T.ms(500)).restart_random()
        sc.at(T.sec(10)).halt()
    """

    def __init__(self):
        self.rows: list[_Row] = []

    # -- time cursor -------------------------------------------------------
    def at(self, time: int) -> "_At":
        return _At(self, int(time))

    def has_halt(self) -> bool:
        return any(r.op == T.OP_HALT for r in self.rows)

    def recipe_class(self) -> str:
        """This script's recipe family (the classifier over the
        describe()/parse() row table — triage attribution's view of a
        scenario): `classify_recipe` over every row's class, with
        OP_SET_DISK rows reading their torn flag from wherever build()
        would encode it (payload_tail for builder rows, the full
        payload's P-2 word for rows re-entered via KnobPlan)."""
        def torn_of(r):
            if r.op != T.OP_SET_DISK:
                return False
            vals = [0, 0] + list(r.payload_tail or r.payload)
            return bool(vals[-2])
        return classify_recipe(
            row_recipe_class(r.op, torn_of(r)) for r in self.rows)

    _OP_NAMES = {
        T.OP_INIT: "boot", T.OP_KILL: "kill", T.OP_RESTART: "restart",
        T.OP_PAUSE: "pause", T.OP_RESUME: "resume",
        T.OP_CLOG_NODE: "clog", T.OP_UNCLOG_NODE: "unclog",
        T.OP_CLOG_LINK: "clog_link", T.OP_UNCLOG_LINK: "unclog_link",
        T.OP_SET_LOSS: "set_loss", T.OP_SET_LATENCY: "set_latency",
        T.OP_HEAL: "heal", T.OP_PARTITION: "partition", T.OP_HALT: "halt",
        T.OP_PARTITION_ONEWAY: "partition_oneway",
        T.OP_SET_SKEW: "set_skew", T.OP_SET_DISK: "set_disk",
        T.OP_RESET_PEER: "reset_peer", T.OP_SET_DUP: "set_dup",
    }

    @staticmethod
    def _unpack_members(words):
        """Inverse of the 31-nodes/word packing (pools, partitions)."""
        return [w * 31 + b for w, word in enumerate(words)
                for b in range(31) if (int(word) >> b) & 1]

    def describe(self) -> str:
        """Faithful one-line-per-row rendering (repro reports): exact
        tick times, decoded pools/partitions/rates — a script re-entered
        from this text reproduces the original fault model."""
        out = []
        # the r17 value-carrying ops keep how many TAIL payload words?
        # (builder rows carry them in payload_tail; KnobPlan.to_scenario
        # rows bake them into the payload's end — the pool decode below
        # must not read value bits as phantom pool members)
        n_tail = {T.OP_SET_SKEW: 1, T.OP_SET_DISK: 2, T.OP_SET_DUP: 1}
        for r in self.rows:
            name = self._OP_NAMES.get(r.op, f"op{r.op}")
            if r.node == T.NODE_RANDOM:
                pool_words = r.payload
                k = n_tail.get(r.op, 0)
                if k and not r.payload_tail:
                    pool_words = r.payload[:-k]
                pool = self._unpack_members(pool_words)
                tgt = (f"random among {pool}" if pool else "random")
            else:
                tgt = f"node {r.node}"
            extra = ""
            if r.op in (T.OP_CLOG_LINK, T.OP_UNCLOG_LINK):
                extra = f" {r.src}->{r.node}"
                tgt = ""
            elif r.op == T.OP_PARTITION:
                tgt = ""
                extra = f" group_a={self._unpack_members(r.payload)}"
            elif r.op == T.OP_PARTITION_ONEWAY:
                tgt = ""
                extra = (f" group_a={self._unpack_members(r.payload)}"
                         f" dir={'in' if r.src & 1 else 'out'}")
            elif r.op in (T.OP_SET_SKEW, T.OP_SET_DISK, T.OP_SET_DUP):
                # builder rows keep values in payload_tail; rows round-
                # tripped through KnobPlan.to_scenario carry the full
                # payload with the values already right-aligned — the
                # tail IS the payload's tail either way
                vals = [0, 0] + list(r.payload_tail or r.payload)
                extra = (f" skew={vals[-1]}" if r.op == T.OP_SET_SKEW
                         else f" rate={vals[-1] / 1e6:g}"
                         if r.op == T.OP_SET_DUP
                         else f" lat={vals[-1]}us torn={vals[-2]}")
            elif r.op == T.OP_SET_LOSS:
                tgt = ""
                extra = f" rate={r.payload[0] / 1e6:g}"
            elif r.op == T.OP_SET_LATENCY:
                tgt = ""
                extra = (f" latency={r.payload[0]}us"
                         f"..{r.payload[1]}us")
            elif r.op in (T.OP_HALT, T.OP_HEAL):
                tgt = ""
            out.append(f"  t={r.time}us {name}"
                       f"{' ' + tgt if tgt else ''}{extra}")
        return "\n".join(out)

    @classmethod
    def parse(cls, text: str) -> "Scenario":
        """Inverse of `describe()` — the script RE-ENTRY contract: a
        describe()d script parses back into a Scenario whose `build()`
        encodes the identical rows (tests/test_grayfail.py round-trips
        every op in the decode table). Covers the built-in op table;
        extension custom ops (`opN` lines) are rejected — their payload
        encoding is the extension's, not the scenario grammar's."""
        import re
        by_name = {v: k for k, v in cls._OP_NAMES.items()}
        sc = cls()
        for raw in text.splitlines():
            line = raw.strip()
            if not line:
                continue
            m = re.match(r"t=(\d+)us (\w+)\s*(.*)$", line)
            if not m:
                raise ValueError(f"unparseable scenario line: {raw!r}")
            t, name, rest = int(m.group(1)), m.group(2), m.group(3)
            if name not in by_name:
                raise ValueError(
                    f"unknown scenario op {name!r} (extension custom ops "
                    f"don't round-trip through describe/parse): {raw!r}")
            op = by_name[name]
            at = sc.at(t)

            def target(rest):
                """(node, pool, rest) from a leading target clause."""
                mm = re.match(r"node (\d+)\s*(.*)$", rest)
                if mm:
                    return int(mm.group(1)), None, mm.group(2)
                mm = re.match(r"random among \[([\d,\s]*)\]\s*(.*)$", rest)
                if mm:
                    pool = [int(x) for x in mm.group(1).split(",") if
                            x.strip()]
                    return T.NODE_RANDOM, pool, mm.group(2)
                mm = re.match(r"random\s*(.*)$", rest)
                if mm:
                    return T.NODE_RANDOM, None, mm.group(1)
                raise ValueError(f"unparseable target in: {raw!r}")

            if op in (T.OP_CLOG_LINK, T.OP_UNCLOG_LINK):
                mm = re.match(r"(\d+)->(\d+)$", rest)
                s_, d = int(mm.group(1)), int(mm.group(2))
                (at.clog_link if op == T.OP_CLOG_LINK
                 else at.unclog_link)(s_, d)
            elif op == T.OP_PARTITION:
                mm = re.match(r"group_a=\[([\d,\s]*)\]$", rest)
                at.partition([int(x) for x in mm.group(1).split(",")
                              if x.strip()])
            elif op == T.OP_PARTITION_ONEWAY:
                mm = re.match(r"group_a=\[([\d,\s]*)\] dir=(out|in)$", rest)
                at.partition_oneway(
                    [int(x) for x in mm.group(1).split(",") if x.strip()],
                    direction=1 if mm.group(2) == "in" else 0)
            elif op == T.OP_SET_LOSS:
                at.set_loss(round(float(rest.split("=")[1]) * 1e6) / 1e6)
            elif op == T.OP_SET_LATENCY:
                mm = re.match(r"latency=(\d+)us\.\.(\d+)us$", rest)
                at.set_latency(int(mm.group(1)), int(mm.group(2)))
            elif op == T.OP_HALT:
                at.halt()
            elif op == T.OP_HEAL:
                at.heal()
            elif op == T.OP_SET_SKEW:
                node, pool, rest = target(rest)
                v = int(re.match(r"skew=(-?\d+)$", rest).group(1))
                if node == T.NODE_RANDOM:
                    at.set_skew_random(v, among=pool)
                else:
                    at.set_skew(node, v)
            elif op == T.OP_SET_DISK:
                node, pool, rest = target(rest)
                mm = re.match(r"lat=(\d+)us torn=(\d+)$", rest)
                lat, torn = int(mm.group(1)), bool(int(mm.group(2)))
                if node == T.NODE_RANDOM:
                    at.set_disk_random(lat, torn=torn, among=pool)
                else:
                    at.set_disk(node, lat, torn=torn)
            elif op == T.OP_SET_DUP:
                node, pool, rest = target(rest)
                rate = float(re.match(r"rate=([\d.e+-]+)$", rest).group(1))
                if node == T.NODE_RANDOM:
                    at.set_dup_random(rate, among=pool)
                else:
                    at.set_dup(node, rate)
            elif op == T.OP_RESET_PEER:
                node, pool, _ = target(rest)
                if node == T.NODE_RANDOM:
                    at._add(op, T.NODE_RANDOM,
                            payload=_At._pool(pool) if pool else ())
                else:
                    at.reset_peer(node)
            else:               # node-lifecycle / clog ops
                node, pool, _ = target(rest)
                method = {
                    T.OP_INIT: "boot", T.OP_KILL: "kill",
                    T.OP_RESTART: "restart", T.OP_PAUSE: "pause",
                    T.OP_RESUME: "resume", T.OP_CLOG_NODE: "clog_node",
                    T.OP_UNCLOG_NODE: "unclog_node"}[op]
                if node == T.NODE_RANDOM:
                    # re-enter the exact encoding the builders produce:
                    # NODE_RANDOM target + the 31-nodes/word pool words
                    at._add(op, T.NODE_RANDOM,
                            payload=_At._pool(pool) if pool else ())
                else:
                    getattr(at, method)(node)
        return sc

    def build(self, cfg: T.SimConfig):
        """-> dict of numpy arrays (time, op, node, src, payload[R, P])."""
        R = len(self.rows)
        P = cfg.payload_words
        out = dict(
            time=np.zeros(R, np.int32), op=np.zeros(R, np.int32),
            node=np.zeros(R, np.int32), src=np.zeros(R, np.int32),
            payload=np.zeros((R, P), np.int32),
        )
        n_pool_words = min(P, (cfg.n_nodes + 30) // 31)
        for i, r in enumerate(self.rows):
            if len(r.payload) + len(r.payload_tail) > P:
                raise ValueError(
                    f"scenario op {r.op} at t={r.time} needs "
                    f"{len(r.payload)}+{len(r.payload_tail)} payload words "
                    f"but cfg.payload_words={P} (pools pack 31 nodes per "
                    f"word; set_skew/set_disk values ride the tail words)")
            if (r.payload_tail and r.node == T.NODE_RANDOM
                    and P - len(r.payload_tail) < n_pool_words):
                # a value word landing INSIDE the pool segment would be
                # bit-decoded as phantom pool members by the NODE_RANDOM
                # resolution (step.py reads pools from the first
                # ceil(N/31) words) — refuse instead of mistargeting
                raise ValueError(
                    f"scenario op {r.op} at t={r.time}: its "
                    f"{len(r.payload_tail)} value word(s) overlap the "
                    f"{n_pool_words}-word NODE_RANDOM pool segment — "
                    f"raise cfg.payload_words past "
                    f"{n_pool_words + len(r.payload_tail)}")
            out["time"][i] = r.time
            out["op"][i] = r.op
            out["node"][i] = r.node
            out["src"][i] = r.src
            for j, w in enumerate(r.payload):
                out["payload"][i, j] = w
            # value words land right-aligned (tail[-1] at P-1), where
            # step.py _apply_super reads them past any pool segment
            for j, w in enumerate(r.payload_tail):
                out["payload"][i, P - len(r.payload_tail) + j] = w
        return out


class _At:
    def __init__(self, sc: Scenario, time: int):
        self._sc, self._t = sc, time

    def _add(self, op, node=0, src=0, payload=(), payload_tail=()):
        self._sc.rows.append(_Row(self._t, op, int(node), int(src),
                                  tuple(payload), tuple(payload_tail)))
        return self

    # -- node lifecycle (Handle::kill/restart/pause/resume) ----------------
    def boot(self, node):
        """Bring `node` up at this time instead of t=0 — the
        Handle::create_node analog (runtime/mod.rs:66-76): scheduling a
        boot makes the Runtime skip that node's automatic t=0 init, so the
        node simply does not exist (messages to it vanish) until now."""
        return self._add(T.OP_INIT, node)

    def kill(self, node):
        return self._add(T.OP_KILL, node)

    def restart(self, node):
        return self._add(T.OP_RESTART, node)

    def pause(self, node):
        return self._add(T.OP_PAUSE, node)

    def resume(self, node):
        return self._add(T.OP_RESUME, node)

    @staticmethod
    def _pool(among):
        """Candidate bitmask for random targets (None = everyone).
        Packed 31 nodes/word across payload words (the OP_PARTITION
        packing), so pools cover any N <= 31 * payload_words."""
        if among is None:
            return ()
        among = list(among)
        assert among, "among=[] would mean 'no restriction'; pass None for that"
        words = [0] * (1 + max(int(n) for n in among) // 31)
        for n in among:
            assert int(n) >= 0, "node ids are non-negative"
            words[int(n) // 31] |= 1 << (int(n) % 31)
        return tuple(words)

    def kill_random(self, among=None):
        """Kill a random alive node — target drawn per-seed at fire time.
        `among` restricts candidates (e.g. servers only, not clients)."""
        return self._add(T.OP_KILL, T.NODE_RANDOM, payload=self._pool(among))

    def restart_random(self, among=None):
        """Restart a random dead node."""
        return self._add(T.OP_RESTART, T.NODE_RANDOM,
                         payload=self._pool(among))

    def pause_random(self, among=None):
        return self._add(T.OP_PAUSE, T.NODE_RANDOM, payload=self._pool(among))

    def resume_random(self, among=None):
        return self._add(T.OP_RESUME, T.NODE_RANDOM,
                         payload=self._pool(among))

    # -- network faults (NetSim) ------------------------------------------
    def clog_node(self, node):
        return self._add(T.OP_CLOG_NODE, node)

    def unclog_node(self, node):
        return self._add(T.OP_UNCLOG_NODE, node)

    def clog_node_random(self):
        return self._add(T.OP_CLOG_NODE, T.NODE_RANDOM)

    def clog_link(self, src, dst):
        return self._add(T.OP_CLOG_LINK, dst, src)

    def unclog_link(self, src, dst):
        return self._add(T.OP_UNCLOG_LINK, dst, src)

    def partition(self, group_a):
        """Cut group_a <-> everyone else, both directions (disconnect2 x N^2
        collapsed into one op). Membership is packed 31 nodes per payload
        word (sign bit unused), so up to 31 * payload_words nodes."""
        words = [0] * (1 + max((int(n) for n in group_a), default=0) // 31)
        for n in group_a:
            n = int(n)
            words[n // 31] |= 1 << (n % 31)
        return self._add(T.OP_PARTITION, payload=tuple(words))

    def partition_oneway(self, group_a, direction: int = 0):
        """ASYMMETRIC cut (madsim `disconnect2` parity, r17): direction 0
        cuts A -> not-A — group_a's sends to the outside vanish while
        everything the outside sends A still arrives; direction 1 cuts the
        reverse. Directional entries are OR'd INTO the clog_link matrix,
        so one-way cuts compose (two opposite one-way cuts == a full
        partition); only `heal()` clears them. Membership packs 31 nodes
        per payload word, like `partition()`."""
        words = [0] * (1 + max((int(n) for n in group_a), default=0) // 31)
        for n in group_a:
            n = int(n)
            words[n // 31] |= 1 << (n % 31)
        return self._add(T.OP_PARTITION_ONEWAY, src=int(direction) & 1,
                         payload=tuple(words))

    def set_skew(self, node, skew: int):
        """Set `node`'s clock-RATE skew in 1/1024ths (r17): its local
        clock runs at (1 + skew/1024)x — handlers observe the drifted
        `ctx.now` and the node's timer delays stretch/shrink inversely,
        so a fast clock expires leases/timeouts early in global time.
        Clipped to ±SKEW_CAP (±50%) at application; 0 restores a
        synchronized clock."""
        return self._add(T.OP_SET_SKEW, node,
                         payload_tail=(int(skew),))

    def set_skew_random(self, skew: int, among=None):
        """Skew a random node's clock (pool-restricted like
        kill_random); the value rides the tail payload word, so pool and
        value coexist."""
        return self._add(T.OP_SET_SKEW, T.NODE_RANDOM,
                         payload=self._pool(among),
                         payload_tail=(int(skew),))

    def set_disk(self, node, latency: int = 0, torn: bool = False):
        """Set `node`'s disk fault state (r17): `latency` ticks are added
        to every emission the node makes (the fsync-stalled event loop —
        replies and timers leave late), and `torn=True` arms torn-write-
        on-kill mode (a kill flushes a random prefix of each fs file's
        unsynced tail to disk, so recovery can see a partially-written
        final record). `set_disk(n)` restores a healthy disk."""
        return self._add(T.OP_SET_DISK, node,
                         payload_tail=(int(bool(torn)), int(latency)))

    def set_disk_random(self, latency: int = 0, torn: bool = False,
                        among=None):
        """Disk-fault a random node (pool-restricted like kill_random)."""
        return self._add(T.OP_SET_DISK, T.NODE_RANDOM,
                         payload=self._pool(among),
                         payload_tail=(int(bool(torn)), int(latency)))

    def reset_peer(self, node):
        """Tear down every established connection/stream touching `node`,
        on BOTH sides, and bump the incarnation epochs (r19 — the madsim
        NetSim::reset_node parity): in-flight segments and RSTs from the
        torn incarnation are rejected by whatever connection comes next.
        Inert for models without the net/conn+stream state leaves."""
        return self._add(T.OP_RESET_PEER, node)

    def reset_peer_random(self, among=None):
        """Reset-peer a random node (pool-restricted like kill_random)."""
        return self._add(T.OP_RESET_PEER, T.NODE_RANDOM,
                         payload=self._pool(among))

    def set_dup(self, node, rate: float):
        """Set `node`'s duplicate-delivery rate (r19): each MESSAGE
        dispatched at the node is delivered one more time with this
        probability (fresh latency draw, byte-identical payload — the
        retransmit-storm regime; duplicates can duplicate again).
        Clipped to DUP_RATE_CAP (0.9) at application; `set_dup(n, 0)`
        restores exactly-once datagram delivery."""
        return self._add(T.OP_SET_DUP, node,
                         payload_tail=(int(rate * 1e6),))

    def set_dup_random(self, rate: float, among=None):
        """Dup-storm a random node (pool-restricted like kill_random);
        the rate rides the tail payload word, so pool and value
        coexist."""
        return self._add(T.OP_SET_DUP, T.NODE_RANDOM,
                         payload=self._pool(among),
                         payload_tail=(int(rate * 1e6),))

    def heal(self):
        """Clear all clogs/partitions (one-way cuts included)."""
        return self._add(T.OP_HEAL)

    def set_loss(self, rate: float):
        return self._add(T.OP_SET_LOSS, payload=(int(rate * 1e6),))

    def set_latency(self, lo: int, hi: int):
        return self._add(T.OP_SET_LATENCY, payload=(int(lo), int(hi)))

    # -- extension custom ops (plugin framework analog) --------------------
    def custom(self, op: int, node=0, src=0, payload=()):
        """Schedule an extension's supervisor op (op >= extension.OP_USER);
        dispatched to every registered Extension.on_op at fire time."""
        from ..core.extension import OP_USER
        assert op >= OP_USER, f"custom ops must be >= {OP_USER}"
        return self._add(op, node, src, payload)

    # -- end of simulation -------------------------------------------------
    def halt(self):
        return self._add(T.OP_HALT)
