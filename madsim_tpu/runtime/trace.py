"""Event-trace formatting: the virtual-time logger.

The reference's logger stamps every record with virtual time, node, and
target (`[virtual-time level node target] msg`, runtime/mod.rs:342-383) and
can filter records before a virtual instant (MADSIM_LOG_TIME_START,
runtime/mod.rs:349-358). Here the engine emits a structured event record per
step (when run with collect_events=True); this module renders one seed's
stream the same way for debugging a replayed failure.
"""

from __future__ import annotations

import numpy as np

from ..core import types as T
from ..obs.trace import _KIND, _OP  # one source for event-name rendering


def _columns(events: dict, b: int):
    """One seed's event columns + the indices of fired steps."""
    cols = {k: np.asarray(events[k])[:, b]
            for k in ("fired", "now", "kind", "node", "src", "tag")}
    return cols, np.nonzero(cols["fired"])[0]


def format_trace(events: dict, b: int = 0, time_start: int | None = None,
                 node_names=None, limit: int | None = None) -> list[str]:
    """Render trajectory b's event stream as text lines.

    events: the structure returned by Runtime.run(collect_events=True) —
    arrays shaped [steps, batch, ...]. time_start filters records before a
    virtual instant; when None it honors the MADSIM_LOG_TIME_START env var
    (milliseconds — the runtime/mod.rs:349-358 contract).
    """
    if time_start is None:
        import os
        v = os.environ.get("MADSIM_LOG_TIME_START")
        time_start = int(float(v) * T.TICKS_PER_MS) if v else 0
    cols, idx = _columns(events, b)
    now, kind = cols["now"], cols["kind"]
    node, src, tag = cols["node"], cols["src"], cols["tag"]
    lines = []
    for i in idx:
        if now[i] < time_start:
            continue
        t_ms = now[i] / T.TICKS_PER_MS
        name = (node_names[node[i]] if node_names is not None
                else f"node{node[i]}")
        k = _KIND.get(int(kind[i]), f"?{kind[i]}")
        if kind[i] == T.EV_MSG:
            detail = f"tag={tag[i]} from {src[i]}"
        elif kind[i] == T.EV_SUPER:
            detail = _OP.get(int(tag[i]), f"op={tag[i]}")
        else:
            detail = f"tag={tag[i]}"
        lines.append(f"[{t_ms:12.3f}ms {name:>7} {k:>5}] {detail}")
        if limit is not None and len(lines) >= limit:
            break
    return lines


def print_trace(events: dict, b: int = 0, **kw) -> None:
    for line in format_trace(events, b, **kw):
        print(line)


def export_chrome_trace(events: dict, path: str, b: int = 0,
                        node_names=None) -> int:
    """Back-compat shim for the original exporter signature; the
    implementation (and the ring-source variant `run_fused` sweeps need)
    lives in obs/trace.py."""
    from ..obs.trace import export_chrome_trace as _export
    return _export(path, events=events, b=b, node_names=node_names)
