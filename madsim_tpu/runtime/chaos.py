"""Canned chaos recipes — reusable Scenario builders.

The reference's chaos tests hand-roll the same supervisor futures over and
over (MadRaft's random_kill/random_partition loops; tonic-example's
crash-the-server); so did this repo's test files. These builders capture
the recurring shapes once. Each takes an optional `sc` to compose onto and
returns it, so recipes chain:

    sc = chaos.rolling_kills(rounds=4, among=range(5))
    sc = chaos.split_brain(at=sec(2), group=[0, 1], heal_after=sec(1), sc=sc)
"""

from __future__ import annotations

from ..core.types import ms, sec
from .scenario import Scenario


def rolling_kills(rounds: int = 4, first=ms(800), period=ms(900),
                  down=ms(500), among=None, sc: Scenario | None = None):
    """Kill a random eligible node every `period`, restarting it `down`
    later — the MadRaft random_kill loop."""
    sc = sc or Scenario()
    for t in range(rounds):
        sc.at(first + period * t).kill_random(among=among)
        sc.at(first + period * t + down).restart_random(among=among)
    return sc


def rolling_pauses(rounds: int = 4, first=ms(800), period=ms(900),
                   down=ms(300), among=None, sc: Scenario | None = None):
    """Pause/resume churn: nodes freeze (clock keeps moving — leases and
    timeouts expire around them) instead of dying."""
    sc = sc or Scenario()
    for t in range(rounds):
        sc.at(first + period * t).pause_random(among=among)
        sc.at(first + period * t + down).resume_random(among=among)
    return sc


def split_brain(at, group, heal_after, sc: Scenario | None = None):
    """Partition `group` from everyone else, heal after `heal_after`."""
    sc = sc or Scenario()
    sc.at(at).partition(group)
    sc.at(at + heal_after).heal()
    return sc


def flaky_network(at, loss: float, until, latency=None,
                  restore_loss: float = 0.0, restore_latency=None,
                  heal: bool = True, sc: Scenario | None = None):
    """Degrade the network for a window: raise loss (and optionally the
    latency range), then restore.

    `heal=True` (default) also emits OP_HEAL at the window end: the
    loss/latency scalars restore by themselves, but per-LINK state (clogs,
    partitions, one-way cuts) composed into the same window by other
    recipes has no scalar to restore through — without the heal a
    composed recipe could leak cuts past its window. A heal on a
    cut-free scenario clears nothing."""
    sc = sc or Scenario()
    sc.at(at).set_loss(loss)
    if latency is not None:
        sc.at(at).set_latency(*latency)
    sc.at(until).set_loss(restore_loss)
    if restore_latency is not None:
        sc.at(until).set_latency(*restore_latency)
    if heal:
        sc.at(until).heal()
    return sc


def madraft_churn(servers, rounds: int = 4, first=ms(800), period=ms(900),
                  down=ms(500), partition_at=sec(2), partition_group=(0, 1),
                  heal_after=sec(1), sc: Scenario | None = None):
    """The standard MadRaft fuzz mix: rolling kills over the servers plus
    one partition/heal cycle — the shape BASELINE.md configs 2/4 use."""
    sc = rolling_kills(rounds, first, period, down, among=servers, sc=sc)
    return split_brain(partition_at, list(partition_group), heal_after,
                       sc=sc)


# ---------------------------------------------------------------------------
# gray-failure recipes (r17, DESIGN §18): the fault shapes madsim simulates
# that clean kills and symmetric partitions cannot express — each is a
# knob-plane scenario, so the fuzzer mutates its times/targets/values for
# free (search/mutate.py fault_perturb).
# ---------------------------------------------------------------------------

def asymmetric_partition(at, group, heal_after, direction: int = 0,
                         sc: Scenario | None = None):
    """One-way cut for a window (madsim disconnect2 parity): direction 0
    silences `group`'s OUTBOUND traffic while it still hears everything —
    the classic gray failure where a node looks alive to itself (inbound
    heartbeats arrive) but the cluster stopped hearing it. Healed at
    window end (one-way cuts have no scalar to restore through)."""
    sc = sc or Scenario()
    sc.at(at).partition_oneway(group, direction=direction)
    sc.at(at + heal_after).heal()
    return sc


def clock_drift(at, skew: int, node=None, among=None, until=None,
                sc: Scenario | None = None):
    """Skew one node's clock rate by `skew`/1024 from `at` (a random
    pool-restricted node when `node` is None), restoring a synchronized
    clock at `until` when given. Positive skew = fast clock: leases and
    timeouts expire early in global time."""
    sc = sc or Scenario()
    if node is None:
        sc.at(at).set_skew_random(skew, among=among)
        if until is not None:
            # restore over the same pool: the restore targets a random
            # pool member too — with a 1-node pool it is exact; wider
            # pools model operators fixing one drifting clock at a time
            sc.at(until).set_skew_random(0, among=among)
    else:
        sc.at(at).set_skew(node, skew)
        if until is not None:
            sc.at(until).set_skew(node, 0)
    return sc


def slow_disk(at, latency, until, node=None, among=None,
              sc: Scenario | None = None):
    """Stall one node's disk for a window: every emission it makes
    (acks, replication, its own timers) leaves `latency` ticks late —
    the limping-but-alive node gray failure."""
    sc = sc or Scenario()
    if node is None:
        sc.at(at).set_disk_random(latency, among=among)
        sc.at(until).set_disk_random(0, among=among)
    else:
        sc.at(at).set_disk(node, latency)
        sc.at(until).set_disk(node, 0)
    return sc


def torn_write_kill(at, node, down=ms(500), sc: Scenario | None = None):
    """Power-fail `node` with a TORN final write: torn mode is armed one
    tick before the kill (same-instant ops would tie-break randomly
    against it), so the kill flushes a random prefix of each fs file's
    unsynced tail — recovery sees a partially-written final record
    instead of clean old-or-new. Restarts `down` later with a healthy
    disk."""
    sc = sc or Scenario()
    sc.at(at - 1).set_disk(node, 0, torn=True)
    sc.at(at).kill(node)
    sc.at(at + down).restart(node)
    sc.at(at + down + 1).set_disk(node, 0, torn=False)
    return sc


# ---------------------------------------------------------------------------
# connection-fault recipes (r19, DESIGN §20): TCP-grade transport faults —
# the fault shapes madsim's NetSim::reset_node injects that datagram-level
# loss/latency cannot express. Knob-plane scenarios like everything else,
# so the fuzzer mutates times/targets/rates for free (fault_perturb).
# ---------------------------------------------------------------------------

def conn_reset_storm(rounds: int = 3, first=ms(300), period=ms(450),
                     node=None, among=None, sc: Scenario | None = None):
    """Repeatedly tear down every connection touching the target (a
    random pool member when `node` is None) — the reset_node churn
    regime: established sessions die mid-pipeline on BOTH sides, and
    whatever was in flight belongs to a dead incarnation. Sound
    transports re-handshake onto a fresh epoch; unsound ones accept the
    dead incarnation's retransmits into the new window."""
    sc = sc or Scenario()
    for t in range(rounds):
        if node is None:
            sc.at(first + period * t).reset_peer_random(among=among)
        else:
            sc.at(first + period * t).reset_peer(node)
    return sc


def retransmit_storm(at, rate: float, until, node=None, among=None,
                     sc: Scenario | None = None):
    """Duplicate-delivery window: every datagram dispatched at the target
    is redelivered with probability `rate` (duplicates can duplicate
    again — a geometric storm) from `at` until `until` — the regime a
    Go-Back-N transport's exactly-once claim must survive."""
    sc = sc or Scenario()
    if node is None:
        sc.at(at).set_dup_random(rate, among=among)
        sc.at(until).set_dup_random(0, among=among)
    else:
        sc.at(at).set_dup(node, rate)
        sc.at(until).set_dup(node, 0)
    return sc


def half_open_churn(node, rounds: int = 2, first=ms(300), period=ms(600),
                    down=ms(150), sc: Scenario | None = None):
    """Kill/restart churn that leaves HALF-OPEN connections behind — a
    kill alone deliberately does NOT tear the survivors' conn state
    (conn.py: only a reset does), so peers keep talking to an
    ESTABLISHED ghost until a reset-peer pulse at the end of each round
    finally tears both sides down. Composes with gray_failure like
    every recipe."""
    sc = sc or Scenario()
    for t in range(rounds):
        t0 = first + period * t
        sc.at(t0).kill(node)
        sc.at(t0 + down).restart(node)
        sc.at(t0 + down + ms(100)).reset_peer(node)
    return sc


def gray_failure(at, until, group=(0,), skew: int = 256,
                 disk_latency=ms(20), direction: int = 0,
                 sc: Scenario | None = None):
    """The composed gray-failure window: one-way partition the group,
    drift the first member's clock fast, and stall its disk — then
    restore EVERYTHING at `until`, including an OP_HEAL so the one-way
    cuts (which have no restore scalar) cannot leak past the window."""
    sc = sc or Scenario()
    members = list(group)
    sc.at(at).partition_oneway(members, direction=direction)
    sc.at(at).set_skew(members[0], skew)
    sc.at(at).set_disk(members[0], disk_latency)
    sc.at(until).set_skew(members[0], 0)
    sc.at(until).set_disk(members[0], 0)
    sc.at(until).heal()
    return sc
