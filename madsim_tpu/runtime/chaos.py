"""Canned chaos recipes — reusable Scenario builders.

The reference's chaos tests hand-roll the same supervisor futures over and
over (MadRaft's random_kill/random_partition loops; tonic-example's
crash-the-server); so did this repo's test files. These builders capture
the recurring shapes once. Each takes an optional `sc` to compose onto and
returns it, so recipes chain:

    sc = chaos.rolling_kills(rounds=4, among=range(5))
    sc = chaos.split_brain(at=sec(2), group=[0, 1], heal_after=sec(1), sc=sc)
"""

from __future__ import annotations

from ..core.types import ms, sec
from .scenario import Scenario


def rolling_kills(rounds: int = 4, first=ms(800), period=ms(900),
                  down=ms(500), among=None, sc: Scenario | None = None):
    """Kill a random eligible node every `period`, restarting it `down`
    later — the MadRaft random_kill loop."""
    sc = sc or Scenario()
    for t in range(rounds):
        sc.at(first + period * t).kill_random(among=among)
        sc.at(first + period * t + down).restart_random(among=among)
    return sc


def rolling_pauses(rounds: int = 4, first=ms(800), period=ms(900),
                   down=ms(300), among=None, sc: Scenario | None = None):
    """Pause/resume churn: nodes freeze (clock keeps moving — leases and
    timeouts expire around them) instead of dying."""
    sc = sc or Scenario()
    for t in range(rounds):
        sc.at(first + period * t).pause_random(among=among)
        sc.at(first + period * t + down).resume_random(among=among)
    return sc


def split_brain(at, group, heal_after, sc: Scenario | None = None):
    """Partition `group` from everyone else, heal after `heal_after`."""
    sc = sc or Scenario()
    sc.at(at).partition(group)
    sc.at(at + heal_after).heal()
    return sc


def flaky_network(at, loss: float, until, latency=None,
                  restore_loss: float = 0.0, restore_latency=None,
                  sc: Scenario | None = None):
    """Degrade the network for a window: raise loss (and optionally the
    latency range), then restore."""
    sc = sc or Scenario()
    sc.at(at).set_loss(loss)
    if latency is not None:
        sc.at(at).set_latency(*latency)
    sc.at(until).set_loss(restore_loss)
    if restore_latency is not None:
        sc.at(until).set_latency(*restore_latency)
    return sc


def madraft_churn(servers, rounds: int = 4, first=ms(800), period=ms(900),
                  down=ms(500), partition_at=sec(2), partition_group=(0, 1),
                  heal_after=sec(1), sc: Scenario | None = None):
    """The standard MadRaft fuzz mix: rolling kills over the servers plus
    one partition/heal cycle — the shape BASELINE.md configs 2/4 use."""
    sc = rolling_kills(rounds, first, period, down, among=servers, sc=sc)
    return split_brain(partition_at, list(partition_group), heal_after,
                       sc=sc)
