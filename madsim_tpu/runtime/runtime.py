"""Runtime: the batched supervisor — madsim::runtime::Runtime, vectorized.

The reference Runtime owns RNG + executor + simulators and drives one seed to
completion on one thread (runtime/mod.rs:39-187). This Runtime compiles the
step engine once and drives a whole `[seed_batch]` of clusters through it in
fixed-size scan chunks, syncing to the host only between chunks (to test
"all halted" and to let host code inspect/fault-inject). Chunked scanning is
the host/device boundary discipline: supervisor logic lives in the scenario
table *inside* the trace; the Python loop only orchestrates jitted calls.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..compile.cache import COMPILE_LOG, PROGRAM_CACHE
from ..compile.signature import runtime_signature
from ..core import prng
from ..core import types as T
from ..core.api import Program
from ..core.state import SimState, init_state
from ..core.step import make_step
from ..utils.hashing import batch_fingerprints
from ..utils.hostcopy import owned_host_copy
from .scenario import Scenario


def _halted_count(state) -> int | None:
    """Halted-lane count for observer records; None when the batch spans
    non-addressable shards (multi-process sharding), where fetching the
    [B] lane would raise — the replicated-scalar `halted.all()` sync the
    runners rely on still works there, so observers degrade gracefully
    instead of killing the sweep."""
    h = state.halted
    if not getattr(h, "is_fully_addressable", True):
        return None
    return int(np.asarray(h).sum())


class Runtime:
    """Batched simulation runtime.

    Args:
      cfg: static SimConfig.
      programs: node programs (state machines).
      state_spec: one node's default protocol-state pytree (no node axis).
      node_prog: node -> program index (default: all nodes run programs[0]).
      scenario: scheduled supervisor ops; a HALT at cfg.time_limit is
        appended automatically if the scenario has none (set_time_limit
        analog, runtime/mod.rs:175-177).
      invariant: optional global safety check f(state) -> (bad, code).
      share_programs: resolve this Runtime's jitted runners through the
        process-level `compile.PROGRAM_CACHE` (keyed on the structural
        signature — see compile/signature.py), so structurally-identical
        Runtimes share one trace+compile per (batch shape, chunk length).
        False restores private per-instance jits (the fresh-compile
        control used by the cache-correctness tests and
        `bench.py --mode compile_ab`).
    """

    def __init__(self, cfg: T.SimConfig, programs: Sequence[Program],
                 state_spec: Any, node_prog=None,
                 scenario: Scenario | None = None,
                 invariant: Callable | None = None,
                 persist: Any = None,
                 halt_when: Callable | None = None,
                 extensions: Sequence = (),
                 share_programs: bool = True,
                 lint: bool | str = False):
        self.cfg = cfg
        self.programs = list(programs)
        self.state_spec = state_spec
        self.node_prog = np.asarray(
            node_prog if node_prog is not None
            else np.zeros(cfg.n_nodes, np.int32), np.int32)
        self.invariant = invariant
        self.extensions = list(extensions)
        self._halt_when = halt_when
        self._persist = persist      # kept for derived() re-construction
        if lint:
            # the DetSan construction gate (analyze/lint.py, DESIGN §14):
            # lint=True raises on active findings BEFORE anything traces,
            # lint="warn" prints them and proceeds. Off by default — the
            # repo-wide `python -m madsim_tpu.analyze` gate covers source
            # statically; this flag adds the closure checks only live
            # objects allow (sig-degrade, mutable captures).
            from ..analyze.lint import (DeterminismLintError, active,
                                        lint_runtime)
            bad = active(lint_runtime(self))
            if bad and lint != "warn":
                raise DeterminismLintError(bad)
            for f in bad:
                print(f"detsan warn: {f.format()}")
        self._step = make_step(cfg, self.programs, self.node_prog,
                               self.state_spec, invariant, persist=persist,
                               halt_when=halt_when,
                               extensions=self.extensions)
        # structural signature: programs/specs/invariants are frozen into
        # the key AT CONSTRUCTION — mutating a program object afterwards
        # was already unsupported (the first run bakes the trace); with
        # sharing it would alias another Runtime's executable, so the
        # freeze formalizes the contract
        self._sig = (runtime_signature(cfg, self.programs, self.node_prog,
                                       self.state_spec, invariant, persist,
                                       halt_when, self.extensions)
                     if share_programs else None)
        self.set_scenario(scenario)

    def _shared(self, kind, build):
        """Resolve a jitted runner: through the process-level ProgramCache
        when sharing is on (a hit means another structurally-identical
        Runtime already built — and possibly compiled — it), else build
        privately."""
        if self._sig is None:
            return build()
        return PROGRAM_CACHE.get((self._sig, kind), build)

    def set_scenario(self, scenario: Scenario | None) -> None:
        """Swap the scheduled supervisor script WITHOUT recompiling.

        A scenario is initial-state DATA (event-table rows pre-loaded by
        `_build_template`), not part of the compiled step program — so
        replacing it never retraces. Copies the rows (the auto-HALT must
        never mutate a caller's object that might be shared across
        Runtimes with different time limits) and re-applies the auto-HALT
        at cfg.time_limit when the script has none. `harness.minimize`
        uses this to ddmin failing chaos scripts."""
        new = Scenario()
        if scenario is not None:
            new.rows = list(scenario.rows)
        if not new.has_halt():
            new.at(self.cfg.time_limit).halt()
        # build first, assign together: a capacity-overflow ValueError
        # must not leave rt.scenario describing a script the template
        # doesn't encode
        old = getattr(self, "scenario", None)
        self.scenario = new
        try:
            self._template = self._build_template()
        except Exception:
            self.scenario = old
            raise

    def derived(self, **overrides) -> "Runtime":
        """A Runtime over the SAME world — programs, state spec,
        node->program map, scenario, invariants, persistence mask,
        extensions — with config fields replaced. The
        observability-upgrade constructor window replay rides
        (obs/timetravel.py, DESIGN §21): derive a big-ring/profiled
        build of a runtime whose live sweep ran lean, replay a lane
        checkpoint through it, get the identical trajectory with more
        instrumentation. Replay-domain overrides (n_nodes, time_limit,
        jitter gate, ...) are legal too but produce a DIFFERENT replay
        domain — checkpoints then reject via the world-signature check.
        Shares the process program cache, so structurally-equal derived
        runtimes cost zero new compiles."""
        return Runtime(dataclasses.replace(self.cfg, **overrides),
                       self.programs, self.state_spec,
                       node_prog=self.node_prog, scenario=self.scenario,
                       invariant=self.invariant, persist=self._persist,
                       halt_when=self._halt_when,
                       extensions=self.extensions,
                       share_programs=self._sig is not None)

    def _ckpt_setup(self, ckpt_every, ckpt_log):
        """Shared ckpt_every/ckpt_log normalization for run()/run_fused:
        returns (ckpt_every, ckpt_log) or (None, None) when harvesting
        is off. The log is also stashed as `self.last_ckpt_log` so the
        sugar form `run(..., ckpt_every=K)` (no explicit log) still
        hands the harvest back."""
        if ckpt_every is None and ckpt_log is None:
            return None, None
        from ..obs.timetravel import CheckpointLog
        if ckpt_log is None:
            ckpt_log = CheckpointLog(every=ckpt_every)
        if ckpt_every is None:
            ckpt_every = ckpt_log.every
        if not ckpt_every or int(ckpt_every) <= 0:
            raise ValueError("ckpt_every must be a positive step count "
                             "(or pass a CheckpointLog with .every set)")
        ckpt_log.signature = self.cfg.structural_signature()
        self.last_ckpt_log = ckpt_log
        return int(ckpt_every), ckpt_log

    # ------------------------------------------------------------------
    def _build_template(self) -> SimState:
        """One-trajectory initial state with the event table pre-loaded:
        an OP_INIT row per node at t=0 (node boot) + all scenario rows."""
        cfg = self.cfg
        rows = self.scenario.build(cfg)
        n_init = cfg.n_nodes
        n_rows = n_init + rows["time"].shape[0]
        if n_rows > cfg.event_capacity:
            raise ValueError(
                f"scenario ({n_rows} rows) exceeds event_capacity "
                f"({cfg.event_capacity})")
        node_state = jax.tree.map(
            lambda a: jnp.broadcast_to(jnp.asarray(a),
                                       (cfg.n_nodes,) + jnp.asarray(a).shape),
            self.state_spec)
        from ..core.extension import build_ext_state
        s = init_state(cfg, prng.seed_key(0), node_state,
                       build_ext_state(cfg, self.extensions))

        C, Pw = cfg.event_capacity, cfg.payload_words
        deadline = np.full(C, T.T_INF, np.int32)
        kind = np.zeros(C, np.int32)
        node = np.zeros(C, np.int32)
        src = np.zeros(C, np.int32)
        tag = np.zeros(C, np.int32)
        payload = np.zeros((C, Pw), np.int32)
        # node boots at t=0 — except nodes with a scheduled Scenario.boot
        # (the create_node analog), which come up at their scheduled time
        deferred = {r.node for r in self.scenario.rows
                    if r.op == T.OP_INIT and r.node != T.NODE_RANDOM}
        deadline[:n_init] = 0
        kind[:n_init] = T.EV_SUPER
        node[:n_init] = np.arange(n_init)
        tag[:n_init] = T.OP_INIT
        for d in deferred:
            deadline[d] = T.T_INF
            kind[d] = 0
            tag[d] = 0
        # scenario ops
        r = rows["time"].shape[0]
        deadline[n_init:n_rows] = rows["time"]
        kind[n_init:n_rows] = T.EV_SUPER
        node[n_init:n_rows] = rows["node"]
        src[n_init:n_rows] = rows["src"]
        tag[n_init:n_rows] = rows["op"]
        payload[n_init:n_rows] = rows["payload"]
        return s.replace(
            t_deadline=jnp.asarray(deadline),
            t_kind=jnp.asarray(kind, s.t_kind.dtype),       # table_dtype
            t_node=jnp.asarray(node, s.t_node.dtype),
            t_src=jnp.asarray(src, s.t_src.dtype),
            t_tag=jnp.asarray(tag), t_payload=jnp.asarray(payload))

    # ------------------------------------------------------------------
    @staticmethod
    def _lane_mask(lanes, B: int, what: str) -> np.ndarray:
        """Normalize a lane selection (int index array or bool[B] mask)
        into a bool[B] mask — shared by the trace_lanes and
        profile_lanes sampling knobs."""
        lanes = np.asarray(lanes)
        if lanes.dtype == bool:
            if lanes.shape != (B,):
                raise ValueError(
                    f"bool {what} mask shape {lanes.shape} != "
                    f"batch ({B},)")
            return lanes
        mask = np.zeros(B, bool)
        mask[lanes.astype(np.int64)] = True
        return mask

    def init_batch(self, seeds, trace_lanes=None,
                   profile_lanes=None, latency_lanes=None,
                   series_lanes=None, span_lanes=None) -> SimState:
        """Initial batched state for an array of seeds (replay-by-seed:
        the same seed always reproduces the same trajectory, the
        MADSIM_TEST_SEED contract of macros lib.rs:141-145).

        trace_lanes: which LANES the flight-recorder ring records when
        cfg.trace_cap > 0 (None = all; an int index array or a bool[B]
        mask narrows it — the lane-sampling knob that lets a B=4096
        sweep record 8 lanes instead of paying ring bandwidth on all of
        them). Lanes, not seeds: sampling is a property of this batch's
        layout, and obs/rings.py readers take lane indices too.

        profile_lanes: which lanes the sim-profiler counter plane counts
        when cfg.profile (None = all; same index/bool-mask forms). The
        masked-off build is the ship-with-it shape: profile=True
        compiled in, lanes flipped on only for the sweeps being
        profiled (bench.py --mode prof_ab bounds the masked cost).

        latency_lanes: which lanes the SLO latency plane histograms
        when cfg.latency_hist > 0 (None = all; same forms; bench.py
        --mode lat_ab bounds the masked cost). NOTE: the root-time
        column ev_root_t is maintained on every lane regardless — only
        the histogram folds are gated — so flipping a lane on mid-
        campaign needs no warm-up. A runtime whose `invariant=` is
        harness.slo_invariant should keep every lane on: a masked lane
        never folds, so its SLO can never fire.

        series_lanes: which lanes the windowed telemetry plane records
        when cfg.series_windows > 0 (None = all; same forms; bench.py
        --mode series_ab bounds the masked cost). A runtime whose
        `invariant=` is harness.recovery_invariant should keep every
        lane on — a masked lane's windows never fill, so its recovery
        oracle can never fire (the slo_invariant rule).

        span_lanes: which lanes the critical-path attribution plane
        attributes when cfg.span_attr (None = all; same forms; bench.py
        --mode span_ab bounds the masked cost). Like ev_root_t, the
        carried ev_span column is maintained on every lane regardless —
        only the sa_* counter folds are gated — so flipping a lane on
        mid-campaign needs no warm-up.
        """
        seeds = jnp.atleast_1d(jnp.asarray(seeds, jnp.uint32))
        keys = jax.vmap(prng.seed_key)(seeds)
        batched = jax.tree.map(
            lambda a: jnp.broadcast_to(a, (seeds.shape[0],) + a.shape),
            self._template)
        # hash_base keeps the UNCONSUMED seed key frozen beside the
        # splitting trajectory key — the (seed, node) hash-stream root.
        # An owned copy: aliasing keys' buffer would break donation
        batched = batched.replace(key=keys,
                                  hash_base=jnp.array(keys, copy=True))
        if trace_lanes is not None:
            if self.cfg.trace_cap == 0:
                raise ValueError(
                    "trace_lanes given but cfg.trace_cap == 0 — the ring "
                    "is compiled out; set SimConfig(trace_cap=...) > 0")
            mask = self._lane_mask(trace_lanes, int(seeds.shape[0]),
                                   "trace_lanes")
            batched = batched.replace(trace_on=jnp.asarray(mask))
        if profile_lanes is not None:
            if not self.cfg.profile:
                raise ValueError(
                    "profile_lanes given but cfg.profile is False — the "
                    "counter plane is compiled out; set "
                    "SimConfig(profile=True)")
            mask = self._lane_mask(profile_lanes, int(seeds.shape[0]),
                                   "profile_lanes")
            batched = batched.replace(pf_on=jnp.asarray(mask))
        if latency_lanes is not None:
            if self.cfg.latency_hist == 0:
                raise ValueError(
                    "latency_lanes given but cfg.latency_hist == 0 — the "
                    "latency plane is compiled out; set "
                    "SimConfig(latency_hist=...) > 0")
            mask = self._lane_mask(latency_lanes, int(seeds.shape[0]),
                                   "latency_lanes")
            batched = batched.replace(lh_on=jnp.asarray(mask))
        if series_lanes is not None:
            if self.cfg.series_windows == 0:
                raise ValueError(
                    "series_lanes given but cfg.series_windows == 0 — the "
                    "windowed telemetry plane is compiled out; set "
                    "SimConfig(series_windows=...) > 0")
            mask = self._lane_mask(series_lanes, int(seeds.shape[0]),
                                   "series_lanes")
            batched = batched.replace(sr_on=jnp.asarray(mask))
        if span_lanes is not None:
            if not self.cfg.span_attr:
                raise ValueError(
                    "span_lanes given but cfg.span_attr is False — the "
                    "attribution plane is compiled out; set "
                    "SimConfig(span_attr=True)")
            mask = self._lane_mask(span_lanes, int(seeds.shape[0]),
                                   "span_lanes")
            batched = batched.replace(sp_on=jnp.asarray(mask))
        return batched

    def init_single(self, seed: int) -> SimState:
        return self.init_batch(jnp.asarray([seed], jnp.uint32))

    # ------------------------------------------------------------------
    @functools.cached_property
    def _run_chunk(self):
        return {c: self._shared(("chunk", c),
                                functools.partial(self._compile_chunk, c))
                for c in (True, False)}

    def _compile_chunk(self, collect_events: bool):
        # scan over steps of the vmapped step: one XLA program advances the
        # whole batch chunk_len times
        vstep = jax.vmap(self._step)

        def run(state: SimState, chunk_len: int):
            # traced-Python side effect: fires once per retrace, i.e. per
            # fresh executable (modulo persistent-cache compile skips) —
            # the compile counter CI prints and tests assert on
            COMPILE_LOG.note_trace("chunk_runner", collect=collect_events,
                                   chunk=chunk_len,
                                   batch=int(state.halted.shape[0]))

            def body(s, _):
                s, rec = vstep(s)
                return s, (rec if collect_events else 0)
            return jax.lax.scan(body, state, length=chunk_len)

        return jax.jit(run, static_argnums=1, donate_argnums=0)

    @functools.cached_property
    def _fused_runner(self):
        """Whole-sweep-in-one-dispatch runner: a jitted `lax.while_loop`
        whose body is the same vmapped-scan chunk as `_run_chunk` and whose
        predicate — `(chunks_done < n_chunks) & ~halted.all()` — evaluates
        ON-DEVICE. The chunked `run()` pays a device→host round-trip per
        chunk for `bool(state.halted.all())`; here the whole sweep is one
        XLA dispatch with donated buffers, so the host thread returns
        immediately (async dispatch) and the device never idles between
        chunks. Under a sharded batch the predicate's `all()` lowers to a
        cross-chip all-reduce — no host involvement there either.

        `n_chunks` is a traced operand (no recompile per sweep length);
        `chunk_len` is static (scan length must be)."""
        return self._shared("fused", self._compile_fused)

    def _compile_fused(self):
        vstep = jax.vmap(self._step)

        def run(state: SimState, n_chunks, chunk_len: int):
            COMPILE_LOG.note_trace("fused_runner", chunk=chunk_len,
                                   batch=int(state.halted.shape[0]))

            def chunk_body(s, _):
                s, _ = vstep(s)
                return s, None

            def cond(carry):
                i, s = carry
                return (i < n_chunks) & ~s.halted.all()

            def body(carry):
                i, s = carry
                s, _ = jax.lax.scan(chunk_body, s, length=chunk_len)
                return i + 1, s

            _, final = jax.lax.while_loop(
                cond, body, (jnp.asarray(0, jnp.int32), state))
            return final

        return jax.jit(run, static_argnums=2, donate_argnums=0)

    def run_fused(self, state: SimState, max_steps: int,
                  chunk: int = 512,
                  ckpt_every: int | None = None, ckpt_log=None) -> SimState:
        """`run()` without the per-chunk host sync: advance until every
        trajectory halts or ~max_steps events each (rounded up to a chunk
        multiple), as ONE XLA dispatch (see `_fused_runner`).

        Bitwise-equivalent to `run(state, max_steps, chunk)`: the loop
        applies the identical vmapped-scan chunk body under the identical
        continue condition, so final states (and fingerprints) match the
        chunked runner exactly (tests/test_fused.py asserts this).

        Trade-offs vs `run()`: no `collect_events` (a while_loop cannot
        stack per-step records; use `run()`/`run_single` for the full
        stream) and no between-chunk host inspection (use `run()` for
        interactive `inject`/`kill` supervision). The fused path is NOT
        blind, though: with `cfg.trace_cap > 0` the flight-recorder ring
        rides in SimState through the while_loop, so the last trace_cap
        events of every sampled lane come back with the final state
        (obs/rings.py reads them; obs/trace.py exports Perfetto JSON).
        Input buffers are DONATED — do not reuse `state` after calling.
        Works on sharded, non-addressable batches (it is pure SPMD),
        unlike `run_compacting`.

        ckpt_every / ckpt_log (r20, DESIGN §21): when set, the sweep is
        segmented into fused dispatches of ~ckpt_every steps each and a
        per-lane checkpoint (owned host copy of the batch) is harvested
        at each segment boundary — the boundary IS the sync the harvest
        needs, so checkpointing adds exactly the syncs it is paid for
        and the default (off) keeps the single-dispatch shape
        untouched. Trajectories are bit-identical either way: segments
        re-enter the same fused executable and frozen lanes are
        identity (tests/test_timetravel.py holds it).
        """
        n_chunks = -(-max_steps // chunk)
        ckpt_every, ckpt_log = self._ckpt_setup(ckpt_every, ckpt_log)
        if ckpt_every is None:
            return self._fused_runner(state,
                                      jnp.asarray(n_chunks, jnp.int32),
                                      chunk)
        seg = max(1, -(-ckpt_every // chunk))     # chunks per segment
        ckpt_log.harvest(state, steps_done=0)     # entry = zeroth ckpt
        total = 0
        while total < n_chunks:
            m = min(seg, n_chunks - total)
            state = self._fused_runner(state, jnp.asarray(m, jnp.int32),
                                       chunk)
            total += m
            if bool(state.halted.all()):
                break
            if total < n_chunks:   # a post-final harvest is dead weight
                ckpt_log.harvest(state, steps_done=total * chunk)
        return state

    def run_fused_sharded(self, state: SimState, max_steps: int,
                          chunk: int = 512, mesh=None) -> SimState:
        """Lane→shard plumbing (r13): place `state`'s leading [B] lane
        axis over a device mesh and drive it with the fused runner as
        ONE SPMD dispatch. Lanes never talk to each other, so the only
        cross-shard traffic is the while_loop predicate's `halted.all()`
        — an all-reduce per chunk riding ICI (or host threads on a
        virtual CPU mesh), no host round-trips.

        Unlike `parallel.distributed.run_fused_sharded` (which builds
        the batch FROM seeds and handles multi-process assembly), this
        takes an already-built batched state — the entry point the
        sharded fuzz driver needs, where knob mutation has already been
        applied to the init state before it shards. `mesh` defaults to
        a 1-D 'seeds' mesh over every local device; B must divide the
        mesh size. A 1-device mesh is the bitwise-degenerate case: the
        sharded executable computes exactly the unsharded values
        (tests/test_shard.py holds the whole-campaign version of that).
        Input buffers are donated, like `run_fused`."""
        from ..parallel.mesh import seed_mesh, shard_batch
        if mesh is None:
            mesh = seed_mesh()
        return self.run_fused(shard_batch(state, mesh), max_steps, chunk)

    def run(self, state: SimState, max_steps: int, chunk: int = 512,
            collect_events: bool = False, observer=None,
            ckpt_every: int | None = None, ckpt_log=None):
        """Advance until every trajectory halts or ~max_steps events each
        (rounded up to a chunk multiple). Returns (state, events|None).

        Overshoot contract (`collect_events=True`): chunks are always run
        in full and the loop continues while ANY lane is live, so a lane
        that halts early keeps emitting records for every remaining chunk
        of the sweep (not just its own chunk's tail — a lane halting in
        chunk 1 of 8 gets ~7 chunks of frozen records). Those records
        carry `fired=False` — trace consumers must filter on `fired`,
        never on step count (tests/test_fused.py asserts the frozen-lane
        tail is present and `fired=False`).

        observer: optional obs.metrics.SweepObserver — gets an `on_chunk`
        record at every chunk boundary (lanes halted, dispatched
        lane-steps/s wall-clock) and an `on_done` at the end. The hooks
        ride the host sync each chunk ALREADY pays for the
        `halted.all()` test — no new sync points; the only extra cost is
        transferring the [B] halted lane at a boundary the host was
        blocked on anyway.

        ckpt_every / ckpt_log (r20, DESIGN §21): harvest periodic
        per-lane checkpoints — an owned host copy of the whole batch —
        into an `obs.timetravel.CheckpointLog` at the first chunk sync
        on or past each multiple of `ckpt_every` steps. Harvests ride
        the per-chunk host sync this runner already pays (no new sync
        points, the §9 rule); off (the default) costs literally
        nothing. Pass an explicit log to accumulate across runs, or
        just `ckpt_every=K` — the auto-created log is also stashed as
        `self.last_ckpt_log`. Any lane's checkpoint re-seeds via
        `core.state.seed_batch_from` / `obs.timetravel.replay_window`.
        """
        ckpt_every, ckpt_log = self._ckpt_setup(ckpt_every, ckpt_log)
        if ckpt_every is not None:
            # the ENTRY state is the zeroth checkpoint: with it in the
            # log, some checkpoint always precedes any causal root, so
            # time_travel_explain's truncated=False guarantee holds
            # unconditionally (ring capacity allowing). Costs one host
            # copy of a state the host just built — no device sync.
            ckpt_log.harvest(state, steps_done=0)
        next_harvest = ckpt_every
        # always run full chunks: halted trajectories are frozen by the
        # live-mask gating inside the step, so overshooting max_steps is free
        # and avoids a second XLA compile for a partial tail chunk
        runner = self._run_chunk[collect_events]
        events = [] if collect_events else None
        B = state.halted.shape[0]
        done = 0
        k = 0
        t0 = time.perf_counter()
        t_prev = t0
        while done < max_steps:
            state, recs = runner(state, chunk)
            done += chunk
            k += 1
            if collect_events:
                # np.asarray (zero-copy where possible) is safe here:
                # records are runner OUTPUTS and are never donated —
                # only the threaded state is — and the view's base
                # reference keeps the buffer alive. The owned-copy rule
                # (utils/hostcopy) applies to stashes of soon-to-be-
                # donated state, like run_compacting's.
                events.append(jax.tree.map(np.asarray, recs))
            all_halted = bool(state.halted.all())
            if (ckpt_every is not None and done >= next_harvest
                    and not all_halted and done < max_steps):
                # at the sync the halted.all() test just paid; an owned
                # host copy (utils/hostcopy) — the next runner() call
                # donates these buffers. An all-halted batch — or the
                # sweep's final state (done >= max_steps) — is an end
                # state, not a restart point, so it is never harvested
                # (run_fused applies the same post-final rule).
                ckpt_log.harvest(state, steps_done=done)
                next_harvest = done + ckpt_every
            if observer is not None:
                t_now = time.perf_counter()
                observer.on_chunk(dict(
                    kind="chunk", chunk=k, steps_done=done, batch=B,
                    lanes_halted=_halted_count(state),
                    wall_s=t_now - t0,
                    lane_steps_per_sec=B * chunk / max(t_now - t_prev, 1e-9)))
                t_prev = t_now
            if all_halted:
                break
        if observer is not None:
            wall = time.perf_counter() - t0
            rec = dict(
                kind="done", steps_done=done, batch=B, chunks=k,
                lanes_halted=_halted_count(state),
                wall_s=wall,
                lane_steps_per_sec=B * done / max(wall, 1e-9))
            if self.cfg.latency_hist > 0 and getattr(
                    state.halted, "is_fully_addressable", True):
                # the sweep's tail-latency rollup rides the final sync
                # the observer already pays (O(buckets) transfer);
                # skipped on non-addressable multi-process batches,
                # like lanes_halted
                from ..parallel.stats import latency_brief
                lb = latency_brief(state)
                if lb is not None:
                    rec.update(lat_p50=lb["e2e_p50"],
                               lat_p99=lb["e2e_p99"],
                               slo_miss=lb["slo_miss"])
            observer.on_done(rec)
        if collect_events and events:
            events = jax.tree.map(
                lambda *xs: np.concatenate(xs, axis=0), *events)
        return state, events

    def run_compacting(self, state: SimState, max_steps: int,
                       chunk: int = 512, compact_when: float = 0.5,
                       min_batch: int = 256, observer=None):
        """Like run(), but with divergent-trajectory early-exit compaction
        (BASELINE.md config 4): when more than `compact_when` of the lanes
        have halted, stash them host-side and re-pack the survivors into a
        smaller batch (padded to a power of two so at most log2(B) distinct
        XLA programs compile). With long-tailed workloads most lanes finish
        early; without compaction they occupy device lanes doing nothing.

        Returns the full-batch final state in the ORIGINAL lane order.

        Single-process only: compaction re-packs lanes through host numpy,
        which requires every shard to be addressable from this process.
        Under multi-process sharding (parallel/distributed.py) run() works
        unchanged — frozen lanes are already ~free there — or compact each
        host's local slice before assembling the global batch.

        observer: optional obs.metrics.SweepObserver — `on_chunk` per
        chunk, `on_compact` at every re-pack (from/to batch widths), and
        `on_done` at the end; hooks ride the per-chunk host sync this
        runner already pays (it transfers the full halted lane anyway).
        """
        leaf = jax.tree.leaves(state)[0]
        if (hasattr(leaf, "is_fully_addressable")
                and not leaf.is_fully_addressable):
            raise ValueError(
                "run_compacting gathers lanes host-side and needs a fully "
                "addressable (single-process) batch; under multi-process "
                "sharding use run(), or compact per-host slices before "
                "assembly")
        runner = self._run_chunk[False]
        B = int(np.asarray(state.halted).shape[0])
        orig_idx = np.arange(B)
        stash: list[tuple[np.ndarray, Any]] = []  # (orig indices, host copy)
        done = 0
        k = 0
        repacks = 0
        stashed_total = 0
        t0 = time.perf_counter()
        t_prev = t0
        while done < max_steps:
            state, _ = runner(state, chunk)
            done += chunk
            k += 1
            halted = np.asarray(state.halted)
            n = halted.shape[0]
            if observer is not None:
                t_now = time.perf_counter()
                # same convention as run(): lanes_halted is a fraction
                # OF `batch` (the current, post-compaction width);
                # stashed lanes are reported separately so global
                # progress is lanes_halted + stashed_total of the
                # original batch, and a h/batch progress bar never
                # exceeds 100%
                observer.on_chunk(dict(
                    kind="chunk", chunk=k, steps_done=done, batch=n,
                    lanes_halted=int(halted.sum()),
                    stashed_total=stashed_total,
                    wall_s=t_now - t0,
                    lane_steps_per_sec=n * chunk / max(t_now - t_prev,
                                                       1e-9)))
                t_prev = t_now
            if halted.all():
                break
            live = int((~halted).sum())
            if n > min_batch and live / n < (1 - compact_when):
                # pad the live set with halted lanes up to a power of two
                # (frozen lanes are ~free); stash the rest host-side
                target = max(min_batch, 1 << int(np.ceil(np.log2(live))))
                if target < n:
                    live_idx = np.nonzero(~halted)[0]
                    pad_idx = np.nonzero(halted)[0][:target - live]
                    keep = np.concatenate([live_idx, pad_idx])
                    drop = np.setdiff1d(np.arange(n), keep)
                    # OWNED copies, not np.asarray views: the next
                    # runner() call DONATES the state buffers — a
                    # stashed view would read recycled memory (the PR-2
                    # warm-compile-cache bug class; utils/hostcopy.py
                    # documents it)
                    host = owned_host_copy(state)
                    stash.append((orig_idx[drop],
                                  jax.tree.map(lambda a: a[drop], host)))
                    state = jax.tree.map(lambda a: jnp.asarray(a[keep]), host)
                    orig_idx = orig_idx[keep]
                    repacks += 1
                    stashed_total += len(drop)
                    if observer is not None:
                        observer.on_compact(dict(
                            kind="compact", steps_done=done,
                            from_batch=n, to_batch=target,
                            stashed=len(drop), stashed_total=stashed_total,
                            wall_s=time.perf_counter() - t0))
        if observer is not None:
            wall = time.perf_counter() - t0
            # done is batch-global: every stashed lane is halted by
            # construction, so halted-in-final + stashed is of B
            observer.on_done(dict(
                kind="done", steps_done=done, batch=B, chunks=k,
                repacks=repacks,
                lanes_halted=int(np.asarray(state.halted).sum())
                + stashed_total,
                stashed_total=stashed_total,
                wall_s=wall))
        # merge: stashed lanes + final state, back in original order
        # (owned copies for the same donation-aliasing reason as above)
        final_host = owned_host_copy(state)
        parts = stash + [(orig_idx, final_host)]
        order = np.concatenate([p[0] for p in parts])
        inv = np.argsort(order)

        def merge(*leaves):
            return jnp.asarray(np.concatenate(leaves, axis=0)[inv])

        return jax.tree.map(merge, *[p[1] for p in parts])

    def run_single(self, seed: int, max_steps: int, chunk: int = 512,
                   collect_events: bool = True):
        """Debug path: one seed, optionally with the event trace — the
        single-seed replay used to debug a failing seed (the env_logger +
        MADSIM_TEST_SEED repro analog)."""
        state = self.init_single(seed)
        return self.run(state, max_steps, chunk, collect_events)

    def state_at(self, seed: int, step: int):
        """Time travel: the exact state after `step` events of `seed`.

        Decomposes `step` into power-of-two chunks so at most log2(step)
        distinct chunk lengths ever compile (each cached per Runtime) —
        an arbitrary step count never costs an arbitrary-length compile.
        Pair with `find_divergence` / `run_single(collect_events=True)`:
        localize a step, then inspect the full cluster state right there.
        The one exact-step loop, shared with the r20 replay plane
        (`obs.timetravel.advance_exact` — this call is the uncapped
        single-lane case).
        """
        from ..obs.timetravel import advance_exact
        return advance_exact(self, self.init_single(seed), step,
                             chunk=1 << 30)

    # ------------------------------------------------------------------
    # Imperative supervisor surface (Handle::kill/... runtime/mod.rs:200-256)
    # for host-driven scenarios: injects a supervisor op into every
    # trajectory's event table at its current virtual time; it dispatches on
    # the next step. Prefer Scenario for anything that can be pre-scripted
    # (it stays entirely on-device); this is for interactive control between
    # run() chunks.
    @functools.cached_property
    def _inject(self):
        return self._shared("inject", self._compile_inject)

    def _compile_inject(self):
        from ..core import types as Ty
        from ..ops.select import first_k_free

        cfg = self.cfg

        def one(state, op, node, src, payload):
            free = state.t_kind == Ty.EV_FREE
            slots, ok = first_k_free(free, 1)
            slot, ok = slots[0], ok[0]
            w = ok & ~state.halted
            lineage = {}
            if cfg.trace_cap > 0:
                # host-injected ops are EXTERNAL causes (parent -1,
                # carried clock 0) — without this the reused slot would
                # keep a stale parent from its previous occupant
                lineage = dict(
                    ev_prov=state.ev_prov.at[slot].set(
                        jnp.where(w, jnp.asarray([-1, 0], jnp.int32),
                                  state.ev_prov[slot])))
            if cfg.latency_hist > 0:
                # same external-cause contract for the latency plane:
                # the injected op's root time (-1 = unset) is minted at
                # its own dispatch, not inherited from the slot's
                # previous occupant
                lineage["ev_root_t"] = state.ev_root_t.at[slot].set(
                    jnp.where(w, jnp.asarray(-1, jnp.int32),
                              state.ev_root_t[slot]))
            if cfg.span_attr:
                # and for the span plane: an injected op starts a fresh
                # chain — nothing accumulated, no dominant segment, no
                # emitter stamp
                lineage["ev_span"] = state.ev_span.at[slot].set(
                    jnp.where(w,
                              jnp.asarray([0, 0, 0, -1, 0, -1], jnp.int32),
                              state.ev_span[slot]))
            return state.replace(
                **lineage,
                t_deadline=state.t_deadline.at[slot].set(
                    jnp.where(w, state.now, state.t_deadline[slot])),
                t_kind=state.t_kind.at[slot].set(
                    jnp.where(w, Ty.EV_SUPER,
                              state.t_kind[slot]).astype(state.t_kind.dtype)),
                t_node=state.t_node.at[slot].set(
                    jnp.where(w, node,
                              state.t_node[slot]).astype(state.t_node.dtype)),
                t_src=state.t_src.at[slot].set(
                    jnp.where(w, src,
                              state.t_src[slot]).astype(state.t_src.dtype)),
                t_tag=state.t_tag.at[slot].set(
                    jnp.where(w, op, state.t_tag[slot])),
                t_payload=state.t_payload.at[slot].set(
                    jnp.where(w, payload, state.t_payload[slot])),
                oops=state.oops | jnp.where(~ok & ~state.halted,
                                            Ty.OOPS_EVENT_OVERFLOW, 0),
            )

        return jax.jit(jax.vmap(one, in_axes=(0, None, None, None, None)))

    def inject(self, state: SimState, op: int, node: int = 0, src: int = 0,
               payload=()) -> SimState:
        pw = np.zeros(self.cfg.payload_words, np.int32)
        pw[:len(payload)] = payload
        return self._inject(state, jnp.asarray(op, jnp.int32),
                            jnp.asarray(node, jnp.int32),
                            jnp.asarray(src, jnp.int32), jnp.asarray(pw))

    def kill(self, state, node):
        return self.inject(state, T.OP_KILL, node)

    def restart(self, state, node):
        return self.inject(state, T.OP_RESTART, node)

    def pause(self, state, node):
        return self.inject(state, T.OP_PAUSE, node)

    def resume(self, state, node):
        return self.inject(state, T.OP_RESUME, node)

    def clog_link(self, state, src, dst):
        return self.inject(state, T.OP_CLOG_LINK, dst, src)

    def heal(self, state):
        return self.inject(state, T.OP_HEAL)

    def set_time_limit(self, state: SimState, limit: int) -> SimState:
        """Move the virtual-time limit of every trajectory (the
        runtime/mod.rs:175-177 set_time_limit analog). The limit is dynamic
        state, so no recompile: both the hard-stop check and the auto-HALT
        scenario row (identified by sitting exactly at the current limit)
        are rewritten in place."""
        limit = jnp.asarray(limit, jnp.int32)
        auto = ((state.t_kind == T.EV_SUPER) & (state.t_tag == T.OP_HALT)
                & (state.t_deadline == jnp.expand_dims(state.tlimit, -1)))
        return state.replace(
            tlimit=jnp.full_like(state.tlimit, limit),
            t_deadline=jnp.where(auto, limit, state.t_deadline))

    def set_slo_target(self, state: SimState, target: int) -> SimState:
        """Retune every trajectory's SLO target (ticks; 0 disables the
        miss counter) — slo_target is dynamic state like tlimit, so no
        recompile. Requires the latency plane compiled in
        (cfg.latency_hist > 0): a target with no histograms to miss
        against would silently count nothing."""
        if self.cfg.latency_hist == 0:
            raise ValueError(
                "set_slo_target needs cfg.latency_hist > 0 — the latency "
                "plane is compiled out")
        return state.replace(
            slo_target=jnp.full_like(state.slo_target, int(target)))

    def set_window_len(self, state: SimState, ticks: int) -> SimState:
        """Retune every trajectory's series window length (virtual ticks
        per window) — window_len is dynamic state like slo_target, so no
        recompile (the r8 structural/dynamic discipline: the window
        COUNT shapes the program, the window LENGTH rides as an
        operand). Requires the windowed telemetry plane compiled in
        (cfg.series_windows > 0). Retuning MID-RUN re-buckets only
        future dispatches — already-folded windows keep their old
        boundaries — so retune between sweeps, not inside one, unless
        a mixed axis is what you want."""
        if self.cfg.series_windows == 0:
            raise ValueError(
                "set_window_len needs cfg.series_windows > 0 — the "
                "windowed telemetry plane is compiled out")
        if int(ticks) < 1:
            raise ValueError("window_len must be >= 1 tick")
        return state.replace(
            window_len=jnp.full_like(state.window_len, int(ticks)))

    # ------------------------------------------------------------------
    def fingerprints(self, state: SimState) -> np.ndarray:
        """uint32 fingerprint per trajectory (determinism checks). Uses
        the ONE process-level jitted fingerprint (utils/hashing): the old
        per-call `jax.jit(jax.vmap(...))` retraced on every invocation."""
        return np.asarray(batch_fingerprints(state))

    def check_determinism(self, seed: int, max_steps: int,
                          net_override=None) -> bool:
        """Run the same seed twice and bitwise-compare final state — the
        enable_determinism_check analog (runtime/mod.rs:144-187), but over
        the full state rather than the RNG draw log. `net_override` (a
        NetConfig) is applied to both replays so the check validates the
        same fault model the test actually ran."""
        from ..harness.simtest import apply_net_override

        def once():
            s = apply_net_override(self.init_single(seed), net_override,
                                   cfg=self.cfg)
            s, _ = self.run(s, max_steps, collect_events=False)
            return s

        return bool((self.fingerprints(once())
                     == self.fingerprints(once())).all())
