"""Real-world runtime: the production twin of the simulator.

madsim's signature property is compile-time world-switching — the same
application source runs inside the simulator or against real tokio/TCP with
zero changes (madsim/src/lib.rs:15-24 selects `mod sim` vs `mod std`;
std/net/tcp.rs is the real Endpoint). The analog here: the SAME `Program`
subclasses (state machines over jnp ops, which execute eagerly on concrete
arrays) run either vectorized under jit (runtime/runtime.py) or against real
wall-clock time and real sockets via this asyncio runtime. Protocol code
is written once; the world is chosen at Runtime-construction time.

Transports are pluggable (real/transport.py — the std/net/mod.rs seam):
"udp", "tcp", and the in-memory "local" backend ship; new ones register
without editing this file.

Wire format: little-endian int32s [tag, src_node, payload[P]] — the
tag-matched datagram model of the reference's real TCP backend
(std/net/tcp.rs frames [len][tag][payload]), minus streams (UDP fits the
sim's message semantics; loss/reorder are real-network properties here).

Durability: with `data_dir` set, persist-marked state leaves are spilled
to disk after every event (write-fsync-rename, so a kill -9 of the whole
OS process can never observe a torn file) and reloaded on node start —
the std/fs.rs twin (fs.rs:1-60 backs sim files with real ones). Because
fs.py keeps page-cache and disk-view as SEPARATE leaves and only sync_all
copies cache->disk, spilling the persist leaves (the disk views) after
each event makes on-disk state exactly "stable storage as of the last
sync": unsynced writes die with the process, synced ones survive it.
"""

from __future__ import annotations

import os
import struct
import time
from typing import Any, Sequence

import asyncio

import jax.numpy as jnp
import numpy as np

from ..core import prng
from ..core import types as T
from ..core.api import Ctx, Program
from .transport import TRANSPORTS


class _Staged:
    """Ctx-shaped view over a compiled handler's returned effects."""

    __slots__ = ("state", "_sends", "_timers", "_cancels", "_crash",
                 "_crash_code", "_halt")

    def __init__(self, state, sends, timers, cancels, crash, crash_code,
                 halt):
        self.state = state
        self._sends, self._timers, self._cancels = sends, timers, cancels
        self._crash, self._crash_code, self._halt = crash, crash_code, halt


class RealNode:
    def __init__(self, node_id: int, state):
        self.id = node_id
        self.state = state
        self.alive = False
        self.paused = False
        self.parked: list = []         # events deferred while paused
        self.timers: list[tuple[int, asyncio.TimerHandle]] = []  # (tag, h)


class RealRuntime:
    """Run programs against real time + real sockets on 127.0.0.1.

    API mirrors the simulator Runtime's supervisor surface
    (kill/restart/pause/resume — runtime/mod.rs:200-256) but every operation
    is a real effect: sockets close, wall-clock timers cancel.
    """

    def __init__(self, cfg: T.SimConfig, programs: Sequence[Program],
                 state_spec: Any, node_prog=None, base_port: int = 19200,
                 seed: int = 0, transport: str = "udp",
                 persist: Any = None, loss: float = 0.0,
                 data_dir: str | None = None, compiled: bool = False):
        assert transport in TRANSPORTS, \
            f"unknown transport {transport!r}; registered: " \
            f"{sorted(TRANSPORTS)}"
        self.transport = transport
        self.cfg = cfg
        self.programs = list(programs)
        self.node_prog = list(node_prog if node_prog is not None
                              else [0] * cfg.n_nodes)
        self.spec = state_spec
        self.base_port = base_port
        # persist: same pytree-of-bools as the simulator Runtime — leaves
        # marked True survive restart() (the std/fs.rs stable-storage twin:
        # process memory dies, "disk" doesn't). With data_dir they also
        # survive death of this whole OS process.
        self.persist = persist
        self.data_dir = data_dir
        if data_dir is not None:
            assert persist is not None, "data_dir requires a persist spec"
            os.makedirs(data_dir, exist_ok=True)
        # loss: drop this fraction of outgoing datagrams — loopback is
        # near-lossless, so injected loss is how real-world tests exercise
        # retry paths with real sockets
        self.loss = float(loss)
        import random as _random
        self._loss_rng = _random.Random(seed)
        self.key = prng.seed_key(seed)
        self.nodes = [RealNode(i, self._boot_state(i))
                      for i in range(cfg.n_nodes)]
        self.t0 = time.monotonic()
        self.crashed: list[tuple[int, int]] = []   # (node, code)
        self._halted = asyncio.Event()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._net = TRANSPORTS[transport](cfg.n_nodes, base_port,
                                          self._on_packet)
        # compiled dispatch: jit each (program, handler-kind) once and run
        # events through XLA instead of eager op dispatch — measured
        # 3.4x on the echo workload (bench.py --realworld: ~0.9ms vs
        # ~3.2ms per handler event on a 1-core box; remaining cost is
        # jit-call overhead + host sync + asyncio, not the ops) — toward
        # the real-mode performance the reference gets from Rust. Opt-in: the
        # first event of each combo pays its compile, which short demo
        # runs may not amortize. Programs are trace-safe by construction
        # (they run under vmap+jit in the simulator), so behavior is
        # identical; effects come back as staged pytrees with concrete
        # masks and the apply loop below is unchanged.
        self.compiled = bool(compiled)
        self._compiled_fns: dict[tuple[int, str], Any] = {}

    # ------------------------------------------------------------------
    def _fresh_state(self):
        return {k: jnp.asarray(v) for k, v in self.spec.items()} \
            if isinstance(self.spec, dict) else \
            __import__("jax").tree.map(lambda a: jnp.asarray(a), self.spec)

    def _boot_state(self, i: int):
        fresh = self._fresh_state()
        if self.data_dir is None:
            return fresh
        return self._load_persist(i, fresh)

    def now(self) -> int:
        """Virtual-time API, real clock: ticks (us) since runtime start."""
        return int((time.monotonic() - self.t0) * T.TICKS_PER_SEC)

    def _next_key(self):
        self.key, k = prng.split(self.key)
        return k

    # -- on-disk stable storage (std/fs.rs twin) ------------------------
    def _disk_path(self, i: int) -> str:
        return os.path.join(self.data_dir, f"node{i}.npz")

    def _persist_items(self, state):
        """(key, array) for every persist-marked leaf, stable order."""
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(state)
        keep = jax.tree_util.tree_leaves(self.persist)
        assert len(keep) == len(leaves), "persist spec shape mismatch"
        return [(f"leaf{ix}", lf) for ix, (lf, k)
                in enumerate(zip(leaves, keep)) if k], treedef

    def _save_persist(self, i: int):
        import io
        items, _ = self._persist_items(self.nodes[i].state)
        vals = [np.asarray(v) for _, v in items]
        # most events never touch stable storage (fs.py's disk views only
        # change on sync_all/set_len): skip the serialize+fsync when the
        # persist leaves are bit-identical to what's already on disk —
        # a cheap host compare instead of an fsync per dispatched event
        prev = getattr(self, "_persist_cache", {}).get(i)
        if prev is not None and len(prev) == len(vals) and all(
                np.array_equal(a, b) for a, b in zip(prev, vals)):
            return
        buf = io.BytesIO()
        np.savez(buf, **{k: v for (k, _), v in zip(items, vals)})
        tmp = self._disk_path(i) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(buf.getvalue())
            f.flush()
            os.fsync(f.fileno())      # the sync in sync_all made durable
        os.replace(tmp, self._disk_path(i))   # atomic: never a torn file
        if not hasattr(self, "_persist_cache"):
            self._persist_cache = {}
        self._persist_cache[i] = vals

    def _load_persist(self, i: int, fresh):
        import jax
        path = self._disk_path(i)
        if not os.path.exists(path):
            return fresh
        with np.load(path) as z:
            saved = dict(z)
        leaves, treedef = jax.tree_util.tree_flatten(fresh)
        keep = jax.tree_util.tree_leaves(self.persist)
        out = [jnp.asarray(saved[f"leaf{ix}"])
               if k and f"leaf{ix}" in saved else lf
               for ix, (lf, k) in enumerate(zip(leaves, keep))]
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- lifecycle (Handle analog) -------------------------------------
    async def start_node(self, i: int):
        await self._net.start_node(i)
        self.nodes[i].alive = True
        self._dispatch(i, "init")

    def kill(self, i: int):
        n = self.nodes[i]
        n.alive = False
        n.paused = False
        n.parked.clear()
        for _, h in n.timers:
            h.cancel()
        n.timers.clear()
        self._net.close_node(i)

    async def restart(self, i: int):
        self.kill(i)
        old = self.nodes[i].state
        fresh = self._fresh_state()                # process memory is lost
        if self.data_dir is not None:
            # stable storage IS the disk file — reload it, exactly what a
            # new process would see after kill -9
            fresh = self._load_persist(i, fresh)
        elif self.persist is not None:             # in-process stable store
            import jax
            fresh = jax.tree.map(
                lambda f, o, keep: o if keep else f, fresh, old,
                self.persist)
        self.nodes[i].state = fresh
        await self.start_node(i)

    def pause(self, i: int):
        self.nodes[i].paused = True

    def resume(self, i: int):
        n = self.nodes[i]
        n.paused = False
        parked, n.parked = n.parked, []
        for kind, args in parked:
            self._dispatch(i, kind, *args)

    # -- event plumbing -------------------------------------------------
    def _on_packet(self, node: int, data: bytes):
        P = self.cfg.payload_words
        tag, src, *payload = struct.unpack(f"<ii{P}i", data)
        self._dispatch(node, "message", src, tag,
                       jnp.asarray(payload, jnp.int32))

    def _get_compiled(self, p_idx: int, kind: str):
        """jit of one (program, handler-kind): (state, node, now, key,
        src, tag, payload) -> (state', sends, timers, cancels, crash,
        crash_code, halt). Effect lists have static length per trace, so
        they return as pytrees of concrete arrays; the apply loop below
        consumes them exactly like an eager Ctx."""
        fn = self._compiled_fns.get((p_idx, kind))
        if fn is None:
            import jax
            prog = self.programs[p_idx]
            cfg = self.cfg

            def run(state, node, now, key, src, tag, payload):
                ctx = Ctx(cfg, node, now, key, state)
                self._invoke(prog, ctx, kind, src, tag, payload)
                return (ctx.state, ctx._sends, ctx._timers, ctx._cancels,
                        ctx._crash, ctx._crash_code, ctx._halt)

            fn = jax.jit(run)
            self._compiled_fns[(p_idx, kind)] = fn
        return fn

    @staticmethod
    def _invoke(prog, ctx, kind, src, tag, payload):
        """The one handler-kind dispatch, shared by the compiled and
        eager paths so they can never diverge."""
        if kind == "init":
            prog.init(ctx)
        elif kind == "message":
            prog.on_message(ctx, src, tag, payload)
        else:
            prog.on_timer(ctx, tag, payload)

    def _warm_compiled(self):
        """Compile every (program-in-use, kind) combo up front — XLA
        compiles are seconds-long and would otherwise run synchronously
        inside the event loop on each combo's FIRST event, firing every
        node's timers late in a burst mid-protocol. Dummy inputs on the
        fresh state template; handlers are pure, results discarded; the
        fixed key leaves the runtime's real key stream untouched."""
        P = self.cfg.payload_words
        dummy = (self._fresh_state(), jnp.asarray(0, jnp.int32),
                 jnp.asarray(0, jnp.int32), prng.seed_key(0xC0FFEE),
                 jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
                 jnp.zeros((P,), jnp.int32))
        for p_idx in sorted(set(self.node_prog)):
            for kind in ("init", "message", "timer"):
                self._get_compiled(p_idx, kind)(*dummy)

    def _dispatch(self, node: int, kind: str, *args):
        n = self.nodes[node]
        if not n.alive:
            return
        if n.paused:
            n.parked.append((kind, args))
            return
        p_idx = self.node_prog[node]
        node_j = jnp.asarray(node, jnp.int32)
        now_j = jnp.asarray(self.now(), jnp.int32)
        if self.compiled:
            P = self.cfg.payload_words
            if kind == "init":
                src, tag, pl = 0, 0, jnp.zeros((P,), jnp.int32)
            elif kind == "message":
                src, tag, pl = args[0], args[1], args[2]
            else:
                src, tag, pl = 0, args[0], args[1]
            out = self._get_compiled(p_idx, kind)(
                n.state, node_j, now_j, self._next_key(),
                jnp.asarray(src, jnp.int32), jnp.asarray(tag, jnp.int32),
                pl)
            self._apply(n, _Staged(*out))
            return
        prog = self.programs[p_idx]
        ctx = Ctx(self.cfg, node_j, now_j, self._next_key(), n.state)
        if kind == "init":
            src, tag, pl = None, None, None
        elif kind == "message":
            src = jnp.asarray(args[0], jnp.int32)
            tag, pl = jnp.asarray(args[1], jnp.int32), args[2]
        else:
            src = None
            tag, pl = jnp.asarray(args[0], jnp.int32), args[1]
        self._invoke(prog, ctx, kind, src, tag, pl)
        self._apply(n, ctx)

    def _apply(self, n: RealNode, ctx: Ctx):
        P = self.cfg.payload_words
        n.state = ctx.state
        if self.data_dir is not None:
            # spill stable storage BEFORE effects escape: an ack that
            # promises durability must not be sent while the synced bytes
            # exist only in this process's memory
            self._save_persist(n.id)
        for e in ctx._sends:
            if not bool(e["m"]):
                continue
            dst = int(e["dst"])
            if not (0 <= dst < self.cfg.n_nodes) or not n.alive:
                continue
            if self.loss and self._loss_rng.random() < self.loss:
                continue  # injected packet loss (real networks drop; loopback won't)
            pkt = struct.pack(f"<ii{P}i", int(e["tag"]), n.id,
                              *np.asarray(e["payload"], np.int32))
            # real send: straight to the peer; latency, loss, and
            # reordering are whatever the real backend does
            self._net.send(n.id, dst, pkt)
        for e in ctx._cancels:
            if not bool(e["m"]):
                continue
            # Sleep::reset/abort analog: wall-clock timers really cancel.
            # Also purge matching timer events parked by pause() — their
            # handles are already spent, but the event must not fire at
            # resume (narrows the inherent wall-clock-vs-virtual-schedule
            # divergence; exact schedule equivalence across worlds is
            # not a goal — the real world has no tie-break scheduler).
            t = int(e["tag"])
            for tag_i, h in n.timers:
                if tag_i == t:
                    h.cancel()
            n.timers = [(tg, h) for tg, h in n.timers if tg != t]
            n.parked = [(kind, args) for kind, args in n.parked
                        if not (kind == "timer" and int(args[0]) == t)]
        for e in ctx._timers:
            if not bool(e["m"]):
                continue
            delay = int(e["delay"]) / T.TICKS_PER_SEC
            tag = jnp.asarray(int(e["tag"]), jnp.int32)
            payload = e["payload"]
            entry = []

            def fire(n=n, tag=tag, payload=payload, entry=entry):
                # self-prune: spent handles must not accumulate (a
                # periodic timer would otherwise grow the list per fire)
                if entry and entry[0] in n.timers:
                    n.timers.remove(entry[0])
                self._dispatch(n.id, "timer", tag, payload)

            h = self._loop.call_later(delay, fire)
            entry.append((int(e["tag"]), h))
            n.timers.append(entry[0])
        if bool(ctx._crash):
            self.crashed.append((n.id, int(ctx._crash_code)))
            self._halted.set()
        if bool(ctx._halt):
            self._halted.set()

    # -- entry point ----------------------------------------------------
    async def start(self, nodes: Sequence[int] | None = None):
        """Begin real-time execution on the CURRENT event loop: bind the
        loop (timers dispatch via call_later), zero the clock origin, and
        start the given nodes (default: all).

        The public entry for custom supervisor scripts — tests and demos
        await this, then drive kill/restart/pause between awaits (the
        block_on-a-supervisor-future shape, runtime/mod.rs:119) — and for
        single-node boots like recovery inspection (start just the
        server, read its recovered state)."""
        if self.compiled:
            self._warm_compiled()      # before sockets/timers exist
        self._loop = asyncio.get_running_loop()
        self.t0 = time.monotonic()
        for i in (range(self.cfg.n_nodes) if nodes is None else nodes):
            await self.start_node(i)

    async def _main(self, duration: float):
        await self.start()
        try:
            await asyncio.wait_for(self._halted.wait(), timeout=duration)
        except asyncio.TimeoutError:
            pass
        for i in range(self.cfg.n_nodes):
            self.kill(i)

    def run(self, duration: float = 2.0):
        """Block until a program halts/crashes or `duration` seconds pass.
        The `#[madsim::main]` real-mode analog (macros lib.rs:46-78)."""
        asyncio.run(self._main(duration))
        return self

    def states(self):
        return [n.state for n in self.nodes]
