"""Real-world runtime: the production twin of the simulator.

madsim's signature property is compile-time world-switching — the same
application source runs inside the simulator or against real tokio/TCP with
zero changes (madsim/src/lib.rs:15-24 selects `mod sim` vs `mod std`;
std/net/tcp.rs is the real Endpoint). The analog here: the SAME `Program`
subclasses (state machines over jnp ops, which execute eagerly on concrete
arrays) run either vectorized under jit (runtime/runtime.py) or against real
wall-clock time and real UDP sockets via this asyncio runtime. Protocol code
is written once; the world is chosen at Runtime-construction time.

Wire format: little-endian int32s [tag, src_node, payload[P]] — the
tag-matched datagram model of the reference's real TCP backend
(std/net/tcp.rs frames [len][tag][payload]), minus streams (UDP fits the
sim's message semantics; loss/reorder are real-network properties here).
"""

from __future__ import annotations

import asyncio
import struct
import time
from typing import Any, Sequence

import jax.numpy as jnp
import numpy as np

from ..core import prng
from ..core import types as T
from ..core.api import Ctx, Program


class _NodeProtocol(asyncio.DatagramProtocol):
    def __init__(self, rt: "RealRuntime", node: int):
        self.rt, self.node = rt, node

    def datagram_received(self, data, addr):
        self.rt._on_datagram(self.node, data)


class RealNode:
    def __init__(self, node_id: int, state):
        self.id = node_id
        self.state = state
        self.alive = False
        self.paused = False
        self.parked: list = []         # events deferred while paused
        self.transport = None          # udp transport
        self.server = None             # tcp server
        self.conns: dict = {}          # tcp: dst -> StreamWriter
        self.conn_locks: dict = {}     # tcp: dst -> Lock (one dial at a time)
        self.tasks: list = []          # tcp reader tasks
        self.timers: list[asyncio.TimerHandle] = []


class RealRuntime:
    """Run programs against real time + UDP on 127.0.0.1.

    API mirrors the simulator Runtime's supervisor surface
    (kill/restart/pause/resume — runtime/mod.rs:200-256) but every operation
    is a real effect: sockets close, wall-clock timers cancel.
    """

    def __init__(self, cfg: T.SimConfig, programs: Sequence[Program],
                 state_spec: Any, node_prog=None, base_port: int = 19200,
                 seed: int = 0, transport: str = "udp",
                 persist: Any = None, loss: float = 0.0):
        assert transport in ("udp", "tcp")
        self.transport = transport
        self.cfg = cfg
        self.programs = list(programs)
        self.node_prog = list(node_prog if node_prog is not None
                              else [0] * cfg.n_nodes)
        self.spec = state_spec
        self.base_port = base_port
        # persist: same pytree-of-bools as the simulator Runtime — leaves
        # marked True survive restart() (the std/fs.rs stable-storage twin:
        # process memory dies, "disk" doesn't)
        self.persist = persist
        # loss: drop this fraction of outgoing datagrams — loopback is
        # near-lossless, so injected loss is how real-world tests exercise
        # retry paths with real sockets
        self.loss = float(loss)
        import random as _random
        self._loss_rng = _random.Random(seed)
        self.key = prng.seed_key(seed)
        self.nodes = [RealNode(i, self._fresh_state())
                      for i in range(cfg.n_nodes)]
        self.t0 = time.monotonic()
        self.crashed: list[tuple[int, int]] = []   # (node, code)
        self._halted = asyncio.Event()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._bg: set = set()          # in-flight tcp send tasks

    # ------------------------------------------------------------------
    def _fresh_state(self):
        return {k: jnp.asarray(v) for k, v in self.spec.items()} \
            if isinstance(self.spec, dict) else \
            __import__("jax").tree.map(lambda a: jnp.asarray(a), self.spec)

    def now(self) -> int:
        """Virtual-time API, real clock: ticks (us) since runtime start."""
        return int((time.monotonic() - self.t0) * T.TICKS_PER_SEC)

    def _next_key(self):
        self.key, k = prng.split(self.key)
        return k

    # -- lifecycle (Handle analog) -------------------------------------
    async def start_node(self, i: int):
        n = self.nodes[i]
        loop = asyncio.get_running_loop()
        if self.transport == "udp":
            n.transport, _ = await loop.create_datagram_endpoint(
                lambda: _NodeProtocol(self, i),
                local_addr=("127.0.0.1", self.base_port + i))
        else:
            # TCP backend: length-delimited frames over lazily-established
            # per-peer connections — the shape of the reference's real TCP
            # Endpoint (std/net/tcp.rs:69-151: connect-on-first-send, a
            # reader task per connection feeding the mailbox)
            n.server = await asyncio.start_server(
                lambda r, w: self._tcp_reader(i, r, w),
                "127.0.0.1", self.base_port + i)
        n.alive = True
        self._dispatch(i, "init")

    async def _tcp_reader(self, node: int, reader, writer):
        n = self.nodes[node]
        task = asyncio.current_task()
        n.tasks.append(task)
        try:
            while True:
                hdr = await reader.readexactly(4)
                (ln,) = struct.unpack("<I", hdr)
                data = await reader.readexactly(ln)
                if self.nodes[node].alive:
                    self._on_datagram(node, data)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            writer.close()
            if task in n.tasks:        # prune on normal close, not just kill
                n.tasks.remove(task)

    async def _tcp_send(self, src: int, dst: int, pkt: bytes):
        n = self.nodes[src]
        if not n.alive:                # killed after the send was queued
            return
        lock = n.conn_locks.setdefault(dst, asyncio.Lock())
        try:
            async with lock:           # one dial per peer at a time — no
                w = n.conns.get(dst)   # duplicate-connection leak on
                if w is None or w.is_closing():  # broadcast bursts
                    _, w = await asyncio.open_connection(
                        "127.0.0.1", self.base_port + dst)
                    if not n.alive:    # killed while dialing
                        w.close()
                        return
                    n.conns[dst] = w
            w.write(struct.pack("<I", len(pkt)) + pkt)
            await w.drain()
        except (ConnectionError, OSError):
            n.conns.pop(dst, None)  # peer down: datagram-like drop

    def kill(self, i: int):
        n = self.nodes[i]
        n.alive = False
        n.paused = False
        n.parked.clear()
        for t in n.timers:
            t.cancel()
        n.timers.clear()
        if n.transport:
            n.transport.close()
            n.transport = None
        if n.server:
            n.server.close()
            n.server = None
        for w in n.conns.values():
            w.close()
        n.conns.clear()
        for t in n.tasks:
            t.cancel()
        n.tasks.clear()

    async def restart(self, i: int):
        self.kill(i)
        old = self.nodes[i].state
        fresh = self._fresh_state()                # process memory is lost
        if self.persist is not None:               # ...stable storage isn't
            import jax
            fresh = jax.tree.map(
                lambda f, o, keep: o if keep else f, fresh, old,
                self.persist)
        self.nodes[i].state = fresh
        await self.start_node(i)

    def pause(self, i: int):
        self.nodes[i].paused = True

    def resume(self, i: int):
        n = self.nodes[i]
        n.paused = False
        parked, n.parked = n.parked, []
        for kind, args in parked:
            self._dispatch(i, kind, *args)

    # -- event plumbing -------------------------------------------------
    def _on_datagram(self, node: int, data: bytes):
        P = self.cfg.payload_words
        tag, src, *payload = struct.unpack(f"<ii{P}i", data)
        self._dispatch(node, "message", src, tag,
                       jnp.asarray(payload, jnp.int32))

    def _dispatch(self, node: int, kind: str, *args):
        n = self.nodes[node]
        if not n.alive:
            return
        if n.paused:
            n.parked.append((kind, args))
            return
        prog = self.programs[self.node_prog[node]]
        ctx = Ctx(self.cfg, jnp.asarray(node, jnp.int32),
                  jnp.asarray(self.now(), jnp.int32), self._next_key(),
                  n.state)
        if kind == "init":
            prog.init(ctx)
        elif kind == "message":
            prog.on_message(ctx, jnp.asarray(args[0], jnp.int32),
                            jnp.asarray(args[1], jnp.int32), args[2])
        else:
            prog.on_timer(ctx, jnp.asarray(args[0], jnp.int32), args[1])
        self._apply(n, ctx)

    def _apply(self, n: RealNode, ctx: Ctx):
        P = self.cfg.payload_words
        n.state = ctx.state
        for e in ctx._sends:
            if not bool(e["m"]):
                continue
            dst = int(e["dst"])
            if not (0 <= dst < self.cfg.n_nodes) or not n.alive:
                continue
            if self.loss and self._loss_rng.random() < self.loss:
                continue  # injected packet loss (real networks drop; loopback won't)
            pkt = struct.pack(f"<ii{P}i", int(e["tag"]), n.id,
                              *np.asarray(e["payload"], np.int32))
            # real send: straight to the peer; latency, loss, and
            # reordering are whatever the real network does
            if self.transport == "udp":
                if n.transport is not None:
                    n.transport.sendto(pkt,
                                       ("127.0.0.1", self.base_port + dst))
            else:
                task = self._loop.create_task(self._tcp_send(n.id, dst, pkt))
                self._bg.add(task)
                task.add_done_callback(self._bg.discard)
        for e in ctx._timers:
            if not bool(e["m"]):
                continue
            delay = int(e["delay"]) / T.TICKS_PER_SEC
            tag = jnp.asarray(int(e["tag"]), jnp.int32)
            payload = e["payload"]
            h = self._loop.call_later(
                delay, self._dispatch, n.id, "timer", tag, payload)
            n.timers.append(h)
        if bool(ctx._crash):
            self.crashed.append((n.id, int(ctx._crash_code)))
            self._halted.set()
        if bool(ctx._halt):
            self._halted.set()

    # -- entry point ----------------------------------------------------
    async def _main(self, duration: float):
        self._loop = asyncio.get_running_loop()
        self.t0 = time.monotonic()
        for i in range(self.cfg.n_nodes):
            await self.start_node(i)
        try:
            await asyncio.wait_for(self._halted.wait(), timeout=duration)
        except asyncio.TimeoutError:
            pass
        for i in range(self.cfg.n_nodes):
            self.kill(i)

    def run(self, duration: float = 2.0):
        """Block until a program halts/crashes or `duration` seconds pass.
        The `#[madsim::main]` real-mode analog (macros lib.rs:46-78)."""
        asyncio.run(self._main(duration))
        return self

    def states(self):
        return [n.state for n in self.nodes]
