"""Real-world runtime: the production twin of the simulator.

madsim's signature property is compile-time world-switching — the same
application source runs inside the simulator or against real tokio/TCP with
zero changes (madsim/src/lib.rs:15-24 selects `mod sim` vs `mod std`;
std/net/tcp.rs is the real Endpoint). The analog here: the SAME `Program`
subclasses (state machines over jnp ops, which execute eagerly on concrete
arrays) run either vectorized under jit (runtime/runtime.py) or against real
wall-clock time and real sockets via this asyncio runtime. Protocol code
is written once; the world is chosen at Runtime-construction time.

Transports are pluggable (real/transport.py — the std/net/mod.rs seam):
"udp", "tcp", and the in-memory "local" backend ship; new ones register
without editing this file.

Wire format: little-endian int32s [tag, src_node, payload[P]] — the
tag-matched datagram model of the reference's real TCP backend
(std/net/tcp.rs frames [len][tag][payload]), minus streams (UDP fits the
sim's message semantics; loss/reorder are real-network properties here).

Durability: with `data_dir` set, persist-marked state leaves are spilled
to disk after every event (write-fsync-rename, so a kill -9 of the whole
OS process can never observe a torn file) and reloaded on node start —
the std/fs.rs twin (fs.rs:1-60 backs sim files with real ones). Because
fs.py keeps page-cache and disk-view as SEPARATE leaves and only sync_all
copies cache->disk, spilling the persist leaves (the disk views) after
each event makes on-disk state exactly "stable storage as of the last
sync": unsynced writes die with the process, synced ones survive it.
"""

from __future__ import annotations

import os
import struct
import time
from typing import Any, Sequence

import asyncio

import jax.numpy as jnp
import numpy as np

from ..core import prng
from ..core import types as T
from ..core.api import Ctx, Program
from .transport import TRANSPORTS


class _Staged:
    """Ctx-shaped view over a compiled handler's returned effects."""

    __slots__ = ("state", "_sends", "_timers", "_cancels", "_crash",
                 "_crash_code", "_halt")

    def __init__(self, state, sends, timers, cancels, crash, crash_code,
                 halt):
        self.state = state
        self._sends, self._timers, self._cancels = sends, timers, cancels
        self._crash, self._crash_code, self._halt = crash, crash_code, halt


class RealNode:
    def __init__(self, node_id: int, state):
        self.id = node_id
        self.state = state
        self.alive = False
        self.paused = False
        self.parked: list = []         # events deferred while paused
        self.timers: list[tuple[int, asyncio.TimerHandle]] = []  # (tag, h)


class RealRuntime:
    """Run programs against real time + real sockets on 127.0.0.1.

    API mirrors the simulator Runtime's supervisor surface
    (kill/restart/pause/resume — runtime/mod.rs:200-256) but every operation
    is a real effect: sockets close, wall-clock timers cancel.
    """

    def __init__(self, cfg: T.SimConfig, programs: Sequence[Program],
                 state_spec: Any, node_prog=None, base_port: int = 19200,
                 seed: int = 0, transport: str = "udp",
                 persist: Any = None, loss: float = 0.0,
                 data_dir: str | None = None, compiled: bool = False,
                 batch_drain: int = 0):
        assert transport in TRANSPORTS, \
            f"unknown transport {transport!r}; registered: " \
            f"{sorted(TRANSPORTS)}"
        self.transport = transport
        self.cfg = cfg
        self.programs = list(programs)
        self.node_prog = list(node_prog if node_prog is not None
                              else [0] * cfg.n_nodes)
        self.spec = state_spec
        self.base_port = base_port
        # persist: same pytree-of-bools as the simulator Runtime — leaves
        # marked True survive restart() (the std/fs.rs stable-storage twin:
        # process memory dies, "disk" doesn't). With data_dir they also
        # survive death of this whole OS process.
        self.persist = persist
        self.data_dir = data_dir
        if data_dir is not None:
            assert persist is not None, "data_dir requires a persist spec"
            os.makedirs(data_dir, exist_ok=True)
        # loss: drop this fraction of outgoing datagrams — loopback is
        # near-lossless, so injected loss is how real-world tests exercise
        # retry paths with real sockets
        self.loss = float(loss)
        import random as _random
        self._loss_rng = _random.Random(seed)
        self.key = prng.seed_key(seed)
        # the frozen seed key beside the splitting draw key: the real
        # twin's Ctx.hash_key root (same (seed, node) derivation as the
        # simulator's SimState.hash_base, so a model's hash streams are
        # bit-identical across the two worlds)
        self.hash_base = prng.seed_key(seed)
        self.nodes = [RealNode(i, self._boot_state(i))
                      for i in range(cfg.n_nodes)]
        self.t0 = time.monotonic()
        self.crashed: list[tuple[int, int]] = []   # (node, code)
        self._halted = asyncio.Event()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._net = TRANSPORTS[transport](cfg.n_nodes, base_port,
                                          self._on_packet)
        # compiled dispatch: jit each (program, handler-kind) once and run
        # events through XLA instead of eager op dispatch — measured
        # 3.4x on the echo workload (bench.py --realworld: ~0.9ms vs
        # ~3.2ms per handler event on a 1-core box; remaining cost is
        # jit-call overhead + host sync + asyncio, not the ops) — toward
        # the real-mode performance the reference gets from Rust. Opt-in: the
        # first event of each combo pays its compile, which short demo
        # runs may not amortize. Programs are trace-safe by construction
        # (they run under vmap+jit in the simulator), so behavior is
        # identical; effects come back as staged pytrees with concrete
        # masks and the apply loop below is unchanged.
        self.compiled = bool(compiled)
        self._compiled_fns: dict[tuple[int, str], Any] = {}
        # batched drain: queue incoming events and run up to `batch_drain`
        # of them through ONE jitted lax.scan per drain instead of one
        # XLA round-trip per event, amortizing the per-call dispatch
        # overhead. Measured scope (PARITY §2.2): this helps
        # concurrency-heavy workloads but does NOT make the twin
        # perf-grade — per-slot XLA work and per-event asyncio/socket
        # costs remain; the simulator is the throughput path by design.
        # Semantics match per-event dispatch except that (a) all events
        # of one drain observe the same `now`, and (b) effects escape
        # after the whole drain's state updates (and persist spills) are
        # done — a strictly stronger durability order. 0 disables.
        assert batch_drain >= 0
        self.batch_drain = int(batch_drain)
        if self.batch_drain:
            self.compiled = True       # drains ARE compiled dispatch
        # coalescing window (seconds): deferring the drain this long lets
        # more deliveries queue behind it, deepening the batch — the
        # latency/throughput trade of any interrupt-coalescing NIC.
        # 0 drains on the next loop pass (minimum latency). ADAPTIVE:
        # the delay is only paid while drains actually observe depth
        # (last drain >= 2 events) — on depth-1 traffic coalescing buys
        # nothing and the delay would throttle a closed loop to
        # ~1/delay events/s (the measured 0.74x-eager ping-pong trap),
        # so the window self-disables until depth reappears.
        self.drain_delay = 0.0
        self._last_drain_depth = 0
        self._queue: list = []
        self._drain_scheduled = False
        self._drain_fn = None
        # [N]-stacked authoritative node state while draining; None when
        # per-node states were mutated outside the drain (boot/restart)
        self._stacked = None

    # ------------------------------------------------------------------
    def _fresh_state(self):
        return {k: jnp.asarray(v) for k, v in self.spec.items()} \
            if isinstance(self.spec, dict) else \
            __import__("jax").tree.map(lambda a: jnp.asarray(a), self.spec)

    def _boot_state(self, i: int):
        fresh = self._fresh_state()
        if self.data_dir is None:
            return fresh
        return self._load_persist(i, fresh)

    def now(self) -> int:
        """Virtual-time API, real clock: ticks (us) since runtime start."""
        return int((time.monotonic() - self.t0) * T.TICKS_PER_SEC)

    def _next_key(self):
        self.key, k = prng.split(self.key)
        return k

    # -- on-disk stable storage (std/fs.rs twin) ------------------------
    def _disk_path(self, i: int) -> str:
        return os.path.join(self.data_dir, f"node{i}.npz")

    def _persist_items(self, state):
        """(key, array) for every persist-marked leaf, stable order."""
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(state)
        keep = jax.tree_util.tree_leaves(self.persist)
        assert len(keep) == len(leaves), "persist spec shape mismatch"
        return [(f"leaf{ix}", lf) for ix, (lf, k)
                in enumerate(zip(leaves, keep)) if k], treedef

    def _save_persist(self, i: int):
        import io
        items, _ = self._persist_items(self.nodes[i].state)
        vals = [np.asarray(v) for _, v in items]
        # most events never touch stable storage (fs.py's disk views only
        # change on sync_all/set_len): skip the serialize+fsync when the
        # persist leaves are bit-identical to what's already on disk —
        # a cheap host compare instead of an fsync per dispatched event
        prev = getattr(self, "_persist_cache", {}).get(i)
        if prev is not None and len(prev) == len(vals) and all(
                np.array_equal(a, b) for a, b in zip(prev, vals)):
            return
        buf = io.BytesIO()
        np.savez(buf, **{k: v for (k, _), v in zip(items, vals)})
        tmp = self._disk_path(i) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(buf.getvalue())
            f.flush()
            os.fsync(f.fileno())      # the sync in sync_all made durable
        os.replace(tmp, self._disk_path(i))   # atomic: never a torn file
        # fsync the directory too: os.replace makes the rename atomic in
        # the namespace, but only a dir fsync makes it durable across
        # whole-OS power loss (process kill -9 alone doesn't need this).
        # Cheap here because no-op spills are already skipped above.
        dfd = os.open(self.data_dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        if not hasattr(self, "_persist_cache"):
            self._persist_cache = {}
        self._persist_cache[i] = vals

    def _load_persist(self, i: int, fresh):
        import jax
        path = self._disk_path(i)
        if not os.path.exists(path):
            return fresh
        with np.load(path) as z:
            saved = dict(z)
        leaves, treedef = jax.tree_util.tree_flatten(fresh)
        keep = jax.tree_util.tree_leaves(self.persist)
        out = [jnp.asarray(saved[f"leaf{ix}"])
               if k and f"leaf{ix}" in saved else lf
               for ix, (lf, k) in enumerate(zip(leaves, keep))]
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- lifecycle (Handle analog) -------------------------------------
    async def start_node(self, i: int):
        await self._net.start_node(i)
        self.nodes[i].alive = True
        self._dispatch(i, "init")

    def kill(self, i: int):
        n = self.nodes[i]
        n.alive = False
        n.paused = False
        n.parked.clear()
        for _, h in n.timers:
            h.cancel()
        n.timers.clear()
        # purge the node's queued drain events too: a killed process's
        # pending events must never fire — without this, a kill+restart
        # inside the coalescing window would run old-incarnation events
        # against the new incarnation's recovered state
        self._queue = [ev for ev in self._queue if ev[0] != i]
        self._net.close_node(i)

    async def restart(self, i: int):
        self.kill(i)
        old = self.nodes[i].state
        fresh = self._fresh_state()                # process memory is lost
        if self.data_dir is not None:
            # stable storage IS the disk file — reload it, exactly what a
            # new process would see after kill -9
            fresh = self._load_persist(i, fresh)
        elif self.persist is not None:             # in-process stable store
            import jax
            fresh = jax.tree.map(
                lambda f, o, keep: o if keep else f, fresh, old,
                self.persist)
        self.nodes[i].state = fresh
        self._stacked = None            # per-node write: restack on drain
        await self.start_node(i)

    def pause(self, i: int):
        self.nodes[i].paused = True

    def resume(self, i: int):
        n = self.nodes[i]
        n.paused = False
        parked, n.parked = n.parked, []
        for kind, args in parked:
            self._dispatch(i, kind, *args)

    # -- event plumbing -------------------------------------------------
    def _on_packet(self, node: int, data: bytes):
        P = self.cfg.payload_words
        # drop malformed/foreign frames like a corrupt datagram: a reused
        # port (or any third-party transport) can hand us bytes that are
        # not an 8+4P frame, and a struct.error here would unwind the
        # transport's reader loop instead of one packet
        if len(data) != 8 + 4 * P:
            return
        tag, src, *payload = struct.unpack(f"<ii{P}i", data)
        self._dispatch(node, "message", src, tag,
                       jnp.asarray(payload, jnp.int32))

    def _get_compiled(self, p_idx: int, kind: str):
        """jit of one (program, handler-kind): (state, node, now, key,
        src, tag, payload) -> (state', sends, timers, cancels, crash,
        crash_code, halt). Effect lists have static length per trace, so
        they return as pytrees of concrete arrays; the apply loop below
        consumes them exactly like an eager Ctx."""
        fn = self._compiled_fns.get((p_idx, kind))
        if fn is None:
            import jax
            prog = self.programs[p_idx]
            cfg = self.cfg

            hash_base = self.hash_base

            def run(state, node, now, key, src, tag, payload):
                ctx = Ctx(cfg, node, now, key, state, hash_base=hash_base)
                self._invoke(prog, ctx, kind, src, tag, payload)
                return (ctx.state, ctx._sends, ctx._timers, ctx._cancels,
                        ctx._crash, ctx._crash_code, ctx._halt)

            fn = jax.jit(run)
            self._compiled_fns[(p_idx, kind)] = fn
        return fn

    @staticmethod
    def _invoke(prog, ctx, kind, src, tag, payload):
        """The one handler-kind dispatch, shared by the compiled and
        eager paths so they can never diverge."""
        if kind == "init":
            prog.init(ctx)
        elif kind == "message":
            prog.on_message(ctx, src, tag, payload)
        else:
            prog.on_timer(ctx, tag, payload)

    def _warm_compiled(self):
        """Compile every (program-in-use, kind) combo up front — XLA
        compiles are seconds-long and would otherwise run synchronously
        inside the event loop on each combo's FIRST event, firing every
        node's timers late in a burst mid-protocol. Dummy inputs on the
        fresh state template; handlers are pure, results discarded; the
        fixed key leaves the runtime's real key stream untouched."""
        P = self.cfg.payload_words
        dummy = (self._fresh_state(), jnp.asarray(0, jnp.int32),
                 jnp.asarray(0, jnp.int32), prng.seed_key(0xC0FFEE),
                 jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
                 jnp.zeros((P,), jnp.int32))
        for p_idx in sorted(set(self.node_prog)):
            for kind in ("init", "message", "timer"):
                self._get_compiled(p_idx, kind)(*dummy)

    def _warm_drain(self):
        """Compile the batched drain for every slot bucket up front (same
        rationale as _warm_compiled; jit caches one program per bucket
        shape, so no mid-protocol compile stall on the first deep/shallow
        queue)."""
        import jax
        P = self.cfg.payload_words
        stacked = jax.tree.map(
            lambda *xs: jnp.stack(xs), *[n.state for n in self.nodes])
        fn = self._get_drain_fn()
        done = set()
        for m in self._BUCKETS + (self.batch_drain,):
            K = self._bucket(m)
            # K=1 is unreachable since the depth-1 bypass (a one-event
            # drain runs per-event compiled dispatch) — don't pay its
            # compile at startup
            if K in done or K == 1:
                continue
            done.add(K)
            out = fn(stacked, jnp.asarray(0, jnp.int32),
                     jnp.zeros((K,), bool), jnp.zeros((K,), jnp.int32),
                     jnp.zeros((K,), jnp.int32), jnp.zeros((K,), jnp.int32),
                     jnp.zeros((K,), jnp.int32), jnp.zeros((K, P),
                                                           jnp.int32),
                     jnp.stack([prng.seed_key(0)] * K))
            jax.block_until_ready(out[0])

    def _dispatch(self, node: int, kind: str, *args):
        n = self.nodes[node]
        if not n.alive:
            return
        if n.paused:
            n.parked.append((kind, args))
            return
        if self.batch_drain:
            P = self.cfg.payload_words
            if kind == "init":
                src, tag, pl = 0, 0, jnp.zeros((P,), jnp.int32)
            elif kind == "message":
                src, tag, pl = args[0], args[1], args[2]
            else:
                src, tag, pl = 0, args[0], args[1]
            self._queue.append((node, {"init": 0, "message": 1,
                                       "timer": 2}[kind],
                                int(src), int(tag), pl))
            if not self._drain_scheduled and self._loop is not None:
                self._drain_scheduled = True
                if self.drain_delay > 0 and self._last_drain_depth >= 2:
                    self._loop.call_later(self.drain_delay, self._drain)
                else:
                    self._loop.call_soon(self._drain)
            return
        p_idx = self.node_prog[node]
        node_j = jnp.asarray(node, jnp.int32)
        now_j = jnp.asarray(self.now(), jnp.int32)
        if self.compiled:
            P = self.cfg.payload_words
            if kind == "init":
                src, tag, pl = 0, 0, jnp.zeros((P,), jnp.int32)
            elif kind == "message":
                src, tag, pl = args[0], args[1], args[2]
            else:
                src, tag, pl = 0, args[0], args[1]
            self._run_compiled_event(n, kind, src, tag, pl)
            return
        prog = self.programs[p_idx]
        ctx = Ctx(self.cfg, node_j, now_j, self._next_key(), n.state,
                  hash_base=self.hash_base)
        if kind == "init":
            src, tag, pl = None, None, None
        elif kind == "message":
            src = jnp.asarray(args[0], jnp.int32)
            tag, pl = jnp.asarray(args[1], jnp.int32), args[2]
        else:
            src = None
            tag, pl = jnp.asarray(args[0], jnp.int32), args[1]
        self._invoke(prog, ctx, kind, src, tag, pl)
        self._apply(n, ctx)

    def _run_compiled_event(self, n: RealNode, kind: str, src, tag, pl):
        """Per-event compiled dispatch tail — the ONE incantation shared
        by _dispatch's compiled branch and _drain's depth-1 bypass, so
        the two paths can never diverge (same rationale as _invoke)."""
        out = self._get_compiled(self.node_prog[n.id], kind)(
            n.state, jnp.asarray(n.id, jnp.int32),
            jnp.asarray(self.now(), jnp.int32), self._next_key(),
            jnp.asarray(src, jnp.int32), jnp.asarray(tag, jnp.int32),
            jnp.asarray(pl, jnp.int32))
        self._apply(n, _Staged(*out))

    # -- batched drain ---------------------------------------------------
    def _get_drain_fn(self):
        """One jitted lax.scan over `batch_drain` event slots. The body is
        the real-world re-telling of the sim step's dispatch phase
        (core/step.py §3): every (program x handler-kind) combo executes
        for every slot, masks decide which commits — here the "batch"
        axis is queued real events instead of seeds, and effects return
        as [K]-stacked staged pytrees for the host to apply in order."""
        if self._drain_fn is not None:
            return self._drain_fn
        import jax
        from jax import lax
        from ..core.step import (EMPTY_CANCEL, EMPTY_SEND, EMPTY_TIMER,
                                 _scatter_node, _slice_node, _where_tree)
        cfg = self.cfg
        programs = self.programs
        node_prog_j = jnp.asarray(self.node_prog, jnp.int32)
        P = cfg.payload_words
        hash_base = self.hash_base
        def body(carry, xs):
            stacked, now = carry
            valid, node, kindc, src, tag, pl, key = xs
            base = _slice_node(stacked, node)
            combos = []
            p_of_node = jnp.sum(
                jnp.where(jnp.arange(cfg.n_nodes) == node, node_prog_j, 0))
            for p_idx, prog in enumerate(programs):
                pmask = p_of_node == p_idx
                for code, run in (
                        (0, lambda c: prog.init(c)),
                        (1, lambda c: prog.on_message(c, src, tag, pl)),
                        (2, lambda c: prog.on_timer(c, tag, pl))):
                    ctx = Ctx(cfg, node, now, key, base,
                              hash_base=hash_base)
                    run(ctx)
                    combos.append((valid & pmask & (kindc == code), ctx))
            any_h = jnp.asarray(False)
            new_slice = base
            crash = jnp.asarray(False)
            crash_code = jnp.asarray(0, jnp.int32)
            halt = jnp.asarray(False)
            ns = max((len(c._sends) for _, c in combos), default=0)
            nt = max((len(c._timers) for _, c in combos), default=0)
            nc = max((len(c._cancels) for _, c in combos), default=0)
            sends = [EMPTY_SEND(P) for _ in range(ns)]
            timers = [EMPTY_TIMER(P) for _ in range(nt)]
            cancels = [EMPTY_CANCEL() for _ in range(nc)]
            for m, ctx in combos:
                any_h = any_h | m
                new_slice = _where_tree(m, ctx.state, new_slice)
                crash = crash | (m & ctx._crash)
                crash_code = jnp.where(m & ctx._crash, ctx._crash_code,
                                       crash_code)
                halt = halt | (m & ctx._halt)
                for j, e in enumerate(ctx._sends):
                    sends[j] = _where_tree(m, dict(e, m=e["m"] & m),
                                           sends[j])
                for j, e in enumerate(ctx._timers):
                    timers[j] = _where_tree(m, dict(e, m=e["m"] & m),
                                            timers[j])
                for j, e in enumerate(ctx._cancels):
                    cancels[j] = _where_tree(m, dict(e, m=e["m"] & m),
                                             cancels[j])
            stacked = _scatter_node(stacked, node, new_slice, any_h)
            return (stacked, now), (sends, timers, cancels, crash,
                                    crash_code, halt)

        def drain(stacked, now, valid, node, kindc, src, tag, pl, keys):
            (stacked, _), eff = lax.scan(
                body, (stacked, now),
                (valid, node, kindc, src, tag, pl, keys))
            return stacked, eff

        self._drain_fn = jax.jit(drain)
        return self._drain_fn

    _BUCKETS = (1, 4, 16, 64, 256)

    def _bucket(self, m: int) -> int:
        """Smallest slot-count bucket holding m events (jit re-specializes
        per shape, so each bucket compiles once — warmed up front — and a
        shallow queue never pays the full-depth scan)."""
        for k in self._BUCKETS:
            if m <= k or k >= self.batch_drain:
                return min(k, self.batch_drain)
        return self.batch_drain

    def _get_stacked(self):
        if self._stacked is None:
            import jax
            self._stacked = jax.tree.map(
                lambda *xs: jnp.stack(xs), *[n.state for n in self.nodes])
        return self._stacked

    def _drain(self):
        self._drain_scheduled = False
        if not self._queue or self._loop is None:
            return
        K = self.batch_drain
        take, self._queue = self._queue[:K], self._queue[K:]
        if self._queue:                 # more than one drain's worth
            self._drain_scheduled = True
            self._loop.call_soon(self._drain)
        events = []
        for ev in take:
            n = self.nodes[ev[0]]
            if not n.alive:             # killed after enqueue: drop
                continue
            if n.paused:                # paused after enqueue: park
                kind = ("init", "message", "timer")[ev[1]]
                args = (() if ev[1] == 0 else
                        (ev[2], ev[3], ev[4]) if ev[1] == 1 else
                        (ev[3], ev[4]))
                n.parked.append((kind, args))
                continue
            events.append(ev)
        # adaptive coalescing signal: LIVE depth only — dead/parked
        # events didn't run, so counting them would keep the delay
        # engaged on traffic that observes no real depth
        self._last_drain_depth = len(events)
        if not events:
            return
        if len(events) == 1:
            # depth-1 guard rail: a one-event scan amortizes nothing and
            # pays the stacked-state round-trip (measured 0.64x eager on
            # the depth-1 ping-pong shape, BENCH_realworld_r04) — run the
            # event through per-event compiled dispatch instead. Key draw
            # order is identical (one key per event in both modes).
            node, kc, src, tag, pl = events[0]
            self._stacked = None        # per-node write: restack on drain
            self._run_compiled_event(self.nodes[node],
                                     ("init", "message", "timer")[kc],
                                     src, tag, pl)
            return
        import jax
        m = len(events)
        B = self._bucket(m)
        P = self.cfg.payload_words
        valid = np.zeros(B, bool)
        valid[:m] = True
        nodei = np.zeros(B, np.int32)
        kindc = np.zeros(B, np.int32)
        srca = np.zeros(B, np.int32)
        taga = np.zeros(B, np.int32)
        pla = np.zeros((B, P), np.int32)
        keys = [prng.seed_key(0)] * B
        for j, (node, kc, src, tag, pl) in enumerate(events):
            nodei[j], kindc[j], srca[j], taga[j] = node, kc, src, tag
            pla[j] = np.asarray(pl, np.int32)
            keys[j] = self._next_key()  # same draw order as per-event mode
        stacked, eff = self._get_drain_fn()(
            self._get_stacked(), jnp.asarray(self.now(), jnp.int32),
            jnp.asarray(valid), jnp.asarray(nodei), jnp.asarray(kindc),
            jnp.asarray(srca), jnp.asarray(taga), jnp.asarray(pla),
            jnp.stack(keys))
        self._stacked = stacked         # stays authoritative across drains
        sends, timers, cancels, crash, crash_code, halt = eff
        # sync ONLY touched per-node rows (others are bit-identical); spill
        # stable storage for all touched nodes BEFORE any send escapes —
        # the per-event durability order, at drain granularity
        touched = sorted({node for node, *_ in events})
        for i in touched:
            self.nodes[i].state = jax.tree.map(lambda a: a[i], stacked)
            if self.data_dir is not None:
                self._save_persist(i)
        # host-apply effects in queue order (sends, then cancels, then
        # timers per event — exactly _apply's order)
        sends = jax.tree.map(np.asarray, sends)
        timers = jax.tree.map(np.asarray, timers)
        cancels = jax.tree.map(np.asarray, cancels)
        crash, crash_code, halt = (np.asarray(crash),
                                   np.asarray(crash_code), np.asarray(halt))
        for j, (node, *_rest) in enumerate(events):
            n = self.nodes[node]
            row = _Staged(
                n.state,
                [{k: v[j] for k, v in e.items()} for e in sends],
                [{k: v[j] for k, v in e.items()} for e in timers],
                [{k: v[j] for k, v in e.items()} for e in cancels],
                bool(crash[j]), int(crash_code[j]), bool(halt[j]))
            self._apply_effects(n, row)

    def _apply(self, n: RealNode, ctx: Ctx):
        n.state = ctx.state
        if self.data_dir is not None:
            # spill stable storage BEFORE effects escape: an ack that
            # promises durability must not be sent while the synced bytes
            # exist only in this process's memory
            self._save_persist(n.id)
        self._apply_effects(n, ctx)

    def _apply_effects(self, n: RealNode, ctx):
        """Apply a handler's staged effects (the shared tail of per-event
        _apply and the batched drain; state/persist are already settled
        by the caller)."""
        P = self.cfg.payload_words
        for e in ctx._sends:
            if not bool(e["m"]):
                continue
            dst = int(e["dst"])
            if not (0 <= dst < self.cfg.n_nodes) or not n.alive:
                continue
            if self.loss and self._loss_rng.random() < self.loss:
                continue  # injected packet loss (real networks drop; loopback won't)
            pkt = struct.pack(f"<ii{P}i", int(e["tag"]), n.id,
                              *np.asarray(e["payload"], np.int32))
            # real send: straight to the peer; latency, loss, and
            # reordering are whatever the real backend does
            self._net.send(n.id, dst, pkt)
        cancelled_tags = set()
        for e in ctx._cancels:
            if not bool(e["m"]):
                continue
            # Sleep::reset/abort analog: wall-clock timers really cancel.
            # Also purge matching timer events parked by pause() — their
            # handles are already spent, but the event must not fire at
            # resume (narrows the inherent wall-clock-vs-virtual-schedule
            # divergence; exact schedule equivalence across worlds is
            # not a goal — the real world has no tie-break scheduler).
            t = int(e["tag"])
            for tag_i, h in n.timers:
                if tag_i == t:
                    h.cancel()
            n.timers = [(tg, h) for tg, h in n.timers if tg != t]
            n.parked = [(kind, args) for kind, args in n.parked
                        if not (kind == "timer" and int(args[0]) == t)]
            cancelled_tags.add(t)
        # batched mode: also purge matching timer firings already
        # sitting in the drain queue (a handle that fired during the
        # coalescing window), mirroring per-event semantics where
        # the cancel lands before the call_later fires. Events of
        # the SAME drain are inherently concurrent — a cancel
        # cannot retract a firing that ran earlier in its own scan;
        # the call-id payload idiom covers that residual window.
        # ONE filter pass for all of this handler's cancels: a per-cancel
        # rebuild would be O(cancels x queue_len) per drain.
        if self.batch_drain and cancelled_tags:
            self._queue = [ev for ev in self._queue
                           if not (ev[0] == n.id and ev[1] == 2
                                   and int(ev[3]) in cancelled_tags)]
        for e in ctx._timers:
            if not bool(e["m"]):
                continue
            delay = int(e["delay"]) / T.TICKS_PER_SEC
            tag = jnp.asarray(int(e["tag"]), jnp.int32)
            payload = e["payload"]
            entry = []

            def fire(n=n, tag=tag, payload=payload, entry=entry):
                # self-prune: spent handles must not accumulate (a
                # periodic timer would otherwise grow the list per fire)
                if entry and entry[0] in n.timers:
                    n.timers.remove(entry[0])
                self._dispatch(n.id, "timer", tag, payload)

            h = self._loop.call_later(delay, fire)
            entry.append((int(e["tag"]), h))
            n.timers.append(entry[0])
        if bool(ctx._crash):
            self.crashed.append((n.id, int(ctx._crash_code)))
            self._halted.set()
        if bool(ctx._halt):
            self._halted.set()

    # -- entry point ----------------------------------------------------
    async def start(self, nodes: Sequence[int] | None = None):
        """Begin real-time execution on the CURRENT event loop: bind the
        loop (timers dispatch via call_later), zero the clock origin, and
        start the given nodes (default: all).

        The public entry for custom supervisor scripts — tests and demos
        await this, then drive kill/restart/pause between awaits (the
        block_on-a-supervisor-future shape, runtime/mod.rs:119) — and for
        single-node boots like recovery inspection (start just the
        server, read its recovered state)."""
        if self.batch_drain:
            self._warm_drain()         # before sockets/timers exist
            self._warm_compiled()      # depth-1 drains bypass to per-event
        elif self.compiled:
            self._warm_compiled()
        self._loop = asyncio.get_running_loop()
        self.t0 = time.monotonic()
        for i in (range(self.cfg.n_nodes) if nodes is None else nodes):
            await self.start_node(i)
        if self._queue and not self._drain_scheduled:
            # events enqueued before the loop was bound (e.g. init
            # dispatched by an external start_node call)
            self._drain_scheduled = True
            self._loop.call_soon(self._drain)

    async def _main(self, duration: float):
        await self.start()
        try:
            await asyncio.wait_for(self._halted.wait(), timeout=duration)
        except asyncio.TimeoutError:
            pass
        for i in range(self.cfg.n_nodes):
            self.kill(i)

    def run(self, duration: float = 2.0):
        """Block until a program halts/crashes or `duration` seconds pass.
        The `#[madsim::main]` real-mode analog (macros lib.rs:46-78)."""
        asyncio.run(self._main(duration))
        return self

    def states(self):
        return [n.state for n in self.nodes]
