"""Pluggable real-world transports — the std/net backend seam.

The reference ships three interchangeable real backends behind one
Endpoint API, selected at compile time (std/net/mod.rs:33-49): plain TCP
(std/net/tcp.rs:69-151, connect-on-first-send + a reader task per
connection), UCX/RDMA driven by a dedicated progress-worker thread
(std/net/ucx.rs:43-60), and eRPC/ibverbs with a custom MsgHeader
(std/net/erpc.rs:95-124). The analog here is a runtime registry: a
Transport subclass implements start_node/send/close_node against a
deliver-callback, registers under a name, and RealRuntime resolves the
name — so a new backend (the UCX slot, when RDMA hardware exists) plugs
in with ZERO RealRuntime edits.

Contract (all methods called from inside the runtime's event loop):
  * ``start_node(i)``    — bind/listen for node *i*; may await.
  * ``send(src, dst, pkt)`` — fire-and-forget; a failed/refused/dead-peer
    send behaves like a dropped datagram (the sim's loss model; retry
    logic lives in the Programs, both worlds).
  * ``close_node(i)``    — release node *i*'s endpoints; in-flight
    receives for it may still fire (the runtime filters on ``alive``).
Delivery: call ``deliver(node, payload_bytes)`` with the node-local wire
frame; ordering/loss/latency guarantees are whatever the backend gives —
exactly the reference's stance (UDP-like tag-matched messages).
"""

from __future__ import annotations

import asyncio
import struct
from typing import Callable

TRANSPORTS: dict[str, type] = {}


def register_transport(name: str):
    """Class decorator: make `RealRuntime(transport=name)` resolve to cls.

    The runtime analog of the reference's cargo-feature selection
    (std/net/mod.rs:33-49 picks tcp/ucx/erpc at compile time)."""
    def deco(cls):
        TRANSPORTS[name] = cls
        cls.name = name
        return cls
    return deco


class Transport:
    """Base: owns per-node endpoints; subclasses fill the three hooks."""

    name = "?"

    def __init__(self, n_nodes: int, base_port: int,
                 deliver: Callable[[int, bytes], None]):
        self.n_nodes = n_nodes
        self.base_port = base_port
        self.deliver = deliver
        self._up: set[int] = set()      # nodes with live endpoints

    def addr(self, node: int):
        return ("127.0.0.1", self.base_port + node)

    async def start_node(self, node: int) -> None:
        await self._bind(node)
        self._up.add(node)

    def close_node(self, node: int) -> None:
        self._up.discard(node)
        self._close(node)

    def send(self, src: int, dst: int, pkt: bytes) -> None:
        if src in self._up and 0 <= dst < self.n_nodes:
            self._send(src, dst, pkt)

    # -- subclass hooks -------------------------------------------------
    async def _bind(self, node: int) -> None:
        raise NotImplementedError

    def _send(self, src: int, dst: int, pkt: bytes) -> None:
        raise NotImplementedError

    def _close(self, node: int) -> None:
        raise NotImplementedError


class _UdpProtocol(asyncio.DatagramProtocol):
    def __init__(self, tr: "UdpTransport", node: int):
        self.tr, self.node = tr, node

    def datagram_received(self, data, addr):
        self.tr.deliver(self.node, data)


@register_transport("udp")
class UdpTransport(Transport):
    """One datagram socket per node; the network's own loss/reorder."""

    def __init__(self, *a):
        super().__init__(*a)
        self._eps: dict[int, asyncio.DatagramTransport] = {}

    async def _bind(self, node: int):
        loop = asyncio.get_running_loop()
        ep, _ = await loop.create_datagram_endpoint(
            lambda: _UdpProtocol(self, node), local_addr=self.addr(node))
        self._eps[node] = ep

    def _send(self, src: int, dst: int, pkt: bytes):
        ep = self._eps.get(src)
        if ep is not None:
            ep.sendto(pkt, self.addr(dst))

    def _close(self, node: int):
        ep = self._eps.pop(node, None)
        if ep is not None:
            ep.close()


@register_transport("tcp")
class TcpTransport(Transport):
    """Length-delimited frames over lazily-established per-peer
    connections — the reference's real TCP Endpoint shape
    (std/net/tcp.rs:69-151: connect-on-first-send, a reader task per
    connection feeding the mailbox)."""

    def __init__(self, *a):
        super().__init__(*a)
        self._servers: dict[int, asyncio.AbstractServer] = {}
        self._conns: dict[int, dict[int, asyncio.StreamWriter]] = {}
        self._locks: dict[int, dict[int, asyncio.Lock]] = {}
        self._readers: dict[int, list[asyncio.Task]] = {}
        self._bg: set = set()           # in-flight send tasks

    async def _bind(self, node: int):
        self._conns.setdefault(node, {})
        self._locks.setdefault(node, {})
        self._readers.setdefault(node, [])
        self._servers[node] = await asyncio.start_server(
            lambda r, w: self._reader(node, r, w), *self.addr(node))

    async def _reader(self, node: int, reader, writer):
        task = asyncio.current_task()
        self._readers.setdefault(node, []).append(task)
        try:
            while True:
                hdr = await reader.readexactly(4)
                (ln,) = struct.unpack("<I", hdr)
                data = await reader.readexactly(ln)
                if node in self._up:
                    self.deliver(node, data)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            writer.close()
            rs = self._readers.get(node, [])
            if task in rs:              # prune on normal close, not just kill
                rs.remove(task)

    def _send(self, src: int, dst: int, pkt: bytes):
        task = asyncio.get_running_loop().create_task(
            self._asend(src, dst, pkt))
        self._bg.add(task)
        task.add_done_callback(self._bg.discard)

    async def _asend(self, src: int, dst: int, pkt: bytes):
        if src not in self._up:         # killed after the send was queued
            return
        lock = self._locks.setdefault(src, {}).setdefault(
            dst, asyncio.Lock())
        conns = self._conns.setdefault(src, {})
        try:
            async with lock:            # one dial per peer at a time — no
                w = conns.get(dst)      # duplicate-connection leak on
                if w is None or w.is_closing():  # broadcast bursts
                    _, w = await asyncio.open_connection(*self.addr(dst))
                    if src not in self._up:      # killed while dialing
                        w.close()
                        return
                    conns[dst] = w
            w.write(struct.pack("<I", len(pkt)) + pkt)
            await w.drain()
        except (ConnectionError, OSError):
            conns.pop(dst, None)        # peer down: datagram-like drop

    def _close(self, node: int):
        srv = self._servers.pop(node, None)
        if srv is not None:
            srv.close()
        # clear IN PLACE, never rebind: an _asend suspended in its dial
        # holds a reference to these dicts; if kill+restart swapped in
        # fresh ones, its writer would land in an orphaned dict no future
        # _close ever iterates — a leaked connection
        conns = self._conns.get(node, {})
        for w in conns.values():
            w.close()
        conns.clear()
        self._locks.get(node, {}).clear()
        readers = self._readers.get(node, [])
        for t in readers:
            t.cancel()
        readers.clear()


@register_transport("local")
class LocalTransport(Transport):
    """In-memory backend occupying the UCX slot — proof the seam is real.

    Models the reference's UCX design (std/net/ucx.rs:43-60): each node
    owns a DEDICATED progress worker (there a thread spinning
    worker.progress(); here a task draining the node's send queue) and
    payloads move by direct buffer handoff, never through a kernel
    socket — the zero-copy/registered-memory analog. When actual RDMA
    hardware exists, a UCX binding implements this same three-hook
    interface and registers beside it."""

    def __init__(self, *a):
        super().__init__(*a)
        self._outbox: dict[int, asyncio.Queue] = {}
        self._workers: dict[int, asyncio.Task] = {}

    async def _bind(self, node: int):
        self._outbox[node] = asyncio.Queue()
        self._workers[node] = asyncio.get_running_loop().create_task(
            self._progress(node))

    async def _progress(self, node: int):
        # the ucx.rs worker loop: progress posted sends in order
        q = self._outbox[node]
        while True:
            dst, pkt = await q.get()
            if dst in self._up:         # dead peer: datagram-like drop
                self.deliver(dst, pkt)

    def _send(self, src: int, dst: int, pkt: bytes):
        self._outbox[src].put_nowait((dst, pkt))

    def _close(self, node: int):
        w = self._workers.pop(node, None)
        if w is not None:
            w.cancel()
        self._outbox.pop(node, None)
