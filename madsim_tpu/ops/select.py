"""Vectorized selection primitives for the event engine.

These are the TPU-native equivalents of madsim's two scheduler data
structures: the random-pop ready queue (madsim/src/sim/utils/mpsc.rs:75-85 —
`try_recv_random` picks a uniformly random element with the global RNG) and
the binary-heap timer (madsim/src/sim/time/mod.rs:41-56 — pop earliest
deadline). Both become masked reductions over the fixed-shape event table:
argmin for the next deadline, a masked categorical draw for the tie-break.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def masked_choice(key, mask):
    """Pick a uniformly random index among True entries of `mask`.

    Returns (idx:int32, valid:bool). idx is 0 when no entry is set (callers
    must gate on `valid`). Deterministic given `key` — this is the replayable
    analog of mpsc.rs:75 `try_recv_random`.
    """
    mask = mask.astype(jnp.int32)
    cnt = mask.sum()
    r = jax.random.randint(key, (), 0, jnp.maximum(cnt, 1), dtype=jnp.int32)
    cum = jnp.cumsum(mask)
    idx = jnp.argmax(cum == r + 1).astype(jnp.int32)
    return idx, cnt > 0


def min_deadline(deadlines, eligible, inf):
    """Earliest eligible deadline and its tie mask.

    Returns (dmin:int32, at_min:bool[T], any_eligible:bool).
    """
    masked = jnp.where(eligible, deadlines, inf)
    dmin = masked.min()
    any_eligible = dmin < inf
    at_min = eligible & (deadlines == dmin)
    return dmin, at_min, any_eligible


def take1(vec, idx):
    """`vec[idx]` for a 1-D `vec` and any-shape integer `idx`, via a one-hot
    masked sum. On TPU, a gather whose index operand has many elements costs
    ~10ns PER ELEMENT (measured: the [N,N,L] invariant gather was 78% of the
    whole Raft step); the one-hot compare+select+reduce stays on the VPU and
    is bandwidth-trivial for the small tables this engine uses. Out-of-range
    indices must be pre-clipped (they select nothing and return 0).
    """
    n = vec.shape[0]
    oh = idx[..., None] == jnp.arange(n, dtype=jnp.int32)
    if vec.dtype == jnp.bool_:
        return (oh & vec).any(-1)
    return jnp.where(oh, vec, jnp.zeros((), vec.dtype)).sum(-1)


def row_onehot(n, idx):
    """bool[n] with True at `idx` (the building block of take_row/put_row;
    use it directly when composing custom one-hot updates so the TPU
    gather-avoidance semantics live in one place)."""
    return jnp.arange(n, dtype=jnp.int32) == idx


def take_row(mat, idx):
    """`mat[idx]` for mat[R, ...] and a SCALAR traced idx, via one-hot
    (same TPU rationale as take1; under vmap the scalar is per-lane)."""
    oh = row_onehot(mat.shape[0], idx).reshape(
        (mat.shape[0],) + (1,) * (mat.ndim - 1))
    if mat.dtype == jnp.bool_:
        return (oh & mat).any(0)
    return jnp.where(oh, mat, jnp.zeros((), mat.dtype)).sum(0)


def put_row(mat, idx, val, mask=True):
    """`mat.at[idx].set(val)` where `mask` holds, via one-hot select.
    `val` broadcasts against one row; out-of-range idx writes nothing."""
    oh = row_onehot(mat.shape[0], idx).reshape(
        (mat.shape[0],) + (1,) * (mat.ndim - 1))
    return jnp.where(oh & mask, val, mat)


def first_k_free(free_mask, k: int, scatter: bool = False):
    """Indices of the first k free slots (stable by index).

    Returns (slots:int32[k], ok:bool[k]) where ok[j] is False when fewer than
    j+1 slots are free; not-ok rows return slot 0 (callers gate on ok).

    Two lowerings, identical results (the emission_write knob,
    types.py): the default cumsum rank-match is O(kC) compares — cheap on
    the TPU VPU, but the k*C product is quadratic in cluster width when
    k ~ n and C = 16n (DESIGN §5 width tax); `scatter=True` writes each
    free slot's index into its rank row instead — one O(C) scatter, the
    CPU-friendly form.
    """
    pos = jnp.cumsum(free_mask.astype(jnp.int32))
    if scatter:
        C = free_mask.shape[0]
        rank = pos - 1
        dst = jnp.where(free_mask & (rank < k), rank, k)   # k = dropped
        slots = jnp.zeros((k,), jnp.int32).at[dst].set(
            jnp.arange(C, dtype=jnp.int32), mode="drop")
    else:
        targets = jnp.arange(1, k + 1, dtype=jnp.int32)
        eq = (pos[None, :] == targets[:, None]) & free_mask[None, :]
        slots = jnp.argmax(eq, axis=1).astype(jnp.int32)
    ok = jnp.arange(1, k + 1, dtype=jnp.int32) \
        <= (pos[-1] if pos.shape[0] else 0)
    return slots, ok
