"""Fused Pallas scheduler kernel — the select+pop+free-scan of the event
engine in ONE VMEM pass per batch block.

The engine's per-step scheduling reads the `[batch, C]` event table three
times through separate XLA reductions (ops/select.py): earliest eligible
deadline, random tie-break, first-K free slots. On TPU those are small
VPU kernels whose cost is dominated by HBM round-trips of the same table
slices; this kernel fuses them so each `[8, C]` block is loaded into VMEM
once. It is the kernel DESIGN.md §5 contemplates and VERDICT r1 names as
the lever IF XLA's fusion of the unfused path proves poor — so it ships
OPT-IN (engine integration pending a real-chip profile), with interpret-
mode differential tests (tests/test_pallas_select.py) proving semantics
against ops/select on any platform.

Design notes (TPU constraints, /opt/skills/guides/pallas_guide.md):
  * no lane-axis cumsum: the uniform tie-break uses keyed HASH PRIORITIES
    (argmax of iid hashes over the tie set is a uniform draw) and
    first-K-free uses K iterative min-index extractions — min/max
    reductions only, all VPU-friendly;
  * the tie-break therefore draws DIFFERENTLY from ops/select.masked_choice
    for the same key (both uniform; schedules are reproducible per path,
    not across paths);
  * outputs pack into one [batch, 128] int32 tile (col 0 dmin, 1 idx,
    2 any-eligible, 8.. slots, 64.. ok flags) to keep every ref lane-tiled.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_HASH_A = -1640531527   # 2654435761 as int32 (Knuth multiplicative)
_HASH_B = -1028477387   # 0xC2B2AE3D as int32 (murmur3 finalizer constant)

_COL_DMIN, _COL_IDX, _COL_ANY = 0, 1, 2
_COL_SLOTS, _COL_OK = 8, 64
MAX_FREE = _COL_OK - _COL_SLOTS  # 56 emission slots — far above any model


def _kernel(dl_ref, el_ref, fr_ref, rnd_ref, out_ref, *, n_free, inf):
    _body(dl_ref[:], el_ref[:] != 0, fr_ref[:] != 0, rnd_ref, out_ref,
          n_free=n_free, inf=inf)


def _kernel_nofree(dl_ref, el_ref, rnd_ref, out_ref, *, inf):
    # select-only variant: no free-mask input at all — the engine's lane
    # entry must not DMA a dummy buffer into VMEM on the hot path
    _body(dl_ref[:], el_ref[:] != 0, None, rnd_ref, out_ref,
          n_free=0, inf=inf)


def _body(dl, el, fr, rnd_ref, out_ref, *, n_free, inf):
    rnd = rnd_ref[:, :1]                       # [BB, 1] per-lane random bits
    bb, cc = dl.shape
    lane = jax.lax.broadcasted_iota(jnp.int32, (bb, cc), 1)
    ocol = jax.lax.broadcasted_iota(jnp.int32, (bb, 128), 1)
    big = jnp.asarray(inf, jnp.int32)

    # earliest eligible deadline + tie set
    masked = jnp.where(el, dl, big)
    dmin = jnp.min(masked, axis=1, keepdims=True)          # [BB, 1]
    any_el = (dmin < big).astype(jnp.int32)
    at_min = el & (dl == dmin)

    # uniform tie-break: max keyed hash priority, lowest lane breaks the
    # (measure-zero) hash collision deterministically
    h = (rnd ^ (lane * jnp.asarray(_HASH_A, jnp.int32))) \
        * jnp.asarray(_HASH_B, jnp.int32)
    pri = jnp.where(at_min, h, jnp.asarray(-2**31, jnp.int32))
    pmax = jnp.max(pri, axis=1, keepdims=True)
    cand = jnp.where(at_min & (pri == pmax), lane, big)
    idx = jnp.min(cand, axis=1, keepdims=True)             # [BB, 1]
    idx = jnp.where(any_el == 1, idx, 0)

    out = jnp.zeros((bb, 128), jnp.int32)
    out = jnp.where(ocol == _COL_DMIN, dmin, out)
    out = jnp.where(ocol == _COL_IDX, idx, out)
    out = jnp.where(ocol == _COL_ANY, any_el, out)

    # first n_free free slots, in index order: iterative min-extraction
    frm = fr
    for j in range(n_free):
        candf = jnp.where(frm, lane, big)
        sj = jnp.min(candf, axis=1, keepdims=True)         # [BB, 1]
        okj = (sj < big).astype(jnp.int32)
        frm = frm & (lane != sj)
        out = jnp.where(ocol == _COL_SLOTS + j, jnp.where(okj == 1, sj, 0),
                        out)
        out = jnp.where(ocol == _COL_OK + j, okj, out)

    out_ref[:] = out


@functools.partial(jax.jit,
                   static_argnames=("n_free", "inf", "interpret"))
def fused_schedule(deadlines, eligible, free, rand_bits, *, n_free: int,
                   inf: int, interpret: bool | None = None):
    """Batched fused scheduling pass.

    Args:
      deadlines: int32[B, C]; eligible/free: bool[B, C];
      rand_bits: int32[B] (one draw per lane, e.g. prng bits).
      n_free: how many free slots to extract (the engine's E).
      inf:    the T_INF sentinel.
      interpret: force pallas interpreter (default: auto — True off-TPU).

    Returns (dmin[B], idx[B], any_eligible[B], slots[B, n_free],
    ok[B, n_free]) with ops/select semantics (tie-break draw differs; see
    module docstring).
    """
    assert n_free <= MAX_FREE, f"n_free > {MAX_FREE} packed-output slots"
    out = _fused_call(deadlines, eligible, free, rand_bits, rows=8,
                      n_free=n_free, inf=inf, interpret=interpret)
    dmin = out[:, _COL_DMIN]
    idx = out[:, _COL_IDX]
    any_el = out[:, _COL_ANY] == 1
    slots = out[:, _COL_SLOTS:_COL_SLOTS + n_free]
    ok = out[:, _COL_OK:_COL_OK + n_free] == 1
    return dmin, idx, any_el, slots, ok


@functools.partial(jax.jit, static_argnames=("inf", "interpret"))
def fused_select_lane(deadlines, eligible, rand_bits, *, inf: int,
                      interpret: bool | None = None):
    """Per-trajectory fused select (no free-scan): the vmappable entry the
    engine uses under `SimConfig(scheduler="fused")`.

    Args: deadlines int32[C], eligible bool[C], rand_bits int32 scalar.
    Returns (dmin, idx, any_eligible) scalars. Same semantics as
    `sel.min_deadline` + `sel.masked_choice` but the tie-break draw
    differs (hash priorities vs masked categorical — both uniform; each
    scheduler value is its own replay domain).

    vmap over the seed batch lifts the pallas_call with a batching rule
    (one grid row per lane); a [1, C] block avoids the batched entry's
    8-row padding, which under vmap would cost 8x waste per lane. The
    free-mask input is omitted entirely (n_free=0) — no dummy buffer DMA
    on the hot path.
    """
    out = _fused_call(deadlines[None], eligible[None], None,
                      jnp.asarray(rand_bits, jnp.int32)[None], rows=1,
                      n_free=0, inf=inf, interpret=interpret)
    return out[0, _COL_DMIN], out[0, _COL_IDX], out[0, _COL_ANY] == 1


def _fused_call(deadlines, eligible, free, rand_bits, *, rows: int,
                n_free: int, inf: int, interpret: bool | None):
    """Shared plumbing for both entries: pad to (rows, 128) tiles, build
    the pallas_call, return packed [B, 128] output rows. `free=None`
    selects the no-free-input kernel variant."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    B, C = deadlines.shape
    BB = -(-B // rows) * rows
    CC = -(-C // 128) * 128
    pad = ((0, BB - B), (0, CC - C))
    table_spec = pl.BlockSpec((rows, CC), lambda i: (i, 0))
    out_spec = pl.BlockSpec((rows, 128), lambda i: (i, 0))

    dl = jnp.pad(jnp.asarray(deadlines, jnp.int32), pad,
                 constant_values=inf)
    el = jnp.pad(eligible.astype(jnp.int32), pad)
    rnd = jnp.pad(jnp.broadcast_to(
        jnp.asarray(rand_bits, jnp.int32)[:, None], (B, 128)),
        ((0, BB - B), (0, 0)))
    if free is None:
        kern = functools.partial(_kernel_nofree, inf=inf)
        ins, specs = (dl, el, rnd), [table_spec, table_spec, out_spec]
    else:
        kern = functools.partial(_kernel, n_free=n_free, inf=inf)
        fr = jnp.pad(free.astype(jnp.int32), pad)
        ins = (dl, el, fr, rnd)
        specs = [table_spec, table_spec, table_spec, out_spec]

    out = pl.pallas_call(
        kern,
        grid=(BB // rows,),
        in_specs=specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((BB, 128), jnp.int32),
        interpret=interpret,
    )(*ins)
    return out[:B]
