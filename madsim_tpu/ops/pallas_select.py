"""Fused Pallas scheduler kernel — the select+pop+free-scan of the event
engine in ONE VMEM pass per batch block.

The engine's per-step scheduling reads the `[batch, C]` event table three
times through separate XLA reductions (ops/select.py): earliest eligible
deadline, random tie-break, first-K free slots. On TPU those are small
VPU kernels whose cost is dominated by HBM round-trips of the same table
slices; this kernel fuses them so each `[8, C]` block is loaded into VMEM
once. It is the kernel DESIGN.md §5 contemplates and VERDICT r1 names as
the lever IF XLA's fusion of the unfused path proves poor — so it ships
OPT-IN (engine integration pending a real-chip profile), with interpret-
mode differential tests (tests/test_pallas_select.py) proving semantics
against ops/select on any platform.

Design notes (TPU constraints, /opt/skills/guides/pallas_guide.md):
  * no lane-axis cumsum: the uniform tie-break uses keyed HASH PRIORITIES
    (argmax of iid hashes over the tie set is a uniform draw) and
    first-K-free uses K iterative min-index extractions — min/max
    reductions only, all VPU-friendly;
  * the tie-break therefore draws DIFFERENTLY from ops/select.masked_choice
    for the same key (both uniform; schedules are reproducible per path,
    not across paths);
  * outputs pack into one [batch, 128] int32 tile (col 0 dmin, 1 idx,
    2 any-eligible, 8.. slots, 64.. ok flags) to keep every ref lane-tiled.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_HASH_A = -1640531527   # 2654435761 as int32 (Knuth multiplicative)
_HASH_B = -1028477387   # 0xC2B2AE3D as int32 (murmur3 finalizer constant)

_COL_DMIN, _COL_IDX, _COL_ANY = 0, 1, 2
_COL_SLOTS, _COL_OK = 8, 64
MAX_FREE = _COL_OK - _COL_SLOTS  # 56 emission slots — far above any model


def _kernel(dl_ref, el_ref, fr_ref, rnd_ref, out_ref, *, n_free, inf):
    dl = dl_ref[:]
    el = el_ref[:] != 0
    fr = fr_ref[:] != 0
    rnd = rnd_ref[:, :1]                       # [BB, 1] per-lane random bits
    bb, cc = dl.shape
    lane = jax.lax.broadcasted_iota(jnp.int32, (bb, cc), 1)
    ocol = jax.lax.broadcasted_iota(jnp.int32, (bb, 128), 1)
    big = jnp.asarray(inf, jnp.int32)

    # earliest eligible deadline + tie set
    masked = jnp.where(el, dl, big)
    dmin = jnp.min(masked, axis=1, keepdims=True)          # [BB, 1]
    any_el = (dmin < big).astype(jnp.int32)
    at_min = el & (dl == dmin)

    # uniform tie-break: max keyed hash priority, lowest lane breaks the
    # (measure-zero) hash collision deterministically
    h = (rnd ^ (lane * jnp.asarray(_HASH_A, jnp.int32))) \
        * jnp.asarray(_HASH_B, jnp.int32)
    pri = jnp.where(at_min, h, jnp.asarray(-2**31, jnp.int32))
    pmax = jnp.max(pri, axis=1, keepdims=True)
    cand = jnp.where(at_min & (pri == pmax), lane, big)
    idx = jnp.min(cand, axis=1, keepdims=True)             # [BB, 1]
    idx = jnp.where(any_el == 1, idx, 0)

    out = jnp.zeros((bb, 128), jnp.int32)
    out = jnp.where(ocol == _COL_DMIN, dmin, out)
    out = jnp.where(ocol == _COL_IDX, idx, out)
    out = jnp.where(ocol == _COL_ANY, any_el, out)

    # first n_free free slots, in index order: iterative min-extraction
    frm = fr
    for j in range(n_free):
        candf = jnp.where(frm, lane, big)
        sj = jnp.min(candf, axis=1, keepdims=True)         # [BB, 1]
        okj = (sj < big).astype(jnp.int32)
        frm = frm & (lane != sj)
        out = jnp.where(ocol == _COL_SLOTS + j, jnp.where(okj == 1, sj, 0),
                        out)
        out = jnp.where(ocol == _COL_OK + j, okj, out)

    out_ref[:] = out


@functools.partial(jax.jit,
                   static_argnames=("n_free", "inf", "interpret"))
def fused_schedule(deadlines, eligible, free, rand_bits, *, n_free: int,
                   inf: int, interpret: bool | None = None):
    """Batched fused scheduling pass.

    Args:
      deadlines: int32[B, C]; eligible/free: bool[B, C];
      rand_bits: int32[B] (one draw per lane, e.g. prng bits).
      n_free: how many free slots to extract (the engine's E).
      inf:    the T_INF sentinel.
      interpret: force pallas interpreter (default: auto — True off-TPU).

    Returns (dmin[B], idx[B], any_eligible[B], slots[B, n_free],
    ok[B, n_free]) with ops/select semantics (tie-break draw differs; see
    module docstring).
    """
    from jax.experimental import pallas as pl

    assert n_free <= MAX_FREE, f"n_free > {MAX_FREE} packed-output slots"
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    B, C = deadlines.shape
    BB = -(-B // 8) * 8
    CC = -(-C // 128) * 128
    pad = ((0, BB - B), (0, CC - C))
    dl = jnp.pad(jnp.asarray(deadlines, jnp.int32), pad,
                 constant_values=inf)
    el = jnp.pad(eligible.astype(jnp.int32), pad)
    fr = jnp.pad(free.astype(jnp.int32), pad)
    rnd = jnp.pad(jnp.broadcast_to(
        jnp.asarray(rand_bits, jnp.int32)[:, None], (B, 128)),
        ((0, BB - B), (0, 0)))

    kern = functools.partial(_kernel, n_free=n_free, inf=inf)
    out = pl.pallas_call(
        kern,
        grid=(BB // 8,),
        in_specs=[pl.BlockSpec((8, CC), lambda i: (i, 0)),
                  pl.BlockSpec((8, CC), lambda i: (i, 0)),
                  pl.BlockSpec((8, CC), lambda i: (i, 0)),
                  pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((BB, 128), jnp.int32),
        interpret=interpret,
    )(dl, el, fr, rnd)

    out = out[:B]
    dmin = out[:, _COL_DMIN]
    idx = out[:, _COL_IDX]
    any_el = out[:, _COL_ANY] == 1
    slots = out[:, _COL_SLOTS:_COL_SLOTS + n_free]
    ok = out[:, _COL_OK:_COL_OK + n_free] == 1
    return dmin, idx, any_el, slots, ok
