"""madsim_tpu — a TPU-native deterministic simulation-testing framework.

Built from scratch with the capabilities of madsys-dev/madsim (a seeded
deterministic simulator for distributed systems), re-architected for TPU:
instead of one seed per single-threaded async runtime, the simulator core is
a pure jitted `step(state) -> state` transition vmapped over a `[seed_batch]`
axis, so thousands of trajectories (seeds) advance in lockstep as one XLA
program and shard across chips with jax.sharding.

    from madsim_tpu import Runtime, Program, Scenario, SimConfig, ms, sec
"""

from .core.api import Ctx, Program
from .core.state import (CheckpointMismatch, LaneCheckpoint, SimState,
                         checkpoint_lane, seed_batch_from)
from .core.types import (
    CRASH_DEADLOCK,
    CRASH_INVARIANT,
    CRASH_RECOVERY,
    CRASH_SLO,
    CRASH_TIME_LIMIT,
    EV_MSG,
    EV_SUPER,
    EV_TIMER,
    NODE_RANDOM,
    NetConfig,
    SimConfig,
    ms,
    sec,
)
from .core.extension import Extension
from .analyze import (confirm_race, find_races, lint_runtime, scan_races)
from .harness.determinism import find_divergence
from .obs import (
    CheckpointLog,
    JsonlObserver,
    ProgressObserver,
    ReplayDivergence,
    SweepObserver,
    divergence_report,
    explain_crash,
    explain_latency,
    export_chrome_trace,
    export_profile_trace,
    format_latency,
    format_profile,
    format_series,
    format_span,
    full_chain_replay,
    lane_series,
    latency_summary,
    profile_summary,
    replay_window,
    request_spans,
    ring_records,
    series_summary,
)
from .harness.minimize import minimize_scenario
from .harness.simtest import (DetSanFailure, SimFailure, detsan_check,
                              run_seeds, simtest)
from .harness.recovery import recovery_invariant
from .harness.slo import slo_invariant
from .parallel.explore import explore
from .parallel.stats import (divergence_profile, schedule_representatives,
                             summarize)
from .runtime.runtime import Runtime
from .runtime.scenario import Scenario
from .harness.witness import success_witness
from .obs.support import extract_support, support_from_records
from .search import (Corpus, KnobPlan, LdfiConfig, fuzz, fuzz_sharded,
                     pct_sweep, with_prio_nudge)
from .service import (CorpusStore, audit_buckets, campaign_report,
                      merged_buckets, replay_bucket, run_campaign,
                      supervise_campaign, triage_diff, triage_snapshot)

__version__ = "0.1.0"

__all__ = [
    "Ctx", "Program", "Extension", "SimState", "SimConfig", "NetConfig",
    "Runtime", "Scenario", "simtest", "run_seeds", "SimFailure", "ms", "sec",
    "NODE_RANDOM", "EV_MSG", "EV_TIMER", "EV_SUPER", "CRASH_DEADLOCK",
    "CRASH_TIME_LIMIT", "CRASH_INVARIANT", "CRASH_SLO", "slo_invariant",
    "CRASH_RECOVERY", "recovery_invariant",
    "explore", "minimize_scenario", "summarize", "schedule_representatives",
    "find_divergence",
    "fuzz", "fuzz_sharded", "Corpus", "KnobPlan", "pct_sweep",
    "with_prio_nudge",
    "LdfiConfig", "success_witness", "support_from_records",
    "extract_support",
    "SweepObserver", "JsonlObserver", "ProgressObserver", "ring_records",
    "export_chrome_trace", "explain_crash", "divergence_profile",
    "profile_summary", "format_profile", "export_profile_trace",
    "latency_summary", "format_latency",
    "series_summary", "format_series", "lane_series",
    "explain_latency", "format_span", "request_spans",
    "CorpusStore", "run_campaign", "supervise_campaign", "campaign_report",
    "merged_buckets", "replay_bucket",
    "triage_snapshot", "triage_diff", "audit_buckets",
    "lint_runtime", "find_races", "confirm_race", "scan_races",
    "detsan_check", "DetSanFailure",
    "LaneCheckpoint", "CheckpointMismatch", "checkpoint_lane",
    "seed_batch_from", "CheckpointLog", "replay_window",
    "full_chain_replay", "divergence_report", "ReplayDivergence",
]
