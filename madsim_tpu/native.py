"""Native host-side components: build + ctypes bindings, with pure-Python
fallbacks.

The TPU runs the vectorized simulation; history *checking* is sequential
search on the host, so it is native C++ (native/linearize.cpp), compiled on
first use with g++ into a cached shared object and bound via ctypes (no
pybind11 in this environment). Every native entry point has a pure-Python
fallback used when no compiler is available — and for differential testing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_ROOT, "native", "linearize.cpp")
_SO = os.path.join(_ROOT, "native", "liblinearize.so")
_SIM_SRC = os.path.join(_ROOT, "native", "simloop.cpp")
_SIM_SO = os.path.join(_ROOT, "native", "libsimloop.so")

_lib = None
_lib_tried = False
_simlib = None
_simlib_tried = False


def _load():
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    try:
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
                check=True, capture_output=True)
        lib = ctypes.CDLL(_SO)
        lib.lin_check_register.restype = ctypes.c_int
        lib.lin_check_register.argtypes = [
            ctypes.c_int,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ]
        _lib = lib
    except Exception as e:  # no compiler / load failure -> fallback
        print(f"madsim_tpu.native: falling back to python checker ({e})",
              file=sys.stderr)
        _lib = None
    return _lib


def _load_simloop():
    """native/simloop.cpp — the single-seed discrete-event baseline twin
    (the reference execution-model stand-in, task.rs:110-124). No Python
    fallback: this engine at batch=1 IS the fallback denominator, and a
    Python rewrite would misstate the native rate it exists to measure."""
    global _simlib, _simlib_tried
    if _simlib is not None or _simlib_tried:
        return _simlib
    _simlib_tried = True
    try:
        if (not os.path.exists(_SIM_SO)
                or os.path.getmtime(_SIM_SO) < os.path.getmtime(_SIM_SRC)):
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", _SIM_SO, _SIM_SRC],
                check=True, capture_output=True)
        lib = ctypes.CDLL(_SIM_SO)
        lib.simloop_run.restype = None
        lib.simloop_run.argtypes = [
            ctypes.c_uint64, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")]
        _simlib = lib
    except Exception as e:
        print(f"madsim_tpu.native: simloop unavailable ({e})",
              file=sys.stderr)
        _simlib = None
    return _simlib


def native_baseline_run(seed: int, max_events: int) -> dict | None:
    """Run the native single-seed flagship workload for `max_events`
    events; returns {events, wall_s, events_per_sec, max_commit,
    elections} or None when no C++ toolchain is available."""
    lib = _load_simloop()
    if lib is None:
        return None
    out = np.zeros(4, np.int64)
    lib.simloop_run(seed, max_events, out)
    ev, ns = int(out[0]), max(int(out[1]), 1)
    return dict(events=ev, wall_s=ns / 1e9,
                events_per_sec=ev / (ns / 1e9),
                max_commit=int(out[2]), elections=int(out[3]))


def _check_register_py(op, val, inv, resp) -> bool:
    """Pure-Python mirror of native/linearize.cpp (same algorithm)."""
    n = len(op)
    if n == 0:
        return True
    seen = set()

    def dfs(mask, value):
        if mask == 0:
            return True
        key = (mask, value)
        if key in seen:
            return False
        seen.add(key)
        minresp = min((resp[i] for i in range(n)
                       if (mask >> i) & 1 and resp[i] >= 0),
                      default=None)
        for i in range(n):
            if not (mask >> i) & 1:
                continue
            if minresp is not None and inv[i] > minresp:
                continue
            rest = mask & ~(1 << i)
            if op[i] == 1:
                if dfs(rest, val[i]):
                    return True
            else:
                if val[i] == value and dfs(rest, value):
                    return True
            if resp[i] < 0 and dfs(rest, value):
                return True
        return False

    return dfs((1 << n) - 1, 0)


def check_register(op, val, inv, resp, force_python=False) -> bool:
    """Is this single-register history linearizable (initial value 0)?

    op: 1=PUT, 2=GET; val: written/observed value; inv/resp: times,
    resp < 0 marks a pending op (may or may not have taken effect).
    """
    op = np.ascontiguousarray(op, np.int32)
    val = np.ascontiguousarray(val, np.int32)
    inv = np.ascontiguousarray(inv, np.int64)
    resp = np.ascontiguousarray(resp, np.int64)
    lib = None if force_python else _load()
    if lib is not None and len(op) <= 57:
        r = lib.lin_check_register(len(op), op, val, inv, resp)
        if r >= 0:
            return bool(r)
    return _check_register_py(op.tolist(), val.tolist(), inv.tolist(),
                              resp.tolist())


def check_kv_history(hist: dict, force_python=False) -> bool:
    """Linearizability of a multi-key KV history: registers compose, so
    each key's sub-history is checked independently (P-compositionality).

    hist: dict of numpy arrays op/key/val/inv/resp (see
    models/raft_kv.extract_histories).
    """
    keys = np.unique(hist["key"])
    for k in keys:
        m = hist["key"] == k
        if not check_register(hist["op"][m], hist["val"][m], hist["inv"][m],
                              hist["resp"][m], force_python=force_python):
            return False
    return True
