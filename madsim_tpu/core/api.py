"""Handler-side API: what protocol code can do inside a transition.

madsim application tasks call `Endpoint::send_to` (net/mod.rs:232),
`time::sleep` (time/sleep.rs), and `rand::thread_rng` (rand.rs:118) as async
ops against ambient thread-local context (runtime/context.rs). Here protocol
code is a *state-machine handler* — `on_message` / `on_timer` / `init` — that
receives a `Ctx` and records its effects (sends, timers, state update, crash
or halt requests) functionally. The number of `send`/`set_timer` calls in a
handler is static (it is traced Python); conditional behavior is expressed
with the `when=` mask, keeping everything fixed-shape for XLA.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

from . import prng
from . import types as T


def as_payload(payload, n_words: int) -> jax.Array:
    """Coerce None / list of ints / array into an int32[n_words] payload.

    madsim messages are `Box<dyn Any>` (net/mod.rs:366) — arbitrary heap
    payloads. Fixed shapes require a typed encoding: protocols pack their
    message fields into int32 words (see utils/structs.py for helpers).
    """
    if payload is None:
        return jnp.zeros((n_words,), jnp.int32)
    if isinstance(payload, (list, tuple)):
        items = [jnp.asarray(x, jnp.int32) for x in payload]
        assert len(items) <= n_words, "payload too wide for cfg.payload_words"
        vec = jnp.stack(items) if items else jnp.zeros((0,), jnp.int32)
        return jnp.concatenate(
            [vec, jnp.zeros((n_words - len(items),), jnp.int32)])
    # Array payloads narrower than payload_words are DELIBERATELY
    # zero-padded (protocols build exact-semantic-width stacks, e.g. raft's
    # merged RV/AE payload); word-layout correctness is the protocol's
    # responsibility — decode reads fixed positions.
    arr = jnp.asarray(payload, jnp.int32)
    assert arr.ndim == 1 and arr.shape[0] <= n_words, \
        f"payload shape {arr.shape} too wide for ({n_words},)"
    if arr.shape[0] < n_words:
        arr = jnp.concatenate(
            [arr, jnp.zeros((n_words - arr.shape[0],), jnp.int32)])
    return arr


class Ctx:
    """Effect-collecting handler context (one node, one event, one trajectory).

    Attributes:
      node:  int32 — this node's id (madsim NodeId analog)
      now:   int32 — virtual time in ticks
      state: user pytree — this node's protocol state; REASSIGN it
             (``ctx.state = new_state``) to update.
    """

    def __init__(self, cfg: T.SimConfig, node, now, key, state,
                 hash_base=None):
        self.cfg = cfg
        self.node = node
        self.now = now
        self.state = state
        self._key = key
        self._hash_base = hash_base
        self._sends: list[dict[str, Any]] = []
        self._timers: list[dict[str, Any]] = []
        self._cancels: list[dict[str, Any]] = []
        self._crash = jnp.asarray(False)
        self._crash_code = jnp.asarray(0, jnp.int32)
        self._halt = jnp.asarray(False)

    # -- randomness (thread_rng analog; draws are replay-stable per event) --
    def rand_key(self) -> jax.Array:
        self._key, k = prng.split(self._key)
        return k

    def randint(self, lo, hi) -> jax.Array:
        """Uniform int32 in [lo, hi] inclusive."""
        return prng.randint(self.rand_key(), lo, hi)

    def uniform(self) -> jax.Array:
        return prng.uniform(self.rand_key())

    def bernoulli(self, p) -> jax.Array:
        return prng.bernoulli(self.rand_key(), p)

    # -- per-node deterministic hash streams (collections.rs parity) -------
    def hash_key(self, stream=0) -> jax.Array:
        """This node's deterministic HASH-SEED key for `stream` — a pure
        function of (lane seed, ctx.node, stream), identical at every
        event of every schedule (r18). madsim seeds each HashMap's
        hasher from the sim rng (collections.rs) so iteration order is
        replay-stable; the analog here: a model that needs hash-like
        randomness (consistent-hash rings, probe orders, sampled
        subsets) derives it from this stream instead of `rand_key()`,
        whose value depends on the dispatch order — with `rand_key` a
        different interleaving reseeds every node's hash state and
        COUPLES nodes through the scheduler; with this stream node a's
        hash order never moves node b's. Consumes nothing: calling it
        (any number of times) leaves every other draw bit-identical."""
        if self._hash_base is None:
            raise ValueError(
                "hash_key() needs the runtime's seed-derived hash base — "
                "this Ctx was built without one (custom driver?); pass "
                "hash_base=SimState.hash_base / seed_key(seed)")
        return prng.node_hash_key(self._hash_base, self.node, stream)

    def hash_randint(self, lo, hi, stream=0) -> jax.Array:
        """Uniform int32 in [lo, hi] off this node's hash stream."""
        return prng.randint(self.hash_key(stream), lo, hi)

    # -- effects -----------------------------------------------------------
    def send(self, dst, tag, payload=None, *, when=True) -> None:
        """Queue a message (Endpoint::send_to analog, net/mod.rs:232-307).

        Delivery is scheduled by the engine at now + Uniform[latency range],
        subject to packet loss and the clog matrix (network.rs:222-229).
        `when` masks the send (handlers have static call counts; a
        CONCRETELY-False mask — only possible in the eager real-world
        runtime — skips the bookkeeping entirely).
        """
        from ..utils.maskutil import statically_false
        if statically_false(when):
            return
        self._sends.append(dict(
            m=jnp.asarray(when) & jnp.asarray(True),
            dst=jnp.asarray(dst, jnp.int32),
            tag=jnp.asarray(tag, jnp.int32),
            payload=as_payload(payload, self.cfg.payload_words),
        ))

    def set_timer(self, delay, tag, payload=None, *, when=True) -> None:
        """Schedule on_timer(tag, payload) at now + delay ticks
        (time::sleep analog, time/sleep.rs)."""
        from ..utils.maskutil import statically_false
        if statically_false(when):
            return
        self._timers.append(dict(
            m=jnp.asarray(when) & jnp.asarray(True),
            delay=jnp.maximum(jnp.asarray(delay, jnp.int32), 0),
            tag=jnp.asarray(tag, jnp.int32),
            payload=as_payload(payload, self.cfg.payload_words),
        ))

    def cancel_timer(self, tag, *, when=True) -> None:
        """Drop ALL of this node's pending timers carrying `tag` (the
        Sleep::reset / JoinHandle::abort analog, sleep.rs:44-55,
        task.rs:401-420).

        The freed event-table rows are reusable by this same handler's
        emissions. Protocols that re-arm retry timers per attempt can
        cancel the stale ones instead of letting them fire as no-ops —
        an event-table-pressure relief valve; the alternative idiom
        (call-id payloads that make stale firings no-ops) remains valid
        and replay-compatible.

        Ordering within one handler invocation (both worlds agree):
        ALL cancels are applied BEFORE any of the same invocation's
        set_timer emissions, regardless of call order in the handler
        body. So cancel-then-set is the supported re-arm idiom;
        set-then-cancel of the same tag leaves the NEW timer armed —
        the cancel only drops timers that existed when the handler
        began.
        """
        from ..utils.maskutil import statically_false
        if statically_false(when):
            return
        self._cancels.append(dict(
            m=jnp.asarray(when) & jnp.asarray(True),
            tag=jnp.asarray(tag, jnp.int32),
        ))

    def defer(self, tag, payload=None, *, when=True) -> None:
        """Continuation idiom: schedule on_timer(tag, payload) at the
        CURRENT deadline (a zero-delay timer).

        A madsim node's tasks interleave at every await point under the
        random scheduler (task.rs:128-143); here a handler is atomic, so a
        long multi-phase handler under-explores schedules. Splitting its
        phases with `defer` re-opens the interleaving: the continuation
        lands in the event table at the same virtual time as anything else
        due now, and the same-deadline random tie-break (mpsc.rs:75
        semantics) orders it against other nodes' events — the explicit
        state-machine form of yield_now/await. See DESIGN.md §3 and the
        coverage measurement in tests/test_core.py.
        """
        self.set_timer(0, tag, payload, when=when)

    def crash_if(self, cond, code: int) -> None:
        """Assert: if cond, the trajectory crashes with user code > 0 —
        the panic-in-task analog; the harness reports the seed."""
        cond = jnp.asarray(cond)
        first = cond & ~self._crash
        self._crash_code = jnp.where(first, jnp.asarray(code, jnp.int32),
                                     self._crash_code)
        self._crash = self._crash | cond

    def halt_if(self, cond=True) -> None:
        """Request normal end of simulation for this trajectory."""
        self._halt = self._halt | jnp.asarray(cond)


class Program:
    """A node program: the NodeBuilder::init + task-body analog
    (runtime/mod.rs:259-318), restructured as an explicit state machine
    (the TLA+/P-style modeling of distributed protocols).

    Subclass and override. All methods must be JAX-traceable (jnp ops,
    no data-dependent Python control flow).
    """

    def init(self, ctx: Ctx) -> None:
        """Node boot / restart: set initial state, arm initial timers."""

    def on_message(self, ctx: Ctx, src, tag, payload) -> None:
        """A message addressed to this node arrived."""

    def on_timer(self, ctx: Ctx, tag, payload) -> None:
        """A timer armed with set_timer fired."""
