"""The simulation state pytree.

One SimState value is an ENTIRE simulated cluster for one trajectory (seed):
virtual clock, PRNG key, the event table (timers + in-flight messages +
scheduled supervisor ops), per-node liveness and user protocol state, and the
network fault matrix. The reference spreads this across GlobalRng
(rand.rs:48), TimeRuntime (time/mod.rs), the executor's task queue (task.rs),
Network {clogged_node, clogged_link, config, stat} (network.rs:20-29), and
per-node mailboxes (net/mod.rs:368-411); here it is one fixed-shape pytree so
that `vmap` batches thousands of clusters and `jit` compiles one XLA program
that advances them all in lockstep.

There are no mailboxes: madsim needs them because a receiver task may not yet
be awaiting a tag when a message lands (net/mod.rs:368-411). In the
state-machine model, delivery *is* the invocation of `on_message`, so the
event table subsumes the mailbox.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from . import types as T

# SimState fields owned by the flight recorder (cfg.trace_cap), the
# causal-lineage layer (r10 — rides the same gate), the
# prefix-coverage sketch (cfg.sketch_slots), the sim-profiler
# counter plane (cfg.profile, r15 — the pf_* columns + the tr_qlen
# ring column), the SLO latency plane (cfg.latency_hist, r16 —
# the lh_* histograms, the ev_root_t root-birth-time column, and the
# tr_lat ring column), and the windowed telemetry plane
# (cfg.series_windows, r21 — the sr_* per-window series and the
# dynamic window_len operand). One schema constant so every consumer follows it
# automatically: excluded from fingerprints (utils/hashing —
# observation only, never a replay domain), read by obs/rings.py (the
# tr_* columns) and obs/profiler.py (the pf_* columns), compared
# explicitly in the fused-vs-chunked equivalence tests and bench.py
# --obs-smoke / --causal-smoke / --prof-smoke. trace_cap is the
# DYNAMIC capacity operand (columns are sized to the power-of-two
# bucket, cfg.trace_cap_bucket — DESIGN §10); sketch_every is the
# DYNAMIC fold period for the structurally sized cov_sketch column
# (DESIGN §12).
TRACE_FIELDS = ("trace_on", "trace_pos", "trace_cap", "tr_now", "tr_step",
                "tr_kind", "tr_node", "tr_src", "tr_tag",
                "tr_parent", "tr_lamport", "tr_qlen", "tr_lat", "tr_qw",
                "ev_prov", "lamport",
                "cov_sketch", "sketch_every",
                "pf_on", "pf_dispatch", "pf_busy", "pf_kill", "pf_restart",
                "pf_qmax", "pf_drop", "pf_delay",
                "lh_on", "ev_root_t", "lh_sojourn", "lh_e2e",
                "lh_slo_miss", "slo_target",
                "sr_on", "window_len", "sr_dispatch", "sr_busy", "sr_qhw",
                "sr_drop", "sr_dup", "sr_complete", "sr_slo_miss",
                "sr_lat", "sr_fault",
                "sp_on", "ev_span", "sa_tail", "sa_bottleneck",
                "hash_base")
# hash_base rides TRACE_FIELDS for the fingerprint-exclusion contract
# only: it is a CONSTANT pure function of the lane's seed (never
# written after init), so folding it into fingerprints would make two
# seeds with identical trajectories fingerprint differently — breaking
# distinct_outcomes. Unlike the recorder columns it IS consumed by the
# replay domain when a model opts in (Ctx.hash_key), but the seed that
# fingerprints already imply determines it completely.

# pf_dispatch's kind axis: one column per event kind (EV_FREE's column
# exists so t_kind values index directly but is never written — only
# valid dispatches count, and a valid dispatch is never EV_FREE).
# Derived from the enum so a new kind widens the counter automatically
N_EV_KINDS = T.EV_SUPER + 1

# ev_span's word axis (the r23 critical-path attribution plane): the
# per-row carried span vector, broadcast to every emission of a dispatch
# exactly like the ev_prov provenance pair. All words describe the
# row's CHAIN as of its enqueue; the dispatch that pops the row folds in
# its own queue-wait and incoming-edge transit before re-broadcasting.
SP_QWAIT = 0     # accumulated queue-wait ticks since the chain's root
SP_NET = 1       # accumulated network/disk transit ticks since the root
SP_HOPS = 2      # hop index: dispatches since the root (root row = 0)
SP_DOM_NODE = 3  # node owning the DOMINANT segment so far (-1 = none)
SP_DOM_MAG = 4   # that segment's magnitude (transit + wait ticks)
SP_EMIT_T = 5    # the emitting dispatch's virtual time (-1 = external)
SPAN_WORDS = 6

# sa_tail's component axis: per-completion-node tail attribution
SA_COUNT = 0     # tail completions (e2e > slo_target) at this node
SA_QWAIT = 1     # their accumulated queue-wait ticks
SA_NET = 2       # their accumulated network/disk transit ticks
SA_HOPS = 3      # their accumulated hop counts
SA_COMPONENTS = 4


@struct.dataclass
class SimState:
    # --- clock & rng & lifecycle -----------------------------------------
    now: jax.Array          # int32 ticks — virtual clock (ClockHandle analog)
    key: jax.Array          # uint32[2] — trajectory PRNG (GlobalRng analog)
    hash_base: jax.Array    # uint32[2] — the lane's UNCONSUMED seed key
                            # (seed_key(seed), frozen at init while `key`
                            # splits away): the root of the per-node
                            # deterministic HASH-SEED streams (r18,
                            # madsim collections.rs parity). Ctx.hash_key
                            # derives fold_in(fold_in(this, DOMAIN),
                            # node) — a pure (seed, node) function, so
                            # model-level hash iteration order is
                            # schedule-stable and can't couple nodes.
                            # Never written after init; excluded from
                            # fingerprints (see TRACE_FIELDS note).
    halted: jax.Array       # bool — simulation finished (normally or crashed)
    crashed: jax.Array      # bool — an invariant/assertion failed
    crash_code: jax.Array   # int32 — which invariant (user >0, engine <0)
    crash_node: jax.Array   # int32 — node implicated, -1 if n/a
    oops: jax.Array         # int32 bitmask — capacity overflows
    steps: jax.Array        # int32 — events dispatched so far
    sched_hash: jax.Array   # uint32[2] — running hash of the dispatch
                            # sequence (kind/node/src/tag of every event, in
                            # order). Two trajectories with different
                            # interleavings get different hashes even when
                            # they converge to the same terminal state — the
                            # schedule-coverage metric proper, vs the
                            # terminal-fingerprint proxy (task.rs:572-596
                            # asserts N seeds -> N schedules; this is the
                            # batched measurement of that property).
                            # Two independent 32-bit lanes = 64 effective
                            # bits: at the 100k-seed fuzz scale a single
                            # 32-bit lane's birthday collisions (~n²/2³³)
                            # would undercount distinct_schedules and stop
                            # explore()'s dry-round loop early. Combine with
                            # parallel/stats.sched_hash_u64 for analysis.
    tlimit: jax.Array       # int32 ticks — virtual-time limit; DYNAMIC (like
                            # loss/latency) so set_time_limit / the
                            # MADSIM_TEST_TIME_LIMIT env knob need no recompile

    # --- event table [C] --------------------------------------------------
    t_deadline: jax.Array   # int32[C] — fire time (T_INF when slot free)
    t_kind: jax.Array       # int32[C] — EV_FREE/MSG/TIMER/SUPER
    t_node: jax.Array       # int32[C] — destination node
    t_src: jax.Array        # int32[C] — source node (msgs) / link src (super)
    t_tag: jax.Array        # int32[C] — msg tag / timer tag / super opcode
    t_payload: jax.Array    # int32[C, P]

    # --- causal lineage (r10; compiled in iff cfg.trace_cap > 0) ----------
    # A provenance matrix for the pending rows above, plus one Lamport
    # clock per node — together they let the ring carry (parent_dispatch,
    # lamport) for every dispatched event, so a crash explains itself by
    # walking parent edges backward (obs/causal.py) even after the ring
    # wrapped. Observation only: no randomness consumed, excluded from
    # fingerprints, zero-size when the recorder is compiled out. One
    # [C, 2] matrix, not two [C] columns: the step then pays ONE extra
    # emission write and ONE extra dispatch gather (the t_payload shape,
    # half the lineage cost measured by bench.py --mode causal_ab).
    ev_prov: jax.Array      # int32[C, 2] — per pending row:
                            # [0] dispatch index of the step that
                            #     enqueued it; -1 = external (scenario
                            #     row, node boot, host-injected op)
                            # [1] the Lamport timestamp it carries
                            #     (sender's clock at enqueue — the
                            #     "message timestamp" of the Lamport rule)
    lamport: jax.Array      # int32[N] — per-node Lamport clock:
                            # max(own, carried) + 1 at every dispatch

    # --- nodes ------------------------------------------------------------
    alive: jax.Array        # bool[N]
    paused: jax.Array       # bool[N]
    node_state: Any         # user pytree, leaves with leading [N] axis

    # --- network fault matrix (NetSim analog) ----------------------------
    clog_node: jax.Array    # bool[N] — NetSim::clog_node
    clog_link: jax.Array    # bool[N, N] — NetSim::clog_link (src, dst);
                            # OP_PARTITION_ONEWAY (r17) ORs directional
                            # cuts into it — asymmetric partitions are
                            # just an asymmetric matrix
    loss: jax.Array         # float32 — packet_loss_rate
    lat_lo: jax.Array       # int32 ticks — send_latency range
    lat_hi: jax.Array       # int32 ticks
    jitter: jax.Array       # int32 ticks — per-op micro-jitter bound
                            # (NetConfig.op_jitter_max; net/mod.rs:151-156)

    # --- gray-failure fault plane (r17; DESIGN §18) ------------------------
    # All three are DYNAMIC replay-domain state (they change trajectories,
    # so they ride in fingerprints and checkpoints — simconfig-v5 rejects
    # pre-r17 snapshots): always compiled in, exact identity at the zero
    # defaults (the bit-identical-to-r16 contract tests/test_grayfail.py
    # holds against captured golden digests). Set by scenario ops
    # (OP_SET_SKEW / OP_SET_DISK), mutated by the fuzzer's fault_perturb
    # havoc operator through the scenario rows.
    skew: jax.Array         # int32[N] — per-node clock-RATE skew in
                            # 1/1024ths: node n's local clock reads
                            # now + (now·skew[n])>>10 (handlers observe it
                            # as ctx.now) and its timer delays shrink or
                            # stretch inversely — a fast clock fires
                            # timeouts early in global time, the
                            # lease-expiry/timeout-ordering gray failure.
                            # Exact integer arithmetic (no float log/mul):
                            # deterministic, identity at 0.
    disk_lat: jax.Array     # int32[N] — slow-disk emission delay in ticks:
                            # every send latency and timer deadline the
                            # node emits is pushed this much later (an
                            # fsync-stalled event loop emits late). 0 = no
                            # fault.
    torn: jax.Array         # bool[N] — torn-write-on-kill mode: a KILL of
                            # this node flushes a random prefix of each
                            # fs file's unsynced tail to the durable view
                            # before process memory dies, so recovery can
                            # observe a partially-written final record
                            # (fs-layer state schemas only; inert
                            # otherwise). The tear draw rides a key split
                            # the step already made, so enabling it never
                            # shifts the PRNG stream of anything else.

    # --- connection-fault plane (r19; DESIGN §20) --------------------------
    dup_rate: jax.Array     # int32[N] — per-node duplicate-delivery rate
                            # in PARTS PER MILLION (the OP_SET_LOSS
                            # encoding), set by OP_SET_DUP and capped at
                            # DUP_RATE_CAP: a dispatched MESSAGE at the
                            # node is re-armed for one more delivery with
                            # this probability instead of being freed —
                            # byte-identical payload, later deadline, and
                            # it may duplicate again (the retransmit-storm
                            # regime). The decision/delay draws ride keys
                            # FOLDED off the already-consumed scheduler
                            # key, so the zero default consumes nothing
                            # from any stream — bit-identical to r18
                            # (tests/test_connfault.py holds it against
                            # golden digests captured at r18 HEAD).
                            # Replay-domain state like skew/disk_lat:
                            # rides in fingerprints and checkpoints
                            # (simconfig-v6 rejects pre-r19 snapshots).

    # --- schedule search (search/pct.py) ----------------------------------
    prio_nudge: jax.Array   # int32 — PCT-style priority-perturbation point.
                            # 0 (the default) leaves the scheduler's random
                            # tie-break untouched and is BIT-IDENTICAL to a
                            # build without the hook; any nonzero value
                            # replaces the tie-break among earliest-deadline
                            # slots with a deterministic priority order keyed
                            # on (nudge, slot identity) — one nudge = one
                            # tie-breaking policy, so a fuzzer sweeps
                            # scheduler decisions as a DYNAMIC knob (no
                            # recompile, step.py §1). Part of the replay
                            # domain: it changes trajectories, so it rides
                            # in fingerprints, unlike the trace ring.

    # --- stats (NetSim::stat analog, network.rs:82-85) --------------------
    msg_sent: jax.Array
    msg_delivered: jax.Array
    msg_dropped: jax.Array
    ev_peak: jax.Array      # int32 — high-water mark of occupied event rows
                            # (capacity-tuning aid: size event_capacity to
                            # the workload instead of guessing)

    # --- flight-recorder ring (obs/rings.py; cfg.trace_cap) ---------------
    # A fixed-capacity ring of the last trace_cap dispatched events for
    # this lane, written inside the step — ring state RIDES IN SimState,
    # so it survives `lax.while_loop` and the fused runner yields traces.
    # trace_cap == 0 gives zero-size columns (compiled out). Columns are
    # always int32: like the collect_events record schema, table_dtype
    # must not leak into what observers read.
    trace_on: jax.Array     # bool — lane-sampling gate (init_batch sets it;
                            # lets a B=4096 sweep record e.g. 8 lanes)
    trace_pos: jax.Array    # int32 — events recorded so far (monotonic;
                            # the write slot is trace_pos % trace_cap, so
                            # pos > cap means the ring wrapped)
    trace_cap: jax.Array    # int32 — LOGICAL ring capacity (dynamic:
                            # cfg.trace_cap; the columns below are sized
                            # to its power-of-two bucket so sweeping
                            # trace_cap never recompiles — rows past
                            # trace_cap are simply never written)
    tr_now: jax.Array       # int32[bucket] — virtual time of the event
    tr_step: jax.Array      # int32[bucket] — step index (cross-ref with
                            # collect_events row order / state_at)
    tr_kind: jax.Array      # int32[bucket]
    tr_node: jax.Array      # int32[bucket]
    tr_src: jax.Array       # int32[bucket]
    tr_tag: jax.Array       # int32[bucket]
    tr_parent: jax.Array    # int32[bucket] — the dispatched event's
                            # ev_parent (the happens-before edge; -1 =
                            # external) — recorded per event, so the
                            # causal chain survives ring wrap up to the
                            # oldest surviving record
    tr_lamport: jax.Array   # int32[bucket] — the acting node's Lamport
                            # clock AFTER this dispatch
    tr_qlen: jax.Array      # int32[bucket] — event-table occupancy at
                            # this dispatch (rows pending INCLUDING the
                            # row being dispatched) — the queue-depth
                            # counter-track source (obs/profiler.py).
                            # Compiled in only when BOTH the ring and
                            # the profiler are (cfg.trace_cap > 0 and
                            # cfg.profile); zero-size otherwise, and
                            # ring readers skip zero-size columns
    tr_lat: jax.Array       # int32[bucket] — the dispatch's END-TO-END
                            # request latency when it was a completion
                            # (cfg.complete_kinds), -1 otherwise — the
                            # rolling-p99 counter-track source
                            # (obs/profiler.py). Compiled in only when
                            # BOTH the ring and the latency plane are
                            # (cfg.trace_cap > 0 and cfg.latency_hist);
                            # same skip contract as tr_qlen

    # --- prefix-coverage sketch (cfg.sketch_slots; obs/causal.py) ---------
    # Slot j holds the running sched_hash (lanes XOR-folded) after this
    # lane's (j+1)*sketch_every-th dispatch: two lanes' sketches first
    # differ at the slot whose schedule prefix first diverged — the
    # per-lane divergence depth parallel/stats.divergence_profile and
    # the corpus's early-divergence energy bonus read, with zero host
    # round-trips during the run. 0 means "checkpoint not reached".
    cov_sketch: jax.Array   # uint32[sketch_slots]
    sketch_every: jax.Array  # int32 — DYNAMIC fold period (cfg.sketch_every)

    # --- sim-profiler counter plane (cfg.profile; obs/profiler.py) --------
    # Per-lane, on-device counters written through the step's existing
    # one-hot dispatch machinery — where the simulated cluster spends
    # its effort, resident in SimState so a fused while_loop sweep
    # comes back with per-node utilization at zero new host
    # round-trips. Observation only (TRACE_FIELDS): no randomness
    # consumed, excluded from fingerprints, zero-size [N]/[N, K]
    # columns when compiled out (cfg.profile=False). All counters
    # SATURATE at int32 max — a long campaign reads "pegged", never a
    # wrapped negative (DESIGN §16).
    pf_on: jax.Array        # bool — lane gate (init_batch(profile_lanes=))
    pf_dispatch: jax.Array  # int32[N, N_EV_KINDS] — dispatches by
                            # (acting node, event kind); supervisor ops
                            # count at the node _apply_super RESOLVED
                            # (the Lamport-rule node), not the
                            # NODE_RANDOM placeholder
    pf_busy: jax.Array      # int32[N] — busy virtual time: each
                            # dispatch's now-delta attributed to its
                            # acting node (sums to final `now` over
                            # nodes when every step advanced the clock)
    pf_kill: jax.Array      # int32[N] — effective KILL/RESTART ops at
                            # this node (crash injections landed)
    pf_restart: jax.Array   # int32[N] — effective INIT/RESTART boots
    pf_qmax: jax.Array      # int32 — event-table occupancy high-water
                            # mark as seen at dispatch + emission time
                            # (capacity tuning; unlike ev_peak this
                            # also counts the pre-pop dispatch row and
                            # rides the profile gate, not collect_stats)
    pf_drop: jax.Array      # int32 — messages lost: send-side
                            # clog/loss + deliveries to dead nodes
    pf_delay: jax.Array     # int32 — total latency ticks added to
                            # delivered sends (mean delay =
                            # pf_delay / delivered sends)

    # --- SLO latency plane (cfg.latency_hist; obs/profiler.py) ------------
    # Log2-bucketed request-latency histograms that live ON the device
    # (DESIGN §17): bucket j counts latencies in [2^(j-1), 2^j) ticks
    # (bucket 0 = zero). Written through the step's one-hot dispatch
    # machinery like the pf_* counters; SATURATING; observation only
    # (TRACE_FIELDS — no randomness, no non-latency state, excluded
    # from fingerprints; zero-size when compiled out).
    lh_on: jax.Array        # bool — lane gate (init_batch(latency_lanes=))
    ev_root_t: jax.Array    # int32[C] — per pending row: virtual birth
                            # time of the row's causal ROOT request;
                            # -1 = external/unset (scenario rows, boots,
                            # host injections) — minted as the dispatch
                            # `now` at dispatch time and inherited by
                            # every emission of that dispatch (the r10
                            # provenance broadcast, carrying a time)
    lh_sojourn: jax.Array   # int32[N, B] — queue-wait per dispatch
                            # (now − dispatched row's deadline) at the
                            # acting node, log2-bucketed
    lh_e2e: jax.Array       # int32[N, B] — end-to-end latency
                            # (now − root birth time) of dispatches of
                            # cfg.complete_kinds, at the completion node
    lh_slo_miss: jax.Array  # int32[N] — completions with e2e latency
                            # > slo_target (when slo_target > 0)
    slo_target: jax.Array   # int32 ticks — DYNAMIC per-lane SLO target
                            # (cfg.slo_target seeds it; retune/fuzz
                            # without recompile, like tlimit)

    # --- windowed telemetry plane (cfg.series_windows; obs/series.py) -----
    # Per-lane sim-time metric SERIES resident in SimState (DESIGN §22):
    # window w covers virtual ticks [w*window_len, (w+1)*window_len),
    # events past W*window_len clamp into the last window. Written
    # through the step's one-hot dispatch machinery like the pf_*/lh_*
    # planes; SATURATING; observation only (TRACE_FIELDS — no
    # randomness, no non-series state, excluded from fingerprints;
    # zero-size when compiled out). Answers WHEN, not just how much:
    # a brownout during a partition window, a queue that spikes and
    # drains, a system that never recovers after heal.
    sr_on: jax.Array        # bool — lane gate (init_batch(series_lanes=))
    window_len: jax.Array   # int32 ticks per window — DYNAMIC operand
                            # (cfg.window_len seeds it; retune without
                            # recompile via Runtime.set_window_len)
    sr_dispatch: jax.Array  # int32[W, N] — dispatches by (window,
                            # acting node); supervisor ops count at the
                            # node _apply_super resolved (the pf_dispatch
                            # attribution rule)
    sr_busy: jax.Array      # int32[W, N] — busy virtual ticks by
                            # (window, acting node): each dispatch's
                            # now-delta lands in the window it ended in
    sr_qhw: jax.Array       # int32[W] — event-table occupancy
                            # high-water inside the window (dispatch +
                            # emission time, the pf_qmax rule per window)
    sr_drop: jax.Array      # int32[W] — messages lost in the window
                            # (send-side clog/loss + dead-node delivery)
    sr_dup: jax.Array       # int32[W] — duplicate re-arms fired in the
                            # window (the r19 dup-storm axis over time)
    sr_complete: jax.Array  # int32[W] — completions (cfg.complete_kinds)
                            # dispatched in the window; stays zero when
                            # the latency plane is off
    sr_slo_miss: jax.Array  # int32[W] — completions over slo_target in
                            # the window
    sr_lat: jax.Array       # int32[W, B] — per-window e2e log2
                            # histograms (the per-window p99 source for
                            # the recovery oracle and the sim-time
                            # counter tracks). Compiled in only when
                            # BOTH this plane and cfg.latency_hist are;
                            # zero-size otherwise
    sr_fault: jax.Array     # int32[W] — SRF_* bitmask of fault classes
                            # that landed in the window (OR-accumulated,
                            # never saturates) — the recovery oracle's
                            # "last disturbed window" axis

    # --- critical-path attribution plane (cfg.span_attr; obs/spans.py) ----
    # WHERE the tail comes from (DESIGN §24): every pending row carries
    # its chain's accumulated span vector (the ev_prov/ev_root_t
    # broadcast-select, carrying SPAN_WORDS words instead of one), and a
    # completion over the dynamic slo_target folds it into per-node
    # tail-attribution counters through the one-hot machinery.
    # Observation only (TRACE_FIELDS): no randomness, no non-span state,
    # excluded from fingerprints; zero-size when compiled out
    # (cfg.span_attr=False). Counters SATURATE at int32 max (§16).
    sp_on: jax.Array        # bool — lane gate (init_batch(span_lanes=))
    ev_span: jax.Array      # int32[C, SPAN_WORDS] — per pending row: the
                            # chain's accumulated queue-wait / transit /
                            # hops, dominant (node, magnitude), and the
                            # emitting dispatch's virtual time (see the
                            # SP_* word index above); external rows are
                            # [0, 0, 0, -1, 0, -1]
    sa_tail: jax.Array      # int32[N, SA_COMPONENTS] — per COMPLETION
                            # node: count / queue-wait / transit / hops
                            # of tail completions (e2e > slo_target);
                            # queue + transit of a completion sum to its
                            # e2e latency exactly (the telescoping rule,
                            # DESIGN §24) — the invariant the host
                            # parent-walk cross-check holds device-vs-ring
    sa_bottleneck: jax.Array  # int32[N] — how often node n owned a tail
                            # completion's DOMINANT segment (largest
                            # wait+transit hop) — the bottleneck histogram
    tr_qw: jax.Array        # int32[bucket] — the dispatch's OWN
                            # queue-wait (now − the popped row's
                            # deadline): the ring column that lets a host
                            # parent-walk split every hop into wait vs
                            # transit (obs/spans.py). Compiled in only
                            # when BOTH the ring and the span plane are
                            # (cfg.trace_cap > 0 and cfg.span_attr);
                            # same skip contract as tr_qlen/tr_lat

    # --- extension state (plugin framework analog, plugin.rs) -------------
    ext: Any                # dict: extension name -> its state subtree


def init_state(cfg: T.SimConfig, key: jax.Array, node_state: Any,
               ext_state: Any = None) -> SimState:
    """Fresh state for one trajectory. `node_state` must already carry the
    leading [N] axis (Runtime stacks the per-node spec)."""
    C, P, N = cfg.event_capacity, cfg.payload_words, cfg.n_nodes
    i32 = jnp.int32
    # narrow columns (cfg.table_dtype): same values, half the bytes —
    # t_tag/t_deadline/t_payload stay int32 (29-bit tags, time, data)
    ti = jnp.int16 if cfg.table_dtype == "int16" else jnp.int32
    return SimState(
        now=jnp.asarray(0, i32),
        key=key,
        # an OWNED copy, never the same buffer: runners donate the state,
        # and two pytree leaves aliasing one buffer break donation
        hash_base=jnp.array(key, copy=True),
        halted=jnp.asarray(False),
        crashed=jnp.asarray(False),
        crash_code=jnp.asarray(T.CRASH_NONE, i32),
        crash_node=jnp.asarray(-1, i32),
        oops=jnp.asarray(0, i32),
        steps=jnp.asarray(0, i32),
        # lane 0: FNV-1a 32 offset basis; lane 1: low half of the FNV-1a 64
        # offset basis (any distinct odd-ish seed works — the lanes only
        # need independent trajectories)
        sched_hash=jnp.asarray([2166136261, 0x84222325], jnp.uint32),
        tlimit=jnp.asarray(cfg.time_limit, i32),
        t_deadline=jnp.full((C,), T.T_INF, i32),
        t_kind=jnp.zeros((C,), ti),
        t_node=jnp.zeros((C,), ti),
        t_src=jnp.zeros((C,), ti),
        t_tag=jnp.zeros((C,), i32),
        t_payload=jnp.zeros((C, P), i32),
        # lineage rides the recorder gate (zero-size when compiled out);
        # template/scenario rows are external: parent -1, carried clock 0
        ev_prov=jnp.tile(jnp.asarray([[-1, 0]], i32),
                         (C if cfg.trace_cap > 0 else 0, 1)),
        lamport=jnp.zeros((N if cfg.trace_cap > 0 else 0,), i32),
        alive=jnp.zeros((N,), bool),
        paused=jnp.zeros((N,), bool),
        node_state=node_state,
        clog_node=jnp.zeros((N,), bool),
        clog_link=jnp.zeros((N, N), bool),
        loss=jnp.asarray(cfg.net.packet_loss_rate, jnp.float32),
        lat_lo=jnp.asarray(cfg.net.send_latency_min, i32),
        lat_hi=jnp.asarray(cfg.net.send_latency_max, i32),
        jitter=jnp.asarray(cfg.net.op_jitter_max, i32),
        skew=jnp.zeros((N,), i32),
        disk_lat=jnp.zeros((N,), i32),
        torn=jnp.zeros((N,), bool),
        dup_rate=jnp.zeros((N,), i32),
        prio_nudge=jnp.asarray(0, i32),
        msg_sent=jnp.asarray(0, i32),
        msg_delivered=jnp.asarray(0, i32),
        msg_dropped=jnp.asarray(0, i32),
        ev_peak=jnp.asarray(0, i32),
        # recorder default: every lane samples (when the ring is compiled
        # in at all); init_batch(trace_lanes=...) narrows the mask.
        # Columns are bucket-sized; trace_cap is the dynamic capacity.
        trace_on=jnp.asarray(cfg.trace_cap > 0),
        trace_pos=jnp.asarray(0, i32),
        trace_cap=jnp.asarray(cfg.trace_cap, i32),
        tr_now=jnp.zeros((cfg.trace_cap_bucket,), i32),
        tr_step=jnp.zeros((cfg.trace_cap_bucket,), i32),
        tr_kind=jnp.zeros((cfg.trace_cap_bucket,), i32),
        tr_node=jnp.zeros((cfg.trace_cap_bucket,), i32),
        tr_src=jnp.zeros((cfg.trace_cap_bucket,), i32),
        tr_tag=jnp.zeros((cfg.trace_cap_bucket,), i32),
        tr_parent=jnp.zeros((cfg.trace_cap_bucket,), i32),
        tr_lamport=jnp.zeros((cfg.trace_cap_bucket,), i32),
        # the queue-depth ring column needs both gates (see field docs)
        tr_qlen=jnp.zeros((cfg.trace_cap_bucket if cfg.profile else 0,),
                          i32),
        # the e2e-latency ring column likewise (ring AND latency plane)
        tr_lat=jnp.full((cfg.trace_cap_bucket if cfg.latency_hist > 0
                         else 0,), -1, i32),
        cov_sketch=jnp.zeros((cfg.sketch_slots,), jnp.uint32),
        sketch_every=jnp.asarray(cfg.sketch_every, i32),
        # profiler default: every lane counts (when the plane is
        # compiled in at all); init_batch(profile_lanes=...) narrows.
        # Vector columns are zero-size when compiled out, scalars stay
        # (never written then — same shape discipline as trace_pos)
        pf_on=jnp.asarray(cfg.profile),
        pf_dispatch=jnp.zeros((N if cfg.profile else 0, N_EV_KINDS), i32),
        pf_busy=jnp.zeros((N if cfg.profile else 0,), i32),
        pf_kill=jnp.zeros((N if cfg.profile else 0,), i32),
        pf_restart=jnp.zeros((N if cfg.profile else 0,), i32),
        pf_qmax=jnp.asarray(0, i32),
        pf_drop=jnp.asarray(0, i32),
        pf_delay=jnp.asarray(0, i32),
        # latency-plane default: every lane records (when compiled in);
        # init_batch(latency_lanes=...) narrows. Same zero-size shape
        # discipline as the pf_* columns; ev_root_t starts all-external
        lh_on=jnp.asarray(cfg.latency_hist > 0),
        ev_root_t=jnp.full((C if cfg.latency_hist > 0 else 0,), -1, i32),
        lh_sojourn=jnp.zeros((N if cfg.latency_hist > 0 else 0,
                              cfg.latency_hist), i32),
        lh_e2e=jnp.zeros((N if cfg.latency_hist > 0 else 0,
                          cfg.latency_hist), i32),
        lh_slo_miss=jnp.zeros((N if cfg.latency_hist > 0 else 0,), i32),
        slo_target=jnp.asarray(cfg.slo_target, i32),
        # windowed-telemetry default: every lane records (when compiled
        # in); init_batch(series_lanes=...) narrows. Zero-size [W]/[W, .]
        # columns at series_windows == 0; window_len stays a scalar
        # operand either way (never read then — the trace_pos shape
        # discipline). sr_lat needs BOTH gates, like tr_lat.
        sr_on=jnp.asarray(cfg.series_windows > 0),
        window_len=jnp.asarray(cfg.window_len, i32),
        sr_dispatch=jnp.zeros((cfg.series_windows, N), i32),
        sr_busy=jnp.zeros((cfg.series_windows, N), i32),
        sr_qhw=jnp.zeros((cfg.series_windows,), i32),
        sr_drop=jnp.zeros((cfg.series_windows,), i32),
        sr_dup=jnp.zeros((cfg.series_windows,), i32),
        sr_complete=jnp.zeros((cfg.series_windows,), i32),
        sr_slo_miss=jnp.zeros((cfg.series_windows,), i32),
        sr_lat=jnp.zeros((cfg.series_windows if cfg.latency_hist > 0
                          else 0, cfg.latency_hist), i32),
        sr_fault=jnp.zeros((cfg.series_windows,), i32),
        # span-attribution default: every lane attributes (when compiled
        # in); init_batch(span_lanes=...) narrows. Rows start external
        # ([0,0,0,-1,0,-1] — nothing accumulated, no dominant segment,
        # no emitter); tr_qw needs BOTH gates, like tr_qlen/tr_lat.
        sp_on=jnp.asarray(cfg.span_attr),
        ev_span=jnp.tile(jnp.asarray([[0, 0, 0, -1, 0, -1]], i32),
                         (C if cfg.span_attr else 0, 1)),
        sa_tail=jnp.zeros((N if cfg.span_attr else 0, SA_COMPONENTS), i32),
        sa_bottleneck=jnp.zeros((N if cfg.span_attr else 0,), i32),
        tr_qw=jnp.zeros((cfg.trace_cap_bucket if cfg.span_attr else 0,),
                        i32),
        ext=ext_state if ext_state is not None else {},
    )


# ---------------------------------------------------------------------------
# Lane checkpoints (r20, DESIGN §21): checkpoint ONE lane of a batched
# state — gather its leaves into an owned host copy — and broadcast it
# back into a fresh batch later. The snapshot/fork primitive: a lane
# seeded back with unchanged knobs/nudge continues leaf-for-leaf
# bit-identical to the parent lane (the step is a pure function of
# state), and a batch of B clones forked with fresh nudges/knob deltas
# amortizes the shared prefix (the Podracer branching-rollout shape).
# ---------------------------------------------------------------------------

# Observation planes a checkpoint may be re-seeded into a runtime with a
# DIFFERENT observability build than it was captured under (window
# replay upgrades the ring/profiler/latency plane mid-trajectory).
# Each plane adapts as a UNIT: when every leaf of the plane matches the
# target runtime's shapes/dtypes the checkpoint values are preserved
# verbatim (the bit-identical-continuation case); when any leaf differs
# the whole plane is re-initialized from the target runtime's template
# (fresh empty ring, external provenance, zeroed counters) — legal
# because the planes are observation-only (TRACE_FIELDS, DESIGN §9):
# they never feed the replay domain, so the trajectory and its
# fingerprint are unchanged either way. hash_base is the one
# TRACE_FIELDS member outside the planes: it IS consumed by the replay
# domain (ctx.hash_key) and is always carried over.
_CKPT_PLANES = {
    "ring": ("trace_on", "trace_pos", "trace_cap", "tr_now", "tr_step",
             "tr_kind", "tr_node", "tr_src", "tr_tag", "tr_parent",
             "tr_lamport", "tr_qlen", "tr_lat", "tr_qw"),
    "lineage": ("ev_prov", "lamport"),
    "sketch": ("cov_sketch", "sketch_every"),
    "profile": ("pf_on", "pf_dispatch", "pf_busy", "pf_kill", "pf_restart",
                "pf_qmax", "pf_drop", "pf_delay"),
    "latency": ("lh_on", "ev_root_t", "lh_sojourn", "lh_e2e",
                "lh_slo_miss", "slo_target"),
    "series": ("sr_on", "window_len", "sr_dispatch", "sr_busy", "sr_qhw",
               "sr_drop", "sr_dup", "sr_complete", "sr_slo_miss",
               "sr_lat", "sr_fault"),
    "span": ("sp_on", "ev_span", "sa_tail", "sa_bottleneck"),
}

# the WORLD slice of a structural signature: the fields two runtimes
# must agree on for a checkpoint's replay state to continue bit-
# identically — shapes of the replay-domain leaves (n_nodes,
# event_capacity, payload_words, table_dtype), the stats gate
# (collect_stats changes msg_* trajectories), and the jitter gate (a
# distinct replay domain). The OBSERVABILITY fields (trace bucket,
# sketch_slots, profile, latency_hist, complete/root kinds) and the
# emission_write lowering are deliberately excluded: differing there is
# the point of window replay. Indexes into the simconfig-v8 tuple
# (types.SimConfig.structural_signature — v7/v8 appended
# series_windows/span_attr at the END, so these indices still name the
# same world fields); the version string at [0] keeps the indexing
# honest across future signature revisions, and a pre-r23 (v7)
# checkpoint/store rejects on it automatically.
_SIG_WORLD_IDX = (0, 1, 2, 3, 4, 6, 9)

_LANE_CKPT_FORMAT = "madsim-lane-ckpt-r20"


class CheckpointMismatch(ValueError):
    """A LaneCheckpoint does not fit the target runtime's world shape
    (the StoreMismatch analog for checkpoints): different cluster
    size/event capacity/table dtype/model state schema, or a pre-r20
    checkpoint file without the versioned lane-checkpoint header."""


def _world_slice(sig) -> tuple:
    sig = tuple(sig)
    return tuple(sig[i] for i in _SIG_WORLD_IDX if i < len(sig))


def checkpoint_lane(batch_state: SimState, lane: int,
                    signature=None) -> "LaneCheckpoint":
    """Snapshot ONE lane of a batched SimState: one gather per leaf,
    then an owned host copy (the r8 donation discipline — the returned
    checkpoint outlives later donated runs of the batch's buffers).

    `signature` (the capturing runtime's `cfg.structural_signature()`)
    rides along for the save/load contract and the world-shape check in
    `seed_batch_from(rt=...)`; None skips the signature check (leaf
    shape/dtype validation still applies)."""
    leaf0 = jax.tree.leaves(batch_state)[0]
    if np.ndim(leaf0) < 1:
        raise ValueError("checkpoint_lane takes a BATCHED state "
                         "(leading lane axis); got an unbatched pytree")
    from ..utils.hostcopy import owned_host_copy
    lane = int(lane)
    lane_state = owned_host_copy(
        jax.tree.map(lambda a: a[lane], batch_state))
    return LaneCheckpoint(state=lane_state,
                          steps=int(np.asarray(lane_state.steps)),
                          signature=(tuple(signature)
                                     if signature is not None else None))


@dataclasses.dataclass
class LaneCheckpoint:
    """One lane's full simulation state, host-owned — everything the
    step function needs to continue the trajectory (clock, key, event
    table, node state, fault matrices, knobs/nudge) plus whatever
    observation-plane state the capturing build carried.

    `steps` is the lane's dispatch count at capture; `signature` the
    capturing runtime's structural signature (None when captured
    without one)."""

    state: Any
    steps: int
    signature: tuple | None = None

    # -- durable form (MIGRATION r20: versioned like the corpus store) --
    def save(self, path: str) -> None:
        """Write the checkpoint as an .npz with a versioned header —
        format marker, structural signature, step count, treedef — so
        `load` can reject mismatches cleanly instead of replaying a
        foreign world. Pre-r20 batch snapshots (runtime/checkpoint.py)
        carry no header and are rejected by `load`."""
        leaves, treedef = jax.tree.flatten(self.state)
        np.savez_compressed(
            path,
            __lane_ckpt__=np.frombuffer(
                _LANE_CKPT_FORMAT.encode(), dtype=np.uint8),
            __signature__=np.frombuffer(
                repr(self.signature).encode(), dtype=np.uint8),
            __steps__=np.asarray(int(self.steps), np.int64),
            __treedef__=np.frombuffer(
                repr(treedef).encode(), dtype=np.uint8),
            **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)})

    @staticmethod
    def load(path: str, rt=None, like: SimState | None = None
             ) -> "LaneCheckpoint":
        """Read a checkpoint written by `save`. Pass the runtime it will
        be seeded into (`rt`, preferred — supplies both the pytree
        structure and the structural signature for the world-shape
        check) or a bare single-lane `like` state for structure only.

        Rejections are CLEAN and typed: a file without the r20 header
        (e.g. a pre-r20 `runtime.checkpoint.save` batch snapshot) or
        with a mismatched format version raises CheckpointMismatch, as
        does a stored signature whose WORLD slice disagrees with `rt`'s
        (observability fields may differ — that is window replay's
        upgrade path, resolved leaf-by-leaf in `seed_batch_from`)."""
        import ast
        if rt is not None and like is None:
            like = rt._template
        if like is None:
            raise ValueError("LaneCheckpoint.load needs rt= or like= "
                             "to supply the pytree structure")
        with np.load(path) as z:
            if "__lane_ckpt__" not in z.files:
                raise CheckpointMismatch(
                    f"{path}: no lane-checkpoint header — a pre-r20 "
                    "snapshot (runtime.checkpoint.save batch format?) "
                    "cannot be loaded as a LaneCheckpoint")
            fmt = bytes(z["__lane_ckpt__"]).decode()
            if fmt != _LANE_CKPT_FORMAT:
                raise CheckpointMismatch(
                    f"{path}: lane-checkpoint format {fmt!r} != "
                    f"{_LANE_CKPT_FORMAT!r}")
            sig = ast.literal_eval(bytes(z["__signature__"]).decode())
            steps = int(z["__steps__"])
            # the signature is the authoritative world contract — check
            # it BEFORE leaf counting so a foreign world is named as
            # such, not as a leaf-count coincidence
            if (rt is not None and sig is not None
                    and _world_slice(sig)
                    != _world_slice(rt.cfg.structural_signature())):
                raise CheckpointMismatch(
                    f"{path}: checkpoint world signature "
                    f"{_world_slice(sig)} != runtime's "
                    f"{_world_slice(rt.cfg.structural_signature())}")
            leaves_like, treedef = jax.tree.flatten(like)
            n = len([k for k in z.files if k.startswith("leaf_")])
            if n != len(leaves_like):
                raise CheckpointMismatch(
                    f"{path}: checkpoint has {n} leaves, target expects "
                    f"{len(leaves_like)} — different world/model?")
            state = jax.tree.unflatten(
                treedef, [z[f"leaf_{i}"] for i in range(n)])
        return LaneCheckpoint(state=state, steps=steps, signature=sig)


def _tree_spec_equal(a, b) -> bool:
    if jax.tree.structure(a) != jax.tree.structure(b):
        return False
    return all(np.shape(x) == np.shape(y)
               and np.asarray(x).dtype == np.asarray(y).dtype
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def seed_batch_from(ckpt: LaneCheckpoint, batch: int, rt=None,
                    reset_planes: tuple = ()) -> SimState:
    """Broadcast a lane checkpoint into a fresh [batch]-lane SimState:
    every lane a clone of the checkpointed lane, mid-trajectory. With
    unchanged knobs/nudge each lane continues leaf-for-leaf
    bit-identical to the parent (the fidelity contract,
    tests/test_timetravel.py); perturb lanes afterwards
    (`with_prio_nudge`, `KnobPlan.apply`) to FORK the trajectory — the
    prefix-fork primitive.

    rt=None broadcasts the checkpoint verbatim (the caller promises a
    structurally identical runtime). With `rt`, the checkpoint is
    validated against — and adapted to — that runtime: every
    replay-domain leaf must match shape/dtype exactly
    (CheckpointMismatch otherwise — a different world NEVER silently
    produces garbage), while observation planes whose compiled shape
    differs are re-initialized from the runtime's template (the
    observability-UPGRADE path: replay a checkpoint captured ring-off
    under a big ring/profiler/latency build; same trajectory, DESIGN
    §21). `reset_planes` names planes to re-initialize even when their
    shapes match (e.g. ("ring",) for a window replay that must start
    from an empty ring)."""
    unknown = set(reset_planes) - set(_CKPT_PLANES)
    if unknown:
        raise ValueError(f"unknown reset_planes {sorted(unknown)} — "
                         f"valid planes: {sorted(_CKPT_PLANES)}")
    if reset_planes and rt is None:
        # fresh plane values come from the runtime's template — without
        # it the reset would be a silent no-op (the clones would carry
        # the parent's ring/counters into the "fresh" window)
        raise ValueError("reset_planes needs rt= (the reset re-"
                         "initializes planes from the runtime template)")
    src = ckpt.state
    if rt is None:
        merged = src
    else:
        if ckpt.signature is not None:
            want = _world_slice(rt.cfg.structural_signature())
            got = _world_slice(ckpt.signature)
            if got != want:
                raise CheckpointMismatch(
                    f"checkpoint world signature {got} != runtime's "
                    f"{want} — different cluster/world shape")
        tpl = rt._template
        plane_of = {f: p for p, fs in _CKPT_PLANES.items() for f in fs}
        fresh = {p: (p in reset_planes
                     or not _tree_spec_equal(
                         {f: getattr(src, f) for f in fs},
                         {f: getattr(tpl, f) for f in fs}))
                 for p, fs in _CKPT_PLANES.items()}
        vals = {}
        for f in type(src).__dataclass_fields__:
            s_v, t_v = getattr(src, f), getattr(tpl, f)
            plane = plane_of.get(f)
            if plane is not None:
                vals[f] = t_v if fresh[plane] else s_v
                continue
            # replay-domain leaf (hash_base included — consumed by
            # ctx.hash_key): must fit the target world exactly
            if not _tree_spec_equal(s_v, t_v):
                raise CheckpointMismatch(
                    f"checkpoint leaf {f!r} does not fit the target "
                    f"runtime (shape/dtype/structure mismatch) — "
                    f"different world or model schema")
            vals[f] = s_v
        merged = type(src)(**vals)
    B = int(batch)
    return jax.tree.map(
        lambda a: jnp.broadcast_to(jnp.asarray(a),
                                   (B,) + jnp.asarray(a).shape), merged)


