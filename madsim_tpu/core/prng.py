"""Counter-based per-trajectory PRNG.

madsim routes every random decision through one seeded SmallRng behind a mutex
(madsim/src/sim/rand.rs:48-96); replay-by-seed works because the draw order is
deterministic under the deterministic scheduler. Here each trajectory carries a
threefry key in its state; every step splits it in a *fixed static order*
(scheduler pick, supervisor draw, handler draws, per-send network draws), so a
seed reproduces an execution bit-exactly — including on a different batch size
or device layout, because trajectories never share randomness.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def seed_key(seed) -> jax.Array:
    """uint32[2] threefry key from an int64-ish seed (vmappable)."""
    seed = jnp.asarray(seed, dtype=jnp.uint32)
    return jax.random.PRNGKey(seed)


def split(key, n: int = 2):
    return jax.random.split(key, n)


def randint(key, lo, hi) -> jax.Array:
    """Uniform int32 in [lo, hi] inclusive. hi >= lo."""
    lo = jnp.asarray(lo, jnp.int32)
    hi = jnp.asarray(hi, jnp.int32)
    return jax.random.randint(key, (), lo, hi + 1, dtype=jnp.int32)


def uniform(key) -> jax.Array:
    return jax.random.uniform(key, (), dtype=jnp.float32)


def bernoulli(key, p) -> jax.Array:
    return jax.random.uniform(key, (), dtype=jnp.float32) < p
