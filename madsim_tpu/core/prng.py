"""Counter-based per-trajectory PRNG.

madsim routes every random decision through one seeded SmallRng behind a mutex
(madsim/src/sim/rand.rs:48-96); replay-by-seed works because the draw order is
deterministic under the deterministic scheduler. Here each trajectory carries a
threefry key in its state; every step splits it in a *fixed static order*
(scheduler pick, supervisor draw, handler draws, per-send network draws), so a
seed reproduces an execution bit-exactly — including on a different batch size
or device layout, because trajectories never share randomness.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def seed_key(seed) -> jax.Array:
    """uint32[2] threefry key from an int64-ish seed (vmappable)."""
    seed = jnp.asarray(seed, dtype=jnp.uint32)
    return jax.random.PRNGKey(seed)


def split(key, n: int = 2):
    return jax.random.split(key, n)


def randint(key, lo, hi) -> jax.Array:
    """Uniform int32 in [lo, hi] inclusive. hi >= lo."""
    lo = jnp.asarray(lo, jnp.int32)
    hi = jnp.asarray(hi, jnp.int32)
    return jax.random.randint(key, (), lo, hi + 1, dtype=jnp.int32)


def uniform(key) -> jax.Array:
    return jax.random.uniform(key, (), dtype=jnp.float32)


def bernoulli(key, p) -> jax.Array:
    return jax.random.uniform(key, (), dtype=jnp.float32) < p


# Domain separator for the per-node HASH-SEED streams (r18): keeps the
# (seed, node)-derived keys out of the trajectory key's split lineage,
# so consuming a hash stream can never alias a scheduler/handler draw.
HASH_STREAM_DOMAIN = 0x48534853  # "HSHS"


def node_hash_key(seed_or_key, node, stream: int = 0) -> jax.Array:
    """Node `node`'s deterministic hash-seed key, derived from
    (seed, node, stream) alone — madsim's collections.rs parity: there
    every HashMap gets its hasher seed from the sim rng so iteration
    order is replay-stable; here a model that needs hash-like tie-break
    randomness (consistent hashing, probe sequences, sampled sets)
    draws it from this stream instead of `ctx.rand_key()`.

    The property that matters: the stream is a pure function of
    (seed, node), NOT of the schedule. A `ctx.rand_key()` draw in
    `init` depends on how many events dispatched before this node's
    boot — a different interleaving reseeds every node's hash state,
    COUPLING nodes through the scheduler. This stream is identical
    across schedules, and node a's stream never moves node b's.

    Accepts the raw int seed or an already-derived uint32[2] key
    (`SimState.hash_base` / `Ctx.hash_key` pass the latter).
    Vmappable; consumes nothing from any other stream.
    """
    key = jnp.asarray(seed_or_key)
    if key.ndim == 0:
        key = seed_key(key)
    k = jax.random.fold_in(key, HASH_STREAM_DOMAIN)
    k = jax.random.fold_in(k, jnp.asarray(node, jnp.uint32))
    return jax.random.fold_in(k, jnp.asarray(stream, jnp.uint32))
