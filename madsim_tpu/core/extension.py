"""Extensions: the pluggable-simulator framework.

madsim lets user crates register custom resource simulators keyed by TypeId
— `trait Simulator { new, create_node, reset_node }` plus
`plugin::simulator::<S>()` (sim/plugin.rs:13-40, registered via
Runtime::add_simulator, runtime/mod.rs:66-76). The tensor-world analog: an
Extension contributes
  * its own per-trajectory state subtree (a named column group in SimState),
  * handlers for custom supervisor opcodes (op >= OP_USER, schedulable from
    a Scenario like any built-in fault op), and
  * an optional per-event hook observing every dispatched event
(all traced into the same jitted step, so extensions run at engine speed
and vectorize over the seed batch like everything else).

See tests/test_extension.py for a power-budget simulator example.
"""

from __future__ import annotations

from typing import Any

import jax

# user opcode space: built-ins stay below, extensions at or above
OP_USER = 100


class Extension:
    """Subclass and register via Runtime(extensions=[...])."""

    #: unique key — the TypeId analog; also the SimState.ext dict key
    name: str = "extension"

    def state(self, cfg) -> Any:
        """Default per-trajectory state subtree (pytree of jnp arrays)."""
        return {}

    def on_op(self, cfg, sub, op, target, src, payload, key):
        """Handle a custom supervisor op (fires for ANY op >= OP_USER;
        check `op` against your opcodes with masked updates). Returns the
        updated subtree. `target`/`src`/`payload` come from the scenario
        row; `key` is a per-event PRNG key."""
        return sub

    def on_event(self, cfg, sub, state, record) -> Any:
        """Observe every dispatched event (record: now/kind/node/src/tag/
        payload/fired) — the create_node/reset_node-style bookkeeping hook.
        Returns the updated subtree. Masked no-op when record['fired'] is
        False."""
        return sub

    def reset_node(self, cfg, sub, node, when):
        """A node was killed or (re)booted (Simulator::reset_node analog,
        plugin.rs:24). Returns the updated subtree."""
        return sub


def build_ext_state(cfg, extensions) -> dict:
    names = [e.name for e in extensions]
    assert len(set(names)) == len(names), f"duplicate extension names {names}"
    return {e.name: jax.tree.map(lambda a: a, e.state(cfg))
            for e in extensions}
