"""Core constants, event kinds, supervisor opcodes, and static simulation config.

TPU-native rethink of madsim's world: instead of an async executor with a
random-pop ready queue (reference: madsim/src/sim/task.rs:88-143) plus a
binary-heap timer wheel (madsim/src/sim/time/mod.rs:41-56), the whole
simulation is ONE fixed-shape event table. Every future occurrence — a message
delivery (madsim/src/sim/net/mod.rs:301-306 schedules messages as timers), a
protocol timer, a supervisor fault-injection op — is a row in the timer table.
The step function pops the earliest eligible row (random tie-break, mirroring
the seeded random ready-queue pop of madsim/src/sim/utils/mpsc.rs:75-85) and
dispatches it. All shapes are static so the step jit-compiles and vmaps over a
[seed_batch] leading axis.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any

import numpy as np

# ---------------------------------------------------------------------------
# Time. Virtual time is int32 *ticks*; 1 tick == 1 microsecond. This bounds a
# trajectory at ~35 simulated minutes (2**31 us), far beyond any chaos test in
# the reference suite (which run simulated seconds). An overflow sets an oops
# bit instead of wrapping.
# ---------------------------------------------------------------------------
TICKS_PER_MS = 1_000
TICKS_PER_SEC = 1_000_000
T_INF = np.int32(2**31 - 1)

# ---------------------------------------------------------------------------
# Event kinds (t_kind column of the event table).
# ---------------------------------------------------------------------------
EV_FREE = 0    # empty slot
EV_MSG = 1     # message delivery (madsim: net/mod.rs:301-306 timer-scheduled)
EV_TIMER = 2   # protocol timer (madsim: time/sleep.rs)
EV_SUPER = 3   # supervisor op (madsim: Handle::kill/... runtime/mod.rs:214-245)

# ---------------------------------------------------------------------------
# Supervisor opcodes (t_tag column when t_kind == EV_SUPER).
# Mirrors the fault-injection surface of madsim::runtime::Handle
# (runtime/mod.rs:200-256) and NetSim (net/mod.rs:98-157).
# ---------------------------------------------------------------------------
OP_INIT = 1          # run program.init on node (node boot; NodeBuilder::init)
OP_KILL = 2          # Handle::kill — drop tasks, reset sim node state
OP_RESTART = 3       # Handle::restart — kill + re-run init closure
OP_PAUSE = 4         # Handle::pause
OP_RESUME = 5        # Handle::resume
OP_CLOG_NODE = 6     # NetSim::clog_node (disconnect)
OP_UNCLOG_NODE = 7   # NetSim::unclog_node (connect)
OP_CLOG_LINK = 8     # NetSim::clog_link (disconnect2); args (src=t_src, dst=t_node)
OP_UNCLOG_LINK = 9   # NetSim::unclog_link (connect2)
OP_SET_LOSS = 10     # update packet_loss_rate; payload[0] = rate * 1e6
OP_HALT = 11         # end of simulation (time limit)
OP_SET_LATENCY = 12  # payload[0]=lo ticks, payload[1]=hi ticks
OP_HEAL = 13         # clear the whole clog matrix + clogged nodes
OP_PARTITION = 14    # payload[0] = bitmask of group A; cuts A <-> not-A both
                     # ways (single-row analog of N^2 disconnect2 calls)
# --- gray-failure ops (r17) ------------------------------------------------
OP_PARTITION_ONEWAY = 15  # ASYMMETRIC cut (madsim disconnect2 parity):
                          # payload packs group A (31 nodes/word, the
                          # OP_PARTITION packing); t_src is the direction
                          # flag — 0 cuts A -> not-A (A's sends vanish,
                          # A still hears), 1 cuts not-A -> A. Directional
                          # entries are OR'd INTO the clog_link matrix
                          # (cuts compose); OP_HEAL clears them all.
OP_SET_SKEW = 16     # per-node clock skew: payload[LAST] = signed RATE in
                     # 1/1024ths (clipped to ±SKEW_CAP): node's local clock
                     # runs at (1 + skew/1024)x — observed `now` drifts and
                     # its timer delays stretch/shrink inversely. Target may
                     # be NODE_RANDOM with a pool in the LEADING payload
                     # words (value and pool coexist; see _apply_super).
OP_SET_DISK = 17     # per-node disk fault: payload[LAST] = disk latency in
                     # ticks (every emission of the node leaves that much
                     # later — the fsync-stall "limping node" model),
                     # payload[LAST-1] = torn-write flag (nonzero: a KILL of
                     # this node flushes a random PREFIX of each file's
                     # unsynced tail to disk — a partially-written final
                     # record instead of clean old-or-new; fs-layer models
                     # only). Same pool/value packing as OP_SET_SKEW.
# --- connection-fault ops (r19) ---------------------------------------------
OP_RESET_PEER = 18   # tear down ALL conn/stream fabric touching the target
                     # node, on BOTH sides (madsim NetSim::reset_node parity,
                     # sim/net/tcp/stream.rs:185-192: live TCP connections
                     # die; a kill alone deliberately leaves the survivor's
                     # half-open state): every cn_state entry touching the
                     # node drops to CLOSED, every stream ring/counter
                     # touching it is wiped, and both sides' incarnation
                     # epochs bump — so in-flight segments and RSTs from the
                     # torn incarnation are rejected by the successor
                     # connection (DESIGN §20). Inert for state schemas
                     # without the conn/stream leaf quartets (like torn
                     # mode for non-fs models). Target may be NODE_RANDOM
                     # with a pool, like every node-lifecycle op.
OP_SET_DUP = 19      # per-node duplicate-delivery rate: payload[LAST] =
                     # rate * 1e6 (the OP_SET_LOSS encoding). A dispatched
                     # MESSAGE at the node is re-armed for one more
                     # delivery with that probability instead of being
                     # freed — the retransmit-storm / datagram-duplication
                     # regime Go-Back-N's exactly-once claim must survive.
                     # Duplicates can duplicate again (geometric storm,
                     # bounded by the rate cap). Same pool/value packing
                     # as OP_SET_SKEW.

# bounds enforced wherever the values enter state (supervisor op apply,
# KnobPlan.apply): skew is a rate in 1/1024ths (±512 = ±50% clock rate),
# disk latency is capped at 10 simulated seconds, duplicate delivery at
# 0.9 (like the loss-mutation cap: past that lanes mostly stall)
SKEW_CAP = 512
DISK_LAT_CAP = 10_000_000
DUP_RATE_CAP = 900_000

# Node argument sentinel: draw a random target at fire time (fuzzing aid).
# KILL/PAUSE/CLOG pick a random *alive* node; RESTART picks a random *dead* one.
NODE_RANDOM = -1

# ---------------------------------------------------------------------------
# Crash codes (state.crash_code). User codes must be > 0.
# ---------------------------------------------------------------------------
CRASH_NONE = 0
CRASH_DEADLOCK = -1        # no eligible event and no HALT reached
                           # (madsim panics "the task will block forever",
                           #  task.rs:110-124)
CRASH_TIME_LIMIT = -2      # virtual-time limit exceeded (set_time_limit)
CRASH_INVARIANT = -3       # global invariant check failed (generic)
CRASH_SLO = -4             # tail-latency SLO invariant failed
                           # (harness.slo_invariant over the latency plane)
CRASH_RECOVERY = -5        # recovery invariant failed: per-window p99/queue
                           # never returned under threshold within the
                           # allowed windows after the last fault window
                           # (harness.recovery_invariant over the windowed
                           # telemetry plane, DESIGN §22)

# Oops bits (state.oops) — resource-exhaustion flags instead of UB. The
# reference grows Vecs unboundedly; static shapes require capacities.
OOPS_EVENT_OVERFLOW = 1    # event table full; an emission was dropped
OOPS_TIME_OVERFLOW = 2     # virtual clock would exceed int32 ticks

# ---------------------------------------------------------------------------
# Windowed-telemetry fault-marker bits (SimState.sr_fault, DESIGN §22): each
# virtual-time window records WHICH fault classes landed in it, so the
# recovery oracle (harness.recovery_invariant) and the sim-time renderers
# (obs/series.py) can name the last disturbed window without replaying.
# KILL counts only when it actually reset a node (the _apply_super
# reset mask — a NODE_RANDOM kill with no eligible target marks nothing);
# the matrix/knob ops mark when the scheduled op dispatched.
# ---------------------------------------------------------------------------
SRF_KILL = 1          # effective OP_KILL / the kill half of OP_RESTART
SRF_BOOT = 2          # effective OP_INIT / OP_RESTART boot
SRF_PARTITION = 4     # OP_CLOG_NODE/CLOG_LINK/PARTITION/PARTITION_ONEWAY
SRF_HEAL = 8          # OP_HEAL / OP_UNCLOG_NODE / OP_UNCLOG_LINK
SRF_NET = 16          # OP_SET_LOSS / OP_SET_LATENCY
SRF_GRAY = 32         # OP_SET_SKEW / OP_SET_DISK (r17 gray-failure knobs)
SRF_CONN = 64         # OP_RESET_PEER / OP_SET_DUP (r19 connection faults)
# the DISRUPTIVE subset: what the recovery oracle counts as "a fault
# happened here" (boot/heal are recovery actions, not disturbances)
SRF_DISRUPT = SRF_KILL | SRF_PARTITION | SRF_NET | SRF_GRAY | SRF_CONN


@dataclasses.dataclass(frozen=True)
class NetConfig:
    """Network fault model — madsim sim::net::config::Config
    (network.rs:49-69): packet loss rate + latency range.

    Latencies are ticks (us). Reference default: 1-10 ms latency, 0 loss.
    """

    packet_loss_rate: float = 0.0
    send_latency_min: int = 1 * TICKS_PER_MS
    send_latency_max: int = 10 * TICKS_PER_MS
    # per-op micro-jitter: 0..op_jitter_max ticks (INCLUSIVE) added to every
    # send's latency draw AND every timer's deadline. Inspired by — but
    # deliberately wider than — the reference's rand_delay
    # (net/mod.rs:151-156), which draws gen_range(0..5) (EXCLUSIVE, 0-4 us)
    # and wraps network ops only; jittering timer deadlines too widens
    # explored interleavings beyond what the reference perturbs.
    # STATIC gate, dynamic bound: 0 (default)
    # compiles the fold out entirely (zero extra draws on the emission
    # phase); > 0 compiles it in, and the bound then lives in
    # SimState.jitter where set-ops/overrides can tune it without
    # recompile. Enabled/disabled builds are distinct replay domains
    # (the config hash covers this field).
    op_jitter_max: int = 0

    def __post_init__(self):
        assert 0.0 <= self.packet_loss_rate <= 1.0, \
            f"packet_loss_rate {self.packet_loss_rate} not in [0, 1]"
        assert 0 <= self.send_latency_min <= self.send_latency_max, \
            (f"inverted latency range {self.send_latency_min}.."
             f"{self.send_latency_max}")
        assert self.op_jitter_max >= 0

    @staticmethod
    def from_toml(text: str) -> "NetConfig":
        """Parse the reference's TOML config shape (config.rs:35-66):

            [net]
            packet_loss_rate = 0.1
            send_latency = "1ms..10ms"   # or send_latency_min/max in ticks
        """
        data = _toml_loads(text).get("net", {})
        kw = {}
        if "packet_loss_rate" in data:
            kw["packet_loss_rate"] = float(data["packet_loss_rate"])
        if "send_latency" in data:  # "Xms..Yms" range string
            lo, hi = str(data["send_latency"]).split("..")
            kw["send_latency_min"] = _parse_dur(lo)
            kw["send_latency_max"] = _parse_dur(hi)
        if "send_latency_min" in data:
            kw["send_latency_min"] = int(data["send_latency_min"])
        if "send_latency_max" in data:
            kw["send_latency_max"] = int(data["send_latency_max"])
        if "op_jitter_max" in data:  # ticks or a "5us"-style duration
            kw["op_jitter_max"] = _parse_dur(str(data["op_jitter_max"]))
        return NetConfig(**kw)


def _toml_loads(text: str) -> dict:
    """stdlib tomllib when available (3.11+); otherwise a fallback parser
    for the flat `[section]` / `key = value` subset the config shape
    actually uses (this image ships 3.10 and no tomli — the container's
    packages are fixed, so the knob must not require one)."""
    try:
        import tomllib
        return tomllib.loads(text)
    except ImportError:
        pass
    out: dict = {}
    section = out
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        header = line.split("#", 1)[0].strip()   # header may carry a comment
        if header.startswith("[") and header.endswith("]"):
            section = out.setdefault(header[1:-1].strip(), {})
            continue
        key, _, val = line.partition("=")
        val = val.strip()
        if not _:
            raise ValueError(f"unparseable config line: {raw!r}")
        try:
            if val[:1] in ('"', "'"):           # quoted string (anything
                q = val[0]                       # past the close quote —
                val = val[1:val.index(q, 1)]     # e.g. a comment — ignored)
            else:
                val = val.split("#", 1)[0].strip()  # bare value, no comment
                if val in ("true", "false"):
                    val = val == "true"
                else:
                    try:
                        val = int(val)
                    except ValueError:
                        val = float(val)
        except ValueError as e:
            raise ValueError(f"unparseable config line: {raw!r} ({e})") \
                from None
        section[key.strip()] = val
    return out


def _parse_dur(s: str) -> int:
    """'5ms' / '10us' / '1s' -> ticks."""
    s = s.strip()
    for suffix, mul in (("us", 1), ("ms", TICKS_PER_MS), ("s", TICKS_PER_SEC)):
        if s.endswith(suffix):
            return int(float(s[:-len(suffix)]) * mul)
    return int(s)


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Simulation configuration — split into a STRUCTURAL signature and
    DYNAMIC knobs (DESIGN §10 has the full field table).

    Structural fields shape/lower the XLA program: `n_nodes`,
    `event_capacity`, `payload_words`, `table_dtype`, `emission_write`,
    `collect_stats`, `trace_cap`'s power-of-two BUCKET, and the static
    jitter GATE (`net.op_jitter_max > 0`). Only these key a compile
    (`structural_signature()` — the `compile.PROGRAM_CACHE` key), so
    Runtimes differing in anything else share executables.

    Dynamic knobs become traced operands carried in SimState: `time_limit`
    (SimState.tlimit; `set_time_limit` / MADSIM_TEST_TIME_LIMIT), the
    NetConfig scalars (loss/lat_lo/lat_hi/jitter; supervisor ops and
    `apply_net_override` retune them), and `trace_cap`'s exact value
    within its bucket (SimState.trace_cap masks the ring down). They
    still change TRAJECTORIES — `hash()` covers every field, because the
    repro contract needs the config that actually ran — they just no
    longer cost a recompile.
    """

    n_nodes: int
    event_capacity: int = 128      # rows in the event table, per trajectory
    payload_words: int = 8         # int32 words per message/timer payload
    time_limit: int = 10 * TICKS_PER_SEC
    net: NetConfig = dataclasses.field(default_factory=NetConfig)
    collect_stats: bool = True
    # (the r3 opt-in "fused" Pallas scheduler was CUT in r5: three rounds
    # without on-hardware justification, a separate replay domain to
    # maintain, and the roofline (DESIGN §5) shows the select phase is
    # too small a slice of per-step bytes for a select-only kernel to
    # pay — the whole-step VMEM-resident kernel is the real Pallas play)
    # narrow event-table columns: "int16" stores t_kind/t_node/t_src in
    # half the bytes (the [batch, C] table dominates step cost — DESIGN
    # §5b; t_tag stays int32: service tags are 29-bit hashes, t_deadline
    # is virtual time). Values are identical either way, so trajectories
    # and fingerprints are BIT-IDENTICAL across this knob — a pure
    # bandwidth lever, not a replay domain.
    table_dtype: str = "int32"
    # flight-recorder ring (obs/): rows per lane in the on-device trace
    # ring. 0 (default) compiles the recorder out entirely — zero-size
    # ring leaves, no write code in the step. > 0 keeps the last
    # trace_cap dispatched events per SAMPLED lane (see
    # Runtime.init_batch(trace_lanes=...)) resident in SimState, so the
    # ring survives `lax.while_loop` and `run_fused` sweeps stop being
    # blind. The write consumes no randomness and touches no other
    # state, so all non-trace state is BIT-IDENTICAL across trace_cap
    # settings — an observation lever like table_dtype, not a replay
    # domain (the config hash does cover it, since the compiled program
    # differs).
    trace_cap: int = 0
    # prefix-coverage sketch (obs/causal.py, parallel/stats.py): number
    # of on-device checkpoint slots per lane. 0 (default) compiles the
    # sketch out (zero-size column, no fold code in the step). > 0 folds
    # the running `sched_hash` into slot j after the lane's
    # (j+1)*sketch_every-th dispatch, so two lanes' sketches first
    # differ at the slot whose prefix first diverged — a per-lane
    # divergence DEPTH (not just a terminal distinct/same bit) that
    # never leaves the device mid-run. Like trace_cap, an observation
    # lever: the fold consumes no randomness and touches no non-sketch
    # state, so trajectories are BIT-IDENTICAL across settings.
    # sketch_slots is STRUCTURAL (it shapes the column); sketch_every is
    # DYNAMIC (SimState.sketch_every — retune without recompile).
    sketch_slots: int = 0
    sketch_every: int = 64
    # sim-profiler counter plane (obs/profiler.py, DESIGN §16): False
    # (default) compiles the counters out entirely — zero-size columns,
    # no counter code in the step. True adds per-lane, on-device
    # counters written through the step's existing one-hot dispatch
    # machinery: per-node dispatch counts by event kind, per-node busy
    # virtual time, event-table occupancy high-water mark, message
    # drop/delay totals, per-node kill/restart counts. Counters SATURATE
    # at int32 max instead of wrapping. Like trace_cap, an observation
    # lever, not a replay domain: the writes consume no randomness and
    # touch no non-counter state, so trajectories are BIT-IDENTICAL
    # across settings and the pf_* columns are excluded from
    # fingerprints (TRACE_FIELDS). Per-lane masking rides
    # `init_batch(profile_lanes=...)` — a build can ship with
    # profile=True and flip lanes on per sweep (the masked-off overhead
    # bar is ≤3% on the tiny-step worst case, bench.py --mode prof_ab).
    profile: bool = False
    # SLO latency plane (obs/profiler.py, DESIGN §17): number of log2
    # buckets in the on-device request-latency histograms. 0 (default)
    # compiles the plane out entirely — zero-size columns, no latency
    # code in the step. > 0 adds, per lane:
    #   lh_sojourn [N, B]  queue-wait per dispatch (now − the dispatched
    #                      row's deadline), bucketed by floor-log2 ticks
    #                      at the acting node;
    #   lh_e2e     [N, B]  END-TO-END request latency: every pending row
    #                      carries the birth time of its causal ROOT
    #                      (ev_root_t — external/scenario rows mint
    #                      root = dispatch `now`, emissions inherit the
    #                      dispatching event's root through the same
    #                      broadcast-select as the r10 provenance pair),
    #                      and a dispatch of a model-declared COMPLETION
    #                      kind (complete_kinds below) folds now − root
    #                      into the completion node's histogram;
    #   lh_slo_miss [N]    completions whose e2e latency exceeded the
    #                      DYNAMIC per-lane SimState.slo_target knob
    #                      (slo_target below; 0 disables — retune or
    #                      fuzz the target without recompile).
    # Bucket j holds latencies in [2^(j-1), 2^j) ticks (bucket 0 = zero
    # ticks); 32 buckets cover the whole int32 tick range. Counts
    # SATURATE at int32 max (the §16 discipline). Like trace_cap, an
    # observation lever, not a replay domain: the writes consume no
    # randomness and touch no non-latency state, trajectories are
    # BIT-IDENTICAL across settings, and the lh_*/ev_root_t columns
    # ride TRACE_FIELDS out of fingerprints. Per-lane masking rides
    # `init_batch(latency_lanes=...)`. (Installing harness.slo_invariant
    # deliberately pierces this: an SLO miss becomes a crash code —
    # that runtime's replay domain includes the plane, see DESIGN §17.)
    latency_hist: int = 0
    # which dispatches COMPLETE a request, as ((event_kind, tag), ...)
    # pairs — e.g. ((EV_MSG, CRSP),) for "client saw its reply".
    # STRUCTURAL: the completion mask compiles into the step. Empty
    # (default) = no end-to-end tracking; the sojourn histogram still
    # fills (it needs no request notion).
    complete_kinds: tuple = ()
    # which dispatches START a request: ((event_kind, tag), ...) pairs
    # that MINT a fresh root (root = dispatch now) instead of
    # inheriting the chain's. External dispatches (scenario rows, node
    # boots, host injections) always mint — an OPEN-loop client whose
    # arrivals are scenario rows needs no root_kinds at all. Declare a
    # CLOSED-loop client's new-request timer here (e.g.
    # ((EV_TIMER, T_NEW),)), or its e2e would measure time since the
    # chain's external root (the node's boot), not per-request latency.
    # A pair may appear in BOTH complete_kinds and root_kinds (a reply
    # delivery that starts the next sequential call): the completion
    # measures against the INHERITED root, then the mint restarts the
    # chain. CAVEAT (DESIGN §17): roots ride the single-parent causal
    # chain, so pick completion events whose chain actually descends
    # from the request — a reply emitted while applying a REPLICATION
    # ack (raft-backed servers) descends from the ack chain, not the
    # request; measure such systems at a chain-correct point (e.g. the
    # request's arrival at the group) or use a direct-reply server.
    root_kinds: tuple = ()
    # initial SimState.slo_target in ticks (DYNAMIC knob — the per-lane
    # state field is what the miss counter compares against; 0 disables)
    slo_target: int = 0
    # windowed telemetry plane (obs/series.py, DESIGN §22): number of
    # sim-time WINDOWS in the on-device metric series. 0 (default)
    # compiles the plane out entirely — zero-size columns, no series
    # code in the step. > 0 adds, per lane, saturating per-window
    # series written through the step's one-hot dispatch machinery:
    #   sr_dispatch [W, N]  dispatches by (window, acting node);
    #   sr_busy     [W, N]  busy virtual ticks by (window, acting node);
    #   sr_qhw      [W]     event-table occupancy high-water inside the
    #                       window (dispatch + emission time, the
    #                       pf_qmax rule per window);
    #   sr_drop     [W]     messages lost in the window;
    #   sr_dup      [W]     duplicate re-arms fired in the window;
    #   sr_complete [W]     request completions (needs latency_hist +
    #                       complete_kinds — zero otherwise);
    #   sr_slo_miss [W]     completions over slo_target in the window;
    #   sr_lat      [W, B]  per-window e2e log2 histograms (compiled in
    #                       only when BOTH this plane and latency_hist
    #                       are — the per-window p99 source);
    #   sr_fault    [W]     SRF_* bitmask of fault classes that landed
    #                       in the window (the recovery oracle's axis).
    # A dispatch at virtual time `now` lands in window
    # min(now // window_len, W - 1): a dispatch exactly ON a window_len
    # boundary opens the NEXT window, and events past W*window_len
    # CLAMP into the last window (size W*window_len >= time_limit for
    # clean tails). Like trace_cap, an observation lever, not a replay
    # domain: the writes consume no randomness and touch no non-series
    # state, trajectories are BIT-IDENTICAL across settings, and the
    # sr_* columns ride TRACE_FIELDS out of fingerprints. Per-lane
    # masking rides `init_batch(series_lanes=...)`; the window COUNT is
    # STRUCTURAL (it shapes the columns), the window LENGTH is the
    # DYNAMIC SimState.window_len operand — retune without recompile
    # (Runtime.set_window_len). Installing harness.recovery_invariant
    # deliberately pierces the transparency contract exactly like
    # slo_invariant does for the latency plane (DESIGN §22).
    series_windows: int = 0
    # initial SimState.window_len in ticks per window (DYNAMIC knob,
    # like slo_target/sketch_every; default 1 simulated second)
    window_len: int = TICKS_PER_SEC
    # critical-path attribution plane (obs/spans.py, DESIGN §24): False
    # (default) compiles the plane out entirely — zero-size columns, no
    # span code in the step. True adds, per lane, carried span columns
    # riding the r10/r16 provenance broadcast-select (every pending row
    # carries its chain's accumulated queue-wait ticks, accumulated
    # network/disk-delay ticks, hop count, the dominant segment's
    # (node, magnitude), and the emitting dispatch's virtual time), and
    # at complete_kinds dispatches folds them through the one-hot
    # machinery into saturating tail-attribution counters:
    #   sa_tail       [N, 4]  per completion node: tail-request count,
    #                         queue-wait ticks, network/disk ticks, hops
    #                         — accumulated ONLY for completions over the
    #                         dynamic SimState.slo_target (tail requests
    #                         attribute; the healthy majority stays out);
    #   sa_bottleneck [N]     how often node n owned a tail request's
    #                         DOMINANT segment (largest queue+transit
    #                         hop) — the bottleneck-node histogram.
    # With trace_cap > 0 the ring also grows a `tr_qw` column (the
    # dispatch's own queue-wait), so a host parent-walk can split every
    # hop into wait vs transit (obs/spans.py `explain_latency`). Like
    # trace_cap, an observation lever, not a replay domain: the writes
    # consume no randomness and touch no non-span state, trajectories
    # are BIT-IDENTICAL across settings, and the ev_span/sa_* columns
    # ride TRACE_FIELDS out of fingerprints. Per-lane masking rides
    # `init_batch(span_lanes=...)`. Requires the latency plane
    # (latency_hist > 0) and complete_kinds — attribution is a property
    # of measured completions.
    span_attr: bool = False
    # emission-write lowering: how staged emissions land in the event
    # table. "onehot" = [E, C] one-hot masked-sum (VPU-friendly — the TPU
    # default); "scatter" = one XLA scatter per column at distinct slot
    # rows (O(E) work — the CPU default: the [E, C] product is the
    # dominant term of the measured n^1.8 cluster-width tax, DESIGN §5).
    # "auto" resolves by backend at trace time. Written VALUES are
    # identical across all three, so trajectories and fingerprints are
    # BIT-IDENTICAL — a lowering lever like table_dtype, not a replay
    # domain.
    emission_write: str = "auto"

    def __post_init__(self):
        assert self.n_nodes >= 1
        assert self.event_capacity >= 4
        assert self.payload_words >= 1
        assert self.trace_cap >= 0
        assert self.sketch_slots >= 0
        assert isinstance(self.profile, bool)
        assert 0 <= self.latency_hist <= 32, \
            "latency_hist is a log2 BUCKET COUNT; 32 covers int32 ticks"
        assert self.slo_target >= 0
        assert self.series_windows >= 0
        assert self.window_len >= 1, \
            "window_len is ticks per series window; must be >= 1"
        # normalize to a tuple of (kind, tag) int pairs (frozen dataclass:
        # go through object.__setattr__) so the signature/hash are stable
        # across list-vs-tuple spellings
        for field in ("complete_kinds", "root_kinds"):
            object.__setattr__(
                self, field,
                tuple((int(p[0]), int(p[1])) for p in getattr(self, field)))
            for pair in getattr(self, field):
                # messages/timers only: a supervisor op is an external
                # CAUSE (it mints a root by being external), never a
                # request boundary — and its scheduled row may carry a
                # NODE_RANDOM placeholder that would misattribute the
                # completion's node
                assert pair[0] in (EV_MSG, EV_TIMER), \
                    f"{field} entries are (EV_MSG|EV_TIMER, tag) " \
                    f"pairs: {pair}"
        if self.complete_kinds or self.root_kinds or self.slo_target:
            assert self.latency_hist > 0, \
                "complete_kinds/root_kinds/slo_target need the latency " \
                "plane compiled in (latency_hist > 0)"
        assert isinstance(self.span_attr, bool)
        if self.span_attr:
            assert self.latency_hist > 0 and self.complete_kinds, \
                "span_attr attributes measured completions: it needs " \
                "the latency plane (latency_hist > 0) AND complete_kinds"
        assert self.sketch_every >= 1
        assert self.table_dtype in ("int32", "int16")
        assert self.emission_write in ("auto", "onehot", "scatter")
        if self.table_dtype == "int16":
            assert self.n_nodes < 2**15, "int16 t_node caps nodes at 32767"

    @property
    def trace_cap_bucket(self) -> int:
        """Ring capacity as COMPILED: trace_cap rounded up to the next
        power of two (0 stays 0 — recorder compiled out). The exact
        trace_cap value rides dynamically in SimState and masks the ring
        down, so sweeping trace_cap within one bucket shares one
        executable; rows past trace_cap are never written."""
        from ..compile.signature import next_pow2
        return next_pow2(self.trace_cap)

    def structural_signature(self) -> tuple:
        """The shape/lowering-affecting slice of this config — what keys
        a step-program compile (`compile.PROGRAM_CACHE`). Two configs
        with equal signatures trace to the same program; their dynamic
        knobs (time_limit, NetConfig scalar values, exact trace_cap)
        ride as operands. `emission_write` stays raw here — 'auto'
        resolves per backend at trace time, and the cache keys the
        backend separately."""
        return ("simconfig-v8", self.n_nodes, self.event_capacity,
                self.payload_words, self.table_dtype, self.emission_write,
                bool(self.collect_stats), self.trace_cap_bucket,
                self.sketch_slots, self.net.op_jitter_max > 0,
                bool(self.profile),
                self.latency_hist, self.complete_kinds, self.root_kinds,
                # v7 (r21): the windowed-telemetry plane's window COUNT —
                # appended at the END so the _SIG_WORLD_IDX world-slice
                # indices (core/state.py) keep naming the same fields
                self.series_windows,
                # v8 (r23): the critical-path attribution plane's gate —
                # appended at the END, same rationale
                bool(self.span_attr))

    def hash(self) -> str:
        """Stable 8-hex-digit config hash, printed on test failure so a repro
        requires the same config — madsim sim::config::Config::hash
        (config.rs:27-31) and the MADSIM_CONFIG_HASH echo (macros lib.rs:189).
        Covers EVERY field (dynamic knobs change trajectories even though
        they no longer key compiles — replay domain != compile domain).
        """
        blob = json.dumps(dataclasses.asdict(self), sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:8]


def ms(x: float) -> int:
    """Milliseconds -> ticks."""
    return int(x * TICKS_PER_MS)


def sec(x: float) -> int:
    """Seconds -> ticks."""
    return int(x * TICKS_PER_SEC)


PyTree = Any
