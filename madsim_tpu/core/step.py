"""The event engine: one jitted, vmappable `step(state) -> (state, record)`.

This is the TPU-native replacement for madsim's hot loop
(Executor::block_on, task.rs:110-124):

    reference (one seed, one thread)          this engine (B seeds, lockstep)
    ---------------------------------         --------------------------------
    pop random ready task (mpsc.rs:75)        masked categorical over earliest-
                                              deadline ties (ops/select.py)
    poll future, may send/sleep               dispatch handler; effects are
                                              fixed-shape emission records
    TimeRuntime::advance (time/mod.rs:41)     now = max(now, earliest deadline)
    message = timer cb (net/mod.rs:301)       message = event-table row
    Handle::kill/clog (runtime/mod.rs:214)    supervisor op = event-table row

Every branch executes for every trajectory each step (vmap turns `cond` into
`select`); masks decide what commits. That is the SIMD price of advancing
thousands of seeds in lockstep, and it is why handlers must be small tensor
programs.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import select as sel
from . import prng
from . import types as T
from .api import Ctx, Program
from . import state as ST
from .state import N_EV_KINDS, SimState


def _where_tree(mask, new, old):
    return jax.tree.map(lambda a, b: jnp.where(mask, a, b), new, old)


_I32_MAX = np.int32(2**31 - 1)


def _sat_add(a, d):
    """a + d for nonnegative int32 `d`, SATURATING at int32 max instead
    of wrapping — the profiler counter discipline (DESIGN §16): a pegged
    counter reads as pegged, never as a wrapped negative. The wrapped
    sum on the saturating branch is computed but never selected."""
    return jnp.where(a > _I32_MAX - d, _I32_MAX, a + d)


def _drift(t, sk):
    """(t * sk) >> 10 in exact int32-safe pieces — the clock-skew fold
    (DESIGN §18). `t` is a nonnegative tick count (now, or a timer
    delay), `sk` a per-1024 rate deviation bounded by ±SKEW_CAP (512),
    so (t>>10) ≤ 2^21 times 512 and (t&1023)*512 both stay far inside
    int32. Exact integer arithmetic — no float rounding to leak
    nondeterminism across backends — and identically 0 at sk == 0 (the
    bit-identical-when-disabled contract)."""
    return (t >> 10) * sk + (((t & 1023) * sk) >> 10)


# node-state slice/scatter via one-hot over the [N] axis: a traced node
# index would lower to a per-lane gather/scatter under vmap, which TPU
# executes at ~10ns per element (DESIGN.md §5) — for the log-shaped leaves
# that alone was several ms/step
def _slice_node(tree, node):
    return jax.tree.map(lambda a: sel.take_row(a, node), tree)


def _scatter_node(tree, node, new, mask):
    return jax.tree.map(
        lambda full, val: sel.put_row(full, node, val, mask), tree, new)


EMPTY_SEND = lambda P: dict(
    m=jnp.asarray(False), dst=jnp.asarray(0, jnp.int32),
    tag=jnp.asarray(0, jnp.int32), payload=jnp.zeros((P,), jnp.int32))
EMPTY_TIMER = lambda P: dict(
    m=jnp.asarray(False), delay=jnp.asarray(0, jnp.int32),
    tag=jnp.asarray(0, jnp.int32), payload=jnp.zeros((P,), jnp.int32))
EMPTY_CANCEL = lambda: dict(
    m=jnp.asarray(False), tag=jnp.asarray(0, jnp.int32))


def make_step(
    cfg: T.SimConfig,
    programs: Sequence[Program],
    node_prog: np.ndarray,
    state_spec: Any,
    invariant: Callable[[SimState], tuple[jax.Array, jax.Array]] | None = None,
    persist: Any = None,
    halt_when: Callable[[SimState], jax.Array] | None = None,
    extensions: Sequence = (),
) -> Callable[[SimState], tuple[SimState, dict[str, jax.Array]]]:
    """Build the per-trajectory step function.

    Args:
      cfg: static SimConfig.
      programs: node programs; node i runs programs[node_prog[i]].
      node_prog: int array [N] mapping node -> program index (static).
      state_spec: one node's default user-state pytree (no N axis).
      invariant: optional global safety check `f(state) -> (bad, code)`
        evaluated after every dispatch (e.g. Raft election safety). This is
        strictly stronger than the reference, where the supervisor can only
        observe at its own wakeups.
      persist: optional pytree of bools matching state_spec: True leaves are
        STABLE STORAGE — they survive kill/restart (the FsSim analog,
        fs.rs:66-122: files outlive the process; everything else is process
        memory and resets on boot). None = all volatile.
      halt_when: optional global success condition `f(state) -> bool`; when
        True the trajectory halts cleanly (the "supervisor future returned"
        analog of Runtime::block_on resolving).
    """
    node_prog = np.asarray(node_prog, np.int32)
    assert node_prog.shape == (cfg.n_nodes,)
    assert node_prog.min() >= 0 and node_prog.max() < len(programs)
    node_prog_j = jnp.asarray(node_prog)
    P = cfg.payload_words
    # emission-write lowering (types.py): values identical either way;
    # resolved once at trace time so the whole step compiles one form
    em_scatter = cfg.emission_write == "scatter" or (
        cfg.emission_write == "auto" and jax.default_backend() == "cpu")
    spec_default = jax.tree.map(lambda a: jnp.asarray(a), state_spec)
    if persist is None:
        persist_mask = jax.tree.map(lambda a: False, spec_default)
    else:
        persist_mask = persist
        assert (jax.tree.structure(persist_mask)
                == jax.tree.structure(spec_default)), \
            "persist mask must match state_spec structure"

    def live_step(s: SimState):
        live = ~s.halted  # frozen trajectories no-op via mask gating (the
        # vmap-friendly alternative to freezing with a whole-tree select)
        key, k_sched, k_super, k_handler, k_net = prng.split(s.key, 5)
        key = jnp.where(live, key, s.key)

        # ---- 1. pick next event: earliest eligible deadline, random tie-break
        occupied = s.t_kind != T.EV_FREE
        tnode = jnp.clip(s.t_node, 0, cfg.n_nodes - 1)
        # one-hot instead of alive[tnode]/paused[tnode]: a [C]-index gather
        # costs ~10ns/element on TPU (it was the 2nd-hottest op in the
        # profiled Raft step); the [C, N] compare+reduce is ~free
        parked_nodes = s.alive & s.paused
        parked = (sel.take1(parked_nodes, tnode)
                  & (s.t_kind != T.EV_SUPER))  # paused nodes park their events
        eligible = occupied & ~parked
        dmin, at_min, any_ev = sel.min_deadline(s.t_deadline, eligible,
                                                T.T_INF)
        idx, picked = sel.masked_choice(k_sched, at_min)
        u32 = jnp.uint32

        # ---- PCT-style priority perturbation (search/pct.py) -------------
        # When the per-lane `prio_nudge` operand is nonzero, the uniform
        # tie-break above is REPLACED by a deterministic priority argmax
        # over the earliest-deadline candidates: each slot's priority is a
        # hash of (nudge, slot identity), so one nudge value = one
        # tie-breaking policy, and sweeping nudges enumerates scheduler
        # decisions the way PCT sweeps priority assignments. Contract:
        #  - nudge == 0 is bit-identical to the hook's absence (the
        #    `where` keeps the masked_choice pick, and k_sched was already
        #    consumed either way, so the PRNG stream never shifts);
        #  - nudge is DYNAMIC state — mutating it never recompiles.
        prio = (s.t_tag.astype(u32) * u32(0x9E3779B1)
                ^ s.t_node.astype(u32) * u32(0x85EBCA77)
                ^ jnp.arange(cfg.event_capacity,
                             dtype=jnp.int32).astype(u32) * u32(0xC2B2AE3D)
                ^ s.prio_nudge.astype(u32) * u32(0x27D4EB2F))
        prio = (prio ^ (prio >> 15)) * u32(0x2C1B3C6D)
        # `| 1` floors candidate priorities above the masked-out 0, so the
        # argmax can only land on an at_min slot whenever one exists
        nudged = jnp.argmax(jnp.where(at_min, prio | u32(1),
                                      u32(0))).astype(jnp.int32)
        idx = jnp.where(s.prio_nudge != 0, nudged, idx)
        valid = picked & any_ev & live

        # ---- sim-profiler inputs (cfg.profile; obs/profiler.py) ----------
        # Captured here, written in one block after the emission phase:
        # queue depth at dispatch (pre-pop, so the dispatched row counts)
        # and the clock advance this dispatch buys. Pure reductions over
        # already-computed values — no randomness, no non-pf state. The
        # windowed telemetry plane (cfg.series_windows, r21) shares both
        # captures — same values, same transparency contract.
        if cfg.profile or cfg.series_windows > 0:
            occ_disp = occupied.sum(dtype=jnp.int32)

        ev_kind = jnp.where(valid, sel.take1(s.t_kind, idx), T.EV_FREE)
        ev_node_raw = sel.take1(s.t_node, idx)  # may be NODE_RANDOM (super)
        ev_node = jnp.clip(ev_node_raw, 0, cfg.n_nodes - 1)
        ev_src = sel.take1(s.t_src, idx)
        ev_tag = sel.take1(s.t_tag, idx)
        ev_payload = sel.take_row(s.t_payload, idx)

        # ---- causal lineage (cfg.trace_cap gate; obs/causal.py) ----------
        # The dispatched row's provenance: which dispatch enqueued it
        # (-1 = external) and the Lamport timestamp it carried. The
        # Lamport-rule clock advance happens below, after _apply_super
        # resolves NODE_RANDOM targets. Pure selects over the lineage
        # columns: no randomness consumed, no non-lineage state touched,
        # so trajectories are bit-identical with the recorder compiled
        # out (the r7 ring discipline).
        if cfg.trace_cap > 0:
            disp_idx = s.steps              # this dispatch's index (the
            # value tr_step records for it: steps increments by `valid`
            # below, so the ring's `s.steps - 1` equals this)
            prov = sel.take_row(s.ev_prov, idx)          # [parent, carried]
            ev_parent = jnp.where(valid, prov[0], jnp.asarray(-1,
                                                             jnp.int32))

        # schedule-coverage hash: fold the dispatched event's identity into
        # a running FNV-style mix. Pure VPU arithmetic, consumes no
        # randomness, so it cannot perturb replay; distinct interleavings
        # yield distinct hashes even when terminal states coincide.
        # two independent lanes (64 effective bits — see state.py): same
        # event fields, different multiplier assignment per lane, different
        # FNV-style folding primes
        ev_mix = jnp.stack([
            (ev_kind.astype(u32) * u32(0x9E3779B1)
             ^ ev_node.astype(u32) * u32(0x85EBCA77)
             ^ ev_src.astype(u32) * u32(0xC2B2AE3D)
             ^ ev_tag.astype(u32) * u32(0x27D4EB2F)),
            (ev_kind.astype(u32) * u32(0x27D4EB2F)
             ^ ev_node.astype(u32) * u32(0xC2B2AE3D)
             ^ ev_src.astype(u32) * u32(0x9E3779B1)
             ^ ev_tag.astype(u32) * u32(0x85EBCA77)),
        ])
        fold = jnp.asarray([16777619, 0x85EBCA6B], u32)  # both odd
        sched_hash = jnp.where(valid, (s.sched_hash ^ ev_mix) * fold,
                               s.sched_hash)

        # ---- duplicate-delivery fault (r19; DESIGN §20) ------------------
        # A dispatched MESSAGE may be delivered AGAIN: with the acting
        # node's per-million dup rate (OP_SET_DUP), the popped row is
        # re-armed at a fresh latency draw instead of being freed —
        # byte-identical payload/provenance/root, later deadline, and the
        # duplicate can duplicate again (the retransmit-storm regime).
        # Both draws ride keys FOLDED off k_sched, which the tie-break
        # already consumed, so the zero-rate default consumes nothing
        # from any other stream — trajectories stay bit-identical to r18
        # (the golden-digest contract, tests/test_connfault.py).
        dup_p = (sel.take1(s.dup_rate, ev_node).astype(jnp.float32)
                 * jnp.float32(1e-6))
        k_dupf = jax.random.fold_in(k_sched, 0x44555031)
        dup_fire = (valid & (ev_kind == T.EV_MSG)
                    & prng.bernoulli(k_dupf, dup_p))

        # pop the slot; clock never runs backward (resumed nodes' past-due
        # events fire "now", the park/unpark analog of task.rs:134-137)
        now = jnp.where(valid, jnp.maximum(s.now, dmin), s.now)
        if cfg.profile or cfg.series_windows > 0:
            now_delta = now - s.now          # >= 0; 0 when not valid

        # ---- SLO latency plane inputs (cfg.latency_hist; DESIGN §17) -----
        # Read BEFORE the pop/emission phase: the popped slot may be
        # reclaimed by this very dispatch's emissions, which overwrite
        # ev_root_t. Root rule: a row whose root is unset (-1 — scenario
        # rows, node boots, host injections: external causes) MINTS its
        # root at dispatch (`now`); everything it emits inherits it.
        # Sojourn = now − the dispatched row's deadline (all
        # earliest-deadline ties share dmin) — the queue-wait this row
        # paid to contention/parking. Pure selects, no randomness.
        if cfg.latency_hist > 0:
            root_raw = sel.take1(s.ev_root_t, idx)
            inherit = valid & (root_raw >= 0)
            # the root this dispatch MEASURES against (completion fold):
            # always the inherited one, so a (complete AND root) kind —
            # e.g. a reply delivery that also starts the next sequential
            # call — measures the finished request before restarting
            root_measured = jnp.where(inherit, root_raw, now)
            if cfg.root_kinds:
                # model-declared request STARTS re-mint the root even on
                # an inherited chain (the closed-loop client's new-
                # request timer; see types.py root_kinds)
                is_root_kind = functools.reduce(
                    jnp.logical_or,
                    [(ev_kind == k) & (ev_tag == t)
                     for k, t in cfg.root_kinds])
                inherit = inherit & ~is_root_kind
            # the root this dispatch's EMISSIONS inherit (post-mint)
            ev_root = jnp.where(inherit, root_raw, now)
            lat_sojourn = jnp.maximum(jnp.where(valid, now - dmin, 0), 0)
        if cfg.span_attr:
            # ---- span-attribution carried reads (r23; DESIGN §24) --------
            # Pre-pop like ev_root_t (the popped slot may be reclaimed by
            # this dispatch's own emissions). The carried vector follows
            # the root's inherit/measure split: the completion fold
            # measures the INHERITED chain (pre-re-mint), emissions carry
            # the post-mint one. A row minting its root starts a fresh
            # chain — nothing accumulated, no dominant segment. The
            # incoming edge's transit is recoverable at dispatch with no
            # per-emission storage: deadline − the emitter's stamped
            # `now` (SP_EMIT_T) = the latency + disk delay the emission
            # imposed (a dup re-arm moves the deadline, so the duplicate
            # delivery honestly measures to ITS deadline). Pure selects,
            # no randomness.
            inherit_sp = valid & (root_raw >= 0)
            span_raw = sel.take_row(s.ev_span, idx)        # [SPAN_WORDS]
            in_sq = jnp.where(inherit_sp, span_raw[ST.SP_QWAIT], 0)
            in_sn = jnp.where(inherit_sp, span_raw[ST.SP_NET], 0)
            in_sh = jnp.where(inherit_sp, span_raw[ST.SP_HOPS], 0)
            in_dnode = jnp.where(inherit_sp, span_raw[ST.SP_DOM_NODE], -1)
            in_dmag = jnp.where(inherit_sp, span_raw[ST.SP_DOM_MAG], 0)
            in_emit = jnp.where(inherit_sp, span_raw[ST.SP_EMIT_T], -1)
            net_seg = jnp.where(inherit_sp & (in_emit >= 0),
                                jnp.maximum(dmin - in_emit, 0), 0)
        # strict >: the scenario's HALT op sits at exactly time_limit, and
        # same-deadline ties may dispatch before it without being late
        time_over = now > s.tlimit
        # the duplicate's redelivery instant: a fresh network-latency draw
        # past the dispatch (never the same tick — the copy is a distinct
        # future delivery, like the reference's re-sent datagram)
        k_dupd = jax.random.fold_in(k_sched, 0x44555032)
        redeliver = now + jnp.maximum(
            prng.randint(k_dupd, s.lat_lo, s.lat_hi), 1)
        s = s.replace(
            key=key,
            now=now,
            sched_hash=sched_hash,
            # a duplicating dispatch keeps its row (kind/node/src/tag/
            # payload — and ev_prov/ev_root_t, which the pop never
            # touches) and only moves the deadline; everything else frees
            t_kind=sel.put_row(s.t_kind, idx,
                               jnp.asarray(T.EV_FREE, s.t_kind.dtype),
                               valid & ~dup_fire),
            t_deadline=sel.put_row(s.t_deadline, idx,
                                   jnp.where(dup_fire, redeliver,
                                             jnp.asarray(T.T_INF,
                                                         jnp.int32)),
                                   valid),
        )

        # ---- 2. supervisor op (Handle::kill/restart/... as events) ---------
        is_super = valid & (ev_kind == T.EV_SUPER)
        op = jnp.where(is_super, ev_tag, 0)
        ext_keys = prng.split(k_super, 1 + max(len(extensions), 1))
        s, init_node, reset_target, reset_mask = _apply_super(
            cfg, spec_default, persist_mask, s, op, ev_node_raw, ev_src,
            ev_payload, ext_keys[0])
        # extension custom ops + node-reset hooks (plugin.rs analog).
        # Extensions get the RESOLVED target so NODE_RANDOM scheduled ops
        # work for custom opcodes exactly like for built-ins.
        if extensions:
            new_ext = dict(s.ext)
            for i, e in enumerate(extensions):
                sub = new_ext[e.name]
                sub = e.on_op(cfg, sub, op, reset_target, ev_src, ev_payload,
                              ext_keys[1 + i])
                sub = e.reset_node(cfg, sub, reset_target, reset_mask)
                new_ext[e.name] = sub
            s = s.replace(ext=new_ext)

        # Lamport rule at the node the dispatch actually ACTED on:
        # clock = max(own, carried) + 1. For supervisor ops the scheduled
        # row may say NODE_RANDOM (ev_node clips it to 0), but a
        # kill/restart is an event AT the node _apply_super resolved —
        # so the clock advances there, not at the clipped placeholder.
        if cfg.trace_cap > 0:
            lam_node = jnp.where(is_super, reset_target, ev_node)
            ev_lamport = jnp.maximum(sel.take1(s.lamport, lam_node),
                                     prov[1]) + 1
            s = s.replace(lamport=sel.put_row(s.lamport, lam_node,
                                              ev_lamport, valid))

        # ---- span-attribution accumulation (cfg.span_attr; DESIGN §24) ---
        # Fold THIS dispatch's hop into the chain it inherited: its own
        # queue-wait (lat_sojourn) into the wait accumulator, the
        # incoming edge's transit (net_seg) into the transit accumulator,
        # and the hop's total cost against the dominant segment, owned by
        # the ACTING node (the pf_busy attribution rule). The measured
        # accumulators telescope: wait + transit of a completion equals
        # now − root EXACTLY (every hop contributes (deadline − emit) +
        # (dispatch − deadline) = dispatch − emit, and emit stamps chain
        # from the root's own `now`) — the invariant the host parent-walk
        # cross-check and the sa_tail fold both stand on. A dispatch
        # minting a fresh root measures zero (it IS the root).
        if cfg.span_attr:
            act_sp = jnp.where(is_super, reset_target, ev_node)
            meas_sq = jnp.where(inherit_sp, in_sq + lat_sojourn, 0)
            meas_sn = jnp.where(inherit_sp, in_sn + net_seg, 0)
            meas_sh = in_sh
            seg_sp = net_seg + lat_sojourn          # this hop's cost
            dom_up = inherit_sp & (seg_sp > in_dmag)
            meas_dnode = jnp.where(dom_up, act_sp, in_dnode)
            meas_dmag = jnp.where(dom_up, seg_sp, in_dmag)
            # what this dispatch's EMISSIONS carry (post-mint, like
            # ev_root): a re-minted root restarts the chain at zero; the
            # child's hop index is this dispatch's plus one; every
            # emission is stamped with this dispatch's `now`
            span_new = jnp.stack([
                jnp.where(inherit, meas_sq, 0),
                jnp.where(inherit, meas_sn, 0),
                jnp.where(inherit, meas_sh, 0) + 1,
                jnp.where(inherit, meas_dnode, -1),
                jnp.where(inherit, meas_dmag, 0),
                now])                               # [SPAN_WORDS]

        # ---- 3. protocol handler dispatch ---------------------------------
        node_ok = (sel.take1(s.alive, ev_node)
                   & ~sel.take1(s.paused, ev_node))
        is_msg = valid & (ev_kind == T.EV_MSG) & node_ok
        is_timer = valid & (ev_kind == T.EV_TIMER) & node_ok
        is_init = init_node >= 0
        dropped = valid & (ev_kind == T.EV_MSG) & ~node_ok
        h_node = jnp.where(is_init, jnp.clip(init_node, 0, cfg.n_nodes - 1),
                           ev_node)
        base_slice = _slice_node(s.node_state, h_node)

        # ---- gray-failure fault plane reads (r17; DESIGN §18) ------------
        # The acting node's clock-rate skew and disk-stall delay. Handlers
        # observe the node's LOCAL clock (now + drift) as ctx.now — a
        # skewed node timestamps its messages wrong, which is the whole
        # point; its timer delays stretch inversely below, and every
        # emission leaves disk_lat late. All exact-identity at the zero
        # defaults, no randomness consumed.
        sk_h = sel.take1(s.skew, h_node)
        h_now = s.now + _drift(s.now, sk_h)
        dlat_h = sel.take1(s.disk_lat, h_node)

        combos = []  # (mask, ctx) pairs; masks are mutually exclusive
        h_prog = sel.take1(node_prog_j, h_node)
        for p_idx, prog in enumerate(programs):
            pmask = h_prog == p_idx
            for hkind, run in (
                (is_init, lambda c: prog.init(c)),
                (is_msg, lambda c: prog.on_message(c, ev_src, ev_tag,
                                                   ev_payload)),
                (is_timer, lambda c: prog.on_timer(c, ev_tag, ev_payload)),
            ):
                ctx = Ctx(cfg, h_node, h_now, k_handler, base_slice,
                          hash_base=s.hash_base)
                run(ctx)
                combos.append((hkind & pmask, ctx))

        # merge combo results (masks are mutually exclusive by construction)
        any_h = functools.reduce(jnp.logical_or, [m for m, _ in combos])
        new_slice = base_slice
        crash = jnp.asarray(False)
        crash_code = jnp.asarray(0, jnp.int32)
        halt_req = jnp.asarray(False)
        n_sends = max((len(c._sends) for _, c in combos), default=0)
        n_timers = max((len(c._timers) for _, c in combos), default=0)
        n_cancels = max((len(c._cancels) for _, c in combos), default=0)
        sends = [EMPTY_SEND(P) for _ in range(n_sends)]
        timers = [EMPTY_TIMER(P) for _ in range(n_timers)]
        cancels = [EMPTY_CANCEL() for _ in range(n_cancels)]
        for m, ctx in combos:
            new_slice = _where_tree(m, ctx.state, new_slice)
            crash = crash | (m & ctx._crash)
            crash_code = jnp.where(m & ctx._crash, ctx._crash_code, crash_code)
            halt_req = halt_req | (m & ctx._halt)
            for j, e in enumerate(ctx._sends):
                e = dict(e, m=e["m"] & m)
                sends[j] = _where_tree(m, e, sends[j])
            for j, e in enumerate(ctx._timers):
                e = dict(e, m=e["m"] & m)
                timers[j] = _where_tree(m, e, timers[j])
            for j, e in enumerate(ctx._cancels):
                e = dict(e, m=e["m"] & m)
                cancels[j] = _where_tree(m, e, cancels[j])

        s = s.replace(
            node_state=_scatter_node(s.node_state, h_node, new_slice, any_h))

        # timer cancellation first: freed rows are reusable by this same
        # handler's emissions below (Sleep::reset / abort analog)
        for e in cancels:
            hit = (e["m"] & (s.t_kind == T.EV_TIMER)
                   & (s.t_node == h_node) & (s.t_tag == e["tag"]))
            s = s.replace(
                t_kind=jnp.where(hit, T.EV_FREE, s.t_kind),
                t_deadline=jnp.where(hit, T.T_INF, s.t_deadline))

        # ---- 4. materialize emissions into the event table ----------------
        # All emissions are staged into [E]-vectors and written with ONE
        # gather+scatter per table column (slots are distinct by
        # construction), instead of E separate dynamic-index updates — the
        # difference between ~6 and ~6*E scatter ops per step on TPU.
        E = n_sends + n_timers
        sent = delivered_drop = jnp.asarray(0, jnp.int32)
        overflow = jnp.asarray(False)
        high_water = jnp.asarray(0, jnp.int32)
        delay_acc = jnp.asarray(0, jnp.int32)   # cfg.profile: latency sum
        if E > 0:
            free = s.t_kind == T.EV_FREE
            occupied_now = (~free).sum(dtype=jnp.int32)
            slots, slot_ok = sel.first_k_free(free, E, scatter=em_scatter)
            # per-send: loss + latency keys; per-emission (send AND
            # timer): one micro-jitter key (net/mod.rs:151-156 — the
            # reference random-delays EVERY network op). STATICALLY
            # gated: the draws cost a key-split + randint per emission
            # on the dominant phase, so a build with op_jitter_max == 0
            # compiles none of it; when enabled, the BOUND (state.
            # jitter) stays dynamic and tunes without recompile.
            # Enabled/disabled are distinct replay domains (the config
            # hash covers the field); apply_net_override refuses to set
            # a nonzero bound on a jitterless build.
            use_jitter = cfg.net.op_jitter_max > 0
            net_keys = prng.split(
                k_net, 2 * max(n_sends, 1) + (E if use_jitter else 0))
            jit_keys = net_keys[2 * max(n_sends, 1):]

            def jitter_draw(key):
                return (prng.randint(key, 0, s.jitter) if use_jitter
                        else jnp.asarray(0, jnp.int32))
            em_write, em_deadline, em_kind = [], [], []
            em_node, em_tag, em_payload = [], [], []
            src_clog = sel.take1(s.clog_node, h_node)
            src_links = sel.take_row(s.clog_link, h_node)    # [N]

            for j, e in enumerate(sends):
                dst = jnp.clip(e["dst"], 0, cfg.n_nodes - 1)
                # network fault model: clog + loss + latency
                # (network.rs:222-229)
                clogged = (src_clog | sel.take1(s.clog_node, dst)
                           | sel.take1(src_links, dst))
                lost = prng.bernoulli(net_keys[2 * j], s.loss)
                latency = (prng.randint(net_keys[2 * j + 1], s.lat_lo,
                                        s.lat_hi)
                           + jitter_draw(jit_keys[j] if use_jitter
                                         else None))
                ok = e["m"] & ~clogged & ~lost
                sent = sent + e["m"].astype(jnp.int32)
                delivered_drop = delivered_drop + (e["m"] & ~ok).astype(
                    jnp.int32)
                if cfg.profile:
                    # latency actually imposed on delivered sends (the
                    # profiler's delay counter; dropped sends impose no
                    # delay — they impose a drop)
                    delay_acc = delay_acc + jnp.where(ok, latency, 0)
                write = ok & slot_ok[j]
                overflow = overflow | (ok & ~slot_ok[j])
                em_write.append(write)
                # slow-disk fault: a stalled node's replies leave late
                # (dlat_h == 0 on healthy nodes — exact identity)
                em_deadline.append(s.now + latency + dlat_h)
                em_kind.append(jnp.asarray(T.EV_MSG, jnp.int32))
                em_node.append(dst)
                em_tag.append(e["tag"])
                em_payload.append(e["payload"])

            for j, e in enumerate(timers):
                write = e["m"] & slot_ok[n_sends + j]
                overflow = overflow | (e["m"] & ~slot_ok[n_sends + j])
                em_write.append(write)
                # clock-skew stretch: a delay is measured on the node's
                # LOCAL clock, so a fast clock (skew > 0) fires it
                # earlier in global time — d_eff = d − (d·skew)>>10,
                # identity at skew 0; the slow-disk delay then pushes
                # the deadline back like every other emission
                d_eff = jnp.maximum(e["delay"]
                                    - _drift(e["delay"], sk_h), 0)
                em_deadline.append(s.now + d_eff + dlat_h
                                   + jitter_draw(
                                       jit_keys[n_sends + j]
                                       if use_jitter else None))
                em_kind.append(jnp.asarray(T.EV_TIMER, jnp.int32))
                em_node.append(h_node)
                em_tag.append(e["tag"])
                em_payload.append(e["payload"])

            w = jnp.stack(em_write)                      # [E] bool
            high_water = occupied_now + w.sum(dtype=jnp.int32)
            if em_scatter:
                # O(E) scatter per column: real slots are distinct by
                # construction; masked-off emissions target DISTINCT
                # out-of-range rows (C + j) so `unique_indices` holds and
                # mode="drop" discards them
                slots_eff = jnp.where(
                    w, slots,
                    cfg.event_capacity + jnp.arange(E, dtype=jnp.int32))

                def put(col, vals):
                    v = jnp.stack(vals)                  # [E] or [E, P]
                    return col.at[slots_eff].set(
                        v.astype(col.dtype), mode="drop",
                        unique_indices=True)
            else:
                # one-hot write instead of an [E]-index scatter (serializes
                # on TPU, ~10ns/element): real slots are distinct by
                # construction, so summing the one-hot rows yields each
                # written value exactly once; masked-off emissions match no
                # column and write nothing. The [E, C] product is what the
                # scatter form above avoids on CPU (width tax, DESIGN §5).
                slots_eff = jnp.where(
                    w, slots, jnp.asarray(cfg.event_capacity, jnp.int32))
                slot_oh = slots_eff[:, None] == jnp.arange(
                    cfg.event_capacity, dtype=jnp.int32)     # [E, C]
                written = slot_oh.any(0)                     # [C]

                def put(col, vals):
                    v = jnp.stack(vals)                      # [E] or [E, P]
                    ohi = slot_oh.astype(v.dtype)
                    if v.ndim == 1:
                        upd = (ohi * v[:, None]).sum(0)
                        # cast, not promote: staged values are int32 but the
                        # column may be a narrow (table_dtype) dtype
                        return jnp.where(written, upd, col).astype(col.dtype)
                    upd = jnp.einsum("ec,ep->cp", ohi, v)
                    return jnp.where(written[:, None], upd, col)

            s = s.replace(
                t_deadline=put(s.t_deadline, em_deadline),
                t_kind=put(s.t_kind, em_kind),
                t_node=put(s.t_node, em_node),
                t_src=put(s.t_src, [h_node] * E),
                t_tag=put(s.t_tag, em_tag),
                t_payload=put(s.t_payload, em_payload),
            )
            if cfg.trace_cap > 0:
                # provenance of every emitted row: enqueued by THIS
                # dispatch, carrying the acting node's post-dispatch
                # clock (the Lamport message timestamp). Every emission
                # of a dispatch writes the SAME pair, so each lowering
                # reuses its own machinery — the scatter path's
                # drop-mode slots_eff, the one-hot path's existing [C]
                # `written` mask (never rebuilt; --mode causal_ab
                # bounds the whole lineage build's cost)
                prov_new = jnp.stack([disp_idx, ev_lamport])
                if em_scatter:
                    s = s.replace(ev_prov=s.ev_prov.at[slots_eff].set(
                        jnp.broadcast_to(prov_new, (E, 2)),
                        mode="drop", unique_indices=True))
                else:
                    s = s.replace(ev_prov=jnp.where(
                        written[:, None], prov_new[None, :], s.ev_prov))
            if cfg.latency_hist > 0:
                # root-birth-time inheritance: every row this dispatch
                # emits carries the dispatch's own root — the same
                # one-broadcast-per-dispatch shape as ev_prov above,
                # riding the identical slots_eff / written machinery
                if em_scatter:
                    s = s.replace(ev_root_t=s.ev_root_t.at[slots_eff].set(
                        jnp.broadcast_to(ev_root, (E,)),
                        mode="drop", unique_indices=True))
                else:
                    s = s.replace(ev_root_t=jnp.where(
                        written, ev_root, s.ev_root_t))
            if cfg.span_attr:
                # carried span vector: every row this dispatch emits
                # inherits the chain THROUGH this dispatch (its own
                # queue-wait and incoming transit folded in above) — one
                # [SPAN_WORDS] broadcast per dispatch riding the same
                # slots_eff / written machinery as ev_prov/ev_root_t
                if em_scatter:
                    s = s.replace(ev_span=s.ev_span.at[slots_eff].set(
                        jnp.broadcast_to(span_new, (E, ST.SPAN_WORDS)),
                        mode="drop", unique_indices=True))
                else:
                    s = s.replace(ev_span=jnp.where(
                        written[:, None], span_new[None, :], s.ev_span))

        # oops/steps are correctness-bearing and always tracked; the stat
        # counters honor cfg.collect_stats (Stat is optional in the
        # reference too — NetSim::stat is a query, not a requirement)
        if cfg.collect_stats:
            s = s.replace(
                msg_sent=s.msg_sent + sent,
                msg_delivered=s.msg_delivered + is_msg.astype(jnp.int32),
                msg_dropped=s.msg_dropped + delivered_drop
                + dropped.astype(jnp.int32),
                ev_peak=jnp.maximum(s.ev_peak, high_water),
            )
        s = s.replace(
            oops=s.oops | jnp.where(overflow, T.OOPS_EVENT_OVERFLOW, 0)
            | jnp.where(s.now > T.T_INF - 64 * T.TICKS_PER_SEC,
                        T.OOPS_TIME_OVERFLOW, 0),
            steps=s.steps + valid.astype(jnp.int32),
        )

        # ---- sim-profiler counter plane (cfg.profile; DESIGN §16) --------
        # One block of saturating one-hot increments over values the step
        # already computed: per-(node, kind) dispatch counts and per-node
        # busy time at the ACTING node (for supervisor ops the node
        # _apply_super resolved — the Lamport-rule node), effective
        # kill/boot counts at the reset target, occupancy high-water,
        # drop and delay totals. No randomness consumed, no non-pf state
        # touched: trajectories are bit-identical across the knob, and
        # the pf_* columns ride TRACE_FIELDS out of fingerprints.
        if cfg.profile:
            rec_p = valid & s.pf_on
            act_node = jnp.where(is_super, reset_target, ev_node)
            ohP = sel.row_onehot(cfg.n_nodes, act_node)      # [N]
            k_oh = (jnp.arange(N_EV_KINDS, dtype=jnp.int32)
                    == ev_kind)                              # [K]
            was_kill = reset_mask & ((op == T.OP_KILL)
                                     | (op == T.OP_RESTART))
            was_boot = reset_mask & ((op == T.OP_INIT)
                                     | (op == T.OP_RESTART))
            s = s.replace(
                pf_dispatch=_sat_add(
                    s.pf_dispatch,
                    (ohP[:, None] & k_oh[None, :] & rec_p)
                    .astype(jnp.int32)),
                pf_busy=_sat_add(s.pf_busy,
                                 jnp.where(ohP & rec_p, now_delta, 0)),
                pf_kill=_sat_add(s.pf_kill,
                                 (ohP & was_kill & rec_p)
                                 .astype(jnp.int32)),
                pf_restart=_sat_add(s.pf_restart,
                                    (ohP & was_boot & rec_p)
                                    .astype(jnp.int32)),
                pf_qmax=jnp.where(
                    rec_p,
                    jnp.maximum(s.pf_qmax,
                                jnp.maximum(occ_disp, high_water)),
                    s.pf_qmax),
                pf_drop=_sat_add(s.pf_drop, jnp.where(
                    rec_p, delivered_drop + dropped.astype(jnp.int32), 0)),
                pf_delay=_sat_add(s.pf_delay,
                                  jnp.where(rec_p, delay_acc, 0)),
            )

        # ---- SLO latency plane (cfg.latency_hist; DESIGN §17) ------------
        # Fold this dispatch's queue-wait — and, on completion kinds, its
        # end-to-end request latency — into the per-node log2 histograms.
        # Bucketing is EXACT integer arithmetic: bucket(d) counts the
        # thresholds 2^j <= d, so d in [2^(j-1), 2^j) lands in bucket j
        # and d == 0 in bucket 0 (a float log2 would misbucket near
        # power-of-two boundaries). One [N]x[B] one-hot saturating write
        # per histogram; no randomness, no non-latency state — the same
        # transparency contract as the pf_* counters, and the fold runs
        # BEFORE the end-condition checks so an `invariant=` (e.g.
        # harness.slo_invariant) sees this dispatch's completion.
        lat_e2e = None
        if cfg.latency_hist > 0:
            LB = cfg.latency_hist
            rec_l = valid & s.lh_on
            thr = jnp.asarray([1 << j for j in range(LB - 1)], jnp.int32)

            def bucket_oh(d):     # [LB] one-hot of d's log2 bucket
                b = (d >= thr).sum(dtype=jnp.int32)
                return jnp.arange(LB, dtype=jnp.int32) == b

            # sojourn at the ACTING node (supervisor ops: the resolved
            # target — same attribution rule as pf_busy)
            act_l = jnp.where(is_super, reset_target, ev_node)
            oh_act = sel.row_onehot(cfg.n_nodes, act_l)       # [N]
            s = s.replace(lh_sojourn=_sat_add(
                s.lh_sojourn,
                (oh_act[:, None] & bucket_oh(lat_sojourn)[None, :]
                 & rec_l).astype(jnp.int32)))
            if cfg.complete_kinds:
                is_complete = valid & functools.reduce(
                    jnp.logical_or,
                    [(ev_kind == k) & (ev_tag == t)
                     for k, t in cfg.complete_kinds])
                lat_e2e = jnp.maximum(now - root_measured, 0)
                lat_e2e_raw = lat_e2e    # pre-sentinel value: the series
                # plane below folds the completion's latency per WINDOW
                oh_cpl = sel.row_onehot(cfg.n_nodes, ev_node)  # [N]
                done_l = is_complete & s.lh_on
                miss = (done_l & (s.slo_target > 0)
                        & (lat_e2e > s.slo_target))
                s = s.replace(
                    lh_e2e=_sat_add(
                        s.lh_e2e,
                        (oh_cpl[:, None] & bucket_oh(lat_e2e)[None, :]
                         & done_l).astype(jnp.int32)),
                    lh_slo_miss=_sat_add(
                        s.lh_slo_miss,
                        (oh_cpl & miss).astype(jnp.int32)))
                # the ring's per-dispatch latency value (tr_lat):
                # completions record e2e, everything else -1
                lat_e2e = jnp.where(is_complete, lat_e2e,
                                    jnp.asarray(-1, jnp.int32))

        # ---- span-attribution fold (cfg.span_attr; DESIGN §24) -----------
        # Only TAIL completions attribute (e2e over the dynamic
        # slo_target — the lh_slo_miss gate, on this plane's own lane
        # mask): the healthy majority would drown the tail's signal.
        # One [N, SA_COMPONENTS] saturating masked add at the completion
        # node plus one [N] one-hot increment at the dominant segment's
        # owner. No randomness, no non-span state — the pf_*/lh_*
        # transparency contract.
        if cfg.span_attr:
            tail_sp = (is_complete & s.sp_on & (s.slo_target > 0)
                       & (lat_e2e_raw > s.slo_target))
            comp_vals = jnp.stack([jnp.asarray(1, jnp.int32), meas_sq,
                                   meas_sn, meas_sh])  # [SA_COMPONENTS]
            oh_dom = (sel.row_onehot(
                cfg.n_nodes, jnp.clip(meas_dnode, 0, cfg.n_nodes - 1))
                & tail_sp & (meas_dnode >= 0))
            s = s.replace(
                sa_tail=_sat_add(
                    s.sa_tail,
                    jnp.where(oh_cpl[:, None] & tail_sp,
                              comp_vals[None, :], 0)),
                sa_bottleneck=_sat_add(s.sa_bottleneck,
                                       oh_dom.astype(jnp.int32)))

        # ---- prefix-coverage sketch (cfg.sketch_slots; DESIGN §12) -------
        # Fold the running sched_hash into slot j = steps/every - 1 at
        # every sketch_every-th dispatch: slot j then witnesses the whole
        # (j+1)*every-step prefix, so the first slot where two lanes'
        # sketches differ bounds their first schedule divergence — depth
        # telemetry that never leaves the device mid-run. One [slots]
        # one-hot select per step; `every` is a dynamic operand
        # (s.sketch_every), only the slot COUNT shapes the program.
        if cfg.sketch_slots > 0:
            period = jnp.maximum(s.sketch_every, 1)
            ck = s.steps // period
            at_ck = (valid & (s.steps == ck * period) & (ck >= 1)
                     & (ck <= cfg.sketch_slots))
            oh_ck = sel.row_onehot(
                cfg.sketch_slots,
                jnp.clip(ck - 1, 0, cfg.sketch_slots - 1)) & at_ck
            s = s.replace(cov_sketch=jnp.where(
                oh_ck, s.sched_hash[0] ^ s.sched_hash[1], s.cov_sketch))

        # ---- windowed telemetry plane (cfg.series_windows; DESIGN §22) ---
        # Fold this dispatch into its sim-time WINDOW: the dispatch's
        # post-advance `now` picks window min(now // window_len, W-1) —
        # a dispatch exactly ON a boundary opens the next window, events
        # past W*window_len clamp into the last one. window_len is a
        # DYNAMIC operand (retune without recompile, the trace_cap/
        # sketch_every discipline); only the window COUNT shapes the
        # program. One [W] one-hot (and one [W, N] outer product for the
        # per-node series) of saturating writes over values the step
        # already computed — no randomness, no non-series state, so
        # trajectories are bit-identical across the knob and the sr_*
        # columns ride TRACE_FIELDS out of fingerprints. Runs BEFORE the
        # end-condition checks so an `invariant=` (e.g.
        # harness.recovery_invariant) sees this dispatch's window.
        if cfg.series_windows > 0:
            SW = cfg.series_windows
            rec_s = valid & s.sr_on
            w_idx = jnp.minimum(now // jnp.maximum(s.window_len, 1),
                                SW - 1)
            oh_w = sel.row_onehot(SW, w_idx)                  # [W]
            # acting-node attribution: the _apply_super-resolved target
            # for supervisor ops (the pf_dispatch/pf_busy rule)
            act_s = jnp.where(is_super, reset_target, ev_node)
            oh_ns = sel.row_onehot(cfg.n_nodes, act_s)        # [N]
            cell = oh_w[:, None] & oh_ns[None, :] & rec_s     # [W, N]
            # fault-marker word: which fault classes landed in this
            # window (SRF_* bits, types.py). Kill/boot bits require the
            # op to have been EFFECTIVE (reset_mask); matrix/knob ops
            # mark on dispatch. OR-accumulated — bits, not counts.
            eff_kill = reset_mask & ((op == T.OP_KILL)
                                     | (op == T.OP_RESTART))
            eff_boot = reset_mask & ((op == T.OP_INIT)
                                     | (op == T.OP_RESTART))

            def opin(*ops):
                return is_super & functools.reduce(
                    jnp.logical_or, [op == o for o in ops])

            f_bits = (
                jnp.where(eff_kill, T.SRF_KILL, 0)
                | jnp.where(eff_boot, T.SRF_BOOT, 0)
                | jnp.where(opin(T.OP_CLOG_NODE, T.OP_CLOG_LINK,
                                 T.OP_PARTITION, T.OP_PARTITION_ONEWAY),
                            T.SRF_PARTITION, 0)
                | jnp.where(opin(T.OP_HEAL, T.OP_UNCLOG_NODE,
                                 T.OP_UNCLOG_LINK), T.SRF_HEAL, 0)
                | jnp.where(opin(T.OP_SET_LOSS, T.OP_SET_LATENCY),
                            T.SRF_NET, 0)
                | jnp.where(opin(T.OP_SET_SKEW, T.OP_SET_DISK),
                            T.SRF_GRAY, 0)
                | jnp.where(opin(T.OP_RESET_PEER, T.OP_SET_DUP),
                            T.SRF_CONN, 0))
            s = s.replace(
                sr_dispatch=_sat_add(s.sr_dispatch,
                                     cell.astype(jnp.int32)),
                sr_busy=_sat_add(s.sr_busy,
                                 jnp.where(cell, now_delta, 0)),
                # per-window occupancy high-water: max, never saturates
                sr_qhw=jnp.where(
                    oh_w & rec_s,
                    jnp.maximum(s.sr_qhw,
                                jnp.maximum(occ_disp, high_water)),
                    s.sr_qhw),
                sr_drop=_sat_add(s.sr_drop, jnp.where(
                    oh_w & rec_s,
                    delivered_drop + dropped.astype(jnp.int32), 0)),
                sr_dup=_sat_add(s.sr_dup,
                                (oh_w & rec_s & dup_fire)
                                .astype(jnp.int32)),
                sr_fault=s.sr_fault | jnp.where(oh_w & rec_s, f_bits, 0),
            )
            if cfg.latency_hist > 0 and cfg.complete_kinds:
                # per-window completion/miss counts + e2e histogram —
                # the same fold as the lh_* plane, bucketed by WINDOW
                # instead of node, gated on THIS plane's lane mask
                done_s = is_complete & s.sr_on
                miss_s = (done_s & (s.slo_target > 0)
                          & (lat_e2e_raw > s.slo_target))
                s = s.replace(
                    sr_complete=_sat_add(s.sr_complete,
                                         (oh_w & done_s)
                                         .astype(jnp.int32)),
                    sr_slo_miss=_sat_add(s.sr_slo_miss,
                                         (oh_w & miss_s)
                                         .astype(jnp.int32)),
                    sr_lat=_sat_add(
                        s.sr_lat,
                        (oh_w[:, None] & bucket_oh(lat_e2e_raw)[None, :]
                         & done_s).astype(jnp.int32)))

        # ---- 5. end conditions -------------------------------------------
        # deadlock: nothing can ever run again (madsim task.rs:116 panic)
        crash = crash | ((~any_ev | time_over) & live)
        crash_code = jnp.where(
            ~any_ev & live, T.CRASH_DEADLOCK,
            jnp.where(time_over & live & (crash_code == 0),
                      T.CRASH_TIME_LIMIT, crash_code))
        halted_now = halt_req | (is_super & (op == T.OP_HALT))
        if halt_when is not None:
            # global success condition (the root-future-ready analog): e.g.
            # "all clients acked" — has whole-cluster visibility
            halted_now = halted_now | (halt_when(s) & live)

        if invariant is not None:
            bad, code = invariant(s)
            bad = bad & live
            first = bad & ~crash
            crash_code = jnp.where(first, code, crash_code)
            crash = crash | bad

        s = s.replace(
            crashed=s.crashed | crash,
            crash_code=jnp.where(crash & (s.crash_code == 0), crash_code,
                                 s.crash_code),
            crash_node=jnp.where(crash & (s.crash_node < 0), h_node,
                                 s.crash_node),
            halted=s.halted | halted_now | crash,
        )

        # records always int32: table_dtype is an internal bandwidth
        # lever and must not leak into the trace schema
        record = dict(
            now=s.now, kind=ev_kind.astype(jnp.int32),
            node=ev_node.astype(jnp.int32), src=ev_src.astype(jnp.int32),
            tag=ev_tag.astype(jnp.int32), payload=ev_payload,
            fired=valid,
        )

        # ---- flight-recorder ring (cfg.trace_cap; obs/rings.py) ----------
        # The same record, written into a per-lane ring that lives in
        # SimState — so it survives `lax.while_loop` and the fused runner
        # is no longer blind. Only FIRED events of SAMPLED lanes write
        # (the ring never holds frozen-lane records, unlike the
        # collect_events stream, whose consumers must filter on `fired`).
        # One one-hot row write per column, no randomness consumed: all
        # non-trace state stays bit-identical across trace_cap settings.
        if cfg.trace_cap > 0:
            rec_w = record["fired"] & s.trace_on
            # DYNAMIC capacity (s.trace_cap), bucket-sized columns: the
            # compiled program depends only on cfg.trace_cap_bucket, so
            # sweeping trace_cap within a bucket shares one executable;
            # slots stay < trace_cap, so rows past it are never written
            # and ring contents are bit-identical to an unbucketed build
            slot = jnp.mod(s.trace_pos, s.trace_cap)
            # one shared one-hot row mask for all six columns (the
            # columns are [bucket] vectors, so put_row's per-call reshape
            # is unnecessary); the recorder's whole per-step cost is six
            # [bucket] selects + one masked increment
            oh = sel.row_onehot(cfg.trace_cap_bucket, slot) & rec_w

            def ringput(col, v):
                return jnp.where(oh, v.astype(col.dtype), col)

            # queue-depth ring column: only when the profiler is also
            # compiled in (its counter-track source; zero-size otherwise)
            extra_cols = (dict(tr_qlen=ringput(s.tr_qlen, occ_disp))
                          if cfg.profile else {})
            if cfg.latency_hist > 0:
                # e2e-latency ring column (rolling-p99 track source):
                # completions record their latency, everything else -1
                extra_cols["tr_lat"] = ringput(
                    s.tr_lat,
                    lat_e2e if lat_e2e is not None
                    else jnp.asarray(-1, jnp.int32))
            if cfg.span_attr:
                # queue-wait ring column: the dispatch's own sojourn, so
                # a host parent-walk splits every hop into wait vs
                # transit (obs/spans.py explain_latency)
                extra_cols["tr_qw"] = ringput(s.tr_qw, lat_sojourn)
            s = s.replace(
                **extra_cols,
                tr_now=ringput(s.tr_now, record["now"]),
                tr_step=ringput(s.tr_step, s.steps - 1),
                tr_kind=ringput(s.tr_kind, record["kind"]),
                tr_node=ringput(s.tr_node, record["node"]),
                tr_src=ringput(s.tr_src, record["src"]),
                tr_tag=ringput(s.tr_tag, record["tag"]),
                # the lineage pair: each recorded event carries its
                # happens-before parent and post-dispatch Lamport clock,
                # so causal chains survive ring wrap (obs/causal.py)
                tr_parent=ringput(s.tr_parent, ev_parent),
                tr_lamport=ringput(s.tr_lamport, ev_lamport),
                trace_pos=s.trace_pos + rec_w.astype(jnp.int32),
            )
        if extensions:
            new_ext = dict(s.ext)
            for e in extensions:
                new_ext[e.name] = e.on_event(cfg, new_ext[e.name], s, record)
            s = s.replace(ext=new_ext)
        return s, record

    return live_step


def _apply_super(cfg, spec_default, persist_mask, s: SimState, op, node, src,
                 payload, key):
    """Apply one supervisor opcode as masked state edits.

    Returns (state, init_node) where init_node >= 0 requests the program
    `init` handler to run on that node this step (OP_INIT / OP_RESTART —
    the NodeBuilder::init respawn of runtime/mod.rs:287-295).
    """
    k_t, k_tear = prng.split(key)
    N = cfg.n_nodes

    # resolve NODE_RANDOM targets (fuzzing): each op draws from the pool of
    # nodes it can meaningfully act on — kill/pause/clog a random alive node,
    # restart a random dead one, resume a random paused one, unclog a random
    # clogged one. A nonzero payload restricts candidates to a bitmask
    # (31 nodes/word, same packing as OP_PARTITION) so e.g. chaos kills
    # target servers but not client/harness nodes, for any
    # N <= 31 * payload_words. Only the words node ids can actually pack
    # into count as "a pool was given" — the r17 value-carrying ops
    # (OP_SET_SKEW / OP_SET_DISK) put their values in the TAIL payload
    # words, past the pool segment, so value and pool coexist.
    want_alive = (op == T.OP_KILL) | (op == T.OP_PAUSE) | (op == T.OP_CLOG_NODE)
    pool = jnp.where(want_alive, s.alive,
                     jnp.where(op == T.OP_RESTART, ~s.alive,
                               jnp.where(op == T.OP_RESUME, s.paused,
                                         jnp.where(op == T.OP_UNCLOG_NODE,
                                                   s.clog_node,
                                                   jnp.ones((N,), bool)))))
    ids = jnp.arange(N, dtype=jnp.int32)
    pool_words = sel.take1(payload, ids // 31)    # one-hot: vector-index
    in_pool = ((pool_words >> (ids % 31)) & 1) == 1     # gathers serialize
    n_pool_words = min(cfg.payload_words, (N + 30) // 31)   # static
    pool = pool & jnp.where((payload[:n_pool_words] != 0).any(), in_pool,
                            jnp.ones((N,), bool))
    rnd, rnd_ok = sel.masked_choice(k_t, pool)
    is_random = node == T.NODE_RANDOM
    target = jnp.clip(jnp.where(is_random, rnd, node), 0, N - 1)
    effective = ~is_random | rnd_ok  # no eligible random target -> no-op
    src_c = jnp.clip(src, 0, N - 1)

    def when(cond):
        return cond & effective

    kill = when((op == T.OP_KILL) | (op == T.OP_RESTART))
    boot = when((op == T.OP_INIT) | (op == T.OP_RESTART))

    # KILL: drop the node's queued events — its tasks die (task.rs:170-182)
    # and its sockets close so undelivered messages vanish (network.rs:113-118)
    clear = kill & (s.t_node == target) & (
        (s.t_kind == T.EV_MSG) | (s.t_kind == T.EV_TIMER))
    t_kind = jnp.where(clear, T.EV_FREE, s.t_kind)
    t_deadline = jnp.where(clear, T.T_INF, s.t_deadline)

    # all per-node edits below are one-hot selects, not .at[target] scatters
    # (a traced scatter index serializes per lane on TPU — DESIGN.md §5)
    ohT = sel.row_onehot(N, target)                         # [N]
    alive = jnp.where(ohT & kill & ~boot, False,
                      jnp.where(ohT & boot, True, s.alive))
    paused = jnp.where(ohT & (kill | boot | when(op == T.OP_RESUME)), False,
                       jnp.where(ohT & when(op == T.OP_PAUSE), True,
                                 s.paused))

    # torn-write kill flush (r17, DESIGN §18): when the target runs in
    # torn mode, a KILL first flushes a RANDOM PREFIX of each fs file's
    # unsynced tail [dlen, mlen) into the durable view — the disk got
    # part of the final record before power died, instead of clean
    # old-or-new. Synced words (< dlen) are never touched, so a synced
    # record can never tear; a cut can land mid-record, which is the
    # point. Compiled only for fs-layer state schemas (the fs.py leaf
    # quartet); the draw uses a key split this function already made,
    # so enabling torn mode never shifts anyone else's PRNG stream.
    ns = s.node_state
    if isinstance(ns, dict) and {"fs_mem", "fs_mlen", "fs_disk",
                                 "fs_dlen"} <= set(ns.keys()):
        # only a LIVE node's power-fail tears: the kill half of an
        # OP_RESTART aimed at an already-dead node is a no-op process-
        # wise, and re-drawing a tear over the corpse's stale unsynced
        # tail would flush words the original power-fail never did
        tearing = kill & sel.take1(s.torn & s.alive, target)
        mem_t = sel.take_row(ns["fs_mem"], target)      # [F, S]
        mlen_t = sel.take_row(ns["fs_mlen"], target)    # [F]
        disk_t = sel.take_row(ns["fs_disk"], target)
        dlen_t = sel.take_row(ns["fs_dlen"], target)
        F, S = mem_t.shape
        gap = jnp.maximum(mlen_t - dlen_t, 0)
        draw = jax.random.randint(k_tear, (F,), 0, jnp.int32(2**30),
                                  dtype=jnp.int32)
        cut = dlen_t + draw % (gap + 1)                 # in [dlen, mlen]
        ws = jnp.arange(S, dtype=jnp.int32)
        flushed = ((ws[None, :] >= dlen_t[:, None])
                   & (ws[None, :] < cut[:, None]))
        ns = dict(
            ns,
            fs_disk=sel.put_row(ns["fs_disk"], target,
                                jnp.where(flushed, mem_t, disk_t),
                                tearing),
            fs_dlen=sel.put_row(ns["fs_dlen"], target,
                                jnp.maximum(dlen_t, cut), tearing))

    # connection-fault tear (r19, DESIGN §20): OP_RESET_PEER kills every
    # live conn/stream touching the target, on BOTH sides — the
    # NetSim::reset_node parity a kill deliberately lacks (the survivor
    # keeps half-open state; only a reset tears streams down). For any
    # state schema carrying the conn/stream leaf quartets: cn_state rows
    # AND columns of the target drop to CLOSED, the stream rings/counters
    # touching it wipe, and both sides' incarnation epochs bump — the RST
    # notification, applied atomically to both endpoints, so segments and
    # RSTs still in flight from the torn incarnation are STALE to the
    # successor connection (net/stream.py drop-on-less rule). Masked
    # edits only; inert for schemas without the leaves, and a no-op mask
    # costs the same selects the other per-node ops already pay.
    rp = when(op == T.OP_RESET_PEER)
    if isinstance(ns, dict):
        touched = (ohT[:, None] | ohT[None, :]) & rp        # [N, N]

        def _cut(col, zero):
            m = touched.reshape(touched.shape
                                + (1,) * (col.ndim - 2))
            return jnp.where(m, zero, col)

        if {"cn_state", "cn_epoch"} <= set(ns.keys()):
            ns = dict(ns,
                      cn_state=_cut(ns["cn_state"], 0),
                      cn_epoch=ns["cn_epoch"]
                      + touched.astype(jnp.int32))
        if {"sx_seq", "sx_base", "sx_val", "sr_next", "sr_val",
                "sr_have", "st_epoch"} <= set(ns.keys()):
            ns = dict(ns,
                      st_epoch=ns["st_epoch"] + touched.astype(jnp.int32),
                      sx_seq=_cut(ns["sx_seq"], 0),
                      sx_base=_cut(ns["sx_base"], 0),
                      sr_next=_cut(ns["sr_next"], 0),
                      sx_val=_cut(ns["sx_val"], 0),
                      sr_val=_cut(ns["sr_val"], 0),
                      sr_have=_cut(ns["sr_have"], False))

    # node boot/restart resets protocol state to the spec default — process
    # memory does not survive a crash. Leaves marked persistent are stable
    # storage (the FsSim analog) and DO survive.
    node_state = jax.tree.map(
        lambda full, dflt, keep: full if keep
        else sel.put_row(full, target, dflt, boot),
        ns, spec_default, persist_mask)

    clog_node = jnp.where(ohT & when(op == T.OP_CLOG_NODE), True,
                          jnp.where(ohT & when(op == T.OP_UNCLOG_NODE),
                                    False, s.clog_node))
    oh_link = sel.row_onehot(N, src_c)[:, None] & ohT[None, :]
    clog_link = jnp.where(oh_link & when(op == T.OP_CLOG_LINK), True,
                          jnp.where(oh_link & when(op == T.OP_UNCLOG_LINK),
                                    False, s.clog_link))

    # whole-matrix ops: OP_PARTITION replaces the link matrix with the cut
    # A <-> not-A (payload packs membership 31 nodes/word); OP_HEAL clears
    # everything. OP_PARTITION_ONEWAY (r17) ORs a DIRECTIONAL cut into the
    # matrix instead — src bit 0 picks the direction (0: A's sends to
    # not-A vanish while A still hears; 1: the reverse) — so one-way cuts
    # compose with each other and with clog_link, and only HEAL clears
    # them (madsim disconnect2 parity).
    words = sel.take1(payload, ids // 31)     # one-hot: vector-index
    in_a = ((words >> (ids % 31)) & 1).astype(bool)       # gathers serialize
    cut = in_a[:, None] != in_a[None, :]
    clog_link = jnp.where(when(op == T.OP_PARTITION), cut, clog_link)
    a_out = in_a[:, None] & ~in_a[None, :]          # [src, dst]: A -> not-A
    cut_dir = jnp.where((src & 1) == 1, a_out.T, a_out)
    clog_link = jnp.where(when(op == T.OP_PARTITION_ONEWAY),
                          clog_link | cut_dir, clog_link)
    clog_link = jnp.where(when(op == T.OP_HEAL),
                          jnp.zeros_like(clog_link), clog_link)
    clog_node = jnp.where(when(op == T.OP_HEAL),
                          jnp.zeros_like(clog_node), clog_node)

    loss = jnp.where(when(op == T.OP_SET_LOSS),
                     payload[0].astype(jnp.float32) / 1e6, s.loss)
    lat_lo = jnp.where(when(op == T.OP_SET_LATENCY), payload[0], s.lat_lo)
    lat_hi = jnp.where(when(op == T.OP_SET_LATENCY),
                       jnp.maximum(payload[1], payload[0]), s.lat_hi)

    # gray-failure per-node knobs (r17): values ride the TAIL payload
    # words (the leading words may hold a NODE_RANDOM pool), bounded at
    # application — a scenario/mutant can explore, never corrupt
    P = cfg.payload_words
    ohSk = ohT & when(op == T.OP_SET_SKEW)
    skew = jnp.where(ohSk, jnp.clip(payload[P - 1], -T.SKEW_CAP,
                                    T.SKEW_CAP), s.skew)
    ohDk = ohT & when(op == T.OP_SET_DISK)
    disk_lat = jnp.where(ohDk, jnp.clip(payload[P - 1], 0, T.DISK_LAT_CAP),
                         s.disk_lat)
    torn = jnp.where(ohDk, payload[P - 2] != 0, s.torn)
    ohDup = ohT & when(op == T.OP_SET_DUP)
    dup_rate = jnp.where(ohDup,
                         jnp.clip(payload[P - 1], 0, T.DUP_RATE_CAP),
                         s.dup_rate)

    init_node = jnp.where(boot, target, jnp.asarray(-1, jnp.int32))
    s = s.replace(t_kind=t_kind, t_deadline=t_deadline, alive=alive,
                  paused=paused, node_state=node_state, clog_node=clog_node,
                  clog_link=clog_link, loss=loss, lat_lo=lat_lo,
                  lat_hi=lat_hi, skew=skew, disk_lat=disk_lat, torn=torn,
                  dup_rate=dup_rate)
    return s, init_node, target, (kill | boot)
