"""Structural signatures: the cache key for "compiles to the same program".

Two Runtime constructions may share a compiled step program iff everything
that is BAKED INTO THE TRACE is equal: the structural slice of SimConfig
(`SimConfig.structural_signature()`), the program handlers' code and
captured parameters, the state-spec defaults (they become boot-reset
constants in `_apply_super`), the node->program map, the persist mask, and
the invariant/halt_when checks. Everything else — scenario rows, seeds,
time limit, loss/latency/jitter values — is initial-state DATA and must
NOT appear here, or it would key spurious recompiles.

`freeze()` turns those ingredients into a hashable value. It is
deliberately conservative: anything it cannot prove stable (an object of
unknown type, a recursive structure) freezes to a per-object identity
token, which silently disables CROSS-Runtime sharing for that runtime but
never produces a false cache hit. Functions freeze to (code object,
frozen defaults, frozen closure cells), so factory-built closures like
`raft_invariant(5, 32)` compare equal across calls — the flagship models
all build their invariants that way.
"""

from __future__ import annotations

import itertools
import types
import weakref
from typing import Any

import numpy as np

# leaves bigger than this hash to a digest instead of carrying raw bytes
# in the key (keys live for the cache entry's lifetime)
_INLINE_BYTES = 1 << 12

_TOKENS = itertools.count()
_TOKEN_ATTR = "_madsim_tpu_sig_token"


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (0 -> 0): the bucketing rule for
    capacity-like knobs whose exact value rides as a dynamic operand."""
    n = int(n)
    return 0 if n <= 0 else 1 << (n - 1).bit_length()


class _Unique:
    """Identity token: hashable, equal only to itself. Freezing a value to
    one of these keeps the cache sound (the same OBJECT still reuses its
    entry) while opting that runtime out of cross-instance sharing."""

    __slots__ = ("_n",)

    def __init__(self):
        self._n = next(_TOKENS)

    def __hash__(self):
        return hash(("_madsim_unique", self._n))

    def __eq__(self, other):
        return self is other

    def __repr__(self):
        return f"<unique #{self._n}>"


_WEAK_TOKENS: "weakref.WeakKeyDictionary[Any, _Unique]" = \
    weakref.WeakKeyDictionary()


def _unique_for(obj: Any):
    """A token stable for the lifetime of `obj`: stashed on the object
    when it allows attributes, else weak-keyed by it. Never keyed by
    bare id() — id reuse after GC could alias a live cache entry; here
    the token (or the weak entry) dies with the object."""
    try:
        tok = getattr(obj, _TOKEN_ATTR, None)
        if tok is None:
            tok = _Unique()
            setattr(obj, _TOKEN_ATTR, tok)
        return tok
    except (AttributeError, TypeError):
        pass
    try:
        tok = _WEAK_TOKENS.get(obj)
        if tok is None:
            tok = _Unique()
            _WEAK_TOKENS[obj] = tok
        return tok
    except TypeError:   # neither attributable nor weakref-able
        return _Unique()


def contains_identity_token(frozen: Any) -> bool:
    """Whether a frozen value carries an identity token somewhere — i.e.
    freezing DEGRADED: the value is cache-sound but opts its Runtime out
    of cross-instance program sharing. `analyze/lint.py` uses this for
    its `sig-degrade` rule; `freeze` itself uses it to emit the
    COMPILE_LOG warning for degraded closure cells."""
    if isinstance(frozen, _Unique):
        return True
    if isinstance(frozen, (tuple, frozenset)):
        return any(contains_identity_token(x) for x in frozen)
    return False


def _note_degrade(owner, cell: str, val: Any) -> None:
    """Route one degraded capture to the compile log (observer record +
    suite-end summary line). Best-effort: observability must never turn
    a valid construction into an error."""
    try:
        from .cache import COMPILE_LOG
        COMPILE_LOG.note_degrade(
            getattr(owner, "__qualname__", repr(owner)), cell,
            detail=type(val).__name__)
    except Exception:  # noqa: BLE001
        pass


def _global_names(code, _depth: int = 0) -> set:
    """Names a code object (and its nested lambdas/comprehensions) may
    resolve from module globals — co_names, walked through co_consts."""
    names = set(code.co_names)
    if _depth < 4:
        for c in code.co_consts:
            if isinstance(c, types.CodeType):
                names |= _global_names(c, _depth + 1)
    return names


def _freeze_array(a) -> tuple:
    arr = np.asarray(a)
    blob = arr.tobytes()
    if len(blob) > _INLINE_BYTES:
        import hashlib
        blob = hashlib.sha256(blob).digest()
    return ("arr", str(arr.dtype), arr.shape, blob)


def freeze(v: Any, _depth: int = 0, _seen: frozenset = frozenset()) -> Any:
    """Hashable, value-based encoding of `v` — see module docstring for
    the soundness contract (unknown -> identity token, never a false
    equality). `_seen` carries the ids on the CURRENT walk path so
    cyclic references (a recursive function's own global binding,
    mutually-referencing module helpers) encode as a stable structural
    marker instead of an identity token — the cycle's shape is already
    captured by the enclosing tuples."""
    if _depth > 24:                      # pathological nesting
        return _unique_for(v)
    if id(v) in _seen:
        return ("cycle", type(v).__name__)
    d = _depth + 1
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        # tag with the type name: 1 == True == 1.0 under Python hashing,
        # but they trace differently
        return (type(v).__name__, v)
    s = _seen | {id(v)}
    if isinstance(v, (tuple, list)):
        return ("seq", type(v).__name__,
                tuple(freeze(x, d, s) for x in v))
    if isinstance(v, dict):
        items = sorted(v.items(), key=lambda kv: repr(kv[0]))
        return ("dict", tuple((freeze(k, d, s), freeze(x, d, s))
                              for k, x in items
                              if not (isinstance(k, str)
                                      and k.startswith("_madsim"))))
    if isinstance(v, (frozenset, set)):
        # frozenset of the frozen elements: order-independent equality
        # without repr() (code-object reprs embed memory addresses)
        return ("set", frozenset(freeze(x, d, s) for x in v))
    if isinstance(v, np.ndarray) or type(v).__module__.startswith("jax"):
        try:
            return _freeze_array(v)
        except Exception:  # noqa: BLE001 - tracer/abstract value etc.
            return _unique_for(v)
    if isinstance(v, np.generic):
        return _freeze_array(v)
    if isinstance(v, types.ModuleType):
        # by-name ONLY for the module actually registered under that
        # name — two distinct module objects sharing a __name__ (exec'd
        # namespaces, test doubles) must not alias. NOTE the contract
        # limit this implies: mutating a REGISTERED module's attributes
        # between Runtime constructions is invisible to the signature,
        # exactly like mutating a Program after construction (DESIGN
        # §10 freezes both at construction time).
        import sys
        if sys.modules.get(v.__name__) is v:
            return ("mod", v.__name__)
        return _unique_for(v)
    if isinstance(v, types.MethodType):
        return ("method", freeze(v.__func__, d, s), freeze(v.__self__, d, s))
    if isinstance(v, types.FunctionType):
        # a cell that freezes to an identity token is the silent-cache-
        # degrade case: name it (qualname + cell) through COMPILE_LOG
        # instead of letting the cache misses stay undiagnosable
        cells_l = []
        for cname, c in zip(v.__code__.co_freevars, v.__closure__ or ()):
            fz = freeze(c.cell_contents, d, s)
            if contains_identity_token(fz):
                _note_degrade(v, cname, c.cell_contents)
            cells_l.append(fz)
        cells = tuple(cells_l)
        # referenced module globals are part of the function's behavior:
        # CPython compares code objects by VALUE, so byte-identical
        # source in two modules yields equal code objects even when the
        # globals they read differ — fold those bindings in like cells
        gnames = sorted(_global_names(v.__code__)
                        & v.__globals__.keys())
        gvals = []
        for n in gnames:
            fz = freeze(v.__globals__[n], d, s)
            if contains_identity_token(fz):
                _note_degrade(v, f"global:{n}", v.__globals__[n])
            gvals.append((n, fz))
        gvals = tuple(gvals)
        return ("fn", v.__code__,
                freeze(v.__defaults__, d, s),
                freeze(v.__kwdefaults__, d, s),  # kw-only defaults bake
                cells, gvals)                    # into the trace too
    if isinstance(v, type):
        return ("cls", v)                  # class object itself (hashable)
    import functools
    if isinstance(v, functools.partial):
        return ("partial", freeze(v.func, d, s), freeze(v.args, d, s),
                freeze(v.keywords, d, s))
    # objects with a plain attribute dict (Programs, Extensions, config
    # dataclasses): type + frozen attributes. This is what makes two
    # `Raft(5, 32, ...)` instances from different factory calls equal.
    dct = getattr(v, "__dict__", None)
    if isinstance(dct, dict):
        return ("obj", type(v), freeze(dct, d, s))
    return _unique_for(v)


def program_signature(prog) -> Any:
    """Value signature of one Program (type + captured parameters)."""
    return freeze(prog)


def runtime_signature(cfg, programs, node_prog, state_spec, invariant,
                      persist, halt_when, extensions) -> Any:
    """The full step-program cache key for one Runtime construction —
    every ingredient `core.step.make_step` bakes into the trace.

    The batch shape is deliberately absent: `jax.jit` re-specializes per
    input aval under one cached callable, so distinct batch widths share
    the Python-level entry and split only at XLA level (which is exactly
    the granularity executables differ at)."""
    node_prog = np.asarray(node_prog, np.int32)
    return (
        "rt-sig-v1",
        cfg.structural_signature(),
        tuple(program_signature(p) for p in programs),
        ("node_prog", node_prog.shape, node_prog.tobytes()),
        freeze(state_spec),
        freeze(invariant),
        freeze(persist),
        freeze(halt_when),
        tuple(freeze(e) for e in extensions),
    )
