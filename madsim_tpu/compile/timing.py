"""AOT stage timers: where does getting-to-execution time actually go?

`bench.py --mode compile_ab` uses these to decompose a runner's cold cost
into trace -> lower -> compile (the jax AOT pipeline) plus first-execute,
instead of reporting one opaque "warmup" number. Falls back gracefully on
jax versions without `.trace` (trace+lower then report as one stage).
"""

from __future__ import annotations

import time
from typing import Any


def timed_stages(jitted, *args, **kwargs) -> dict[str, Any]:
    """Run the AOT pipeline of a jitted callable on `args`, timing each
    stage. Returns {trace_s, lower_s, compile_s, total_s, compiled}
    (trace_s is None when this jax only exposes the fused lower()).

    NOTE: jax's AOT objects do not seed the jitted function's own
    dispatch cache — use the returned `compiled` for execution, or
    accept one more (cached-by-XLA-persistent-layer) compile on the
    first ordinary call."""
    t0 = time.perf_counter()
    trace_s = None
    if hasattr(jitted, "trace"):
        traced = jitted.trace(*args, **kwargs)
        t1 = time.perf_counter()
        trace_s = t1 - t0
        lowered = traced.lower()
    else:
        lowered = jitted.lower(*args, **kwargs)
    t2 = time.perf_counter()
    compiled = lowered.compile()
    t3 = time.perf_counter()
    return dict(
        trace_s=trace_s,
        lower_s=(t2 - t0) if trace_s is None else (t2 - t0 - trace_s),
        compile_s=t3 - t2,
        total_s=t3 - t0,
        compiled=compiled,
    )
