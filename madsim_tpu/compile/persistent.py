"""The cross-process compile tier: JAX's persistent compilation cache.

`PROGRAM_CACHE` amortizes compiles within a process; this wires the
on-disk tier so COLD processes (a fresh CI lane, a new harness run) reuse
warm XLA artifacts. JAX keys persistent entries by the serialized HLO +
compile options, so the structural/dynamic split upstream matters here
too: with dynamic knobs as traced operands, a sweep over time limits or
fault models maps onto ONE on-disk artifact.

Contract (DESIGN §10): the cache stores post-optimization executables
keyed by program content — it can never change results, only skip the
XLA compile stage (traces still run, so `COMPILE_LOG.note_trace` counts
are unaffected). Safe to share between lanes of one workspace; do not
share a directory across incompatible jaxlib versions (jax already keys
the version in, stale entries are simply missed).
"""

from __future__ import annotations

import os


def enable_persistent_cache(cache_dir: str | None = None,
                            min_compile_secs: float | None = None
                            ) -> str | None:
    """Point jax at an on-disk compilation cache; idempotent.

    Resolution order: explicit `cache_dir` argument, then the
    JAX_COMPILATION_CACHE_DIR env var (what `scripts/ci.sh` exports),
    else no-op (returns None) — callers sprinkle this at harness entry
    points without forcing a cache on ad-hoc runs. `min_compile_secs`
    skips persisting trivial programs whose disk round-trip would cost
    more than the compile: an EXPLICIT value always applies; the 1.0s
    default applies only when the dir is newly configured, so repeated
    default-argument calls (harness/simtest makes one per run_seeds)
    never clobber a threshold the caller chose."""
    d = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not d:
        return None
    import jax
    d = os.path.abspath(d)
    os.makedirs(d, exist_ok=True)
    newly = jax.config.jax_compilation_cache_dir != d
    if newly:
        jax.config.update("jax_compilation_cache_dir", d)
    if min_compile_secs is not None or newly:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(1.0 if min_compile_secs is None else min_compile_secs))
    return d
