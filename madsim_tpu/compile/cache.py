"""The process-level step-program cache + the compile observability log.

`PROGRAM_CACHE` maps (backend, structural signature, runner kind) to the
jitted runner callable, so every Runtime whose construction freezes to
the same signature (compile/signature.py) shares ONE Python-level jit
entry — and therefore one trace and one XLA executable per (batch shape,
static chunk length). The chunked/fused runners, `_inject`, the
compacting path, `find_divergence`, and the batched fingerprint jit all
resolve through here; `explore()` rounds, `harness/simtest` tests, and
whole test files stop paying per-Runtime recompiles.

`COMPILE_LOG` is the observability half: runner bodies call
`COMPILE_LOG.note_trace(label, ...)` as their first traced-Python side
effect, so every retrace (= every fresh executable, modulo persistent
compile-cache hits that skip only the XLA stage) is counted and labeled.
When available, `jax.monitoring` duration listeners add real
trace/lower/compile stage timings. Records fan out to any attached
`obs.metrics.SweepObserver` via its `on_compile` hook, and
`COMPILE_LOG.summary()` is what `scripts/ci.sh` prints at suite end.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable


class CompileLog:
    """Process-global compile counter / stage-timing log (thread-safe)."""

    MAX_EVENTS = 1024   # bounded: a long suite must not accumulate RAM

    def __init__(self):
        self._lock = threading.Lock()
        self.traces = collections.Counter()      # label -> retrace count
        self.events = collections.deque(maxlen=self.MAX_EVENTS)
        self.durations = collections.Counter()   # stage -> seconds
        self.degrades: list[dict] = []           # signature degradations
        self._degraded_keys: set = set()
        self._observers: list[Any] = []
        self._t0 = time.time()

    # -- the counter (called from inside traced runner bodies) -----------
    def note_trace(self, label: str, **info) -> None:
        rec = dict(kind="compile", label=label, t=round(
            time.time() - self._t0, 3), **info)
        with self._lock:
            self.traces[label] += 1
            self.events.append(rec)
            observers = list(self._observers)
        for o in observers:
            o.on_compile(rec)

    # -- signature degradations (compile/signature.py) --------------------
    def note_degrade(self, owner: str, cell: str, detail: str = "") -> None:
        """A closure capture froze to an identity token: `owner`'s cell
        `cell` opted its Runtime out of cross-instance program sharing
        (compile/signature.py module docstring). Before r12 this was a
        SILENT cache degrade — cache misses were undiagnosable; now it
        is an observer record (kind="compile",
        label="signature_degrade") and a line in `summary()` — the
        suite-end report scripts/ci.sh prints. De-duplicated per
        (owner, cell): freeze() runs on every construction."""
        rec = dict(kind="compile", label="signature_degrade",
                   owner=owner, cell=cell, detail=detail,
                   t=round(time.time() - self._t0, 3))
        with self._lock:
            if (owner, cell) in self._degraded_keys:
                return
            self._degraded_keys.add((owner, cell))
            self.degrades.append(rec)
            self.events.append(rec)
            observers = list(self._observers)
        for o in observers:
            o.on_compile(rec)

    # -- stage durations (fed by jax.monitoring when available) ----------
    def note_duration(self, stage: str, secs: float) -> None:
        with self._lock:
            self.durations[stage] += secs

    # -- observers (obs.metrics.SweepObserver.on_compile) ----------------
    def attach(self, observer) -> None:
        with self._lock:
            if observer not in self._observers:
                self._observers.append(observer)

    def detach(self, observer) -> None:
        with self._lock:
            if observer in self._observers:
                self._observers.remove(observer)

    # -- reporting --------------------------------------------------------
    def recent(self, n: int = 20) -> list[dict]:
        """The last `n` compile records (what retraced, when) — the
        drill-down behind snapshot()'s counters; bench.py --mode
        compile_ab embeds it in the artifact."""
        with self._lock:
            return list(self.events)[-n:]

    def snapshot(self) -> dict:
        with self._lock:
            return dict(
                traces=dict(self.traces),
                traces_total=sum(self.traces.values()),
                stage_secs={k: round(v, 3)
                            for k, v in self.durations.items()},
                degrades=list(self.degrades),
            )

    def summary(self) -> str:
        s = self.snapshot()
        parts = [f"{n}x {label}" for label, n in
                 sorted(s["traces"].items(), key=lambda kv: -kv[1])]
        stages = " ".join(f"{k}={v:.1f}s"
                          for k, v in sorted(s["stage_secs"].items()))
        deg = s["degrades"]
        deg_s = ""
        if deg:
            who = ", ".join(f"{d['owner']}.{d['cell']}" for d in deg[:6])
            deg_s = (f" | {len(deg)} signature degrade(s) — no cross-"
                     f"Runtime sharing for: {who}"
                     + (" …" if len(deg) > 6 else ""))
        return (f"compile log: {s['traces_total']} trace(s)"
                + (f" [{', '.join(parts)}]" if parts else "")
                + (f" | {stages}" if stages else "")
                + deg_s
                + f" | {PROGRAM_CACHE.describe()}")


COMPILE_LOG = CompileLog()


def _install_monitoring() -> bool:
    """Best-effort: route jax's own compile-phase duration events into
    COMPILE_LOG (jax.monitoring exists on this jaxlib; gate anyway — the
    listener API is not a stability promise)."""
    try:
        from jax import monitoring

        def _listen(event: str, secs: float, **kw):
            # keep only the compilation pipeline events; key by tail name
            if "compil" in event or "trace" in event or "lower" in event:
                COMPILE_LOG.note_duration(event.rsplit("/", 1)[-1], secs)

        monitoring.register_event_duration_secs_listener(_listen)
        return True
    except Exception:  # noqa: BLE001 - observability must never break runs
        return False


_MONITORING = _install_monitoring()


class ProgramCache:
    """LRU cache of jitted runner callables keyed on (backend, runtime
    structural signature, runner kind).

    Eviction only drops the SHARED entry — Runtimes that already resolved
    a runner keep their reference (functools.cached_property), so an
    evicted entry costs at most one recompile for a future construction,
    never a dangling executable. Size via MADSIM_PROGRAM_CACHE_SIZE
    (entries hold compiled executables alive; the default bounds a long
    test session's RAM)."""

    def __init__(self, maxsize: int | None = None):
        if maxsize is None:
            maxsize = int(os.environ.get("MADSIM_PROGRAM_CACHE_SIZE", "128"))
        self.maxsize = max(1, maxsize)
        self._entries: collections.OrderedDict = collections.OrderedDict()
        self._lock = threading.RLock()
        self.hits = 0
        self.misses = 0
        self.unhashable = 0
        self.evictions = 0

    def get(self, key: Any, build: Callable[[], Any]) -> Any:
        """The cached value for `key`, building (and caching) on miss.
        An unhashable key — a signature ingredient froze to something
        mutable — degrades to per-call building, never to a wrong hit."""
        import jax
        full = (jax.default_backend(), key)
        try:
            hash(full)
        except TypeError:
            with self._lock:
                self.unhashable += 1
            return build()
        with self._lock:
            if full in self._entries:
                self.hits += 1
                self._entries.move_to_end(full)
                return self._entries[full]
        val = build()   # outside the lock: build may trigger work
        with self._lock:
            if full in self._entries:      # lost a race: keep the winner
                self.hits += 1
                return self._entries[full]
            self.misses += 1
            self._entries[full] = val
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)
                self.evictions += 1
        return val

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return dict(entries=len(self._entries), hits=self.hits,
                        misses=self.misses, unhashable=self.unhashable,
                        evictions=self.evictions, maxsize=self.maxsize)

    def describe(self) -> str:
        s = self.stats()
        return (f"program cache: {s['entries']} entries, {s['hits']} hits, "
                f"{s['misses']} misses"
                + (f", {s['unhashable']} unhashable" if s['unhashable']
                   else "")
                + (f", {s['evictions']} evicted" if s['evictions'] else ""))


PROGRAM_CACHE = ProgramCache()
