"""Compilation layer: compile each structural shape once, not once per Runtime.

Execution is fast (the fused runner, DESIGN §5); *getting to execution*
was not: every `Runtime` construction used to own private `jax.jit`
closures, so jit's function-identity cache could never share a step
program across Runtime instances — `explore()` studies, `harness/simtest`
tests, and sweep harnesses all paid a fresh trace+compile for programs
that were structurally identical to ones already built (the Podracer
lesson, PAPERS.md: an accelerator-resident loop only wins when program
construction is amortized; veScale makes the same point about keeping the
compiled-program cache hot across logically-distinct runs).

Three tiers, from hot to cold:

  * `signature.py`  — what "structurally identical" means: the
    shape/lowering-affecting slice of `SimConfig`
    (`SimConfig.structural_signature()`) plus a deep freeze of programs,
    state spec, invariant/halt_when closures, persist mask, and
    extensions. Dynamic knobs (time limit, loss, latency, jitter bound,
    `trace_cap` within its power-of-two bucket) are traced operands in
    `SimState` and never key a compile.
  * `cache.py`      — `PROGRAM_CACHE`, the process-level cache of jitted
    runners keyed on (structural signature, runner kind, backend), and
    `COMPILE_LOG`, the compile counter / stage-timing log that observers
    (`obs.metrics.SweepObserver.on_compile`) and CI summaries read.
  * `persistent.py` — the cross-process tier: wires JAX's persistent
    compilation cache (`jax_compilation_cache_dir`) so cold CI processes
    reuse warm on-disk executables.

`timing.py` holds the AOT trace/lower/compile stage timers used by
`bench.py --mode compile_ab`.
"""

from .cache import COMPILE_LOG, PROGRAM_CACHE, ProgramCache
from .persistent import enable_persistent_cache
from .signature import (freeze, next_pow2, program_signature,
                        runtime_signature)

__all__ = [
    "COMPILE_LOG", "PROGRAM_CACHE", "ProgramCache",
    "enable_persistent_cache",
    "freeze", "next_pow2", "program_signature", "runtime_signature",
]
