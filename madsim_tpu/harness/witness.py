"""Success witnesses: declaring WHAT a green run's outcome was, so the
lineage plane can explain WHY it happened.

The crash oracles (`recovery_invariant`, `slo_invariant`, model
invariants) are traced callables that mark a lane RED and implicate the
dispatch that did it — the causal plane then walks backward from that
dispatch for free, because the crash check runs inside the step it
indicts. A GREEN lane has no such anchor: nothing in the state says
which dispatch *was* the success. `success_witness` is the host-side
mirror of the oracle pattern: the model declares the shape of its
success event (kind / tag / node), and the witness locates the LAST
matching dispatch in a lane's flight-recorder ring — the record
lineage-driven fault injection (search/ldfi.py, DESIGN §23) walks
backward from to extract the support of success.

Host-side on purpose: witnesses run on `ring_records()` dicts after the
sweep, never inside the jitted step — declaring a witness changes no
compiled program and pierces no replay-domain contract (unlike
installing a recovery oracle, which makes the series plane observable).

Default witness (kinds=()): the lane's final dispatch. For a lane that
ran to quiescence or HALT that is exactly "the outcome", and it keeps
`extract_support` usable on models that never declare anything.
"""

from __future__ import annotations

import numpy as np


def success_witness(kinds=(), *, tags=None, node=None):
    """Build a witness finder for `obs.support.extract_support`.

    Args:
      kinds: event kinds (EV_MSG / EV_TIMER / EV_SUPER) a success record
        may have; empty = any kind.
      tags: message/timer tags that mark success (e.g. the commit-ack
        tag); None = any tag.
      node: the node that must have dispatched it; None = any node.

    Returns `find(recs) -> ring index | None`: the LAST record of a
    `ring_records()` dict matching every given constraint, or None when
    the lane never dispatched a matching event (the run was not a
    witnessed success — callers skip its support).
    """
    kinds = tuple(int(k) for k in kinds)
    tagset = None if tags is None else {int(t) for t in tags}
    want_node = None if node is None else int(node)

    def find(recs: dict):
        n = len(np.asarray(recs["step"]))
        for i in range(n - 1, -1, -1):
            if kinds and int(recs["kind"][i]) not in kinds:
                continue
            if tagset is not None and int(recs["tag"][i]) not in tagset:
                continue
            if want_node is not None and int(recs["node"][i]) != want_node:
                continue
            return i
        return None

    return find
