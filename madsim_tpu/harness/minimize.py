"""Chaos-script minimization: shrink a failing scenario to a minimal repro.

A failing seed from a fuzz sweep comes with the whole chaos script that
produced it — rolling kills, partitions, latency flips — most of which is
noise. This ddmin-style pass deletes scenario rows while the SAME seed
still crashes with the SAME code, converging to a 1-minimal script: every
remaining row is load-bearing (dropping any one of them makes the crash
vanish). The reference has nothing like this; its repro is "same seed,
same code, same config hash" with the full test body
(madsim-macros/src/lib.rs:188-190).

Batched by default (r9): a deletion candidate is initial-state data — a
freed event-table slot — so ALL candidates of a ddmin round run as ONE
batched dispatch (lane i = script minus row i) instead of one single-lane
run each. The mask-domain evaluation keeps surviving rows at their
original slots; since slot layout can shift tie-breaks, the final minimal
script is re-verified through `set_scenario` (the layout the returned
Scenario actually implies), with an automatic fall-back to the serial
row-by-row pass in the rare case the verification misses.

`minimize_knobs` is the same engine over a fuzzer knob vector
(search/mutate.py): items are the enabled scenario rows AND dup slots, the
scalar knobs (loss/latency/jitter/prio_nudge) are held fixed, and
candidate evaluation + final repro live in one domain, so no verification
gap exists there at all.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..runtime.scenario import Scenario


def _pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length() if n > 1 else 1


def _crash_code(rt, seed: int, max_steps: int, chunk: int):
    """-> crash code of the single-lane run, or None if it didn't crash."""
    state, _ = rt.run(rt.init_single(seed), max_steps, chunk,
                      collect_events=False)
    if not bool(np.asarray(state.crashed).any()):
        return None
    return int(np.asarray(state.crash_code).reshape(-1)[0])


def ddmin_mask(n_items: int, pinned: np.ndarray, test_batch) -> tuple:
    """Greedy batched ddmin over a keep-mask.

    `test_batch(masks: bool[K, n_items]) -> ok: bool[K]` evaluates K
    candidate masks in one batched dispatch (ok = the crash still
    reproduces). Each round: one dispatch tests every single-item
    deletion from the current mask; when several items are individually
    droppable, a second dispatch tests the NESTED PREFIX UNIONS of that
    set and accepts the largest prefix that still reproduces (prefix 1
    is a re-run of a known-good single deletion, so progress is
    guaranteed every round). Dispatch count is O(rounds) — typically a
    handful — not O(items x passes) like the serial row-by-row loop.
    Returns (mask, dispatches)."""
    mask = np.ones(n_items, bool)
    dispatches = 0
    while True:
        cand = np.nonzero(mask & ~pinned)[0]
        if cand.size == 0:
            break
        masks = np.repeat(mask[None], cand.size, axis=0)
        masks[np.arange(cand.size), cand] = False
        ok = np.asarray(test_batch(masks))
        dispatches += 1
        drop = cand[ok[:cand.size]]
        if drop.size == 0:
            break                                   # 1-minimal
        if drop.size == 1:
            mask[drop[0]] = False
            continue
        prefixes = np.repeat(mask[None], drop.size, axis=0)
        for j in range(drop.size):
            prefixes[j:, drop[j]] = False           # row j: drop[:j+1] off
        okp = np.asarray(test_batch(prefixes))[:drop.size]
        dispatches += 1
        best = int(np.max(np.nonzero(okp)[0], initial=0)) + 1
        mask[drop[:best]] = False
    return mask, dispatches


def _scenario_test_batch(rt, seed: int, max_steps: int, chunk: int,
                         code: int, W: int):
    """Mask-domain candidate evaluator: lane j runs `seed` with the
    scenario slots of mask j's False rows freed (EV_FREE / T_INF) —
    surviving rows KEEP their template slots. One `init_batch` + one
    batched run per call."""
    from ..core import types as T
    n_init = rt.cfg.n_nodes
    R = len(rt.scenario.rows)
    C = rt.cfg.event_capacity

    def test(masks: np.ndarray) -> np.ndarray:
        K = masks.shape[0]
        keep = np.ones((W, C), bool)
        keep[:K, n_init:n_init + R] = masks
        state = rt.init_batch(np.full(W, seed, np.uint32))
        kf = jnp.asarray(keep)
        state = state.replace(
            t_kind=jnp.where(kf, state.t_kind,
                             jnp.asarray(T.EV_FREE, state.t_kind.dtype)),
            t_deadline=jnp.where(kf, state.t_deadline,
                                 jnp.asarray(T.T_INF, jnp.int32)))
        state, _ = rt.run(state, max_steps, chunk, collect_events=False)
        crashed = np.asarray(state.crashed)
        codes = np.asarray(state.crash_code)
        return (crashed & (codes == code))[:K]

    return test


def minimize_scenario(rt, seed: int, max_steps: int, chunk: int = 512,
                      batched: bool = True):
    """Shrink `rt.scenario` to a 1-minimal script that still crashes
    `seed` with the original crash code.

    Returns (minimal: Scenario, info: dict) and leaves `rt` restored to
    its original scenario. info carries kept/dropped row counts, `runs`
    (device dispatches executed — for the batched path each one evaluates
    a whole candidate round), the crash code, and `mode`
    ("batched" / "serial" / "batched+serial_fallback").

    `batched=False` forces the pre-r9 serial loop (one single-lane run per
    candidate row) — kept as the reference the batched path's test
    measures its dispatch-count drop against."""
    from ..core import types as T

    original = rt.scenario
    code = _crash_code(rt, seed, max_steps, chunk)
    if code is None:
        raise ValueError(
            f"seed {seed} does not crash under the full scenario — "
            f"nothing to minimize")
    runs = 1

    if batched:
        rows = list(original.rows)
        R = len(rows)
        # OP_INIT is pinned alongside OP_HALT: in the mask domain "off"
        # means the boot never fires (node absent forever), while deleting
        # the row from a Scenario means the node boots at t=0 — mask
        # acceptance would diverge from set_scenario semantics (the same
        # template-bookkeeping reason search/mutate.py pins INIT rows)
        pinned = np.asarray([r.op in (T.OP_HALT, T.OP_INIT) for r in rows])
        # one fixed lane width for the whole pass (padded, power of two):
        # every round reuses a single compiled batch shape
        W = _pow2(max(R, 1))
        test = _scenario_test_batch(rt, seed, max_steps, chunk, code, W)
        mask, dispatches = ddmin_mask(R, pinned, test)
        runs += dispatches
        minimal = Scenario()
        minimal.rows = [r for i, r in enumerate(rows) if mask[i]]
        # the returned Scenario implies a REPACKED slot layout; verify the
        # crash survives it (tie-breaks can shift with slot positions)
        try:
            rt.set_scenario(minimal)
            runs += 1
            verified = _crash_code(rt, seed, max_steps, chunk) == code
        finally:
            rt.set_scenario(original)
        if verified:
            return minimal, dict(
                kept=len(minimal.rows),
                dropped=len(rows) - len(minimal.rows),
                runs=runs, crash_code=code, mode="batched")
        # rare: mask-domain acceptance doesn't survive repacking — redo
        # serially (which evaluates candidates in the repacked layout)
        minimal, info = _minimize_serial(rt, seed, max_steps, chunk, code)
        info["runs"] += runs
        info["mode"] = "batched+serial_fallback"
        return minimal, info

    minimal, info = _minimize_serial(rt, seed, max_steps, chunk, code)
    info["runs"] += runs
    return minimal, info


def _minimize_serial(rt, seed: int, max_steps: int, chunk: int, code: int):
    """The pre-r9 loop: greedy 1-minimal pass to fixpoint, one single-lane
    run per candidate deletion, candidates evaluated through
    `set_scenario` (so acceptance and the returned script share one slot
    layout). HALT rows are pinned: set_scenario would re-add one, so
    "deleting" a user HALT would silently test a longer horizon."""
    from ..core import types as T

    original = rt.scenario
    rows = list(original.rows)
    runs = 0
    try:
        changed = True
        while changed:
            changed = False
            i = 0
            while i < len(rows):
                if rows[i].op == T.OP_HALT:
                    i += 1
                    continue
                cand = Scenario()
                cand.rows = rows[:i] + rows[i + 1:]
                rt.set_scenario(cand)
                runs += 1
                if _crash_code(rt, seed, max_steps, chunk) == code:
                    rows = cand.rows         # row i was noise
                    changed = True
                else:
                    i += 1                   # row i is load-bearing
    finally:
        rt.set_scenario(original)
    minimal = Scenario()
    minimal.rows = rows
    return minimal, dict(
        kept=len(rows), dropped=len(original.rows) - len(rows),
        runs=runs, crash_code=code, mode="serial")


# ---------------------------------------------------------------------------
# knob-domain shrinking (the fuzzer hand-off, search/fuzz.py)
# ---------------------------------------------------------------------------


def minimize_knobs(rt, plan, knobs: dict, seed: int, max_steps: int,
                   chunk: int = 512):
    """Shrink a fuzzer knob vector's FAULT ROWS to a 1-minimal set that
    still crashes `seed` with the same code: items are the enabled
    droppable scenario rows plus enabled dup slots; the scalar knobs
    (loss/latency/jitter/prio_nudge) are held fixed — they are part of the
    repro, not candidates for deletion. Candidate evaluation, the
    returned knob vector, and its replay all live in the SAME apply-knobs
    domain, so there is no slot-layout verification gap.

    Returns (minimal_knobs, info) with info carrying kept/dropped counts,
    `runs` (batched dispatches), the crash code, and a human-readable
    `script` rendering of the minimal fault schedule."""
    kn0 = {k: np.array(np.asarray(v)) for k, v in knobs.items()}
    R, D = plan.R, plan.D

    def run_masks(masks: np.ndarray):
        """masks bool[K, R+D] -> ok bool[K]; one batched dispatch."""
        K = masks.shape[0]
        W = _pow2(max(R + D, 1))
        variants = []
        for j in range(W):
            kn = {k: v.copy() for k, v in kn0.items()}
            m = masks[min(j, K - 1)]
            kn["row_on"] = kn0["row_on"] & m[:R]
            if D:
                kn["dup_on"] = kn0["dup_on"] & m[R:]
            variants.append(kn)
        batch = plan.stack(variants)
        state = plan.apply(rt.init_batch(np.full(W, seed, np.uint32)),
                           batch)
        state, _ = rt.run(state, max_steps, chunk, collect_events=False)
        return (np.asarray(state.crashed)
                & (np.asarray(state.crash_code) == code))[:K]

    # target code from the UNSHRUNK knobs (one width-W dispatch keeps a
    # single compiled batch shape for the whole pass)
    state = plan.apply(rt.init_batch(np.full(_pow2(max(R + D, 1)), seed,
                                             np.uint32)),
                       plan.stack([kn0] * _pow2(max(R + D, 1))))
    state, _ = rt.run(state, max_steps, chunk, collect_events=False)
    if not bool(np.asarray(state.crashed)[0]):
        raise ValueError(
            f"seed {seed} does not crash under the given knobs — "
            f"nothing to minimize")
    code = int(np.asarray(state.crash_code)[0])
    runs = 1

    on0 = np.concatenate([kn0["row_on"],
                          kn0["dup_on"] if D else np.zeros(0, bool)])
    pinned = np.concatenate([~plan.drop_ok, np.zeros(D, bool)]) | ~on0
    mask, dispatches = ddmin_mask(R + D, pinned, run_masks)
    runs += dispatches
    mask &= on0
    minimal = {k: v.copy() for k, v in kn0.items()}
    minimal["row_on"] = kn0["row_on"] & mask[:R]
    if D:
        minimal["dup_on"] = kn0["dup_on"] & mask[R:]
    kept = int(mask[:R].sum() + (mask[R:].sum() if D else 0))
    return minimal, dict(
        kept=kept, dropped=int(on0.sum()) - kept, runs=runs,
        crash_code=code,
        script=plan.to_scenario(minimal).describe())
