"""Chaos-script minimization: shrink a failing scenario to a minimal repro.

A failing seed from a fuzz sweep comes with the whole chaos script that
produced it — rolling kills, partitions, latency flips — most of which is
noise. This ddmin-style pass deletes scenario rows while the SAME seed
still crashes with the SAME code, converging to a 1-minimal script: every
remaining row is load-bearing (dropping any one of them makes the crash
vanish). The reference has nothing like this; its repro is "same seed,
same code, same config hash" with the full test body
(madsim-macros/src/lib.rs:188-190).

Cheap by construction: a scenario is initial-state data, not program
(`Runtime.set_scenario` rebuilds the state template without retracing),
so each candidate costs one single-lane run of the already-compiled step.
"""

from __future__ import annotations

import numpy as np

from ..runtime.scenario import Scenario


def _crash_code(rt, seed: int, max_steps: int, chunk: int):
    """-> crash code of the single-lane run, or None if it didn't crash."""
    state, _ = rt.run(rt.init_single(seed), max_steps, chunk,
                      collect_events=False)
    if not bool(np.asarray(state.crashed).any()):
        return None
    return int(np.asarray(state.crash_code).reshape(-1)[0])


def minimize_scenario(rt, seed: int, max_steps: int, chunk: int = 512):
    """Shrink `rt.scenario` to a 1-minimal script that still crashes
    `seed` with the original crash code.

    Returns (minimal: Scenario, info: dict) and leaves `rt` restored to
    its original scenario. info carries kept/dropped row counts, the
    number of candidate runs executed, and the crash code.
    """
    from ..core import types as T

    original = rt.scenario
    rows = list(original.rows)
    code = _crash_code(rt, seed, max_steps, chunk)
    if code is None:
        raise ValueError(
            f"seed {seed} does not crash under the full scenario — "
            f"nothing to minimize")
    runs = 1
    try:
        # greedy 1-minimal pass to fixpoint: try deleting each row; keep
        # the deletion if the same crash still reproduces. Chunked first
        # passes (halves, quarters) would cut runs for big scripts, but
        # scripts are tens of rows and each run is milliseconds-to-
        # seconds on an already-compiled program. HALT rows are pinned:
        # set_scenario would re-add one at cfg.time_limit, so "deleting"
        # a user HALT would silently test a longer virtual-time horizon
        # than the script being minimized.
        changed = True
        while changed:
            changed = False
            i = 0
            while i < len(rows):
                if rows[i].op == T.OP_HALT:
                    i += 1
                    continue
                cand = Scenario()
                cand.rows = rows[:i] + rows[i + 1:]
                rt.set_scenario(cand)
                runs += 1
                if _crash_code(rt, seed, max_steps, chunk) == code:
                    rows = cand.rows         # row i was noise
                    changed = True
                else:
                    i += 1                   # row i is load-bearing
    finally:
        rt.set_scenario(original)
    minimal = Scenario()
    minimal.rows = rows
    return minimal, dict(
        kept=len(rows), dropped=len(original.rows) - len(rows),
        runs=runs, crash_code=code,
    )
