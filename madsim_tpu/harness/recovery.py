"""Recovery invariants: failing to HEAL as a first-class crash code.

The windowed telemetry plane (cfg.series_windows, DESIGN §22) records
WHEN things happened in sim time; this module ENFORCES a shape on that
timeline: `recovery_invariant(p99_le=..., within=R)` builds a traced
callable over the per-window series columns usable as
`Runtime(invariant=)`, crashing a lane that keeps missing its
steady-state envelope after the last disruptive fault window has had R
windows to drain. An aggregate SLO can't express this — a run that
degrades under partition and RECOVERS looks identical, in whole-run
percentiles, to one that degrades and stays degraded. The recovery
oracle separates them: transient pain inside the grace windows is
tolerated; pain that persists past it is a bug with its own code
(`CRASH_RECOVERY`), which the whole search/triage stack inherits for
free — the fuzzer harvests (seed, knobs) repros, `harness.minimize`
ddmin-shrinks the fault script, `service.CrashBuckets` dedups by
causal fingerprint.

The deliberate contract pierce (the `slo_invariant` precedent):
installing a recovery invariant makes the series plane OBSERVABLE —
crash_code now depends on sr_* for THAT runtime, so the plane joins
its replay domain. Every runtime that doesn't install one keeps the
plane transparent; tests hold both directions. Keep every lane's
series recording ON (the init_batch default): a `series_lanes`-masked
lane never accumulates windows, so its oracle can never fire.

Windowing semantics the oracle leans on (core/step.py):
  - a dispatch at post-advance `now` lands in window
    min(now // max(window_len, 1), W-1);
  - a window w is JUDGED only once complete ((w+1)·window_len <= now) —
    a half-filled window's p99 over three samples is noise, not verdict;
  - fault markers (sr_fault) are set ON DISPATCH of the disrupting
    operation; only `SRF_DISRUPT` bits (kill/partition/net/gray/conn)
    start the recovery clock — boots and heals are the cure, not the
    disease.

Determinism: per-window p99 is the all-integer bucket-CDF lower bound
(the `slo_invariant` rule, one bucket→edge encoding via
`bucket_lower_edge`), window completeness is integer arithmetic on
`now`, and the fault word is an exact bitmask — the check is a pure
function of the lane's dispatch history and fires on the SAME dispatch
in every replay.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core import types as T
from ..parallel.stats import bucket_lower_edge


def recovery_invariant(p99_le: int | None = None,
                       qhw_le: int | None = None, *,
                       within: int = 2, min_count: int = 1,
                       code: int = T.CRASH_RECOVERY):
    """Build a `Runtime(invariant=)` callable that crashes a lane which
    fails to return to its steady-state envelope after faults stop.

    Args:
      p99_le: per-window end-to-end p99 estimate must be back at or
        under this many ticks in every judged window (needs
        cfg.latency_hist > 0 and complete_kinds — the sr_lat columns).
      qhw_le: per-window queue high-water must be back at or under
        this occupancy in every judged window (no latency plane
        needed). Give either threshold or both.
      within: grace windows after the LAST disruptive fault window;
        judging starts at window last_fault + within (R in DESIGN
        §22). A fault too close to the end of the W-window timeline
        leaves nothing to judge — size series_windows so the tail of
        the run keeps at least `within` + 1 windows past the last
        planned fault.
      min_count: a window's p99 is judged only once it folded at least
        this many completions (per lane, per window) — an empty or
        near-empty recovery window proves silence, not health; the
        qhw_le check has no such guard (an empty window's high-water
        is legitimately 0).
      code: the crash code reported (default CRASH_RECOVERY).

    A lane with NO disruptive fault window never fires — the oracle
    judges recovery, not steady-state (install `slo_invariant` for
    that). Windows that never completed (run ended mid-window, or
    overflow-clamped tail traffic) are never judged. The p99 estimate
    is the bucket-CDF LOWER bound: it can only under-read, so a firing
    oracle means the true bucketed quantile genuinely exceeds the
    threshold.

    Requires cfg.series_windows > 0 (raises at trace time otherwise);
    the p99_le form additionally requires the latency plane.
    """
    if p99_le is None and qhw_le is None:
        raise ValueError("recovery_invariant needs p99_le= or qhw_le= "
                         "(or both)")
    if int(within) < 1:
        raise ValueError("within must be >= 1 window of grace")
    within_i = int(within)
    min_count_i = int(min_count)

    def check(state):
        sf = state.sr_fault
        W = sf.shape[-1]
        if W == 0:
            raise ValueError(
                "recovery_invariant needs the windowed telemetry plane "
                "compiled in — set SimConfig(series_windows=...) > 0")
        if p99_le is not None and (state.sr_lat.shape[-2] == 0
                                   or state.sr_lat.shape[-1] == 0):
            raise ValueError(
                "recovery_invariant(p99_le=) needs the latency plane — "
                "set SimConfig(latency_hist=...) > 0 and declare "
                "complete_kinds (use qhw_le= for a queue-only oracle)")
        wl = jnp.maximum(state.window_len, 1)
        widx = jnp.arange(W)
        complete = (widx + 1) * wl <= state.now
        fault_w = (sf & T.SRF_DISRUPT) != 0
        has_fault = fault_w.any()
        # last disruptive window: index of the final True (argmax of
        # the reversed mask); garbage when has_fault is False, but the
        # verdict is gated on has_fault so it never leaks
        last = (W - 1) - jnp.argmax(fault_w[::-1]).astype(jnp.int32)
        judged = complete & (widx >= last + within_i)
        bad_w = jnp.zeros((W,), bool)
        if qhw_le is not None:
            bad_w = bad_w | (state.sr_qhw > int(qhw_le))
        if p99_le is not None:
            counts = state.sr_lat.astype(jnp.int32)       # [W, LB]
            total = counts.sum(-1)                        # [W]
            cdf = jnp.cumsum(counts, axis=-1)
            # ceil(total*99/100) all-integer, >= 1 (slo.py rule)
            need = jnp.maximum((total * 99 + 99) // 100, 1)[:, None]
            b = jnp.argmax(cdf >= need, axis=-1).astype(jnp.int32)
            edge = jnp.where(total > 0, bucket_lower_edge(b), 0)
            bad_w = bad_w | ((total >= min_count_i) & (edge > int(p99_le)))
        bad = state.sr_on & has_fault & (judged & bad_w).any()
        return bad, jnp.asarray(code, jnp.int32)

    return check
