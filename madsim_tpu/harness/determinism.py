"""Nondeterminism detection and localization.

The reference's rand-log checker hashes every RNG draw and panics at the
first divergent draw on replay, localizing nondeterminism in virtual time
(rand.rs:72-96, runtime/mod.rs:144-187, MADSIM_TEST_CHECK_DETERMINISM).
Because our whole cluster is a tensor state, the analog is stronger and
simpler: run two replicas of the same seed in lockstep, fingerprint the
full state, and bisect to the first divergent STEP — then show the event
that was dispatched there.
"""

from __future__ import annotations

import jax
import numpy as np

from ..utils.hashing import batch_fingerprints as vfp


def find_divergence(rt, seed: int, max_steps: int, probe: int = 64):
    """Run seed twice in lockstep; return None if identical, else a dict
    {step, event} locating the first step whose post-state fingerprints
    differ (the take-rand-log/check panic analog, with the event attached).

    Shares compiled programs twice over: the chunk runner comes from the
    Runtime (which resolves through `compile.PROGRAM_CACHE`), and the
    fingerprint jit is the process-level one in utils/hashing — a
    divergence hunt no longer pays its own compiles.
    """
    runner = rt._run_chunk[True]

    def keep(s):
        # runner donates its input buffers (donate_argnums=0); snapshot
        # any state we may need to re-run from
        return jax.tree.map(lambda a: a.copy(), s)

    s1 = rt.init_single(seed)
    s2 = rt.init_single(seed)
    step = 0
    while step < max_steps:
        c1, c2 = keep(s1), keep(s2)    # window-start snapshots
        n1, e1 = runner(s1, probe)
        n2, e2 = runner(s2, probe)
        if np.asarray(vfp(n1))[0] != np.asarray(vfp(n2))[0]:
            # true binary search inside the divergent window: invariant is
            # (a1, a2) identical after `lo` window steps, divergence within
            # the next hi-lo. Halves are powers of two (use a power-of-two
            # probe), so at most log2(probe) distinct chunk lengths ever
            # compile (each cached per Runtime) instead of a length-1
            # recompile + linear walk.
            a1, a2 = c1, c2            # identical states at `step`
            lo, hi = 0, probe
            while hi - lo > 1:
                half = (hi - lo) // 2
                m1, _ = runner(keep(a1), half)
                m2, _ = runner(keep(a2), half)
                if np.asarray(vfp(m1))[0] != np.asarray(vfp(m2))[0]:
                    hi = lo + half     # diverges in the first half
                else:
                    a1, a2, lo = m1, m2, lo + half
            # confirm the localization: the divergence we're hunting is
            # nondeterminism, which may not reproduce on re-execution from
            # the snapshot — in that case report the window with
            # event=None ("could not pin it") rather than a false step
            f1, e1 = runner(a1, 1)
            f2, _ = runner(a2, 1)
            if np.asarray(vfp(f1))[0] == np.asarray(vfp(f2))[0]:
                return dict(step=step + lo, event=None)
            ev = {k: np.asarray(v)[0, 0] for k, v in e1.items()}
            return dict(step=step + lo, event=ev)
        s1, s2 = n1, n2
        step += probe
        if bool(np.asarray(n1.halted).all()):
            break
    return None
