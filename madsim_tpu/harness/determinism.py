"""Nondeterminism detection and localization.

The reference's rand-log checker hashes every RNG draw and panics at the
first divergent draw on replay, localizing nondeterminism in virtual time
(rand.rs:72-96, runtime/mod.rs:144-187, MADSIM_TEST_CHECK_DETERMINISM).
Because our whole cluster is a tensor state, the analog is stronger and
simpler: run two replicas of the same seed in lockstep, fingerprint the
full state, and bisect to the first divergent STEP — then show the event
that was dispatched there.
"""

from __future__ import annotations

import jax
import numpy as np

from ..utils.hashing import fingerprint


def find_divergence(rt, seed: int, max_steps: int, probe: int = 64):
    """Run seed twice in lockstep; return None if identical, else a dict
    {step, event} locating the first step whose post-state fingerprints
    differ (the take-rand-log/check panic analog, with the event attached).
    """
    vfp = jax.jit(jax.vmap(fingerprint))
    runner = rt._run_chunk[True]

    s1 = rt.init_single(seed)
    s2 = rt.init_single(seed)
    step = 0
    while step < max_steps:
        n1, e1 = runner(s1, probe)
        n2, e2 = runner(s2, probe)
        if np.asarray(vfp(n1))[0] != np.asarray(vfp(n2))[0]:
            # bisect inside this probe window, one step at a time (probe is
            # small; recompiling a length-1 chunk once is fine)
            one = rt._run_chunk[True]
            for j in range(probe):
                s1, e1 = one(s1, 1)
                s2, e2 = one(s2, 1)
                if np.asarray(vfp(s1))[0] != np.asarray(vfp(s2))[0]:
                    ev = {k: np.asarray(v)[0, 0] for k, v in e1.items()}
                    return dict(step=step + j, event=ev)
            return dict(step=step + probe - 1, event=None)
        s1, s2 = n1, n2
        step += probe
        if bool(np.asarray(n1.halted).all()):
            break
    return None
