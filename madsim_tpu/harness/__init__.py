from .witness import success_witness

__all__ = ["success_witness"]
