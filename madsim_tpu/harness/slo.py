"""SLO invariants: tail latency as a first-class crash code.

The latency plane (cfg.latency_hist, DESIGN §17) measures; this module
ENFORCES: `slo_invariant(p99_le=...)` builds a traced callable over the
on-device histogram columns usable as `Runtime(invariant=)`, so an SLO
miss is a crash code the whole search/triage stack inherits for free —
crashed lanes carry `CRASH_SLO`, the fuzzer harvests (seed, knobs)
repros, `harness.minimize` ddmin-shrinks the fault script that caused
the tail, and `service.CrashBuckets` dedups SLO regressions by causal
fingerprint next to safety bugs.

The deliberate contract pierce: installing an SLO invariant makes the
latency plane OBSERVABLE — crash_code now depends on lh_e2e, so for
THAT runtime the plane is part of the replay domain (exactly like
`halt_when` reading any state). The plane stays transparent for every
runtime that doesn't install one; tests hold both directions. Keep
every lane's latency recording ON (the init_batch default): a
`latency_lanes`-masked lane never folds, so its SLO can never fire.

Determinism: the p99 estimate is the bucket-CDF lower bound
(parallel/stats quantile rule — exact integer bucketing, exact integer
CDF), so the check is a pure function of the lane's dispatch history
and fires on the SAME dispatch in every replay.

When an SLO lane needs a diagnosis, not just a verdict: build the
runtime with `SimConfig(span_attr=True)` and point
`obs.explain_latency(state, lane, rt=rt)` at the crashed lane — it
names the slowest request's hop-by-hop critical path (queue-wait vs
transit per hop, the dominant segment's node) off the same ring the
repro replays, and `parallel.stats.attribution_brief` /
`summarize()["attribution"]` aggregate the tail's time split
fleet-wide (DESIGN §24).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core import types as T
from ..parallel.stats import bucket_lower_edge

# quantile -> (numerator, denominator) so the threshold stays exact
# integer arithmetic: the q-th sample index is ceil(total * num / den)
_Q_RATIONAL = {"p50": (1, 2), "p90": (9, 10), "p99": (99, 100),
               "p999": (999, 1000)}


def _hist_quantile_edge(hist2d, num: int, den: int):
    """Lower bucket edge (ticks) of the q = num/den quantile of a
    per-lane [N, B] int32 histogram, nodes folded — all-integer, so the
    traced check is bit-deterministic. 0 when the histogram is empty."""
    counts = hist2d.sum(0).astype(jnp.int32)          # [B]
    total = counts.sum()
    cdf = jnp.cumsum(counts)
    # ceil(total*num/den) without floats; >= 1 so an empty cdf row
    # can't match bucket 0 spuriously (guarded by total > 0 anyway).
    # int32-exact while total < 2^31/den (~2.1M samples per LANE at
    # p999) — orders of magnitude above any per-trajectory completion
    # count here (total counts one lane's own dispatches)
    need = jnp.maximum((total * num + den - 1) // den, 1)
    b = jnp.argmax(cdf >= need).astype(jnp.int32)
    return jnp.where(total > 0, bucket_lower_edge(b), 0), total


def slo_invariant(p99_le: int | None = None, *, q: str = "p99",
                  target: int | None = None, sojourn: bool = False,
                  min_count: int = 1, code: int = T.CRASH_SLO):
    """Build a `Runtime(invariant=)` callable that crashes a lane when
    its request-latency quantile exceeds a target.

    Args:
      p99_le: the common case — crash when the lane's end-to-end p99
        estimate exceeds this many ticks. Sugar for q="p99",
        target=p99_le.
      q / target: any of p50/p90/p99/p999 against `target` ticks.
      sojourn: check the queue-wait histogram (lh_sojourn) instead of
        end-to-end (lh_e2e) — queue-pressure SLOs without a request
        notion (no complete_kinds needed).
      min_count: fire only once the lane folded at least this many
        samples (an SLO over 1 request is noise; raise it to let the
        workload warm up).
      code: the crash code reported (default CRASH_SLO).

    The estimate is the bucket-CDF LOWER bound (quantile rule,
    parallel/stats): it can only under-read, so a firing invariant
    means the true bucketed quantile genuinely exceeds the target —
    no false positives from bucket granularity. Conservative direction:
    a target inside a bucket's span may fire one bucket late, never
    early.

    Requires cfg.latency_hist > 0 (raises at trace time with a clear
    message otherwise) and, for the e2e form, cfg.complete_kinds
    declared — an empty histogram never fires (min_count).
    """
    if p99_le is not None:
        q, target = "p99", p99_le
    if target is None:
        raise ValueError("slo_invariant needs p99_le= or (q=, target=)")
    if q not in _Q_RATIONAL:
        raise ValueError(f"q must be one of {sorted(_Q_RATIONAL)}: {q!r}")
    num, den = _Q_RATIONAL[q]
    target = int(target)
    min_count = int(min_count)
    field = "lh_sojourn" if sojourn else "lh_e2e"

    def check(state):
        hist = getattr(state, field)
        if hist.shape[-2] == 0 or hist.shape[-1] == 0:
            raise ValueError(
                "slo_invariant needs the latency plane compiled in — "
                "set SimConfig(latency_hist=...) > 0"
                + ("" if sojourn else
                   " and declare complete_kinds (no completions = the "
                   "e2e histogram never fills)"))
        edge, total = _hist_quantile_edge(hist, num, den)
        bad = (total >= min_count) & (edge > target)
        return bad, jnp.asarray(code, jnp.int32)

    return check
