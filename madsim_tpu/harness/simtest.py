"""Chaos-test harness: the `#[madsim::test]` analog.

The reference macro expands every test into a seed loop driven by env vars —
MADSIM_TEST_SEED, MADSIM_TEST_NUM, MADSIM_TEST_TIME_LIMIT,
MADSIM_TEST_CHECK_DETERMINISM — and prints a `MADSIM_TEST_SEED={seed}` repro
line plus a config hash on failure (madsim-macros/src/lib.rs:120-206). Here
the seed loop IS the batch axis: MADSIM_TEST_NUM seeds run as one vmapped
program, and the repro line points at the first crashed trajectory, which can
then be replayed alone with `Runtime.run_single` for a full event trace.
"""

from __future__ import annotations

import functools
import hashlib
import os
from typing import Callable

import numpy as np

from ..core import types as T
from ..runtime.runtime import Runtime

_CODE_NAMES = {
    T.CRASH_DEADLOCK: "DEADLOCK (no runnable event — 'task will block forever')",
    T.CRASH_TIME_LIMIT: "TIME_LIMIT exceeded",
    T.CRASH_INVARIANT: "INVARIANT violated",
}


class SimFailure(AssertionError):
    def __init__(self, seed, code, node, cfg_hash, msg=""):
        self.seed, self.code, self.node = int(seed), int(code), int(node)
        name = _CODE_NAMES.get(self.code, f"user crash code {self.code}")
        super().__init__(
            f"simulation failed: {name} at node {self.node}. {msg}\n"
            f"reproduce with: MADSIM_TEST_SEED={self.seed} "
            f"(MADSIM_CONFIG_HASH={cfg_hash})")


def _env_int(name, default):
    v = os.environ.get(name)
    return int(v) if v else default


def apply_net_override(state, net, cfg=None):
    """Apply a NetConfig onto a (batched) state's DYNAMIC network knobs —
    loss and latency live in state, so MADSIM_TEST_CONFIG can reshape the
    fault model without recompiling (the TOML-injection contract of
    macros lib.rs:146-151).

    op_jitter_max's BOUND is dynamic too, but the jitter fold itself is
    compiled in only when the build's SimConfig enabled it (step.py §4:
    a jitterless build pays zero draws) — pass `cfg` to catch the
    silent no-op of overriding jitter onto a jitterless build."""
    import jax.numpy as jnp
    if net is None:
        return state
    if cfg is not None and net.op_jitter_max > 0 \
            and cfg.net.op_jitter_max == 0:
        raise ValueError(
            "op_jitter_max override needs a build with jitter enabled: "
            "construct SimConfig(net=NetConfig(op_jitter_max>0)) — the "
            "fold is static (step.py §4); only its bound is dynamic")
    return state.replace(
        loss=jnp.full_like(state.loss, net.packet_loss_rate),
        lat_lo=jnp.full_like(state.lat_lo, net.send_latency_min),
        lat_hi=jnp.full_like(state.lat_hi, net.send_latency_max),
        jitter=jnp.full_like(state.jitter, net.op_jitter_max))


def env_net_override():
    """NetConfig from the MADSIM_TEST_CONFIG env var (a TOML file path),
    or None."""
    path = os.environ.get("MADSIM_TEST_CONFIG")
    if not path:
        return None
    with open(path) as f:
        return T.NetConfig.from_toml(f.read())


def effective_config_hash(rt: Runtime, net_override=None,
                          time_limit_override=None) -> str:
    """Repro hash covering the static config and any runtime overrides —
    the printed hash must identify the config that actually ran
    (the config.rs:27-31 contract)."""
    h = rt.cfg.hash()
    if net_override is None and not time_limit_override:
        return h
    blob = f"{h}|{net_override}|{time_limit_override}".encode()
    return hashlib.sha256(blob).hexdigest()[:8]


def run_seeds(rt: Runtime, seeds, max_steps: int, chunk: int = 512,
              net_override=None, time_limit_override=None):
    """Run a seed batch to completion; raise SimFailure on the first crashed
    seed (lowest index). Returns the final batched state."""
    # cross-process compile tier: honor JAX_COMPILATION_CACHE_DIR (what
    # scripts/ci.sh exports) so cold harness processes reuse warm
    # executables; no-op when the env var is unset
    from ..compile.persistent import enable_persistent_cache
    enable_persistent_cache()
    init = apply_net_override(rt.init_batch(np.asarray(seeds, np.uint32)),
                              net_override, cfg=rt.cfg)
    if time_limit_override:
        init = rt.set_time_limit(init, time_limit_override)
    cfg_hash = effective_config_hash(rt, net_override, time_limit_override)
    state, _ = rt.run(init, max_steps, chunk=chunk)
    crashed = np.asarray(state.crashed)
    if crashed.any():
        i = int(np.argmax(crashed))
        msg = f"({int(crashed.sum())}/{len(seeds)} seeds crashed)"
        if os.environ.get("MADSIM_TEST_MINIMIZE"):
            # opt-in ddmin of the chaos script (one compiled run per
            # candidate row). Overrides aren't threaded into the
            # minimizer's replays, so under MADSIM_TEST_CONFIG the crash
            # may not reproduce — report that rather than fail the report
            try:
                from .minimize import minimize_scenario
                minimal, info = minimize_scenario(rt, int(seeds[i]),
                                                  max_steps, chunk)
                msg += (f"\nminimal chaos script ({info['kept']} of "
                        f"{info['kept'] + info['dropped']} rows, "
                        f"{info['runs']} runs):\n{minimal.describe()}")
            except Exception as e:  # noqa: BLE001 - repro line still stands
                msg += f"\n(minimization unavailable: {e})"
        raise SimFailure(
            seeds[i], np.asarray(state.crash_code)[i],
            np.asarray(state.crash_node)[i], cfg_hash, msg=msg)
    oops = np.asarray(state.oops)
    if (oops != 0).any():
        i = int(np.argmax(oops != 0))
        raise SimFailure(
            seeds[i], 0, -1, cfg_hash,
            msg=f"capacity overflow (oops bits {int(oops[i])}) — raise "
                f"event_capacity")
    return state


def simtest(num_seeds: int = 16, max_steps: int = 20_000,
            seed: int | None = None, check_determinism: bool = False,
            chunk: int = 512):
    """Decorator: the wrapped function builds and returns a Runtime (or
    (Runtime, check_fn) where check_fn(final_state) does extra asserts).

    Env knobs (same contract as the reference macro,
    madsim-macros/src/lib.rs:120-206):
      MADSIM_TEST_SEED               base seed (default: stable per-test hash)
      MADSIM_TEST_NUM                number of seeds (the batch axis!)
      MADSIM_TEST_TIME_LIMIT         virtual-time limit in SECONDS (overrides
                                     cfg.time_limit without recompiling — the
                                     limit is dynamic state, lib.rs:157-159)
      MADSIM_TEST_CHECK_DETERMINISM  also run seed twice and compare state
    """

    def deco(fn: Callable):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if seed is not None:
                default_seed = seed
            else:  # stable across interpreter runs (hash() is randomized)
                digest = hashlib.sha256(fn.__qualname__.encode()).hexdigest()
                default_seed = int(digest[:8], 16) % (2**31)
            base = _env_int("MADSIM_TEST_SEED", default_seed)
            n = _env_int("MADSIM_TEST_NUM", num_seeds)
            limit_s = _env_int("MADSIM_TEST_TIME_LIMIT", 0)
            out = fn(*args, **kwargs)
            rt, check_fn = out if isinstance(out, tuple) else (out, None)
            seeds = np.arange(base, base + n, dtype=np.uint32)
            override = env_net_override()
            state = run_seeds(rt, seeds, max_steps, chunk,
                              net_override=override,
                              time_limit_override=(T.sec(limit_s)
                                                   if limit_s else None))
            if check_fn is not None:
                check_fn(state)
            if check_determinism or os.environ.get(
                    "MADSIM_TEST_CHECK_DETERMINISM"):
                assert rt.check_determinism(base, max_steps,
                                            net_override=override), (
                    f"nondeterminism detected for seed {base} "
                    f"(MADSIM_CONFIG_HASH="
                    f"{effective_config_hash(rt, override)})")
            return state
        return wrapper
    return deco
