"""Chaos-test harness: the `#[madsim::test]` analog.

The reference macro expands every test into a seed loop driven by env vars —
MADSIM_TEST_SEED, MADSIM_TEST_NUM, MADSIM_TEST_TIME_LIMIT,
MADSIM_TEST_CHECK_DETERMINISM — and prints a `MADSIM_TEST_SEED={seed}` repro
line plus a config hash on failure (madsim-macros/src/lib.rs:120-206). Here
the seed loop IS the batch axis: MADSIM_TEST_NUM seeds run as one vmapped
program, and the repro line points at the first crashed trajectory, which can
then be replayed alone with `Runtime.run_single` for a full event trace.
"""

from __future__ import annotations

import functools
import hashlib
import os
from typing import Callable

import numpy as np

from ..core import types as T
from ..runtime.runtime import Runtime

_CODE_NAMES = {
    T.CRASH_DEADLOCK: "DEADLOCK (no runnable event — 'task will block forever')",
    T.CRASH_TIME_LIMIT: "TIME_LIMIT exceeded",
    T.CRASH_INVARIANT: "INVARIANT violated",
}


class SimFailure(AssertionError):
    def __init__(self, seed, code, node, cfg_hash, msg=""):
        self.seed, self.code, self.node = int(seed), int(code), int(node)
        name = _CODE_NAMES.get(self.code, f"user crash code {self.code}")
        super().__init__(
            f"simulation failed: {name} at node {self.node}. {msg}\n"
            f"reproduce with: MADSIM_TEST_SEED={self.seed} "
            f"(MADSIM_CONFIG_HASH={cfg_hash})")


class DetSanFailure(AssertionError):
    """The determinism sanitizer (detsan=True) found a seed whose final
    state depends on WHICH LANE it ran in — a violation of the lane-
    independence half of DESIGN §4 (seed i in any batch == seed i
    alone). The lint pass (analyze/lint.py) catches the static causes;
    this is the net for everything it can't see."""

    def __init__(self, diffs: list, seeds, cfg_hash: str):
        self.diffs = diffs
        first = diffs[0]
        lane = first["lanes"][0] if first["lanes"] else 0
        seeds = np.asarray(seeds).reshape(-1)
        self.seed = int(seeds[lane])
        leaves = ", ".join(d["leaf"] for d in diffs[:8])
        # unlike SimFailure, a single-seed repro line would be a lie
        # here: the finding is that the seed's trajectory depended on
        # its LANE PLACEMENT, so only re-creating the exact batch
        # (base + count, the @simtest seed layout) reproduces it
        super().__init__(
            f"determinism sanitizer: {len(diffs)} state leaf(s) differ "
            f"between identity and permuted lane placement.\n"
            f"  first: leaf {first['leaf']}, {first['n_lanes']} lane(s), "
            f"first lane {lane} (seed {self.seed})\n"
            f"  differing leaves: {leaves}\n"
            f"reproduce the exact batch with: "
            f"MADSIM_TEST_SEED={int(seeds[0])} "
            f"MADSIM_TEST_NUM={len(seeds)} MADSIM_TEST_DETSAN=1 "
            f"(MADSIM_CONFIG_HASH={cfg_hash}; the differing seed alone "
            f"may pass — lane placement is the variable under test)")


def _env_int(name, default):
    v = os.environ.get(name)
    return int(v) if v else default


def apply_net_override(state, net, cfg=None):
    """Apply a NetConfig onto a (batched) state's DYNAMIC network knobs —
    loss and latency live in state, so MADSIM_TEST_CONFIG can reshape the
    fault model without recompiling (the TOML-injection contract of
    macros lib.rs:146-151).

    op_jitter_max's BOUND is dynamic too, but the jitter fold itself is
    compiled in only when the build's SimConfig enabled it (step.py §4:
    a jitterless build pays zero draws) — pass `cfg` to catch the
    silent no-op of overriding jitter onto a jitterless build."""
    import jax.numpy as jnp
    if net is None:
        return state
    if cfg is not None and net.op_jitter_max > 0 \
            and cfg.net.op_jitter_max == 0:
        raise ValueError(
            "op_jitter_max override needs a build with jitter enabled: "
            "construct SimConfig(net=NetConfig(op_jitter_max>0)) — the "
            "fold is static (step.py §4); only its bound is dynamic")
    return state.replace(
        loss=jnp.full_like(state.loss, net.packet_loss_rate),
        lat_lo=jnp.full_like(state.lat_lo, net.send_latency_min),
        lat_hi=jnp.full_like(state.lat_hi, net.send_latency_max),
        jitter=jnp.full_like(state.jitter, net.op_jitter_max))


def env_net_override():
    """NetConfig from the MADSIM_TEST_CONFIG env var (a TOML file path),
    or None."""
    path = os.environ.get("MADSIM_TEST_CONFIG")
    if not path:
        return None
    with open(path) as f:
        return T.NetConfig.from_toml(f.read())


def effective_config_hash(rt: Runtime, net_override=None,
                          time_limit_override=None) -> str:
    """Repro hash covering the static config and any runtime overrides —
    the printed hash must identify the config that actually ran
    (the config.rs:27-31 contract)."""
    h = rt.cfg.hash()
    if net_override is None and not time_limit_override:
        return h
    blob = f"{h}|{net_override}|{time_limit_override}".encode()
    return hashlib.sha256(blob).hexdigest()[:8]


def detsan_perm(B: int) -> np.ndarray:
    """The sanitizer's deterministic lane permutation: a Knuth-hash
    shuffle (a real permutation for any B), falling back to reversal if
    the hash order happens to be the identity — for B > 1 the permuted
    run always places at least one seed in a different lane."""
    keys = (np.arange(B, dtype=np.uint64) * np.uint64(2654435761)
            + np.uint64(0x9E3779B9)) & np.uint64(0xFFFFFFFF)
    perm = np.argsort(keys, kind="stable").astype(np.int64)
    if B > 1 and bool((perm == np.arange(B)).all()):
        perm = np.arange(B - 1, -1, -1, dtype=np.int64)
    return perm


def diff_states(a, b, align=None) -> list[dict]:
    """Leaf-for-leaf diff of two batched states (the detsan comparator).
    `align` re-indexes `b`'s batch axis first (the inverse of the lane
    permutation, so lane i compares against the lane that ran seed i).
    Returns one dict per differing leaf: {leaf, n_lanes, lanes} with
    `lanes` the first few differing lane indices. NaN == NaN (a NaN
    that reproduces as the same NaN is deterministic)."""
    import jax
    la = jax.tree_util.tree_flatten_with_path(a)[0]
    lb = jax.tree_util.tree_flatten_with_path(b)[0]
    diffs: list[dict] = []
    for (path, xa), (_, xb) in zip(la, lb):
        va, vb = np.asarray(xa), np.asarray(xb)
        if align is not None:
            vb = vb[np.asarray(align)]
        if va.size == 0:
            continue
        neq = va != vb
        if va.dtype.kind == "f":
            neq &= ~(np.isnan(va) & np.isnan(vb))
        if not neq.any():
            continue
        lanes = np.nonzero(neq.reshape(neq.shape[0], -1).any(axis=1))[0]
        diffs.append(dict(leaf=jax.tree_util.keystr(path),
                          n_lanes=int(len(lanes)),
                          lanes=lanes[:8].tolist()))
    return diffs


def detsan_check(rt: Runtime, seeds, max_steps: int, chunk: int = 512, *,
                 net_override=None, time_limit_override=None,
                 fused: bool = True, perm=None, baseline_state=None,
                 raise_on_diff: bool = True) -> dict:
    """The determinism sanitizer: run the seed batch twice — once in
    given order, once under a permuted LANE PLACEMENT — un-permute, and
    diff the final states leaf-for-leaf. Lane independence (DESIGN §4:
    seed i in any batch == seed i alone) makes the two runs bitwise
    equal for any program inside the determinism discipline; whatever
    the static lint pass cannot see (a host value baked per-trace, a
    cross-lane leak through an extension, a placement-sensitive
    collective) shows up here as a named leaf + lane + seed.

    Both runs use the same runner (`fused` selects which) and the same
    executable, so the sanitizer's cost is one extra sweep plus a host
    diff — the ≤2x contract `bench.py --mode detsan_ab` measures. When
    `baseline_state` is given (run_seeds already ran the batch), only
    the permuted sweep is paid. With no baseline both sweeps are
    DISPATCHED before either is forced: JAX async dispatch overlaps
    them where the backend allows.

    Returns {ok, batch, leaves, diffs, perm}; raises `DetSanFailure`
    on a diff unless `raise_on_diff=False`."""
    import jax
    seeds = np.asarray(seeds, np.uint32).reshape(-1)
    B = seeds.shape[0]
    perm = detsan_perm(B) if perm is None else np.asarray(perm, np.int64)
    if sorted(perm.tolist()) != list(range(B)):
        raise ValueError(f"perm is not a permutation of range({B})")

    def _run(sds):
        init = apply_net_override(rt.init_batch(sds), net_override,
                                  cfg=rt.cfg)
        if time_limit_override:
            init = rt.set_time_limit(init, time_limit_override)
        if fused:
            return rt.run_fused(init, max_steps, chunk)
        s, _ = rt.run(init, max_steps, chunk=chunk)
        return s

    if baseline_state is None:
        a = _run(seeds)
        b = _run(seeds[perm])
    else:
        a = baseline_state
        b = _run(seeds[perm])
    diffs = diff_states(a, b, align=np.argsort(perm))
    if diffs and raise_on_diff:
        raise DetSanFailure(diffs, seeds, effective_config_hash(
            rt, net_override, time_limit_override))
    return dict(ok=not diffs, batch=int(B),
                leaves=len(jax.tree_util.tree_leaves(a)),
                diffs=diffs, perm=perm.tolist())


def run_seeds(rt: Runtime, seeds, max_steps: int, chunk: int = 512,
              net_override=None, time_limit_override=None,
              detsan: bool = False):
    """Run a seed batch to completion; raise SimFailure on the first crashed
    seed (lowest index). Returns the final batched state.

    detsan=True (or MADSIM_TEST_DETSAN=1) additionally replays the batch
    under a permuted lane placement and diffs final states leaf-for-leaf
    (`detsan_check`) — DetSanFailure outranks SimFailure, because a
    crash report from a nondeterministic run is not a repro."""
    # cross-process compile tier: honor JAX_COMPILATION_CACHE_DIR (what
    # scripts/ci.sh exports) so cold harness processes reuse warm
    # executables; no-op when the env var is unset
    from ..compile.persistent import enable_persistent_cache
    enable_persistent_cache()
    init = apply_net_override(rt.init_batch(np.asarray(seeds, np.uint32)),
                              net_override, cfg=rt.cfg)
    if time_limit_override:
        init = rt.set_time_limit(init, time_limit_override)
    cfg_hash = effective_config_hash(rt, net_override, time_limit_override)
    state, _ = rt.run(init, max_steps, chunk=chunk)
    if detsan or os.environ.get("MADSIM_TEST_DETSAN"):
        detsan_check(rt, seeds, max_steps, chunk,
                     net_override=net_override,
                     time_limit_override=time_limit_override,
                     fused=False, baseline_state=state)
    crashed = np.asarray(state.crashed)
    if crashed.any():
        i = int(np.argmax(crashed))
        msg = f"({int(crashed.sum())}/{len(seeds)} seeds crashed)"
        if os.environ.get("MADSIM_TEST_MINIMIZE"):
            # opt-in ddmin of the chaos script (one compiled run per
            # candidate row). Overrides aren't threaded into the
            # minimizer's replays, so under MADSIM_TEST_CONFIG the crash
            # may not reproduce — report that rather than fail the report
            try:
                from .minimize import minimize_scenario
                minimal, info = minimize_scenario(rt, int(seeds[i]),
                                                  max_steps, chunk)
                msg += (f"\nminimal chaos script ({info['kept']} of "
                        f"{info['kept'] + info['dropped']} rows, "
                        f"{info['runs']} runs):\n{minimal.describe()}")
            except Exception as e:  # noqa: BLE001 - repro line still stands
                msg += f"\n(minimization unavailable: {e})"
        raise SimFailure(
            seeds[i], np.asarray(state.crash_code)[i],
            np.asarray(state.crash_node)[i], cfg_hash, msg=msg)
    oops = np.asarray(state.oops)
    if (oops != 0).any():
        i = int(np.argmax(oops != 0))
        raise SimFailure(
            seeds[i], 0, -1, cfg_hash,
            msg=f"capacity overflow (oops bits {int(oops[i])}) — raise "
                f"event_capacity")
    return state


def simtest(num_seeds: int = 16, max_steps: int = 20_000,
            seed: int | None = None, check_determinism: bool = False,
            chunk: int = 512, detsan: bool = False):
    """Decorator: the wrapped function builds and returns a Runtime (or
    (Runtime, check_fn) where check_fn(final_state) does extra asserts).

    Env knobs (same contract as the reference macro,
    madsim-macros/src/lib.rs:120-206):
      MADSIM_TEST_SEED               base seed (default: stable per-test hash)
      MADSIM_TEST_NUM                number of seeds (the batch axis!)
      MADSIM_TEST_TIME_LIMIT         virtual-time limit in SECONDS (overrides
                                     cfg.time_limit without recompiling — the
                                     limit is dynamic state, lib.rs:157-159)
      MADSIM_TEST_CHECK_DETERMINISM  also run seed twice and compare state
      MADSIM_TEST_DETSAN             determinism sanitizer: replay the whole
                                     batch under permuted lane placement and
                                     diff leaf-for-leaf (detsan_check) —
                                     catches lane-placement dependence the
                                     same-lane replay check above cannot
    """

    def deco(fn: Callable):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if seed is not None:
                default_seed = seed
            else:  # stable across interpreter runs (hash() is randomized)
                digest = hashlib.sha256(fn.__qualname__.encode()).hexdigest()
                default_seed = int(digest[:8], 16) % (2**31)
            base = _env_int("MADSIM_TEST_SEED", default_seed)
            n = _env_int("MADSIM_TEST_NUM", num_seeds)
            limit_s = _env_int("MADSIM_TEST_TIME_LIMIT", 0)
            out = fn(*args, **kwargs)
            rt, check_fn = out if isinstance(out, tuple) else (out, None)
            seeds = np.arange(base, base + n, dtype=np.uint32)
            override = env_net_override()
            state = run_seeds(rt, seeds, max_steps, chunk,
                              net_override=override,
                              time_limit_override=(T.sec(limit_s)
                                                   if limit_s else None),
                              detsan=detsan)
            if check_fn is not None:
                check_fn(state)
            if check_determinism or os.environ.get(
                    "MADSIM_TEST_CHECK_DETERMINISM"):
                assert rt.check_determinism(base, max_steps,
                                            net_override=override), (
                    f"nondeterminism detected for seed {base} "
                    f"(MADSIM_CONFIG_HASH="
                    f"{effective_config_hash(rt, override)})")
            return state
        return wrapper
    return deco
