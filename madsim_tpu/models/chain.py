"""Chain replication with a reconfiguring master (van Renesse & Schneider,
OSDI'04) — a second replication family beside Raft, exercising a different
fault-tolerance style: fail-stop membership ruled by a master, not quorum
voting.

Cluster: node 0 = master, nodes 1..R = replicas, R+1.. = clients.

  * WRITES enter at the HEAD and propagate down the chain; the TAIL acks
    the client. Propagation is idempotent (monotonic per-client ids dedup
    at every hop), so client retry-through-head is the repair mechanism
    for writes stranded by a mid-chain failure.
  * READS are served by the tail alone, gated by a LEASE. Virtual time is
    one synchronized clock across the cluster, so leases are EXACT — the
    sim can state, and check after every event, the invariant that at most
    one replica ever believes it is a lease-holding tail
    (CRASH_TWO_TAILS). The master activates a new epoch only after
    old leases provably expired (wait > lease + max latency).
  * Membership: replicas ping the master; a silent replica is declared
    dead and the chain shrinks (survivors keep their order — which is
    what makes acked writes safe across reconfiguration: an ack means
    every live chain member applied, and the new chain is a subset).
    A restarted replica re-enters ONLY if the master had not yet removed
    it (short blip: persisted kv + client retries make that safe);
    once removed it stays out — rejoin-with-state-transfer is Raft's
    jurisdiction (models/raft_kv.py).

Histories are recorded client-side and checked with the linearizability
checker (the same oracle as KV-on-Raft).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.api import Ctx, Program
from ..core.types import ms

# message tags
CFG_REQ, CFG, BEAT, PING, WRITE, READ, CRSP = 11, 12, 13, 14, 15, 16, 17
# timer tags
T_BEAT, T_PING, T_CHECK, T_ACT, T_NEW, T_RETRY = 1, 2, 3, 4, 5, 6
# CRSP statuses
ST_OK, ST_REFUSE = 1, 2

OP_PUT, OP_GET = 1, 2

CRASH_TWO_TAILS = 501

MASTER = 0


def chain_state_spec(n_nodes: int, n_replicas: int, n_keys: int,
                     n_ops: int):
    z = jnp.asarray(0, jnp.int32)
    R = n_replicas
    return dict(
        # master
        m_last=jnp.zeros((n_nodes,), jnp.int32),   # last ping per node
        m_epoch=jnp.asarray(1, jnp.int32),
        m_chain=jnp.zeros((R,), jnp.int32),
        m_len=z,
        m_pend=z,
        # replica
        r_epoch=z,
        r_chain=jnp.zeros((R,), jnp.int32),
        r_len=z,
        r_pos=jnp.asarray(-1, jnp.int32),
        r_lease=z,
        kv=jnp.zeros((n_keys,), jnp.int32),
        sess_rtag=jnp.zeros((n_nodes,), jnp.int32),
        # client
        c_epoch=z, c_head=z, c_tail=z, c_have=z,
        c_opn=z, c_wait=z, c_op=z, c_key=z, c_val=z,
        h_op=jnp.zeros((n_ops,), jnp.int32),
        h_key=jnp.zeros((n_ops,), jnp.int32),
        h_val=jnp.zeros((n_ops,), jnp.int32),
        h_inv=jnp.full((n_ops,), -1, jnp.int32),
        h_resp=jnp.full((n_ops,), -1, jnp.int32),
    )


def chain_persist_spec(spec):
    """The replicated register state survives a blip-restart; config and
    lease deliberately do NOT (a restarted node must re-learn the epoch
    before it can act, and can never resurrect an expired lease)."""
    return {k: k in ("kv", "sess_rtag") for k in spec}


class ChainMaster(Program):
    """Failure detector + configuration service.

    Reconfiguration protocol: on detecting a dead chain member, wait
    `wait` (> lease: expiries are grant-anchored at send time, so every
    lease granted under the old epoch has expired after `wait` regardless
    of delivery delays), then activate epoch+1 with the dead members
    removed and resume config beats. `wait` <= lease is a real protocol
    bug — tests inject it and the two-tails invariant catches the
    consequence.
    """

    def __init__(self, n_replicas: int, lease=ms(120), beat_every=ms(30),
                 check_every=ms(40), dead_after=ms(100), wait=None):
        self.R = n_replicas
        self.lease = lease
        self.hb = beat_every
        self.chk = check_every
        self.dead = dead_after
        self.wait = wait if wait is not None else lease + ms(30)

    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        only = ctx.node == MASTER
        # initial chain: all replicas, in id order
        st["m_chain"] = jnp.where(only,
                                  jnp.arange(1, self.R + 1, dtype=jnp.int32),
                                  st["m_chain"])
        st["m_len"] = jnp.where(only, self.R, st["m_len"])
        st["m_last"] = jnp.where(only, jnp.full_like(st["m_last"], ctx.now),
                                 st["m_last"])
        ctx.set_timer(self.hb, T_BEAT, [0], when=only)
        ctx.set_timer(self.chk, T_CHECK, [0], when=only)
        ctx.state = st

    def _members(self, st):
        ks = jnp.arange(self.R, dtype=jnp.int32)
        return st["m_chain"], ks < st["m_len"]

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        chain, member = self._members(st)

        # config beats to current members (removed nodes must never get a
        # fresh lease). The lease expiry is GRANT-anchored (computed at
        # send time and carried in the beat): a delayed or parked beat can
        # then never resurrect an expired lease at delivery time, so the
        # master's wait bound is simply wait > lease, independent of
        # network latency or pause/resume timing.
        is_beat = tag == T_BEAT
        expiry = ctx.now + self.lease
        beat_payload = jnp.concatenate(
            [jnp.stack([st["m_epoch"], st["m_len"], expiry]), chain])
        for i in range(self.R):
            ctx.send(chain[i], BEAT, beat_payload,
                     when=is_beat & member[i] & (st["m_pend"] == 0))
        ctx.set_timer(self.hb, T_BEAT, [0], when=is_beat)

        # failure detection: a silent chain member triggers reconfiguration
        is_chk = tag == T_CHECK
        silent = (ctx.now - st["m_last"][jnp.clip(chain, 0, None)]
                  > self.dead)
        any_dead = (silent & member).any()
        start = is_chk & any_dead & (st["m_pend"] == 0)
        st["m_pend"] = jnp.where(start, 1, st["m_pend"])
        ctx.set_timer(self.wait, T_ACT, [0], when=start)
        ctx.set_timer(self.chk, T_CHECK, [0], when=is_chk)

        # activation: drop every member that is STILL silent now, bump the
        # epoch, resume beats. Survivors keep their relative order.
        is_act = (tag == T_ACT) & (st["m_pend"] == 1)
        alive_now = ~(ctx.now - st["m_last"][jnp.clip(chain, 0, None)]
                      > self.dead)
        keep = member & alive_now
        # compact survivors, preserving order — gather formulation (the
        # j-th new slot takes the (j+1)-th kept element; a duplicate-index
        # scatter would have undefined, nondeterministic ordering)
        cs = jnp.cumsum(keep.astype(jnp.int32))
        ks_r = jnp.arange(self.R, dtype=jnp.int32)
        srcs = jnp.searchsorted(cs, ks_r + 1)
        new_chain = jnp.where(ks_r < keep.sum(),
                              chain[jnp.clip(srcs, 0, self.R - 1)], 0)
        changed = keep.sum() < st["m_len"]
        st["m_chain"] = jnp.where(is_act & changed, new_chain,
                                  st["m_chain"])
        st["m_len"] = jnp.where(is_act & changed,
                                keep.sum(dtype=jnp.int32), st["m_len"])
        st["m_epoch"] = st["m_epoch"] + (is_act & changed)
        st["m_pend"] = jnp.where(is_act, 0, st["m_pend"])
        ctx.state = st

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        is_ping = tag == PING
        sc = jnp.clip(src, 0, st["m_last"].shape[0] - 1)
        st["m_last"] = st["m_last"].at[sc].set(
            jnp.where(is_ping, ctx.now, st["m_last"][sc]))
        # config queries (clients): head/tail of the CURRENT epoch
        is_req = tag == CFG_REQ
        head = st["m_chain"][0]
        tail = st["m_chain"][jnp.clip(st["m_len"] - 1, 0, self.R - 1)]
        ctx.send(src, CFG, [st["m_epoch"], head, tail,
                            payload[0]], when=is_req & (st["m_len"] > 0))
        ctx.state = st


class ChainReplica(Program):
    def __init__(self, n_replicas: int, n_keys: int, ping_every=ms(25)):
        self.R = n_replicas
        self.K = n_keys
        self.hp = ping_every

    def init(self, ctx: Ctx):
        ctx.set_timer(ctx.randint(0, self.hp), T_PING, [0])

    def on_timer(self, ctx: Ctx, tag, payload):
        is_ping = tag == T_PING
        ctx.send(MASTER, PING, [0], when=is_ping)
        ctx.set_timer(self.hp, T_PING, [0], when=is_ping)

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        R = self.R

        # ---- config beat: adopt newer epochs, extend the lease ----------
        is_beat = (tag == BEAT) & (src == MASTER)
        epoch, clen, expiry = payload[0], payload[1], payload[2]
        chain = payload[3:3 + R]
        newer = is_beat & (epoch >= st["r_epoch"])
        st["r_epoch"] = jnp.where(newer, epoch, st["r_epoch"])
        st["r_chain"] = jnp.where(newer, chain, st["r_chain"])
        st["r_len"] = jnp.where(newer, clen, st["r_len"])
        ks = jnp.arange(R, dtype=jnp.int32)
        mypos = jnp.max(jnp.where((chain == ctx.node) & (ks < clen),
                                  ks, -1))
        st["r_pos"] = jnp.where(newer, mypos, st["r_pos"])
        # grant-anchored: take the master's expiry, never ctx.now + lease —
        # a parked/delayed beat must not revive a lease at delivery time
        st["r_lease"] = jnp.where(newer,
                                  jnp.maximum(st["r_lease"], expiry),
                                  st["r_lease"])

        # ---- write propagation (idempotent at every hop) ----------------
        is_w = (tag == WRITE) & (payload[0] == st["r_epoch"]) & (
            st["r_pos"] >= 0)
        client, rtag = payload[1], payload[2]
        key = jnp.clip(payload[3], 0, self.K - 1)
        val = payload[4]
        cc = jnp.clip(client, 0, st["sess_rtag"].shape[0] - 1)
        fresh = is_w & (rtag > st["sess_rtag"][cc])
        st["kv"] = st["kv"].at[key].set(jnp.where(fresh, val,
                                                  st["kv"][key]))
        st["sess_rtag"] = st["sess_rtag"].at[cc].set(
            jnp.where(fresh, rtag, st["sess_rtag"][cc]))
        at_tail = st["r_pos"] == st["r_len"] - 1
        succ = st["r_chain"][jnp.clip(st["r_pos"] + 1, 0, R - 1)]
        # forward down-chain or ack the client (shared send slot)
        ctx.send(jnp.where(at_tail, client, succ),
                 jnp.where(at_tail, CRSP, WRITE),
                 jnp.where(at_tail,
                           jnp.stack([rtag, jnp.asarray(ST_OK, jnp.int32),
                                      val, 0, 0]),
                           payload[:5]),
                 when=is_w)

        # ---- reads: tail-only, lease-gated ------------------------------
        is_r = (tag == READ) & (payload[0] == st["r_epoch"])
        serving = (st["r_pos"] >= 0) & at_tail & (ctx.now < st["r_lease"])
        rr_client, rr_tag = payload[1], payload[2]
        rkey = jnp.clip(payload[3], 0, self.K - 1)
        ctx.send(rr_client, CRSP,
                 [rr_tag,
                  jnp.where(serving, ST_OK, ST_REFUSE),
                  st["kv"][rkey]],
                 when=is_r)
        # stale-epoch reads are refused too (shares the same slot via mask)
        ctx.send(payload[1], CRSP, [payload[2], ST_REFUSE, 0],
                 when=(tag == READ) & (payload[0] != st["r_epoch"]))
        ctx.state = st


class ChainClient(Program):
    """Sequential PUT/GET over its own key range; refetches the config and
    retries (same monotonic rtag) on timeout or refusal."""

    def __init__(self, n_replicas: int, n_ops: int,
                 keys_per_client: int = 2, timeout=ms(60), think=ms(8)):
        self.R = n_replicas
        self.O = n_ops
        self.KPC = keys_per_client
        self.timeout = timeout
        self.think = think

    def _key(self, ctx, st):
        base = (ctx.node - 1 - self.R) * self.KPC
        return base + (st["c_opn"] // 2) % self.KPC

    def init(self, ctx: Ctx):
        ctx.set_timer(ctx.randint(0, ms(15)), T_NEW, [0])

    def _issue(self, ctx, st, when):
        rtag = st["c_opn"] + 1
        is_put = st["c_op"] == OP_PUT
        dst = jnp.where(is_put, st["c_head"], st["c_tail"])
        body = jnp.stack([st["c_epoch"], ctx.node, rtag,
                          self._key(ctx, st), st["c_val"]])
        ctx.send(dst, jnp.where(is_put, WRITE, READ), body,
                 when=when & (st["c_have"] == 1))
        ctx.send(MASTER, CFG_REQ, [rtag], when=when & (st["c_have"] == 0))
        ctx.set_timer(self.timeout, T_RETRY, [rtag], when=when)

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        start = ((tag == T_NEW) & (st["c_wait"] == 0)
                 & (st["c_opn"] < self.O))
        st["c_op"] = jnp.where(start,
                               jnp.where(st["c_opn"] % 2 == 0, OP_PUT,
                                         OP_GET), st["c_op"])
        st["c_val"] = jnp.where(start & (st["c_op"] == OP_PUT),
                                ctx.node * 4096 + st["c_opn"] + 1,
                                st["c_val"])
        st["c_wait"] = jnp.where(start, 1, st["c_wait"])
        oidx = jnp.clip(st["c_opn"], 0, self.O - 1)
        for col, v in (("h_op", st["c_op"]), ("h_key", self._key(ctx, st)),
                       ("h_val", st["c_val"]), ("h_inv", ctx.now)):
            st[col] = st[col].at[oidx].set(
                jnp.where(start, v, st[col][oidx]))

        # timeout: config may be stale (dead head/tail, new epoch) —
        # refetch, then retry the SAME rtag
        retry = ((tag == T_RETRY) & (st["c_wait"] == 1)
                 & (payload[0] == st["c_opn"] + 1))
        st["c_have"] = jnp.where(retry, 0, st["c_have"])
        self._issue(ctx, st, start | retry)
        ctx.state = st

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        # config reply -> re-issue the in-flight op immediately
        is_cfg = (tag == CFG) & (src == MASTER)
        st["c_epoch"] = jnp.where(is_cfg, payload[0], st["c_epoch"])
        st["c_head"] = jnp.where(is_cfg, payload[1], st["c_head"])
        st["c_tail"] = jnp.where(is_cfg, payload[2], st["c_tail"])
        st["c_have"] = jnp.where(is_cfg, 1, st["c_have"])
        reissue = is_cfg & (st["c_wait"] == 1)
        self._issue(ctx, st, reissue)

        # operation response
        hit = ((tag == CRSP) & (st["c_wait"] == 1)
               & (payload[0] == st["c_opn"] + 1))
        ok = hit & (payload[1] == ST_OK)
        # a refusal (stale tail / expired lease) = refetch config + retry
        refused = hit & (payload[1] == ST_REFUSE)
        st["c_have"] = jnp.where(refused, 0, st["c_have"])
        ctx.send(MASTER, CFG_REQ, [st["c_opn"] + 1], when=refused)

        oidx = jnp.clip(st["c_opn"], 0, self.O - 1)
        st["h_resp"] = st["h_resp"].at[oidx].set(
            jnp.where(ok, ctx.now, st["h_resp"][oidx]))
        st["h_val"] = st["h_val"].at[oidx].set(
            jnp.where(ok & (st["h_op"][oidx] == OP_GET), payload[2],
                      st["h_val"][oidx]))
        st["c_opn"] = st["c_opn"] + ok
        st["c_wait"] = jnp.where(ok, 0, st["c_wait"])
        ctx.set_timer(self.think, T_NEW, [0], when=ok)
        ctx.state = st


def chain_invariant(n_nodes: int, n_replicas: int):
    """At most one replica may simultaneously believe it is a
    lease-holding tail — the property the master's wait-before-activate
    protocol guarantees, checkable exactly because virtual time is one
    synchronized clock."""
    replica = np.zeros(n_nodes, bool)
    replica[1:1 + n_replicas] = True
    rmask = jnp.asarray(replica)

    def invariant(state):
        ns = state.node_state
        serving = (rmask & state.alive & (ns["r_pos"] >= 0)
                   & (ns["r_pos"] == ns["r_len"] - 1)
                   & (state.now < ns["r_lease"]))
        bad = serving.sum() > 1
        return bad, jnp.asarray(CRASH_TWO_TAILS, jnp.int32)

    return invariant


def all_done(n_replicas: int, n_ops: int):
    def check(state):
        return (state.node_state["c_opn"][1 + n_replicas:] >= n_ops).all()
    return check


def make_chain_runtime(n_replicas=3, n_clients=2, n_ops=10,
                       keys_per_client=2, scenario=None, cfg=None,
                       lease=ms(120), master_wait=None):
    from ..core.types import NetConfig, SimConfig, sec
    from ..runtime.runtime import Runtime
    n = 1 + n_replicas + n_clients
    n_keys = n_clients * keys_per_client
    if cfg is None:
        cfg = SimConfig(n_nodes=n, event_capacity=384, payload_words=12,
                        time_limit=sec(10),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(8)))
    assert cfg.payload_words >= 3 + n_replicas  # BEAT: epoch,len,expiry,chain
    spec = chain_state_spec(n, n_replicas, n_keys, n_ops)
    master = ChainMaster(n_replicas, lease=lease, wait=master_wait)
    replica = ChainReplica(n_replicas, n_keys)
    client = ChainClient(n_replicas, n_ops, keys_per_client)
    node_prog = np.asarray([0] + [1] * n_replicas + [2] * n_clients,
                           np.int32)
    return Runtime(cfg, [master, replica, client], spec,
                   node_prog=node_prog, scenario=scenario,
                   invariant=chain_invariant(n, n_replicas),
                   persist=chain_persist_spec(spec),
                   halt_when=all_done(n_replicas, n_ops))


def extract_histories(state, n_replicas: int, n_clients: int):
    """Client histories for the linearizability checker — same state
    layout as KV-on-Raft, so the extraction is shared; only the client
    slice start differs (clients sit after master + replicas)."""
    from .raft_kv import extract_histories as _extract
    return _extract(state, 1 + n_replicas, n_clients)
