"""Jepsen-style bank workload over the Raft core.

Accounts live in a replicated ledger: TRANSFER(from, to, amt) entries move
money atomically, READ entries capture a snapshot of all balances at their
log position. The safety property is *total conservation*: money is
neither created nor destroyed — checked two ways:
  * in-sim, every event: each node's committed-prefix balance total must
    equal the initial total (the global invariant), and
  * host-side: every completed READ observed a conserving snapshot.

This is the classic concurrent-transfers test (popularized by Jepsen's
"bank" workload) restructured as a vectorizable state machine; it shows the
Raft core carrying a transactional command schema (multi-field entries,
derived state) rather than single-register ops.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.api import Ctx, Program
from ..core.types import ms
from . import raft as R

OP_TRANSFER, OP_READ = 1, 2
CMD, CRSP = 5, 6
T_NEW, T_RETRY = 4, 5

CRASH_MONEY_LEAK = 501        # committed total != initial total
# (client-observed snapshots are checked host-side by the tests — every
# CRSP carries the committed total at the op's log position)

BANK_FIELDS = ("op", "afrom", "ato", "amt", "client", "rtag")


def bank_state_spec(n_nodes: int, log_capacity: int, n_ops: int):
    z = jnp.asarray(0, jnp.int32)
    extra = dict(
        last_replied=z,
        c_target=z, c_id=z, c_op=z, c_from=z, c_to=z, c_amt=z, c_opn=z,
        c_wait=z,
        h_total=jnp.full((n_ops,), -1, jnp.int32),  # total seen by READs
        h_resp=jnp.full((n_ops,), -1, jnp.int32),
    )
    return R.state_spec(n_nodes, log_capacity, BANK_FIELDS, extra)


def bank_persist_spec():
    extra = dict(last_replied=None, c_target=None, c_id=None, c_op=None,
                 c_from=None, c_to=None, c_amt=None, c_opn=None,
                 c_wait=None, h_total=None, h_resp=None)
    return R.persist_spec(BANK_FIELDS, extra)


class RaftBank(R.Raft):
    """Raft peer applying the bank command schema."""

    ENTRY_FIELDS = BANK_FIELDS

    def __init__(self, n_nodes: int, n_accounts: int = 6,
                 init_balance: int = 100, log_capacity: int = 64, **kw):
        super().__init__(n_nodes, log_capacity, n_cmds=0, **kw)
        self.K = n_accounts
        self.init_balance = init_balance

    def _propose_fields(self, ctx, st):
        z = jnp.asarray(0, jnp.int32)
        return {f: z for f in BANK_FIELDS}

    def _entry_total_delta(self, st):
        """Per-entry contribution to the TOTAL balance: summing the
        per-account deltas over accounts collapses to
        amt * (to_in_range - from_in_range) — an [L] vector instead of a
        [K, L] matrix, and zero for every well-formed transfer. Any nonzero
        prefix sum means replication corrupted an entry."""
        in_to = ((st["log_ato"] >= 0)
                 & (st["log_ato"] < self.K)).astype(jnp.int32)
        in_from = ((st["log_afrom"] >= 0)
                   & (st["log_afrom"] < self.K)).astype(jnp.int32)
        is_xfer = (st["log_op"] == OP_TRANSFER).astype(jnp.int32)
        return is_xfer * st["log_amt"] * (in_to - in_from)

    def _total_at(self, st, k):
        """Total balance over all accounts at log position k."""
        ks = jnp.arange(self.L, dtype=jnp.int32)
        pre = jnp.sum(jnp.where(ks < k, self._entry_total_delta(st), 0))
        return self.init_balance * self.K + pre

    # -- hooks ------------------------------------------------------------
    def _extra_message(self, ctx: Ctx, st, src, tag, payload):
        L = self.L
        is_cmd = tag == CMD
        rtag, op = payload[0], payload[1]
        afrom, ato, amt = payload[2], payload[3], payload[4]
        leader = st["role"] == R.LEADER
        ks = jnp.arange(L, dtype=jnp.int32)
        dup = ((st["log_rtag"] == rtag) & (st["log_client"] == src)
               & (ks < st["log_len"]))
        dup_any = dup.any()
        dup_idx = jnp.argmax(dup).astype(jnp.int32)
        self._append(ctx, st, is_cmd & leader & ~dup_any,
                     dict(op=op, afrom=afrom, ato=ato, amt=amt, client=src,
                          rtag=rtag))
        dup_done = is_cmd & leader & dup_any & (dup_idx < st["commit"])
        ctx.send(src, CRSP, [rtag, self._total_at(st, dup_idx)],
                 when=dup_done)

    def _on_leader_commit(self, ctx: Ctx, st, prev_commit, is_aer):
        base = st["last_replied"]
        for j in range(2):
            k = base + j
            kc = jnp.clip(k, 0, self.L - 1)
            m = (is_aer & (st["role"] == R.LEADER) & (k < st["commit"])
                 & (st["log_op"][kc] != 0))
            ctx.send(st["log_client"][kc], CRSP,
                     [st["log_rtag"][kc], self._total_at(st, k)], when=m)
        st["last_replied"] = jnp.where(
            is_aer, jnp.minimum(st["commit"], base + 2), base)

    def _on_become_leader(self, ctx: Ctx, st, become_leader):
        st["last_replied"] = jnp.where(become_leader, st["commit"],
                                       st["last_replied"])
        z = jnp.asarray(0, jnp.int32)
        self._append(ctx, st,
                     become_leader & (st["commit"] < st["log_len"]),
                     {f: z for f in BANK_FIELDS})


class BankClient(Program):
    """Issues random transfers (and READs every third op) sequentially with
    retry-and-rotate; records the total balance each READ observed."""

    def __init__(self, n_raft: int, n_accounts: int = 6, n_ops: int = 12,
                 timeout=ms(60), think=ms(10)):
        self.R = n_raft
        self.K = n_accounts
        self.O = n_ops
        self.timeout = timeout
        self.think = think

    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        st["c_target"] = ctx.randint(0, self.R - 1)
        ctx.set_timer(ctx.randint(0, ms(20)), T_NEW, [0])
        ctx.state = st

    def _issue(self, ctx, st, when):
        ctx.send(st["c_target"], CMD,
                 [st["c_id"], st["c_op"], st["c_from"], st["c_to"],
                  st["c_amt"]], when=when)
        ctx.set_timer(self.timeout, T_RETRY, [st["c_id"]], when=when)

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        start = ((tag == T_NEW) & (st["c_wait"] == 0)
                 & (st["c_opn"] < self.O))
        st["c_id"] = jnp.where(start, ctx.randint(1, 2**30 - 1), st["c_id"])
        is_read = (st["c_opn"] % 3) == 2
        st["c_op"] = jnp.where(start,
                               jnp.where(is_read, OP_READ, OP_TRANSFER),
                               st["c_op"])
        st["c_from"] = jnp.where(start, ctx.randint(0, self.K - 1),
                                 st["c_from"])
        st["c_to"] = jnp.where(start, ctx.randint(0, self.K - 1), st["c_to"])
        st["c_amt"] = jnp.where(start, ctx.randint(1, 20), st["c_amt"])
        st["c_wait"] = jnp.where(start, 1, st["c_wait"])

        retry = ((tag == T_RETRY) & (st["c_wait"] == 1)
                 & (payload[0] == st["c_id"]))
        st["c_target"] = jnp.where(retry, ctx.randint(0, self.R - 1),
                                   st["c_target"])
        self._issue(ctx, st, start | retry)
        ctx.state = st

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        hit = ((tag == CRSP) & (st["c_wait"] == 1)
               & (payload[0] == st["c_id"]))
        oidx = jnp.clip(st["c_opn"], 0, self.O - 1)
        # every reply carries the committed total at the op's log position
        st["h_total"] = st["h_total"].at[oidx].set(
            jnp.where(hit, payload[1], st["h_total"][oidx]))
        st["h_resp"] = st["h_resp"].at[oidx].set(
            jnp.where(hit, ctx.now, st["h_resp"][oidx]))
        st["c_opn"] = st["c_opn"] + hit
        st["c_wait"] = jnp.where(hit, 0, st["c_wait"])
        ctx.set_timer(self.think, T_NEW, [0], when=hit)
        ctx.state = st


def bank_invariant(n_nodes, log_capacity, n_raft, n_accounts, init_balance,
                   window_slides=True):
    """Money conservation on every node's committed prefix, every event."""
    base = R.raft_invariant(n_nodes, log_capacity, BANK_FIELDS,
                            np.asarray([i < n_raft for i in range(n_nodes)]),
                            window_slides=window_slides)
    K, L = n_accounts, log_capacity
    total0 = n_accounts * init_balance

    def invariant(state):
        bad, code = base(state)
        ns = state.node_state
        ks = jnp.arange(L, dtype=jnp.int32)
        in_play = ((ks[None, :] < ns["commit"][:, None])
                   & (ns["log_op"] == OP_TRANSFER))          # [N, L]
        # per-entry TOTAL delta (see RaftBank._entry_total_delta): [N, L]
        in_to = ((ns["log_ato"] >= 0) & (ns["log_ato"] < K)).astype(jnp.int32)
        in_from = ((ns["log_afrom"] >= 0)
                   & (ns["log_afrom"] < K)).astype(jnp.int32)
        delta = ns["log_amt"] * (in_to - in_from)
        totals = (init_balance * K
                  + jnp.sum(jnp.where(in_play, delta, 0), axis=1))  # [N]
        leak = (totals[:n_raft] != total0).any()
        bad2 = bad | leak
        code2 = jnp.where(bad, code, jnp.asarray(CRASH_MONEY_LEAK, jnp.int32))
        return bad2, code2

    return invariant


def all_clients_done(n_raft: int, n_ops: int):
    def check(state):
        return (state.node_state["c_opn"][n_raft:] >= n_ops).all()
    return check


def make_bank_runtime(n_raft=5, n_clients=3, n_accounts=6, n_ops=12,
                      log_capacity=64, init_balance=100, scenario=None,
                      cfg=None, **raft_kw):
    from ..core.types import SimConfig, sec
    from ..runtime.runtime import Runtime
    n = n_raft + n_clients
    if cfg is None:
        cfg = SimConfig(n_nodes=n, event_capacity=96, payload_words=13,
                        time_limit=sec(20))
    assert cfg.payload_words >= 6 + len(BANK_FIELDS)
    assert log_capacity >= n_clients * n_ops + 4
    # RaftBank is NOT snapshot-aware: its leader-commit reply and
    # duplicate-detection paths index the log by ABSOLUTE position, so a
    # slid window would corrupt replies and re-apply retried transfers.
    # Refuse loudly rather than run wrong.
    assert not raft_kw.get("compact_threshold"), \
        "bank does not support log compaction (absolute log indexing)"
    raft_kw.setdefault("n_peers", n_raft)
    prog = RaftBank(n, n_accounts, init_balance, log_capacity, **raft_kw)
    client = BankClient(n_raft, n_accounts, n_ops)
    node_prog = np.asarray([0] * n_raft + [1] * n_clients, np.int32)
    return Runtime(cfg, [prog, client],
                   bank_state_spec(n, log_capacity, n_ops),
                   node_prog=node_prog, scenario=scenario,
                   invariant=bank_invariant(
                       n, log_capacity, n_raft, n_accounts, init_balance,
                       # compaction is refused above, so the window is
                       # statically pinned and the cheap form is safe
                       window_slides=False),
                   persist=bank_persist_spec(),
                   halt_when=all_clients_done(n_raft, n_ops))
