"""RPC echo service under faults — BASELINE.md config 3.

The tonic-example analog (tonic-example/src/server.rs:126-253: one server,
five clients, all method shapes, under the simulator): a server program plus
client programs issuing typed calls with retry-on-timeout through the
net.rpc conventions, fuzzed under packet loss and server kill/restart.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.api import Ctx, Program
from ..core.types import ms
from ..net import rpc

TAG_ECHO = 1          # request tag (Request::ID analog)
T_RETRY = 1           # client retry/timeout timer

SERVER = 0            # node 0 is the server; 1..N-1 are clients


def server_state_spec():
    z = jnp.asarray(0, jnp.int32)
    return dict(served=z, call_id=z, seq=z, acked=z)


client_state_spec = server_state_spec  # one shared schema (union of fields)


class EchoServer(Program):
    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        is_req = tag == TAG_ECHO
        st["served"] = st["served"] + is_req
        # echo the body back, tagged with the caller's call id
        rpc.reply(ctx, src, TAG_ECHO, payload, [payload[1]], when=is_req)
        ctx.state = st


class EchoClient(Program):
    """Issues `target` sequential echo calls; retries until each is acked
    (call_timeout + retry, the loop a madsim test writes by hand around
    Endpoint::call, net/rpc.rs:107-130)."""

    def __init__(self, target: int = 10, timeout=ms(40)):
        self.target = target
        self.timeout = timeout

    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        st["call_id"] = rpc.new_call_id(ctx)
        rpc.call(ctx, SERVER, TAG_ECHO, [st["seq"]], st["call_id"],
                 retry_timer_tag=T_RETRY, timeout=ctx.randint(0, self.timeout))
        ctx.state = st

    def on_timer(self, ctx: Ctx, tag, payload):
        st = ctx.state
        # retry only if this timeout belongs to the still-outstanding call
        stale = payload[0] != st["call_id"]
        done = st["acked"] >= self.target
        rpc.call(ctx, SERVER, TAG_ECHO, [st["seq"]], st["call_id"],
                 retry_timer_tag=T_RETRY, timeout=self.timeout,
                 when=(tag == T_RETRY) & ~stale & ~done)

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        hit = (tag == rpc.reply_tag(TAG_ECHO)) & rpc.matches(
            payload, st["call_id"])
        # the echoed body must match what we asked for
        ctx.crash_if(hit & (payload[1] != st["seq"]), 201)
        st["acked"] = st["acked"] + hit
        st["seq"] = st["seq"] + hit
        new_id = rpc.new_call_id(ctx)
        more = hit & (st["acked"] < self.target)
        st["call_id"] = jnp.where(hit, jnp.where(more, new_id, 0),
                                  st["call_id"])
        rpc.call(ctx, SERVER, TAG_ECHO, [st["seq"]], new_id,
                 retry_timer_tag=T_RETRY, timeout=self.timeout, when=more)
        ctx.state = st


def all_clients_done(target: int):
    """halt_when: every client acked `target` echoes (root future resolved)."""
    def check(state):
        acked = state.node_state["acked"]
        return (acked[1:] >= target).all()
    return check


def make_echo_runtime(n_nodes=6, target=10, scenario=None, cfg=None,
                      timeout=ms(40)):
    from ..core.types import NetConfig, SimConfig, sec
    from ..runtime.runtime import Runtime
    import numpy as np
    if cfg is None:
        cfg = SimConfig(n_nodes=n_nodes, event_capacity=256,
                        time_limit=sec(20))
    node_prog = np.asarray([0] + [1] * (n_nodes - 1), np.int32)
    return Runtime(cfg, [EchoServer(), EchoClient(target, timeout)],
                   server_state_spec(), node_prog=node_prog,
                   scenario=scenario, halt_when=all_clients_done(target))
