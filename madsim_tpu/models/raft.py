"""Raft — the MadRaft-equivalent flagship workload (BASELINE.md configs 2/4).

A full Raft core (leader election + log replication + commit) written as a
vectorizable state machine: every handler is straight-line jnp arithmetic
with masks, so thousands of 5-node Raft clusters fuzz in lockstep on one
chip. term/votedFor/log live in stable storage (the engine's persist mask —
the FsSim analog), so kill/restart chaos exercises real Raft durability
semantics rather than amnesiac restarts.

Safety is checked EVERY event by a global invariant (something the reference
architecture cannot do cheaply — its supervisor only observes at its own
wakeups): Election Safety (at most one leader per term) and State Machine
Safety (committed prefixes never disagree).

Message schema (payload words):
  RV : [term, last_log_len, last_log_term]          RequestVote
  RVR: [term, granted]                               RequestVote reply
  AE : [term, prev_len, prev_term, leader_commit,    AppendEntries
        n_entries, k x (entry_term, entry_cmd)]      (k = ae_batch entries)
  AER: [term, success, match_len]                    AppendEntries reply
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.api import Ctx, Program
from ..core.types import ms
from ..ops.select import put_row, row_onehot, take1

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2

# message tags (5/6 are taken by raft_kv's CMD/CRSP)
RV, RVR, AE, AER, IS = 1, 2, 3, 4, 9
# timer tags
T_ELECTION, T_HEARTBEAT, T_PROPOSE = 1, 2, 3

# Snapshot digest: an order-dependent hash chain over the compacted log
# prefix. Discarded entries stay checkable — State Machine Safety compares
# digests (extended over live entries where bases differ) instead of the
# entries themselves. All arithmetic is int32 wraparound (mod 2^32), which
# keeps the fold exactly associative, so vectorized reduction order can't
# change the result.
DIGEST_P = 1000003     # chain multiplier (odd — invertible mod 2^32)
DIGEST_MIX = 920419823  # column-fold multiplier


def _pow_table(L: int, base: int = DIGEST_P) -> jnp.ndarray:
    """[L+1] table of base**k mod 2^32, as two's-complement int32."""
    out = np.empty(L + 1, np.int64)
    v = 1
    for k in range(L + 1):
        out[k] = v if v < 2 ** 31 else v - 2 ** 32
        v = (v * base) % 2 ** 32
    return jnp.asarray(out, jnp.int32)


# modular inverse of DIGEST_P mod 2^32 (P is odd, so it exists): lets the
# prefix chain be evaluated with a cumsum instead of per-point refolds
DIGEST_P_INV = pow(DIGEST_P, -1, 2 ** 32)


def entry_hash(term_col, field_cols):
    """Mix one log entry's columns into a single int32 word (per slot)."""
    h = term_col
    for c in field_cols:
        h = h * DIGEST_MIX + c
    return h

# crash codes (invariant violations)
CRASH_TWO_LEADERS = 101
CRASH_LOG_MISMATCH = 102
CRASH_COMMIT_GT_LOG = 103


def state_spec(n_nodes: int, log_capacity: int = 32, fields=("cmd",),
               extra=None):
    """Node state schema. `fields` are the per-log-entry columns (the base
    Raft carries one opaque command word; RaftKv carries op/key/val/client/
    rtag). `extra` merges additional volatile leaves (e.g. client-side
    bookkeeping in mixed clusters — all programs share one schema)."""
    z = jnp.asarray(0, jnp.int32)
    L, N = log_capacity, n_nodes
    spec = dict(
        # persistent (stable storage — survives kill/restart)
        term=z,
        voted_for=jnp.asarray(-1, jnp.int32),
        log_term=jnp.zeros((L,), jnp.int32),
        log_len=z,
        # snapshot (Raft §7): physical slot k holds absolute entry
        # snap_len + k; entries below snap_len are summarized by the
        # digest chain. log_len / commit / match / next stay ABSOLUTE.
        snap_len=z,
        snap_term=z,
        snap_digest=z,
        # volatile
        role=z,
        votes=z,
        commit=z,
        next_idx=jnp.zeros((N,), jnp.int32),
        match_idx=jnp.zeros((N,), jnp.int32),
        egen=z,      # election-timer generation (stale-timer filter)
        hgen=z,      # heartbeat-timer generation
        nprop=z,     # proposals issued by this node while leader
    )
    for f in fields:
        spec[f"log_{f}"] = jnp.zeros((L,), jnp.int32)
    if extra:
        spec.update(extra)
    return spec


def persist_spec(fields=("cmd",), extra=None):
    """Which leaves are stable storage (Raft Figure 2 'persistent state')."""
    mask = dict(
        term=True, voted_for=True, log_term=True, log_len=True,
        snap_len=True, snap_term=True, snap_digest=True,
        role=False, votes=False, commit=False, next_idx=False,
        match_idx=False, egen=False, hgen=False, nprop=False,
    )
    for f in fields:
        mask[f"log_{f}"] = True
    if extra:
        mask.update({k: False for k in extra})
    return mask


class Raft(Program):
    """One Raft peer.

    Subclass hooks (used by RaftKv in models/raft_kv.py):
      ENTRY_FIELDS — per-log-entry int32 columns replicated via AE
      _propose_fields(ctx, st) — entry values for the self-proposing client
      _on_leader_commit(ctx, st, prev_commit, is_aer) — leader-side commit
        advancement (e.g. replying to clients)
      _extra_message(ctx, st, src, tag, payload) — extra message tags
        (e.g. client requests)

    Args:
      n_nodes: cluster size (majority = n//2 + 1).
      log_capacity: max entries (static shape).
      n_cmds: proposals each leader stint will issue (self-proposing client).
      halt_on_commit: halt the trajectory when any node's commit index
        reaches this (0 = run to the scenario's HALT).
    """

    def __init__(self, n_nodes: int, log_capacity: int = 32,
                 n_cmds: int = 8, halt_on_commit: int = 0,
                 election_min=ms(150), election_max=ms(300),
                 heartbeat_every=ms(50), propose_every=ms(100),
                 majority_override: int | None = None,
                 n_peers: int | None = None,
                 peer_base: int = 0,
                 compact_threshold: int = 0,
                 ae_batch: int = 1):
        self.n = n_nodes
        # raft peers occupy nodes [peer_base, peer_base + n_peers); the rest
        # of the cluster (KV clients, other raft groups in a multi-group
        # deployment like models/shard_kv.py) never votes, replicates, or
        # receives broadcasts. match_idx/next_idx stay [N]-wide and indexed
        # by absolute node id; rows outside the group are never written
        # (AER only arrives from members), so the commit count over all N
        # still counts only group members.
        self.base = peer_base
        self.npeers = n_peers if n_peers is not None else n_nodes
        self.L = log_capacity
        self.n_cmds = n_cmds
        self.halt_on_commit = halt_on_commit
        self.emin, self.emax = election_min, election_max
        self.hb = heartbeat_every
        self.prop = propose_every
        # test hook: an intentionally wrong quorum size lets the test suite
        # prove the invariant checker catches real protocol bugs
        self.majority = (majority_override if majority_override is not None
                         else self.npeers // 2 + 1)
        # log compaction (Raft §7): once the applied/committed prefix grows
        # past this many entries, fold it into the snapshot and slide the
        # window. 0 disables (logs must then fit log_capacity forever).
        self.compact_threshold = compact_threshold
        # entries carried per AppendEntries (static: payload width is
        # 5 + ae_batch*(1 + len(ENTRY_FIELDS)) words). 1 serializes log
        # catch-up through one event-table row per entry; k batches the
        # replication stream k entries per delivery, cutting the AE
        # round-trips a lagging follower needs by ~k (measured delta in
        # DESIGN §5).
        assert ae_batch >= 1
        self.ae_batch = ae_batch
        self._powP = _pow_table(log_capacity)

    ENTRY_FIELDS = ("cmd",)

    # -- subclass hooks ---------------------------------------------------
    def _propose_fields(self, ctx, st):
        return {"cmd": ctx.node * 65536 + st["nprop"]}

    def _can_propose(self, ctx, st):
        """Gate for the leader's self-propose tick (beyond being leader).
        CfgRaft throttles config proposals through this."""
        return st["nprop"] < self.n_cmds

    def _on_leader_commit(self, ctx, st, prev_commit, is_aer):
        pass

    def _extra_message(self, ctx, st, src, tag, payload):
        pass

    def _on_become_leader(self, ctx, st, become_leader):
        pass

    def _compact_limit(self, st):
        """Highest absolute index the snapshot may cover (default: commit).
        RaftKv returns its applied pointer so the materialized state-machine
        image always sits exactly at the compaction boundary."""
        return st["commit"]

    def _snapshot_extra(self, ctx, st, do, shift):
        """Hook: capture extra state-machine summary when compacting `shift`
        entries (called BEFORE the window slides)."""

    def _is_extra_words(self, ctx, st):
        """Hook: extra InstallSnapshot payload words after the 4-word header
        (RaftKv ships chunked state-machine images here). Width must not
        exceed 1 + ae_batch * (1 + len(ENTRY_FIELDS))."""
        return []

    def _install_ready(self, ctx, st, want, payload):
        """Hook: stage incoming snapshot data; return a mask of whether the
        snapshot is complete enough to install now. The base single-message
        snapshot is always complete."""
        return want

    def _install_extra(self, ctx, st, inst, payload):
        """Hook: adopt extra snapshot state from an InstallSnapshot."""

    def _on_commit_progress(self, ctx, st, active):
        """Hook: called once per message event after commit may have moved
        (follower AE, leader AER, or snapshot install) — RaftKv drains its
        apply loop here."""

    def _append(self, ctx, st, when, vals):
        """Leader-side masked append of one entry (term = current term).
        Shared by the propose tick, client commands, and election no-ops."""
        live = st["log_len"] - st["snap_len"]
        when = when & (live < self.L)
        widx = jnp.clip(live, 0, self.L - 1)
        st["log_term"] = put_row(st["log_term"], widx, st["term"], when)
        for f in self.ENTRY_FIELDS:
            st[f"log_{f}"] = put_row(st[f"log_{f}"], widx, vals[f], when)
        st["log_len"] = st["log_len"] + when
        st["match_idx"] = put_row(st["match_idx"], ctx.node, st["log_len"],
                                  when)
        return when

    # -- helpers ----------------------------------------------------------
    def _last_term(self, st):
        return jnp.where(
            st["log_len"] > st["snap_len"],
            take1(st["log_term"],
                  jnp.clip(st["log_len"] - 1 - st["snap_len"], 0,
                           self.L - 1)),
            st["snap_term"])

    def _entry_hash(self, st):
        return entry_hash(st["log_term"],
                          [st[f"log_{f}"] for f in self.ENTRY_FIELDS])

    def _shift_log(self, st, shift, live):
        """Slide the log window left by `shift` slots, zeroing all slots
        past the `live` surviving entries. One-hot select — jnp.roll with a
        traced shift lowers poorly on TPU, and an [L]-index gather pays
        ~10ns/element (ops/select.take1 notes)."""
        ks = jnp.arange(self.L, dtype=jnp.int32)
        src_idx = (ks + shift) % self.L
        keep = ks < live
        for c in ("log_term",) + tuple(f"log_{f}" for f in self.ENTRY_FIELDS):
            st[c] = jnp.where(keep, take1(st[c], src_idx), 0)

    def _maybe_compact(self, ctx, st, when):
        """Fold the committed prefix into the snapshot once it exceeds
        compact_threshold entries, then slide the window. The digest chain
        is extended over exactly the entries being discarded, so safety
        checks on the prefix survive the discard."""
        if not self.compact_threshold:
            return
        L = self.L
        sl = st["snap_len"]
        target = jnp.minimum(self._compact_limit(st), st["log_len"])
        shift = jnp.maximum(target - sl, 0)
        do = jnp.asarray(when) & (shift >= self.compact_threshold)
        shift = jnp.where(do, shift, 0)
        ks = jnp.arange(L, dtype=jnp.int32)
        h = self._entry_hash(st)
        w = take1(self._powP, jnp.clip(shift - 1 - ks, 0, L))
        contrib = jnp.where(ks < shift, h * w, 0).sum()
        self._snapshot_extra(ctx, st, do, shift)
        st["snap_digest"] = jnp.where(
            do, st["snap_digest"] * take1(self._powP, shift) + contrib,
            st["snap_digest"])
        st["snap_term"] = jnp.where(
            do, take1(st["log_term"], jnp.clip(shift - 1, 0, L - 1)),
            st["snap_term"])
        st["snap_len"] = st["snap_len"] + shift
        self._shift_log(st, shift, st["log_len"] - st["snap_len"])

    def _arm_election(self, ctx, st, when):
        st["egen"] = st["egen"] + jnp.asarray(when, jnp.int32)
        ctx.set_timer(ctx.randint(self.emin, self.emax), T_ELECTION,
                      [st["egen"]], when=when)

    # -- lifecycle --------------------------------------------------------
    def init(self, ctx: Ctx):
        st = dict(ctx.state)  # persistent leaves carry over from before
        # the snapshot IS applied state: a restarted node resumes with its
        # commit floor at the compacted prefix (volatile commit was reset)
        st["commit"] = jnp.maximum(st["commit"], st["snap_len"])
        self._arm_election(ctx, st, True)
        ctx.set_timer(ctx.randint(0, self.prop), T_PROPOSE, [0])
        ctx.state = st

    # -- timers -----------------------------------------------------------
    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        N, L = self.n, self.L

        # election timeout: become candidate, solicit votes (Raft §5.2)
        is_el = ((tag == T_ELECTION) & (payload[0] == st["egen"])
                 & (st["role"] != LEADER))
        st["term"] = st["term"] + is_el
        st["role"] = jnp.where(is_el, CANDIDATE, st["role"])
        st["voted_for"] = jnp.where(is_el, ctx.node, st["voted_for"])
        st["votes"] = jnp.where(is_el, 1, st["votes"])
        last_t = self._last_term(st)
        self._arm_election(ctx, st, is_el)  # candidate retries on split vote

        # heartbeat / replication tick (leader only). AE payload layout:
        # [term, prev_len, prev_term, leader_commit, n_entries,
        #  ae_batch x (entry_term, *ENTRY_FIELDS)]
        is_hb = ((tag == T_HEARTBEAT) & (payload[0] == st["hgen"])
                 & (st["role"] == LEADER))
        # election RV, heartbeat AE, and snapshot IS are mutually exclusive
        # per peer, so they SHARE send slots — per-peer emission count (the
        # dominant per-step engine cost) is npeers, not 3*npeers
        K, F = self.ae_batch, len(self.ENTRY_FIELDS)
        zero = jnp.zeros_like(st["term"])
        sl = st["snap_len"]
        rv_payload = jnp.stack(
            [st["term"], st["log_len"], last_t]
            + [zero] * (2 + K * (1 + F)))
        # InstallSnapshot (§7): a follower whose next entry was compacted
        # away can't be caught up by AE — ship the snapshot summary instead
        extra = self._is_extra_words(ctx, st)
        pad = 1 + K * (1 + F) - len(extra)
        assert pad >= 0, "IS extra words exceed the shared payload width"
        is_payload = jnp.stack(
            [st["term"], sl, st["snap_term"], st["snap_digest"]]
            + list(extra) + [zero] * pad)
        for p in range(self.base, self.base + self.npeers):
            nxt = st["next_idx"][p]
            need_is = nxt < sl
            prev_term = jnp.where(
                nxt > sl,
                take1(st["log_term"], jnp.clip(nxt - 1 - sl, 0, L - 1)),
                st["snap_term"])
            cnt = jnp.clip(st["log_len"] - nxt, 0, K)
            entry_words = []
            for j in range(K):
                eidx = jnp.clip(nxt + j - sl, 0, L - 1)
                entry_words.append(take1(st["log_term"], eidx))
                entry_words += [take1(st[f"log_{f}"], eidx)
                                for f in self.ENTRY_FIELDS]
            ae_payload = jnp.stack(
                [st["term"], nxt, prev_term, st["commit"], cnt]
                + entry_words)
            ctx.send(p,
                     jnp.where(is_el, RV, jnp.where(need_is, IS, AE)),
                     jnp.where(is_el, rv_payload,
                               jnp.where(need_is, is_payload, ae_payload)),
                     when=(is_el | is_hb) & (p != ctx.node))
        ctx.set_timer(self.hb, T_HEARTBEAT, [st["hgen"]], when=is_hb)

        # self-proposing client: leaders append a fresh command
        is_pr = tag == T_PROPOSE
        can = is_pr & (st["role"] == LEADER) & self._can_propose(ctx, st)
        appended = self._append(ctx, st, can, self._propose_fields(ctx, st))
        st["nprop"] = st["nprop"] + appended
        ctx.set_timer(self.prop, T_PROPOSE, [0], when=is_pr)

        if self.halt_on_commit:
            ctx.halt_if(st["commit"] >= self.halt_on_commit)
        ctx.state = st

    # -- messages ---------------------------------------------------------
    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        N, L = self.n, self.L
        majority = self.majority
        term_in = payload[0]
        is_raft_msg = ((tag == RV) | (tag == RVR) | (tag == AE)
                       | (tag == AER) | (tag == IS))

        # a RAFT message with a higher term: step down (Raft §5.1). Gated on
        # tag — other protocols' payload[0] (e.g. a client call id) is NOT a
        # term and must not depose leaders
        higher = is_raft_msg & (term_in > st["term"])
        st["term"] = jnp.where(higher, term_in, st["term"])
        st["role"] = jnp.where(higher, FOLLOWER, st["role"])
        st["voted_for"] = jnp.where(higher, -1, st["voted_for"])

        # ---- RequestVote (§5.2, §5.4.1 up-to-date check) ----------------
        is_rv = tag == RV
        cand_len, cand_last_t = payload[1], payload[2]
        my_last_t = self._last_term(st)
        log_ok = ((cand_last_t > my_last_t)
                  | ((cand_last_t == my_last_t) & (cand_len >= st["log_len"])))
        grant = (is_rv & (term_in == st["term"]) & log_ok
                 & ((st["voted_for"] == -1) | (st["voted_for"] == src)))
        st["voted_for"] = jnp.where(grant, src, st["voted_for"])
        ctx.send(src, RVR, [st["term"], grant.astype(jnp.int32)], when=is_rv)

        # ---- RequestVote reply ------------------------------------------
        is_rvr = ((tag == RVR) & (st["role"] == CANDIDATE)
                  & (term_in == st["term"]) & (payload[1] == 1))
        st["votes"] = st["votes"] + is_rvr
        become_leader = is_rvr & (st["votes"] == majority)  # fires exactly once
        st["role"] = jnp.where(become_leader, LEADER, st["role"])
        st["next_idx"] = jnp.where(become_leader,
                                   jnp.full((N,), 1, jnp.int32)
                                   * st["log_len"], st["next_idx"])
        st["match_idx"] = jnp.where(
            become_leader,
            jnp.where(row_onehot(N, ctx.node), st["log_len"], 0),
            st["match_idx"])
        st["hgen"] = st["hgen"] + become_leader
        ctx.set_timer(0, T_HEARTBEAT, [st["hgen"]], when=become_leader)
        self._on_become_leader(ctx, st, become_leader)

        # ---- AppendEntries (§5.3) ---------------------------------------
        K, F = self.ae_batch, len(self.ENTRY_FIELDS)
        is_ae = tag == AE
        is_is = tag == IS
        prev, prev_t = payload[1], payload[2]
        lcommit, cnt_in = payload[3], payload[4]
        from_leader = (is_ae | is_is) & (term_in == st["term"])
        # a candidate discovering the elected leader returns to follower
        st["role"] = jnp.where(from_leader & (st["role"] == CANDIDATE),
                               FOLLOWER, st["role"])
        sl = st["snap_len"]
        # absolute indices < snap_len are committed, snapshotted state:
        # the prefix check passes there by State Machine Safety; above it,
        # compare the term stored in the sliding window (slot = abs - sl)
        prev_ok = (prev <= sl) | (
            (prev <= st["log_len"])
            & (take1(st["log_term"],
                     jnp.clip(prev - 1 - sl, 0, L - 1)) == prev_t))
        ok = (is_ae & (term_in == st["term"])) & prev_ok & (
            (cnt_in == 0) | (prev - sl < L))
        # accept the batched entries in order (static unroll over K).
        # cur_len threads the §5.3 conflict-truncation through the batch:
        # a term mismatch at slot prev+j truncates the suffix to prev+j+1,
        # later entries of the SAME batch then extend it again.
        cur_len = st["log_len"]
        n_acc = jnp.zeros_like(st["log_len"])
        for j in range(K):
            e_term_j = payload[5 + j * (1 + F)]
            absn = prev + j
            # covered: inside the window (entries below the snapshot are
            # already covered by it — they count toward match but are
            # never written)
            covered_j = ok & (j < cnt_in) & (absn - sl < L)
            valid_j = covered_j & (absn >= sl)
            widx = jnp.clip(absn - sl, 0, L - 1)
            conflict_j = valid_j & (absn < cur_len) & (
                take1(st["log_term"], widx) != e_term_j)
            st["log_term"] = put_row(st["log_term"], widx, e_term_j,
                                     valid_j)
            for i, f in enumerate(self.ENTRY_FIELDS):
                st[f"log_{f}"] = put_row(st[f"log_{f}"], widx,
                                         payload[6 + j * (1 + F) + i],
                                         valid_j)
            cur_len = jnp.where(
                valid_j,
                jnp.where(conflict_j, absn + 1,
                          jnp.maximum(cur_len, absn + 1)),
                cur_len)
            n_acc = n_acc + covered_j
        new_len = cur_len
        st["log_len"] = new_len
        # match reports the contiguous covered prefix (snapshot floor +
        # accepted batch) so the leader's next_idx advances
        match = jnp.where(ok, jnp.maximum(sl, prev + n_acc), 0)
        # commit = min(leaderCommit, index of last VERIFIED entry) —
        # Figure 2's "last new entry", which here is `match`, NOT the
        # follower's log length: an uncommitted stale suffix beyond the
        # verified prefix must not be committed just because
        # leaderCommit is numerically past it (State Machine Safety)
        st["commit"] = jnp.where(
            ok, jnp.maximum(st["commit"], jnp.minimum(lcommit, match)),
            st["commit"])

        # ---- InstallSnapshot (§7, follower side) ------------------------
        # Adopt the leader's compacted prefix; keep our suffix only if it
        # extends the snapshot with a matching last-included entry,
        # otherwise the whole log is superseded.
        s_len, s_term, s_dig = payload[1], payload[2], payload[3]
        want = is_is & (term_in == st["term"]) & (s_len > sl)
        inst = want & self._install_ready(ctx, st, want, payload)
        have_suffix = inst & (st["log_len"] >= s_len) & (
            take1(st["log_term"],
                  jnp.clip(s_len - 1 - sl, 0, L - 1)) == s_term)
        keep_len = jnp.where(inst,
                             jnp.where(have_suffix, st["log_len"], s_len),
                             st["log_len"])
        self._shift_log(st, jnp.where(inst, s_len - sl, 0),
                        keep_len - jnp.where(inst, s_len, sl))
        st["log_len"] = keep_len
        st["snap_len"] = jnp.where(inst, s_len, st["snap_len"])
        st["snap_term"] = jnp.where(inst, s_term, st["snap_term"])
        st["snap_digest"] = jnp.where(inst, s_dig, st["snap_digest"])
        st["commit"] = jnp.where(inst, jnp.maximum(st["commit"], s_len),
                                 st["commit"])
        self._install_extra(ctx, st, inst, payload)

        # AE and IS replies share the AER slot (mutually exclusive tags).
        # The IS match reports the POST-install snap_len: an installed
        # snapshot advances the leader past it; a partially staged chunked
        # snapshot reports the old boundary so the leader keeps sending.
        aer_ok = jnp.where(is_is, 1, ok.astype(jnp.int32))
        aer_match = jnp.where(is_is, st["snap_len"], match)
        ctx.send(src, AER, [st["term"], aer_ok, aer_match],
                 when=is_ae | is_is)

        # ---- AppendEntries reply (leader side) --------------------------
        is_aer = ((tag == AER) & (st["role"] == LEADER)
                  & (term_in == st["term"]))
        succ = payload[1] == 1
        mlen = payload[2]
        old_match = take1(st["match_idx"], src)
        old_next = take1(st["next_idx"], src)
        new_match = jnp.where(is_aer & succ,
                              jnp.maximum(old_match, mlen), old_match)
        st["match_idx"] = put_row(st["match_idx"], src, new_match)
        st["next_idx"] = put_row(
            st["next_idx"], src,
            jnp.where(is_aer & succ, jnp.maximum(old_next, new_match),
                      jnp.where(is_aer & ~succ,
                                jnp.maximum(old_next - 1, 0), old_next)))
        # advance commit: majority-replicated entries of the current term
        # (§5.4.2 — never commit prior-term entries by counting). Slot k
        # holds absolute entry snap_len + k; match_idx is absolute.
        ks = jnp.arange(L, dtype=jnp.int32)
        abs_idx = st["snap_len"] + ks
        replicated = (st["match_idx"][None, :] >= abs_idx[:, None] + 1)
        cnt = replicated.sum(axis=1)
        committable = ((cnt >= majority) & (abs_idx < st["log_len"])
                       & (st["log_term"] == st["term"]))
        best = jnp.max(jnp.where(committable, abs_idx + 1, 0))
        prev_commit = st["commit"]
        st["commit"] = jnp.where(is_aer,
                                 jnp.maximum(st["commit"], best), st["commit"])
        self._on_leader_commit(ctx, st, prev_commit, is_aer)
        self._on_commit_progress(ctx, st, ok | is_aer | inst)

        # ---- election timer reset (vote granted or live leader heard) ---
        self._arm_election(ctx, st, grant | from_leader)
        self._extra_message(ctx, st, src, tag, payload)
        # compaction rides commit advancement: followers after AE, the
        # leader after AER (self-propose commits also flow through AER)
        self._maybe_compact(ctx, st, ok | is_aer)
        if self.halt_on_commit:
            ctx.halt_if(st["commit"] >= self.halt_on_commit)
        ctx.state = st


def window_slides_for(raft_kw) -> bool:
    """The `raft_invariant(window_slides=...)` gate for runtime builders,
    in ONE place next to the rule's definition: the log window can slide
    iff compaction is enabled (`compact_threshold > 0`) — without a
    compacting leader, no InstallSnapshot can arrive to slide it either.
    Builders that support compaction pass their raft kwargs here; any
    new knob that can raise snap_len must be added HERE, not at the
    call sites."""
    return bool(raft_kw.get("compact_threshold", 0))


def raft_invariant(n_nodes: int, log_capacity: int = 32, fields=("cmd",),
                   raft_nodes=None, window_slides: bool = True):
    """Global safety checks, evaluated after every event.

    Election Safety: at most one leader per term — the task.rs analog would
    be MadRaft's test asserting one leader (this is the §5.2 property).
    State Machine Safety: committed prefixes agree pairwise (§5.4.3).

    raft_nodes: optional bool mask [N] restricting the checks to the raft
    peers in mixed clusters (client nodes share the schema but not the
    protocol).

    window_slides: STATIC choice of the prefix-agreement form. True (the
    sound default) uses the pairwise [N,N,L+1] chain evaluation — correct
    for any snap_len configuration. False asserts the builder KNOWS the
    log window never slides (compact_threshold=0 and therefore no
    InstallSnapshot either: with no compacting leader, s_len > snap_len
    never arrives), and uses the commit-sorted ADJACENT chain check —
    O(N·L + N²) instead of O(N²·L), the width-tax fix of DESIGN §5. The
    two are coverage-equivalent (up to int32-hash collision) ONLY when
    snap_len ≡ 0: a slid window can void an adjacent link (a node
    compacted past its sorted predecessor's commit) and a voided link
    breaks the transitivity that the pairwise form does not need — a
    code-review-confirmed soundness gap, hence the static gate rather
    than a dynamic fallback (under vmap both cond branches would run).
    """
    N, L = n_nodes, log_capacity
    eye = jnp.eye(N, dtype=bool)
    peer = (jnp.ones((N,), bool) if raft_nodes is None
            else jnp.asarray(raft_nodes, bool))
    powP = _pow_table(L)
    ipowP = _pow_table(L, DIGEST_P_INV)

    def invariant(state):
        ns = state.node_state
        role, term = ns["role"], ns["term"]
        leader = (role == LEADER) & peer
        same_term = term[:, None] == term[None, :]
        two_leaders = (leader[:, None] & leader[None, :] & same_term
                       & ~eye).any()

        sl = jnp.where(peer, ns["snap_len"], 0)
        loglen = jnp.where(peer, ns["log_len"], 0)
        # effective commit: the snapshot is applied state, so it floors the
        # commit index (covers the restart window before init re-raises it)
        ec = jnp.maximum(jnp.where(peer, ns["commit"], 0), sl)
        dig = ns["snap_digest"]
        h = entry_hash(ns["log_term"], [ns[f"log_{f}"] for f in fields])

        # State Machine Safety via PREFIX DIGEST CHAINS. Define, per node,
        #   chain(t) = P^t * (snap_digest + sum_{k<t} h[k] * P^{-(k+1)})
        #            = snap_digest * P^t + sum_{k<t} h[k] * P^{t-1-k}
        # — the digest of the whole absolute prefix [0, snap_len + t), by
        # the same recurrence _maybe_compact folds with (so chain values at
        # a fixed ABSOLUTE index are invariant under window slides; P is
        # odd, hence invertible mod 2^32, which is what makes the cumsum
        # form exact in int32 wraparound arithmetic). Committed prefixes
        # agree iff both nodes' chains agree at the deepest common
        # committed point a = min(ec_i, ec_j) — chain equality at a point
        # means prefix equality up to it, up to int32-hash collision (the
        # stance the digest design already takes for compacted history).
        S = jnp.cumsum(h * ipowP[None, 1:L + 1], axis=1)        # [N, L]
        S = jnp.concatenate([jnp.zeros((N, 1), jnp.int32), S], axis=1)
        chain = powP[None, :] * (dig[:, None] + S)              # [N, L+1]
        ts = jnp.arange(L + 1, dtype=jnp.int32)
        if window_slides:
            # sound for any snap_len: evaluate every pair at its own
            # deepest common committed point (one [N,N,L+1] one-hot)
            pair = peer[:, None] & peer[None, :] & ~eye
            a = jnp.minimum(ec[:, None], ec[None, :])           # [N, N] sym
            t_i = a - sl[:, None]       # evaluation point in i's window
            ok_i = (t_i >= 0) & (t_i <= L)
            oh = jnp.clip(t_i, 0, L)[:, :, None] == ts          # [N,N,L+1]
            ci = jnp.where(oh, chain[:, None, :], 0).sum(-1)    # chain_i(a)
            cj = ci.T                   # a is symmetric: chain_j at a_ij
            mismatch = (pair & ok_i & ok_i.T & (ci != cj)).any()
        else:
            # window statically pinned at zero: check along the
            # COMMIT-SORTED ADJACENT CHAIN — node k+1 agrees with node k
            # at ec_k; with every link evaluable (sl == 0 always), adjacent
            # agreement composes transitively to every pair. TWO [N,L+1]
            # evaluations + [N]-vector permutes replace the [N,N,L+1]
            # product (which replaced the r2 entry-by-entry [N,N,L]
            # aligned gather, 78% of the TPU step at the time).
            # X_i = chain_i at its OWN ec (in-window: 0 <= ec <= log_len <= L)
            ohX = (ec - sl)[:, None] == ts
            X = jnp.where(ohX, chain, 0).sum(-1)                # [N]
            # sorted order over peers (non-peers pushed last, never checked)
            imax = jnp.asarray(2**31 - 1, jnp.int32)
            order = jnp.argsort(jnp.where(peer, ec, imax))      # [N]
            ids = jnp.arange(N, dtype=jnp.int32)
            rank = jnp.where(ids[None, :] == order[:, None], ids[:, None],
                             0).sum(0)                          # rank[node]
            ec_sorted = take1(ec, order)
            # prev_ec[i] = ec of the peer ranked immediately below i
            prev_ec = take1(ec_sorted, jnp.clip(rank - 1, 0, N - 1))
            prev_node = take1(order, jnp.clip(rank - 1, 0, N - 1))
            tY = prev_ec - sl           # my evaluation point for the link
            okY = (tY >= 0) & (tY <= L)     # belt-and-braces; sl == 0
            ohY = jnp.clip(tY, 0, L)[:, None] == ts
            Y = jnp.where(ohY, chain, 0).sum(-1)                # [N]
            X_prev = take1(X, jnp.clip(prev_node, 0, N - 1))
            link = peer & take1(peer, jnp.clip(prev_node, 0, N - 1)) \
                & (rank > 0) & okY
            mismatch = (link & (Y != X_prev)).any()

        commit_gt = (ec > loglen).any()

        bad = two_leaders | mismatch | commit_gt
        code = jnp.where(
            two_leaders, CRASH_TWO_LEADERS,
            jnp.where(mismatch, CRASH_LOG_MISMATCH, CRASH_COMMIT_GT_LOG))
        return bad, code

    return invariant


def make_raft_runtime(n_nodes=5, log_capacity=32, n_cmds=8,
                      halt_on_commit=0, scenario=None, cfg=None, **raft_kw):
    """Convenience constructor for a Raft fuzzing runtime."""
    from ..core.types import SimConfig, sec
    from ..runtime.runtime import Runtime
    if cfg is None:
        cfg = SimConfig(n_nodes=n_nodes, event_capacity=256,
                        time_limit=sec(10))
    prog = Raft(n_nodes, log_capacity, n_cmds, halt_on_commit, **raft_kw)
    return Runtime(cfg, [prog], state_spec(n_nodes, log_capacity),
                   scenario=scenario,
                   invariant=raft_invariant(
                       n_nodes, log_capacity,
                       window_slides=window_slides_for(raft_kw)),
                   persist=persist_spec())
