"""Percolator-lite transactions — the gray-failure flagship (r17).

A two-shard transactional KV with Percolator's shape (primary/secondary
locks, snapshot reads, lazy commit of secondaries, TTL-based lock
cleanup) and two LITE simplifications that make its snapshot-isolation
invariant precisely the thing asymmetric partitions and skewed clocks
violate:

  1. **Timestamps come from each node's LOCAL clock** (`ctx.now` — which
     the r17 skew plane drifts), not a timestamp oracle. Without skew the
     prewrite conflict check (`write_ts >= start_ts` fails the prewrite)
     still serializes writers per key, so the no-fault baseline is green;
     WITH skew, cross-key timestamp inversions become reachable.
  2. **Lock cleanup never consults the primary.** A reader that finds a
     lock older than `ttl` (by the SERVER's local clock) rolls it back in
     place. Real Percolator rolls FORWARD when the primary committed;
     lite rolls back blindly — so a committed-primary transaction whose
     secondary commit was delayed (slow disk), dropped (one-way cut), or
     whose lock expired early (fast server clock) loses its secondary
     write. The kept fraction of the transaction stays visible: a
     fractured write.

The oracle is bank-style total conservation under snapshot reads: every
client audits by snapshot-reading ALL keys at one timestamp and crashes
the trajectory (CRASH_SNAPSHOT) if the balances don't sum to the initial
total. Two versions per key are retained; an audit whose snapshot
predates both retained versions honestly aborts (R_RETRY) instead of
fabricating history, so the oracle has no false positives.

Durability: committed writes append to a WAL on the simulated fs
(fs.py), synced per commit when `sync_commits=True`. Lock state is
process memory and dies with the server — a killed server's in-flight
transactions are aborted by client timeouts. `sync_commits=False` is the
crash-rich configuration (group commit without the group): acked commits
ride the page cache, so kills — and especially TORN kills, which leave a
partially-written final record — lose or fracture committed state.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import fs
from ..core.api import Ctx, Program
from ..core.types import ms

# message tags
M_READ, M_READ_ACK = 1, 2
M_PREWRITE, M_PW_ACK = 3, 4
M_COMMIT, M_CM_ACK = 5, 6
M_ROLLBACK = 7
# timer tags
T_NEW, T_TO = 1, 2
# read statuses
R_OK, R_LOCKED, R_RETRY = 0, 1, 2
# client phases
PH_IDLE, PH_READ, PH_PREWRITE, PH_COMMIT, PH_AUDIT = 0, 1, 2, 3, 4

CRASH_SNAPSHOT = 501     # snapshot audit saw a fractured total

N_SERVERS = 2            # shards; server_of(key) = key % 2
LOG = 0                  # the commit WAL's fs file id
INIT_BAL = 100


def server_of(key):
    return key % N_SERVERS


def perc_state_spec(n_keys: int, log_cap: int):
    z = jnp.asarray(0, jnp.int32)
    K = n_keys
    return dict(
        **fs.fs_state(1, 3 * log_cap),
        # server: lock column (volatile — a crashed server's locks die
        # with it, clients abort on timeout)
        lock_ts=jnp.zeros((K,), jnp.int32),       # 0 = unlocked
        lock_primary=jnp.zeros((K,), jnp.int32),
        lock_data=jnp.zeros((K,), jnp.int32),
        lock_wall=jnp.zeros((K,), jnp.int32),     # LOCAL time when placed
        # server: two retained versions per key (newest + previous)
        write_ts=jnp.zeros((K,), jnp.int32),
        write_val=jnp.full((K,), INIT_BAL, jnp.int32),
        prev_ts=jnp.zeros((K,), jnp.int32),
        prev_val=jnp.full((K,), INIT_BAL, jnp.int32),
        log_n=z,
        # client txn driver
        c_phase=z, c_ts=z, c_cts=z, c_k1=z, c_k2=z, c_amt=z,
        c_v1=z, c_v2=z, c_got=z, c_pw=z,
        a_got=z, a_sum=z,
        c_opn=z, c_done=z,
    )


def perc_persist_spec():
    """Only the fs disk view survives kill/restart — the commit WAL is
    the server's sole stable storage; locks and version caches rebuild
    from it at boot."""
    vol = dict(lock_ts=False, lock_primary=False, lock_data=False,
               lock_wall=False, write_ts=False, write_val=False,
               prev_ts=False, prev_val=False, log_n=False,
               c_phase=False, c_ts=False, c_cts=False, c_k1=False,
               c_k2=False, c_amt=False, c_v1=False, c_v2=False,
               c_got=False, c_pw=False, a_got=False, a_sum=False,
               c_opn=False, c_done=False)
    return dict(fs.fs_persist(), **vol)


class PercServer(Program):
    def __init__(self, n_keys: int, log_cap: int, ttl=ms(80),
                 sync_commits: bool = True):
        self.K = n_keys
        self.W = log_cap
        self.ttl = ttl
        self.sync_commits = sync_commits

    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        # recovery: mount the disk and replay the commit WAL in append
        # order — write/prev columns rebuild purely from durable records
        fs.mount(st)
        nrec = fs.file_len(st, LOG) // 3
        kid = jnp.arange(self.K, dtype=jnp.int32)
        for i in range(self.W):
            rec = fs.read_at(st, LOG, 3 * i, 3)
            k, ts, val = rec[0], rec[1], rec[2]
            ok = jnp.asarray(i, jnp.int32) < nrec
            oh = (kid == jnp.clip(k, 0, self.K - 1)) & ok
            st["prev_ts"] = jnp.where(oh, st["write_ts"], st["prev_ts"])
            st["prev_val"] = jnp.where(oh, st["write_val"], st["prev_val"])
            st["write_ts"] = jnp.where(oh, ts, st["write_ts"])
            st["write_val"] = jnp.where(oh, val, st["write_val"])
        st["log_n"] = nrec
        ctx.state = st

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        K = self.K
        local_now = ctx.now                       # the SKEWED clock

        # ---- PREWRITE [start_ts, key, val, primary] ---------------------
        is_pw = tag == M_PREWRITE
        ts, key, val, primary = payload[0], payload[1], payload[2], payload[3]
        kc = jnp.clip(key, 0, K - 1)
        held_other = (st["lock_ts"][kc] != 0) & (st["lock_ts"][kc] != ts)
        # conflict: any retained commit at/after start_ts — with local
        # clocks this is what keeps the NO-skew baseline serializable
        conflict = st["write_ts"][kc] >= ts
        pw_ok = is_pw & ~held_other & ~conflict
        fresh = pw_ok & (st["lock_ts"][kc] == 0)
        st["lock_ts"] = st["lock_ts"].at[kc].set(
            jnp.where(fresh, ts, st["lock_ts"][kc]))
        st["lock_primary"] = st["lock_primary"].at[kc].set(
            jnp.where(fresh, primary, st["lock_primary"][kc]))
        st["lock_data"] = st["lock_data"].at[kc].set(
            jnp.where(fresh, val, st["lock_data"][kc]))
        st["lock_wall"] = st["lock_wall"].at[kc].set(
            jnp.where(fresh, local_now, st["lock_wall"][kc]))
        ctx.send(src, M_PW_ACK, [ts, key, pw_ok.astype(jnp.int32)],
                 when=is_pw)

        # ---- COMMIT [start_ts, commit_ts, key] --------------------------
        is_cm = tag == M_COMMIT
        cts = payload[1]
        ck = jnp.clip(jnp.where(is_cm, payload[2], 0), 0, K - 1)
        held = is_cm & (st["lock_ts"][ck] == ts)
        # promote: prev <- cur, cur <- (commit_ts, locked data)
        st["prev_ts"] = st["prev_ts"].at[ck].set(
            jnp.where(held, st["write_ts"][ck], st["prev_ts"][ck]))
        st["prev_val"] = st["prev_val"].at[ck].set(
            jnp.where(held, st["write_val"][ck], st["prev_val"][ck]))
        st["write_ts"] = st["write_ts"].at[ck].set(
            jnp.where(held, cts, st["write_ts"][ck]))
        st["write_val"] = st["write_val"].at[ck].set(
            jnp.where(held, st["lock_data"][ck], st["write_val"][ck]))
        st["lock_ts"] = st["lock_ts"].at[ck].set(
            jnp.where(held, 0, st["lock_ts"][ck]))
        # durable commit record (key, commit_ts, val); sync per commit
        # unless running the group-commit crash-rich configuration
        wrote = fs.write_all_at(
            st, LOG, 3 * st["log_n"],
            jnp.stack([ck, cts, st["write_val"][ck]]), when=held)
        if self.sync_commits:
            fs.sync_all(st, LOG, when=wrote)
        st["log_n"] = st["log_n"] + wrote
        cm_ok = held | (is_cm & (st["write_ts"][ck] == cts))  # idempotent
        ctx.send(src, M_CM_ACK, [ts, payload[2], cm_ok.astype(jnp.int32)],
                 when=is_cm)

        # ---- ROLLBACK [start_ts, key] -----------------------------------
        is_rb = tag == M_ROLLBACK
        rk = jnp.clip(jnp.where(is_rb, payload[1], 0), 0, K - 1)
        undo = is_rb & (st["lock_ts"][rk] == ts)
        st["lock_ts"] = st["lock_ts"].at[rk].set(
            jnp.where(undo, 0, st["lock_ts"][rk]))

        # ---- READ [ts, key] ---------------------------------------------
        is_rd = tag == M_READ
        rts = payload[0]
        dk = jnp.clip(jnp.where(is_rd, payload[1], 0), 0, K - 1)
        blocked = is_rd & (st["lock_ts"][dk] != 0) & (st["lock_ts"][dk] <= rts)
        # THE LITE HOLE: an expired lock (by this server's possibly-skewed
        # local clock) is rolled back in place — no primary consult, so a
        # committed-primary transaction's secondary write dies here
        expired = blocked & (local_now - st["lock_wall"][dk] > self.ttl)
        st["lock_ts"] = st["lock_ts"].at[dk].set(
            jnp.where(expired, 0, st["lock_ts"][dk]))
        blocked = blocked & ~expired
        cur_vis = st["write_ts"][dk] <= rts
        prev_vis = st["prev_ts"][dk] <= rts
        status = jnp.where(
            blocked, R_LOCKED,
            jnp.where(cur_vis | prev_vis, R_OK, R_RETRY))
        rval = jnp.where(cur_vis, st["write_val"][dk], st["prev_val"][dk])
        ctx.send(src, M_READ_ACK, [rts, payload[1], status, rval],
                 when=is_rd)
        ctx.state = st


class PercClient(Program):
    """Alternates transfer transactions (move `amt` between two random
    keys through the 2PC lock protocol) with snapshot AUDITS (read every
    key at one timestamp; the balance total is the SI oracle)."""

    def __init__(self, n_keys: int, n_ops: int, timeout=ms(60),
                 think=ms(10)):
        self.K = n_keys
        self.O = n_ops
        self.timeout = timeout
        self.think = think
        self.total = n_keys * INIT_BAL

    def init(self, ctx: Ctx):
        ctx.set_timer(ctx.randint(0, ms(20)), T_NEW, [0])

    # -- txn driver --------------------------------------------------------
    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        K = self.K
        start = ((tag == T_NEW) & (st["c_phase"] == PH_IDLE)
                 & (st["c_opn"] < self.O))
        # timestamps are LOCAL — the lite design choice skew attacks
        ts = ctx.now + 1
        audit = start & (st["c_opn"] % 3 == 2)
        xfer = start & ~audit
        k1 = ctx.randint(0, K - 1)
        k2 = jnp.mod(k1 + 1 + ctx.randint(0, K - 2), K)   # distinct
        st["c_ts"] = jnp.where(start, ts, st["c_ts"])
        st["c_k1"] = jnp.where(xfer, k1, st["c_k1"])
        st["c_k2"] = jnp.where(xfer, k2, st["c_k2"])
        st["c_amt"] = jnp.where(xfer, 1 + ctx.randint(0, 2), st["c_amt"])
        st["c_got"] = jnp.where(start, 0, st["c_got"])
        st["c_pw"] = jnp.where(start, 0, st["c_pw"])
        st["a_got"] = jnp.where(start, 0, st["a_got"])
        st["a_sum"] = jnp.where(start, 0, st["a_sum"])
        st["c_phase"] = jnp.where(xfer, PH_READ,
                                  jnp.where(audit, PH_AUDIT, st["c_phase"]))
        ctx.send(server_of(k1), M_READ, [ts, k1], when=xfer)
        ctx.send(server_of(k2), M_READ, [ts, k2], when=xfer)
        for k in range(K):
            ctx.send(server_of(k), M_READ, [ts, k], when=audit)
        ctx.set_timer(self.timeout, T_TO, [ts], when=start)

        # timeout: abort whatever is in flight. Rollbacks are best-effort
        # (they can be lost to the same faults that caused the timeout —
        # stuck locks are then the TTL cleanup's problem, by design)
        to = ((tag == T_TO) & (st["c_phase"] != PH_IDLE)
              & (payload[0] == st["c_ts"]))
        undoing = to & ((st["c_phase"] == PH_PREWRITE)
                        | (st["c_phase"] == PH_COMMIT))
        ctx.send(server_of(st["c_k1"]), M_ROLLBACK,
                 [st["c_ts"], st["c_k1"]], when=undoing)
        ctx.send(server_of(st["c_k2"]), M_ROLLBACK,
                 [st["c_ts"], st["c_k2"]], when=undoing)
        self._complete(ctx, st, to)
        ctx.state = st

    def _complete(self, ctx, st, done):
        st["c_phase"] = jnp.where(done, PH_IDLE, st["c_phase"])
        st["c_opn"] = st["c_opn"] + done
        st["c_done"] = jnp.where(st["c_opn"] >= self.O, 1, st["c_done"])
        ctx.set_timer(self.think, T_NEW, [0],
                      when=done & (st["c_opn"] < self.O))

    # -- protocol replies --------------------------------------------------
    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        ts_match = payload[0] == st["c_ts"]

        # READ_ACK [ts, key, status, val] — transfer read phase
        is_rd = (tag == M_READ_ACK) & ts_match
        rd_x = is_rd & (st["c_phase"] == PH_READ)
        key, status, val = payload[1], payload[2], payload[3]
        bad = status != R_OK
        hit1 = rd_x & (key == st["c_k1"]) & ((st["c_got"] & 1) == 0)
        hit2 = rd_x & (key == st["c_k2"]) & ((st["c_got"] & 2) == 0)
        st["c_v1"] = jnp.where(hit1 & ~bad, val, st["c_v1"])
        st["c_v2"] = jnp.where(hit2 & ~bad, val, st["c_v2"])
        st["c_got"] = (st["c_got"] | jnp.where(hit1 & ~bad, 1, 0)
                       | jnp.where(hit2 & ~bad, 2, 0))
        # a locked/too-new key aborts the transfer (no rollback needed:
        # nothing is locked yet)
        self._complete(ctx, st, rd_x & bad)
        st["c_phase"] = jnp.where(rd_x & bad, PH_IDLE, st["c_phase"])
        both = (st["c_phase"] == PH_READ) & (st["c_got"] == 3)
        st["c_phase"] = jnp.where(both, PH_PREWRITE, st["c_phase"])
        # prewrite both, k1 is the primary
        ctx.send(server_of(st["c_k1"]), M_PREWRITE,
                 [st["c_ts"], st["c_k1"], st["c_v1"] - st["c_amt"],
                  st["c_k1"]], when=both)
        ctx.send(server_of(st["c_k2"]), M_PREWRITE,
                 [st["c_ts"], st["c_k2"], st["c_v2"] + st["c_amt"],
                  st["c_k1"]], when=both)

        # READ_ACK — audit phase: accumulate the snapshot total
        rd_a = is_rd & (st["c_phase"] == PH_AUDIT)
        kb = 1 << jnp.clip(key, 0, 30)
        hit_a = rd_a & ~bad & ((st["a_got"] & kb) == 0)
        st["a_sum"] = st["a_sum"] + jnp.where(hit_a, val, 0)
        st["a_got"] = st["a_got"] | jnp.where(hit_a, kb, 0)
        self._complete(ctx, st, rd_a & bad)       # honest abort, no oracle
        st["c_phase"] = jnp.where(rd_a & bad, PH_IDLE, st["c_phase"])
        full = (1 << self.K) - 1
        audited = (st["c_phase"] == PH_AUDIT) & (st["a_got"] == full)
        # THE ORACLE: a complete snapshot must conserve the total
        ctx.crash_if(audited & (st["a_sum"] != self.total), CRASH_SNAPSHOT)
        self._complete(ctx, st, audited)
        st["c_phase"] = jnp.where(audited, PH_IDLE, st["c_phase"])

        # PW_ACK [ts, key, ok]
        is_pw = ((tag == M_PW_ACK) & ts_match
                 & (st["c_phase"] == PH_PREWRITE))
        pw_fail = is_pw & (payload[2] == 0)
        ctx.send(server_of(st["c_k1"]), M_ROLLBACK,
                 [st["c_ts"], st["c_k1"]], when=pw_fail)
        ctx.send(server_of(st["c_k2"]), M_ROLLBACK,
                 [st["c_ts"], st["c_k2"]], when=pw_fail)
        self._complete(ctx, st, pw_fail)
        st["c_phase"] = jnp.where(pw_fail, PH_IDLE, st["c_phase"])
        got1 = is_pw & ~pw_fail & (payload[1] == st["c_k1"])
        got2 = is_pw & ~pw_fail & (payload[1] == st["c_k2"])
        st["c_pw"] = (st["c_pw"] | jnp.where(got1, 1, 0)
                      | jnp.where(got2, 2, 0))
        locked = (st["c_phase"] == PH_PREWRITE) & (st["c_pw"] == 3)
        st["c_phase"] = jnp.where(locked, PH_COMMIT, st["c_phase"])
        cts = jnp.maximum(ctx.now, st["c_ts"] + 1)    # local again
        st["c_cts"] = jnp.where(locked, cts, st["c_cts"])
        # commit the PRIMARY first; secondaries follow lazily
        ctx.send(server_of(st["c_k1"]), M_COMMIT,
                 [st["c_ts"], st["c_cts"], st["c_k1"]], when=locked)

        # CM_ACK [ts, key, ok] — primary outcome decides the txn
        is_cm = ((tag == M_CM_ACK) & ts_match
                 & (st["c_phase"] == PH_COMMIT)
                 & (payload[1] == st["c_k1"]))
        cm_ok = is_cm & (payload[2] != 0)
        # LAZY secondary commit: fire-and-forget — if this message is
        # lost (one-way cut) or outrun by the TTL (slow disk, fast
        # server clock), the secondary lock dies by cleanup and the
        # transaction fractures. That is the bug surface, by design.
        ctx.send(server_of(st["c_k2"]), M_COMMIT,
                 [st["c_ts"], st["c_cts"], st["c_k2"]], when=cm_ok)
        # primary lock was cleaned under us: txn aborted — release k2
        ctx.send(server_of(st["c_k2"]), M_ROLLBACK,
                 [st["c_ts"], st["c_k2"]], when=is_cm & ~cm_ok)
        self._complete(ctx, st, is_cm)
        st["c_phase"] = jnp.where(is_cm, PH_IDLE, st["c_phase"])
        ctx.cancel_timer(T_TO, when=is_cm)
        ctx.state = st


def clients_done(n_nodes: int):
    def check(state):
        return (state.node_state["c_done"][N_SERVERS:n_nodes] == 1).all()
    return check


def make_percolator_runtime(n_clients=3, n_ops=9, n_keys=6, ttl=ms(80),
                            sync_commits=True, scenario=None, cfg=None):
    """2 shard servers (nodes 0, 1; key % 2) + `n_clients` txn clients.
    Green with no faults injected; the gray-failure recipes
    (runtime/chaos.py) break its snapshot-isolation oracle by design."""
    from ..core.types import NetConfig, SimConfig, sec
    from ..runtime.runtime import Runtime
    n = N_SERVERS + n_clients
    # every op commits at most 2 records; margin for retries
    log_cap = 2 * n_clients * n_ops + 8
    if cfg is None:
        cfg = SimConfig(n_nodes=n, event_capacity=256, payload_words=8,
                        time_limit=sec(10),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(8)))
    server = PercServer(n_keys, log_cap, ttl=ttl,
                        sync_commits=sync_commits)
    client = PercClient(n_keys, n_ops)
    node_prog = np.asarray([0] * N_SERVERS + [1] * n_clients, np.int32)
    return Runtime(cfg, [server, client],
                   perc_state_spec(n_keys, log_cap),
                   node_prog=node_prog, scenario=scenario,
                   persist=perc_persist_spec(),
                   halt_when=clients_done(n))
