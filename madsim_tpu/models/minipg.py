"""minipg — a postgres-shaped session protocol over the sim TCP stack.

The reference's strongest ecosystem claim is that an UNMODIFIED complex
client protocol (tokio-postgres: startup/auth handshake, pipelined queries,
transactions) runs over its simulated sockets
(madsim-tokio-postgres/src/socket.rs:6-13 swaps the socket; everything
above is untouched). This model is that claim rebuilt natively: one
protocol state machine with

  * a multi-phase session handshake: STARTUP -> salted-challenge AUTH ->
    READY (wrong credentials draw ERROR + connection reset),
  * PIPELINED queries: the client issues a whole transaction's statements
    without awaiting responses; the server answers strictly in order,
  * TRANSACTIONS: BEGIN / SET / GET (read-your-writes through the txn
    buffer) / COMMIT / ROLLBACK, with exactly-once commits across
    reconnect-and-retry (txn ids dedup against the last committed id),

running over the full sim TCP stack — conn.py lifecycle (SYN/SYN-ACK/RST)
+ stream.py reliable ordered framing — under kill/loss chaos, AND over
real asyncio sockets (real/runtime.py) with the SAME code: the dual-world
contract, proven by tests/test_minipg.py + tests/test_real_runtime.py.

Client-side oracles (ctx.crash_if): response statuses per pipeline
position, read-your-writes inside transactions, committed-state visibility
after COMMIT, rollback invisibility — so a run completing IS the
correctness assertion.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.api import Ctx, Program
from ..core.types import ms
from ..net import conn, stream

# wire frames (ride reliable stream items): [mtype, a, b, c, d]
M_STARTUP, M_AUTHREQ, M_AUTH, M_READY, M_ERROR, M_QUERY, M_RESULT = \
    1, 2, 3, 4, 5, 6, 7
FRAME_WORDS = 5
PROTO_VER = 3          # a nod to the postgres v3 protocol

# query ops
OP_BEGIN, OP_SET, OP_GET, OP_COMMIT, OP_ROLLBACK = 1, 2, 3, 4, 5
# result statuses
ST_OK, ST_VAL, ST_ERR, ST_DUP = 1, 2, 3, 4

# session phases (server, per client)
S_NONE, S_AWAIT_AUTH, S_READY = 0, 1, 2

CRASH_BAD_STATUS = 401
CRASH_TXN_READ = 402
CRASH_VISIBILITY = 403

SERVER = 0
TXN_BUF = 4            # statements a transaction may buffer
RING = 12              # server response backpressure ring, per client
OPS_PER_TXN = 6        # BEGIN, SET, GET, SET, COMMIT|ROLLBACK, verify-GET

AUTH_MIX = 1540483477  # odd multiplier for the toy digest


def password_for(user):
    """The shared secret both sides derive (a stand-in for a password
    file); tests break it deliberately to exercise the refusal path."""
    return user * 7 + 13


def auth_digest(user, password, salt):
    return (user * AUTH_MIX + password) ^ salt


def pg_state_spec(n_nodes: int, n_keys: int, window: int = 8):
    z = jnp.asarray(0, jnp.int32)
    N = n_nodes
    return dict(
        **conn.conn_state(N),
        **stream.stream_state(N, window=window, item_words=FRAME_WORDS),
        # server: sessions
        sess=jnp.zeros((N,), jnp.int32),
        salt=jnp.zeros((N,), jnp.int32),
        susr=jnp.zeros((N,), jnp.int32),
        # server: transactions
        txn=jnp.zeros((N,), jnp.int32),
        tb_key=jnp.zeros((N, TXN_BUF), jnp.int32),
        tb_val=jnp.zeros((N, TXN_BUF), jnp.int32),
        tb_n=jnp.zeros((N,), jnp.int32),
        # server: durable storage (persist mask) — the database survives
        # power-fail; sessions and open transactions do not
        kv=jnp.zeros((n_keys,), jnp.int32),
        ltid=jnp.zeros((N,), jnp.int32),
        # server: in-order response ring (backpressure, never drop)
        rb=jnp.zeros((N, RING, FRAME_WORDS), jnp.int32),
        rb_w=jnp.zeros((N,), jnp.int32),
        rb_r=jnp.zeros((N,), jnp.int32),
        # client
        c_phase=z, c_salt=z, c_tid=jnp.asarray(1, jnp.int32),
        c_sq=z, c_rid=z, c_dup0=z,
        c_exp=jnp.zeros((2,), jnp.int32),
        c_prog=z, c_done=z, c_rej=z,
    )


def pg_persist_spec(spec):
    """Only the database (kv) and commit-dedup table (ltid) are durable."""
    return {k: k in ("kv", "ltid") for k in spec}


class PgServer(Program):
    def __init__(self, n_nodes: int, n_keys: int, tick=ms(10),
                 epoch_guard: bool = True):
        self.n = n_nodes
        self.K = n_keys
        self.tick = tick
        # r19 incarnation guard (net/conn.py, net/stream.py): True is the
        # sound default; False compiles the pre-r19 accept-everything
        # transport — the honest red control tests/test_connfault.py and
        # bench's connfault regime use to PROVE the guard is what makes
        # exactly-once survive connection churn
        self.guard = epoch_guard

    # ---- response ring (strict output order + backpressure) -------------
    def _rpush(self, st, src, words, when):
        w = st["rb_w"][src]
        slot = w % RING
        ok = jnp.asarray(when) & (w - st["rb_r"][src] < RING)
        frame = jnp.stack([jnp.asarray(x, jnp.int32) for x in words])
        st["rb"] = st["rb"].at[src, slot].set(
            jnp.where(ok, frame, st["rb"][src, slot]))
        st["rb_w"] = st["rb_w"].at[src].set(w + ok)

    def _drain(self, ctx, st):
        for c in range(1, self.n):
            for _ in range(2):     # ≤2 frames per client per event
                has = st["rb_r"][c] < st["rb_w"][c]
                slot = st["rb_r"][c] % RING
                ok = stream.send(ctx, st, c, st["rb"][c, slot], when=has)
                st["rb_r"] = st["rb_r"].at[c].set(st["rb_r"][c] + ok)

    # ---- one protocol frame ---------------------------------------------
    def _frame(self, ctx: Ctx, st, src, f, when):
        from ..utils.maskutil import needed
        mtype, a, b, c, d = f[0], f[1], f[2], f[3], f[4]
        zero = jnp.asarray(0, jnp.int32)

        # STARTUP: fresh session — void any open txn and pending output,
        # challenge with a salt
        su = when & (mtype == M_STARTUP)
        if needed(su):
            st["sess"] = st["sess"].at[src].set(
                jnp.where(su, S_AWAIT_AUTH, st["sess"][src]))
            st["susr"] = st["susr"].at[src].set(
                jnp.where(su, b, st["susr"][src]))
            st["txn"] = st["txn"].at[src].set(
                jnp.where(su, 0, st["txn"][src]))
            st["salt"] = st["salt"].at[src].set(
                jnp.where(su, ctx.randint(1, 2**30 - 1), st["salt"][src]))
            self._rpush(st, src,
                        [M_AUTHREQ, st["salt"][src], zero, zero, zero], su)

        # AUTH: verify the salted digest
        au = when & (mtype == M_AUTH) & (st["sess"][src] == S_AWAIT_AUTH)
        if needed(au):
            good = a == auth_digest(st["susr"][src],
                                    password_for(st["susr"][src]),
                                    st["salt"][src])
            st["sess"] = st["sess"].at[src].set(
                jnp.where(au & good, S_READY, st["sess"][src]))
            self._rpush(st, src, [M_READY, zero, zero, zero, zero],
                        au & good)
            # bad credentials: best-effort ERROR, then reset the connection
            stream.send(ctx, st, src, [M_ERROR, 1, 0, 0, 0],
                        when=au & ~good)
            conn.reset(ctx, st, src, when=au & ~good)
            st["sess"] = st["sess"].at[src].set(
                jnp.where(au & ~good, S_NONE, st["sess"][src]))

        # QUERY: the pipelined statement machine
        q = when & (mtype == M_QUERY) & (st["sess"][src] == S_READY)
        if not needed(q):
            return
        qid, op, key, val = a, b, jnp.clip(c, 0, self.K - 1), d
        open_ = st["txn"][src] == 1

        beg = q & (op == OP_BEGIN)
        dup = beg & (c <= st["ltid"][src])      # txn id already committed
        st["txn"] = st["txn"].at[src].set(
            jnp.where(beg & ~dup, 1, st["txn"][src]))
        st["tb_n"] = st["tb_n"].at[src].set(
            jnp.where(beg & ~dup, 0, st["tb_n"][src]))

        sets = q & (op == OP_SET) & open_
        room = st["tb_n"][src] < TXN_BUF
        wslot = jnp.clip(st["tb_n"][src], 0, TXN_BUF - 1)
        st["tb_key"] = st["tb_key"].at[src, wslot].set(
            jnp.where(sets & room, key, st["tb_key"][src, wslot]))
        st["tb_val"] = st["tb_val"].at[src, wslot].set(
            jnp.where(sets & room, val, st["tb_val"][src, wslot]))
        st["tb_n"] = st["tb_n"].at[src].set(st["tb_n"][src] + (sets & room))

        # GET reads through the txn buffer (read-your-writes), else storage
        get = q & (op == OP_GET)
        js = jnp.arange(TXN_BUF, dtype=jnp.int32)
        m = (st["tb_key"][src] == key) & (js < st["tb_n"][src]) & open_
        lastb = jnp.max(jnp.where(m, js + 1, 0))
        read = jnp.where(lastb > 0,
                         st["tb_val"][src, jnp.clip(lastb - 1, 0,
                                                    TXN_BUF - 1)],
                         st["kv"][key])

        com = q & (op == OP_COMMIT)
        cdup = com & ~open_ & (c <= st["ltid"][src])
        apply_ = com & open_
        for j in range(TXN_BUF):        # ordered buffer replay
            aj = apply_ & (j < st["tb_n"][src])
            kj = jnp.clip(st["tb_key"][src, j], 0, self.K - 1)
            st["kv"] = st["kv"].at[kj].set(
                jnp.where(aj, st["tb_val"][src, j], st["kv"][kj]))
        st["ltid"] = st["ltid"].at[src].set(
            jnp.where(apply_, jnp.maximum(st["ltid"][src], c),
                      st["ltid"][src]))
        st["txn"] = st["txn"].at[src].set(
            jnp.where(com, 0, st["txn"][src]))

        rol = q & (op == OP_ROLLBACK)
        st["txn"] = st["txn"].at[src].set(jnp.where(rol, 0, st["txn"][src]))
        st["tb_n"] = st["tb_n"].at[src].set(
            jnp.where(com | rol, 0, st["tb_n"][src]))

        status = jnp.where(
            beg, jnp.where(dup, ST_DUP, ST_OK),
            jnp.where(sets, jnp.where(room, ST_OK, ST_ERR),
                      jnp.where(get, ST_VAL,
                                jnp.where(com,
                                          jnp.where(apply_, ST_OK,
                                                    jnp.where(cdup, ST_DUP,
                                                              ST_ERR)),
                                          jnp.where(rol, ST_OK, ST_ERR)))))
        # a SET outside a txn is autocommit-disabled here: explicit ERR
        status = jnp.where(q & (op == OP_SET) & ~open_, ST_ERR, status)
        self._rpush(st, src, [M_RESULT, qid, status,
                              jnp.where(get, read, zero), zero], q)

    # ---- lifecycle -------------------------------------------------------
    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        conn.listen(ctx, st)
        ctx.set_timer(self.tick, 1, [0])
        ctx.state = st

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        self._drain(ctx, st)
        for c in range(1, self.n):
            stream.retransmit(ctx, st, c, when=True)
        ctx.set_timer(self.tick, 1, [0])
        ctx.state = st

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        from ..utils.maskutil import needed
        accept, _, rst = conn.on_message(ctx, st, src, tag, payload,
                                         epoch_guard=self.guard)
        # a (re)connecting or resetting peer voids its session and
        # pending output — new connection, new world (the conn layer
        # already rebased the stream fabric onto the negotiated
        # incarnation, r19)
        fresh = accept | rst
        if needed(fresh):
            for k in ("rb_w", "rb_r", "sess", "txn", "tb_n"):
                st[k] = st[k].at[src].set(jnp.where(fresh, 0, st[k][src]))

        vals, mask = stream.on_message(ctx, st, src, tag, payload,
                                       epoch_guard=self.guard)
        for i in stream.delivered_slots(mask):
            self._frame(ctx, st, src, vals[i], mask[i])
        self._drain(ctx, st)
        ctx.state = st


class PgClient(Program):
    """Runs n_txns pipelined transactions, verifying every response; txn
    ids make retried commits exactly-once. wrong_password exercises the
    auth-refusal path (expects ERROR/RST, never READY)."""

    def __init__(self, n_txns: int = 4, tick=ms(8), stall=ms(250),
                 wrong_password: bool = False, epoch_guard: bool = True):
        self.T = n_txns
        self.tick = tick
        self.stall = stall
        self.wrong = wrong_password
        self.guard = epoch_guard

    def _keys(self, ctx):
        base = (ctx.node - 1) * 2
        return base, base + 1

    def _val(self, ctx, tid):
        return ctx.node * 10000 + tid * 10

    def _is_commit(self, tid):
        return tid % 2 == 1

    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        st["c_prog"] = ctx.now
        ctx.set_timer(ctx.randint(0, self.tick), 1, [0])
        ctx.state = st

    def _reset_session(self, ctx, st, when):
        from ..utils.maskutil import needed
        if not needed(when):
            return
        conn.reset(ctx, st, SERVER, when=when)
        stream.reset_peer(st, SERVER, when=when)
        st["c_phase"] = jnp.where(when, 0, st["c_phase"])
        for k in ("c_sq", "c_rid", "c_dup0"):
            st[k] = jnp.where(when, 0, st[k])

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        done = st["c_done"] == 1

        # stall watchdog: tear the session down, re-handshake, re-run the
        # CURRENT txn (same tid — the server dedups a re-commit)
        stalled = ~done & (ctx.now - st["c_prog"] > self.stall)
        self._reset_session(ctx, st, stalled)
        st["c_prog"] = jnp.where(stalled, ctx.now, st["c_prog"])

        # phase 0: connect, then STARTUP
        est = conn.is_established(st, SERVER)
        conn.connect(ctx, st, SERVER, when=~done & (st["c_phase"] == 0)
                     & ~est)
        ok = stream.send(ctx, st, SERVER,
                         [M_STARTUP, PROTO_VER, ctx.node, 0, 0],
                         when=~done & (st["c_phase"] == 0) & est)
        st["c_phase"] = jnp.where(ok, 1, st["c_phase"])
        st["c_prog"] = jnp.where(ok, ctx.now, st["c_prog"])

        # phase 3: issue the pipelined statements of the current txn —
        # never waiting for a response before the next statement
        from ..utils.maskutil import needed
        tid = st["c_tid"]
        k0, k1 = self._keys(ctx)
        v = self._val(ctx, tid)
        commit = self._is_commit(tid)
        sq = st["c_sq"]
        issuing = ~done & (st["c_phase"] == 3) & (sq < OPS_PER_TXN) & (
            tid <= self.T)
        if needed(issuing):
            op = jnp.where(
                sq == 0, OP_BEGIN,
                jnp.where((sq == 1) | (sq == 3), OP_SET,
                          jnp.where(sq == 2, OP_GET,
                                    jnp.where(sq == 4,
                                              jnp.where(commit, OP_COMMIT,
                                                        OP_ROLLBACK),
                                              OP_GET))))
            key = jnp.where((sq == 0) | (sq == 4), tid,
                            jnp.where(sq == 3, k1, k0))
            val = jnp.where(sq == 1, v, jnp.where(sq == 3, v + 1000, 0))
            qid = tid * 8 + sq
            sent = stream.send(ctx, st, SERVER,
                               [M_QUERY, qid, op, key, val], when=issuing)
            st["c_sq"] = st["c_sq"] + sent
            st["c_prog"] = jnp.where(sent, ctx.now, st["c_prog"])

        stream.retransmit(ctx, st, SERVER, when=~done)
        ctx.set_timer(self.tick, 1, [0], when=True)
        ctx.state = st

    def _result(self, ctx: Ctx, st, f, when):
        from ..utils.maskutil import needed
        mtype, a, b, c = f[0], f[1], f[2], f[3]

        # handshake frames
        hs = when & ((mtype == M_AUTHREQ) | (mtype == M_READY)
                     | (mtype == M_ERROR))
        if needed(hs):
            ar = when & (mtype == M_AUTHREQ) & (st["c_phase"] == 1)
            pw = password_for(ctx.node) + (1 if self.wrong else 0)
            dig = auth_digest(ctx.node, pw, a)
            ok = stream.send(ctx, st, SERVER, [M_AUTH, dig, 0, 0, 0],
                             when=ar)
            st["c_phase"] = jnp.where(ok, 2, st["c_phase"])
            rdy = when & (mtype == M_READY) & (st["c_phase"] == 2)
            st["c_phase"] = jnp.where(rdy, 3, st["c_phase"])
            # the refusal oracle: with bad credentials READY must never come
            if self.wrong:
                ctx.crash_if(rdy, CRASH_BAD_STATUS)
            err = when & (mtype == M_ERROR)
            st["c_rej"] = jnp.where(err, 1, st["c_rej"])
            st["c_done"] = jnp.where(err & self.wrong, 1, st["c_done"])
            st["c_prog"] = jnp.where(ar | rdy | err, ctx.now, st["c_prog"])

        if not needed(when & (mtype == M_RESULT)):
            return
        # pipelined results, strictly in order: c_rid is the position
        tid = st["c_tid"]
        v = self._val(ctx, tid)
        commit = self._is_commit(tid)
        res = (when & (mtype == M_RESULT) & (st["c_phase"] == 3)
               & (st["c_done"] == 0) & (a == tid * 8 + st["c_rid"]))
        pos = st["c_rid"]
        dup0 = st["c_dup0"] == 1

        p0 = res & (pos == 0)
        ctx.crash_if(p0 & (b != ST_OK) & (b != ST_DUP), CRASH_BAD_STATUS)
        st["c_dup0"] = jnp.where(p0 & (b == ST_DUP), 1, st["c_dup0"])

        pset = res & ((pos == 1) | (pos == 3)) & ~dup0
        ctx.crash_if(pset & (b != ST_OK), CRASH_BAD_STATUS)

        # read-your-writes inside the txn
        p2 = res & (pos == 2) & ~dup0
        ctx.crash_if(p2 & ((b != ST_VAL) | (c != v)), CRASH_TXN_READ)

        p4 = res & (pos == 4)
        if True:  # commit/rollback status check
            ctx.crash_if(p4 & commit & (b != ST_OK) & (b != ST_DUP),
                         CRASH_BAD_STATUS)
            ctx.crash_if(p4 & ~commit & ~dup0 & (b != ST_OK),
                         CRASH_BAD_STATUS)
        # commit visibility: remember what the database must now hold
        landed = p4 & commit & ((b == ST_OK) | (b == ST_DUP))
        st["c_exp"] = jnp.where(landed,
                                jnp.stack([v, v + 1000]), st["c_exp"])

        # the out-of-txn verify GET must see exactly the committed state
        p5 = res & (pos == 5)
        ctx.crash_if(p5 & ((b != ST_VAL) | (c != st["c_exp"][0])),
                     CRASH_VISIBILITY)

        st["c_rid"] = st["c_rid"] + res
        st["c_prog"] = jnp.where(res, ctx.now, st["c_prog"])

        # txn complete -> next txn (or done)
        fin = res & (st["c_rid"] >= OPS_PER_TXN)
        st["c_tid"] = st["c_tid"] + fin
        for k in ("c_sq", "c_rid", "c_dup0"):
            st[k] = jnp.where(fin, 0, st[k])
        st["c_done"] = jnp.where(fin & (st["c_tid"] > self.T), 1,
                                 st["c_done"])

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        _, _, rst = conn.on_message(ctx, st, src, tag, payload,
                                    epoch_guard=self.guard)
        # server reset (or refusal): back to square one, unless we're the
        # wrong-password client, for whom RST is the expected outcome
        if self.wrong:
            st["c_rej"] = jnp.where(rst, 1, st["c_rej"])
            st["c_done"] = jnp.where(rst, 1, st["c_done"])
        else:
            self._reset_session(ctx, st,
                                rst & (st["c_done"] == 0))
        vals, mask = stream.on_message(ctx, st, src, tag, payload,
                                       epoch_guard=self.guard)
        for i in stream.delivered_slots(mask):
            self._result(ctx, st, vals[i], mask[i] & (src == SERVER))
        ctx.state = st


def clients_done(n_nodes: int):
    def check(state):
        return (state.node_state["c_done"][1:n_nodes] == 1).all()
    return check


def make_minipg_runtime(n_clients=2, n_txns=4, scenario=None, cfg=None,
                        wrong_password=False, epoch_guard=True):
    from ..core.types import NetConfig, SimConfig, sec
    from ..runtime.runtime import Runtime
    n = 1 + n_clients
    n_keys = 2 * n_clients
    if cfg is None:
        cfg = SimConfig(n_nodes=n, event_capacity=64, payload_words=8,
                        time_limit=sec(10),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(8)))
    spec = pg_state_spec(n, n_keys)
    server = PgServer(n, n_keys, epoch_guard=epoch_guard)
    client = PgClient(n_txns, wrong_password=wrong_password,
                      epoch_guard=epoch_guard)
    node_prog = np.asarray([0] + [1] * n_clients, np.int32)
    return Runtime(cfg, [server, client], spec, node_prog=node_prog,
                   scenario=scenario, persist=pg_persist_spec(spec),
                   halt_when=clients_done(n))
