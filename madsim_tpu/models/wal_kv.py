"""Durable KV server with a write-ahead log on the simulated filesystem —
the workload that makes fs.py's power-fail semantics FALSIFIABLE.

Protocol (the classic WAL + checkpoint design):
  PUT: append (key, val) to the WAL file, `sync_all`, apply to the in-memory
       table, ack. The ack therefore PROMISES durability.
  WAL full: checkpoint — write the whole table to the DB file, sync it,
       truncate the WAL (set_len 0 + sync). Exercises every fs.py call.
  Recovery (init after kill): mount(), load the table from the DB file,
       replay the WAL on top. Memory state is rebuilt purely from disk.

Clients own disjoint key ranges and write strictly increasing values, so
"a synced ack can never be un-written" becomes a per-key monotonicity
oracle: any GET observing a value below the last acked PUT for that key is
a durability violation (ctx.crash_if -> CRASH_LOST_WRITE).

`sync_wal=False` removes the one sync_all between append and ack — with
kill chaos the oracle then MUST fire (tests assert the red case too),
proving the sync gate is load-bearing, not decorative. The reference left
power-fail as TODO (fs.rs:48-51); this beats it.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import fs
from ..core.api import Ctx, Program
from ..core.types import ms

WAL, DB = 0, 1
M_PUT, M_GET, M_ACK = 1, 2, 3
T_NEW, T_RETRY = 1, 2

CRASH_LOST_WRITE = 301

SERVER = 0


def wal_state_spec(n_nodes: int, n_keys: int, wal_cap: int, keys_per_client):
    z = jnp.asarray(0, jnp.int32)
    file_words = max(2 * wal_cap, n_keys)
    return dict(
        **fs.fs_state(2, file_words),
        kv=jnp.zeros((n_keys,), jnp.int32),
        wal_n=z,
        # per-client dedup: call ids are monotonic (op index + 1), so a
        # delayed duplicate of an older PUT is acked but never re-applied.
        # Volatile is sound here: a kill drops all in-flight messages, so
        # no stale duplicate can cross a restart.
        last_cid=jnp.zeros((n_nodes,), jnp.int32),
        # client side
        c_cid=z, c_opn=z, c_wait=z, c_key=z, c_val=z, c_op=z, c_done=z,
        acked=jnp.zeros((keys_per_client,), jnp.int32),
    )


def wal_persist_spec():
    """ONLY the fs disk view persists — kv/wal_n are process memory and the
    whole point is that they die with the process."""
    vol = dict(kv=False, wal_n=False, last_cid=False, c_cid=False,
               c_opn=False, c_wait=False, c_key=False, c_val=False,
               c_op=False, c_done=False, acked=False)
    return dict(fs.fs_persist(), **vol)


class WalKvServer(Program):
    def __init__(self, n_keys: int, wal_cap: int, sync_wal: bool = True):
        self.K = n_keys
        self.W = wal_cap
        self.sync_wal = sync_wal

    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        # recovery: mount the disk, load the last checkpoint, replay the WAL
        fs.mount(st)
        db = fs.read_at(st, DB, 0, self.K)
        have_db = fs.file_len(st, DB) >= self.K
        st["kv"] = jnp.where(have_db, db, jnp.zeros_like(st["kv"]))
        recs = fs.read_at(st, WAL, 0, 2 * self.W)
        keys, vals = recs[0::2], recs[1::2]
        nrec = fs.file_len(st, WAL) // 2
        ridx = jnp.arange(self.W, dtype=jnp.int32)
        for k in range(self.K):
            m = (keys == k) & (ridx < nrec)
            last = jnp.max(jnp.where(m, ridx + 1, 0))
            st["kv"] = st["kv"].at[k].set(
                jnp.where(last > 0, vals[jnp.clip(last - 1, 0, self.W - 1)],
                          st["kv"][k]))
        st["wal_n"] = nrec
        ctx.state = st

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        cid, key, val = payload[0], payload[1], payload[2]
        kc = jnp.clip(key, 0, self.K - 1)
        is_put = tag == M_PUT
        is_get = tag == M_GET

        # WAL full -> checkpoint: table to DB (synced), truncate WAL
        ckpt = is_put & (st["wal_n"] >= self.W)
        fs.write_all_at(st, DB, 0, st["kv"], when=ckpt)
        fs.sync_all(st, DB, when=ckpt)
        fs.set_len(st, WAL, 0, when=ckpt)
        fs.sync_all(st, WAL, when=ckpt)
        st["wal_n"] = jnp.where(ckpt, 0, st["wal_n"])

        # append + sync + apply + ack (the ack promises durability — which
        # is only TRUE if sync_wal actually runs). Only FRESH puts apply:
        # duplicates/stale retries are acked without touching state.
        fresh = is_put & (cid > st["last_cid"][src])
        ok = fs.write_all_at(st, WAL, 2 * st["wal_n"],
                             jnp.stack([kc, val]), when=fresh)
        if self.sync_wal:
            fs.sync_all(st, WAL, when=ok)
        st["wal_n"] = st["wal_n"] + ok
        st["kv"] = st["kv"].at[kc].set(jnp.where(ok, val, st["kv"][kc]))
        st["last_cid"] = st["last_cid"].at[src].set(
            jnp.where(ok, cid, st["last_cid"][src]))

        reply = jnp.where(is_get, st["kv"][kc], val)
        ctx.send(src, M_ACK, [cid, reply, key], when=is_put | is_get)
        ctx.state = st


class WalKvClient(Program):
    """Alternates PUT(key, increasing val) and verifying GET(key) over its
    own key range; retries on timeout. The GET oracle: a response below the
    last acked PUT for that key means a synced write was lost."""

    def __init__(self, n_ops: int, keys_per_client: int,
                 timeout=ms(60), think=ms(8)):
        self.O = n_ops
        self.KPC = keys_per_client
        self.timeout = timeout
        self.think = think

    def _key_local(self, st):
        return (st["c_opn"] // 2) % self.KPC

    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        ctx.set_timer(ctx.randint(0, ms(20)), T_NEW, [0])
        ctx.state = st

    def _issue(self, ctx, st, when):
        key = (ctx.node - 1) * self.KPC + self._key_local(st)
        ctx.send(SERVER, jnp.where(st["c_op"] == M_PUT, M_PUT, M_GET),
                 [st["c_cid"], key, st["c_val"]], when=when)
        ctx.set_timer(self.timeout, T_RETRY, [st["c_cid"]], when=when)

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        start = ((tag == T_NEW) & (st["c_wait"] == 0)
                 & (st["c_opn"] < self.O))
        # even ops PUT a fresh (strictly increasing) value, odd ops GET it
        st["c_op"] = jnp.where(start,
                               jnp.where(st["c_opn"] % 2 == 0, M_PUT, M_GET),
                               st["c_op"])
        # monotonic call ids (op index + 1): the server's dedup can order
        # retries; a random id could not be ordered against the session
        st["c_cid"] = jnp.where(start, st["c_opn"] + 1, st["c_cid"])
        st["c_val"] = jnp.where(start & (st["c_op"] == M_PUT),
                                st["c_opn"] + 1, st["c_val"])
        st["c_wait"] = jnp.where(start, 1, st["c_wait"])
        retry = ((tag == T_RETRY) & (st["c_wait"] == 1)
                 & (payload[0] == st["c_cid"]))
        self._issue(ctx, st, start | retry)
        ctx.state = st

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        hit = ((tag == M_ACK) & (st["c_wait"] == 1)
               & (payload[0] == st["c_cid"]))
        kl = jnp.clip(self._key_local(st), 0, self.KPC - 1)
        # durability oracle: GET must observe >= the last acked PUT
        ctx.crash_if(hit & (st["c_op"] == M_GET)
                     & (payload[1] < st["acked"][kl]),
                     CRASH_LOST_WRITE)
        st["acked"] = st["acked"].at[kl].set(
            jnp.where(hit & (st["c_op"] == M_PUT),
                      jnp.maximum(st["acked"][kl], st["c_val"]),
                      st["acked"][kl]))
        st["c_opn"] = st["c_opn"] + hit
        st["c_wait"] = jnp.where(hit, 0, st["c_wait"])
        st["c_done"] = jnp.where(st["c_opn"] >= self.O, 1, st["c_done"])
        ctx.set_timer(self.think, T_NEW, [0], when=hit)
        ctx.state = st


def clients_done(n_nodes: int):
    def check(state):
        return (state.node_state["c_done"][1:n_nodes] == 1).all()
    return check


def make_wal_kv_runtime(n_clients=2, n_ops=12, keys_per_client=2,
                        wal_cap=8, sync_wal=True, scenario=None, cfg=None):
    from ..core.types import NetConfig, SimConfig, sec
    from ..runtime.runtime import Runtime
    n = 1 + n_clients
    n_keys = n_clients * keys_per_client
    if cfg is None:
        cfg = SimConfig(n_nodes=n, event_capacity=256, payload_words=8,
                        time_limit=sec(10),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(8)))
    server = WalKvServer(n_keys, wal_cap, sync_wal=sync_wal)
    client = WalKvClient(n_ops, keys_per_client)
    node_prog = np.asarray([0] + [1] * n_clients, np.int32)
    return Runtime(cfg, [server, client],
                   wal_state_spec(n, n_keys, wal_cap, keys_per_client),
                   node_prog=node_prog, scenario=scenario,
                   persist=wal_persist_spec(),
                   halt_when=clients_done(n))
