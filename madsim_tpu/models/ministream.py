"""ministream — a streaming dataflow with barrier-aligned exactly-once
epochs, the workload shape of the reference's flagship downstream user
(RisingWave runs its deterministic e2e tests on madsim; README.md:25-33
names it). The sim framework's job is to break exactly-once — this model
makes that falsifiable.

Topology (4 nodes):

    source(0) --DATA(idx even)--> mapper(1) --CNT--> sink(3)
              --DATA(idx odd)---> mapper(2) --CNT-->

Protocol (epoch barriers with upstream replay — the Chandy-Lamport
pattern streaming engines use for consistent checkpoints):
  * The source emits one epoch at a time: K records DATA(e, att, idx)
    split by idx parity, then BARRIER(e, att) to both mappers, and
    retransmits the whole epoch on a timer until the sink's COMMIT(e)
    arrives (at-least-once transport under loss).
  * A mapper accumulates an idx BITMASK per (e, att) — popcount is its
    record count, immune to duplicate/reordered delivery — and forwards
    CNT(e, att, count) on barrier ONLY once its residue class is
    complete: a barrier must never overtake in-flight data. That gate is
    the alignment invariant, and it is this model's red/green knob
    (`strict_barrier=False` ships the classic bug: commit on first
    barrier, records still in flight).
  * A restarted mapper lost its mask (volatile state); its init HELLO
    makes the source bump `att` and replay the epoch from scratch; the
    sink pairs counts only when both carry the same attempt, so a stale
    pre-restart count can never match a fresh one.
  * The sink commits epochs strictly in order, re-acks duplicate CNTs of
    already-committed epochs (COMMIT may be lost), and checks the
    exactly-once oracle at every commit:
        crash_if(total != K)   (CRASH_STREAM_LOST_OR_DUP)
    a lost record undershoots, a double count overshoots.

tests/test_ministream.py: green under loss + mapper kill/restart chaos;
red (the oracle MUST fire) as soon as `strict_barrier=False` lets a
barrier pass incomplete data under loss.

Capacity note: K <= 31 (idx bitmask in one int32 word); chaos targets
mappers (the stateful middle); source/sink are the stable harness edge,
like wal_kv's client.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.api import Ctx, Program
from ..core.types import ms

SOURCE, MAP_A, MAP_B, SINK = 0, 1, 2, 3

M_DATA, M_BARRIER, M_CNT, M_COMMIT, M_HELLO = 1, 2, 3, 4, 5
T_RETX = 1

CRASH_STREAM_LOST_OR_DUP = 401


def stream_state_spec():
    z = jnp.asarray(0, jnp.int32)
    return dict(
        # source
        s_epoch=z, s_att=z, s_done=z,
        # mapper (volatile by design: a kill erases the epoch's progress)
        m_mask=z, m_e=z, m_att=z,
        # sink
        k_cnt=jnp.zeros((2,), jnp.int32),     # per-mapper count
        k_att=jnp.full((2,), -1, jnp.int32),  # attempt each count carries
        k_have=jnp.zeros((2,), jnp.int32),    # count present this epoch
        k_committed=z,                        # epochs committed so far
    )


class Source(Program):
    def __init__(self, k: int, epochs: int, retx=ms(40)):
        assert 2 <= k <= 31, "idx bitmask packs into one int32 word"
        self.K = k
        self.E = epochs
        self.retx = retx

    def _emit_epoch(self, ctx: Ctx, st, when):
        """(Re)send the whole current epoch: K records + barriers.
        Exactly ONE retransmit chain stays armed: every (re)emission
        cancels the previous T_RETX before re-arming, so HELLO-triggered
        replays don't multiply retransmission traffic for the rest of
        the epoch (ctx.cancel_timer — the Sleep::reset idiom)."""
        e, att = st["s_epoch"], st["s_att"]
        for idx in range(self.K):
            dst = MAP_A if idx % 2 == 0 else MAP_B
            ctx.send(dst, M_DATA, [e, att, idx], when=when)
        ctx.send(MAP_A, M_BARRIER, [e, att], when=when)
        ctx.send(MAP_B, M_BARRIER, [e, att], when=when)
        ctx.cancel_timer(T_RETX, when=when)
        ctx.set_timer(self.retx, T_RETX, [e], when=when)

    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        self._emit_epoch(ctx, st, when=True)
        ctx.state = st

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        # retransmit while the epoch payload[0] is still uncommitted
        live = ((tag == T_RETX) & (payload[0] == st["s_epoch"])
                & (st["s_done"] == 0))
        self._emit_epoch(ctx, st, when=live)
        ctx.state = st

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        # a mapper came back amnesic: replay the epoch under a fresh
        # attempt so a stale partial count can never pair with a new one
        hello = (tag == M_HELLO) & (st["s_done"] == 0)
        st["s_att"] = st["s_att"] + hello
        self._emit_epoch(ctx, st, when=hello)

        # sink committed our current epoch: advance (or finish)
        commit = (tag == M_COMMIT) & (payload[0] == st["s_epoch"])
        nxt = st["s_epoch"] + 1
        st["s_done"] = jnp.where(commit & (nxt >= self.E), 1, st["s_done"])
        advance = commit & (nxt < self.E)
        st["s_epoch"] = jnp.where(advance, nxt, st["s_epoch"])
        st["s_att"] = jnp.where(advance, 0, st["s_att"])
        self._emit_epoch(ctx, st, when=advance)
        ctx.state = st


class Mapper(Program):
    def __init__(self, k: int, strict_barrier: bool = True):
        self.K = k
        self.strict = strict_barrier

    def init(self, ctx: Ctx):
        # rebirth: progress is gone; ask the source for an epoch replay
        ctx.send(SOURCE, M_HELLO)

    def _mine(self, ctx, idx):
        return jnp.where(ctx.node == MAP_A, idx % 2 == 0, idx % 2 == 1)

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        e, att = payload[0], payload[1]
        newer = (e > st["m_e"]) | ((e == st["m_e"]) & (att > st["m_att"]))
        stale = (e < st["m_e"]) | ((e == st["m_e"]) & (att < st["m_att"]))

        is_data_raw = (tag == M_DATA) & self._mine(ctx, payload[2])
        is_barrier = tag == M_BARRIER
        # ANY message from a newer (epoch, attempt) advances the key and
        # resets the mask — including a barrier, so a stale mask can
        # never masquerade as the new attempt's count
        adv = (is_data_raw | is_barrier) & newer
        st["m_mask"] = jnp.where(adv, 0, st["m_mask"])
        st["m_e"] = jnp.where(adv, e, st["m_e"])
        st["m_att"] = jnp.where(adv, att, st["m_att"])

        is_data = is_data_raw & ~stale
        bit = 1 << jnp.clip(payload[2], 0, 30)
        st["m_mask"] = jnp.where(is_data, st["m_mask"] | bit, st["m_mask"])

        # barrier for the CURRENT (e, att): forward the count. The
        # strict (correct) gate also requires the residue class to be
        # COMPLETE — a barrier must not overtake in-flight records; the
        # retransmission loop will deliver another barrier once it is.
        # strict_barrier=False ships the classic alignment bug.
        n_mine = (self.K + jnp.where(ctx.node == MAP_A, 1, 0)) // 2
        count = jnp.sum((st["m_mask"] >> jnp.arange(31)) & 1,
                        dtype=jnp.int32)
        cur_barrier = (is_barrier & (e == st["m_e"])
                       & (att == st["m_att"]))
        done = cur_barrier & ((count == n_mine) if self.strict
                              else jnp.asarray(True))
        ctx.send(SINK, M_CNT, [st["m_e"], st["m_att"], count], when=done)
        ctx.state = st


class Sink(Program):
    def __init__(self, k: int, epochs: int):
        self.K = k
        self.E = epochs

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        e, att, cnt = payload[0], payload[1], payload[2]
        slot = jnp.clip(src - MAP_A, 0, 1)
        is_cnt = tag == M_CNT

        # COMMIT acks can be lost: re-ack stragglers of committed epochs
        # so the source never wedges waiting for a commit that happened
        ctx.send(SOURCE, M_COMMIT, [e],
                 when=is_cnt & (e < st["k_committed"]))

        # counts for the epoch being committed; newest attempt wins
        cur = is_cnt & (e == st["k_committed"])
        take = cur & (att >= st["k_att"][slot])
        st["k_cnt"] = st["k_cnt"].at[slot].set(
            jnp.where(take, cnt, st["k_cnt"][slot]))
        st["k_att"] = st["k_att"].at[slot].set(
            jnp.where(take, att, st["k_att"][slot]))
        st["k_have"] = st["k_have"].at[slot].set(
            jnp.where(take, 1, st["k_have"][slot]))

        # barrier ALIGNMENT at the join: both inputs present AND from the
        # same attempt (a stale pre-restart count never pairs with a
        # fresh one)
        both = ((st["k_have"][0] == 1) & (st["k_have"][1] == 1)
                & (st["k_att"][0] == st["k_att"][1]))
        total = st["k_cnt"][0] + st["k_cnt"][1]
        commit = cur & both & (st["k_committed"] < self.E)
        # THE exactly-once oracle: an aligned epoch must count every
        # record exactly once — a loss undershoots, a duplicate/stale
        # count overshoots
        ctx.crash_if(commit & (total != self.K), CRASH_STREAM_LOST_OR_DUP)
        ctx.send(SOURCE, M_COMMIT, [st["k_committed"]], when=commit)
        st["k_committed"] = st["k_committed"] + commit
        # fresh epoch: clear the alignment slots
        st["k_cnt"] = jnp.where(commit, jnp.zeros_like(st["k_cnt"]),
                                st["k_cnt"])
        st["k_att"] = jnp.where(commit, jnp.full_like(st["k_att"], -1),
                                st["k_att"])
        st["k_have"] = jnp.where(commit, jnp.zeros_like(st["k_have"]),
                                 st["k_have"])
        ctx.state = st


def make_ministream_runtime(k=8, epochs=4, strict_barrier=True,
                            scenario=None, cfg=None):
    from ..core.types import NetConfig, SimConfig, sec
    from ..runtime.runtime import Runtime

    if cfg is None:
        cfg = SimConfig(n_nodes=4, event_capacity=160, time_limit=sec(60),
                        net=NetConfig(packet_loss_rate=0.05))
    progs = [Source(k, epochs), Mapper(k, strict_barrier), Sink(k, epochs)]
    return Runtime(cfg, progs, stream_state_spec(),
                   node_prog=[0, 1, 1, 2], scenario=scenario,
                   halt_when=lambda s: s.node_state["s_done"][SOURCE] == 1)
