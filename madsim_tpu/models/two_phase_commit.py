"""Two-phase commit — a second protocol family for the chaos harness.

Coordinator (node 0) drives a sequence of transactions over participants
1..N-1: PREPARE -> votes -> COMMIT iff every vote is yes, else ABORT ->
acks. Votes and decisions are write-ahead state (engine persist mask), so a
crashed coordinator re-drives its persisted decision after restart — the
classic "2PC blocks on coordinator failure, but never diverges" behavior.

The per-event global invariant is atomicity itself: no transaction may be
COMMITted on one node and ABORTed on another, and a participant that voted
NO must never see COMMIT. `early_decide_quorum` deliberately re-introduces
the classic bug (deciding before all votes arrive) so tests can prove the
fuzzer finds it and reports a reproducing seed.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.api import Ctx, Program
from ..core.types import ms

# message tags
PREPARE, VOTE, DECIDE, ACK = 1, 2, 3, 4
# timer tags
T_TICK = 1
# decision encoding
NONE, COMMIT, ABORT = 0, 1, 2

CRASH_DIVERGED = 401        # same tx committed here, aborted there
CRASH_NO_VOTE_COMMIT = 402  # committed against a NO vote


def state_spec(n_nodes: int, n_tx: int):
    z = jnp.asarray(0, jnp.int32)
    return dict(
        # persisted write-ahead state
        voted=jnp.zeros((n_tx,), jnp.int32),    # NONE/COMMIT(yes)/ABORT(no)
        decided=jnp.zeros((n_tx,), jnp.int32),  # NONE/COMMIT/ABORT
        # coordinator volatile driving state
        tx=z, phase=z,                           # 0 idle, 1 voting, 2 decide
        votes_mask=z, no_seen=z, acks_mask=z,    # participant bitmasks
    )


def persist_spec():
    return dict(voted=True, decided=True, tx=False, phase=False,
                votes_mask=False, no_seen=False, acks_mask=False)


class TwoPhaseCommit(Program):
    def __init__(self, n_nodes: int, n_tx: int = 6, p_yes: float = 0.85,
                 tick=ms(30), early_decide_quorum: int | None = None):
        assert n_nodes <= 31
        self.n = n_nodes
        self.tx_count = n_tx
        self.p_yes = p_yes
        self.tick = tick
        # BUG KNOB: decide once this many votes arrived (None = all — correct)
        self.early_quorum = early_decide_quorum
        self.all_mask = 0
        for p in range(1, n_nodes):
            self.all_mask |= 1 << p

    # -- coordinator ------------------------------------------------------
    def init(self, ctx: Ctx):
        ctx.set_timer(ctx.randint(0, self.tick), T_TICK,
                      when=ctx.node == 0)

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        is_tick = (tag == T_TICK) & (ctx.node == 0)
        running = st["tx"] < self.tx_count
        t = jnp.clip(st["tx"], 0, self.tx_count - 1)

        # idle -> start the next transaction
        start = is_tick & running & (st["phase"] == 0)
        st["phase"] = jnp.where(start, 1, st["phase"])
        st["votes_mask"] = jnp.where(start, 0, st["votes_mask"])
        st["no_seen"] = jnp.where(start, 0, st["no_seen"])
        st["acks_mask"] = jnp.where(start, 0, st["acks_mask"])

        # voting phase: (re)send PREPARE to participants lacking a vote
        voting = is_tick & running & ((st["phase"] == 1) | start)
        n_votes = _popcount(st["votes_mask"], self.n)
        need = (self.n - 1 if self.early_quorum is None
                else self.early_quorum)
        complete = voting & (n_votes >= need)
        # recovery rule: a persisted decision is FINAL — a restarted
        # coordinator re-drives it rather than re-deciding
        decision = jnp.where(st["decided"][t] != NONE, st["decided"][t],
                             jnp.where(st["no_seen"] != 0, ABORT, COMMIT))
        st["decided"] = st["decided"].at[t].set(
            jnp.where(complete, decision, st["decided"][t]))  # WAL write
        st["phase"] = jnp.where(complete, 2, st["phase"])

        # decide phase: (re)send DECIDE to un-acked participants
        deciding = is_tick & running & (st["phase"] == 2)
        for p in range(1, self.n):
            bit = 1 << p
            ctx.send(p, jnp.where(deciding, DECIDE, PREPARE),
                     [t, st["decided"][t]],
                     when=(voting & ~complete & ((st["votes_mask"] & bit) == 0))
                     | (deciding & ((st["acks_mask"] & bit) == 0)))

        # all acked -> next transaction
        done = deciding & ((st["acks_mask"] & self.all_mask) == self.all_mask)
        st["tx"] = st["tx"] + done
        st["phase"] = jnp.where(done, 0, st["phase"])

        ctx.set_timer(self.tick, T_TICK, when=is_tick & running)
        ctx.halt_if((ctx.node == 0) & (st["tx"] >= self.tx_count))
        ctx.state = st

    # -- both roles -------------------------------------------------------
    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        t = jnp.clip(payload[0], 0, self.tx_count - 1)

        # participant: PREPARE -> vote once (persisted), resend same vote
        is_prep = (tag == PREPARE) & (ctx.node != 0)
        fresh = is_prep & (st["voted"][t] == NONE)
        vote = jnp.where(ctx.bernoulli(self.p_yes), COMMIT, ABORT)
        st["voted"] = st["voted"].at[t].set(
            jnp.where(fresh, vote, st["voted"][t]))
        ctx.send(src, VOTE, [t, st["voted"][t], ctx.node], when=is_prep)

        # participant: DECIDE -> record + ack; atomicity asserts
        is_dec = (tag == DECIDE) & (ctx.node != 0)
        d = payload[1]
        ctx.crash_if(is_dec & (st["voted"][t] == ABORT) & (d == COMMIT),
                     CRASH_NO_VOTE_COMMIT)
        st["decided"] = st["decided"].at[t].set(
            jnp.where(is_dec & (st["decided"][t] == NONE), d,
                      st["decided"][t]))
        ctx.send(src, ACK, [t, ctx.node], when=is_dec)

        # coordinator: collect votes / acks
        is_vote = (tag == VOTE) & (ctx.node == 0) & (t == jnp.clip(
            st["tx"], 0, self.tx_count - 1))
        voter_bit = 1 << jnp.clip(payload[2], 0, 30)
        st["votes_mask"] = jnp.where(is_vote, st["votes_mask"] | voter_bit,
                                     st["votes_mask"])
        st["no_seen"] = jnp.where(is_vote & (payload[1] == ABORT),
                                  st["no_seen"] | voter_bit, st["no_seen"])
        # ACKs are tx-guarded like votes: a stale duplicate ACK from the
        # previous transaction must not pre-mark a participant as acked
        is_ack = ((tag == ACK) & (ctx.node == 0)
                  & (t == jnp.clip(st["tx"], 0, self.tx_count - 1)))
        ack_bit = 1 << jnp.clip(payload[1], 0, 30)
        st["acks_mask"] = jnp.where(is_ack, st["acks_mask"] | ack_bit,
                                    st["acks_mask"])
        ctx.state = st


def _popcount(x, n_bits):
    bits = (x[None] >> jnp.arange(n_bits, dtype=jnp.int32)) & 1
    return bits.sum(dtype=jnp.int32)


def tpc_invariant(n_nodes: int, n_tx: int):
    """Atomicity: a transaction never COMMITs on one node and ABORTs on
    another (checked across all nodes after every event)."""
    def invariant(state):
        dec = state.node_state["decided"]            # [N, TX]
        committed = (dec == COMMIT).any(axis=0)
        aborted = (dec == ABORT).any(axis=0)
        bad = (committed & aborted).any()
        return bad, jnp.asarray(CRASH_DIVERGED, jnp.int32)
    return invariant


def make_tpc_runtime(n_nodes=5, n_tx=6, scenario=None, cfg=None, **kw):
    from ..core.types import SimConfig, sec
    from ..runtime.runtime import Runtime
    if cfg is None:
        cfg = SimConfig(n_nodes=n_nodes, event_capacity=128,
                        time_limit=sec(20))
    prog = TwoPhaseCommit(n_nodes, n_tx, **kw)
    return Runtime(cfg, [prog], state_spec(n_nodes, n_tx),
                   scenario=scenario, invariant=tpc_invariant(n_nodes, n_tx),
                   persist=persist_spec())
