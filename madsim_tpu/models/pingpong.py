"""Ping-pong workload — BASELINE.md configs 0 and 1.

The state-machine re-telling of the reference's endpoint examples
(net/mod.rs:3-36 doctest; tests at net/mod.rs:413-630): node 0 pings peers
round-robin with a retry timer (so packet loss / partitions cannot deadlock
it), peers pong back, and the trajectory halts when `target` pongs have been
acknowledged.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.api import Ctx, Program
from ..core.types import ms

TAG_PING = 1
TAG_PONG = 2
TIMER_RETRY = 1


def state_spec():
    z = jnp.asarray(0, jnp.int32)
    return dict(seq=z, acked=z, pings_got=z, pongs_sent=z)


class PingPong(Program):
    def __init__(self, n_nodes: int, target: int = 10, retry=ms(20)):
        self.n = n_nodes
        self.target = target
        self.retry = retry

    def _dst(self, seq):
        # round-robin over peers 1..N-1 (single-node: self-ping)
        if self.n == 1:
            return jnp.asarray(0, jnp.int32)
        return 1 + seq % (self.n - 1)

    def init(self, ctx: Ctx):
        # only node 0 drives; jittered kick-off for schedule diversity
        ctx.set_timer(ctx.randint(0, ms(1)), TIMER_RETRY,
                      when=ctx.node == 0)

    def on_timer(self, ctx: Ctx, tag, payload):
        st = ctx.state
        done = st["acked"] >= self.target
        ctx.send(self._dst(st["seq"]), TAG_PING, [st["seq"]], when=~done)
        ctx.set_timer(self.retry, TIMER_RETRY, when=~done)

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        is_ping = tag == TAG_PING
        ctx.send(src, TAG_PONG, [payload[0]], when=is_ping)
        st["pings_got"] = st["pings_got"] + is_ping
        st["pongs_sent"] = st["pongs_sent"] + is_ping

        is_pong = (tag == TAG_PONG) & (payload[0] == st["seq"])
        st["seq"] = st["seq"] + is_pong
        st["acked"] = st["acked"] + is_pong
        done = st["acked"] >= self.target
        # fire the next ping immediately on ack (retry timer is the backstop)
        ctx.send(self._dst(st["seq"]), TAG_PING, [st["seq"]],
                 when=is_pong & ~done)
        ctx.state = st
        ctx.halt_if((ctx.node == 0) & done)
