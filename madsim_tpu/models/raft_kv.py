"""Replicated KV register store on Raft, with client-observed histories —
the full MadRaft workload (BASELINE.md config 4: log replication +
linearizability fuzz).

Cluster layout: nodes [0, R) run RaftKv (the consensus core of
models/raft.py with a richer log entry: op/key/val/client/rtag); nodes
[R, N) run KvClient, issuing sequential PUT/GET calls with retry-and-rotate
on timeout. Clients record an invocation/response history into fixed-size
state arrays; the host extracts it after the run and feeds it to the
linearizability checker (madsim_tpu/native.py — C++ with Python fallback).

Exactly-once: entries carry (client, rtag); a leader deduplicates retries
against its own authoritative log, and replies immediately for already-
committed duplicates. GETs are linearized through the log like writes
(no lease/read-index shortcut), so every response is a committed operation.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.api import Ctx, Program
from ..core.types import ms
from . import raft as R

OP_PUT, OP_GET = 1, 2
# message tags (beyond RV/RVR/AE/AER = 1..4)
CMD, CRSP = 5, 6
# client timer tags
T_NEW, T_RETRY = 4, 5

KV_FIELDS = ("op", "key", "val", "client", "rtag")


def kv_state_spec(n_nodes: int, log_capacity: int, n_ops: int):
    z = jnp.asarray(0, jnp.int32)
    extra = dict(
        last_replied=z,
        # client-side bookkeeping
        c_target=z, c_id=z, c_op=z, c_key=z, c_val=z, c_opn=z,
        c_wait=z,
        h_op=jnp.zeros((n_ops,), jnp.int32),
        h_key=jnp.zeros((n_ops,), jnp.int32),
        h_val=jnp.zeros((n_ops,), jnp.int32),
        h_inv=jnp.full((n_ops,), -1, jnp.int32),
        h_resp=jnp.full((n_ops,), -1, jnp.int32),
    )
    return R.state_spec(n_nodes, log_capacity, KV_FIELDS, extra)


def kv_persist_spec():
    extra = dict(last_replied=None, c_target=None, c_id=None, c_op=None,
                 c_key=None, c_val=None, c_opn=None, c_wait=None, h_op=None,
                 h_key=None, h_val=None, h_inv=None, h_resp=None)
    return R.persist_spec(KV_FIELDS, extra)


class RaftKv(R.Raft):
    """Raft peer serving PUT/GET commands from clients."""

    ENTRY_FIELDS = KV_FIELDS

    def __init__(self, n_nodes: int, log_capacity: int = 64,
                 replies_per_event: int = 2, **kw):
        super().__init__(n_nodes, log_capacity, n_cmds=0, **kw)
        self.replies_per_event = replies_per_event

    def _propose_fields(self, ctx, st):
        # RaftKv never self-proposes (n_cmds=0); entries come from clients
        z = jnp.asarray(0, jnp.int32)
        return {f: z for f in KV_FIELDS}

    # -- read the register value an entry observes ------------------------
    def _result_at(self, st, k):
        """Result for log entry k: a PUT echoes its value; a GET reads the
        last committed PUT to its key strictly before k (initial value 0)."""
        L = self.L
        kc = jnp.clip(k, 0, L - 1)
        ks = jnp.arange(L, dtype=jnp.int32)
        key_k = st["log_key"][kc]
        isput = ((st["log_op"] == OP_PUT) & (st["log_key"] == key_k)
                 & (ks < k))
        lastput = jnp.max(jnp.where(isput, ks + 1, 0))
        read = jnp.where(lastput > 0,
                         st["log_val"][jnp.clip(lastput - 1, 0, L - 1)], 0)
        return jnp.where(st["log_op"][kc] == OP_GET, read, st["log_val"][kc])

    # -- hooks into the consensus core ------------------------------------
    def _extra_message(self, ctx: Ctx, st, src, tag, payload):
        L = self.L
        is_cmd = tag == CMD
        rtag, op, key, val = payload[0], payload[1], payload[2], payload[3]
        leader = st["role"] == R.LEADER

        # dedup retries against the authoritative log (exactly-once)
        ks = jnp.arange(L, dtype=jnp.int32)
        dup = ((st["log_rtag"] == rtag) & (st["log_client"] == src)
               & (ks < st["log_len"]))
        dup_any = dup.any()
        dup_idx = jnp.argmax(dup).astype(jnp.int32)

        self._append(ctx, st, is_cmd & leader & ~dup_any,
                     dict(op=op, key=key, val=val, client=src, rtag=rtag))

        # a duplicate that already committed answers immediately
        dup_done = is_cmd & leader & dup_any & (dup_idx < st["commit"])
        ctx.send(src, CRSP, [rtag, self._result_at(st, dup_idx)],
                 when=dup_done)
        # non-leaders drop client commands; the client's retry timer rotates
        # it to another node (no redirect hints — pure fuzzing pressure)

    def _on_leader_commit(self, ctx: Ctx, st, prev_commit, is_aer):
        base = st["last_replied"]
        for j in range(self.replies_per_event):
            k = base + j
            kc = jnp.clip(k, 0, self.L - 1)
            m = (is_aer & (st["role"] == R.LEADER) & (k < st["commit"])
                 & (st["log_op"][kc] != 0))  # no-op entries have no caller
            ctx.send(st["log_client"][kc], CRSP,
                     [st["log_rtag"][kc], self._result_at(st, k)], when=m)
        st["last_replied"] = jnp.where(
            is_aer, jnp.minimum(st["commit"],
                                base + self.replies_per_event), base)

    def _on_become_leader(self, ctx: Ctx, st, become_leader):
        # entries committed under predecessors were already answered (or
        # will be re-asked and hit the dedup fast path)
        st["last_replied"] = jnp.where(become_leader, st["commit"],
                                       st["last_replied"])
        # append a no-op entry (op=0): a leader can only count commits for
        # current-term entries (§5.4.2), and clients' retries dedup against
        # inherited entries instead of re-appending — without a fresh entry
        # the new leader could never advance commit (livelock). Only needed
        # when uncommitted inherited entries exist; gating on that keeps
        # leader churn from eating the log capacity.
        z = jnp.asarray(0, jnp.int32)
        self._append(ctx, st,
                     become_leader & (st["commit"] < st["log_len"]),
                     {f: z for f in KV_FIELDS})


class KvClient(Program):
    """Sequential closed-loop client: one outstanding op, retry with target
    rotation on timeout, per-op invocation/response history recording."""

    def __init__(self, n_raft: int, n_keys: int = 4, n_ops: int = 12,
                 timeout=ms(60), think=ms(10)):
        self.R = n_raft
        self.K = n_keys
        self.O = n_ops
        self.timeout = timeout
        self.think = think

    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        st["c_target"] = ctx.randint(0, self.R - 1)
        ctx.set_timer(ctx.randint(0, ms(20)), T_NEW, [0])
        ctx.state = st

    def _issue(self, ctx, st, when):
        ctx.send(st["c_target"], CMD,
                 [st["c_id"], st["c_op"], st["c_key"], st["c_val"]],
                 when=when)
        ctx.set_timer(self.timeout, T_RETRY, [st["c_id"]], when=when)

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        start = ((tag == T_NEW) & (st["c_wait"] == 0)
                 & (st["c_opn"] < self.O))
        st["c_id"] = jnp.where(start, ctx.randint(1, 2**30 - 1), st["c_id"])
        st["c_op"] = jnp.where(start,
                               jnp.where(ctx.bernoulli(0.5), OP_PUT, OP_GET),
                               st["c_op"])
        st["c_key"] = jnp.where(start, ctx.randint(0, self.K - 1),
                                st["c_key"])
        st["c_val"] = jnp.where(start, ctx.node * 4096 + st["c_opn"],
                                st["c_val"])
        st["c_wait"] = jnp.where(start, 1, st["c_wait"])
        oidx = jnp.clip(st["c_opn"], 0, self.O - 1)
        st["h_op"] = st["h_op"].at[oidx].set(
            jnp.where(start, st["c_op"], st["h_op"][oidx]))
        st["h_key"] = st["h_key"].at[oidx].set(
            jnp.where(start, st["c_key"], st["h_key"][oidx]))
        st["h_val"] = st["h_val"].at[oidx].set(
            jnp.where(start, st["c_val"], st["h_val"][oidx]))
        st["h_inv"] = st["h_inv"].at[oidx].set(
            jnp.where(start, ctx.now, st["h_inv"][oidx]))

        # timeout: rotate to a random raft node and retry the SAME call id
        retry = ((tag == T_RETRY) & (st["c_wait"] == 1)
                 & (payload[0] == st["c_id"]))
        st["c_target"] = jnp.where(retry, ctx.randint(0, self.R - 1),
                                   st["c_target"])
        self._issue(ctx, st, start | retry)
        ctx.state = st

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        hit = ((tag == CRSP) & (st["c_wait"] == 1)
               & (payload[0] == st["c_id"]))
        oidx = jnp.clip(st["c_opn"], 0, self.O - 1)
        st["h_resp"] = st["h_resp"].at[oidx].set(
            jnp.where(hit, ctx.now, st["h_resp"][oidx]))
        st["h_val"] = st["h_val"].at[oidx].set(
            jnp.where(hit & (st["h_op"][oidx] == OP_GET), payload[1],
                      st["h_val"][oidx]))
        st["c_opn"] = st["c_opn"] + hit
        st["c_wait"] = jnp.where(hit, 0, st["c_wait"])
        ctx.set_timer(self.think, T_NEW, [0], when=hit)
        ctx.state = st


def all_clients_done(n_raft: int, n_ops: int):
    def check(state):
        return (state.node_state["c_opn"][n_raft:] >= n_ops).all()
    return check


def make_kv_runtime(n_raft=5, n_clients=3, n_keys=4, n_ops=12,
                    log_capacity=64, scenario=None, cfg=None, **raft_kw):
    from ..core.types import NetConfig, SimConfig, sec
    from ..runtime.runtime import Runtime
    n = n_raft + n_clients
    if cfg is None:
        cfg = SimConfig(n_nodes=n, event_capacity=384, payload_words=12,
                        time_limit=sec(20))
    assert cfg.payload_words >= 6 + len(KV_FIELDS)
    assert log_capacity >= n_clients * n_ops + 4, \
        ("log must fit every client op plus slack for election no-ops "
         "(one per leader change with uncommitted inherited entries)")
    raft_kw.setdefault("n_peers", n_raft)  # quorum over servers, not clients
    prog_raft = RaftKv(n, log_capacity, **raft_kw)
    prog_client = KvClient(n_raft, n_keys, n_ops)
    node_prog = np.asarray([0] * n_raft + [1] * n_clients, np.int32)
    peer_mask = np.asarray([True] * n_raft + [False] * n_clients)
    rt = Runtime(cfg, [prog_raft, prog_client],
                 kv_state_spec(n, log_capacity, n_ops),
                 node_prog=node_prog, scenario=scenario,
                 invariant=R.raft_invariant(n, log_capacity, KV_FIELDS,
                                            peer_mask),
                 persist=kv_persist_spec(),
                 halt_when=all_clients_done(n_raft, n_ops))
    return rt


def extract_histories(state, n_raft: int, n_clients: int):
    """Pull per-trajectory client histories out of the final batched state.

    Returns a list (one per trajectory) of dicts with numpy arrays
    op/key/val/inv/resp flattened over clients (resp == -1 for ops still
    outstanding at halt — the checker treats those as possibly-applied).
    """
    ns = state.node_state
    out = []
    h = {k: np.asarray(ns[k]) for k in
         ("h_op", "h_key", "h_val", "h_inv", "h_resp")}
    B = h["h_op"].shape[0]
    for b in range(B):
        sl = slice(n_raft, n_raft + n_clients)
        started = h["h_inv"][b, sl] >= 0
        out.append(dict(
            op=h["h_op"][b, sl][started],
            key=h["h_key"][b, sl][started],
            val=h["h_val"][b, sl][started],
            inv=h["h_inv"][b, sl][started],
            resp=h["h_resp"][b, sl][started],
        ))
    return out
