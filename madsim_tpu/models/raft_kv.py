"""Replicated KV register store on Raft, with client-observed histories —
the full MadRaft workload (BASELINE.md config 4: log replication + snapshots
+ linearizability fuzz).

Cluster layout: nodes [0, R) run RaftKv (the consensus core of
models/raft.py with a richer log entry: op/key/val/client/rtag); nodes
[R, N) run KvClient, issuing sequential PUT/GET calls with retry-and-rotate
on timeout. Clients record an invocation/response history into fixed-size
state arrays; the host extracts it after the run and feeds it to the
linearizability checker (madsim_tpu/native.py — C++ with Python fallback).

State machine: every node applies committed entries in order into a
materialized image (kv registers + per-client session table), bounded per
event by `apply_per_event`. The leader replies at apply time. Exactly-once:
entries carry (client, rtag); retries dedup against the session table (for
applied ops — their log entries may be compacted away) and against the live
log window (for in-flight ops). GETs are linearized through the log like
writes, so every response is a committed operation.

Snapshots (Raft §7): compaction folds exactly the applied prefix, capturing
the (kv, sessions) image at that boundary. InstallSnapshot ships the image
CHUNKED over the fixed-width payload (the madsim analog is tonic streaming
a snapshot blob): each IS carries [chunk_idx, n_chunks, words...] after the
4-word header; followers stage chunks keyed by snap_len and install only
when the image is complete — the bulk-data-over-fixed-payload pattern
DESIGN.md prescribes.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.api import Ctx, Program
from ..core.types import ms
from . import raft as R

OP_PUT, OP_GET = 1, 2
# message tags (beyond RV/RVR/AE/AER/IS = 1..4, 9)
CMD, CRSP = 5, 6
# client timer tags
T_NEW, T_RETRY = 4, 5

KV_FIELDS = ("op", "key", "val", "client", "rtag")
# IS data words per chunk: rides the slots the AE entry fields occupy, so
# every payload variant stacks to the same width
CHUNK_WORDS = len(KV_FIELDS)


def _image_words(n_keys: int, n_clients: int) -> int:
    """Flattened snapshot image: kv registers + session (rtag, val) rows."""
    return n_keys + 2 * n_clients


def kv_state_spec(n_nodes: int, log_capacity: int, n_ops: int,
                  n_keys: int = 4, n_clients: int = 3):
    z = jnp.asarray(0, jnp.int32)
    K, NC = n_keys, n_clients
    SW = _image_words(K, NC)
    extra = dict(
        # materialized state machine (persistent — it IS applied state)
        kv=jnp.zeros((K,), jnp.int32),
        applied=z,
        sess_rtag=jnp.zeros((NC,), jnp.int32),
        sess_val=jnp.zeros((NC,), jnp.int32),
        # frozen image at snap_len, captured at compaction — IS chunks read
        # this so a multi-chunk transfer stays internally consistent even
        # while the live kv keeps advancing
        snap_kv=jnp.zeros((K,), jnp.int32),
        snap_sess_rtag=jnp.zeros((NC,), jnp.int32),
        snap_sess_val=jnp.zeros((NC,), jnp.int32),
        # incoming-snapshot staging (volatile — restart restages)
        stage_buf=jnp.zeros((SW,), jnp.int32),
        stage_mask=z,
        stage_slen=z,
        # client-side bookkeeping
        c_target=z, c_id=z, c_op=z, c_key=z, c_val=z, c_opn=z,
        c_wait=z,
        h_op=jnp.zeros((n_ops,), jnp.int32),
        h_key=jnp.zeros((n_ops,), jnp.int32),
        h_val=jnp.zeros((n_ops,), jnp.int32),
        h_inv=jnp.full((n_ops,), -1, jnp.int32),
        h_resp=jnp.full((n_ops,), -1, jnp.int32),
    )
    return R.state_spec(n_nodes, log_capacity, KV_FIELDS, extra)


def kv_persist_spec():
    persist = ("kv", "applied", "sess_rtag", "sess_val",
               "snap_kv", "snap_sess_rtag", "snap_sess_val")
    volatile = dict(stage_buf=None, stage_mask=None, stage_slen=None,
                    c_target=None, c_id=None, c_op=None, c_key=None,
                    c_val=None, c_opn=None, c_wait=None, h_op=None,
                    h_key=None, h_val=None, h_inv=None, h_resp=None)
    mask = R.persist_spec(KV_FIELDS, volatile)
    mask.update({k: True for k in persist})
    return mask


class RaftKv(R.Raft):
    """Raft peer serving PUT/GET commands from clients."""

    ENTRY_FIELDS = KV_FIELDS

    def __init__(self, n_nodes: int, log_capacity: int = 64,
                 apply_per_event: int = 2, n_keys: int = 4, **kw):
        super().__init__(n_nodes, log_capacity, n_cmds=0, **kw)
        self.apply_per_event = apply_per_event
        self.K = n_keys
        self.NC = n_nodes - self.npeers          # client nodes [R, N)
        self.SW = _image_words(self.K, self.NC)
        self.n_chunks = -(-self.SW // CHUNK_WORDS)
        assert self.n_chunks <= 31, "stage_mask is a single int32 bitmap"

    def _propose_fields(self, ctx, st):
        # RaftKv never self-proposes (n_cmds=0); entries come from clients
        z = jnp.asarray(0, jnp.int32)
        return {f: z for f in KV_FIELDS}

    # -- the apply loop: committed entries -> (kv, sessions), in order ----
    def _on_commit_progress(self, ctx: Ctx, st, active):
        L, K = self.L, self.K
        for _ in range(self.apply_per_event):
            k = st["applied"]
            can = active & (k < st["commit"]) & (k >= st["snap_len"])
            slot = jnp.clip(k - st["snap_len"], 0, L - 1)
            op = st["log_op"][slot]
            key = jnp.clip(st["log_key"][slot], 0, K - 1)
            client = st["log_client"][slot]
            rtag = st["log_rtag"][slot]
            do_put = can & (op == OP_PUT)
            st["kv"] = st["kv"].at[key].set(
                jnp.where(do_put, st["log_val"][slot], st["kv"][key]))
            # post-write read: a PUT's result is its own value, a GET's is
            # the register as of this log position — both are kv[key] now
            result = st["kv"][key]
            cid = jnp.clip(client - self.npeers, 0, self.NC - 1)
            isop = can & (op != 0)                # no-op entries: no caller
            st["sess_rtag"] = st["sess_rtag"].at[cid].set(
                jnp.where(isop, rtag, st["sess_rtag"][cid]))
            st["sess_val"] = st["sess_val"].at[cid].set(
                jnp.where(isop, result, st["sess_val"][cid]))
            ctx.send(client, CRSP, [rtag, result],
                     when=isop & (st["role"] == R.LEADER))
            st["applied"] = st["applied"] + can

    # -- client commands ---------------------------------------------------
    def _extra_message(self, ctx: Ctx, st, src, tag, payload):
        L = self.L
        is_cmd = tag == CMD
        rtag, op, key, val = payload[0], payload[1], payload[2], payload[3]
        leader = st["role"] == R.LEADER
        cid = jnp.clip(src - self.npeers, 0, self.NC - 1)

        # exactly-once, two levels: the session table answers retries of
        # already-APPLIED ops (whose log entries may be compacted away);
        # the live-window scan suppresses re-append of in-flight ops.
        # rtags are MONOTONIC per client (KvClient issues c_opn + 1), so a
        # delayed duplicate of an op OLDER than the session entry is
        # rejected outright — with random ids it would be re-appended and
        # re-executed once its original entry had been compacted away
        sess_hit = st["sess_rtag"][cid] == rtag
        stale = rtag < st["sess_rtag"][cid]
        ks = jnp.arange(L, dtype=jnp.int32)
        live = st["log_len"] - st["snap_len"]
        pending = ((st["log_rtag"] == rtag) & (st["log_client"] == src)
                   & (ks < live)).any()

        self._append(ctx, st,
                     is_cmd & leader & ~sess_hit & ~stale & ~pending,
                     dict(op=op, key=key, val=val, client=src, rtag=rtag))
        ctx.send(src, CRSP, [rtag, st["sess_val"][cid]],
                 when=is_cmd & leader & sess_hit)
        # non-leaders drop client commands; the client's retry timer rotates
        # it to another node (no redirect hints — pure fuzzing pressure)

    def _on_become_leader(self, ctx: Ctx, st, become_leader):
        # append a no-op entry (op=0): a leader can only count commits for
        # current-term entries (§5.4.2), and clients' retries dedup against
        # inherited entries instead of re-appending — without a fresh entry
        # the new leader could never advance commit (livelock). Only needed
        # when uncommitted inherited entries exist; gating on that keeps
        # leader churn from eating the log capacity.
        z = jnp.asarray(0, jnp.int32)
        self._append(ctx, st,
                     become_leader & (st["commit"] < st["log_len"]),
                     {f: z for f in KV_FIELDS})

    # -- snapshots ---------------------------------------------------------
    def _compact_limit(self, st):
        # compact exactly the applied prefix: the (kv, sessions) image then
        # sits precisely at the new snap_len, so the captured shipping copy
        # is the state AT the boundary
        return st["applied"]

    def _snapshot_extra(self, ctx, st, do, shift):
        st["snap_kv"] = jnp.where(do, st["kv"], st["snap_kv"])
        st["snap_sess_rtag"] = jnp.where(do, st["sess_rtag"],
                                         st["snap_sess_rtag"])
        st["snap_sess_val"] = jnp.where(do, st["sess_val"],
                                        st["snap_sess_val"])

    def _is_extra_words(self, ctx, st):
        # rotate chunks on the heartbeat clock: every n_chunks ticks each
        # lagging follower has seen the whole image (lossy links just take
        # another cycle)
        chunk = (ctx.now // self.hb) % self.n_chunks
        svec = jnp.concatenate(
            [st["snap_kv"], st["snap_sess_rtag"], st["snap_sess_val"]])
        base = chunk * CHUNK_WORDS
        words = []
        for w in range(CHUNK_WORDS):
            idx = jnp.clip(base + w, 0, self.SW - 1)
            words.append(jnp.where(base + w < self.SW, svec[idx], 0))
        return [chunk, jnp.asarray(self.n_chunks, jnp.int32)] + words

    def _install_ready(self, ctx, st, want, payload):
        # stage the incoming chunk, keyed by the snapshot's snap_len —
        # chunks of a superseded snapshot are discarded wholesale
        s_len, cidx = payload[1], payload[4]
        fresh = want & (st["stage_slen"] != s_len)
        st["stage_mask"] = jnp.where(fresh, 0, st["stage_mask"])
        st["stage_slen"] = jnp.where(want, s_len, st["stage_slen"])
        base = cidx * CHUNK_WORDS
        for w in range(CHUNK_WORDS):
            pos = jnp.clip(base + w, 0, self.SW - 1)
            ok_w = want & (base + w < self.SW)
            st["stage_buf"] = st["stage_buf"].at[pos].set(
                jnp.where(ok_w, payload[6 + w], st["stage_buf"][pos]))
        st["stage_mask"] = jnp.where(
            want,
            st["stage_mask"] | (1 << jnp.clip(cidx, 0, 30)),
            st["stage_mask"])
        return st["stage_mask"] == (1 << self.n_chunks) - 1

    def _install_extra(self, ctx, st, inst, payload):
        s_len = payload[1]
        K, NC = self.K, self.NC
        buf = st["stage_buf"]
        # adopt the image only if it's ahead of our own applied state (a
        # node that kept a matching suffix may already be further along)
        adopt = inst & (st["applied"] < s_len)
        st["kv"] = jnp.where(adopt, buf[:K], st["kv"])
        st["sess_rtag"] = jnp.where(adopt, buf[K:K + NC], st["sess_rtag"])
        st["sess_val"] = jnp.where(adopt, buf[K + NC:K + 2 * NC],
                                   st["sess_val"])
        st["applied"] = jnp.where(adopt, s_len, st["applied"])
        # the installed image is also our shipping copy at the new
        # snap_len — on EVERY install (not just adopt): snap_len moved to
        # s_len, so keeping an image captured at the old boundary would
        # ship a wrong snapshot if this node later leads
        st["snap_kv"] = jnp.where(inst, buf[:K], st["snap_kv"])
        st["snap_sess_rtag"] = jnp.where(inst, buf[K:K + NC],
                                         st["snap_sess_rtag"])
        st["snap_sess_val"] = jnp.where(inst, buf[K + NC:K + 2 * NC],
                                        st["snap_sess_val"])


class KvClient(Program):
    """Sequential closed-loop client: one outstanding op, retry with target
    rotation on timeout, per-op invocation/response history recording."""

    def __init__(self, n_raft: int, n_keys: int = 4, n_ops: int = 12,
                 timeout=ms(60), think=ms(10)):
        self.R = n_raft
        self.K = n_keys
        self.O = n_ops
        self.timeout = timeout
        self.think = think

    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        st["c_target"] = ctx.randint(0, self.R - 1)
        ctx.set_timer(ctx.randint(0, ms(20)), T_NEW, [0])
        ctx.state = st

    # call ids are MONOTONIC per client (op index + 1): the server's
    # session dedup can then reject a delayed duplicate of an OLDER op
    # even after its log entry was compacted (see RaftKv._extra_message)
    def _next_call_id(self, st):
        return st["c_opn"] + 1

    def _issue(self, ctx, st, when):
        ctx.send(st["c_target"], CMD,
                 [st["c_id"], st["c_op"], st["c_key"], st["c_val"]],
                 when=when)
        ctx.set_timer(self.timeout, T_RETRY, [st["c_id"]], when=when)

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        start = ((tag == T_NEW) & (st["c_wait"] == 0)
                 & (st["c_opn"] < self.O))
        st["c_id"] = jnp.where(start, self._next_call_id(st), st["c_id"])
        st["c_op"] = jnp.where(start,
                               jnp.where(ctx.bernoulli(0.5), OP_PUT, OP_GET),
                               st["c_op"])
        st["c_key"] = jnp.where(start, ctx.randint(0, self.K - 1),
                                st["c_key"])
        st["c_val"] = jnp.where(start, ctx.node * 4096 + st["c_opn"],
                                st["c_val"])
        st["c_wait"] = jnp.where(start, 1, st["c_wait"])
        oidx = jnp.clip(st["c_opn"], 0, self.O - 1)
        st["h_op"] = st["h_op"].at[oidx].set(
            jnp.where(start, st["c_op"], st["h_op"][oidx]))
        st["h_key"] = st["h_key"].at[oidx].set(
            jnp.where(start, st["c_key"], st["h_key"][oidx]))
        st["h_val"] = st["h_val"].at[oidx].set(
            jnp.where(start, st["c_val"], st["h_val"][oidx]))
        st["h_inv"] = st["h_inv"].at[oidx].set(
            jnp.where(start, ctx.now, st["h_inv"][oidx]))

        # timeout: rotate to a random raft node and retry the SAME call id
        retry = ((tag == T_RETRY) & (st["c_wait"] == 1)
                 & (payload[0] == st["c_id"]))
        st["c_target"] = jnp.where(retry, ctx.randint(0, self.R - 1),
                                   st["c_target"])
        self._issue(ctx, st, start | retry)
        ctx.state = st

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        hit = ((tag == CRSP) & (st["c_wait"] == 1)
               & (payload[0] == st["c_id"]))
        oidx = jnp.clip(st["c_opn"], 0, self.O - 1)
        st["h_resp"] = st["h_resp"].at[oidx].set(
            jnp.where(hit, ctx.now, st["h_resp"][oidx]))
        st["h_val"] = st["h_val"].at[oidx].set(
            jnp.where(hit & (st["h_op"][oidx] == OP_GET), payload[1],
                      st["h_val"][oidx]))
        st["c_opn"] = st["c_opn"] + hit
        st["c_wait"] = jnp.where(hit, 0, st["c_wait"])
        ctx.set_timer(self.think, T_NEW, [0], when=hit)
        ctx.state = st


def all_clients_done(n_raft: int, n_ops: int):
    def check(state):
        return (state.node_state["c_opn"][n_raft:] >= n_ops).all()
    return check


def make_kv_runtime(n_raft=5, n_clients=3, n_keys=4, n_ops=12,
                    log_capacity=64, scenario=None, cfg=None,
                    halt_when_all_done=True, **raft_kw):
    from ..core.types import NetConfig, SimConfig, sec
    from ..runtime.runtime import Runtime
    n = n_raft + n_clients
    if cfg is None:
        cfg = SimConfig(n_nodes=n, event_capacity=128, payload_words=12,
                        time_limit=sec(20))
    assert cfg.payload_words >= 6 + len(KV_FIELDS)
    if not raft_kw.get("compact_threshold"):
        assert log_capacity >= n_clients * n_ops + 4, \
            ("without compaction the log must fit every client op plus "
             "slack for election no-ops (one per leader change with "
             "uncommitted inherited entries)")
    raft_kw.setdefault("n_peers", n_raft)  # quorum over servers, not clients
    prog_raft = RaftKv(n, log_capacity, n_keys=n_keys, **raft_kw)
    prog_client = KvClient(n_raft, n_keys, n_ops)
    node_prog = np.asarray([0] * n_raft + [1] * n_clients, np.int32)
    peer_mask = np.asarray([True] * n_raft + [False] * n_clients)
    rt = Runtime(cfg, [prog_raft, prog_client],
                 kv_state_spec(n, log_capacity, n_ops, n_keys, n_clients),
                 node_prog=node_prog, scenario=scenario,
                 invariant=R.raft_invariant(
                     n, log_capacity, KV_FIELDS, peer_mask,
                     window_slides=R.window_slides_for(raft_kw)),
                 persist=kv_persist_spec(),
                 halt_when=(all_clients_done(n_raft, n_ops)
                            if halt_when_all_done else None))
    return rt


def extract_histories(state, n_raft: int, n_clients: int):
    """Pull per-trajectory client histories out of the final batched state.

    Returns a list (one per trajectory) of dicts with numpy arrays
    op/key/val/inv/resp flattened over clients (resp == -1 for ops still
    outstanding at halt — the checker treats those as possibly-applied).
    """
    ns = state.node_state
    out = []
    h = {k: np.asarray(ns[k]) for k in
         ("h_op", "h_key", "h_val", "h_inv", "h_resp")}
    B = h["h_op"].shape[0]
    for b in range(B):
        sl = slice(n_raft, n_raft + n_clients)
        started = h["h_inv"][b, sl] >= 0
        out.append(dict(
            op=h["h_op"][b, sl][started],
            key=h["h_key"][b, sl][started],
            val=h["h_val"][b, sl][started],
            inv=h["h_inv"][b, sl][started],
            resp=h["h_resp"][b, sl][started],
        ))
    return out
