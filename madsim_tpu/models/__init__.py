"""Protocol workloads (the examples/ and downstream-crates analog).

Each model is a set of `Program` state machines plus invariants and a
`make_*_runtime` convenience constructor:

  pingpong          — request/response with retries (endpoint examples)
  rpc_echo          — client/server RPC service under faults (tonic-example)
  stream_echo       — streaming RPC shapes: client/server/bidi with
                      backpressure + kill-mid-stream recovery (tonic streams)
  raft              — leader election + log replication + log compaction /
                      InstallSnapshot (MadRaft core)
  raft_kv           — replicated KV with materialized state machine, chunked
                      snapshots, client histories + linearizability
  chain             — chain replication: reconfiguring master, lease-gated
                      tail reads, per-event two-tails invariant
  minipg            — postgres-shaped session protocol (auth handshake,
                      pipelining, transactions) over sim AND real sockets
  wal_kv            — WAL + checkpoint durability on the simulated
                      filesystem; red/green power-fail proof
  two_phase_commit  — atomic commit with write-ahead state
  gossip            — epidemic broadcast with anti-entropy push-back
  bank              — Jepsen-style transfers with money conservation
  ministream        — streaming dataflow with Chandy-Lamport-style epoch
                      barriers + exactly-once commit oracle (the
                      RisingWave-shaped e2e workload)
  percolator        — Percolator-lite transactions (primary/secondary
                      locks, snapshot reads at local-clock timestamps,
                      TTL lock cleanup) whose bank-sum snapshot audit is
                      the gray-failure plane's oracle (DESIGN §18)
"""
