"""Protocol workloads (the examples/ and downstream-crates analog).

Each model is a set of `Program` state machines plus invariants and a
`make_*_runtime` convenience constructor:

  pingpong          — request/response with retries (endpoint examples)
  rpc_echo          — client/server RPC service under faults (tonic-example)
  raft              — leader election + log replication (MadRaft core)
  raft_kv           — replicated KV with client histories + linearizability
  two_phase_commit  — atomic commit with write-ahead state
  gossip            — epidemic broadcast with anti-entropy push-back
  bank              — Jepsen-style transfers with money conservation
"""
