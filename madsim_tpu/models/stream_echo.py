"""Streaming-RPC echo workload — the tonic-example streaming suite analog.

The reference's tonic-example exercises unary, client-streaming,
server-streaming and bidi methods against a sim network with loss and kills
(tonic-example/src/server.rs:126-253 is the test shape; madsim-tonic
client.rs:52-124 the machinery). This model does the same over the
framed-stream fabric (net/streaming.py):

  mode="bidi"      client pushes n items, server echoes each (paced through
                   a backpressure ring, not fire-and-forget), both END
  mode="sum"       client-streaming: n items up, one aggregate K_REPLY down
  mode="download"  server-streaming: one request up, n items + END down

Clients verify payloads in-model (ctx.crash_if), detect stalls (lost END,
peer restart) and recover by resetting the peer stream and re-issuing the
whole call with a fresh call id — the reconnect-after-channel-break idiom.
Kill-mid-stream chaos is therefore survivable end-to-end: see
tests/test_streaming.py.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.api import Ctx, Program
from ..core.types import ms
from ..net import conn, streaming
from ..net.service import Service, rpc_stream

T_TICK = 1

CRASH_BAD_ECHO = 201
CRASH_BAD_SUM = 202
CRASH_BAD_DOWNLOAD = 203

SERVER = 0          # node 0 serves; nodes 1.. are clients
ECHO_RING = 8       # server-side backpressure buffer per client


def echo_state_spec(n_nodes: int, window: int = 4):
    z = jnp.asarray(0, jnp.int32)
    N = n_nodes
    return dict(
        **conn.conn_state(N),
        **streaming.streaming_state(N, window=window, body_words=1),
        # server: echo backpressure ring + END bookkeeping (bidi)
        eb_val=jnp.zeros((N, ECHO_RING), jnp.int32),
        eb_w=jnp.zeros((N,), jnp.int32),
        eb_r=jnp.zeros((N,), jnp.int32),
        eb_cid=jnp.zeros((N,), jnp.int32),
        eb_end=jnp.zeros((N,), jnp.int32),
        # server: client-streaming aggregation
        acc=jnp.zeros((N,), jnp.int32),
        # server: server-streaming download pacing
        dl_rem=jnp.zeros((N,), jnp.int32),
        dl_next=jnp.zeros((N,), jnp.int32),
        dl_cid=jnp.zeros((N,), jnp.int32),
        dl_end=jnp.zeros((N,), jnp.int32),
        # client
        c_phase=z,      # 0 open, 1 push, 2 awaiting, 3 done
        c_cid=z,
        c_sent=z,
        c_fin=z,        # our END went out
        c_got=z,        # items received back
        c_done=z,
        c_prog=z,       # virtual time of last forward progress
    )


class StreamEchoServer(Service):
    """All three streaming shapes behind @rpc_stream methods."""

    def __init__(self, n_nodes: int, tick=ms(10)):
        self.n = n_nodes
        self.tick = tick

    # ---- bidi echo: buffer delivered items, push them back paced --------
    @rpc_stream
    def echo(self, ctx: Ctx, st, src, kind, cid, body, when):
        fresh = when & (kind == streaming.K_CALL)
        # a new call resets the ring (a retried call replaces the old one)
        for k in ("eb_w", "eb_r", "eb_end"):
            st[k] = st[k].at[src].set(jnp.where(fresh, 0, st[k][src]))
        st["eb_cid"] = st["eb_cid"].at[src].set(
            jnp.where(fresh, cid, st["eb_cid"][src]))
        item = when & (kind == streaming.K_ITEM) & (cid == st["eb_cid"][src])
        wslot = st["eb_w"][src] % ECHO_RING
        st["eb_val"] = st["eb_val"].at[src, wslot].set(
            jnp.where(item, body[0], st["eb_val"][src, wslot]))
        st["eb_w"] = st["eb_w"].at[src].set(st["eb_w"][src] + item)
        st["eb_end"] = st["eb_end"].at[src].set(
            st["eb_end"][src]
            | (when & (kind == streaming.K_END)
               & (cid == st["eb_cid"][src])))

    # ---- client-streaming sum: aggregate, reply on END ------------------
    @rpc_stream
    def sum(self, ctx: Ctx, st, src, kind, cid, body, when):
        st["acc"] = st["acc"].at[src].set(
            jnp.where(when & (kind == streaming.K_CALL), 0,
                      st["acc"][src]
                      + jnp.where(when & (kind == streaming.K_ITEM),
                                  body[0], 0)))
        streaming.reply(ctx, st, src, cid, [st["acc"][src]],
                        method=StreamEchoServer.sum.tag,
                        when=when & (kind == streaming.K_END))

    # ---- server-streaming download: K_CALL asks for n items -------------
    @rpc_stream
    def download(self, ctx: Ctx, st, src, kind, cid, body, when):
        fresh = when & (kind == streaming.K_CALL)
        st["dl_rem"] = st["dl_rem"].at[src].set(
            jnp.where(fresh, body[0], st["dl_rem"][src]))
        st["dl_next"] = st["dl_next"].at[src].set(
            jnp.where(fresh, 0, st["dl_next"][src]))
        st["dl_cid"] = st["dl_cid"].at[src].set(
            jnp.where(fresh, cid, st["dl_cid"][src]))
        st["dl_end"] = st["dl_end"].at[src].set(
            jnp.where(fresh, 0, st["dl_end"][src]))

    def _drain(self, ctx: Ctx, st):
        """Paced response streaming: ≤1 echo item + ≤1 download item per
        client per tick, window permitting (backpressure-correct — a full
        send window delays, never drops)."""
        for c in range(1, self.n):
            # bidi echo ring
            has = st["eb_r"][c] < st["eb_w"][c]
            rslot = st["eb_r"][c] % ECHO_RING
            ok = streaming.push(ctx, st, c, st["eb_cid"][c],
                                [st["eb_val"][c, rslot]],
                                method=StreamEchoServer.echo.tag, when=has)
            st["eb_r"] = st["eb_r"].at[c].set(st["eb_r"][c] + ok)
            drained = (st["eb_end"][c] == 1) & (st["eb_r"][c]
                                                >= st["eb_w"][c])
            fin = streaming.finish(ctx, st, c, st["eb_cid"][c],
                                   method=StreamEchoServer.echo.tag,
                                   when=drained)
            st["eb_end"] = st["eb_end"].at[c].set(
                jnp.where(fin, 0, st["eb_end"][c]))
            # download stream
            dhas = st["dl_rem"][c] > 0
            dok = streaming.push(ctx, st, c, st["dl_cid"][c],
                                 [st["dl_next"][c]],
                                 method=StreamEchoServer.download.tag,
                                 when=dhas)
            st["dl_next"] = st["dl_next"].at[c].set(st["dl_next"][c] + dok)
            st["dl_rem"] = st["dl_rem"].at[c].set(st["dl_rem"][c] - dok)
            last = dok & (st["dl_rem"][c] == 0)
            st["dl_end"] = st["dl_end"].at[c].set(
                st["dl_end"][c] | last)
            dfin = streaming.finish(ctx, st, c, st["dl_cid"][c],
                                    method=StreamEchoServer.download.tag,
                                    when=st["dl_end"][c] == 1)
            st["dl_end"] = st["dl_end"].at[c].set(
                jnp.where(dfin, 0, st["dl_end"][c]))

    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        conn.listen(ctx, st)
        ctx.set_timer(self.tick, T_TICK, [0])
        ctx.state = st

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        is_tick = tag == T_TICK
        self._drain(ctx, st)
        streaming.tick(ctx, st, range(1, self.n), when=is_tick)
        ctx.set_timer(self.tick, T_TICK, [0], when=is_tick)
        ctx.state = st

    def on_message(self, ctx: Ctx, src, tag, payload):
        # connection lifecycle first: a (re)connecting or resetting client
        # restarts the sequence space on BOTH sides — without this, a
        # client-side reset after a mere connectivity gap (server alive)
        # would desynchronize the windows forever
        from ..utils.maskutil import needed
        st = dict(ctx.state)
        accept, _, rst = conn.on_message(ctx, st, src, tag, payload)
        fresh = accept | rst
        if needed(fresh):
            # the conn layer already rebased the stream fabric onto the
            # negotiated incarnation (r19); only the app state resets here
            for k in ("eb_w", "eb_r", "eb_end", "acc", "dl_rem", "dl_end"):
                st[k] = st[k].at[src].set(jnp.where(fresh, 0, st[k][src]))
        ctx.state = st
        super().on_message(ctx, src, tag, payload)
        # ACKs open send-window room: drain immediately, don't wait a tick
        st = dict(ctx.state)
        self._drain(ctx, st)
        ctx.state = st


class StreamEchoClient(Program):
    """Drives one call of the configured shape to completion, verifying
    every frame; stalls (kill-mid-stream, lost END) trigger a full
    reconnect-and-retry with a fresh call id."""

    def __init__(self, mode: str, n_items: int = 6, tick=ms(10),
                 stall=ms(200)):
        assert mode in ("bidi", "sum", "download")
        self.mode = mode
        self.n = n_items
        self.tick = tick
        self.stall = stall
        self.method = dict(
            bidi=StreamEchoServer.echo.tag,
            sum=StreamEchoServer.sum.tag,
            download=StreamEchoServer.download.tag)[mode]

    def _value(self, ctx, i):
        return ctx.node * 1000 + i * 7

    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        st["c_cid"] = ctx.randint(1, 2**30 - 1)
        st["c_prog"] = ctx.now
        ctx.set_timer(ctx.randint(0, self.tick), T_TICK, [0])
        ctx.state = st

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        is_tick = tag == T_TICK
        done = st["c_done"] == 1

        # stall watchdog: tear the CONNECTION down (notifying a live
        # server so it resets its side too), then re-issue from scratch
        stalled = (is_tick & ~done
                   & (ctx.now - st["c_prog"] > self.stall))
        conn.reset(ctx, st, SERVER, when=stalled)
        streaming.reset_peer(st, SERVER, when=stalled)
        st["c_cid"] = jnp.where(stalled, ctx.randint(1, 2**30 - 1),
                                st["c_cid"])
        for k in ("c_sent", "c_fin", "c_got"):
            st[k] = jnp.where(stalled, 0, st[k])
        st["c_phase"] = jnp.where(stalled, 0, st["c_phase"])
        st["c_prog"] = jnp.where(stalled, ctx.now, st["c_prog"])

        # phase 0: connect, then open the call
        est = conn.is_established(st, SERVER)
        conn.connect(ctx, st, SERVER,
                     when=is_tick & ~done & (st["c_phase"] == 0) & ~est)
        opening = is_tick & ~done & (st["c_phase"] == 0) & est
        open_body = [self.n] if self.mode == "download" else [0]
        ok = streaming.open_call(ctx, st, SERVER, self.method, st["c_cid"],
                                 open_body, when=opening)
        st["c_phase"] = jnp.where(
            ok, 2 if self.mode == "download" else 1, st["c_phase"])
        st["c_prog"] = jnp.where(ok, ctx.now, st["c_prog"])

        # phase 1: push request items, then our END
        if self.mode in ("bidi", "sum"):
            pushing = is_tick & ~done & (st["c_phase"] == 1) & (
                st["c_sent"] < self.n)
            pok = streaming.push(ctx, st, SERVER, st["c_cid"],
                                 [self._value(ctx, st["c_sent"])],
                                 method=self.method, when=pushing)
            st["c_sent"] = st["c_sent"] + pok
            fin_w = (is_tick & ~done & (st["c_phase"] == 1)
                     & (st["c_sent"] >= self.n) & (st["c_fin"] == 0))
            fok = streaming.finish(ctx, st, SERVER, st["c_cid"],
                                   method=self.method, when=fin_w)
            st["c_fin"] = st["c_fin"] + fok
            st["c_phase"] = jnp.where(fok, 2, st["c_phase"])
            st["c_prog"] = jnp.where(pok | fok, ctx.now, st["c_prog"])

        streaming.tick(ctx, st, [SERVER], when=is_tick)
        ctx.set_timer(self.tick, T_TICK, [0], when=is_tick)
        ctx.state = st

    def on_message(self, ctx: Ctx, src, tag, payload):
        from ..net.stream import delivered_slots
        from ..utils.maskutil import needed
        st = dict(ctx.state)
        _, _, rst = conn.on_message(ctx, st, src, tag, payload)
        # server reset our connection: start over (fresh call id next
        # tick; the conn layer already tore the stream fabric)
        if needed(rst):
            for k in ("c_sent", "c_fin", "c_got"):
                st[k] = jnp.where(rst, 0, st[k])
            st["c_phase"] = jnp.where(rst, 0, st["c_phase"])
        kinds, methods, cids, bodies, mask = streaming.on_stream(
            ctx, st, src, tag, payload)
        for i in delivered_slots(mask):
            mine = (mask[i] & (src == SERVER) & (cids[i] == st["c_cid"])
                    & (st["c_done"] == 0))
            item = mine & (kinds[i] == streaming.K_ITEM)
            end = mine & (kinds[i] == streaming.K_END)
            repl = mine & (kinds[i] == streaming.K_REPLY)
            if self.mode == "bidi":
                # echoed values come back exactly once, in order
                ctx.crash_if(
                    item & (bodies[i][0]
                            != self._value(ctx, st["c_got"])),
                    CRASH_BAD_ECHO)
                st["c_got"] = st["c_got"] + item
                got_all = end & (st["c_got"] >= self.n)
                ctx.crash_if(end & (st["c_got"] < self.n), CRASH_BAD_ECHO)
                st["c_done"] = jnp.where(got_all, 1, st["c_done"])
            elif self.mode == "sum":
                expect = sum(ctx.node * 1000 + i * 7 for i in range(self.n))
                ctx.crash_if(repl & (bodies[i][0] != expect), CRASH_BAD_SUM)
                st["c_done"] = jnp.where(repl, 1, st["c_done"])
            else:  # download
                ctx.crash_if(item & (bodies[i][0] != st["c_got"]),
                             CRASH_BAD_DOWNLOAD)
                st["c_got"] = st["c_got"] + item
                ctx.crash_if(end & (st["c_got"] < self.n),
                             CRASH_BAD_DOWNLOAD)
                st["c_done"] = jnp.where(end & (st["c_got"] >= self.n), 1,
                                         st["c_done"])
            st["c_prog"] = jnp.where(mine, ctx.now, st["c_prog"])
        ctx.state = st


def clients_done(n_nodes: int):
    def check(state):
        return (state.node_state["c_done"][1:n_nodes] == 1).all()
    return check


def make_stream_echo_runtime(mode: str, n_clients: int = 2, n_items: int = 6,
                             scenario=None, cfg=None):
    from ..core.types import NetConfig, SimConfig, sec
    from ..runtime.runtime import Runtime
    n = 1 + n_clients
    if cfg is None:
        cfg = SimConfig(n_nodes=n, event_capacity=64, payload_words=8,
                        time_limit=sec(10),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(8)))
    assert cfg.payload_words >= 2 + streaming.HEADER_WORDS + 1
    server = StreamEchoServer(n)
    client = StreamEchoClient(mode, n_items)
    node_prog = np.asarray([0] + [1] * n_clients, np.int32)
    return Runtime(cfg, [server, client], echo_state_spec(n),
                   node_prog=node_prog, scenario=scenario,
                   halt_when=clients_done(n))
