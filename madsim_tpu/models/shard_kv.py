"""ShardKV — multi-group Raft with reconfiguration and shard migration.

The MadRaft suite's hardest lab (shardkv: a sharded, linearizable KV store
over MULTIPLE Raft groups with live shard movement) as a vectorizable state
machine. Nothing in the reference implements this — madsim only provides the
simulator MadRaft's labs run on — so this model demonstrates the framework
carrying a workload at the top of the reference ecosystem's difficulty
range: three+ independent Raft groups in one simulated cluster, a
raft-replicated configuration service, cross-group data handoff with
config-number fencing, and client routing that chases the configuration.

Cluster layout (node ids):
  [0, RC)                    controller group — CfgRaft (config service)
  [RC + g*RG, RC+(g+1)*RG)   kv group g in [0, G) — ShardServer
  [RC + G*RG, N)             clients — ShardClient

Shards: key k belongs to shard k % S. A configuration is one int32 word
packing 3 bits of owner-group per shard (config 0 = nothing assigned; the
controller's first proposal creates config 1). Configurations are processed
by every group STRICTLY in sequence (my_cfg -> my_cfg+1), the property the
MadRaft lab tests enforce.

Migration protocol (all through the groups' Raft logs, so every replica of
a group transitions identically):
  1. controller leader self-proposes OP_NEWCFG entries (initial assignment,
     then single random shard moves) — configs are its committed log.
  2. each kv-group leader polls CFGQ(my_cfg+1); any controller node answers
     CFGR from its APPLIED config history.
  3. the leader proposes OP_CFG(num, asn). Applying it is the pivot: lost
     shards freeze their data (kv image + per-shard client sessions) into
     an outgoing buffer stamped out_num[s]=num and stop serving; gained
     shards (beyond config 1) become not-ready and record the previous
     owner group.
  4. the new owner's leader sends PULL(s, num); any node of the old group
     whose frozen buffer matches num exactly answers PULLR with the whole
     shard image (keys of s + session rows — fits one payload at model
     scale; bulk shards would chunk over net/streaming like RaftKv's
     InstallSnapshot does).
  5. the puller replicates the image THROUGH ITS OWN LOG as OP_INS_KV /
     OP_INS_SES entries fenced by (shard, num), closed by OP_INS_DONE which
     flips the shard ready. Client commands for a shard are accepted only
     when owned AND ready, so the handoff has no dual-serving window: the
     old group stops at its OP_CFG apply, the new group starts only after
     an image frozen at that very point is installed.

Exactly-once across moves: the per-(client, shard) session table rides the
shard image, so a retry that lands on the new owner still dedups. Client
call ids stay monotonic per client (see RaftKv's rationale).

Safety evidence: per-group Raft invariants (election safety + prefix digest
chains) checked every event via compose_invariants, and client histories
checked per-key with the native linearizability checker — across kills,
restarts, partitions, loss, and live migrations. A cross-group "unique
ready owner" invariant is deliberately NOT asserted: a lagging follower of
the old group legitimately still believes it owns a shard until it applies
the OP_CFG entry; safety lives in the serving gates (leader + applied
state), which the linearizability check validates end to end.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.api import Ctx, Program
from ..core.types import ms
from ..ops.select import take_row
from . import raft as R

# log-entry ops
OP_PUT, OP_GET = 1, 2
OP_CFG, OP_INS_KV, OP_INS_SES, OP_INS_DONE, OP_NEWCFG = 3, 4, 5, 6, 7
# message tags (1-4, 9 are raft; 5/6 shared with raft_kv's CMD/CRSP)
CMD, CRSP = 5, 6
CFGQ, CFGR, PULL, PULLR, CWRONG = 11, 12, 13, 14, 15
# timer tags (1-3 raft, 4/5 shared with raft_kv's client)
T_NEW, T_RETRY, T_CFGPOLL = 4, 5, 6

FIELDS = ("op", "key", "val", "client", "rtag")
MAXCFG_BITS = 5          # config numbers pack into 5 bits in OP_INS_SES.rtag
GRP_BITS = 3             # owner group packs into 3 bits per shard


def grp_of(asn, s):
    """Owner group of shard `s` under assignment word `asn` (s may be
    traced)."""
    return (asn >> (GRP_BITS * s)) & ((1 << GRP_BITS) - 1)


def shard_state_spec(n_nodes, log_capacity, *, n_keys, n_shards, n_groups,
                     n_clients, max_cfg, n_ops):
    z = jnp.asarray(0, jnp.int32)
    K, S, NC = n_keys, n_shards, n_clients
    extra = dict(
        # ---- controller state machine (applied; persists) ---------------
        cfg_n=z,
        cfg_hist=jnp.zeros((max_cfg + 1,), jnp.int32),   # [0] = invalid
        # ---- kv-server state machine (applied; persists) ----------------
        kv=jnp.zeros((K,), jnp.int32),
        applied=z,
        my_cfg=z,
        my_asn=z,
        ready=z,                                  # bitmask over shards
        src_grp=jnp.full((S,), -1, jnp.int32),    # pull-from group per shard
        sess_rtag=jnp.zeros((NC, S), jnp.int32),  # per-(client, shard) dedup
        sess_val=jnp.zeros((NC, S), jnp.int32),
        out_num=jnp.full((S,), -1, jnp.int32),    # frozen-at config number
        out_kv=jnp.zeros((S, K), jnp.int32),
        out_rtag=jnp.zeros((S, NC), jnp.int32),
        out_val=jnp.zeros((S, NC), jnp.int32),
        # ---- client bookkeeping (volatile) ------------------------------
        cl_cfg=z, cl_asn=z,
        c_target=z, c_id=z, c_op=z, c_key=z, c_val=z, c_opn=z, c_wait=z,
        h_op=jnp.zeros((n_ops,), jnp.int32),
        h_key=jnp.zeros((n_ops,), jnp.int32),
        h_val=jnp.zeros((n_ops,), jnp.int32),
        h_inv=jnp.full((n_ops,), -1, jnp.int32),
        h_resp=jnp.full((n_ops,), -1, jnp.int32),
    )
    return R.state_spec(n_nodes, log_capacity, FIELDS, extra)


def shard_persist_spec():
    keep = ("cfg_n", "cfg_hist", "kv", "applied", "my_cfg", "my_asn",
            "ready", "src_grp", "sess_rtag", "sess_val", "out_num",
            "out_kv", "out_rtag", "out_val")
    vol = ("cl_cfg", "cl_asn", "c_target", "c_id", "c_op", "c_key", "c_val",
           "c_opn", "c_wait", "h_op", "h_key", "h_val", "h_inv", "h_resp")
    mask = R.persist_spec(FIELDS, {k: None for k in keep + vol})
    mask.update({k: True for k in keep})
    mask.update({k: False for k in vol})
    return mask


def _noop_on_become_leader(self, ctx, st, become_leader):
    # current-term no-op entry so a new leader can advance commit over
    # inherited entries (§5.4.2) — same rationale as RaftKv
    z = jnp.asarray(0, jnp.int32)
    self._append(ctx, st, become_leader & (st["commit"] < st["log_len"]),
                 {f: z for f in FIELDS})


class CfgRaft(R.Raft):
    """The configuration service: a Raft group whose committed log IS the
    sequence of cluster configurations (the shardctrler analog)."""

    ENTRY_FIELDS = FIELDS

    def __init__(self, n_nodes, log_capacity, *, rc, n_groups, n_shards,
                 max_cfg, **kw):
        super().__init__(n_nodes, log_capacity, n_cmds=max_cfg,
                         n_peers=rc, peer_base=0, **kw)
        self.G, self.S, self.maxcfg = n_groups, n_shards, max_cfg

    _on_become_leader = _noop_on_become_leader

    def _can_propose(self, ctx, st):
        # one config in flight at a time: propose only when everything this
        # node has appended is already applied (paces moves so groups can
        # keep up, and makes each proposal read the latest applied config).
        # The budget is the APPLIED config count, not nprop — nprop is
        # per-leader-stint and would let every new controller leader mint
        # max_cfg more configs after a crash
        return (st["cfg_n"] < self.maxcfg) & (st["applied"] >= st["log_len"])

    def _propose_fields(self, ctx, st):
        cur = st["cfg_hist"][jnp.clip(st["cfg_n"], 0, self.maxcfg)]
        # config 1: random initial spread; later: move one random shard
        init_asn = jnp.asarray(0, jnp.int32)
        for s in range(self.S):
            init_asn = init_asn | (ctx.randint(0, self.G - 1)
                                   << (GRP_BITS * s))
        mv_s = ctx.randint(0, self.S - 1)
        mv_g = ctx.randint(0, self.G - 1)
        moved = ((cur & ~(((1 << GRP_BITS) - 1) << (GRP_BITS * mv_s)))
                 | (mv_g << (GRP_BITS * mv_s)))
        asn = jnp.where(st["cfg_n"] == 0, init_asn, moved)
        z = jnp.asarray(0, jnp.int32)
        return dict(op=jnp.asarray(OP_NEWCFG, jnp.int32), key=z, val=asn,
                    client=z, rtag=z)

    def _on_commit_progress(self, ctx: Ctx, st, active):
        # apply committed OP_NEWCFG entries into the config history
        for _ in range(2):
            k = st["applied"]
            can = active & (k < st["commit"]) & (k >= st["snap_len"])
            slot = jnp.clip(k - st["snap_len"], 0, self.L - 1)
            # entries past the budget apply as no-ops (NEVER overwrite a
            # config number that may already have been served): distinct
            # leaders can each have one proposal in flight, so the append
            # gate alone cannot bound the committed count
            is_cfg = (can & (st["log_op"][slot] == OP_NEWCFG)
                      & (st["cfg_n"] < self.maxcfg))
            nxt = jnp.clip(st["cfg_n"] + 1, 0, self.maxcfg)
            st["cfg_hist"] = st["cfg_hist"].at[nxt].set(
                jnp.where(is_cfg, st["log_val"][slot], st["cfg_hist"][nxt]))
            st["cfg_n"] = jnp.where(is_cfg, nxt, st["cfg_n"])
            st["applied"] = st["applied"] + can

    def _extra_message(self, ctx: Ctx, st, src, tag, payload):
        # CFGQ [want] -> CFGR [num, asn]: any node answers from its APPLIED
        # history (num = min(want, cfg_n); askers ignore what they didn't
        # ask for). Answering from followers keeps the config service
        # available while the controller group elects.
        is_q = tag == CFGQ
        num = jnp.clip(jnp.minimum(payload[0], st["cfg_n"]), 0, self.maxcfg)
        ctx.send(src, CFGR, [num, st["cfg_hist"][num]], when=is_q)


class ShardServer(R.Raft):
    """One kv group's Raft peer, serving shard-gated client commands and
    migrating shards by config number (see module docstring)."""

    ENTRY_FIELDS = FIELDS

    def __init__(self, n_nodes, log_capacity, *, gid, rc, rg, n_groups,
                 n_keys, n_shards, n_clients, max_cfg,
                 cfg_poll=ms(60), apply_per_event=3, **kw):
        super().__init__(n_nodes, log_capacity, n_cmds=0,
                         n_peers=rg, peer_base=rc + gid * rg, **kw)
        self.gid, self.rc, self.rg, self.G = gid, rc, rg, n_groups
        self.K, self.S, self.NC = n_keys, n_shards, n_clients
        self.maxcfg = max_cfg
        self.cfg_poll = cfg_poll
        self.apply_per_event = apply_per_event
        self.clients_base = rc + n_groups * rg
        self.Ks = n_keys // n_shards
        assert n_keys % n_shards == 0, "keys must spread evenly over shards"
        assert max_cfg < (1 << MAXCFG_BITS)
        assert n_groups <= (1 << GRP_BITS)

    _on_become_leader = _noop_on_become_leader

    def _propose_fields(self, ctx, st):
        z = jnp.asarray(0, jnp.int32)
        return {f: z for f in FIELDS}   # never self-proposes (n_cmds=0)

    def _owns(self, st, s):
        """Applied-state serving gate for shard s (may be traced)."""
        return ((st["my_cfg"] >= 1)
                & (grp_of(st["my_asn"], s) == self.gid)
                & ((st["ready"] >> s) & 1).astype(bool))

    # -- lifecycle ---------------------------------------------------------
    def init(self, ctx: Ctx):
        super().init(ctx)
        ctx.set_timer(ctx.randint(0, self.cfg_poll), T_CFGPOLL, [0])

    def on_timer(self, ctx: Ctx, tag, payload):
        super().on_timer(ctx, tag, payload)
        st = dict(ctx.state)
        is_poll = tag == T_CFGPOLL
        leader = st["role"] == R.LEADER
        # poll the next config from a random controller node
        ctx.send(ctx.randint(0, self.rc - 1), CFGQ, [st["my_cfg"] + 1],
                 when=is_poll & leader)
        # pull every owned-but-not-ready shard from its previous owner,
        # rotating through the old group's members (stateless, like the
        # InstallSnapshot chunk rotation)
        for s in range(self.S):
            need = (is_poll & leader & (st["my_cfg"] >= 1)
                    & (grp_of(st["my_asn"], s) == self.gid)
                    & (((st["ready"] >> s) & 1) == 0)
                    & (st["src_grp"][s] >= 0))
            member = (ctx.now // self.cfg_poll + s) % self.rg
            tgt = self.rc + st["src_grp"][s] * self.rg + member
            ctx.send(tgt, PULL, [s, st["my_cfg"]], when=need)
        ctx.set_timer(self.cfg_poll, T_CFGPOLL, [0], when=is_poll)
        ctx.state = st

    # -- the apply loop ----------------------------------------------------
    # Indexing note: the traced indices below (kv[key], sess[cid, s],
    # out_*[ps]) are SCALAR per lane — the cheap case on TPU (DESIGN.md §5:
    # scalar-per-lane dynamic indices lower to one dynamic-slice each; it
    # is many-element index VECTORS that serialize at ~10ns/element, and
    # none appear here).
    def _on_commit_progress(self, ctx: Ctx, st, active):
        L, K, S, NC = self.L, self.K, self.S, self.NC
        for _ in range(self.apply_per_event):
            k = st["applied"]
            can = active & (k < st["commit"]) & (k >= st["snap_len"])
            slot = jnp.clip(k - st["snap_len"], 0, L - 1)
            op = st["log_op"][slot]
            key = jnp.clip(st["log_key"][slot], 0, K - 1)
            val = st["log_val"][slot]
            client = st["log_client"][slot]
            rtag = st["log_rtag"][slot]
            cid = jnp.clip(client - self.clients_base, 0, NC - 1)
            s_of_key = key % S

            # client PUT/GET — only while the shard is owned AND ready at
            # APPLY time (an OP_CFG between append and apply revokes it)
            is_cli = can & ((op == OP_PUT) | (op == OP_GET))
            valid = is_cli & self._owns(st, s_of_key)
            do_put = valid & (op == OP_PUT)
            st["kv"] = st["kv"].at[key].set(
                jnp.where(do_put, val, st["kv"][key]))
            result = st["kv"][key]
            st["sess_rtag"] = st["sess_rtag"].at[cid, s_of_key].set(
                jnp.where(valid, rtag, st["sess_rtag"][cid, s_of_key]))
            st["sess_val"] = st["sess_val"].at[cid, s_of_key].set(
                jnp.where(valid, result, st["sess_val"][cid, s_of_key]))
            # one reply slot: OK with the result, or wrong-group so the
            # client refreshes its config
            ctx.send(client, jnp.where(valid, CRSP, CWRONG),
                     [rtag, result],
                     when=is_cli & (st["role"] == R.LEADER))

            # OP_CFG(num=key', asn=val): the migration pivot. Entries carry
            # key=num directly (not clipped to K).
            num = st["log_key"][slot]
            is_cfg = can & (op == OP_CFG) & (num == st["my_cfg"] + 1)
            asn_new = val
            for s in range(S):
                old = (st["my_cfg"] >= 1) & (grp_of(st["my_asn"], s)
                                             == self.gid)
                new = grp_of(asn_new, s) == self.gid
                lost = is_cfg & old & ~new
                gained = is_cfg & new & ~old
                # freeze outgoing shard data at the pivot
                st["out_kv"] = st["out_kv"].at[s].set(
                    jnp.where(lost, st["kv"], st["out_kv"][s]))
                st["out_rtag"] = st["out_rtag"].at[s].set(
                    jnp.where(lost, st["sess_rtag"][:, s],
                              st["out_rtag"][s]))
                st["out_val"] = st["out_val"].at[s].set(
                    jnp.where(lost, st["sess_val"][:, s], st["out_val"][s]))
                st["out_num"] = st["out_num"].at[s].set(
                    jnp.where(lost, num, st["out_num"][s]))
                # gained at config 1 = initial assignment (nothing to pull)
                st["ready"] = jnp.where(
                    lost, st["ready"] & ~(1 << s),
                    jnp.where(gained & (num == 1), st["ready"] | (1 << s),
                              jnp.where(gained, st["ready"] & ~(1 << s),
                                        st["ready"])))
                st["src_grp"] = st["src_grp"].at[s].set(
                    jnp.where(gained & (num > 1),
                              grp_of(st["my_asn"], s), st["src_grp"][s]))
            st["my_cfg"] = jnp.where(is_cfg, num, st["my_cfg"])
            st["my_asn"] = jnp.where(is_cfg, asn_new, st["my_asn"])

            # OP_INS_* — install a pulled shard image, fenced by (s, num)
            ins_s = jnp.clip(st["log_key"][slot], 0, S - 1)   # SES/DONE key
            not_ready = (((st["ready"] >> ins_s) & 1) == 0)
            # ownership fence mirrors is_done: a stale OP_INS_* must not
            # touch cells for a shard this group no longer owns
            is_ikv = (can & (op == OP_INS_KV) & (rtag == st["my_cfg"])
                      & (((st["ready"] >> s_of_key) & 1) == 0)
                      & (grp_of(st["my_asn"], s_of_key) == self.gid))
            st["kv"] = st["kv"].at[key].set(
                jnp.where(is_ikv, val, st["kv"][key]))
            is_ses = (can & (op == OP_INS_SES)
                      & ((rtag & ((1 << MAXCFG_BITS) - 1)) == st["my_cfg"])
                      & not_ready
                      & (grp_of(st["my_asn"], ins_s) == self.gid))
            st["sess_rtag"] = st["sess_rtag"].at[cid, ins_s].set(
                jnp.where(is_ses, rtag >> MAXCFG_BITS,
                          st["sess_rtag"][cid, ins_s]))
            st["sess_val"] = st["sess_val"].at[cid, ins_s].set(
                jnp.where(is_ses, val, st["sess_val"][cid, ins_s]))
            is_done = (can & (op == OP_INS_DONE) & (rtag == st["my_cfg"])
                       & not_ready
                       & (grp_of(st["my_asn"], ins_s) == self.gid))
            st["ready"] = jnp.where(is_done, st["ready"] | (1 << ins_s),
                                    st["ready"])

            st["applied"] = st["applied"] + can

    # -- messages ----------------------------------------------------------
    def _extra_message(self, ctx: Ctx, st, src, tag, payload):
        L, S, NC, Ks = self.L, self.S, self.NC, self.Ks
        leader = st["role"] == R.LEADER
        live = st["log_len"] - st["snap_len"]
        ks = jnp.arange(L, dtype=jnp.int32)

        # ---- CFGR [num, asn]: advance to the next config ----------------
        is_cfgr = tag == CFGR
        num, asn = payload[0], payload[1]
        owned_all_ready = jnp.ones((), bool)
        for s in range(S):
            owned = (st["my_cfg"] >= 1) & (grp_of(st["my_asn"], s)
                                           == self.gid)
            owned_all_ready = owned_all_ready & (
                ~owned | ((st["ready"] >> s) & 1).astype(bool))
        cfg_pending = ((st["log_op"] == OP_CFG) & (st["log_key"] == num)
                       & (ks < live)).any()
        adv = (is_cfgr & leader & (num == st["my_cfg"] + 1)
               & owned_all_ready & ~cfg_pending)
        self._append(ctx, st, adv,
                     dict(op=jnp.asarray(OP_CFG, jnp.int32), key=num,
                          val=asn, client=jnp.asarray(0, jnp.int32),
                          rtag=jnp.asarray(0, jnp.int32)))

        # ---- CMD [rtag, op, key, val] from a client ---------------------
        is_cmd = tag == CMD
        rtag, cop = payload[0], payload[1]
        ckey = jnp.clip(payload[2], 0, self.K - 1)
        cval = payload[3]
        s_of = ckey % S
        cid = jnp.clip(src - self.clients_base, 0, NC - 1)
        owns = self._owns(st, s_of)
        sess_hit = st["sess_rtag"][cid, s_of] == rtag
        stale = rtag < st["sess_rtag"][cid, s_of]
        # in-flight dedup covers UNAPPLIED entries only. Unlike RaftKv,
        # an applied entry here may have executed as a no-op (ownership
        # revoked by an OP_CFG between append and apply) WITHOUT touching
        # the session table — counting it as pending would drop the
        # client's retries forever; re-appending is the correct replay.
        unapplied = ks >= (st["applied"] - st["snap_len"])
        # op filter: an unapplied OP_INS_SES for this client carries a
        # migrated session tag in log_rtag that can collide with a small
        # call id and transiently suppress a legitimate append
        is_cli_op = (st["log_op"] == OP_PUT) | (st["log_op"] == OP_GET)
        pending = ((st["log_rtag"] == rtag) & (st["log_client"] == src)
                   & is_cli_op & (ks < live) & unapplied).any()
        self._append(ctx, st,
                     is_cmd & leader & owns & ~sess_hit & ~stale & ~pending,
                     dict(op=cop, key=ckey, val=cval, client=src, rtag=rtag))
        # dedup hit answers from the session; wrong-group redirects — one
        # shared reply slot, mutually exclusive conditions
        hit = is_cmd & leader & owns & sess_hit
        wrong = is_cmd & leader & ~owns
        ctx.send(src, jnp.where(wrong, CWRONG, CRSP),
                 [rtag, st["sess_val"][cid, s_of]], when=hit | wrong)

        # ---- PULL [s, num]: hand a frozen shard image out ---------------
        is_pull = tag == PULL
        ps = jnp.clip(payload[0], 0, S - 1)
        pnum = payload[1]
        have = is_pull & (st["out_num"][ps] == pnum)
        okv = take_row(st["out_kv"], ps)          # [K]
        kvals = [okv[ps + p * S] for p in range(Ks)]
        ortag = take_row(st["out_rtag"], ps)      # [NC]
        oval = take_row(st["out_val"], ps)
        ctx.send(src, PULLR,
                 [ps, pnum] + kvals + list(ortag) + list(oval), when=have)

        # ---- PULLR: replicate the image through our own log -------------
        is_pr = tag == PULLR
        rs = jnp.clip(payload[0], 0, S - 1)
        rnum = payload[1]
        ins_pending = ((st["log_op"] == OP_INS_DONE) & (st["log_key"] == rs)
                       & (st["log_rtag"] == rnum) & (ks < live)).any()
        take = (is_pr & leader & (rnum == st["my_cfg"])
                & (grp_of(st["my_asn"], rs) == self.gid)
                & (((st["ready"] >> rs) & 1) == 0) & ~ins_pending)
        z = jnp.asarray(0, jnp.int32)
        for p in range(Ks):
            self._append(ctx, st, take, dict(
                op=jnp.asarray(OP_INS_KV, jnp.int32), key=rs + p * S,
                val=payload[2 + p], client=z, rtag=rnum))
        for c in range(NC):
            self._append(ctx, st, take, dict(
                op=jnp.asarray(OP_INS_SES, jnp.int32), key=rs,
                val=payload[2 + Ks + NC + c],
                client=jnp.asarray(self.clients_base + c, jnp.int32),
                rtag=(payload[2 + Ks + c] << MAXCFG_BITS) | rnum))
        self._append(ctx, st, take, dict(
            op=jnp.asarray(OP_INS_DONE, jnp.int32), key=rs, val=z,
            client=z, rtag=rnum))


class ShardClient(Program):
    """Closed-loop client routing by its cached configuration; refreshes the
    config on wrong-group replies and timeouts, then retries the SAME call
    id (exactly-once is the server's session table's job)."""

    def __init__(self, *, rc, rg, n_groups, n_shards, n_keys, n_ops,
                 max_cfg, timeout=ms(80), think=ms(10)):
        self.rc, self.rg, self.G = rc, rg, n_groups
        self.S, self.K, self.O = n_shards, n_keys, n_ops
        self.maxcfg = max_cfg
        self.timeout, self.think = timeout, think

    def _refresh(self, ctx, when):
        ctx.send(ctx.randint(0, self.rc - 1), CFGQ, [self.maxcfg],
                 when=when)

    def _issue(self, ctx, st, when):
        g = grp_of(st["cl_asn"], st["c_key"] % self.S)
        st["c_target"] = jnp.where(
            when, self.rc + g * self.rg + ctx.randint(0, self.rg - 1),
            st["c_target"])
        ctx.send(st["c_target"], CMD,
                 [st["c_id"], st["c_op"], st["c_key"], st["c_val"]],
                 when=when)
        ctx.set_timer(self.timeout, T_RETRY, [st["c_id"]], when=when)

    def init(self, ctx: Ctx):
        self._refresh(ctx, True)
        ctx.set_timer(ctx.randint(ms(5), ms(30)), T_NEW, [0])

    def on_timer(self, ctx: Ctx, tag, payload):
        st = dict(ctx.state)
        routed = st["cl_cfg"] >= 1
        start = ((tag == T_NEW) & (st["c_wait"] == 0)
                 & (st["c_opn"] < self.O) & routed)
        # no config yet: ask again and come back
        self._refresh(ctx, (tag == T_NEW) & ~routed)
        ctx.set_timer(self.think, T_NEW, [0], when=(tag == T_NEW) & ~routed)

        st["c_id"] = jnp.where(start, st["c_opn"] + 1, st["c_id"])
        st["c_op"] = jnp.where(
            start, jnp.where(ctx.bernoulli(0.5), OP_PUT, OP_GET), st["c_op"])
        st["c_key"] = jnp.where(start, ctx.randint(0, self.K - 1),
                                st["c_key"])
        st["c_val"] = jnp.where(start, ctx.node * 4096 + st["c_opn"],
                                st["c_val"])
        st["c_wait"] = jnp.where(start, 1, st["c_wait"])
        oidx = jnp.clip(st["c_opn"], 0, self.O - 1)
        for h, v in (("h_op", st["c_op"]), ("h_key", st["c_key"]),
                     ("h_val", st["c_val"]), ("h_inv", ctx.now)):
            st[h] = st[h].at[oidx].set(jnp.where(start, v, st[h][oidx]))

        # timeout: refresh the config (the shard may have moved) and retry
        retry = ((tag == T_RETRY) & (st["c_wait"] == 1)
                 & (payload[0] == st["c_id"]))
        self._refresh(ctx, retry)
        self._issue(ctx, st, start | retry)
        ctx.state = st

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        # config updates
        is_cfgr = tag == CFGR
        newer = is_cfgr & (payload[0] > st["cl_cfg"])
        st["cl_cfg"] = jnp.where(newer, payload[0], st["cl_cfg"])
        st["cl_asn"] = jnp.where(newer, payload[1], st["cl_asn"])

        hit = ((tag == CRSP) & (st["c_wait"] == 1)
               & (payload[0] == st["c_id"]))
        oidx = jnp.clip(st["c_opn"], 0, self.O - 1)
        st["h_resp"] = st["h_resp"].at[oidx].set(
            jnp.where(hit, ctx.now, st["h_resp"][oidx]))
        st["h_val"] = st["h_val"].at[oidx].set(
            jnp.where(hit & (st["h_op"][oidx] == OP_GET), payload[1],
                      st["h_val"][oidx]))
        st["c_opn"] = st["c_opn"] + hit
        st["c_wait"] = jnp.where(hit, 0, st["c_wait"])
        ctx.set_timer(self.think, T_NEW, [0], when=hit)

        # wrong group: our config is stale — refresh now; the armed retry
        # timer re-issues with the updated routing
        wrong = ((tag == CWRONG) & (st["c_wait"] == 1)
                 & (payload[0] == st["c_id"]))
        self._refresh(ctx, wrong)
        ctx.state = st


def compose_invariants(*invs):
    """OR a set of per-group invariants into one (bad, code) check."""
    def inv(state):
        bads, codes = [], []
        for f in invs:
            b, c = f(state)
            bads.append(b)
            codes.append(c)
        bad = jnp.stack(bads).any()
        code = jnp.asarray(0, jnp.int32)
        for b, c in zip(reversed(bads), reversed(codes)):
            code = jnp.where(b, c, code)
        return bad, code
    return inv


def all_clients_done(clients_base: int, n_ops: int):
    def check(state):
        return (state.node_state["c_opn"][clients_base:] >= n_ops).all()
    return check


def make_shard_runtime(n_groups=2, rg=3, rc=3, n_clients=2, n_keys=8,
                       n_shards=4, n_ops=6, max_cfg=4, log_capacity=64,
                       scenario=None, cfg=None, extra_invariant=None, **kw):
    """Assemble the full sharded-KV cluster runtime. `extra_invariant`
    composes an additional (bad, code) check alongside the per-group
    Raft invariants — e.g. `harness.slo_invariant` so a p99 regression
    crashes next to the safety checks (examples/open_loop_kv.py)."""
    from ..core.types import NetConfig, SimConfig, sec
    from ..runtime.runtime import Runtime
    n = rc + n_groups * rg + n_clients
    if cfg is None:
        cfg = SimConfig(n_nodes=n, event_capacity=160, payload_words=12,
                        time_limit=sec(30),
                        net=NetConfig(send_latency_min=ms(1),
                                      send_latency_max=ms(10)))
    assert cfg.payload_words >= 2 + n_keys // n_shards + 2 * n_clients, \
        "PULLR must fit one payload (chunk bigger shards over net/streaming)"
    common = dict(n_keys=n_keys, n_shards=n_shards, n_clients=n_clients,
                  max_cfg=max_cfg)
    progs = [CfgRaft(n, log_capacity, rc=rc, n_groups=n_groups,
                     n_shards=n_shards, max_cfg=max_cfg, **kw)]
    for g in range(n_groups):
        progs.append(ShardServer(n, log_capacity, gid=g, rc=rc, rg=rg,
                                 n_groups=n_groups, **common, **kw))
    progs.append(ShardClient(rc=rc, rg=rg, n_groups=n_groups,
                             n_shards=n_shards, n_keys=n_keys, n_ops=n_ops,
                             max_cfg=max_cfg))
    node_prog = np.asarray([0] * rc
                           + sum(([1 + g] * rg for g in range(n_groups)), [])
                           + [1 + n_groups] * n_clients, np.int32)
    masks = [np.arange(n) < rc]
    for g in range(n_groups):
        base = rc + g * rg
        masks.append((np.arange(n) >= base) & (np.arange(n) < base + rg))
    inv = compose_invariants(
        *([R.raft_invariant(n, log_capacity, FIELDS, m,
                            window_slides=R.window_slides_for(kw))
           for m in masks]
          + ([extra_invariant] if extra_invariant is not None else [])))
    clients_base = rc + n_groups * rg
    return Runtime(cfg, progs,
                   shard_state_spec(n, log_capacity, n_groups=n_groups,
                                    n_ops=n_ops, **common),
                   node_prog=node_prog, scenario=scenario, invariant=inv,
                   persist=shard_persist_spec(),
                   halt_when=all_clients_done(clients_base, n_ops))


def extract_histories(state, clients_base: int, n_clients: int):
    """Per-trajectory client histories (same shape as raft_kv's)."""
    ns = state.node_state
    h = {k: np.asarray(ns[k]) for k in
         ("h_op", "h_key", "h_val", "h_inv", "h_resp")}
    out = []
    for b in range(h["h_op"].shape[0]):
        sl = slice(clients_base, clients_base + n_clients)
        started = h["h_inv"][b, sl] >= 0
        out.append(dict(
            op=h["h_op"][b, sl][started], key=h["h_key"][b, sl][started],
            val=h["h_val"][b, sl][started], inv=h["h_inv"][b, sl][started],
            resp=h["h_resp"][b, sl][started]))
    return out
