"""Epidemic broadcast (push gossip with anti-entropy digests) — a
communication-pattern family complementing consensus (Raft) and atomic
commit (2PC).

Node 0 originates a set of rumors; every infected node pushes its rumor
digest to `fanout` random peers per tick, and a receiver holding rumors the
pusher lacks pushes its own digest back (anti-entropy in the reverse
direction). The interesting properties for a chaos harness: eventual full
dissemination despite loss/partitions/churn (liveness checked by the
tests), and per-seed propagation-time distributions (schedule-space
statistics, the kind of measurement the batched runtime makes cheap).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.api import Ctx, Program
from ..core.types import ms

PUSH, PUSH_BACK = 1, 2
T_GOSSIP = 1

RUMOR_BITS = 30


def state_spec():
    z = jnp.asarray(0, jnp.int32)
    return dict(have=z, infected_at=jnp.asarray(-1, jnp.int32), booted=z)


class Gossip(Program):
    def __init__(self, n_nodes: int, n_rumors: int = 4, fanout: int = 2,
                 tick=ms(20)):
        assert n_rumors <= RUMOR_BITS
        self.n = n_nodes
        self.rumors = n_rumors
        self.fanout = fanout
        self.tick = tick
        self.full = (1 << n_rumors) - 1

    def init(self, ctx: Ctx):
        st = dict(ctx.state)
        seeded = ctx.node == 0
        st["have"] = jnp.where(seeded, self.full, 0)  # origin knows all
        st["infected_at"] = jnp.where(seeded, ctx.now, -1)
        st["booted"] = jnp.asarray(1, jnp.int32)
        ctx.set_timer(ctx.randint(0, self.tick), T_GOSSIP, [0])
        ctx.state = st

    def on_timer(self, ctx: Ctx, tag, payload):
        st = ctx.state
        is_tick = tag == T_GOSSIP
        infected = st["have"] != 0
        for _ in range(self.fanout):
            peer = ctx.randint(0, self.n - 1)
            # push our digest + bits; peers pull what they miss
            ctx.send(peer, PUSH, [st["have"]],
                     when=is_tick & infected & (peer != ctx.node))
        ctx.set_timer(self.tick, T_GOSSIP, [0], when=is_tick)

    def on_message(self, ctx: Ctx, src, tag, payload):
        st = dict(ctx.state)
        theirs = payload[0]
        newly = (tag == PUSH) | (tag == PUSH_BACK)
        gained = newly & ((theirs & ~st["have"]) != 0)
        st["infected_at"] = jnp.where(gained & (st["infected_at"] < 0),
                                      ctx.now, st["infected_at"])
        st["have"] = jnp.where(newly, st["have"] | theirs, st["have"])
        # anti-entropy: if the pusher lacks rumors we hold, push back
        ctx.send(src, PUSH_BACK, [st["have"]],
                 when=(tag == PUSH) & ((st["have"] & ~theirs) != 0))
        ctx.state = st


def all_infected(n_rumors: int, require_all_alive: bool = False):
    """Completion predicate. By default dead nodes are excused (a
    permanently-killed node must not block the run); recovery scenarios set
    require_all_alive=True so the run only completes once every victim has
    restarted AND been re-infected."""
    full = (1 << n_rumors) - 1

    def check(state):
        ns = state.node_state
        # booted gate: until every node's t=0 INIT has fired, un-booted
        # nodes must not be mistaken for dead ones
        started = (ns["booted"] == 1).all()
        done = ns["have"] == full
        if not require_all_alive:
            done = done | ~state.alive
        else:
            done = done & state.alive
        return started & done.all()
    return check


def make_gossip_runtime(n_nodes=8, n_rumors=4, fanout=2, scenario=None,
                        cfg=None, require_all_alive=False, **kw):
    from ..core.types import SimConfig, sec
    from ..runtime.runtime import Runtime
    if cfg is None:
        cfg = SimConfig(n_nodes=n_nodes, event_capacity=192,
                        time_limit=sec(20))
    prog = Gossip(n_nodes, n_rumors, fanout, **kw)
    return Runtime(cfg, [prog], state_spec(), scenario=scenario,
                   halt_when=all_infected(n_rumors, require_all_alive))
