#!/bin/bash
# Patient tunnel watcher: ONE no-timeout claim attempt (jax.devices blocks
# until the axon service answers; killed probes risk wedging the claim, so
# no polling loop). On success, run the bench + tuning sweep immediately.
cd /root/repo
echo "$(date -u +%H:%M:%S) patient watcher: blocking on device claim" >> tpu_watch.log
python -c "import jax; d = jax.devices(); print(d, flush=True)" >> tpu_watch.log 2>&1
rc=$?
echo "$(date -u +%H:%M:%S) claim returned rc=$rc" >> tpu_watch.log
if [ $rc -eq 0 ]; then
  python bench.py > BENCH_tpu.json 2>> tpu_watch.log
  echo "$(date -u +%H:%M:%S) bench done rc=$?" >> tpu_watch.log
  python bench.py --sweep > BENCH_tpu_sweep.json 2>> tpu_watch.log
  echo "$(date -u +%H:%M:%S) sweep done rc=$?" >> tpu_watch.log
fi
