"""Ablation profile of the config-4 (KV-on-Raft) step — where does the 8x go?

BASELINE_rows_r04.jsonl: config 4 runs at ~7.2k seed-ev/s vs ~58k for
configs 2/3 — an ~8x per-event cost that BASELINE.md attributed in passing
to "per-event digest-chain invariant + apply loop" without evidence. This
script measures it: build the config-4 runtime with one cost component
removed at a time and compare steady-state step rates on whatever device
answers (CPU when the tunnel is dead — the ratios are what matter; the
reference's criterion benches play the same role, madsim/benches/rpc.rs).

Usage: python scripts/profile_config4.py [--batch 512] [--steps 512] [--out f]

Each variant compiles its own step program; rates are measured on a second
run() call so compile time is excluded. All lanes stay live for the whole
window (fresh states, no compaction), so rate = steps_fired / wall.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _force_cpu_if_dead():
    from bench import _tpu_alive, _force_cpu_inprocess
    if not (_tpu_alive() or _tpu_alive()):
        print("profile_config4: tpu preflight failed; CPU fallback",
              file=sys.stderr)
        _force_cpu_inprocess()


def build(invariant="full", event_capacity=128, log_capacity=48,
          payload_words=12, apply_per_event=2, halt=True):
    """The config-4 runtime (baseline_configs.config4 shapes), with knobs."""
    import jax.numpy as jnp
    from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
    from madsim_tpu.core.types import SimConfig
    from madsim_tpu.runtime.runtime import Runtime
    from madsim_tpu.models import raft as R
    from madsim_tpu.models.raft_kv import (KV_FIELDS, KvClient, RaftKv,
                                           all_clients_done, kv_persist_spec,
                                           kv_state_spec)
    n_raft, n_clients, n_keys, n_ops = 5, 3, 3, 6
    n = n_raft + n_clients
    sc = Scenario()
    for t in range(3):
        sc.at(ms(700 + 900 * t)).kill_random(among=range(5))
        sc.at(ms(1200 + 900 * t)).restart_random(among=range(5))
    cfg = SimConfig(n_nodes=n, event_capacity=event_capacity,
                    payload_words=payload_words, time_limit=sec(8),
                    net=NetConfig(packet_loss_rate=0.05))
    peer_mask = np.asarray([True] * n_raft + [False] * n_clients)
    if invariant == "full":
        inv = R.raft_invariant(n, log_capacity, KV_FIELDS, peer_mask)
    elif invariant == "cheap":
        # leaders-per-term + commit<=len only: drops the digest-chain
        # prefix-agreement machinery (cumsum + [N,N,L+1] one-hot evaluate)
        eye = jnp.eye(n, dtype=bool)
        peer = jnp.asarray(peer_mask)

        def inv(state):
            ns = state.node_state
            leader = (ns["role"] == R.LEADER) & peer
            same_term = ns["term"][:, None] == ns["term"][None, :]
            two = (leader[:, None] & leader[None, :] & same_term & ~eye).any()
            ec = jnp.maximum(jnp.where(peer, ns["commit"], 0),
                             jnp.where(peer, ns["snap_len"], 0))
            gt = (ec > jnp.where(peer, ns["log_len"], 0)).any()
            return two | gt, jnp.where(two, R.CRASH_TWO_LEADERS,
                                       R.CRASH_COMMIT_GT_LOG)
    else:
        inv = None
    prog_raft = RaftKv(n, log_capacity, n_keys=n_keys, n_peers=n_raft,
                       apply_per_event=apply_per_event)
    prog_client = KvClient(n_raft, n_keys, n_ops)
    return Runtime(
        cfg, [prog_raft, prog_client],
        kv_state_spec(n, log_capacity, n_ops, n_keys, n_clients),
        node_prog=np.asarray([0] * n_raft + [1] * n_clients, np.int32),
        scenario=sc, invariant=inv, persist=kv_persist_spec(),
        halt_when=(all_clients_done(n_raft, n_ops) if halt else None))


VARIANTS = [
    # name, build kwargs — each removes/shrinks ONE component vs "full"
    ("full", {}),
    ("inv=cheap", dict(invariant="cheap")),
    ("inv=none", dict(invariant=None)),
    ("halt_when=none", dict(halt=False)),
    ("apply_per_event=1", dict(apply_per_event=1)),
    ("event_capacity=96", dict(event_capacity=96)),
    ("log_capacity=16", dict(log_capacity=16)),
    ("payload_words=11", dict(payload_words=11)),
    # the config-2 shape, for the cross-config anchor
    ("inv=none,C=96,L=16", dict(invariant=None, event_capacity=96,
                                log_capacity=16)),
]

# right-sizing candidates (run with --variants rightsize): the ablation
# found L the dominant axis; these measure the capacity floor config 4 can
# actually run at (log must fit n_clients*n_ops + election no-ops = 22+,
# ev_peak audit gates C)
RIGHTSIZE = [
    ("full", {}),
    ("L=32", dict(log_capacity=32)),
    ("C=96", dict(event_capacity=96)),
    ("L=32,C=96", dict(log_capacity=32, event_capacity=96)),
    ("L=32,C=96,B=1024", dict(log_capacity=32, event_capacity=96,
                              batch=1024)),
]

# host-chunk batch sweep (run with --variants batch): the 100k BASELINE row
# ran B=4096 chunks; per-lane state is ~20KB so 4096 lanes = ~80MB working
# set vs ~10MB at 512 — on CPU the cache footprint sets the rate
BATCH = [
    ("B=512", dict(batch=512)),
    ("B=1024", dict(batch=1024)),
    ("B=2048", dict(batch=2048)),
    ("B=4096", dict(batch=4096)),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--steps", type=int, default=512)
    ap.add_argument("--variants", default="ablate",
                    choices=["ablate", "rightsize", "batch"])
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    _force_cpu_if_dead()
    import jax
    steps = args.steps
    table = {"ablate": VARIANTS, "rightsize": RIGHTSIZE,
             "batch": BATCH}[args.variants]
    rows = []
    for name, kw in table:
        kw = dict(kw)
        B = kw.pop("batch", args.batch)
        rt = build(**kw)
        seeds = np.arange(B)
        t0 = time.perf_counter()
        rt.run(rt.init_batch(seeds), steps, chunk=steps)     # compile+warm
        compile_s = time.perf_counter() - t0
        st0 = rt.init_batch(seeds)
        t0 = time.perf_counter()
        st, _ = rt.run(st0, steps, chunk=steps)
        fired = int(np.asarray(st.steps).sum())
        dt = time.perf_counter() - t0
        row = dict(variant=name, batch=B,
                   seed_events_per_sec=round(fired / dt, 1),
                   steps_fired=fired, wall_s=round(dt, 3),
                   compile_s=round(compile_s - dt, 1))
        rows.append(row)
        print(json.dumps(row), flush=True)
    base = rows[0]["seed_events_per_sec"]
    for r in rows:
        r["speedup_vs_full"] = round(r["seed_events_per_sec"] / base, 3)
    out = dict(metric="config4_ablation",
               platform=jax.devices()[0].platform, variants=args.variants,
               steps=steps, rows=rows)
    print(json.dumps({r["variant"]: r["speedup_vs_full"] for r in rows}))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
