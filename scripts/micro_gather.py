"""Microbenchmark: per-lane dynamic indexing vs one-hot masking on TPU.

Times each pattern inside a lax.scan whose indices change every step
(data-dependent, so nothing hoists), and checks that wall time scales with
step count (guarding against the whole loop being optimized away).
"""

import time
import sys

import jax
import jax.numpy as jnp
import numpy as np

B, C = 4096, 96


def scan_bench(body, steps):
    @jax.jit
    def run(x, idx):
        def f(carry, _):
            x, idx = carry
            x = body(x, idx)
            idx = (idx + x[:, 0]) % C          # data-dependent next index
            return (x, idx), ()
        (x, idx), _ = jax.lax.scan(f, (x, idx), None, length=steps)
        return x.sum() + idx.sum()
    return run


def onehot(idx):
    return jax.lax.broadcasted_iota(jnp.int32, (B, C), 1) == idx[:, None]


x0 = jnp.asarray(np.random.randint(0, 100, (B, C)), jnp.int32)
idx0 = jnp.asarray(np.random.randint(0, C, (B,)), jnp.int32)

PATTERNS = [
    ("elementwise [B,C]", lambda x, idx: (x * 3 + 1) % 1000),
    ("gather take_along_axis", lambda x, idx: x + jnp.take_along_axis(
        x, idx[:, None], axis=1)),
    ("gather vmap r[i]", lambda x, idx: x + jax.vmap(
        lambda r, i: r[i])(x, idx)[:, None]),
    ("gather one-hot", lambda x, idx: x + jnp.where(
        onehot(idx), x, 0).sum(axis=1, keepdims=True)),
    ("scatter vmap .at[i].set", lambda x, idx: jax.vmap(
        lambda r, i: r.at[i].set(r[0]))(x, idx)),
    ("scatter one-hot where", lambda x, idx: jnp.where(
        onehot(idx), x[:, :1], x)),
]

for name, body in PATTERNS:
    rows = []
    for steps in (128, 512):
        fn = scan_bench(body, steps)
        fn(x0, idx0).block_until_ready()          # compile+warm
        t0 = time.perf_counter()
        out = fn(x0, idx0).block_until_ready()
        rows.append(time.perf_counter() - t0)
    us128, us512 = rows[0] / 128 * 1e6, rows[1] / 512 * 1e6
    print(f"{name:28s} {us128:9.2f} us/step @128  {us512:9.2f} us/step @512",
          file=sys.stderr)
