#!/bin/bash
# One-command CI — the analog of the reference's test-std/test-sim matrix
# (ci.yml:57-86 runs the same workspace suite against the simulator AND
# real tokio; here the sim tier is the vectorized engine and the realworld
# tier drives real sockets/wall-clock through the same Programs).
#
# Usage: scripts/ci.sh [fast|full] [--compile-smoke]
#   fast (default)  sim tier minus the long chaos sweeps, then the
#                   realworld tier serially (wall-clock pacing breaks
#                   under CPU contention — see pytest.ini). Green in a few
#                   minutes warm-cached on a 1-core box. With
#                   --compile-smoke, also asserts the shared step-program
#                   cache (two structurally-equal configs -> 1 compile).
#   full            everything: whole suite, a MADSIM_TEST_CHECK_DETERMINISM
#                   re-run of @simtest workloads (the reference's
#                   determinism-check-by-replay mode, macros lib.rs:160-186),
#                   and the 8-device virtual-mesh multichip dryrun.
set -euo pipefail
cd "$(dirname "$0")/.."
tier=${1:-fast}

# Persistent compile cache (DESIGN §10): a workspace-local dir shared by
# both lanes, so a cold CI process reuses warm XLA executables instead of
# recompiling every structurally-known step program. Content-keyed — it
# can only skip the compile stage, never change results.
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"
# ... with the SAME 5s floor tests/conftest.py applies (ROADMAP r12/r16:
# small deserialized executables can corrupt on first invocation — the
# floor keeps them out of the cache). Without this, the bench smokes
# below run floor-less (jax's default floor is 1s) and re-seed the
# shared cache with exactly the small high-traffic executables the
# pytest floor exists to exclude — the suite then deserializes them and
# the r16-era masked-digest flake returns.
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="${JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS:-5}"
# pytest prints the compile-counter summary at suite end (tests/conftest.py)
export MADSIM_COMPILE_SUMMARY="${MADSIM_COMPILE_SUMMARY:-1}"

case "$tier" in
  fast)
    python -m pytest tests/ -q -m "not realworld and not slow"
    python -m pytest tests/ -q -m "realworld and not slow"
    # seconds-scale fused-runner smoke: run_fused must stay bitwise-equal
    # to the chunked runner and the pipelined explore() must round-trip
    python bench.py --fused-smoke
    # observability smoke: a tiny traced fused sweep must yield a readable
    # ring that exports as valid Chrome-trace JSON, and the exporter's
    # event counts must agree with the engine's own fired counts
    python bench.py --obs-smoke
    # schedule-fuzzer smoke: a small coverage-guided campaign must beat
    # blind explore() on the saturating workload, exercise the mutation
    # operators, and enumerate PCT tie-break policies
    python bench.py --search-smoke
    # causal-lineage smoke: lineage/sketch compiled in but masked off
    # must not perturb trajectories, a fuzzer-harvested crash must
    # explain itself (parent chain + Perfetto flow arrows), and the
    # divergence profile must come back from the on-device sketches
    python bench.py --causal-smoke
    # persistent-campaign smoke: two concurrent worker processes must
    # merge into one corpus dir with deduped causal-fingerprint crash
    # buckets, a SIGKILLed campaign must resume to exactly the
    # uninterrupted run, and a structurally different runtime must be
    # rejected by the store's version/signature contract
    python bench.py --campaign-smoke
    # mesh-sharded-campaign smoke: a 1-shard sharded campaign must write
    # a byte-identical durable store to the unsharded fuzzer, a 2-shard
    # CPU-mesh campaign must merge both shard namespaces (coverage
    # superset, foreign entries delivered, consensus tally serialized),
    # and a split 2-shard campaign must resume equal to the
    # uninterrupted control with the verify_resume guard armed
    python bench.py --shard-smoke
    # sim-profiler smoke: on-device counters must match a host-replayed
    # reference on a seeded chaos run, profiling on/off/masked must be
    # bit-identical leaf-for-leaf, Perfetto counter tracks must export
    # next to the instants, and fuzz rounds must report per-operator
    # coverage yield summing to each round's admissions
    python bench.py --prof-smoke
    # SLO latency-plane smoke: the on-device e2e histograms must equal a
    # host parent-walk of the flight-recorder ring (root-inheritance
    # rule end to end), the plane on/masked/compiled-out must be
    # bit-identical, slo_invariant must crash deterministically with
    # CRASH_SLO and replay by seed, and the Perfetto export must carry
    # the rolling per-node e2e-p99 track
    python bench.py --lat-smoke
    # windowed-telemetry smoke: every lane's device series must equal a
    # host replay of the flight-recorder ring bucketed by the window
    # rule, the plane on/masked/compiled-out must be bit-identical, the
    # recovery oracle must stay green on the healed flagship and crash
    # CRASH_RECOVERY deterministically (seed-replayable) on the
    # unhealed one, the Perfetto export must carry true sim-time
    # counter tracks, and a burst-guided fuzz campaign must open a
    # CRASH_RECOVERY bucket whose (seed, knobs) handle replays red
    python bench.py --series-smoke
    # attribution-plane smoke: the device's per-(lane, node) tail
    # counters must equal a host parent-walk of the flight-recorder
    # ring on every component (count/queue-wait/net/hops), the plane
    # on/compiled-out must be bit-identical, a pause/resume workload
    # must telescope host request spans exactly (wait + transit == e2e)
    # with the dominant-node fold matching the device bottleneck
    # histogram, explain_latency must be deterministic on re-run, and
    # the Perfetto export must carry request duration spans iff the
    # plane is on
    python bench.py --span-smoke
    # gray-failure smoke: a one-way cut must be observed asymmetrically
    # by gossip, skewed lease expiry on the Percolator-lite flagship
    # must crash the snapshot oracle and reproduce on seed replay, and
    # a torn-write fuzz campaign must open causal-fingerprint crash
    # buckets with replayable (seed, knobs) handles
    python bench.py --grayfail-smoke
    # connection-fault smoke: OP_RESET_PEER must tear conn/stream state
    # on BOTH sides (vs the kill's deliberate half-open survivor), the
    # minipg exactly-once flagship must survive the reset+dup storm with
    # incarnation guards on AND crash fingerprint-exact-replayably with
    # them compiled to the pre-r19 behavior, and a dup-storm fuzz
    # campaign must open causal buckets whose handles replay red
    python bench.py --conn-smoke
    # campaign-triage smoke: a 2-worker campaign must snapshot
    # byte-stably into the triage/ history, a planted bucket must diff
    # as exactly one `new` entry with its torn_write recipe
    # attribution (both attribution dimensions summing to their
    # totals), the standing HTML dashboard must render, and the
    # repro-health audit must record a verdict via replay_bucket
    python bench.py --triage-smoke
    # time-travel smoke: a crash recorded with a wrapped 4-slot ring
    # must replay from a harvested checkpoint to a complete
    # (truncated=False) causal chain, bit-stably twice, staying
    # bucket-compatible with the live truncated observation; and the
    # divergence microscope must name the same first divergent
    # dispatch on a re-run of the same lane pair
    python bench.py --tt-smoke
    # lineage-driven-fault-injection smoke: green-support extraction on
    # a seeded rpc_echo lane must match an inline host parent-walk
    # reference, every synthesized targeted vector must stay on the
    # knob plane (time-guarded rows only, pool-confined targets,
    # in-bounds values), and one targeted round must replay
    # bit-identically from its (seed, knobs) handle
    python bench.py --ldfi-smoke
    # regression gate (OSS-Fuzz-style): every committed crash bucket in
    # tests/data/regression_corpus must still reproduce (run-twice
    # verified) and the top-energy corpus slice must still land on its
    # recorded schedule hashes
    python bench.py --regression-smoke
    # DetSan smoke: the repo-wide determinism lint gate must be clean,
    # a seeded schedule race must confirm via the forced-commute PCT
    # nudge with a replayable (seed, knobs, nudge) repro and dedupe
    # into one bucket, and the detsan double-run sanitizer must pass on
    # a clean runtime while its differ catches a planted divergence
    python bench.py --analyze-smoke
    if [[ "${2:-}" == "--compile-smoke" ]]; then
      # shared step-program cache smoke: two structurally-equal configs
      # must cost exactly one retrace and stay bitwise-equal to a
      # fresh-compile control
      python bench.py --compile-smoke
    fi
    ;;
  full)
    python -m pytest tests/ -q
    # determinism re-run: every @simtest-decorated workload runs its base
    # seed twice and bit-compares full state
    MADSIM_TEST_CHECK_DETERMINISM=1 python -m pytest -q \
        tests/test_raft.py tests/test_rpc_echo.py tests/test_gossip.py
    # multi-chip sharding compiles + executes on a virtual 8-device mesh
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
    # seconds-scale bench self-tests: the measurement paths (incl. the
    # native baseline twin and the shared-compile cache) must not rot —
    # the reference's ci.yml runs its criterion benches the same way
    python bench.py --smoke
    python bench.py --compile-smoke
    ;;
  *)
    echo "usage: scripts/ci.sh [fast|full] [--compile-smoke]" >&2
    exit 2
    ;;
esac
echo "ci $tier: OK"
