#!/bin/bash
# Poll the axon TPU tunnel; when it answers, run the full bench chain on
# the chip and COMMIT the artifacts (VERDICT r2 next-1: one revival must
# capture everything durably).
# Probe uses a killable child (a wedged tunnel hangs jax.devices forever);
# the bench runs get no timeout (killing mid-compile wedges the device
# claim).
cd /root/repo
for i in $(seq 1 200); do
  if timeout 90 python -c "import jax; d=jax.devices(); assert d and d[0].platform not in ('cpu','none')" 2>/dev/null; then
    echo "$(date -u +%H:%M:%S) tunnel alive, running bench chain" >> tpu_watch.log
    # a wedge verdict cached by a recent bench/example probe (<=4 min
    # TTL) would make the chain's own preflights fall back to CPU on
    # a freshly revived tunnel — clear it now that we KNOW it answers
    rm -f /tmp/madsim_tpu_tunnel_dead.* 2>/dev/null
    # commit after EVERY stage: if the tunnel wedges mid-chain (the bench
    # runs deliberately have no timeout), the stages already captured
    # survive as commits instead of dying with the stuck watcher
    for pair in ":BENCH_tpu.json" "--all:BENCH_tpu_all.json" \
                "--sched-ab:BENCH_tpu_sched_ab.json" \
                "--sweep:BENCH_tpu_sweep.json" \
                "--shape-sweep:BENCH_tpu_shape_sweep.json"; do
      mode="${pair%%:*}"; out="${pair#*:}"
      echo "$(date -u +%H:%M:%S) running bench $mode -> $out" >> tpu_watch.log
      python bench.py $mode > "$out" 2>> tpu_watch.log
      rc=$?
      echo "$(date -u +%H:%M:%S) bench $mode done rc=$rc" >> tpu_watch.log
      if [ $rc -eq 0 ] && [ -s "$out" ]; then
        # -f: some BENCH_tpu_* names are gitignored as scratch; on-chip
        # evidence must be committed regardless. Guarded on rc/size so a
        # failed stage never clobbers previously committed good numbers.
        git add -f "$out" BENCH_TPU_LAST.json tpu_watch.log >> tpu_watch.log 2>&1
        git commit -m "Record on-chip bench artifact: ${mode:-flagship}" \
            >> tpu_watch.log 2>&1
      else
        git checkout -- "$out" 2>> tpu_watch.log || true
      fi
    done
    echo "$(date -u +%H:%M:%S) bench chain complete" >> tpu_watch.log
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) probe $i: tunnel dead" >> tpu_watch.log
  sleep 240
done
echo "gave up" >> tpu_watch.log
exit 1
