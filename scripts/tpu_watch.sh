#!/bin/bash
# Poll the axon TPU tunnel; when it answers, run the full bench chain on
# the chip and COMMIT the artifacts (VERDICT r2 next-1: one revival must
# capture everything durably).
# Probe uses a killable child (a wedged tunnel hangs jax.devices forever);
# the bench runs get no timeout (killing mid-compile wedges the device
# claim).
cd /root/repo
for i in $(seq 1 200); do
  if timeout 90 python -c "import jax; d=jax.devices(); assert d and d[0].platform not in ('cpu','none')" 2>/dev/null; then
    echo "$(date -u +%H:%M:%S) tunnel alive, running bench" >> tpu_watch.log
    python bench.py > BENCH_tpu.json 2>> tpu_watch.log
    echo "$(date -u +%H:%M:%S) bench done rc=$?" >> tpu_watch.log
    echo "$(date -u +%H:%M:%S) running combined --all" >> tpu_watch.log
    python bench.py --all > BENCH_tpu_all.json 2>> tpu_watch.log
    echo "$(date -u +%H:%M:%S) --all done rc=$?" >> tpu_watch.log
    echo "$(date -u +%H:%M:%S) running scheduler A/B" >> tpu_watch.log
    python bench.py --sched-ab > BENCH_tpu_sched_ab.json 2>> tpu_watch.log
    echo "$(date -u +%H:%M:%S) sched-ab done rc=$?" >> tpu_watch.log
    echo "$(date -u +%H:%M:%S) running tuning sweep" >> tpu_watch.log
    python bench.py --sweep > BENCH_tpu_sweep.json 2>> tpu_watch.log
    echo "$(date -u +%H:%M:%S) sweep done rc=$?" >> tpu_watch.log
    git add BENCH_tpu.json BENCH_tpu_all.json BENCH_tpu_sweep.json \
        BENCH_tpu_sched_ab.json BENCH_TPU_LAST.json tpu_watch.log \
        2>> tpu_watch.log
    git commit -m "Record on-chip bench artifacts (flagship + --all + scheduler A/B + sweep)" \
        >> tpu_watch.log 2>&1
    echo "$(date -u +%H:%M:%S) artifacts committed" >> tpu_watch.log
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) probe $i: tunnel dead" >> tpu_watch.log
  sleep 240
done
echo "gave up" >> tpu_watch.log
exit 1
