#!/bin/bash
# Poll the axon TPU tunnel; when it answers, run bench.py on the chip.
# Probe uses a killable child (a wedged tunnel hangs jax.devices forever);
# the bench run itself gets no timeout (killing mid-compile wedges the
# device claim — see memory/axon-tpu-quirks).
cd /root/repo
for i in $(seq 1 200); do
  if timeout 90 python -c "import jax; d=jax.devices(); assert d and d[0].platform not in ('cpu','none')" 2>/dev/null; then
    echo "$(date -u +%H:%M:%S) tunnel alive, running bench" >> tpu_watch.log
    python bench.py > BENCH_tpu.json 2>> tpu_watch.log
    echo "$(date -u +%H:%M:%S) bench done rc=$?" >> tpu_watch.log
    echo "$(date -u +%H:%M:%S) running tuning sweep" >> tpu_watch.log
    python bench.py --sweep > BENCH_tpu_sweep.json 2>> tpu_watch.log
    echo "$(date -u +%H:%M:%S) sweep done rc=$?" >> tpu_watch.log
    echo "$(date -u +%H:%M:%S) running shardkv bench" >> tpu_watch.log
    python bench.py --shardkv > BENCH_tpu_shardkv.json 2>> tpu_watch.log
    echo "$(date -u +%H:%M:%S) shardkv done rc=$?" >> tpu_watch.log
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) probe $i: tunnel dead" >> tpu_watch.log
  sleep 240
done
echo "gave up" >> tpu_watch.log
exit 1
