"""Measure BASELINE.md's configs 0-4 and emit one JSON row per config.

The reference publishes no numbers (BASELINE.md: bench infrastructure
only), so the CPU-reference column is its *execution model* reproduced
here — one seed advancing sequentially (the `cargo test` loop analog,
task.rs:110-124) — and the batched column is this engine on whatever
device answers (CPU fallback when the TPU tunnel is dead; the watcher
re-runs on-chip).

Usage:
    python scripts/baseline_configs.py [--config N] [--scale F] [--out f]

--scale shrinks seed counts for smoke runs (e.g. 0.01); the committed
artifact must be produced at scale 1.0.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _plat():
    import jax
    return jax.devices()[0].platform


def _force_cpu_if_dead():
    from bench import _tpu_alive, _force_cpu_inprocess
    if not (_tpu_alive() or _tpu_alive()):
        print("baseline: tpu preflight failed; CPU fallback",
              file=sys.stderr)
        _force_cpu_inprocess()


def _pingpong_rt():
    from madsim_tpu import Runtime, SimConfig, sec
    from madsim_tpu.models.pingpong import PingPong, state_spec
    cfg = SimConfig(n_nodes=3, time_limit=sec(30), event_capacity=32)
    return Runtime(cfg, [PingPong(3, target=20)], state_spec())


def config0(scale):
    """Single-seed 3-node ping-pong on the CPU sim runtime, plus the
    determinism check — the per-seed baseline every other row divides."""
    rt = _pingpong_rt()
    assert rt.check_determinism(seed=7, max_steps=4000)
    state, _ = rt.run(rt.init_single(3), 512)   # warm
    reps = max(1, int(20 * scale))
    t0 = time.perf_counter()
    ev = 0
    for s in range(reps):
        st, _ = rt.run(rt.init_single(s), 4000)
        ev += int(np.asarray(st.steps).sum())
    dt = time.perf_counter() - t0
    return dict(config=0, platform=_plat(), seeds=reps,
                events_per_sec=round(ev / dt, 1), determinism_check=True,
                wall_s=round(dt, 2))


def config1(scale):
    """1k-seed batched 3-node ping-pong on one device."""
    rt = _pingpong_rt()
    B = max(8, int(1024 * scale))
    seeds = np.arange(B)
    rt.run(rt.init_batch(seeds), 512)           # warm/compile
    t0 = time.perf_counter()
    st, _ = rt.run(rt.init_batch(seeds), 4000)
    dt = time.perf_counter() - t0
    assert bool(st.halted.all()) and not bool(np.asarray(st.crashed).any())
    ev = int(np.asarray(st.steps).sum())
    return dict(config=1, platform=_plat(), seeds=B,
                seed_events_per_sec=round(ev / dt, 1), wall_s=round(dt, 2))


def config2(scale):
    """MadRaft 5-node leader election under random partition, 10k seeds."""
    from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
    from madsim_tpu.models import raft as R
    from madsim_tpu.models.raft import make_raft_runtime
    sc = Scenario()
    for t in range(4):
        sc.at(ms(400 + 800 * t)).partition([t % 5, (t + 1) % 5])
        sc.at(ms(800 + 800 * t)).heal()
    cfg = SimConfig(n_nodes=5, event_capacity=96, time_limit=sec(4),
                    net=NetConfig())
    rt = make_raft_runtime(5, log_capacity=16, n_cmds=0, scenario=sc,
                           cfg=cfg)
    B = max(64, int(10_000 * scale))
    total_ev = 0
    elected = 0
    t0 = time.perf_counter()
    for lo in range(0, B, 4096):
        seeds = np.arange(lo, min(lo + 4096, B))
        st, _ = rt.run(rt.init_batch(seeds), 12_000)
        assert not bool(np.asarray(st.crashed).any())
        total_ev += int(np.asarray(st.steps).sum())
        role = np.asarray(st.node_state["role"])
        elected += int(((role == R.LEADER).sum(axis=1) >= 1).sum())
    dt = time.perf_counter() - t0
    return dict(config=2, platform=_plat(), seeds=B,
                seed_events_per_sec=round(total_ev / dt, 1),
                elected_fraction=round(elected / B, 4), wall_s=round(dt, 2))


def config3(scale):
    """tonic-style RPC service under packet loss + kill/restart, 50k
    seeds — the @rpc service stack (net/service.py) under chaos."""
    import jax.numpy as jnp
    from madsim_tpu import Runtime, Scenario, SimConfig, NetConfig, sec, ms
    from madsim_tpu.models.rpc_echo import (EchoClient, EchoServer,
                                            server_state_spec)
    sc = Scenario()
    sc.at(ms(300)).kill(0)
    sc.at(ms(700)).restart(0)
    cfg = SimConfig(n_nodes=3, event_capacity=48, time_limit=sec(6),
                    net=NetConfig(packet_loss_rate=0.1))
    rt = Runtime(cfg, [EchoServer(), EchoClient(target=10,
                                                timeout=ms(60))],
                 server_state_spec(), node_prog=[0, 1, 1], scenario=sc)
    B = max(64, int(50_000 * scale))
    total_ev = 0
    t0 = time.perf_counter()
    for lo in range(0, B, 8192):
        seeds = np.arange(lo, min(lo + 8192, B))
        st, _ = rt.run(rt.init_batch(seeds), 20_000)
        assert not bool(np.asarray(st.crashed).any())
        total_ev += int(np.asarray(st.steps).sum())
    dt = time.perf_counter() - t0
    return dict(config=3, platform=_plat(), seeds=B,
                seed_events_per_sec=round(total_ev / dt, 1),
                wall_s=round(dt, 2))


def config4(scale):
    """Full MadRaft log replication + linearizability fuzz, 100k seeds,
    early-exit compaction (run_compacting) — the north-star workload.
    Every chunk's client histories run through the linearizability
    checker (native C++, Python fallback beyond 57 ops/key).

    Shapes are right-sized from the r5 ablation (scripts/profile_config4.py,
    CONFIG4_PROFILE_r05.json): log_capacity 48->32 and event_capacity
    128->96 measured 2.0x per-event on CPU at identical workload semantics
    (same nodes/ops/chaos/checker; 32 >= the 22-entry no-compaction floor
    asserted by make_kv_runtime, and any overflow crashes loudly via oops).
    The host chunk is platform-dependent: per-lane state is ~15KB, so CPU
    runs 512-lane chunks (cache-resident) while TPU keeps 4096."""
    from madsim_tpu import Scenario, SimConfig, NetConfig, ms, sec
    from madsim_tpu.models.raft_kv import extract_histories, make_kv_runtime
    from madsim_tpu.native import check_kv_history
    sc = Scenario()
    for t in range(3):
        sc.at(ms(700 + 900 * t)).kill_random(among=range(5))
        sc.at(ms(1200 + 900 * t)).restart_random(among=range(5))
    cfg = SimConfig(n_nodes=8, event_capacity=96, payload_words=12,
                    time_limit=sec(8),
                    net=NetConfig(packet_loss_rate=0.05))
    rt = make_kv_runtime(n_raft=5, n_clients=3, n_keys=3, n_ops=6,
                         log_capacity=32, scenario=sc, cfg=cfg)
    B = max(256, int(100_000 * scale))
    # both chunk axes are platform-dependent: CPU favors small cache-
    # resident lane chunks + frequent compaction checks; TPU keeps the r4
    # geometry (4096 lanes, 2048-step scans) — fewer device->host syncs,
    # and the r5 CPU measurements must not silently change the TPU config
    on_tpu = _plat() == "tpu"
    chunk_lanes = 4096 if on_tpu else 512
    chunk_steps = 2048 if on_tpu else 512
    total_ev = 0
    checked = 0
    check_s = 0.0
    t0 = time.perf_counter()
    for lo in range(0, B, chunk_lanes):
        seeds = np.arange(lo, min(lo + chunk_lanes, B))
        st = rt.run_compacting(rt.init_batch(seeds), 60_000,
                               chunk=chunk_steps)
        assert not bool(np.asarray(st.crashed).any()), \
            f"crash at seed {seeds[np.argmax(np.asarray(st.crashed))]}"
        # the right-sized event_capacity must never overflow silently —
        # dropped emissions are protocol-legal loss, but the measured row
        # has to represent the configured fault model, nothing more
        assert not bool((np.asarray(st.oops) != 0).any()), \
            "oops set (event/time overflow) — capacity too small"
        total_ev += int(np.asarray(st.steps).sum())
        tc = time.perf_counter()
        for h in extract_histories(st, 5, 3):
            assert check_kv_history(h), "non-linearizable history"
            checked += 1
        check_s += time.perf_counter() - tc
        print(f"config4: {min(lo + chunk_lanes, B)}/{B} seeds done",
              file=sys.stderr)
    dt = time.perf_counter() - t0
    # engine rate excludes the host-side checker time (measured
    # separately as check_wall_s) so the figure is comparable to the
    # no-checking configs 0-3; wall_s is the full fuzz+check wall
    return dict(config=4, platform=_plat(), seeds=B,
                seed_events_per_sec=round(total_ev / (dt - check_s), 1),
                histories_checked=checked, all_linearizable=True,
                check_wall_s=round(check_s, 1), wall_s=round(dt, 2),
                compaction=f"run_compacting(chunk={chunk_steps}) x "
                           f"{chunk_lanes}-lane host chunks")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=None)
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    _force_cpu_if_dead()
    fns = [config0, config1, config2, config3, config4]
    todo = fns if args.config is None else [fns[args.config]]
    rows = []
    for fn in todo:
        row = fn(args.scale)
        row["cmd"] = (f"python scripts/baseline_configs.py "
                      f"--config {row['config']} --scale {args.scale}")
        rows.append(row)
        print(json.dumps(row))
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"metric": "baseline_configs", "scale": args.scale,
                       "rows": rows}, f, indent=1)


if __name__ == "__main__":
    main()
