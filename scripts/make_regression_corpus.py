"""(Re)generate the committed regression corpus (tests/data/
regression_corpus/) — the OSS-Fuzz-style gate `bench.py
--regression-smoke` replays in ci.sh fast.

Runs a small DETERMINISTIC durable fuzz campaign on the gray-failure
flagship and freezes the resulting corpus dir (entries + causal-
fingerprint crash buckets + worker state) plus a REGRESSION.json
sidecar naming the runtime factory and replay budget. Re-run this ONLY
when the store signature legitimately moves (a new knob dimension, a
structural change to the flagship) — the whole point of the gate is
that buckets keep reproducing across unrelated changes.

    JAX_PLATFORMS=cpu python scripts/make_regression_corpus.py
"""

import json
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402
from madsim_tpu import fuzz  # noqa: E402
from madsim_tpu.service.store import CorpusStore  # noqa: E402

DEST = os.path.join(REPO, "tests", "data", "regression_corpus",
                    "grayfail_mix")
MAX_STEPS = 30_000

shutil.rmtree(DEST, ignore_errors=True)
rt = bench._make_grayfail_runtime("mix")
res = fuzz(rt, max_steps=MAX_STEPS, batch=64, max_rounds=4, dry_rounds=5,
           chunk=512, corpus_dir=DEST, rng_seed=1)
store = CorpusStore(DEST, create=False)
keys = store.bucket_keys()
assert keys, "campaign found no crash buckets — nothing to gate on"
with open(os.path.join(DEST, "REGRESSION.json"), "w") as f:
    json.dump(dict(
        factory="bench:_make_grayfail_runtime",
        factory_kwargs=dict(recipe="mix"),
        dup_slots=2,
        max_steps=MAX_STEPS,
        buckets=keys,
        note=("frozen by scripts/make_regression_corpus.py; replayed "
              "by bench.py --regression-smoke in ci.sh fast"),
    ), f, indent=1)
print(f"{DEST}: {len(store.entry_names())} entries, "
      f"{len(keys)} buckets: {keys}")
