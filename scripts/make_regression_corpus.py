"""(Re)generate the committed regression corpus (tests/data/
regression_corpus/) — the OSS-Fuzz-style gate `bench.py
--regression-smoke` replays in ci.sh fast.

Runs a small DETERMINISTIC durable fuzz campaign per flagship regime and
freezes the resulting corpus dir (entries + causal-fingerprint crash
buckets + worker state) plus a REGRESSION.json sidecar naming the
runtime factory and replay budget. Re-run this ONLY when the store
signature legitimately moves (a new knob dimension, a structural change
to a flagship) — the whole point of the gate is that buckets keep
reproducing across unrelated changes. (Last re-frozen at r23: the
simconfig-v8 bump — the attribution plane's structural span_attr gate —
rejects pre-r23 corpus dirs with StoreMismatch, so both campaigns were
regenerated; the trajectories themselves are bit-identical to the r21
freeze, per the golden-equivalence gates. The r21 freeze did the same
for the v7 windowed-telemetry bump.)

    JAX_PLATFORMS=cpu python scripts/make_regression_corpus.py [name ...]
"""

import json
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402
from madsim_tpu import fuzz  # noqa: E402
from madsim_tpu.service.store import CorpusStore  # noqa: E402

BASE = os.path.join(REPO, "tests", "data", "regression_corpus")

CAMPAIGNS = {
    # the r17 gray-failure flagship: Percolator-lite under the composed
    # fault mix (asym cut, drifting clocks, slow disk, torn kill)
    "grayfail_mix": dict(
        factory="bench:_make_grayfail_runtime",
        factory_kwargs=dict(recipe="mix"),
        max_steps=30_000, batch=64, max_rounds=4, rng_seed=1),
    # the r19 connection-fault flagship: minipg exactly-once transactions
    # with incarnation guards compiled to the pre-r19 behavior, under the
    # reset+dup storm (the honest red control — these buckets ARE the
    # stale-segment corruptions the guard exists to prevent)
    "connfault_mix": dict(
        factory="bench:_make_connfault_runtime",
        factory_kwargs=dict(recipe="mix"),
        max_steps=30_000, batch=64, max_rounds=4, rng_seed=1),
}

names = sys.argv[1:] or sorted(CAMPAIGNS)
for name in names:
    spec = CAMPAIGNS[name]
    dest = os.path.join(BASE, name)
    shutil.rmtree(dest, ignore_errors=True)
    mod, fn = spec["factory"].split(":")
    rt = getattr(bench, fn)(**spec["factory_kwargs"])
    res = fuzz(rt, max_steps=spec["max_steps"], batch=spec["batch"],
               max_rounds=spec["max_rounds"],
               dry_rounds=spec["max_rounds"] + 1,
               chunk=512, corpus_dir=dest, rng_seed=spec["rng_seed"])
    store = CorpusStore(dest, create=False)
    keys = store.bucket_keys()
    assert keys, f"{name}: campaign found no crash buckets to gate on"
    # freeze the store MINIMAL: the triage/ subdir (ROWS.json, snapshots)
    # is derived state the r18+ fuzz writes on open — the committed
    # fixture stays rowless so tests/test_triage.py can exercise the
    # rows-unknown attribution fallback against it, and triage output
    # never bloats the repo
    shutil.rmtree(os.path.join(dest, "triage"), ignore_errors=True)
    with open(os.path.join(dest, "REGRESSION.json"), "w") as f:
        json.dump(dict(
            factory=spec["factory"],
            factory_kwargs=spec["factory_kwargs"],
            dup_slots=2,
            max_steps=spec["max_steps"],
            buckets=keys,
            note=("frozen by scripts/make_regression_corpus.py; replayed "
                  "by bench.py --regression-smoke in ci.sh fast"),
        ), f, indent=1)
    print(f"{dest}: {len(store.entry_names())} entries, "
          f"{len(keys)} buckets: {keys}")
